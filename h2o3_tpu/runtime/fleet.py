"""Fleet-scope observability — cross-process metric/trace aggregation.

Every observability surface built in PR 6/8 (`/3/Metrics`, `/3/Trace`,
`/3/Memory`, the phase buckets) is strictly single-process; scaling out
(N serving replicas today, `parallel/launcher.py` multi-host ranks
tomorrow) would otherwise mean N scrapes, N disconnected traces, and a
dashboard that has to know the fleet topology. This module makes ONE
process (the aggregator — any process, there is no special role) able to
answer for the whole fleet:

* **Peer registry** — `register_peer(name, url)` (or
  ``H2O3_FLEET_PEERS="r1=http://h:p,r2=..."``, or ``POST /3/Fleet``)
  names the replicas to aggregate. Peers are plain h2o3 REST servers;
  the aggregator itself always counts as the replica named by
  ``H2O3_REPLICA_NAME`` (default ``self``).

* **Metric aggregation** — `GET /3/Metrics?scope=fleet` scrapes every
  peer's lossless JSON export (``GET /3/Metrics?format=json``,
  `metrics_registry.export_state`) under the shared PR 5 `RetryPolicy`
  and merges by family semantics:

  - **counters sum** across replicas per label tuple (fleet totals);
  - **histograms bucket-merge** (per-bucket count sums over the shared
    fixed bounds) so p50/p95/p99 computed from the merged buckets are
    EXACT fleet percentiles, not averages of percentiles;
  - **gauges keep per-replica series** under an added ``replica`` label
    (a gauge is process state — summing RSS across replicas is
    meaningful only sometimes, attributing it always is);
  - an unreachable peer is an EXPLICIT ``h2o3_fleet_peer_up{replica} 0``
    series — the scrape never silently shrinks (absence-of-peer must
    alert, the same stance as the registry's 0-sample counters).

* **Trace aggregation** — ``X-H2O3-Trace-Id`` already propagates through
  the remote client, so one workflow's spans land in several processes;
  `GET /3/Trace?scope=fleet[&trace_id=]` pulls each peer's Chrome-trace
  export and merges them into ONE timeline with one ``process_name``
  track per replica (pid = replica index).

* **Fleet fold** — `snapshot()` backs ``GET /3/Fleet`` and the
  `/3/Profiler` ``fleet`` entry: per-replica liveness + serving request/
  error counts + predict p99, and the fleet-merged totals — the document
  `deploy/loadgen.py --fleet` reports from.

Merge conflicts (a family registered with a different kind or histogram
bounds on two replicas — a version-skewed fleet) keep the FIRST seen
shape and count the rest into ``dropped_series``; nothing is silently
averaged across mismatched semantics.
"""

from __future__ import annotations

import json
import os
import threading
import time
import urllib.parse
import urllib.request
from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

from . import env_float, metrics_registry as _reg
from .metrics_registry import _escape_label, _fmt_value

__all__ = ["register_peer", "remove_peer", "mark_down", "peers",
           "scrape_states", "merge_states", "render_prometheus",
           "fleet_metrics_text", "merge_traces", "fleet_trace", "snapshot",
           "register_with", "origin", "same_origin", "reset"]

_LOCK = threading.Lock()
_PEERS: "OrderedDict[str, Dict]" = OrderedDict()
_ENV_PARSED = False
_FLEET_REG: Dict = {}

# the family the aggregator rebuilds authoritatively per scrape — peers'
# own copies are skipped during the merge so the fleet scrape carries
# exactly one liveness series per replica
_PEER_UP = "h2o3_fleet_peer_up"


def _registry() -> Dict:
    """Memoized registry families + REST bindings for the /3/Fleet doc
    (the metrics-consistency test walks these)."""
    if not _FLEET_REG:
        _FLEET_REG["peer_up"] = _reg.gauge(
            _PEER_UP, "1 when the last scrape of this registered replica "
            "succeeded, 0 when it was unreachable", labelnames=("replica",))
        _FLEET_REG["scrapes"] = _reg.counter(
            "h2o3_fleet_scrapes", "peer scrape attempts, per replica",
            labelnames=("replica",))
        _FLEET_REG["scrape_errors"] = _reg.counter(
            "h2o3_fleet_scrape_errors",
            "failed peer scrapes (after retries), per replica",
            labelnames=("replica",))
        _FLEET_REG["peers"] = _reg.gauge(
            "h2o3_fleet_peers", "registered fleet peers",
            fn=lambda: float(len(_PEERS)))
        _reg.bind_rest_field("fleet", "totals.peers", "h2o3_fleet_peers")
        _reg.bind_rest_field("fleet", "totals.up", _PEER_UP)
        _reg.bind_rest_field("fleet", "totals.scrapes", "h2o3_fleet_scrapes")
        _reg.bind_rest_field("fleet", "totals.scrape_errors",
                             "h2o3_fleet_scrape_errors")
    return _FLEET_REG


def self_name() -> str:
    return os.environ.get("H2O3_REPLICA_NAME", "self")


def _parse_env_once() -> None:
    global _ENV_PARSED
    if _ENV_PARSED:
        return
    _ENV_PARSED = True
    spec = os.environ.get("H2O3_FLEET_PEERS", "")
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        name, _, url = part.partition("=")
        if name and url:
            register_peer(name.strip(), url.strip())


def origin(url: str) -> str:
    """Normalize a URL to its REST origin (``http://host:port``) —
    trailing slashes and path suffixes stripped."""
    u = urllib.parse.urlparse(url if "//" in url else "http://" + url)
    return f"{u.scheme or 'http'}://{u.netloc or u.path}"


def same_origin(a: str, b: str) -> bool:
    return origin(a) == origin(b)


def register_peer(name: str, url: str) -> Dict:
    """Register (or re-point) one replica. `url` is the peer's REST base
    (``http://host:port``); trailing slashes and path suffixes are
    stripped to the origin."""
    if not name or not url:
        raise ValueError("peer name and url are both required")
    base = origin(url)
    with _LOCK:
        _PEERS[name] = dict(name=name, url=base, registered=time.time(),
                            up=None, last_scrape_ms=None, last_error=None)
        return dict(_PEERS[name])


def remove_peer(name: str) -> bool:
    reg = _registry()
    with _LOCK:
        removed = _PEERS.pop(name, None) is not None
        if removed:
            # the liveness gauge is current state: a decommissioned peer
            # must LEAVE the scrape, not freeze at its last 0/1 (the
            # documented contract is "alert on peer_up == 0" — a stale
            # series would alert forever for a replica that no longer
            # exists). Under _LOCK, paired with _scrape_one's membership-
            # gated set, so an in-flight scrape cannot resurrect it.
            reg["peer_up"].remove_series(name)
    return removed


def mark_down(name: str, reason: str = "") -> None:
    """Flip a replica's liveness to DOWN immediately — failure detection
    (the training supervisor's hung-collective abort, the bench
    watchdog's suspect attribution) must reach the fleet scrape NOW, not
    at the next failed scrape of the wedged rank. Sets the
    ``h2o3_fleet_peer_up`` series to 0 whether or not the name is a
    registered peer (a pod rank detected dead locally may only be
    registered at the aggregator) and records the reason on the peer row
    when one exists. Emits a Timeline event naming the replica."""
    reg = _registry()
    with _LOCK:
        reg["peer_up"].set(0.0, name)
        if name in _PEERS:
            _PEERS[name].update(up=False,
                                last_error=reason or "marked down")
    try:
        from .timeline import Timeline
        Timeline.record("fleet_peer_down", name, reason=reason)
    except Exception:
        pass
    try:
        from . import tracing
        tracing.event("fleet_peer_down", replica=name, reason=reason)
    except Exception:
        pass


def peers() -> List[Dict]:
    _parse_env_once()
    with _LOCK:
        return [dict(p) for p in _PEERS.values()]


def reset() -> None:
    """Drop registered peers (tests)."""
    global _ENV_PARSED
    with _LOCK:
        _PEERS.clear()
        _ENV_PARSED = True


def _retry_policy():
    from .retry import RetryPolicy

    return RetryPolicy(name="fleet", max_attempts=2,
                       deadline_s=env_float("H2O3_FLEET_DEADLINE_S", 8.0))


def _fetch_json(url: str) -> Dict:
    timeout = env_float("H2O3_FLEET_TIMEOUT_S", 3.0)

    def one():
        from . import faults

        faults.check("client.request", detail=url)
        with urllib.request.urlopen(url, timeout=timeout) as r:
            return json.loads(r.read().decode())

    return _retry_policy().call(one)


def _scrape_one(p: Dict) -> Tuple[str, Optional[Dict]]:
    reg = _registry()
    name = p["name"]
    reg["scrapes"].inc(1, name)
    t0 = time.perf_counter()
    state: Optional[Dict] = None
    err: Optional[str] = None
    try:
        state = _fetch_json(p["url"] + "/3/Metrics?format=json")
    except Exception as e:
        err = f"{type(e).__name__}: {e}"
        reg["scrape_errors"].inc(1, name)
    with _LOCK:
        # gauge update gated on CURRENT registration, under the registry
        # lock: an in-flight scrape of a peer that remove_peer just
        # deleted must not resurrect its peer_up series (the scrape
        # captured the peer list before the removal)
        if name in _PEERS:
            reg["peer_up"].set(1.0 if state is not None else 0.0, name)
            _PEERS[name].update(
                up=state is not None,
                last_scrape_ms=round((time.perf_counter() - t0) * 1e3, 2),
                last_error=err)
    return (name, state)


def _fan_out(fn, items: List) -> List:
    """Run `fn` over `items` concurrently, results in item order. Peers
    are independent HTTP targets: scraping them serially would make the
    fleet scrape's latency grow linearly with DOWN peers (each one costs
    its full retry deadline) — worst exactly when the scrape matters
    most, and long enough to trip a Prometheus scrape_timeout and lose
    the LIVE peers' data too."""
    if not items:
        return []
    if len(items) == 1:
        return [fn(items[0])]
    from concurrent.futures import ThreadPoolExecutor

    with ThreadPoolExecutor(max_workers=min(8, len(items))) as ex:
        return list(ex.map(fn, items))


def scrape_states() -> List[Tuple[str, Optional[Dict]]]:
    """Pull every registered peer's lossless metric export (peers probed
    concurrently). Returns ``(name, state_or_None)`` per peer — None
    marks an unreachable peer (already counted + gauged; the merge turns
    it into peer_up 0)."""
    _parse_env_once()
    _registry()
    return _fan_out(_scrape_one, peers())


# -- merge semantics (pure functions — unit-tested without HTTP) -------------

def merge_states(states: List[Tuple[str, Optional[Dict]]]) -> Dict:
    """Fold per-replica `export_state` payloads into one fleet family set.

    Returns ``{families: {...}, peer_up: {replica: 0|1},
    dropped_series: int}`` — `families` mirrors the export_state schema
    (kind/help/labelnames/series[+bounds]), with counters summed,
    histogram buckets summed, and gauges carried per-replica under an
    appended ``replica`` label. ``peer_up`` covers every replica in
    `states` (None state → 0)."""
    families: "OrderedDict[str, Dict]" = OrderedDict()
    acc: Dict[str, Dict] = {}            # family -> {labels_tuple: slot}
    src_labels: Dict[str, List[str]] = {}  # family -> first-seen labelnames
    peer_up: "OrderedDict[str, int]" = OrderedDict()
    dropped = 0
    for replica, state in states:
        peer_up[replica] = 0 if state is None else 1
        if not state:
            continue
        for fname, fam in state.items():
            if fname == _PEER_UP:
                continue                 # rebuilt authoritatively below
            kind = fam.get("kind")
            ent = families.get(fname)
            if ent is None:
                src_labels[fname] = list(fam.get("labelnames") or [])
                ent = families[fname] = dict(
                    kind=kind, help=fam.get("help", ""),
                    labelnames=list(src_labels[fname]))
                if kind == "histogram":
                    ent["bounds"] = list(fam.get("bounds") or [])
                if kind == "gauge":
                    ent["labelnames"] = ent["labelnames"] + ["replica"]
                acc[fname] = {}
            elif (ent["kind"] != kind
                  or list(fam.get("labelnames") or []) != src_labels[fname]
                  or (kind == "histogram"
                      and list(fam.get("bounds") or []) != ent["bounds"])):
                # version-skewed replica: same name, different semantics
                # (kind, label arity, or histogram bounds) — keep the
                # first-seen shape, count the rest
                dropped += len(fam.get("series") or ())
                continue
            slots = acc[fname]
            for s in fam.get("series") or ():
                labels = list(s.get("labels") or [])
                if kind == "counter":
                    key = tuple(labels)
                    slot = slots.get(key)
                    if slot is None:
                        slot = slots[key] = dict(labels=labels, value=0.0)
                    slot["value"] += float(s.get("value") or 0.0)
                elif kind == "histogram":
                    key = tuple(labels)
                    slot = slots.get(key)
                    counts = list(s.get("counts") or [])
                    if slot is None:
                        slot = slots[key] = dict(
                            labels=labels, counts=[0] * len(counts),
                            n=0, sum=0.0, min=None, max=None)
                    if len(slot["counts"]) != len(counts):
                        dropped += 1
                        continue
                    slot["counts"] = [a + b for a, b in
                                      zip(slot["counts"], counts)]
                    slot["n"] += int(s.get("n") or 0)
                    slot["sum"] += float(s.get("sum") or 0.0)
                    for fld, fold in (("min", min), ("max", max)):
                        v = s.get(fld)
                        if v is not None:
                            slot[fld] = (v if slot[fld] is None
                                         else fold(slot[fld], v))
                else:                    # gauge: per-replica series
                    key = tuple(labels) + (replica,)
                    slots[key] = dict(labels=labels + [replica],
                                      value=float(s.get("value") or 0.0))
    for fname, slots in acc.items():
        families[fname]["series"] = list(slots.values())
    families[_PEER_UP] = dict(
        kind="gauge",
        help="1 when this replica answered the fleet scrape, 0 when "
             "unreachable",
        labelnames=["replica"],
        series=[dict(labels=[r], value=float(up))
                for r, up in peer_up.items()])
    return dict(families=families, peer_up=dict(peer_up),
                dropped_series=dropped)


def render_prometheus(merged: Dict) -> str:
    """Prometheus text exposition (0.0.4) of a `merge_states` result —
    the ``GET /3/Metrics?scope=fleet`` body."""
    lines: List[str] = []

    def label_str(names, values, extra: str = "") -> str:
        pairs = [f'{n}="{_escape_label(v)}"'
                 for n, v in zip(names, values)]
        if extra:
            pairs.append(extra)
        return "{" + ",".join(pairs) + "}" if pairs else ""

    for fname in sorted(merged["families"]):
        fam = merged["families"][fname]
        kind = fam["kind"]
        names = fam.get("labelnames") or []
        if kind == "counter":
            expo = fname if fname.endswith("_total") else fname + "_total"
            lines.append(f"# HELP {expo} {fam.get('help', '')}")
            lines.append(f"# TYPE {expo} counter")
            for s in sorted(fam.get("series") or (),
                            key=lambda s: s["labels"]):
                lines.append(f"{expo}{label_str(names, s['labels'])} "
                             f"{_fmt_value(s['value'])}")
        elif kind == "histogram":
            lines.append(f"# HELP {fname} {fam.get('help', '')}")
            lines.append(f"# TYPE {fname} histogram")
            bounds = fam.get("bounds") or []
            for s in sorted(fam.get("series") or (),
                            key=lambda s: s["labels"]):
                cum = 0
                for b, cnt in zip(bounds, s["counts"]):
                    cum += cnt
                    le = f'le="{_fmt_value(b)}"'
                    lines.append(f"{fname}_bucket"
                                 f"{label_str(names, s['labels'], le)} {cum}")
                inf_le = 'le="+Inf"'
                lines.append(f"{fname}_bucket"
                             f"{label_str(names, s['labels'], inf_le)}"
                             f" {s['n']}")
                lines.append(f"{fname}_sum{label_str(names, s['labels'])} "
                             f"{_fmt_value(s['sum'])}")
                lines.append(f"{fname}_count{label_str(names, s['labels'])} "
                             f"{s['n']}")
        else:
            lines.append(f"# HELP {fname} {fam.get('help', '')}")
            lines.append(f"# TYPE {fname} gauge")
            for s in sorted(fam.get("series") or (),
                            key=lambda s: s["labels"]):
                lines.append(f"{fname}{label_str(names, s['labels'])} "
                             f"{_fmt_value(s['value'])}")
    return "\n".join(lines) + "\n"


def fleet_metrics_text() -> str:
    """Scrape + merge + render: the whole fleet (this process included) in
    one Prometheus body."""
    states: List[Tuple[str, Optional[Dict]]] = [
        (self_name(), _reg.export_state())]
    states += scrape_states()
    return render_prometheus(merge_states(states))


# -- trace aggregation -------------------------------------------------------

def merge_traces(traces: List[Tuple[str, Optional[Dict]]]) -> Dict:
    """Merge per-replica Chrome-trace exports into one timeline: replica i
    becomes pid i+1 with a ``process_name`` metadata track named
    ``replica:<name>``; span/thread events keep their tids within the
    replica's pid. Unreachable replicas are listed in
    ``otherData.unreachable`` instead of vanishing."""
    events: List[Dict] = []
    unreachable: List[str] = []
    for i, (name, tr) in enumerate(traces):
        pid = i + 1
        if tr is None:
            unreachable.append(name)
            continue
        events.append(dict(name="process_name", ph="M", pid=pid, tid=0,
                           args=dict(name=f"replica:{name}")))
        for ev in tr.get("traceEvents") or ():
            ev = dict(ev)
            ev["pid"] = pid
            events.append(ev)
    return dict(traceEvents=events, displayTimeUnit="ms",
                otherData=dict(source="h2o3_tpu_fleet",
                               replicas=[n for n, _ in traces],
                               unreachable=unreachable))


def fleet_trace(trace_id: Optional[str] = None) -> Dict:
    """The ``GET /3/Trace?scope=fleet`` body: this process's spans plus
    every reachable peer's, one track per replica. With `trace_id`, one
    correlated cross-process request tree (the X-H2O3-Trace-Id the client
    minted travels to every replica it touched)."""
    from . import tracing

    _parse_env_once()
    traces: List[Tuple[str, Optional[Dict]]] = [
        (self_name(), tracing.export_chrome(trace_id))]
    q = f"?trace_id={urllib.parse.quote(trace_id)}" if trace_id else ""

    def one(p):
        try:
            return (p["name"], _fetch_json(p["url"] + "/3/Trace" + q))
        except Exception:
            return (p["name"], None)

    traces += _fan_out(one, peers())
    return merge_traces(traces)


# -- the /3/Fleet document ---------------------------------------------------

# metrics_registry.bucket_percentile applied to MERGED buckets is what
# makes fleet p99 exact over the fleet's observations — and sharing the
# registry's estimator means aggregator and per-replica percentiles can
# never drift apart on identical data
_bucket_percentile = _reg.bucket_percentile


def _counter_total(state: Dict, fname: str) -> float:
    fam = state.get(fname) or {}
    return float(sum(s.get("value") or 0.0 for s in fam.get("series") or ()))


def _serving_summary(state: Dict) -> Dict:
    """Per-replica serving essentials out of one export_state payload —
    the fields loadgen's fleet report needs."""
    out = dict(
        requests=_counter_total(state, "h2o3_serving_requests"),
        errors=_counter_total(state, "h2o3_serving_errors"),
        rejections=_counter_total(state, "h2o3_serving_rejections"),
        rest_requests=_counter_total(state, "h2o3_rest_requests"),
    )
    fam = state.get("h2o3_rest_request_ms") or {}
    for s in fam.get("series") or ():
        if list(s.get("labels") or []) == ["predict"]:
            out["predict_p99_ms"] = _bucket_percentile(
                fam.get("bounds") or [], s.get("counts") or [],
                int(s.get("n") or 0), 0.99, s.get("min"), s.get("max"))
            out["predict_count"] = int(s.get("n") or 0)
            break
    return out


def snapshot(scrape: bool = True) -> Dict:
    """The ``GET /3/Fleet`` / profiler-fold document: per-replica rows
    (liveness, scrape latency, serving counters, predict p99) + fleet
    totals with the bucket-merged fleet predict p99. ``scrape=False``
    reports registration state only — no network, and no registry
    export/merge either (the /3/Profiler fold polls this; it must stay
    O(peers), not O(metric series))."""
    _parse_env_once()
    reg = _registry()

    def _totals(rows: List[Dict]) -> Dict:
        up = sum(1 for r in rows if not r["is_self"] and r["up"])
        return dict(peers=len(rows) - 1, up=up,
                    scrapes=reg["scrapes"].total(),
                    scrape_errors=reg["scrape_errors"].total())

    if not scrape:
        rows = [dict(name=self_name(), url=None, up=1, is_self=True)]
        for p in peers():
            rows.append(dict(name=p["name"], url=p["url"],
                             up=1 if p.get("up") else 0, is_self=False,
                             last_scrape_ms=p.get("last_scrape_ms"),
                             last_error=p.get("last_error")))
        return dict(replica=self_name(), peers=rows, fleet={},
                    dropped_series=0, totals=_totals(rows))

    self_state = _reg.export_state()
    rows = [dict(name=self_name(), url=None, up=1,
                 is_self=True, **_serving_summary(self_state))]
    states: List[Tuple[str, Optional[Dict]]] = [(self_name(), self_state)]
    for name, state in scrape_states():
        with _LOCK:
            meta = dict(_PEERS.get(name) or {})
        row = dict(name=name, url=meta.get("url"),
                   up=1 if state is not None else 0, is_self=False,
                   last_scrape_ms=meta.get("last_scrape_ms"),
                   last_error=meta.get("last_error"))
        if state is not None:
            row.update(_serving_summary(state))
        rows.append(row)
        states.append((name, state))
    # fleet-merged predict latency: exact percentile over summed buckets
    merged = merge_states(states)
    fleet = dict(
        requests=sum(r.get("requests") or 0 for r in rows),
        errors=sum(r.get("errors") or 0 for r in rows),
        rejections=sum(r.get("rejections") or 0 for r in rows),
    )
    fam = merged["families"].get("h2o3_rest_request_ms") or {}
    for s in fam.get("series") or ():
        if list(s.get("labels") or []) == ["predict"]:
            fleet["predict_p99_ms"] = _bucket_percentile(
                fam.get("bounds") or [], s.get("counts") or [],
                int(s.get("n") or 0), 0.99, s.get("min"), s.get("max"))
            fleet["predict_count"] = int(s.get("n") or 0)
            break
    return dict(
        replica=self_name(),
        peers=rows,
        fleet=fleet,
        dropped_series=merged["dropped_series"],
        totals=_totals(rows),
    )


def register_with(aggregator_url: str, name: str, self_url: str) -> bool:
    """Self-registration against a remote aggregator (the launcher hook:
    a rank/replica announces its REST endpoint via ``POST /3/Fleet``).
    Returns False instead of raising when the aggregator is unreachable —
    bring-up order must not matter."""
    try:
        body = urllib.parse.urlencode(dict(name=name, url=self_url)).encode()
        req = urllib.request.Request(
            aggregator_url.rstrip("/") + "/3/Fleet", data=body)
        with urllib.request.urlopen(
                req, timeout=env_float("H2O3_FLEET_TIMEOUT_S", 3.0)) as r:
            r.read()
        return True
    except Exception:
        return False
