"""Log — per-process logger with an in-memory ring exposed over REST.

Reference parity: `h2o-core/src/main/java/water/util/Log.java` (per-node
rotating log files + levels TRACE..FATAL) and `water/api/LogsHandler.java`
(`/3/Logs/download` serves the ring). One process per TPU host plays the role
of one H2O node, so one ring + one file per process.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from typing import List, Optional

LEVELS = ("TRACE", "DEBUG", "INFO", "WARN", "ERRR", "FATAL")


class Log:
    _ring: deque = deque(maxlen=10000)
    _lock = threading.Lock()
    _file = None
    _level = "INFO"

    @classmethod
    def set_level(cls, level: str):
        if level not in LEVELS:
            raise ValueError(f"bad log level {level!r}")
        cls._level = level

    @classmethod
    def set_log_dir(cls, path: Optional[str]):
        with cls._lock:
            if cls._file:
                cls._file.close()
                cls._file = None
            if path:
                os.makedirs(path, exist_ok=True)
                fname = os.path.join(path, f"h2o3tpu_{os.getpid()}.log")
                cls._file = open(fname, "a", buffering=1)

    @classmethod
    def _write(cls, level: str, msg: str):
        if LEVELS.index(level) < LEVELS.index(cls._level):
            return
        ts = time.strftime("%m-%d %H:%M:%S")
        line = f"{ts} {os.getpid()} {level} {msg}"
        with cls._lock:
            cls._ring.append(line)
            if cls._file:
                cls._file.write(line + "\n")

    @classmethod
    def trace(cls, msg):
        cls._write("TRACE", str(msg))

    @classmethod
    def debug(cls, msg):
        cls._write("DEBUG", str(msg))

    @classmethod
    def info(cls, msg):
        cls._write("INFO", str(msg))

    @classmethod
    def warn(cls, msg):
        cls._write("WARN", str(msg))

    @classmethod
    def err(cls, msg):
        cls._write("ERRR", str(msg))

    @classmethod
    def get_logs(cls, n: int = 1000) -> List[str]:
        with cls._lock:
            return list(cls._ring)[-n:]

    @classmethod
    def clear(cls):
        with cls._lock:
            cls._ring.clear()
