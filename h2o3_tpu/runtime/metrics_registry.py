"""Central metrics registry — the one scrape surface for every subsystem.

Before this module, observability was five disconnected cumulative-counter
snapshots (serving / ingest / munge / training / faults) with no time
series, no percentiles and no machine-scrapable surface. The registry is
the spine underneath them: every subsystem registers its counters, gauges
and histograms HERE, and three read surfaces are derived from the one
store —

- ``prometheus_text()`` → ``GET /3/Metrics`` (Prometheus/OpenMetrics text
  exposition: ``# HELP``/``# TYPE`` lines, ``_total`` counter suffixes,
  ``_bucket{le=...}``/``_sum``/``_count`` histogram series);
- ``snapshot()`` → the JSON fold in ``/3/Profiler``;
- per-metric reads (``Counter.value()``, ``Histogram.percentile(q)``,
  ``Counter.rate(window_s)``) for tests and the bench driver.

Semantics follow Prometheus, not the legacy snapshot modules: registry
counters are MONOTONE for the life of the process (module-level ``reset()``
helpers reset the REST-snapshot state, never the scrape surface), so two
scrapes always see non-decreasing counters. The legacy ``/3/*/metrics``
endpoints stay byte-compatible — their modules dual-write (their resettable
snapshot state AND the registry) and declare which REST field each registry
metric backs via ``bind_rest_field``; the metrics-consistency test walks
those bindings so a new counter can never ship outside the scrape surface.

Cost discipline (the idle-overhead acceptance pin): one ``threading.Lock``
per metric child, a handful of float/int adds per record, and a ring-buffer
time-series sample AT MOST once per ``H2O3_METRICS_RING_INTERVAL_S``
(default 1 s) — no background thread, no per-request allocation beyond the
occasional (ts, value) tuple.

Naming convention (docs/observability.md): ``h2o3_<subsystem>_<what>`` +
unit suffix (``_total`` for counters, ``_ms``/``_s``/``_bytes`` inside
histogram/gauge names). Labels are a fixed tuple per family, declared at
registration.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from . import env_float, env_int

__all__ = ["Counter", "Gauge", "Histogram", "counter", "gauge", "histogram",
           "get", "names", "snapshot", "prometheus_text", "export_state",
           "bind_rest_field", "rest_bindings", "register_collect_hook",
           "bucket_percentile", "LATENCY_MS_BOUNDS"]

# shared fixed latency buckets (ms): serving, loadgen and REST request
# histograms all bin into the same bounds so percentiles are comparable
# across surfaces ("the shared histogram buckets" of the loadgen satellite)
LATENCY_MS_BOUNDS = (0.5, 1, 2, 5, 10, 25, 50, 100, 250, 500, 1000, 2500,
                     5000, 10000, 30000)

_RING_LEN = env_int("H2O3_METRICS_RING", 240)
_RING_INTERVAL_S = env_float("H2O3_METRICS_RING_INTERVAL_S", 1.0)
# label cardinality bound per family: registry series are monotone for
# the life of the process, so an unbounded label (uuid-suffixed model
# keys on a fleet that trains/serves/deletes forever) would grow memory
# and the scrape body without limit — past the cap, new label tuples
# collapse into one "_overflow" series (totals stay correct; per-label
# resolution is what saturates)
_MAX_SERIES = env_int("H2O3_METRICS_MAX_SERIES", 256)
_OVERFLOW = "_overflow"


def bucket_percentile(bounds, counts, n, q, vmin=None, vmax=None):
    """Bucket-interpolated q-quantile (q in [0,1]) over raw (bounds,
    counts): linear interpolation within the owning bucket, min/max
    clamping the open-ended buckets. The ONE estimator — shared by
    `Histogram.percentile` and the fleet aggregator's merged-bucket
    percentiles (runtime/fleet), so a per-replica p99 and the fleet p99
    can never disagree on identical data."""
    if not n:
        return None
    rank = q * (n - 1)
    cum = 0
    for i, cnt in enumerate(counts):
        if cnt == 0:
            continue
        if rank < cum + cnt:
            lo = bounds[i - 1] if i > 0 else (
                vmin if vmin is not None else 0.0)
            hi = bounds[i] if i < len(bounds) else (
                vmax if vmax is not None else lo)
            lo = max(lo, vmin) if vmin is not None else lo
            hi = min(hi, vmax) if vmax is not None else hi
            if hi <= lo:
                return float(lo)
            frac = (rank - cum + 1) / cnt if cnt > 1 else 0.5
            frac = min(max(frac, 0.0), 1.0)
            return float(lo + (hi - lo) * frac)
        cum += cnt
    return vmax


def _sanitize_name(name: str) -> str:
    out = []
    for i, ch in enumerate(name):
        ok = ch.isascii() and (ch.isalpha() or ch == "_"
                               or (ch.isdigit() and i > 0))
        out.append(ch if ok else "_")
    return "".join(out)


def _escape_label(v: str) -> str:
    return (str(v).replace("\\", r"\\").replace("\n", r"\n")
            .replace('"', r'\"'))


def _fmt_value(v: float) -> str:
    if v != v:
        return "NaN"
    if v in (float("inf"), float("-inf")):
        return "+Inf" if v > 0 else "-Inf"
    f = float(v)
    return repr(int(f)) if f == int(f) and abs(f) < 1e15 else repr(f)


class _Child:
    """One labeled series of a family: the actual mutation target. Every
    update takes this child's own lock and nothing else — the registry
    lock guards only registration, so a counter add never contends with a
    scrape or another family."""

    __slots__ = ("labels", "_lock", "_v", "_ring", "_t_sample")

    def __init__(self, labels: Tuple[str, ...]):
        self.labels = labels
        self._lock = threading.Lock()
        self._v = 0.0
        self._ring: Optional[deque] = None
        self._t_sample = 0.0

    def _add(self, by: float, ring: bool) -> None:
        with self._lock:
            self._v += by
            if ring:
                now = time.time()
                if now - self._t_sample >= _RING_INTERVAL_S:
                    if self._ring is None:
                        self._ring = deque(maxlen=_RING_LEN)
                    self._ring.append((now, self._v))
                    self._t_sample = now

    def _set(self, v: float) -> None:
        with self._lock:
            self._v = float(v)

    def value(self) -> float:
        with self._lock:
            return self._v

    def rate(self, window_s: float = 60.0) -> Optional[float]:
        """Windowed per-second rate from the ring-buffer time series, or
        None before two samples land inside the window."""
        with self._lock:
            if not self._ring or len(self._ring) < 2:
                return None
            now, v_now = time.time(), self._v
            cutoff = now - window_s
            base = None
            for t, v in self._ring:
                if t >= cutoff:
                    base = (t, v)
                    break
            if base is None or now - base[0] <= 1e-9:
                return None
            return (v_now - base[1]) / (now - base[0])

    def series(self) -> List[Tuple[float, float]]:
        with self._lock:
            return list(self._ring or ())


class _Metric:
    """One metric family: name + help + fixed label names + children."""

    kind = "untyped"

    def __init__(self, name: str, help: str, labelnames: Sequence[str] = ()):
        self.name = _sanitize_name(name)
        self.help = help
        self.labelnames = tuple(labelnames)
        self._lock = threading.Lock()
        self._children: Dict[Tuple[str, ...], _Child] = {}

    def _child(self, labelvalues: Tuple[str, ...]) -> _Child:
        if len(labelvalues) != len(self.labelnames):
            raise ValueError(
                f"{self.name}: expected labels {self.labelnames}, got "
                f"{labelvalues}")
        c = self._children.get(labelvalues)
        if c is None:
            with self._lock:
                c = self._children.get(labelvalues)
                if c is None:
                    if self.labelnames and len(self._children) >= _MAX_SERIES:
                        labelvalues = (_OVERFLOW,) * len(self.labelnames)
                        c = self._children.get(labelvalues)
                        if c is None:
                            c = self._children[labelvalues] = \
                                self._make_child(labelvalues)
                    else:
                        c = self._children[labelvalues] = self._make_child(
                            labelvalues)
        return c

    def _make_child(self, labelvalues: Tuple[str, ...]) -> _Child:
        return _Child(labelvalues)

    def children(self) -> Dict[Tuple[str, ...], _Child]:
        with self._lock:
            return dict(self._children)

    def _label_str(self, labelvalues: Tuple[str, ...],
                   extra: str = "") -> str:
        pairs = [f'{n}="{_escape_label(v)}"'
                 for n, v in zip(self.labelnames, labelvalues)]
        if extra:
            pairs.append(extra)
        return "{" + ",".join(pairs) + "}" if pairs else ""


class Counter(_Metric):
    """Monotone counter (optionally labeled) with a bounded ring-buffer
    time series per child for windowed rates."""

    kind = "counter"

    def inc(self, by: float = 1.0, *labelvalues) -> None:
        if by < 0:
            raise ValueError(f"{self.name}: counters only go up (by={by})")
        self._child(tuple(str(v) for v in labelvalues))._add(by, ring=True)

    def value(self, *labelvalues) -> float:
        key = tuple(str(v) for v in labelvalues)
        if key not in self._children:
            return 0.0
        return self._child(key).value()

    def total(self) -> float:
        return sum(c.value() for c in self.children().values())

    def rate(self, window_s: float = 60.0, *labelvalues) -> Optional[float]:
        key = tuple(str(v) for v in labelvalues)
        if key not in self._children:
            return None
        return self._child(key).rate(window_s)

    def expo_lines(self) -> List[str]:
        name = self.name if self.name.endswith("_total") \
            else self.name + "_total"
        out = [f"# HELP {name} {self.help}", f"# TYPE {name} counter"]
        kids = self.children() or ({(): _Child(())} if not self.labelnames
                                   else {})
        for lv, c in sorted(kids.items()):
            out.append(f"{name}{self._label_str(lv)} {_fmt_value(c.value())}")
        return out


class Gauge(_Metric):
    """Settable value, or a callback sampled at scrape time."""

    kind = "gauge"

    def __init__(self, name: str, help: str, labelnames: Sequence[str] = (),
                 fn: Optional[Callable[[], float]] = None):
        super().__init__(name, help, labelnames)
        if fn is not None and labelnames:
            raise ValueError("callback gauges cannot be labeled")
        self._fn = fn

    def set(self, v: float, *labelvalues) -> None:
        self._child(tuple(str(x) for x in labelvalues))._set(v)

    def remove_series(self, *labelvalues) -> bool:
        """Drop one labeled series. A gauge is CURRENT state — a series
        whose subject no longer exists (a deregistered fleet peer, say)
        must leave the scrape rather than freeze at its last value.
        Counters stay monotone for the life of the process; only gauges
        expose removal."""
        key = tuple(str(x) for x in labelvalues)
        with self._lock:
            return self._children.pop(key, None) is not None

    def value(self, *labelvalues) -> float:
        if self._fn is not None:
            try:
                return float(self._fn())
            except Exception:
                return float("nan")
        key = tuple(str(v) for v in labelvalues)
        if key not in self._children:
            return 0.0
        return self._child(key).value()

    def expo_lines(self) -> List[str]:
        out = [f"# HELP {self.name} {self.help}",
               f"# TYPE {self.name} gauge"]
        if self._fn is not None:
            out.append(f"{self.name} {_fmt_value(self.value())}")
            return out
        kids = self.children() or ({(): _Child(())} if not self.labelnames
                                   else {})
        for lv, c in sorted(kids.items()):
            out.append(
                f"{self.name}{self._label_str(lv)} {_fmt_value(c.value())}")
        return out


class _HistChild(_Child):
    __slots__ = ("counts", "n", "total", "vmin", "vmax")

    def __init__(self, labels: Tuple[str, ...], nbuckets: int):
        super().__init__(labels)
        self.counts = [0] * nbuckets
        self.n = 0
        self.total = 0.0
        self.vmin: Optional[float] = None
        self.vmax: Optional[float] = None


class Histogram(_Metric):
    """Fixed-bound histogram: counts per bucket + running sum/min/max.

    The state is O(len(bounds)) regardless of observation count, so a
    snapshot is cheap and percentiles are estimated by linear interpolation
    inside the owning bucket (tested against numpy within bucket-width
    tolerance). The last bucket is +Inf overflow."""

    kind = "histogram"

    def __init__(self, name: str, help: str,
                 bounds: Sequence[float] = LATENCY_MS_BOUNDS,
                 labelnames: Sequence[str] = ()):
        super().__init__(name, help, labelnames)
        b = tuple(float(x) for x in bounds)
        if list(b) != sorted(b) or len(set(b)) != len(b):
            raise ValueError(f"{name}: bounds must be strictly increasing")
        self.bounds = b

    def _make_child(self, labelvalues: Tuple[str, ...]) -> _HistChild:
        return _HistChild(labelvalues, len(self.bounds) + 1)

    def observe(self, v: float, *labelvalues) -> None:
        c = self._child(tuple(str(x) for x in labelvalues))
        i = len(self.bounds)
        for j, b in enumerate(self.bounds):
            if v <= b:
                i = j
                break
        with c._lock:
            c.counts[i] += 1
            c.n += 1
            c.total += v
            c.vmin = v if c.vmin is None else min(c.vmin, v)
            c.vmax = v if c.vmax is None else max(c.vmax, v)

    def _counts(self, *labelvalues) -> Tuple[List[int], int, float,
                                             Optional[float],
                                             Optional[float]]:
        # read path must not materialize a series: probing an unknown
        # label (typo'd model key, dashboard helper) would otherwise add
        # a permanent all-zero family child to the scrape and burn a slot
        # of the series-cardinality cap
        key = tuple(str(x) for x in labelvalues)
        c = self._children.get(key)
        if c is None:
            return [0] * (len(self.bounds) + 1), 0, 0.0, None, None
        with c._lock:
            return list(c.counts), c.n, c.total, c.vmin, c.vmax

    def percentile(self, q: float, *labelvalues) -> Optional[float]:
        """Estimate the q-quantile (q in [0,1]) by linear interpolation
        within the owning bucket; min/max clamp the open-ended buckets."""
        counts, n, _total, vmin, vmax = self._counts(*labelvalues)
        return bucket_percentile(self.bounds, counts, n, q, vmin, vmax)

    def summary(self, *labelvalues) -> Dict:
        """The legacy LatencyHistogram.snapshot() shape + percentiles, so
        /3/Serving/metrics histograms stay byte-compatible where they were
        and gain p50/p95/p99 where they're new."""
        counts, n, total, vmin, vmax = self._counts(*labelvalues)
        return dict(
            bounds=list(self.bounds), counts=counts, count=n,
            mean=round(total / n, 4) if n else None,
            min=vmin, max=vmax,
            p50=self.percentile(0.50, *labelvalues),
            p95=self.percentile(0.95, *labelvalues),
            p99=self.percentile(0.99, *labelvalues),
        )

    def expo_lines(self) -> List[str]:
        out = [f"# HELP {self.name} {self.help}",
               f"# TYPE {self.name} histogram"]
        for lv, c in sorted(self.children().items()):
            with c._lock:
                counts, n, total = list(c.counts), c.n, c.total
            cum = 0
            for b, cnt in zip(self.bounds, counts):
                cum += cnt
                le = f'le="{_fmt_value(b)}"'
                out.append(f"{self.name}_bucket"
                           f"{self._label_str(lv, le)} {cum}")
            inf_le = 'le="+Inf"'
            out.append(f"{self.name}_bucket"
                       f"{self._label_str(lv, inf_le)} {n}")
            out.append(f"{self.name}_sum{self._label_str(lv)} "
                       f"{_fmt_value(total)}")
            out.append(f"{self.name}_count{self._label_str(lv)} {n}")
        return out


# -- the registry -------------------------------------------------------------

_LOCK = threading.Lock()
_METRICS: Dict[str, _Metric] = {}
# endpoint → {field_path: metric_name}: which registry metric backs each
# REST snapshot field (the metrics-consistency test walks this)
_REST_BINDINGS: Dict[str, Dict[str, str]] = {}


def _register(cls, name: str, help: str, **kw) -> _Metric:
    name = _sanitize_name(name)
    with _LOCK:
        m = _METRICS.get(name)
        if m is not None:
            if type(m) is not cls:
                raise ValueError(
                    f"metric {name!r} already registered as {m.kind}")
            return m
        m = _METRICS[name] = cls(name, help, **kw)
        return m


def counter(name: str, help: str = "",
            labelnames: Sequence[str] = ()) -> Counter:
    """Get-or-create a counter family (idempotent by name)."""
    return _register(Counter, name, help, labelnames=labelnames)


def gauge(name: str, help: str = "", labelnames: Sequence[str] = (),
          fn: Optional[Callable[[], float]] = None) -> Gauge:
    return _register(Gauge, name, help, labelnames=labelnames, fn=fn)


def histogram(name: str, help: str = "",
              bounds: Sequence[float] = LATENCY_MS_BOUNDS,
              labelnames: Sequence[str] = ()) -> Histogram:
    return _register(Histogram, name, help, bounds=bounds,
                     labelnames=labelnames)


def get(name: str) -> Optional[_Metric]:
    with _LOCK:
        return _METRICS.get(_sanitize_name(name))


def names() -> List[str]:
    with _LOCK:
        return sorted(_METRICS)


def bind_rest_field(endpoint: str, field_path: str, metric_name: str) -> None:
    """Declare that `field_path` of `/3/{endpoint}/metrics` is backed by
    registry metric `metric_name` — the contract the metrics-consistency
    test enforces (every declared field's metric must exist AND appear in
    GET /3/Metrics; every counter-ish snapshot field must be declared)."""
    with _LOCK:
        _REST_BINDINGS.setdefault(endpoint, {})[field_path] = \
            _sanitize_name(metric_name)


def rest_bindings() -> Dict[str, Dict[str, str]]:
    with _LOCK:
        return {k: dict(v) for k, v in _REST_BINDINGS.items()}


# pull-model gauges: hooks run at the top of every scrape/snapshot so
# subsystems that account state on demand (the memory ledger) refresh
# their set-gauges no staler than one scrape — the Prometheus custom-
# collector stance without per-family collector plumbing
_COLLECT_HOOKS: List[Callable[[], None]] = []


def register_collect_hook(fn: Callable[[], None]) -> None:
    """Run `fn` before every prometheus_text()/snapshot() read (idempotent
    by identity). Hooks must be cheap or self-rate-limited; a raising hook
    is skipped, never fails the scrape."""
    with _LOCK:
        if fn not in _COLLECT_HOOKS:
            _COLLECT_HOOKS.append(fn)


def _run_collect_hooks() -> None:
    with _LOCK:
        hooks = list(_COLLECT_HOOKS)
    for fn in hooks:
        try:
            fn()
        except Exception:
            pass


def snapshot() -> Dict:
    """JSON view of every family (the /3/Profiler `metrics` fold): value
    per child for counters/gauges, summary for histograms, plus 60s
    windowed rates where a time series exists."""
    _run_collect_hooks()
    with _LOCK:
        metrics = dict(_METRICS)
    out: Dict[str, Dict] = {}
    for name, m in sorted(metrics.items()):
        fam: Dict = dict(kind=m.kind, help=m.help)
        if isinstance(m, Histogram):
            fam["series"] = {
                ",".join(lv) or "": m.summary(*lv)
                for lv in m.children()}
        elif isinstance(m, Gauge) and m._fn is not None:
            fam["value"] = m.value()
        else:
            ser = {}
            for lv, c in m.children().items():
                d: Dict = dict(value=c.value())
                r = c.rate(60.0)
                if r is not None:
                    d["rate_1m"] = round(r, 3)
                ser[",".join(lv) or ""] = d
            fam["series"] = ser
        out[name] = fam
    return out


def export_state() -> Dict:
    """LOSSLESS JSON view of every family — the cross-process aggregation
    payload (``GET /3/Metrics?format=json``, consumed by runtime/fleet).

    Unlike `snapshot()` (a human/profiler fold whose label tuples are
    joined into display strings and whose histograms carry derived
    percentiles), this export preserves exactly what merging needs:
    labelnames + raw per-child label value lists, raw counter/gauge
    values, and histogram bounds + per-bucket counts + sum/min/max — so an
    aggregator can SUM counters, bucket-merge histograms (keeping
    p50/p95/p99 exact over the merged buckets) and keep gauges per-peer."""
    _run_collect_hooks()
    with _LOCK:
        metrics = dict(_METRICS)
    out: Dict[str, Dict] = {}
    for name, m in sorted(metrics.items()):
        fam: Dict = dict(kind=m.kind, help=m.help,
                         labelnames=list(m.labelnames))
        if isinstance(m, Histogram):
            fam["bounds"] = list(m.bounds)
            series = []
            for lv, c in sorted(m.children().items()):
                with c._lock:
                    series.append(dict(labels=list(lv),
                                       counts=list(c.counts), n=c.n,
                                       sum=c.total, min=c.vmin, max=c.vmax))
            fam["series"] = series
        elif isinstance(m, Gauge) and m._fn is not None:
            fam["series"] = [dict(labels=[], value=m.value())]
        else:
            fam["series"] = [dict(labels=list(lv), value=c.value())
                             for lv, c in sorted(m.children().items())]
        out[name] = fam
    return out


def prometheus_text() -> str:
    """The GET /3/Metrics body — Prometheus text exposition format 0.0.4.

    Families are emitted sorted by name, each with exactly one HELP/TYPE
    pair; label-less counters that never fired still expose a 0 sample so
    dashboards can alert on absence-of-traffic rather than absence-of-
    metric."""
    _run_collect_hooks()
    with _LOCK:
        metrics = dict(_METRICS)
    lines: List[str] = []
    for _name, m in sorted(metrics.items()):
        lines.extend(m.expo_lines())
    return "\n".join(lines) + "\n"
