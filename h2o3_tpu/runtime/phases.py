"""Per-phase wall-clock and byte accounting for benchmarks.

Decomposes a training run's wall-clock into the phases that matter on a
remote chip behind a slow tunnel — {h2d_s, compile_s, deserialize_s,
trace_s, compute_s (residual), bytes_h2d} — so "fast" is auditable per
phase instead of one conflated number (the reference logs per-stage Timer
lines, `water/util/Timer` + `water/H2O` timeline; here the decomposition
feeds bench.py's JSON).

Two sources:
- jax monitoring events (always cheap): `backend_compile_duration` →
  compile, `jaxpr_trace/`mlir_module` → trace, persistent-cache
  retrievals → deserialize.
- explicit instrumentation at the few fat host→device transfer points
  (`accounted_h2d`). Through the axon tunnel device_put is async, so
  measuring real transfer time needs a one-element D2H barrier after the
  put — that would serialize transfers a production run deliberately
  overlaps, so the barrier only happens when accounting is enabled
  (H2O3_PHASE_ACCOUNTING=1, set by bench.py). Byte counts are recorded
  unconditionally.
"""

from __future__ import annotations

import itertools
import os
import sys
import threading
import time
import weakref
import zlib
from collections import OrderedDict, defaultdict
from contextlib import contextmanager

_LOCK = threading.Lock()
_SECS: dict = defaultdict(float)
_BYTES: dict = defaultdict(int)
_installed = False

# -- XLA compile/retrace tracker ---------------------------------------------
# Counts compiles / traces / persistent-cache retrievals per program
# signature, so "warm cache never re-traces" is a pinned counter instead of
# a monkeypatch test, and the fused-GBM work can prove per-level retrace
# count == 0. A "retrace" is any trace event for a signature that has
# already traced at least once this process.
_XLA_LOCK = threading.Lock()
_XLA_TOTALS = dict(compiles=0, traces=0, retraces=0, cache_retrievals=0,
                   persistent_cache_hits=0, persistent_cache_misses=0)
_XLA_PER_SIG: "OrderedDict[str, dict]" = OrderedDict()
_XLA_SIG_CAP = 512
# stable per-process serial for each live traced-function object (see
# _xla_signature): weakref-keyed so a dead function's serial dies with it
# instead of its id being recycled into another program's identity
_SIG_SERIALS: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()
_SIG_NEXT = itertools.count(1)


def _fun_serial(obj) -> int:
    try:
        with _XLA_LOCK:
            s = _SIG_SERIALS.get(obj)
            if s is None:
                s = _SIG_SERIALS[obj] = next(_SIG_NEXT)
            return s
    except TypeError:          # not hashable / not weakref-able
        return 0


def _xla_signature() -> str:
    """Attribute a compile-pipeline event to the PROGRAM that triggered it.

    jax emits every duration event from `dispatch.log_elapsed_time`, whose
    generator frame carries the jitted program's name in its `fun_name`
    local (`fun.__name__` for jaxpr traces, the computation name for
    backend compiles); persistent-cache retrievals fire from
    `compiler.py` with `module_name` in scope. That is real program
    identity — stable regardless of which tracing span happens to be open,
    so the retrace pin neither fires on two different programs compiled
    under one span nor misses the same program re-traced under
    differently-named spans.

    Trace events additionally join jax's own cache identity, read from the
    `memoized_fun` caller frame: the lu cache is `fun_caches[fun.f][key]`
    and the event fires exactly when it misses, so "same (fun.f, key)
    traced twice" is by construction "jax re-traced the identical
    program". `fun.f` is identified by a weakref-keyed serial — a new
    function object (e.g. the fresh `partial` each eager primitive bakes
    its static shape into) gets a fresh serial, never a recycled id, so
    two shape buckets are distinct first traces and a GC'd function can
    never alias a live one into a fabricated retrace. `in_type` (input
    avals) is the fallback digest. Only runs on compile/trace events,
    which are rare by design."""
    try:
        f = sys._getframe(1)
        depth = 0
        while f is not None and depth < 40:
            loc = f.f_locals
            if f.f_code.co_name == "log_elapsed_time" and "fun_name" in loc:
                sig = str(loc["fun_name"])
                g, hops, avals = f.f_back, 0, None
                while g is not None and hops < 6:
                    gl = g.f_locals
                    if g.f_code.co_name == "memoized_fun" and "key" in gl:
                        base = gl.get("fun")
                        base = getattr(base, "f", base)
                        return "%s/%d.%016x" % (
                            sig, _fun_serial(base),
                            hash(gl["key"]) & 0xFFFFFFFFFFFFFFFF)
                    if avals is None and "in_type" in gl:
                        avals = str(gl["in_type"])
                    g = g.f_back
                    hops += 1
                if avals is not None:
                    sig += "/%08x" % (zlib.crc32(avals.encode())
                                      & 0xFFFFFFFF)
                return sig
            if "module_name" in loc and "cache_key" in loc:
                return str(loc["module_name"])
            f = f.f_back
            depth += 1
    except Exception:
        pass
    # unknown emission site (a future jax moved the locals): one shared
    # bucket that never counts retraces — missing a real retrace beats
    # fabricating one into a pinned counter
    return "unattributed"


_XLA_REG: dict = {}


def _xla_counters() -> dict:
    """Memoized registry families for the XLA event counters — counting a
    compile-pipeline event must not take the registry's registration lock
    (same stance as every other subsystem's memoized _registry())."""
    if not _XLA_REG:
        from . import metrics_registry as _reg

        for kind in ("compiles", "traces", "cache_retrievals"):
            _XLA_REG[kind] = _reg.counter(
                f"h2o3_xla_{kind}",
                f"XLA compile-pipeline {kind} observed via jax monitoring")
        _XLA_REG["retraces"] = _reg.counter(
            "h2o3_xla_retraces",
            "trace events for an already-traced program signature "
            "(a warm path must keep this flat)")
    return _XLA_REG


def _xla_count(kind: str, sig: str) -> None:
    retraced = False
    with _XLA_LOCK:
        _XLA_TOTALS[kind] += 1
        d = _XLA_PER_SIG.get(sig)
        if d is None:
            d = _XLA_PER_SIG[sig] = dict(compiles=0, traces=0, retraces=0,
                                         cache_retrievals=0)
            while len(_XLA_PER_SIG) > _XLA_SIG_CAP:
                _XLA_PER_SIG.popitem(last=False)
        if kind in d:
            if (kind == "traces" and d["traces"] >= 1
                    and sig != "unattributed"):
                retraced = True
                d["retraces"] += 1
                _XLA_TOTALS["retraces"] += 1
            d[kind] += 1
    reg = _xla_counters()
    reg[kind].inc()
    if retraced:
        reg["retraces"].inc()
    # candidate/batch/request correlation lives on the span as an event
    # annotation, NOT in the signature — span names must not leak into
    # program identity
    try:
        from . import tracing

        tracing.event(f"xla_{kind}", sig=sig)
        if retraced:
            tracing.event("xla_retrace", sig=sig)
    except Exception:
        pass


def xla_counts() -> dict:
    """Cumulative compile/trace/retrace/cache totals (bench JSON embed +
    the warm-path counter pins)."""
    with _XLA_LOCK:
        return dict(_XLA_TOTALS)


def xla_snapshot() -> dict:
    """Totals + per-program-signature breakdown (most recent signatures
    first, bounded)."""
    with _XLA_LOCK:
        sigs = {k: dict(v) for k, v in reversed(_XLA_PER_SIG.items())}
        return dict(totals=dict(_XLA_TOTALS), signatures=sigs)

# per-candidate attribution: a training worker (runtime/trainpool.py)
# installs a thread-local sink around one candidate's fit, and every add()
# from that thread (driver phase marks AND the jax monitoring listener,
# which fires in the dispatching thread) is mirrored into it — so
# /3/Training/metrics can report per-candidate h2d/compile/host_prep even
# when several candidates train concurrently.
_TLS = threading.local()


@contextmanager
def candidate_sink():
    """Install a thread-local phase sink; yields {'secs': {}, 'bytes': {}}."""
    d = {"secs": {}, "bytes": {}}
    prev = getattr(_TLS, "sink", None)
    _TLS.sink = d
    try:
        yield d
    finally:
        _TLS.sink = prev

ENABLED = os.environ.get("H2O3_PHASE_ACCOUNTING", "").lower() not in (
    "", "0", "false", "no")

# phases the jax monitoring listener owns; accounted_h2d subtracts their
# concurrent growth so first-call compilation isn't booked as transfer
COMPILE_KEYS = ("compile", "trace", "deserialize")


def add(phase: str, secs: float = 0.0, nbytes: int = 0) -> None:
    with _LOCK:
        _SECS[phase] += secs
        if nbytes:
            _BYTES[phase] += nbytes
    sink = getattr(_TLS, "sink", None)
    if sink is not None:   # thread-local — no lock needed
        sink["secs"][phase] = sink["secs"].get(phase, 0.0) + secs
        if nbytes:
            sink["bytes"][phase] = sink["bytes"].get(phase, 0) + nbytes


def reset() -> None:
    with _LOCK:
        _SECS.clear()
        _BYTES.clear()


def totals(keys) -> float:
    """Sum of accumulated seconds over the given phase keys."""
    with _LOCK:
        return sum(_SECS.get(k, 0.0) for k in keys)


def snapshot() -> dict:
    """Accumulated seconds per phase + bytes for transfer phases."""
    with _LOCK:
        out = {f"{k}_s": round(v, 3) for k, v in _SECS.items()}
        out.update({f"bytes_{k}": v for k, v in _BYTES.items()})
        return out


@contextmanager
def timed(phase: str, nbytes: int = 0):
    t0 = time.perf_counter()
    try:
        yield
    finally:
        add(phase, time.perf_counter() - t0, nbytes)


def accounted_h2d(thunk, nbytes: int):
    """Run `thunk()` (a host→device transfer, possibly fused with a small
    on-device expand program) with H2D time/byte accounting.

    When accounting is off the thunk runs untouched (only the byte count is
    recorded); when on, a one-element fetch after it makes the recorded
    seconds actual transfer time — through the axon tunnel
    block_until_ready returns before data lands, so a tiny D2H is the only
    reliable barrier. Compile time the call triggers (first-call jit of the
    expand program) is already accounted by the monitoring listener and is
    subtracted out.
    """
    if not ENABLED:
        add("h2d", 0.0, nbytes)
        return thunk()
    import jax
    import numpy as np

    install_listener()
    comp0 = totals(COMPILE_KEYS)
    t0 = time.perf_counter()
    out = thunk()
    try:
        np.asarray(out.ravel()[:1] if hasattr(out, "ravel") else out)
    except Exception:
        jax.block_until_ready(out)
    elapsed = time.perf_counter() - t0 - (totals(COMPILE_KEYS) - comp0)
    add("h2d", max(elapsed, 0.0), nbytes)
    return out


def add_mark(name: str, secs: float) -> None:
    """Fold a training-driver phase boundary (shared_tree._Phase.mark) into
    the canonical phase buckets bench.py reports."""
    if name == "device_put":
        phase = "h2d"
    elif name.endswith("_D2H"):
        phase = "d2h"
    elif name.startswith("chunk_") or name in ("train_loop_dispatch",
                                               "forest_devkeep"):
        phase = "compute"
    elif name in ("frame_to_matrix", "build_bins", "forest_unpack"):
        phase = "host_prep"
    elif name == "training_metrics":
        phase = "metrics"
    else:
        phase = "other"
    add(phase, secs)


def install_listener() -> None:
    """Register the jax monitoring listener (idempotent).

    Maps compile-pipeline event durations onto phases: backend compilation,
    host-side trace/lowering, and persistent-cache executable retrieval
    (the ~4 s/program 'deserialize' cost on cache-warm tunnel runs).
    """
    global _installed
    with _LOCK:
        if _installed:
            return
        _installed = True
    from jax._src import monitoring

    def _on(event: str, duration: float, **kw) -> None:
        if "backend_compile" in event:
            add("compile", duration)
            _xla_count("compiles", _xla_signature())
        elif "jaxpr_trace" in event or "mlir_module" in event:
            add("trace", duration)
            if "jaxpr_trace" in event:
                # one logical trace per program: the mlir lowering event of
                # the same compile must not double-count it
                _xla_count("traces", _xla_signature())
        elif "cache_retrieval" in event or "deserialize" in event:
            add("deserialize", duration)
            _xla_count("cache_retrievals", _xla_signature())

    def _on_event(event: str, **kw) -> None:
        # persistent compilation-cache hit/miss counts (no duration)
        if "compilation_cache/cache_hits" in event:
            with _XLA_LOCK:
                _XLA_TOTALS["persistent_cache_hits"] += 1
        elif "compilation_cache/cache_misses" in event:
            with _XLA_LOCK:
                _XLA_TOTALS["persistent_cache_misses"] += 1

    monitoring.register_event_duration_secs_listener(_on)
    monitoring.register_event_listener(_on_event)


if ENABLED:
    # self-contained accounting: a user script that sets the env flag gets
    # the compile/trace listener without having to know bench.py calls this
    install_listener()
