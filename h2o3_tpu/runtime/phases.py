"""Per-phase wall-clock and byte accounting for benchmarks.

Decomposes a training run's wall-clock into the phases that matter on a
remote chip behind a slow tunnel — {h2d_s, compile_s, deserialize_s,
trace_s, compute_s (residual), bytes_h2d} — so "fast" is auditable per
phase instead of one conflated number (the reference logs per-stage Timer
lines, `water/util/Timer` + `water/H2O` timeline; here the decomposition
feeds bench.py's JSON).

Two sources:
- jax monitoring events (always cheap): `backend_compile_duration` →
  compile, `jaxpr_trace/`mlir_module` → trace, persistent-cache
  retrievals → deserialize.
- explicit instrumentation at the few fat host→device transfer points
  (`accounted_h2d`). Through the axon tunnel device_put is async, so
  measuring real transfer time needs a one-element D2H barrier after the
  put — that would serialize transfers a production run deliberately
  overlaps, so the barrier only happens when accounting is enabled
  (H2O3_PHASE_ACCOUNTING=1, set by bench.py). Byte counts are recorded
  unconditionally.
"""

from __future__ import annotations

import os
import threading
import time
from collections import defaultdict
from contextlib import contextmanager

_LOCK = threading.Lock()
_SECS: dict = defaultdict(float)
_BYTES: dict = defaultdict(int)
_installed = False

# per-candidate attribution: a training worker (runtime/trainpool.py)
# installs a thread-local sink around one candidate's fit, and every add()
# from that thread (driver phase marks AND the jax monitoring listener,
# which fires in the dispatching thread) is mirrored into it — so
# /3/Training/metrics can report per-candidate h2d/compile/host_prep even
# when several candidates train concurrently.
_TLS = threading.local()


@contextmanager
def candidate_sink():
    """Install a thread-local phase sink; yields {'secs': {}, 'bytes': {}}."""
    d = {"secs": {}, "bytes": {}}
    prev = getattr(_TLS, "sink", None)
    _TLS.sink = d
    try:
        yield d
    finally:
        _TLS.sink = prev

ENABLED = os.environ.get("H2O3_PHASE_ACCOUNTING", "").lower() not in (
    "", "0", "false", "no")

# phases the jax monitoring listener owns; accounted_h2d subtracts their
# concurrent growth so first-call compilation isn't booked as transfer
COMPILE_KEYS = ("compile", "trace", "deserialize")


def add(phase: str, secs: float = 0.0, nbytes: int = 0) -> None:
    with _LOCK:
        _SECS[phase] += secs
        if nbytes:
            _BYTES[phase] += nbytes
    sink = getattr(_TLS, "sink", None)
    if sink is not None:   # thread-local — no lock needed
        sink["secs"][phase] = sink["secs"].get(phase, 0.0) + secs
        if nbytes:
            sink["bytes"][phase] = sink["bytes"].get(phase, 0) + nbytes


def reset() -> None:
    with _LOCK:
        _SECS.clear()
        _BYTES.clear()


def totals(keys) -> float:
    """Sum of accumulated seconds over the given phase keys."""
    with _LOCK:
        return sum(_SECS.get(k, 0.0) for k in keys)


def snapshot() -> dict:
    """Accumulated seconds per phase + bytes for transfer phases."""
    with _LOCK:
        out = {f"{k}_s": round(v, 3) for k, v in _SECS.items()}
        out.update({f"bytes_{k}": v for k, v in _BYTES.items()})
        return out


@contextmanager
def timed(phase: str, nbytes: int = 0):
    t0 = time.perf_counter()
    try:
        yield
    finally:
        add(phase, time.perf_counter() - t0, nbytes)


def accounted_h2d(thunk, nbytes: int):
    """Run `thunk()` (a host→device transfer, possibly fused with a small
    on-device expand program) with H2D time/byte accounting.

    When accounting is off the thunk runs untouched (only the byte count is
    recorded); when on, a one-element fetch after it makes the recorded
    seconds actual transfer time — through the axon tunnel
    block_until_ready returns before data lands, so a tiny D2H is the only
    reliable barrier. Compile time the call triggers (first-call jit of the
    expand program) is already accounted by the monitoring listener and is
    subtracted out.
    """
    if not ENABLED:
        add("h2d", 0.0, nbytes)
        return thunk()
    import jax
    import numpy as np

    install_listener()
    comp0 = totals(COMPILE_KEYS)
    t0 = time.perf_counter()
    out = thunk()
    try:
        np.asarray(out.ravel()[:1] if hasattr(out, "ravel") else out)
    except Exception:
        jax.block_until_ready(out)
    elapsed = time.perf_counter() - t0 - (totals(COMPILE_KEYS) - comp0)
    add("h2d", max(elapsed, 0.0), nbytes)
    return out


def add_mark(name: str, secs: float) -> None:
    """Fold a training-driver phase boundary (shared_tree._Phase.mark) into
    the canonical phase buckets bench.py reports."""
    if name == "device_put":
        phase = "h2d"
    elif name.endswith("_D2H"):
        phase = "d2h"
    elif name.startswith("chunk_") or name in ("train_loop_dispatch",
                                               "forest_devkeep"):
        phase = "compute"
    elif name in ("frame_to_matrix", "build_bins", "forest_unpack"):
        phase = "host_prep"
    elif name == "training_metrics":
        phase = "metrics"
    else:
        phase = "other"
    add(phase, secs)


def install_listener() -> None:
    """Register the jax monitoring listener (idempotent).

    Maps compile-pipeline event durations onto phases: backend compilation,
    host-side trace/lowering, and persistent-cache executable retrieval
    (the ~4 s/program 'deserialize' cost on cache-warm tunnel runs).
    """
    global _installed
    with _LOCK:
        if _installed:
            return
        _installed = True
    from jax._src import monitoring

    def _on(event: str, duration: float, **kw) -> None:
        if "backend_compile" in event:
            add("compile", duration)
        elif "jaxpr_trace" in event or "mlir_module" in event:
            add("trace", duration)
        elif "cache_retrieval" in event or "deserialize" in event:
            add("deserialize", duration)

    monitoring.register_event_duration_secs_listener(_on)


if ENABLED:
    # self-contained accounting: a user script that sets the env flag gets
    # the compile/trace listener without having to know bench.py calls this
    install_listener()
