"""Persist SPI — pluggable storage backends for import/export.

Reference parity: `h2o-core/src/main/java/water/persist/Persist.java` with
`PersistNFS`/`PersistFS` in-tree and `h2o-persist-{s3,hdfs,gcs,http}`
extension modules. Scheme-dispatched; local file is fully supported, cloud
schemes are registered stubs that raise with the reference's module name so
the surface (and error text) matches even in this network-less build.
"""

from __future__ import annotations

import glob as _glob
import os
from typing import Callable, Dict, List


class Persist:
    """file:// + bare paths — PersistNFS/PersistFS."""

    scheme = "file"

    def open(self, uri: str, mode: str = "rb"):
        return open(self._strip(uri), mode)

    def exists(self, uri: str) -> bool:
        return os.path.exists(self._strip(uri))

    def list(self, uri: str) -> List[str]:
        p = self._strip(uri)
        if os.path.isdir(p):
            return sorted(os.path.join(p, f) for f in os.listdir(p))
        return sorted(_glob.glob(p))

    def size(self, uri: str) -> int:
        return os.path.getsize(self._strip(uri))

    @staticmethod
    def _strip(uri: str) -> str:
        return uri[len("file://"):] if uri.startswith("file://") else uri


class _StubPersist(Persist):
    def __init__(self, scheme: str, module: str):
        self.scheme = scheme
        self._module = module

    def open(self, uri: str, mode: str = "rb"):
        raise NotImplementedError(
            f"{self.scheme}:// requires the {self._module} persistence "
            f"backend (not available in this build)"
        )

    exists = list = size = open  # type: ignore[assignment]


_REGISTRY: Dict[str, Persist] = {
    "file": Persist(),
    "s3": _StubPersist("s3", "h2o-persist-s3"),
    "s3a": _StubPersist("s3a", "h2o-persist-s3"),
    "hdfs": _StubPersist("hdfs", "h2o-persist-hdfs"),
    "gs": _StubPersist("gs", "h2o-persist-gcs"),
    "http": _StubPersist("http", "h2o-persist-http"),
    "https": _StubPersist("https", "h2o-persist-http"),
}


def register(scheme: str, backend: Persist) -> None:
    _REGISTRY[scheme] = backend


def for_uri(uri: str) -> Persist:
    scheme = uri.split("://", 1)[0] if "://" in uri else "file"
    if scheme not in _REGISTRY:
        raise ValueError(f"no persistence backend for scheme {scheme!r}")
    return _REGISTRY[scheme]
