"""Persist SPI — pluggable storage backends for import/export.

Reference parity: `h2o-core/src/main/java/water/persist/Persist.java` with
`PersistNFS`/`PersistFS` in-tree and `h2o-persist-{s3,hdfs,gcs,http}`
extension modules. Scheme-dispatched:

* local file (PersistNFS/PersistFS) — stdlib
* http/https (h2o-persist-http) — urllib, read-only
* s3/s3a, gs, hdfs (h2o-persist-{s3,gcs,hdfs}) — pyarrow.fs filesystems,
  constructed lazily; credential/connectivity errors surface at first use
  with the scheme and reference module named (this build's CI machine has
  no egress, so these paths are exercised in deployment, not tests).
"""

from __future__ import annotations

import glob as _glob
import os
from typing import Dict, List


class Persist:
    """file:// + bare paths — PersistNFS/PersistFS."""

    scheme = "file"

    def open(self, uri: str, mode: str = "rb"):
        return open(self._strip(uri), mode)

    def exists(self, uri: str) -> bool:
        return os.path.exists(self._strip(uri))

    def list(self, uri: str) -> List[str]:
        p = self._strip(uri)
        if os.path.isdir(p):
            return sorted(os.path.join(p, f) for f in os.listdir(p))
        return sorted(_glob.glob(p))

    def size(self, uri: str) -> int:
        return os.path.getsize(self._strip(uri))

    @staticmethod
    def _strip(uri: str) -> str:
        return uri[len("file://"):] if uri.startswith("file://") else uri


class HttpPersist(Persist):
    """h2o-persist-http — read-only HTTP(S) import."""

    def __init__(self, scheme: str = "http"):
        self.scheme = scheme

    def open(self, uri: str, mode: str = "rb"):
        if "r" not in mode:
            raise NotImplementedError("http persistence is read-only")
        import urllib.request

        # the response object is file-like (read/close, context manager) —
        # returning it directly lets callers stream instead of buffering
        return urllib.request.urlopen(uri)

    def exists(self, uri: str) -> bool:
        import urllib.error
        import urllib.request

        try:
            req = urllib.request.Request(uri, method="HEAD")
            with urllib.request.urlopen(req):
                return True
        except (urllib.error.URLError, OSError):
            return False

    def list(self, uri: str) -> List[str]:
        return [uri]

    def size(self, uri: str) -> int:
        import urllib.request

        req = urllib.request.Request(uri, method="HEAD")
        with urllib.request.urlopen(req) as r:
            return int(r.headers.get("Content-Length", -1))


class ArrowFsPersist(Persist):
    """s3/gs/hdfs via pyarrow.fs — the h2o-persist-{s3,gcs,hdfs} roles.

    The filesystem object is built lazily on first use so importing this
    module never requires credentials; failures name the scheme and the
    reference module they correspond to."""

    def __init__(self, scheme: str, module: str):
        self.scheme = scheme
        self._module = module
        self._fs: Dict[str, object] = {}   # keyed by URI authority

    def _resolve(self, uri: str):
        """(filesystem, path) for one URI — hdfs URIs carry the namenode in
        their authority, so the filesystem is constructed (and cached) per
        authority via from_uri, which also yields the correct path."""
        try:
            from pyarrow import fs as pafs

            if self.scheme == "hdfs":
                rest = uri.split("://", 1)[1]
                authority = rest.split("/", 1)[0]
                if authority not in self._fs:
                    self._fs[authority], _ = pafs.FileSystem.from_uri(uri)
                path = "/" + rest.split("/", 1)[1] if "/" in rest else "/"
                return self._fs[authority], path
            if "" not in self._fs:
                self._fs[""] = (pafs.S3FileSystem()
                                if self.scheme in ("s3", "s3a")
                                else pafs.GcsFileSystem())
            return self._fs[""], uri.split("://", 1)[1]
        except Exception as e:
            raise RuntimeError(
                f"{self.scheme}:// backend ({self._module} role) could "
                f"not initialize a pyarrow filesystem: {e}") from e

    def open(self, uri: str, mode: str = "rb"):
        fs, path = self._resolve(uri)
        if "w" in mode:
            return fs.open_output_stream(path)
        return fs.open_input_file(path)

    def exists(self, uri: str) -> bool:
        fs, path = self._resolve(uri)       # raises RuntimeError w/ context
        from pyarrow import fs as pafs

        return fs.get_file_info(path).type != pafs.FileType.NotFound

    def list(self, uri: str) -> List[str]:
        fs, path = self._resolve(uri)
        from pyarrow import fs as pafs

        sel = pafs.FileSelector(path, recursive=False, allow_not_found=True)
        return sorted(f"{self.scheme}://{i.path}"
                      for i in fs.get_file_info(sel))

    def size(self, uri: str) -> int:
        fs, path = self._resolve(uri)
        return int(fs.get_file_info(path).size)


_REGISTRY: Dict[str, Persist] = {
    "file": Persist(),
    "s3": ArrowFsPersist("s3", "h2o-persist-s3"),
    "s3a": ArrowFsPersist("s3a", "h2o-persist-s3"),
    "hdfs": ArrowFsPersist("hdfs", "h2o-persist-hdfs"),
    "gs": ArrowFsPersist("gs", "h2o-persist-gcs"),
    "http": HttpPersist("http"),
    "https": HttpPersist("https"),
}


def register(scheme: str, backend: Persist) -> None:
    _REGISTRY[scheme] = backend


def for_uri(uri: str) -> Persist:
    scheme = uri.split("://", 1)[0] if "://" in uri else "file"
    if scheme not in _REGISTRY:
        raise ValueError(f"no persistence backend for scheme {scheme!r}")
    return _REGISTRY[scheme]
