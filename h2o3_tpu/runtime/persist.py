"""Persist SPI — pluggable storage backends for import/export.

Reference parity: `h2o-core/src/main/java/water/persist/Persist.java` with
`PersistNFS`/`PersistFS` in-tree and `h2o-persist-{s3,hdfs,gcs,http}`
extension modules. Scheme-dispatched:

* local file (PersistNFS/PersistFS) — stdlib
* http/https (h2o-persist-http) — urllib, read-only
* s3/s3a, gs, hdfs (pyarrow.fs filesystems, constructed lazily; credential/
  connectivity errors surface at first use with the scheme and reference
  module named — this build's CI machine has no egress, so these paths are
  exercised in deployment, not tests).

Fault discipline (docs/robustness.md): every remote-capable operation —
open/read/list/size on the http and pyarrow backends — runs under the
shared `runtime/retry.RetryPolicy` ("persist" policy), so one transient
connection drop mid-import no longer kills the whole parse. HTTP streams
additionally RESUME on read failure via a Range request from the current
offset. Permanent errors (404-shaped `FileNotFoundError`, bad URIs) fail
fast through the policy's classifier. Injection points `persist.open` /
`persist.read` / `persist.list` (runtime/faults.py) exercise these paths
deterministically.
"""

from __future__ import annotations

import glob as _glob
import os
import threading
from typing import Dict, List, Optional

from . import faults
from . import retry as _retry

_POLICY: Optional[_retry.RetryPolicy] = None
_POLICY_LOCK = threading.Lock()


def _policy() -> _retry.RetryPolicy:
    global _POLICY
    with _POLICY_LOCK:
        if _POLICY is None:
            _POLICY = _retry.RetryPolicy(name="persist")
        return _POLICY


def reset_policy() -> None:
    """Rebuild the policy from env (tests tune H2O3_RETRY_* knobs)."""
    global _POLICY
    with _POLICY_LOCK:
        _POLICY = None


class Persist:
    """file:// + bare paths — PersistNFS/PersistFS."""

    scheme = "file"

    def open(self, uri: str, mode: str = "rb"):
        def _open():
            faults.check("persist.open", uri)
            return open(self._strip(uri), mode)

        return _policy().call(_open)

    def exists(self, uri: str) -> bool:
        return os.path.exists(self._strip(uri))

    def list(self, uri: str) -> List[str]:
        def _list():
            faults.check("persist.list", uri)
            p = self._strip(uri)
            if os.path.isdir(p):
                return sorted(os.path.join(p, f) for f in os.listdir(p))
            return sorted(_glob.glob(p))

        return _policy().call(_list)

    def size(self, uri: str) -> int:
        return os.path.getsize(self._strip(uri))

    def open_resuming(self, uri: str):
        """A read stream that survives torn reads: a failed read() re-opens
        the URI through this backend and seeks (or skip-reads) back to the
        current offset under the shared retry policy — the local-file
        analog of the HTTP Range resume. The block store's disk spill tier
        streams packed blocks back through this so one injected/transient
        read failure resumes instead of failing the fit."""
        return _ResumingStream(self, uri)

    @staticmethod
    def _strip(uri: str) -> str:
        return uri[len("file://"):] if uri.startswith("file://") else uri


class _ResumingStream:
    """Backend-generic resuming reader (file/pyarrow): tracks the byte
    offset handed to the caller; a read failure marks the stream dead and
    the next retry attempt re-opens via the backend and positions itself
    at the offset — ``seek`` where the handle supports it, a skip-read
    loop otherwise (the same shape as the Range-ignoring-server path of
    ``_ResumingHttpStream``). ``persist.read`` faults are checked per
    attempt, so an armed fault exercises exactly this resume."""

    def __init__(self, backend: "Persist", uri: str):
        self._backend = backend
        self._uri = uri
        self._pos = 0
        self._fh = None
        self._dead = True          # first read opens lazily via _reopen

    def _reopen(self):
        fh = self._backend.open(self._uri, "rb")
        if self._pos:
            try:
                fh.seek(self._pos)
            except (AttributeError, OSError, ValueError):
                left = self._pos
                while left > 0:
                    chunk = fh.read(min(left, 1 << 20))
                    if not chunk:
                        break
                    left -= len(chunk)
        self._fh = fh

    def read(self, n: int = -1) -> bytes:
        def _read():
            faults.check("persist.read", self._uri)
            # reopen at the top of the attempt (see _ResumingHttpStream):
            # a transiently-failing reopen must leave the stream dead, or
            # the next retry would read the closed handle and truncate
            if self._dead:
                self._reopen()
                self._dead = False
            try:
                return self._fh.read(n)
            except (OSError, ValueError) as e:
                self._dead = True
                try:
                    self._fh.close()
                except Exception:
                    pass
                raise ConnectionError(
                    f"read of {self._uri} dropped at byte "
                    f"{self._pos}: {e}") from e

        out = _policy().call(_read)
        self._pos += len(out)
        return out

    def close(self) -> None:
        if self._fh is not None:
            try:
                self._fh.close()
            except Exception:
                pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class _ResumingHttpStream:
    """File-like wrapper over an HTTP response that survives mid-stream
    connection drops: a failed read() re-opens the URI with a
    ``Range: bytes={offset}-`` header (under the shared retry policy) and
    continues where it left off. Context-manager + read/close, the same
    surface callers of HttpPersist.open already use."""

    def __init__(self, uri: str, resp):
        self._uri = uri
        self._resp = resp
        self._pos = 0
        self._dead = False

    def _reopen(self):
        import urllib.request

        req = urllib.request.Request(
            self._uri, headers={"Range": f"bytes={self._pos}-"})
        resp = urllib.request.urlopen(req)
        if self._pos and resp.status not in (206,):
            # server ignored the Range: skip what we already handed out
            left = self._pos
            while left > 0:
                chunk = resp.read(min(left, 1 << 20))
                if not chunk:
                    break
                left -= len(chunk)
        self._resp = resp

    def read(self, n: int = -1) -> bytes:
        import http.client as _http

        def _read():
            faults.check("persist.read", self._uri)
            # reopen at the top of the attempt, not in the except below: if
            # the reopen itself fails transiently the response must STAY
            # marked dead, or the next retry would read() the closed
            # original — which returns b'' and silently truncates the body
            if self._dead:
                self._reopen()
                self._dead = False
            try:
                return self._resp.read(n)
            # IncompleteRead (the standard mid-body truncation) subclasses
            # HTTPException, NOT OSError — it must hit the resume path too
            except (OSError, ValueError, _http.HTTPException) as e:
                self._dead = True
                try:
                    self._resp.close()
                except OSError:
                    pass
                raise ConnectionError(
                    f"http read of {self._uri} dropped at byte "
                    f"{self._pos}: {e}") from e

        out = _policy().call(_read)
        self._pos += len(out)
        return out

    def readline(self, limit: int = -1) -> bytes:
        # position-tracked pass-throughs (no drop-resume for line reads —
        # but an UNtracked readline would corrupt later Range offsets):
        # the raw HTTPResponse is iterable and callers of the Persist SPI
        # rely on that surface
        line = self._resp.readline(limit)
        self._pos += len(line)
        return line

    def readinto(self, b) -> int:
        n = self._resp.readinto(b)
        self._pos += int(n or 0)
        return n

    def __iter__(self):
        return iter(self.readline, b"")

    def close(self) -> None:
        try:
            self._resp.close()
        except OSError:
            pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def __getattr__(self, name):        # headers, status, geturl, ...
        resp = self.__dict__.get("_resp")
        if resp is None:
            raise AttributeError(name)
        return getattr(resp, name)


class HttpPersist(Persist):
    """h2o-persist-http — read-only HTTP(S) import."""

    def __init__(self, scheme: str = "http"):
        self.scheme = scheme

    def open(self, uri: str, mode: str = "rb"):
        if "r" not in mode:
            raise NotImplementedError("http persistence is read-only")
        import urllib.request

        def _open():
            faults.check("persist.open", uri)
            return urllib.request.urlopen(uri)

        # the response object is file-like (read/close, context manager) —
        # the resuming wrapper preserves that while adding mid-stream
        # retry + Range-resume
        return _ResumingHttpStream(uri, _policy().call(_open))

    def open_resuming(self, uri: str):
        # http reads already resume (Range) — open() IS the resuming stream
        return self.open(uri)

    def exists(self, uri: str) -> bool:
        import http.client as _http
        import urllib.error
        import urllib.request

        try:
            req = urllib.request.Request(uri, method="HEAD")
            with urllib.request.urlopen(req):
                return True
        except (urllib.error.URLError, _http.HTTPException,
                ConnectionError, TimeoutError):
            # URLError covers HTTP status errors and request-phase socket
            # failures, but urlopen does NOT wrap getresponse()-phase drops
            # (RemoteDisconnected, ConnectionResetError) — those are network
            # outcomes too. Anything else (ValueError'd bad URIs,
            # non-network OSErrors) is a caller bug and must propagate
            return False

    def list(self, uri: str) -> List[str]:
        return [uri]

    def size(self, uri: str) -> int:
        import urllib.request

        def _head():
            faults.check("persist.open", uri)
            req = urllib.request.Request(uri, method="HEAD")
            with urllib.request.urlopen(req) as r:
                return r.headers.get("Content-Length")

        # only the network round-trip retries; a server that simply never
        # sends Content-Length is deterministic — raise once, immediately
        ln = _policy().call(_head)
        if ln is None:
            # -1 would silently poison chunk planning downstream
            raise IOError(
                f"{uri}: server reported no Content-Length; size is "
                "unknown (chunked/streamed resource?)")
        return int(ln)


class ArrowFsPersist(Persist):
    """s3/gs/hdfs via pyarrow.fs — the h2o-persist-{s3,gcs,hdfs} roles.

    The filesystem object is built lazily on first use so importing this
    module never requires credentials; failures name the scheme and the
    reference module they correspond to."""

    def __init__(self, scheme: str, module: str):
        self.scheme = scheme
        self._module = module
        self._fs: Dict[str, object] = {}   # keyed by URI authority

    def _resolve(self, uri: str):
        """(filesystem, path) for one URI — hdfs URIs carry the namenode in
        their authority, so the filesystem is constructed (and cached) per
        authority via from_uri, which also yields the correct path."""
        try:
            from pyarrow import fs as pafs

            if self.scheme == "hdfs":
                rest = uri.split("://", 1)[1]
                authority = rest.split("/", 1)[0]
                if authority not in self._fs:
                    self._fs[authority], _ = pafs.FileSystem.from_uri(uri)
                path = "/" + rest.split("/", 1)[1] if "/" in rest else "/"
                return self._fs[authority], path
            if "" not in self._fs:
                self._fs[""] = (pafs.S3FileSystem()
                                if self.scheme in ("s3", "s3a")
                                else pafs.GcsFileSystem())
            return self._fs[""], uri.split("://", 1)[1]
        except Exception as e:
            raise RuntimeError(
                f"{self.scheme}:// backend ({self._module} role) could "
                f"not initialize a pyarrow filesystem: {e}") from e

    def open(self, uri: str, mode: str = "rb"):
        def _open():
            faults.check("persist.open", uri)
            fs, path = self._resolve(uri)
            if "w" in mode:
                return fs.open_output_stream(path)
            return fs.open_input_file(path)

        return _policy().call(_open)

    def exists(self, uri: str) -> bool:
        fs, path = self._resolve(uri)       # raises RuntimeError w/ context
        from pyarrow import fs as pafs

        return fs.get_file_info(path).type != pafs.FileType.NotFound

    def list(self, uri: str) -> List[str]:
        def _list():
            faults.check("persist.list", uri)
            fs, path = self._resolve(uri)
            from pyarrow import fs as pafs

            sel = pafs.FileSelector(path, recursive=False,
                                    allow_not_found=True)
            return sorted(f"{self.scheme}://{i.path}"
                          for i in fs.get_file_info(sel))

        return _policy().call(_list)

    def size(self, uri: str) -> int:
        def _size():
            faults.check("persist.open", uri)
            fs, path = self._resolve(uri)
            return int(fs.get_file_info(path).size)

        return _policy().call(_size)


_REGISTRY: Dict[str, Persist] = {
    "file": Persist(),
    "s3": ArrowFsPersist("s3", "h2o-persist-s3"),
    "s3a": ArrowFsPersist("s3a", "h2o-persist-s3"),
    "hdfs": ArrowFsPersist("hdfs", "h2o-persist-hdfs"),
    "gs": ArrowFsPersist("gs", "h2o-persist-gcs"),
    "http": HttpPersist("http"),
    "https": HttpPersist("https"),
}


def register(scheme: str, backend: Persist) -> None:
    _REGISTRY[scheme] = backend


def for_uri(uri: str) -> Persist:
    scheme = uri.split("://", 1)[0] if "://" in uri else "file"
    if scheme not in _REGISTRY:
        raise ValueError(f"no persistence backend for scheme {scheme!r}")
    return _REGISTRY[scheme]
