"""DKV — the keyed object store behind `h2o.ls`/frames/models.

Reference parity: `h2o-core/src/main/java/water/DKV.java` — a distributed
`Key→Value` hash with home-node placement. In the TPU rebuild there is one
controller process per job (JAX single-controller model); the *data* lives in
HBM as sharded arrays, so the KV store only holds host-side handles (Frame
and Model objects) — a plain dict with a lock, not a distributed hash. The
key namespace and lifecycle (`put/get/remove`, leak checks in tests) match.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional


class DKV:
    _store: Dict[str, object] = {}
    _lock = threading.Lock()

    @classmethod
    def put(cls, key: str, value) -> None:
        with cls._lock:
            cls._store[key] = value

    @classmethod
    def get(cls, key: str):
        with cls._lock:
            return cls._store.get(key)

    @classmethod
    def remove(cls, key: str) -> None:
        with cls._lock:
            cls._store.pop(key, None)

    @classmethod
    def keys(cls, kind: Optional[type] = None) -> List[str]:
        with cls._lock:
            if kind is None:
                return list(cls._store)
            return [k for k, v in cls._store.items() if isinstance(v, kind)]

    @classmethod
    def clear(cls) -> None:
        with cls._lock:
            cls._store.clear()

    # -- size accounting (water.Cleaner / MemoryManager's bookkeeping role) -
    @staticmethod
    def _nbytes(value) -> int:
        """Approximate host+device footprint of one entry."""
        import numpy as np

        seen = 0
        vecs = getattr(value, "_vecs", None)
        if isinstance(vecs, dict):              # Frame
            for v in vecs.values():
                data = getattr(v, "data", None)
                if data is not None:
                    seen += int(np.asarray(data).nbytes)
                strs = getattr(v, "_strings", None)
                if strs is not None and len(strs):
                    # sampled estimate — a per-element Python loop would make
                    # /3/Cloud O(total string cells)
                    import itertools

                    sample = list(itertools.islice(
                        (s for s in strs if s is not None), 256))
                    avg = (sum(len(str(s)) for s in sample) / len(sample)
                           if sample else 0.0)
                    seen += int(avg * len(strs))
            return seen
        pd = getattr(value, "_packed_dev", None)  # tree model, HBM pack
        if pd is not None:
            from ..models.shared_tree import pack_nbytes

            seen += pack_nbytes(pd)
        forest = value.__dict__.get("_forest") if hasattr(value, "__dict__") else None
        if forest:
            for stacked in forest:
                for f in stacked:
                    seen += int(np.asarray(f).nbytes)
        return seen

    @classmethod
    def stats(cls) -> Dict:
        """Entry counts + approximate bytes per kind — the store-level
        accounting `water.Cleaner` keeps for its eviction decisions."""
        with cls._lock:
            items = list(cls._store.items())
        out: Dict[str, Dict] = {}
        total = 0
        for k, v in items:
            kind = type(v).__name__
            b = cls._nbytes(v)
            d = out.setdefault(kind, {"count": 0, "bytes": 0})
            d["count"] += 1
            d["bytes"] += b
            total += b
        return {"entries": len(items), "total_bytes": total, "by_kind": out}
