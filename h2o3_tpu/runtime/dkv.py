"""DKV — the keyed object store behind `h2o.ls`/frames/models.

Reference parity: `h2o-core/src/main/java/water/DKV.java` — a distributed
`Key→Value` hash with home-node placement. In the TPU rebuild there is one
controller process per job (JAX single-controller model); the *data* lives in
HBM as sharded arrays, so the KV store only holds host-side handles (Frame
and Model objects) — a plain dict with a lock, not a distributed hash. The
key namespace and lifecycle (`put/get/remove`, leak checks in tests) match.
"""

from __future__ import annotations

import threading
import weakref
from typing import Dict, List, Optional


def _owner_kind(value) -> str:
    """Ledger owner kind for a DKV value — frames and models are first-
    class in the `h2o3_memory_bytes{owner_kind,...}` breakdown; everything
    else (jobs, grids, sweeps) aggregates under `dkv`."""
    if getattr(value, "_vecs", None) is not None:
        return "frame"
    try:
        from ..models.model_base import H2OModel

        if isinstance(value, H2OModel):
            return "model"
    except Exception:
        pass
    try:
        from ..mojo import MojoScorer

        if isinstance(value, MojoScorer):
            return "model"
    except Exception:
        pass
    return "dkv"


class DKV:
    _store: Dict[str, object] = {}
    _lock = threading.Lock()

    @classmethod
    def put(cls, key: str, value) -> None:
        with cls._lock:
            cls._store[key] = value
        # ledger registration OUTSIDE the store lock: the ledger's refresh
        # pass calls byte callbacks that may take cls._lock (scorer-cache
        # owners call DKV.get), so put must never hold it while entering
        # the ledger
        from . import memory_ledger as ml

        try:
            wr = weakref.ref(value)
        except TypeError:
            wr = None

        def _bytes(_wr=wr, _v=(value if wr is None else None)):
            v = _wr() if _wr is not None else _v
            return ml.measure(v) if v is not None else (0, 0)

        ml.register(f"dkv:{key}", kind=_owner_kind(value), bytes_fn=_bytes,
                    referent=(value if wr is not None else None),
                    type_name=type(value).__name__)

    @classmethod
    def get(cls, key: str):
        with cls._lock:
            return cls._store.get(key)

    @classmethod
    def remove(cls, key: str) -> None:
        with cls._lock:
            existed = cls._store.pop(key, None) is not None
        if existed:
            from . import memory_ledger as ml

            ml.unregister(f"dkv:{key}", event="free", trigger="remove")

    @classmethod
    def keys(cls, kind: Optional[type] = None) -> List[str]:
        with cls._lock:
            if kind is None:
                return list(cls._store)
            return [k for k, v in cls._store.items() if isinstance(v, kind)]

    @classmethod
    def clear(cls) -> None:
        with cls._lock:
            cls._store.clear()
        from . import memory_ledger as ml

        ml.unregister_prefix("dkv:")

    # -- size accounting (water.Cleaner / MemoryManager's bookkeeping role) -
    @staticmethod
    def _nbytes(value) -> int:
        """Approximate host+device footprint of one entry — the ledger's
        `measure()` deep sizer, so device-resident JAX arrays and nested
        Frame/Vec buffers count instead of reporting ~0."""
        from . import memory_ledger as ml

        h, d = ml.measure(value)
        return h + d

    @classmethod
    def stats(cls) -> Dict:
        """Entry counts + approximate bytes per kind — delegated to the
        memory ledger's `dkv:` owners so the store-level accounting and
        `GET /3/Memory` can never disagree."""
        from . import memory_ledger as ml

        return ml.dkv_stats()
