"""DKV — the keyed object store behind `h2o.ls`/frames/models.

Reference parity: `h2o-core/src/main/java/water/DKV.java` — a distributed
`Key→Value` hash with home-node placement. In the TPU rebuild there is one
controller process per job (JAX single-controller model); the *data* lives in
HBM as sharded arrays, so the KV store only holds host-side handles (Frame
and Model objects) — a plain dict with a lock, not a distributed hash. The
key namespace and lifecycle (`put/get/remove`, leak checks in tests) match.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional


class DKV:
    _store: Dict[str, object] = {}
    _lock = threading.Lock()

    @classmethod
    def put(cls, key: str, value) -> None:
        with cls._lock:
            cls._store[key] = value

    @classmethod
    def get(cls, key: str):
        with cls._lock:
            return cls._store.get(key)

    @classmethod
    def remove(cls, key: str) -> None:
        with cls._lock:
            cls._store.pop(key, None)

    @classmethod
    def keys(cls, kind: Optional[type] = None) -> List[str]:
        with cls._lock:
            if kind is None:
                return list(cls._store)
            return [k for k, v in cls._store.items() if isinstance(v, kind)]

    @classmethod
    def clear(cls) -> None:
        with cls._lock:
            cls._store.clear()
