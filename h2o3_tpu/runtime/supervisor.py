"""Elastic training supervisor — mid-fit checkpoints, hung-collective
abort, degrade-and-resume (docs/robustness.md "Recovery matrix").

The reference platform's HeartBeatThread + Paxos recovery keep a cloud
working when a node misbehaves; PR 10/15 made a dead or wedged rank
*visible* (`parallel/mesh.lane_hang_report`, `h2o3_fleet_peer_up`) but a
fit still hung forever on the open collective and lost every tree of
progress with it. This module closes that gap with three cooperating
pieces:

* **Fit checkpoints** — `models/shared_tree` snapshots its loop state
  (forest-so-far, live f32 margins/OOB accumulators, gain partial sum,
  scoring history, early-stop cursor) every ``H2O3_CKPT_TREES`` trees,
  and `models/estimator_engine` snapshots its ``while_loop`` carry at
  the QoS ``segment_stops`` boundaries. Snapshots are one ``.npz`` blob
  written through the persist SPI with the ``.part``+rename pattern and
  stamped with a **run fingerprint** (frame shape + params + seed + the
  shard plan S): a torn write is never restorable (the zip central
  directory and per-array CRCs fail the full-read validation) and a
  checkpoint from different data/params is ignored, exactly like the
  sweep records of PR 5. Restored margins are the LIVE f32 arrays, not a
  forest fast-forward — incremental per-tree adds round differently than
  `_margin_ffwd_jit`'s refold, and bit-identity to the undisturbed fit
  is the whole point.

* **Failure detection + abort** — `deadline_block` wraps
  ``jax.block_until_ready`` in a watcher so a fence whose peer died
  raises `CollectiveTimeout` within ``H2O3_FENCE_DEADLINE_S`` instead of
  waiting on the rendezvous forever; on breach the suspect ranks from
  `lane_hang_report`'s cached topology are marked DOWN in the fleet
  registry (their ``h2o3_fleet_peer_up`` series flips to 0), a Timeline
  event names them, and ``h2o3_supervisor_aborts`` / the detection-
  latency histogram record it. An optional background watcher
  (`start()`, launcher-armed on pods) fires the same detection for
  host-side hangs the fence wrapper cannot see.

* **Elastic resume** — the aborted/killed fit reloads the newest VALID
  checkpoint and continues. Per-rank shards are saved in the pod
  canonical row layout (parallel/distdata), so rank-ordered
  concatenation of the shard files IS the global padded array: a fit
  that lost ranks resumes on one host (``H2O3_TREE_SHARD=1`` degrade)
  bit-identical, because the shard plan S pinned in the checkpoint keeps
  the deterministic reduction grid unchanged. `CollectiveTimeout`
  subclasses ``TimeoutError`` so the trainpool's transient classifier
  retries the candidate — which resumes mid-fit instead of retraining
  from tree 0 (``totals.resumed_mid_fit`` in /3/Training/metrics).

Fault points: ``supervisor.ckpt_corrupt`` (truncates the serialized blob
before the atomic rename — restore must reject it), ``supervisor.fit_abort``
(raises at a chunk boundary — the in-process kill-and-resume pin), and
`parallel/mesh`'s ``mesh.rank_kill`` (hard-exits a rank at fence N — the
``BENCH_CONFIG=pod_chaos`` lane). ``H2O3_CKPT=0`` is the escape hatch:
checkpointing fully off, bit-identical to pre-supervisor behavior.

State surfaces at ``GET /3/Supervisor`` (rest/server.py) and the
``h2o3_supervisor_*`` registry families.
"""

from __future__ import annotations

import io
import json
import os
import re
import threading
import time
from typing import Dict, List, Optional

import numpy as np

from . import metrics_registry as _reg

__all__ = [
    "CollectiveTimeout", "ckpt_enabled", "ckpt_dir", "ckpt_every_trees",
    "fence_deadline_s", "run_fingerprint", "save_fit_checkpoint",
    "load_fit_checkpoint", "latest_fit_checkpoint", "deadline_block",
    "mark_ranks_down", "fit_started", "fit_finished", "pulse",
    "note_checkpoint", "note_mid_fit_resume", "note_abort", "snapshot",
    "start", "stop", "reset",
]


class CollectiveTimeout(TimeoutError):
    """A collective fence exceeded its deadline — a peer rank is dead or
    wedged. Subclasses ``TimeoutError`` (an ``OSError``) so the shared
    retry classifier treats the abort as TRANSIENT: the trainpool retries
    the candidate, which resumes from its newest fit checkpoint."""


# -- config ------------------------------------------------------------------

def ckpt_enabled() -> bool:
    """``H2O3_CKPT=0`` is the escape hatch: no snapshots, no restores,
    bit-identical to pre-supervisor behavior."""
    return os.environ.get("H2O3_CKPT", "1").strip() != "0"


def ckpt_dir() -> Optional[str]:
    """Checkpoint directory (``H2O3_CKPT_DIR``). Unset ⇒ mid-fit
    checkpointing is off — there is nowhere durable to put snapshots."""
    d = os.environ.get("H2O3_CKPT_DIR", "").strip()
    return d or None


def ckpt_every_trees() -> int:
    """Snapshot cadence for tree fits (``H2O3_CKPT_TREES``, default 25 —
    one checkpoint per default scoring chunk)."""
    try:
        return max(int(os.environ.get("H2O3_CKPT_TREES", "25")), 1)
    except ValueError:
        return 25


def fence_deadline_s() -> float:
    """Per-fence collective deadline (``H2O3_FENCE_DEADLINE_S``; 0 = no
    deadline — the pre-supervisor wait-forever behavior)."""
    try:
        return float(os.environ.get("H2O3_FENCE_DEADLINE_S", "0") or 0.0)
    except ValueError:
        return 0.0


# -- metrics -----------------------------------------------------------------

_REG: Dict = {}


def _registry() -> Dict:
    if not _REG:
        _REG["aborts"] = _reg.counter(
            "h2o3_supervisor_aborts",
            "hung-collective aborts: fences that exceeded the deadline and "
            "raised CollectiveTimeout instead of waiting forever")
        _REG["resumes"] = _reg.counter(
            "h2o3_supervisor_resumes",
            "mid-fit checkpoint restores (a fit continued past tree/"
            "iteration 0 from a prior snapshot)")
        _REG["ckpt_saves"] = _reg.counter(
            "h2o3_supervisor_ckpt_saves",
            "fit checkpoints atomically committed (.part+rename)")
        _REG["ckpt_rejects"] = _reg.counter(
            "h2o3_supervisor_ckpt_rejects",
            "checkpoint files rejected at restore (torn zip / CRC mismatch "
            "/ wrong run fingerprint / incomplete rank set)")
        _REG["marked_down"] = _reg.counter(
            "h2o3_supervisor_marked_down",
            "ranks marked down in the fleet registry by failure detection")
        _REG["detect_ms"] = _reg.histogram(
            "h2o3_supervisor_detect_ms",
            "failure detection latency (ms): deadline breach wall from "
            "fence dispatch to abort")
        _reg.bind_rest_field("supervisor", "totals.aborts",
                             "h2o3_supervisor_aborts")
        _reg.bind_rest_field("supervisor", "totals.resumes",
                             "h2o3_supervisor_resumes")
        _reg.bind_rest_field("supervisor", "totals.ckpt_saves",
                             "h2o3_supervisor_ckpt_saves")
        _reg.bind_rest_field("supervisor", "totals.ckpt_rejects",
                             "h2o3_supervisor_ckpt_rejects")
        _reg.bind_rest_field("supervisor", "totals.marked_down",
                             "h2o3_supervisor_marked_down")
    return _REG


# -- supervisor state machine ------------------------------------------------

_LOCK = threading.Lock()
_STATE: Dict = dict(state="idle", fit=None, heartbeat=None,
                    last_abort=None, last_resume=None, last_ckpt=None)


def fit_started(tag: str, fingerprint: str = "", total: int = 0) -> None:
    """idle → watching: a supervised fit entered its loop."""
    with _LOCK:
        _STATE["state"] = "watching"
        _STATE["fit"] = dict(tag=tag, fingerprint=fingerprint,
                             total=int(total), started=time.time())
        _STATE["heartbeat"] = None


def fit_finished(tag: str) -> None:
    """watching → idle (no-op when another fit already took over)."""
    with _LOCK:
        fit = _STATE.get("fit")
        if fit is not None and fit.get("tag") == tag:
            _STATE["state"] = "idle"
            _STATE["fit"] = None


def pulse(tag: str, step: int = 0) -> None:
    """Progress heartbeat from inside a supervised loop (chunk/segment/
    stream-block boundaries). The background watcher reads its age."""
    with _LOCK:
        _STATE["heartbeat"] = dict(tag=tag, step=int(step), ts=time.time())


def note_checkpoint(path: str, step: int, wall_s: float = 0.0) -> None:
    _registry()["ckpt_saves"].inc()
    with _LOCK:
        _STATE["last_ckpt"] = dict(path=path, step=int(step),
                                   wall_s=round(float(wall_s), 4),
                                   ts=time.time())


def note_mid_fit_resume(tag: str, step: int, restored: int = 0) -> None:
    """A fit restored a mid-fit snapshot and continued past step 0. Bumps
    the supervisor counter AND the trainpool's ``resumed_mid_fit`` total
    (the /3/Training/metrics face of the same event)."""
    _registry()["resumes"].inc()
    with _LOCK:
        _STATE["last_resume"] = dict(tag=tag, step=int(step),
                                     restored=int(restored), ts=time.time())
    try:
        from . import trainpool
        trainpool.bump_total("resumed_mid_fit")
    except Exception:
        pass
    try:
        from .timeline import Timeline
        Timeline.record("supervisor_resume", tag,
                        step=int(step), restored=int(restored))
    except Exception:
        pass
    try:
        from . import tracing
        tracing.event("supervisor_resume", tag=tag, step=int(step))
    except Exception:
        pass


def note_abort(tag: str, latency_s: float, suspects: List[int]) -> Dict:
    """Record one hung-collective abort: counters, detection-latency
    histogram, Timeline, state machine. Returns the abort record."""
    reg = _registry()
    reg["aborts"].inc()
    reg["detect_ms"].observe(float(latency_s) * 1e3)
    rec = dict(tag=tag, latency_s=round(float(latency_s), 3),
               suspect_ranks=[int(r) for r in suspects], ts=time.time())
    with _LOCK:
        _STATE["state"] = "aborted"
        _STATE["last_abort"] = rec
    try:
        from .timeline import Timeline
        Timeline.record("supervisor_abort", tag,
                        latency_s=rec["latency_s"],
                        suspect_ranks=rec["suspect_ranks"])
    except Exception:
        pass
    try:
        from . import tracing
        tracing.event("supervisor_abort", tag=tag,
                      latency_s=rec["latency_s"],
                      suspects=",".join(map(str, rec["suspect_ranks"])))
    except Exception:
        pass
    return rec


def mark_ranks_down(ranks: List[int], reason: str = "") -> None:
    """Flip the suspect ranks' ``h2o3_fleet_peer_up`` series to 0 (the
    launcher self-registers ranks as ``rank{N}``) and emit a Timeline
    event — failure detection must reach the fleet scrape immediately,
    not at the next failed scrape."""
    if not ranks:
        return
    reg = _registry()
    try:
        from . import fleet
        for r in ranks:
            fleet.mark_down(f"rank{int(r)}", reason or "supervisor")
            reg["marked_down"].inc()
    except Exception:
        pass


def snapshot() -> Dict:
    """The ``GET /3/Supervisor`` document: state machine + last abort/
    resume/checkpoint + counters + resolved config."""
    reg = _registry()
    with _LOCK:
        st = {k: (dict(v) if isinstance(v, dict) else v)
              for k, v in _STATE.items()}
    st["totals"] = dict(
        aborts=reg["aborts"].value(),
        resumes=reg["resumes"].value(),
        ckpt_saves=reg["ckpt_saves"].value(),
        ckpt_rejects=reg["ckpt_rejects"].value(),
        marked_down=reg["marked_down"].value(),
    )
    st["detect_ms"] = reg["detect_ms"].summary()
    st["config"] = dict(
        ckpt_enabled=ckpt_enabled(), ckpt_dir=ckpt_dir(),
        ckpt_trees=ckpt_every_trees(),
        fence_deadline_s=fence_deadline_s(),
        watcher=_WATCHER is not None,
    )
    return st


def reset() -> None:
    """Back to idle, watcher stopped (tests). Registry counters are
    monotone and stay."""
    stop()
    with _LOCK:
        _STATE.update(state="idle", fit=None, heartbeat=None,
                      last_abort=None, last_resume=None, last_ckpt=None)


# -- run fingerprint ---------------------------------------------------------

def run_fingerprint(**fields) -> str:
    """Stable digest of everything that must match for a checkpoint to be
    restorable: frame identity (global rows + column names + response),
    the param subset that shapes the loop, the seed, and the shard plan S
    (the deterministic reduction grid). Deliberately NOT a content hash —
    it must be computable identically on a 2-rank pod and its 1-host
    degraded resume, where no process holds all the bytes."""
    import hashlib

    def _san(v):
        if isinstance(v, (np.integer,)):
            return int(v)
        if isinstance(v, (np.floating,)):
            return float(v)
        if isinstance(v, (list, tuple)):
            return [_san(x) for x in v]
        if isinstance(v, dict):
            return {str(k): _san(x) for k, x in sorted(v.items())}
        return v

    blob = json.dumps(_san(fields), sort_keys=True,
                      separators=(",", ":"), default=str)
    return hashlib.sha256(blob.encode()).hexdigest()[:16]


# -- fit checkpoint store ----------------------------------------------------

_META_KEY = "__h2o3_meta__"
_FNAME_RE = re.compile(
    r"^fitckpt_(?P<tag>[A-Za-z0-9]+)_(?P<fp>[0-9a-f]+)"
    r"_s(?P<step>\d+)_r(?P<rank>\d+)of(?P<nproc>\d+)\.npz$")


def _ckpt_name(tag: str, fingerprint: str, step: int, rank: int,
               nproc: int) -> str:
    return (f"fitckpt_{tag}_{fingerprint[:12]}_s{step:08d}"
            f"_r{rank}of{nproc}.npz")


def save_fit_checkpoint(directory: str, tag: str, fingerprint: str,
                        step: int, arrays: Dict[str, np.ndarray],
                        meta: Optional[Dict] = None, rank: int = 0,
                        nproc: int = 1, keep: int = 2) -> str:
    """Atomically commit one snapshot through the persist SPI: serialize
    to an in-memory npz, write ``<name>.part``, rename into place. The
    ``supervisor.ckpt_corrupt`` fault truncates the blob BEFORE the
    rename — the committed file is then torn exactly like a mid-write
    crash, and restore must reject it via the full-read validation."""
    from . import faults, persist

    t0 = time.perf_counter()
    meta = dict(meta or {})
    meta.update(tag=tag, fingerprint=fingerprint, step=int(step),
                rank=int(rank), nproc=int(nproc), ts=time.time())
    payload = {k: np.asarray(v) for k, v in arrays.items()}
    payload[_META_KEY] = np.frombuffer(
        json.dumps(meta, default=float).encode(), dtype=np.uint8).copy()
    buf = io.BytesIO()
    np.savez(buf, **payload)
    blob = buf.getvalue()
    try:
        faults.check("supervisor.ckpt_corrupt", detail=f"{tag}:s{step}")
    except Exception:
        blob = blob[: max(len(blob) // 2, 1)]
    os.makedirs(directory, exist_ok=True)
    path = os.path.join(directory, _ckpt_name(tag, fingerprint, step,
                                              rank, nproc))
    part = path + ".part"
    be = persist.for_uri(part)
    with be.open(part, "wb") as f:
        f.write(blob)
    os.replace(part, path)
    note_checkpoint(path, step, time.perf_counter() - t0)
    _gc_old(directory, tag, fingerprint, rank, nproc, keep)
    return path


def _gc_old(directory: str, tag: str, fingerprint: str, rank: int,
            nproc: int, keep: int) -> None:
    """Keep the newest `keep` snapshots for this (tag, fp, rank) — older
    ones only waste disk once a newer valid one exists."""
    try:
        mine = []
        for f in os.listdir(directory):
            m = _FNAME_RE.match(f)
            if (m and m.group("tag") == tag
                    and m.group("fp") == fingerprint[:12]
                    and int(m.group("rank")) == rank
                    and int(m.group("nproc")) == nproc):
                mine.append((int(m.group("step")), f))
        for _, f in sorted(mine)[:-keep] if keep > 0 else []:
            os.unlink(os.path.join(directory, f))
    except OSError:
        pass


def load_fit_checkpoint(path: str) -> (Dict, Dict):
    """Load + VALIDATE one snapshot: every array is fully materialized
    (forcing the zip CRC check over all bytes) and the embedded meta must
    parse. Raises on any damage — callers treat any exception as
    'not restorable'."""
    with np.load(path) as z:
        arrays = {k: np.array(z[k]) for k in z.files if k != _META_KEY}
        meta = json.loads(bytes(z[_META_KEY].tobytes()).decode())
    return arrays, meta


def latest_fit_checkpoint(directory: str, tag: str,
                          fingerprint: str) -> Optional[Dict]:
    """Newest restorable snapshot for (tag, fingerprint): the highest
    step whose COMPLETE rank set loads and validates. Returns
    ``dict(step, nproc, shards=[arrays per rank 0..nproc-1], meta)`` or
    None. Torn files, wrong fingerprints, and incomplete rank sets are
    counted into ``ckpt_rejects`` and skipped — never restored."""
    reg = _registry()
    try:
        names = os.listdir(directory)
    except OSError:
        return None
    groups: Dict[tuple, Dict[int, str]] = {}
    for f in names:
        m = _FNAME_RE.match(f)
        if (not m or m.group("tag") != tag
                or m.group("fp") != fingerprint[:12]):
            continue
        key = (int(m.group("step")), int(m.group("nproc")))
        groups.setdefault(key, {})[int(m.group("rank"))] = f
    for (step, nproc), ranks in sorted(groups.items(), reverse=True):
        if set(ranks) != set(range(nproc)):
            reg["ckpt_rejects"].inc()
            continue
        shards, metas, ok = [], [], True
        for r in range(nproc):
            try:
                arrays, meta = load_fit_checkpoint(
                    os.path.join(directory, ranks[r]))
            except Exception:
                ok = False
                break
            if (meta.get("fingerprint") != fingerprint
                    or int(meta.get("step", -1)) != step):
                ok = False
                break
            shards.append(arrays)
            metas.append(meta)
        if not ok:
            reg["ckpt_rejects"].inc()
            continue
        return dict(step=step, nproc=nproc, shards=shards, meta=metas[0])
    return None


# -- deadline'd collective fence ---------------------------------------------

def deadline_block(x, timeout_s: Optional[float] = None,
                   tag: str = "collective", _blocker=None):
    """``jax.block_until_ready`` with a deadline. The block runs on a
    daemon worker; if it misses the deadline the caller raises
    `CollectiveTimeout` (the abort the surviving ranks need — the wedged
    dispatch itself cannot be cancelled, but the DRIVER regains control,
    marks the suspects down, and moves to resume). `_blocker` is the
    injectable wait for in-process tests."""
    timeout_s = fence_deadline_s() if timeout_s is None else timeout_s
    if _blocker is None:
        import jax

        def _blocker():
            jax.block_until_ready(x)
    if not timeout_s or timeout_s <= 0:
        _blocker()
        return x
    done = threading.Event()
    err: List[BaseException] = []

    def _run():
        try:
            _blocker()
        except BaseException as e:  # noqa: BLE001 — re-raised on the caller
            err.append(e)
        finally:
            done.set()

    t0 = time.perf_counter()
    threading.Thread(target=_run, daemon=True,
                     name="h2o3-fence-deadline").start()
    if not done.wait(timeout_s):
        latency = time.perf_counter() - t0
        suspects: List[int] = []
        try:
            from ..parallel import mesh
            suspects = list(mesh.lane_hang_report().get("suspect_ranks")
                            or [])
        except Exception:
            pass
        mark_ranks_down(suspects, reason="hung_collective")
        note_abort(tag, latency, suspects)
        raise CollectiveTimeout(
            f"collective fence '{tag}' exceeded its {timeout_s:.1f}s "
            f"deadline (waited {latency:.1f}s; suspect ranks: "
            f"{suspects or 'unknown'})")
    if err:
        raise err[0]
    return x


# -- background watcher ------------------------------------------------------

_WATCHER: Optional[threading.Thread] = None
_WATCH_STOP = threading.Event()


def start(poll_s: float = 2.0) -> bool:
    """Start the background failure watcher (launcher-armed on pods when
    a fence deadline is configured). It polls `lane_hang_report`: an open
    fence older than the deadline means a peer died mid-collective — the
    suspects are marked down and the abort recorded even if the driver
    thread is still stuck (detection must not depend on the victim)."""
    global _WATCHER
    with _LOCK:
        if _WATCHER is not None:
            return False
        _WATCH_STOP.clear()
        _WATCHER = threading.Thread(target=_watch_loop, args=(poll_s,),
                                    daemon=True, name="h2o3-supervisor")
        _WATCHER.start()
        return True


def stop() -> None:
    global _WATCHER
    with _LOCK:
        w = _WATCHER
        _WATCHER = None
    if w is not None:
        _WATCH_STOP.set()
        w.join(timeout=5.0)


def _watch_loop(poll_s: float) -> None:
    fired_tag = None
    while not _WATCH_STOP.wait(poll_s):
        deadline = fence_deadline_s()
        if deadline <= 0:
            continue
        try:
            from ..parallel import mesh
            rep = mesh.lane_hang_report()
        except Exception:
            continue
        open_tag = rep.get("open_fence")
        age = rep.get("last_fence_age_s")
        if (open_tag and age is not None and age > deadline
                and open_tag != fired_tag):
            # one detection per open fence: the wedged fence stays open, so
            # without the tag latch every poll would re-count the same hang
            fired_tag = open_tag
            suspects = list(rep.get("suspect_ranks") or [])
            mark_ranks_down(suspects, reason="heartbeat_stall")
            note_abort("watcher", float(age), suspects)
        elif not open_tag:
            fired_tag = None
