"""Micro-batching queue — coalesce concurrent requests into device batches.

The serving analog of XGBoost's GPU batch scoring (arxiv 1806.11248): the
device executes one padded batch far cheaper than N tiny dispatches, so
concurrent `/3/Predictions` requests for the same (model, output_kind) are
coalesced into one scored frame and scattered back per request.

Batching policy (the standard max-size/max-wait window):

- the first queued request opens a window of `max_wait_ms`;
- the window closes early once `max_batch_rows` rows have accumulated;
- only schema-compatible frames coalesce (same column names/types) — a
  mismatched request simply waits for its own batch, it is never rbind-ed
  into someone else's.

Error isolation: a batch that fails is re-scored request-by-request, so one
request's bad rows surface as *that* request's 4xx while its batch-mates
still get their predictions. One worker thread per (model_key, output_kind)
queue, started lazily and expired after `idle_worker_s` of quiet — a
serving host with 500 registered models does not carry 500 idle threads.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..runtime import qos as _qos
from .config import ServingConfig
from .metrics import ServingMetrics
from .model_cache import ScorerCache


class _Pending:
    """One enqueued request: input + rendezvous for the caller thread."""

    __slots__ = ("frame", "nrow", "sig", "model", "event", "result", "error",
                 "t_enqueue", "trace_id", "parent_span_id")

    def __init__(self, frame, model):
        from ..runtime import tracing

        self.frame = frame
        self.nrow = frame.nrow
        # trace correlation: the submitting thread (the REST handler with
        # its root request span) hands its ids over so the batch span the
        # worker thread records lands in the request's trace
        cur = tracing.current()
        self.trace_id = cur.trace_id if cur is not None else None
        self.parent_span_id = cur.span_id if cur is not None else None
        # coalescing compatibility: exact column names + types, in order,
        # AND the live model object's identity — a model re-put under the
        # same DKV key mid-flight must not have its requests scored by its
        # batch-mates' (older or newer) model. id() is stable here because
        # every pending holds a strong reference to its model.
        self.sig = (id(model),
                    tuple((n, frame.vec(n).type) for n in frame.names))
        self.model = model
        self.event = threading.Event()
        self.result = None
        self.error: Optional[BaseException] = None
        self.t_enqueue = time.monotonic()


class _Worker:
    """Owns one (model_key, output_kind) queue + its scoring thread."""

    def __init__(self, batcher: "MicroBatcher", model_key: str,
                 output_kind: str):
        self.batcher = batcher
        self.model_key = model_key
        self.output_kind = output_kind
        self.cond = threading.Condition()
        self.q: "deque[_Pending]" = deque()
        self.closed = False
        self.thread = threading.Thread(
            target=self._run, daemon=True,
            name=f"h2o3tpu-serve-{model_key}-{output_kind}")
        self.thread.start()

    # lock order everywhere: batcher._lock → worker.cond (never reversed)
    def _run(self):
        cfg = self.batcher.config
        while True:
            with self.cond:
                while not self.q and not self.closed:
                    if not self.cond.wait(timeout=cfg.idle_worker_s) \
                            and not self.q:
                        break
                if not self.q:
                    break   # idle-expired (or closed while empty)
                # batching window: first request's dwell bounds the wait
                deadline = self.q[0].t_enqueue + cfg.max_wait_ms / 1e3
                while (sum(p.nrow for p in self.q) < cfg.max_batch_rows
                       and not self.closed):
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        break
                    self.cond.wait(remaining)
                batch = self._pop_batch(cfg.max_batch_rows)
            if batch:   # the window's requests may all have expired
                self._score(batch)
        self.batcher._retire(self)

    def _pop_batch(self, max_rows: int) -> List[_Pending]:
        """Pop the schema-compatible head prefix (callers hold self.cond).

        Expired requests — whose caller's `event.wait` already timed out
        (and released its admission slot) — are retired HERE, unscored:
        under sustained overload the abandoned work would otherwise keep
        consuming device time and the deque could grow without bound
        past `max_queue` (only live requests hold admission slots)."""
        timeout_s = self.batcher.config.request_timeout_s
        cutoff = time.monotonic() - timeout_s
        expired = [p for p in self.q if p.t_enqueue < cutoff]
        if expired:
            dead = set(map(id, expired))
            self.q = deque(p for p in self.q if id(p) not in dead)
            for p in expired:
                p.error = TimeoutError(
                    f"request expired unscored after {timeout_s:.0f}s "
                    "in the batch queue")
                p.event.set()   # caller is already gone; unblock stragglers
            self.batcher.metrics.record_expired(self.model_key,
                                                len(expired))
        if not self.q:
            return []
        sig = self.q[0].sig
        batch, rows = [], 0
        while self.q and self.q[0].sig == sig:
            if batch and rows + self.q[0].nrow > max_rows:
                break
            p = self.q.popleft()
            batch.append(p)
            rows += p.nrow
        return batch

    def _score(self, batch: List[_Pending]) -> None:
        from ..runtime import tracing

        # the batch span adopts the first request's trace (one batch can
        # serve several traces — the rest ride along as an attribute)
        lead = next((p for p in batch if p.trace_id), None)
        extra = sorted({p.trace_id for p in batch
                        if p.trace_id} - {lead.trace_id if lead else None})
        with tracing.span(f"batch:{self.model_key}", kind="batch",
                          trace_id=lead.trace_id if lead else None,
                          parent_id=lead.parent_span_id if lead else None,
                          output_kind=self.output_kind,
                          n_requests=len(batch),
                          n_rows=sum(p.nrow for p in batch),
                          **(dict(other_trace_ids=extra) if extra else {})):
            # serving-class QoS dispatch: closes the gate for training
            # while the batch scores; entry never waits (SERVING > TRAINING)
            with _qos.serving_dispatch(self.model_key):
                self._score_inner(batch)

    def _score_inner(self, batch: List[_Pending]) -> None:
        from ..frame.frame import Frame

        t_start = time.monotonic()
        metrics = self.batcher.metrics
        for p in batch:
            metrics.record_queue_wait(self.model_key, t_start - p.t_enqueue)
        try:
            # every batch member shares one model object (identity is part
            # of the coalescing signature), so batch[0].model is THE model
            frame = (batch[0].frame if len(batch) == 1
                     else Frame.rbind_all([p.frame for p in batch]))
            out, compiled, device_s = self._score_frame(batch[0].model,
                                                        frame)
            metrics.record_batch(self.model_key, len(batch), frame.nrow,
                                 device_s, compiled)
            off = 0
            for p in batch:
                p.result = (out if len(batch) == 1 else
                            out.take(np.arange(off, off + p.nrow)))
                off += p.nrow
        except BaseException as e:
            if len(batch) == 1:
                batch[0].error = e
            else:
                # error isolation: re-score one by one so only the poisoned
                # request fails; batch-mates still get answers
                for p in batch:
                    try:
                        out, compiled, device_s = self._score_frame(p.model,
                                                                    p.frame)
                        metrics.record_batch(self.model_key, 1, p.nrow,
                                             device_s, compiled)
                        p.result = out
                    except BaseException as pe:
                        p.error = pe
        finally:
            for p in batch:
                p.event.set()

    def _score_frame(self, model, frame) -> Tuple[object, Optional[bool],
                                                  float]:
        """Primary scoring with failover (docs/robustness.md):

        device/XLA error → quarantine the compiled executables + rebuild
        once → a second device error opens the circuit breaker and this
        (and every subsequent) request degrades to the CPU-fallback scorer
        until a half-open probe closes it. Non-device errors (bad rows)
        propagate unchanged — they are the REQUEST's fault, not the
        scorer's."""
        from ..runtime import retry as _retrylib

        b = self.batcher
        fo = b.failover
        key = (self.model_key, self.output_kind)
        if b.config.cpu_fallback and fo.use_fallback(key):
            return fo.score_fallback(self.model_key, model,
                                     self.output_kind, frame)
        try:
            entry, _hit = b.cache.get_or_build(self.model_key, model,
                                               self.output_kind)
            out = entry.score(frame)
            fo.record_success(key)
            return out
        except Exception as e:
            if not _retrylib.is_device_error(e):
                fo.abort_probe(key)     # a half-open probe must not wedge
                raise
            b.metrics.record_scorer_fault(self.model_key)
            # quarantine the poisoned executable set, rebuild once — the
            # rebuild (build AND its first score) stays inside the
            # handler so a failure there still resolves the probe slot
            b.cache.invalidate(self.model_key)
            b.metrics.record_quarantine(self.model_key)
            try:
                entry2, _ = b.cache.get_or_build(self.model_key, model,
                                                 self.output_kind)
                out = entry2.score(frame)
                b.metrics.record_rebuild(self.model_key)
                fo.record_success(key)
                return out
            except Exception as e2:
                if not _retrylib.is_device_error(e2):
                    fo.abort_probe(key)
                    raise
                b.metrics.record_scorer_fault(self.model_key)
                fo.open_breaker(key)
                b.metrics.record_breaker_open(self.model_key)
                if not b.config.cpu_fallback:
                    raise
                return fo.score_fallback(self.model_key, model,
                                         self.output_kind, frame)


class MicroBatcher:
    """submit() facade + the per-(model, kind) worker registry."""

    def __init__(self, cache: ScorerCache, metrics: ServingMetrics,
                 config: ServingConfig,
                 failover: Optional["FailoverState"] = None):
        from .model_cache import FailoverState

        self.cache = cache
        self.metrics = metrics
        self.config = config
        # quarantine/breaker state shared with the engine's snapshot; a
        # directly-constructed batcher (tests) gets its own
        self.failover = failover if failover is not None \
            else FailoverState(config)
        self._lock = threading.Lock()
        self._workers: Dict[Tuple[str, str], _Worker] = {}

    def submit(self, model_key: str, model, frame,
               output_kind: str = "predict"):
        """Enqueue one request and block until its slice of the batch
        result is ready. Re-raises the request's own scoring error."""
        p = _Pending(frame, model)
        key = (model_key, output_kind)
        with self._lock:
            w = self._workers.get(key)
            if w is None or w.closed:
                w = self._workers[key] = _Worker(self, model_key,
                                                 output_kind)
            with w.cond:
                w.q.append(p)
                w.cond.notify_all()
        if not p.event.wait(timeout=self.config.request_timeout_s):
            raise TimeoutError(
                f"scoring {model_key!r} did not complete within "
                f"{self.config.request_timeout_s:.0f}s")
        if p.error is not None:
            raise p.error
        return p.result

    def _retire(self, worker: _Worker) -> None:
        """Idle worker exit — re-check emptiness under both locks so a
        submit racing the expiry either lands before (worker stays) or
        after (fresh worker spawns); requests are never stranded."""
        key = (worker.model_key, worker.output_kind)
        with self._lock:
            with worker.cond:
                if worker.q:
                    # raced: requests arrived between expiry and retire —
                    # hand the queue to a fresh worker
                    pending = list(worker.q)
                    worker.q.clear()
                    worker.closed = True
                    if self._workers.get(key) is worker:
                        del self._workers[key]
                    nw = self._workers[key] = _Worker(
                        self, worker.model_key, worker.output_kind)
                    with nw.cond:
                        nw.q.extend(pending)
                        nw.cond.notify_all()
                    return
                worker.closed = True
                if self._workers.get(key) is worker:
                    del self._workers[key]

    def shutdown(self) -> None:
        """Close every worker (tests / engine reset). Queued requests are
        drained by their worker's final loop turn before it exits."""
        with self._lock:
            workers = list(self._workers.values())
            self._workers.clear()
        for w in workers:
            with w.cond:
                w.closed = True
                w.cond.notify_all()
