"""Serving metrics — per-model counters + latency histograms.

The observability face of the scoring subsystem, exposed at
`GET /3/Serving/metrics` and folded into `/3/Profiler` via
`runtime/profiler.serving_stats()`. Counter semantics:

- ``requests`` / ``rejections`` / ``errors``: admission-level accounting —
  every `/3/Predictions` call lands in exactly one of admitted (requests),
  shed (rejections), or admitted-but-failed (errors counts the failures
  among admitted requests).
- ``batches`` / ``batched_requests`` / ``batched_rows``: micro-batcher
  output — how many device dispatches served how much work.
- ``expired``: requests retired unscored at batch-pop time because their
  caller's wait had already timed out (the admission slot was released at
  expiry; scoring abandoned work would spend device time on nobody).
- ``compiles`` / ``cache_hits``: compiled-scorer cache — a compile is a
  scorer build OR a new padded-row-bucket trace; a cache hit is a batch
  served entirely by a warm executable. The warm-path invariant the tests
  pin: a repeat request moves only ``cache_hits``.

Histograms are fixed-bound (log-spaced) so a snapshot is O(bounds), never
O(requests) — the histogram state is a counts vector, not a sample list.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Sequence

# log-ish spaced upper bounds; the last bucket is +inf (overflow)
WAIT_MS_BOUNDS = (0.5, 1, 2, 5, 10, 25, 50, 100, 250, 1000, 5000)
DEVICE_MS_BOUNDS = (1, 2, 5, 10, 25, 50, 100, 250, 1000, 5000, 30000)
BATCH_SIZE_BOUNDS = (1, 2, 4, 8, 16, 32, 64, 128, 512)

_COUNTERS = ("requests", "rejections", "errors", "batches",
             "batched_requests", "batched_rows", "compiles", "cache_hits",
             "expired", "scorer_faults", "quarantines", "scorer_rebuilds",
             "breaker_opens", "fallback_scores")

_REGISTRY = None


def _registry():
    """Central-registry families backing the serving counters/histograms
    (GET /3/Metrics scrape surface). The per-engine ServingMetrics object
    stays the resettable REST-snapshot state; the registry is the monotone
    process-wide view — both are written on every record so the
    /3/Serving/metrics document stays byte-compatible. Model-key label
    cardinality is bounded by the registry itself (H2O3_METRICS_MAX_SERIES
    → `_overflow` series), so uuid-keyed model churn on a long-lived
    fleet cannot grow the scrape surface without limit."""
    global _REGISTRY
    if _REGISTRY is None:
        from ..runtime import metrics_registry as reg

        counters = {c: reg.counter(f"h2o3_serving_{c}",
                                   f"serving {c.replace('_', ' ')}",
                                   labelnames=("model",))
                    for c in _COUNTERS}
        for c in _COUNTERS:
            reg.bind_rest_field("serving", f"totals.{c}",
                                f"h2o3_serving_{c}")
        _REGISTRY = dict(
            counters=counters,
            queue_wait_ms=reg.histogram(
                "h2o3_serving_queue_wait_ms",
                "request dwell in the micro-batch queue (ms)",
                bounds=WAIT_MS_BOUNDS, labelnames=("model",)),
            device_ms=reg.histogram(
                "h2o3_serving_device_ms",
                "scoring-call wall time per batch (ms)",
                bounds=DEVICE_MS_BOUNDS, labelnames=("model",)),
            batch_size=reg.histogram(
                "h2o3_serving_batch_size",
                "requests coalesced per device batch",
                bounds=BATCH_SIZE_BOUNDS, labelnames=("model",)),
        )
    return _REGISTRY


class LatencyHistogram:
    """Fixed-bound histogram: counts per bucket + running sum/min/max."""

    __slots__ = ("bounds", "counts", "n", "total", "vmin", "vmax")

    def __init__(self, bounds: Sequence[float]):
        self.bounds = tuple(bounds)
        self.counts = [0] * (len(self.bounds) + 1)
        self.n = 0
        self.total = 0.0
        self.vmin: Optional[float] = None
        self.vmax: Optional[float] = None

    def record(self, v: float) -> None:
        i = 0
        for i, b in enumerate(self.bounds):
            if v <= b:
                break
        else:
            i = len(self.bounds)
        self.counts[i] += 1
        self.n += 1
        self.total += v
        self.vmin = v if self.vmin is None else min(self.vmin, v)
        self.vmax = v if self.vmax is None else max(self.vmax, v)

    def snapshot(self) -> Dict:
        return dict(
            bounds=list(self.bounds), counts=list(self.counts), count=self.n,
            mean=round(self.total / self.n, 4) if self.n else None,
            min=self.vmin, max=self.vmax,
        )


class _ModelStats:
    __slots__ = ("counters", "queue_wait_ms", "device_ms", "batch_size")

    def __init__(self):
        self.counters = {c: 0 for c in _COUNTERS}
        self.queue_wait_ms = LatencyHistogram(WAIT_MS_BOUNDS)
        self.device_ms = LatencyHistogram(DEVICE_MS_BOUNDS)
        self.batch_size = LatencyHistogram(BATCH_SIZE_BOUNDS)

    def snapshot(self) -> Dict:
        return dict(
            counters=dict(self.counters),
            histograms=dict(queue_wait_ms=self.queue_wait_ms.snapshot(),
                            device_ms=self.device_ms.snapshot(),
                            batch_size=self.batch_size.snapshot()),
        )


class ServingMetrics:
    """Thread-safe per-model stats registry (one per ScoringEngine)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._models: Dict[str, _ModelStats] = {}

    def _stats(self, model_key: str) -> _ModelStats:
        # callers hold self._lock
        s = self._models.get(model_key)
        if s is None:
            s = self._models[model_key] = _ModelStats()
        return s

    def _bump(self, model_key: str, counter: str, by: int = 1) -> None:
        with self._lock:
            self._stats(model_key).counters[counter] += by
        _registry()["counters"][counter].inc(by, model_key)

    # -- admission-level ----------------------------------------------------
    def record_request(self, model_key: str) -> None:
        self._bump(model_key, "requests")

    def record_rejection(self, model_key: str) -> None:
        self._bump(model_key, "rejections")

    def record_error(self, model_key: str) -> None:
        self._bump(model_key, "errors")

    def record_expired(self, model_key: str, n: int = 1) -> None:
        """Queued requests retired at pop time because their caller's wait
        already timed out — abandoned work that never reaches the device."""
        self._bump(model_key, "expired", by=n)

    # -- failover (serving/model_cache.FailoverState + batcher) -------------
    def record_scorer_fault(self, model_key: str) -> None:
        """A compiled scorer raised a device/XLA runtime error."""
        self._bump(model_key, "scorer_faults")

    def record_quarantine(self, model_key: str) -> None:
        """The poisoned executable set was evicted from the scorer cache."""
        self._bump(model_key, "quarantines")

    def record_rebuild(self, model_key: str) -> None:
        """A quarantined scorer was rebuilt and scored successfully."""
        self._bump(model_key, "scorer_rebuilds")

    def record_breaker_open(self, model_key: str) -> None:
        """The rebuild also failed — circuit breaker opened (CPU fallback
        serves until the half-open probe succeeds)."""
        self._bump(model_key, "breaker_opens")

    # -- batcher / cache level ---------------------------------------------
    def record_queue_wait(self, model_key: str, wait_s: float) -> None:
        with self._lock:
            self._stats(model_key).queue_wait_ms.record(wait_s * 1e3)
        _registry()["queue_wait_ms"].observe(wait_s * 1e3, model_key)

    def record_batch(self, model_key: str, n_requests: int, n_rows: int,
                     device_s: float, compiled: Optional[bool]) -> None:
        """`compiled` is True for a cold-bucket trace, False for a warm-
        executable hit, None when the batch was served by the CPU-fallback
        path (quarantine/breaker) — fallback batches must not inflate the
        compile/warm-hit accounting the cache tests pin."""
        with self._lock:
            s = self._stats(model_key)
            s.counters["batches"] += 1
            s.counters["batched_requests"] += n_requests
            s.counters["batched_rows"] += n_rows
            if compiled is None:
                s.counters["fallback_scores"] += 1
            else:
                s.counters["compiles" if compiled else "cache_hits"] += 1
            s.device_ms.record(device_s * 1e3)
            s.batch_size.record(float(n_requests))
        r = _registry()
        r["counters"]["batches"].inc(1, model_key)
        r["counters"]["batched_requests"].inc(n_requests, model_key)
        r["counters"]["batched_rows"].inc(n_rows, model_key)
        if compiled is None:
            r["counters"]["fallback_scores"].inc(1, model_key)
        else:
            r["counters"]["compiles" if compiled
                          else "cache_hits"].inc(1, model_key)
        r["device_ms"].observe(device_s * 1e3, model_key)
        r["batch_size"].observe(float(n_requests), model_key)

    # -- read side ----------------------------------------------------------
    def counter(self, model_key: str, name: str) -> int:
        with self._lock:
            s = self._models.get(model_key)
            return s.counters.get(name, 0) if s else 0

    def snapshot(self) -> Dict:
        with self._lock:
            models = {k: s.snapshot() for k, s in self._models.items()}
        totals = {c: sum(m["counters"][c] for m in models.values())
                  for c in _COUNTERS}
        return dict(models=models, totals=totals)

    def reset(self) -> None:
        with self._lock:
            self._models.clear()
