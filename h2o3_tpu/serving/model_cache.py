"""Compiled-scorer cache — warm executables for the serving hot path.

Reference contrast: upstream's `/3/Predictions` route scores through the
live model object and the JVM's JIT keeps it warm for free. Under XLA every
new (program, shape) pair pays a trace+compile round-trip — seconds through
a remote-chip tunnel — so the serving layer must keep *both* the scorer
closure and its padded-batch shapes resident. This module is the inference
counterpart of the training side's program-economy rules
(docs/architecture.md "Program economy").

Two layers of reuse:

1. **Entry cache** (LRU): keyed on `(model_key, n_features, dtype,
   output_kind)`. The key carries the scoring signature, not just the model
   key, so re-training a model under the same DKV key (different feature
   count) can never serve stale executables; identity of the live model
   object is checked on every hit for the same reason.
2. **Row-bucket warm set**: batch rows pad up to a small set of bucket
   sizes (64/128/256, then multiples of `SCORE_ROW_BUCKET`) so nearby
   request sizes land on one traced program. The first visit to a bucket is
   a compile; later visits are cache hits. Note the `compiles` counter is
   serving-level (cold bucket seen), not an XLA-compile count: scorers with
   their own internal row bucketing (tree/GLM `_margins` pad to
   `SCORE_ROW_BUCKET`) share one device program across the sub-512 buckets,
   so a "compile" there costs only the host-side conversion, not a trace.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict
from typing import Dict, Optional, Tuple

import numpy as np

# sub-SCORE_ROW_BUCKET buckets: REST predict traffic is dominated by small
# frames (single rows to a few hundred); padding a 3-row request straight to
# 512 wastes device work, but 3→64→128→256 keeps the program count bounded
_SMALL_BUCKETS = (64, 128, 256)

# output_kind → model method (the three /3/Predictions scoring surfaces)
OUTPUT_KINDS = {
    "predict": "predict",
    "contributions": "predict_contributions",
    "leaves": "predict_leaf_node_assignment",
}


def bucket_rows(n: int) -> int:
    """Padded row count for an n-row batch (see module docstring)."""
    from ..models.model_base import SCORE_ROW_BUCKET

    for b in _SMALL_BUCKETS:
        if n <= b:
            return b
    return -(-n // SCORE_ROW_BUCKET) * SCORE_ROW_BUCKET


def scoring_signature(model) -> Tuple[int, str]:
    """(n_features, dtype) of a model's compiled scoring-program family —
    the shape-bearing parts of the cache key."""
    sig = getattr(model, "scoring_signature", None)
    if callable(sig):
        return sig()
    x = getattr(model, "x", None)
    nf = len(x) if isinstance(x, (list, tuple)) else (1 if x else 0)
    return (nf, "float32")


class CompiledScorer:
    """One cache entry: a bound scoring callable + its warm bucket set."""

    def __init__(self, model_key: str, model, output_kind: str):
        method = OUTPUT_KINDS.get(output_kind)
        if method is None:
            raise ValueError(f"unknown output kind {output_kind!r}")
        fn = getattr(model, method, None)
        if fn is None:
            what = {"contributions": "contributions",
                    "leaves": "leaf assignment"}.get(output_kind, output_kind)
            raise ValueError(f"{model_key!r} does not support {what}")
        self.model_key = model_key
        self.model = model          # identity-checked on cache hits
        self.output_kind = output_kind
        self._fn = fn
        self.warm_buckets: set = set()
        self.built_at = time.time()
        self._lock = threading.Lock()

    def score(self, frame) -> Tuple[object, bool, float]:
        """Score one (possibly coalesced) batch.

        Returns (result_frame, compiled, device_s): `compiled` is True when
        this call traced a new padded-bucket program (cold bucket)."""
        n = frame.nrow
        pad = bucket_rows(n) if n else 0
        if n and pad != n:
            # repeat row 0 as padding — always in-domain for enum columns,
            # unlike zeros, and sliced off below
            idx = np.concatenate([np.arange(n, dtype=np.int64),
                                  np.zeros(pad - n, np.int64)])
            scored = frame.take(idx)
        else:
            scored = frame
        with self._lock:
            compiled = pad not in self.warm_buckets
            self.warm_buckets.add(pad)
        t0 = time.perf_counter()
        out = self._fn(scored)
        device_s = time.perf_counter() - t0
        if n and pad != n:
            out = out.take(np.arange(n))
        return out, compiled, device_s


class ScorerCache:
    """LRU of CompiledScorer entries, keyed on the full scoring signature."""

    def __init__(self, capacity: int = 32):
        self.capacity = max(int(capacity), 1)
        self._lock = threading.Lock()
        self._entries: "OrderedDict[Tuple, CompiledScorer]" = OrderedDict()
        self.hits = 0
        self.misses = 0
        self.evictions = 0

    @staticmethod
    def _key(model_key: str, model, output_kind: str) -> Tuple:
        nf, dtype = scoring_signature(model)
        return (model_key, nf, dtype, output_kind)

    def get_or_build(self, model_key: str, model,
                     output_kind: str = "predict"
                     ) -> Tuple[CompiledScorer, bool]:
        """(entry, was_hit). Builds (and LRU-inserts) on miss; a hit whose
        entry wraps a *different* live object (model re-trained / re-loaded
        under the same key) rebuilds — stale executables must never score."""
        key = self._key(model_key, model, output_kind)
        with self._lock:
            entry = self._entries.get(key)
            if entry is not None and entry.model is model:
                self._entries.move_to_end(key)
                self.hits += 1
                return entry, True
            # miss or stale: build outside the map but under the lock —
            # scorer construction is cheap (the expensive trace happens at
            # first score()), and one build per key beats a thundering herd
            entry = CompiledScorer(model_key, model, output_kind)
            self._entries[key] = entry
            self._entries.move_to_end(key)
            self.misses += 1
            while len(self._entries) > self.capacity:
                self._entries.popitem(last=False)
                self.evictions += 1
            return entry, False

    def invalidate(self, model_key: Optional[str] = None) -> int:
        """Drop entries for one model key (or all). Returns drop count."""
        with self._lock:
            if model_key is None:
                n = len(self._entries)
                self._entries.clear()
                return n
            doomed = [k for k in self._entries if k[0] == model_key]
            for k in doomed:
                del self._entries[k]
            return len(doomed)

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def stats(self) -> Dict:
        with self._lock:
            entries = [dict(model=k[0], n_features=k[1], dtype=k[2],
                            output_kind=k[3],
                            warm_buckets=sorted(e.warm_buckets))
                       for k, e in self._entries.items()]
            return dict(capacity=self.capacity, size=len(entries),
                        hits=self.hits, misses=self.misses,
                        evictions=self.evictions, entries=entries)
