"""Compiled-scorer cache — warm executables for the serving hot path.

Reference contrast: upstream's `/3/Predictions` route scores through the
live model object and the JVM's JIT keeps it warm for free. Under XLA every
new (program, shape) pair pays a trace+compile round-trip — seconds through
a remote-chip tunnel — so the serving layer must keep *both* the scorer
closure and its padded-batch shapes resident. This module is the inference
counterpart of the training side's program-economy rules
(docs/architecture.md "Program economy").

Two layers of reuse:

1. **Entry cache** (LRU): keyed on `(model_key, n_features, dtype,
   output_kind)`. The key carries the scoring signature, not just the model
   key, so re-training a model under the same DKV key (different feature
   count) can never serve stale executables; identity of the live model
   object is checked on every hit for the same reason.
2. **Row-bucket warm set**: batch rows pad up to a small set of bucket
   sizes (64/128/256, then multiples of `SCORE_ROW_BUCKET`) so nearby
   request sizes land on one traced program. The first visit to a bucket is
   a compile; later visits are cache hits. Note the `compiles` counter is
   serving-level (cold bucket seen), not an XLA-compile count: scorers with
   their own internal row bucketing (tree/GLM `_margins` pad to
   `SCORE_ROW_BUCKET`) share one device program across the sub-512 buckets,
   so a "compile" there costs only the host-side conversion, not a trace.
"""

from __future__ import annotations

import threading
import time
import weakref
from collections import OrderedDict
from typing import Dict, Optional, Tuple

import numpy as np

from ..runtime import memory_ledger as _memory

# sub-SCORE_ROW_BUCKET buckets: REST predict traffic is dominated by small
# frames (single rows to a few hundred); padding a 3-row request straight to
# 512 wastes device work, but 3→64→128→256 keeps the program count bounded
_SMALL_BUCKETS = (64, 128, 256)

# output_kind → model method (the three /3/Predictions scoring surfaces)
OUTPUT_KINDS = {
    "predict": "predict",
    "contributions": "predict_contributions",
    "leaves": "predict_leaf_node_assignment",
}


def bucket_rows(n: int) -> int:
    """Padded row count for an n-row batch (see module docstring)."""
    from ..models.model_base import SCORE_ROW_BUCKET

    for b in _SMALL_BUCKETS:
        if n <= b:
            return b
    return -(-n // SCORE_ROW_BUCKET) * SCORE_ROW_BUCKET


def scoring_signature(model) -> Tuple[int, str]:
    """(n_features, dtype) of a model's compiled scoring-program family —
    the shape-bearing parts of the cache key."""
    sig = getattr(model, "scoring_signature", None)
    if callable(sig):
        return sig()
    x = getattr(model, "x", None)
    nf = len(x) if isinstance(x, (list, tuple)) else (1 if x else 0)
    return (nf, "float32")


class CompiledScorer:
    """One cache entry: a bound scoring callable + its warm bucket set."""

    def __init__(self, model_key: str, model, output_kind: str):
        method = OUTPUT_KINDS.get(output_kind)
        if method is None:
            raise ValueError(f"unknown output kind {output_kind!r}")
        fn = getattr(model, method, None)
        if fn is None:
            what = {"contributions": "contributions",
                    "leaves": "leaf assignment"}.get(output_kind, output_kind)
            raise ValueError(f"{model_key!r} does not support {what}")
        self.model_key = model_key
        self.model = model          # identity-checked on cache hits
        self.output_kind = output_kind
        self._fn = fn
        self.warm_buckets: set = set()
        self.built_at = time.time()
        self._lock = threading.Lock()

    def score(self, frame) -> Tuple[object, bool, float]:
        """Score one (possibly coalesced) batch.

        Returns (result_frame, compiled, device_s): `compiled` is True when
        this call traced a new padded-bucket program (cold bucket)."""
        n = frame.nrow
        pad = bucket_rows(n) if n else 0
        if n and pad != n:
            # repeat row 0 as padding — always in-domain for enum columns,
            # unlike zeros, and sliced off below
            idx = np.concatenate([np.arange(n, dtype=np.int64),
                                  np.zeros(pad - n, np.int64)])
            scored = frame.take(idx)
        else:
            scored = frame
        with self._lock:
            compiled = pad not in self.warm_buckets
            self.warm_buckets.add(pad)
        t0 = time.perf_counter()
        from ..runtime import faults as _faults

        # the serving.scorer fault point stands in for a device/XLA runtime
        # failure of THIS executable — the quarantine/fallback tests and
        # the chaos bench arm it (default off: one dict lookup)
        _faults.check("serving.scorer", self.model_key)
        out = self._fn(scored)
        device_s = time.perf_counter() - t0
        if n and pad != n:
            out = out.take(np.arange(n))
        return out, compiled, device_s


class ScorerCache:
    """LRU of CompiledScorer entries, keyed on the full scoring signature."""

    def __init__(self, capacity: int = 32):
        self.capacity = max(int(capacity), 1)
        self._lock = threading.Lock()
        self._entries: "OrderedDict[Tuple, CompiledScorer]" = OrderedDict()
        self.hits = 0
        self.misses = 0
        self.evictions = 0

    @staticmethod
    def _key(model_key: str, model, output_kind: str) -> Tuple:
        nf, dtype = scoring_signature(model)
        return (model_key, nf, dtype, output_kind)

    @staticmethod
    def _owner(key: Tuple) -> str:
        return f"scorer:{key[0]}:{key[3]}"

    @staticmethod
    def _register_ledger(key: Tuple, entry: "CompiledScorer") -> None:
        """Memory-ledger owner for one cache entry. The bytes attributed
        are the wrapped model's — but ONLY while the scorer is what pins
        it (the model no longer lives in the DKV under its key); while the
        DKV holds the same object, the `dkv:` owner accounts it and the
        scorer reports 0 instead of double-counting."""
        wr = weakref.ref(entry)

        def _bytes():
            e = wr()
            if e is None:
                return (0, 0)
            from ..runtime.dkv import DKV

            if DKV.get(e.model_key) is e.model:
                return (0, 0)
            return _memory.measure(e.model)

        _memory.register(ScorerCache._owner(key), kind="scorer",
                         bytes_fn=_bytes, referent=entry,
                         type_name=type(entry.model).__name__)

    def get_or_build(self, model_key: str, model,
                     output_kind: str = "predict"
                     ) -> Tuple[CompiledScorer, bool]:
        """(entry, was_hit). Builds (and LRU-inserts) on miss; a hit whose
        entry wraps a *different* live object (model re-trained / re-loaded
        under the same key) rebuilds — stale executables must never score."""
        key = self._key(model_key, model, output_kind)
        with self._lock:
            entry = self._entries.get(key)
            if entry is not None and entry.model is model:
                self._entries.move_to_end(key)
                self.hits += 1
                return entry, True
            # miss or stale: build outside the map but under the lock —
            # scorer construction is cheap (the expensive trace happens at
            # first score()), and one build per key beats a thundering herd
            entry = CompiledScorer(model_key, model, output_kind)
            self._entries[key] = entry
            self._entries.move_to_end(key)
            self.misses += 1
            self._register_ledger(key, entry)
            while len(self._entries) > self.capacity:
                old_key, _old = self._entries.popitem(last=False)
                self.evictions += 1
                _memory.unregister(self._owner(old_key), event="evict",
                                   trigger="cap")
            return entry, False

    def invalidate(self, model_key: Optional[str] = None) -> int:
        """Drop entries for one model key (or all). Returns drop count."""
        with self._lock:
            if model_key is None:
                doomed = list(self._entries)
                self._entries.clear()
            else:
                doomed = [k for k in self._entries if k[0] == model_key]
                for k in doomed:
                    del self._entries[k]
        for k in doomed:
            _memory.unregister(self._owner(k), event="evict",
                               trigger="invalidate")
        return len(doomed)

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def stats(self) -> Dict:
        with self._lock:
            entries = [dict(model=k[0], n_features=k[1], dtype=k[2],
                            output_kind=k[3],
                            warm_buckets=sorted(e.warm_buckets))
                       for k, e in self._entries.items()]
            return dict(capacity=self.capacity, size=len(entries),
                        hits=self.hits, misses=self.misses,
                        evictions=self.evictions, entries=entries)


# -- failover: quarantine + circuit breaker + CPU fallback -------------------

def build_fallback_scorer(model, output_kind: str):
    """A device-independent scorer for a quarantined model.

    Round-trips the model through its mojo artifact: `MojoScorer` scores
    with numpy only — the compiled-CPU degrade path (Out-of-Core GPU GBM's
    fall-back-to-the-slower-path stance, arXiv:2005.09148) that cannot be
    poisoned by a sick accelerator. When the artifact format doesn't cover
    the algo (TypeError from the exporter) the model's own bound method is
    the last resort — still isolated from the quarantined executable
    cache. Returns (callable, kind_label)."""
    method = OUTPUT_KINDS[output_kind]
    try:
        import tempfile

        from .. import mojo as mojolib

        with tempfile.TemporaryDirectory(prefix="h2o3_fallback_") as d:
            path = mojolib.save_model(model, d, force=True)
            scorer = mojolib.load_model(path)   # arrays load eagerly
        fn = getattr(scorer, method, None)
        if fn is not None:
            return fn, "mojo-cpu"
    except Exception:
        pass
    return getattr(model, method), "direct"


class FailoverState:
    """Per-(model_key, output_kind) circuit breaker + fallback scorers.

    Lifecycle the batcher drives (docs/robustness.md "Serving failover"):
    a device/XLA error quarantines the compiled-scorer entries (cache
    invalidate) and rebuilds ONCE; a second device error opens the breaker
    — requests are served by the CPU-fallback scorer for
    ``config.breaker_reset_s`` seconds, after which exactly one half-open
    probe retries the primary (success closes the breaker, failure re-opens
    it). The 5xx storm a crashing scorer used to produce becomes a
    latency degradation."""

    def __init__(self, config):
        self.config = config
        self._lock = threading.Lock()
        self._breakers: Dict[Tuple[str, str], Dict] = {}
        # LRU like the ScorerCache it mirrors: fallback scorers hold the
        # model AND its eagerly-loaded artifact arrays alive, so they must
        # not accumulate across model keys forever
        self._fallbacks: "OrderedDict[Tuple[str, str, int], Tuple]" = \
            OrderedDict()
        self.fallback_builds = 0

    # -- breaker ------------------------------------------------------------
    def use_fallback(self, key: Tuple[str, str]) -> bool:
        """True when this request must take the fallback path. After the
        reset dwell, ONE caller is elected half-open prober (gets False)
        while its peers keep falling back."""
        with self._lock:
            b = self._breakers.get(key)
            if b is None or b["state"] == "closed":
                return False
            now = time.monotonic()
            if now >= b["open_until"] and not b["probing"]:
                b["probing"] = True
                b["state"] = "half-open"
                return False
            return True

    def open_breaker(self, key: Tuple[str, str]) -> None:
        with self._lock:
            b = self._breakers.setdefault(
                key, dict(state="open", open_until=0.0, opens=0,
                          probing=False))
            b["state"] = "open"
            b["open_until"] = time.monotonic() + self.config.breaker_reset_s
            b["opens"] += 1
            b["probing"] = False

    def record_success(self, key: Tuple[str, str]) -> None:
        """A primary-path score succeeded: close the breaker (half-open
        probe passed, or the scorer was healthy all along)."""
        with self._lock:
            b = self._breakers.get(key)
            if b is not None and b["state"] != "closed":
                b["state"] = "closed"
                b["probing"] = False

    def abort_probe(self, key: Tuple[str, str]) -> None:
        """The elected half-open probe exited without a device verdict
        (e.g. the REQUEST's own rows were bad): give the probe slot back,
        else `probing=True` would pin every later request to the fallback
        forever even after the device recovers."""
        with self._lock:
            b = self._breakers.get(key)
            if b is not None and b["state"] == "half-open":
                b["state"] = "open"     # open_until already in the past:
                b["probing"] = False    # the next request re-probes

    # -- fallback scorers ---------------------------------------------------
    def fallback_fn(self, model_key: str, model, output_kind: str):
        """The cached CPU-fallback callable for (key, kind, live model) —
        keyed on object identity like the scorer cache, so a re-trained
        model under the same key gets a fresh fallback. The artifact
        round-trip runs OUTSIDE the lock (use_fallback/record_success take
        it on every batch — a multi-second export must not stall healthy
        models); a lost insert race simply adopts the winner's scorer."""
        ck = (model_key, output_kind, id(model))
        with self._lock:
            hit = self._fallbacks.get(ck)
            if hit is not None and hit[0] is model:
                self._fallbacks.move_to_end(ck)
                return hit[1]
        fn, kind = build_fallback_scorer(model, output_kind)
        with self._lock:
            cur = self._fallbacks.get(ck)
            if cur is not None and cur[0] is model:
                return cur[1]       # raced: another thread built it first
            self._fallbacks[ck] = (model, fn, kind)
            self.fallback_builds += 1
            # drop stale identities for this (key, kind), then bound the
            # cache like the compiled-scorer LRU
            for k in [k for k in self._fallbacks
                      if k[:2] == ck[:2] and k != ck]:
                del self._fallbacks[k]
            while len(self._fallbacks) > max(self.config.cache_capacity, 1):
                self._fallbacks.popitem(last=False)
        return fn

    def score_fallback(self, model_key: str, model, output_kind: str,
                       frame) -> Tuple[object, None, float]:
        """Score via the CPU fallback; the None `compiled` slot marks the
        batch as fallback-served for metrics.record_batch."""
        fn = self.fallback_fn(model_key, model, output_kind)
        t0 = time.perf_counter()
        out = fn(frame)
        return out, None, time.perf_counter() - t0

    def stats(self) -> Dict:
        with self._lock:
            now = time.monotonic()
            breakers = [
                dict(model=k[0], output_kind=k[1], state=b["state"],
                     opens=b["opens"],
                     reopens_in_s=(round(max(b["open_until"] - now, 0.0), 3)
                                   if b["state"] == "open" else None))
                for k, b in self._breakers.items()]
            fallbacks = [dict(model=k[0], output_kind=k[1], kind=v[2])
                         for k, v in self._fallbacks.items()]
        return dict(breakers=breakers, fallback_scorers=fallbacks,
                    fallback_builds=self.fallback_builds,
                    breaker_reset_s=self.config.breaker_reset_s,
                    cpu_fallback_enabled=self.config.cpu_fallback)
