"""Serving — the high-throughput scoring subsystem (inference counterpart
of the training stack).

Four cooperating pieces behind one `ScoringEngine` facade:

- `model_cache.ScorerCache` — LRU of compiled-scorer entries with
  padded-row-bucket warm sets: repeat requests hit a warm executable
  instead of re-tracing.
- `batcher.MicroBatcher` — concurrent requests for one (model,
  output_kind) coalesce into a single padded device batch; results scatter
  back per request; a bad request fails alone.
- `admission.AdmissionController` — bounded in-flight counts; overload
  sheds with 429 + Retry-After instead of OOMing the host.
- `metrics.ServingMetrics` — per-model counters + latency histograms,
  served at `GET /3/Serving/metrics` and folded into `/3/Profiler`.

The REST `/3/Predictions` route scores through `get_engine().score(...)`;
direct in-process `model.predict()` stays untouched for training
workflows (docs/serving.md has the architecture + knob matrix).

Above single-replica serving sits the serving FLEET (`registry.py` +
`router.py`): a versioned model registry with atomic publish/hot-swap and
a pressure-aware router fronting N replicas — canary/shadow rollout,
fleet-wide admission, cross-replica failover (`GET/POST /3/Router`;
docs/serving.md "Fleet serving").
"""

from __future__ import annotations

import threading
from typing import Dict, Optional

from .admission import AdmissionController, RejectedError  # noqa: F401
from .batcher import MicroBatcher
from .config import ServingConfig
from .metrics import ServingMetrics
from .model_cache import ScorerCache
from .registry import (ModelRegistry, get_registry,  # noqa: F401
                       peek_registry, reset_registry, versioned_key)
from .router import (Router, RouterConfig, get_router,  # noqa: F401
                     peek_router, reset_router)


class ScoringEngine:
    """Facade: admission → micro-batcher → compiled-scorer cache."""

    def __init__(self, config: Optional[ServingConfig] = None):
        from ..runtime import phases
        from .model_cache import FailoverState

        # serving always tracks XLA compiles/retraces: the warm-cache
        # "zero new traces" invariant is a pinned counter (runtime/phases
        # xla_counts), not a bench-only accounting mode
        phases.install_listener()
        self.config = config or ServingConfig.from_env()
        self.metrics = ServingMetrics()
        self.cache = ScorerCache(self.config.cache_capacity)
        # quarantine + circuit-breaker + CPU-fallback state (the failover
        # layer the batcher drives on device/XLA scorer errors)
        self.failover = FailoverState(self.config)
        self.batcher = MicroBatcher(self.cache, self.metrics, self.config,
                                    failover=self.failover)
        self.admission = AdmissionController(self.config, self.metrics)

    def score(self, model_key: str, model, frame,
              output_kind: str = "predict"):
        """Score `frame` with `model` through the serving path. Raises
        RejectedError under overload; re-raises the request's own scoring
        error otherwise."""
        self.admission.admit(model_key)
        try:
            self.metrics.record_request(model_key)
            try:
                return self.batcher.submit(model_key, model, frame,
                                           output_kind)
            except RejectedError:
                raise
            except BaseException:
                self.metrics.record_error(model_key)
                raise
        finally:
            self.admission.release(model_key)

    def snapshot(self) -> Dict:
        """Full observability document (the /3/Serving/metrics body)."""
        out = self.metrics.snapshot()
        out["cache"] = self.cache.stats()
        out["admission"] = self.admission.stats()
        out["failover"] = self.failover.stats()
        out["config"] = dict(
            max_batch_rows=self.config.max_batch_rows,
            max_wait_ms=self.config.max_wait_ms,
            max_queue=self.config.max_queue,
            model_inflight=self.config.model_inflight,
            shed_pressure=self.config.shed_pressure,
            cache_capacity=self.config.cache_capacity,
            breaker_reset_s=self.config.breaker_reset_s,
            cpu_fallback=self.config.cpu_fallback,
        )
        return out

    def shutdown(self) -> None:
        self.batcher.shutdown()


_engine: Optional[ScoringEngine] = None
_engine_lock = threading.Lock()


def get_engine() -> ScoringEngine:
    """The process-wide engine (lazily built from env config)."""
    global _engine
    with _engine_lock:
        if _engine is None:
            _engine = ScoringEngine()
        return _engine


def peek_engine() -> Optional[ScoringEngine]:
    """The engine if one exists — profiler/metrics readers must not
    instantiate a serving stack just to report that there isn't one."""
    return _engine


def reset_engine(config: Optional[ServingConfig] = None) -> ScoringEngine:
    """Swap in a fresh engine (tests / config reload). The old engine's
    workers drain and expire on their own."""
    global _engine
    with _engine_lock:
        old, _engine = _engine, ScoringEngine(config)
        if old is not None:
            old.shutdown()
        return _engine
