"""Serving fleet router — health/pressure-aware dispatch over N replicas.

The routing half of the serving fleet (docs/serving.md "Fleet serving"):
one stdlib-HTTP layer that fronts N serving replicas (the PR 13 fleet
peer registry names them) and owns rollout policy for the versioned
model registry (`serving/registry.py`). Per request:

1. **Admission at the router** — one fleet-wide in-flight token budget
   (``H2O3_ROUTER_MAX_INFLIGHT``) plus a fleet pressure gate: when every
   up replica's ``memory_ledger.pressure()`` gauge is above
   ``H2O3_ROUTER_SHED_PRESSURE``, new work sheds HERE with 429 +
   Retry-After instead of N per-replica 429s racing each other.
2. **Version split** — the DKV model key is rewritten to the registry's
   live version (``m`` → ``m@v3``); a running canary takes its split
   percent of requests (deterministic: request sequence mod 100, so a
   10% canary gets exactly every 10th-ish request, not a coin flip).
3. **Least-loaded dispatch** — replicas ranked by local in-flight count,
   then scraped pressure, then bucket-merged predict p99 (both from the
   `/3/Fleet` scrape machinery, refreshed at most every
   ``H2O3_ROUTER_REFRESH_S``); drained replicas are skipped.
4. **Failover** — a connection error or 5xx marks the replica; after
   ``H2O3_ROUTER_DRAIN_ERRORS`` consecutive failures it drains from the
   ring for ``H2O3_ROUTER_DRAIN_COOLDOWN_S``. The in-flight request
   retries on a peer (`runtime/retry.is_transient` classification,
   unified `retry.record` accounting) — a replica killed mid-load costs
   latency, never a caller-visible error.
5. **Canary health** — per-lane windows (requests/errors/latency
   buckets) since the canary started; when the canary's error rate or
   p99 breaches the live lane's by the configured ratios, the registry
   rolls it back automatically (``h2o3_router_rollbacks_total`` + a
   timeline event + the /3/Router document tell the story).
6. **Shadow scoring** — mirror requests to the shadow version on a
   daemon thread, optionally compare prediction heads, never return
   shadow results to the caller.

Replica spans join router spans: the forward carries the request's
``X-H2O3-Trace-Id``, so ``GET /3/Trace?scope=fleet&trace_id=`` shows one
tree across processes. Surfaces: ``GET/POST /3/Router`` (RouterV3),
``h2o3_router_*`` registry families, `runtime/profiler.router_stats`.
"""

from __future__ import annotations

import json
import os
import threading
import time
import urllib.error
import urllib.parse
import urllib.request
from typing import Dict, List, Optional, Tuple

from ..runtime import env_float as _env_float
from ..runtime import env_int as _env_int
from .admission import RejectedError
from .metrics import LatencyHistogram
from .registry import get_registry, versioned_key

__all__ = ["RouterConfig", "Router", "get_router", "peek_router",
           "reset_router"]

from dataclasses import dataclass


@dataclass(frozen=True)
class RouterConfig:
    """Router knobs (``H2O3_ROUTER_*`` — docs/serving.md has the table)."""

    max_inflight: int = 256        # fleet-wide in-flight token budget
    retry_after_s: float = 1.0     # Retry-After hint on router 429s
    shed_pressure: float = 0.97    # shed when EVERY up replica is above
    #                                this pressure (0 disables)
    refresh_s: float = 2.0         # min seconds between fleet scrapes
    timeout_s: float = 60.0        # per-forward HTTP timeout
    max_attempts: int = 3          # distinct replicas tried per request
    drain_errors: int = 3          # consecutive errors before a drain
    drain_cooldown_s: float = 5.0  # drained-replica dwell before a probe
    canary_pct: float = 10.0       # default canary split (POST can override)
    canary_min_samples: int = 20   # canary requests before health verdicts
    canary_err_ratio: float = 2.0  # rollback: canary err rate vs live's
    canary_err_tol: float = 0.02   # absolute err-rate floor added to the
    #                                ratio (a 0-error baseline still
    #                                tolerates 2% canary errors)
    canary_p99_ratio: float = 2.0  # rollback: canary p99 vs live p99
    shadow_compare_rows: int = 10  # prediction head rows diffed per shadow
    #                                mirror (0 skips the comparison)
    shadow_max_inflight: int = 8   # concurrent shadow mirrors (beyond →
    #                                dropped, counted — never backpressure)

    @staticmethod
    def from_env() -> "RouterConfig":
        return RouterConfig(
            max_inflight=_env_int("H2O3_ROUTER_MAX_INFLIGHT", 256),
            retry_after_s=_env_float("H2O3_ROUTER_RETRY_AFTER_S", 1.0),
            shed_pressure=_env_float("H2O3_ROUTER_SHED_PRESSURE", 0.97),
            refresh_s=_env_float("H2O3_ROUTER_REFRESH_S", 2.0),
            timeout_s=_env_float("H2O3_ROUTER_TIMEOUT_S", 60.0),
            max_attempts=_env_int("H2O3_ROUTER_ATTEMPTS", 3),
            drain_errors=_env_int("H2O3_ROUTER_DRAIN_ERRORS", 3),
            drain_cooldown_s=_env_float("H2O3_ROUTER_DRAIN_COOLDOWN_S", 5.0),
            canary_pct=_env_float("H2O3_ROUTER_CANARY_PCT", 10.0),
            canary_min_samples=_env_int("H2O3_ROUTER_CANARY_MIN_SAMPLES",
                                        20),
            canary_err_ratio=_env_float("H2O3_ROUTER_CANARY_ERR_RATIO", 2.0),
            canary_err_tol=_env_float("H2O3_ROUTER_CANARY_ERR_TOL", 0.02),
            canary_p99_ratio=_env_float("H2O3_ROUTER_CANARY_P99_RATIO", 2.0),
            shadow_compare_rows=_env_int("H2O3_ROUTER_SHADOW_COMPARE_ROWS",
                                         10),
            shadow_max_inflight=_env_int("H2O3_ROUTER_SHADOW_MAX_INFLIGHT",
                                         8),
        )


# counters mirrored into the central registry AND kept as local totals for
# the /3/Router document (the ServingMetrics dual-write pattern); every
# totals field below is bind_rest_field-declared so the metrics-consistency
# test covers the router surface
_COUNTERS = ("requests", "errors", "shed", "retries", "failovers", "drains",
             "rollbacks", "warm_loads", "shadow_requests", "shadow_errors",
             "shadow_mismatches", "shadow_dropped")

_REGISTRY: Dict = {}


def _router_registry() -> Dict:
    if not _REGISTRY:
        from ..runtime import metrics_registry as reg

        _REGISTRY.update(
            requests=reg.counter("h2o3_router_requests",
                                 "router-dispatched requests, per lane "
                                 "(live/canary/unversioned)",
                                 labelnames=("lane",)),
            errors=reg.counter("h2o3_router_errors",
                               "router requests that failed on every "
                               "attempted replica, per lane",
                               labelnames=("lane",)),
            shed=reg.counter("h2o3_router_shed",
                             "requests shed at the router (429), by reason "
                             "(budget/pressure/no_replicas)",
                             labelnames=("reason",)),
            retries=reg.counter("h2o3_router_retries",
                                "same-request forwards past the first "
                                "replica (failover + replica-429 hops)"),
            failovers=reg.counter("h2o3_router_failovers",
                                  "forwards that failed on a replica and "
                                  "moved to a peer", labelnames=("replica",)),
            drains=reg.counter("h2o3_router_drains",
                               "replicas drained from the ring after "
                               "consecutive errors", labelnames=("replica",)),
            rollbacks=reg.counter("h2o3_router_rollbacks",
                                  "canary auto-rollbacks, per model",
                                  labelnames=("model",)),
            warm_loads=reg.counter("h2o3_router_warm_loads",
                                   "replica warm-loads orchestrated by the "
                                   "router", labelnames=("replica",)),
            shadow=reg.counter("h2o3_router_shadow",
                               "shadow-scoring events "
                               "(requests/errors/mismatches/dropped)",
                               labelnames=("event",)),
            request_ms=reg.histogram("h2o3_router_request_ms",
                                     "router end-to-end request wall (ms), "
                                     "per lane",
                                     bounds=reg.LATENCY_MS_BOUNDS,
                                     labelnames=("lane",)),
        )
        for c in _COUNTERS:
            fam = ("h2o3_router_shadow" if c.startswith("shadow_")
                   else f"h2o3_router_{c}")
            reg.bind_rest_field("router", f"totals.{c}", fam)
    return _REGISTRY


class _Replica:
    """Router-local view of one ring member."""

    __slots__ = ("name", "url", "inflight", "consecutive_errors",
                 "drained_until", "pressure", "predict_p99_ms", "up")

    def __init__(self, name: str, url: str):
        self.name = name
        self.url = url
        self.inflight = 0
        self.consecutive_errors = 0
        self.drained_until = 0.0
        self.pressure: Optional[float] = None
        self.predict_p99_ms: Optional[float] = None
        self.up: Optional[bool] = None

    def describe(self) -> Dict:
        return dict(name=self.name, url=self.url,
                    up=(1 if self.up else 0) if self.up is not None
                    else None,
                    drained=self.drained_until > time.monotonic(),
                    inflight=self.inflight,
                    consecutive_errors=self.consecutive_errors,
                    pressure=self.pressure,
                    predict_p99_ms=self.predict_p99_ms)


class _Lane:
    """One traffic lane's health window (live vs canary since canary
    start) — error rate + bucket percentiles over the shared bounds."""

    __slots__ = ("n", "errors", "hist")

    def __init__(self):
        from ..runtime import metrics_registry as reg

        self.n = 0
        self.errors = 0
        self.hist = LatencyHistogram(reg.LATENCY_MS_BOUNDS)

    def record(self, ok: bool, lat_ms: Optional[float]) -> None:
        self.n += 1
        if not ok:
            self.errors += 1
        if lat_ms is not None:
            self.hist.record(lat_ms)

    def err_rate(self) -> float:
        return self.errors / self.n if self.n else 0.0

    def p99(self) -> Optional[float]:
        from ..runtime import metrics_registry as reg

        h = self.hist
        if not h.n:
            return None
        return reg.bucket_percentile(h.bounds, h.counts, h.n, 0.99,
                                     h.vmin, h.vmax)

    def describe(self) -> Dict:
        return dict(n=self.n, errors=self.errors,
                    err_rate=round(self.err_rate(), 4), p99_ms=self.p99())


class Router:
    """The routing layer: ring + admission + version split + failover."""

    def __init__(self, config: Optional[RouterConfig] = None):
        self.config = config or RouterConfig.from_env()
        self.registry = get_registry()
        self._lock = threading.Lock()
        self._replicas: Dict[str, _Replica] = {}
        self._inflight = 0
        self._shadow_inflight = 0
        self._seq = 0
        self._last_refresh = 0.0
        self._counters = {c: 0 for c in _COUNTERS}
        # model -> {"live": _Lane, "canary": _Lane, "since": ts} while a
        # canary runs; dropped on promote/rollback
        self._canary_windows: Dict[str, Dict] = {}
        _router_registry()

    # -- accounting ----------------------------------------------------------
    def _bump(self, counter: str, *labels) -> None:
        with self._lock:
            self._counters[counter] += 1
        reg = _router_registry()
        if counter.startswith("shadow_"):
            reg["shadow"].inc(1, counter[len("shadow_"):])
        else:
            reg[counter].inc(1, *labels)

    # -- ring state ----------------------------------------------------------
    def _ring(self) -> List[_Replica]:
        """Sync the router-local replica table with the fleet registry
        (the single source of ring membership)."""
        from ..runtime import fleet

        rows = fleet.peers()
        with self._lock:
            names = set()
            for p in rows:
                names.add(p["name"])
                r = self._replicas.get(p["name"])
                if r is None:
                    r = self._replicas[p["name"]] = _Replica(p["name"],
                                                             p["url"])
                r.url = p["url"]
                if p.get("up") is not None:
                    r.up = bool(p["up"])
            for gone in set(self._replicas) - names:
                del self._replicas[gone]
            return list(self._replicas.values())

    def refresh(self, force: bool = False) -> None:
        """Scrape the fleet (rate-limited) and fold per-replica pressure +
        predict p99 into the ring. Rides `fleet.scrape_states`, so
        `h2o3_fleet_peer_up` flips as a side effect — a dead replica is
        marked down on the shared liveness gauge by the same pass that
        drops it from dispatch."""
        now = time.monotonic()
        with self._lock:
            if not force and now - self._last_refresh < self.config.refresh_s:
                return
            self._last_refresh = now
        from ..runtime import fleet

        self._ring()
        for name, state in fleet.scrape_states():
            with self._lock:
                r = self._replicas.get(name)
                if r is None:
                    continue
                r.up = state is not None
                if state is None:
                    continue
                fam = state.get("h2o3_memory_pressure") or {}
                for s in fam.get("series") or ():
                    r.pressure = float(s.get("value") or 0.0)
                    break
                r.predict_p99_ms = fleet._serving_summary(state) \
                    .get("predict_p99_ms")

    def _candidates(self) -> List[_Replica]:
        """Dispatch order: up, undrained replicas first, least-loaded
        first — local in-flight count dominates (it is per-request fresh),
        then scraped pressure, then scraped predict p99 (both at most
        `refresh_s` stale). A replica past its drain cooldown re-enters
        the ring at the back as its own probe: if it is still sick, the
        request that probes it retries on a healthy peer."""
        now = time.monotonic()
        with self._lock:
            reps = list(self._replicas.values())

        def score(r: _Replica) -> Tuple:
            drained = r.drained_until > now
            down = r.up is False
            return (down, drained, r.inflight,
                    r.pressure if r.pressure is not None else 0.0,
                    r.predict_p99_ms if r.predict_p99_ms is not None
                    else 0.0)

        return sorted(reps, key=score)

    def _mark_result(self, r: _Replica, ok: bool) -> None:
        cfg = self.config
        with self._lock:
            if ok:
                r.consecutive_errors = 0
                r.up = True
                return
            r.consecutive_errors += 1
            drain = (r.consecutive_errors >= cfg.drain_errors
                     and r.drained_until <= time.monotonic())
            if drain:
                r.drained_until = time.monotonic() + cfg.drain_cooldown_s
                r.consecutive_errors = 0
        if drain:
            self._bump("drains", r.name)
            from ..runtime import tracing
            from ..runtime.timeline import Timeline

            Timeline.record("router", f"drain {r.name}",
                            cooldown_s=cfg.drain_cooldown_s)
            tracing.event("router_drain", replica=r.name)

    # -- version split -------------------------------------------------------
    def _pick_version(self, model: str,
                      seq: int) -> Tuple[Optional[str], str]:
        """(version, lane): the registry's live version unless the canary
        split claims this request. Deterministic — request seq mod 100
        against the split percent — so a canary at x% sees x% of traffic
        exactly, independent of arrival timing."""
        cv, pct = self.registry.canary(model)
        if cv is not None and pct > 0 and (seq % 100) < pct:
            return cv, "canary"
        live = self.registry.live(model)
        if live is not None:
            return live, "live"
        if cv is not None:
            # canary with no live baseline: the non-canary share passes
            # through to the unversioned key
            return None, "unversioned"
        return None, "unversioned"

    # -- the dispatch --------------------------------------------------------
    def route(self, model: str, frame: str,
              params: Optional[Dict] = None,
              trace_id: Optional[str] = None) -> Dict:
        """Route one predict request; returns the replica's response
        document. Raises RejectedError on shed, `urllib.error.HTTPError`
        to mirror a replica's 4xx/exhausted 5xx, OSError when every
        attempted replica was unreachable."""
        cfg = self.config
        self.refresh()
        with self._lock:
            seq = self._seq
            self._seq += 1
            if self._inflight >= cfg.max_inflight:
                shed_reason = "budget"
            else:
                shed_reason = self._pressure_shed_locked()
            if shed_reason is None:
                self._inflight += 1
        if shed_reason is not None:
            self._bump("shed", shed_reason)
            from ..runtime import tracing

            tracing.event("router_shed", reason=shed_reason)
            raise RejectedError(
                f"router shed ({shed_reason}): "
                f"{self._inflight}/{cfg.max_inflight} in flight",
                retry_after_s=cfg.retry_after_s)
        version, lane = self._pick_version(model, seq)
        key = versioned_key(model, version) if version else model
        win = self._lane_window(model, lane)
        t0 = time.perf_counter()
        try:
            doc, replica = self._dispatch(key, frame, params, trace_id)
        except urllib.error.HTTPError as e:
            if e.code < 500 and e.code != 429:
                raise          # the request's own 4xx — not a lane failure
            self._record_lane(model, lane, win, ok=False, lat_ms=None)
            self._bump("errors", lane)
            raise
        except RejectedError:
            raise              # shed (already counted), not a lane failure
        except Exception:
            self._record_lane(model, lane, win, ok=False, lat_ms=None)
            self._bump("errors", lane)
            raise
        finally:
            with self._lock:
                self._inflight -= 1
        lat_ms = (time.perf_counter() - t0) * 1e3
        self._bump("requests", lane)
        _router_registry()["request_ms"].observe(lat_ms, lane)
        self._record_lane(model, lane, win, ok=True, lat_ms=lat_ms)
        if lane != "canary":
            self._maybe_shadow(model, frame, params, trace_id,
                               doc, replica)
        return doc

    def _pressure_shed_locked(self) -> Optional[str]:
        """Fleet pressure gate (callers hold the lock): shed only when
        every replica we believe is up reports pressure at/above the
        threshold — one hot replica is a ranking problem, a hot FLEET is
        an admission problem."""
        cfg = self.config
        if cfg.shed_pressure <= 0:
            return None
        ups = [r for r in self._replicas.values()
               if r.up is not False and r.pressure is not None]
        if ups and all(r.pressure >= cfg.shed_pressure for r in ups):
            return "pressure"
        return None

    def _dispatch(self, key: str, frame: str, params: Optional[Dict],
                  trace_id: Optional[str]) -> Tuple[Dict, _Replica]:
        """Forward to the best replica, failing over across peers. The
        caller sees an error only when every attempted replica failed."""
        from ..runtime import retry as retrylib

        cfg = self.config
        order = self._candidates()
        if not order:
            self._bump("shed", "no_replicas")
            raise RejectedError("router has no registered replicas "
                                "(POST /3/Fleet to add ring members)",
                                retry_after_s=cfg.retry_after_s)
        last_err: Optional[BaseException] = None
        for i, r in enumerate(order[:max(cfg.max_attempts, 1)]):
            if i > 0:
                self._bump("retries")
                retrylib.record("router", "retries")
            with self._lock:
                r.inflight += 1
            try:
                doc = self._forward_one(r, key, frame, params, trace_id)
                self._mark_result(r, ok=True)
                return doc, r
            except urllib.error.HTTPError as e:
                e.read()
                if e.code < 500 and e.code != 429:
                    # the request's own fault: replica is healthy, mirror
                    # the 4xx to the caller unchanged
                    self._mark_result(r, ok=True)
                    raise
                # replica-shed 429s hop to a less-loaded peer; 5xx marks
                # the replica on its way out
                self._mark_result(r, ok=e.code == 429)
                if e.code >= 500:
                    self._bump("failovers", r.name)
                last_err = e
            except OSError as e:
                # connection-level failure — the killed-replica path
                self._mark_result(r, ok=False)
                self._bump("failovers", r.name)
                last_err = e
                # a dead socket means the scrape view is stale: refresh
                # now so peer_up flips and ranking stops proposing it
                self.refresh(force=True)
            finally:
                with self._lock:
                    r.inflight -= 1
        assert last_err is not None
        retrylib.record("router", "attempts_exhausted")
        raise last_err

    def _forward_one(self, r: _Replica, key: str, frame: str,
                     params: Optional[Dict],
                     trace_id: Optional[str]) -> Dict:
        from ..runtime import faults, tracing

        url = (f"{r.url}/3/Predictions/models/"
               f"{urllib.parse.quote(key, safe='')}/frames/"
               f"{urllib.parse.quote(frame, safe='')}")
        body = urllib.parse.urlencode(params or {}).encode()
        headers = {}
        if trace_id:
            headers["X-H2O3-Trace-Id"] = trace_id
        with tracing.span(f"forward:{r.name}", kind="router",
                          trace_id=trace_id, replica=r.name, model=key):
            faults.check("router.forward", detail=f"{r.name}:{key}")
            req = urllib.request.Request(url, data=body, headers=headers)
            # serving-class QoS dispatch: in a single-process fleet the
            # forward scores in THIS runtime — the gate must see it
            from ..runtime import qos as _qos

            with _qos.serving_dispatch(f"router:{key}"):
                with urllib.request.urlopen(
                        req, timeout=self.config.timeout_s) as resp:
                    return json.loads(resp.read().decode())

    # -- canary health -------------------------------------------------------
    def _lane_window(self, model: str, lane: str) -> Optional[_Lane]:
        cv, _pct = self.registry.canary(model)
        with self._lock:
            if cv is None:
                self._canary_windows.pop(model, None)
                return None
            w = self._canary_windows.get(model)
            if w is None or w["version"] != cv:
                w = self._canary_windows[model] = dict(
                    version=cv, since=time.time(),
                    live=_Lane(), canary=_Lane())
            return w.get(lane if lane == "canary" else "live")

    def _record_lane(self, model: str, lane: str, win: Optional[_Lane],
                     ok: bool, lat_ms: Optional[float]) -> None:
        if win is None:
            return
        with self._lock:
            win.record(ok, lat_ms)
        if lane == "canary":
            self._maybe_rollback(model)

    def _maybe_rollback(self, model: str) -> None:
        """Auto-rollback verdict after each canary-lane request: with at
        least `canary_min_samples` canary observations, the canary's
        error rate must stay under (live's × err_ratio + err_tol), and —
        when the live lane has enough samples to make the comparison
        meaningful — its p99 under live's × p99_ratio."""
        cfg = self.config
        with self._lock:
            w = self._canary_windows.get(model)
            if w is None:
                return
            can, base = w["canary"], w["live"]
            if can.n < cfg.canary_min_samples:
                return
            reason = None
            err_bound = base.err_rate() * cfg.canary_err_ratio \
                + cfg.canary_err_tol
            if can.err_rate() > err_bound:
                reason = (f"error rate {can.err_rate():.3f} > "
                          f"{err_bound:.3f} (live {base.err_rate():.3f})")
            elif base.n >= cfg.canary_min_samples:
                bp, cp = base.p99(), can.p99()
                if (bp is not None and cp is not None and bp > 0
                        and cp > bp * cfg.canary_p99_ratio):
                    reason = (f"p99 {cp:.1f}ms > {cfg.canary_p99_ratio}x "
                              f"live {bp:.1f}ms")
            if reason is None:
                return
            version = w["version"]
            del self._canary_windows[model]
        self.registry.rollback(model, reason=f"auto: {reason}")
        self._bump("rollbacks", model)
        from ..runtime import tracing

        tracing.event("router_rollback", model=model, version=version,
                      reason=reason)

    # -- shadow scoring ------------------------------------------------------
    def _maybe_shadow(self, model: str, frame: str, params: Optional[Dict],
                      trace_id: Optional[str], primary_doc: Dict,
                      primary_replica: _Replica) -> None:
        sv = self.registry.shadow(model)
        if sv is None:
            return
        with self._lock:
            if self._shadow_inflight >= self.config.shadow_max_inflight:
                drop = True
            else:
                drop = False
                self._shadow_inflight += 1
        if drop:
            self._bump("shadow_dropped")
            return
        skey = versioned_key(model, sv)
        pkey = ((primary_doc.get("predictions_frame") or {}).get("name")
                if isinstance(primary_doc, dict) else None)
        t = threading.Thread(
            target=self._shadow_one,
            args=(skey, frame, params, trace_id, pkey, primary_replica),
            daemon=True, name=f"h2o3tpu-shadow-{model}")
        t.start()

    def _shadow_one(self, skey: str, frame: str, params: Optional[Dict],
                    trace_id: Optional[str], primary_pred_key: Optional[str],
                    primary_replica: _Replica) -> None:
        """Mirror one request to the shadow version. Results never reach
        the caller; a differing prediction head bumps
        `h2o3_router_shadow{event="mismatches"}` + a timeline event."""
        try:
            self._bump("shadow_requests")
            order = self._candidates()
            if not order:
                raise OSError("no replicas")
            r = order[0]
            doc = self._forward_one(r, skey, frame, params, trace_id)
            rows = self.config.shadow_compare_rows
            if rows > 0 and primary_pred_key:
                skey_pred = (doc.get("predictions_frame") or {}).get("name")
                a = self._pred_head(primary_replica, primary_pred_key, rows)
                b = self._pred_head(r, skey_pred, rows) if skey_pred \
                    else None
                if a is not None and b is not None and a != b:
                    self._bump("shadow_mismatches")
                    from ..runtime.timeline import Timeline

                    Timeline.record("router", f"shadow mismatch {skey}",
                                    frame=frame, rows=rows)
        except Exception:
            self._bump("shadow_errors")
        finally:
            with self._lock:
                self._shadow_inflight -= 1

    def _pred_head(self, r: _Replica, pred_key: str,
                   rows: int) -> Optional[List]:
        """First `rows` values of the prediction column, fetched from the
        replica that scored it (None when unreadable — an unreadable head
        is a shadow ERROR path, never a mismatch verdict)."""
        try:
            url = (f"{r.url}/3/Frames/"
                   f"{urllib.parse.quote(pred_key, safe='')}")
            with urllib.request.urlopen(
                    url, timeout=self.config.timeout_s) as resp:
                doc = json.loads(resp.read().decode())
            for col in doc.get("columns") or ():
                if col.get("label") == "predict":
                    return list(col.get("data") or ())[:rows]
        except Exception:
            return None
        return None

    # -- warm orchestration --------------------------------------------------
    def warm(self, model: str, version: str,
             frame: Optional[str] = None) -> Dict:
        """Fan the published artifact out to every replica's scorer cache
        (``POST /3/Serving/warm``) BEFORE any traffic flips — each
        replica loads the mojo into its DKV under the versioned key and
        primes the compiled-scorer cache against `frame`, reporting its
        XLA trace delta. Per-replica results land on the registry record
        (the warm-load pin asserts a later first predict traces
        nothing)."""
        from ..runtime import fleet
        from ..runtime.retry import RetryPolicy

        artifact = self.registry.artifact(model, version)
        key = versioned_key(model, version)
        body = urllib.parse.urlencode(
            dict(path=artifact, model=key,
                 **(dict(frame=frame) if frame else {}))).encode()
        policy = RetryPolicy(name="router", max_attempts=2,
                             deadline_s=self.config.timeout_s)

        def one(r: _Replica) -> Tuple[str, Dict]:
            def post():
                req = urllib.request.Request(r.url + "/3/Serving/warm",
                                             data=body)
                with urllib.request.urlopen(
                        req, timeout=self.config.timeout_s) as resp:
                    return json.loads(resp.read().decode())

            try:
                out = policy.call(post)
                self._bump("warm_loads", r.name)
                self.registry.record_warm(model, version, r.name, out)
                return (r.name, dict(ok=True, **out))
            except Exception as e:
                return (r.name, dict(ok=False,
                                     error=f"{type(e).__name__}: {e}"))

        results = dict(fleet._fan_out(one, self._ring()))
        return dict(model=model, version=version, artifact=artifact,
                    replicas=results,
                    warmed=sum(1 for v in results.values() if v.get("ok")))

    # -- read side -----------------------------------------------------------
    def snapshot(self, probe: bool = False) -> Dict:
        """The ``GET /3/Router`` document: ring, per-model versions +
        split, canary health windows, counters, config. `probe=True`
        forces a fleet refresh first."""
        if probe:
            self.refresh(force=True)
        else:
            self._ring()
        with self._lock:
            ring = [r.describe() for r in self._replicas.values()]
            totals = dict(self._counters)
            windows = {m: dict(version=w["version"], since=w["since"],
                               live=w["live"].describe(),
                               canary=w["canary"].describe())
                       for m, w in self._canary_windows.items()}
            inflight = self._inflight
        cfg = self.config
        return dict(
            ring=ring,
            inflight=inflight,
            totals=totals,
            models=self.registry.snapshot()["models"],
            canary_health=windows,
            config=dict(max_inflight=cfg.max_inflight,
                        shed_pressure=cfg.shed_pressure,
                        refresh_s=cfg.refresh_s,
                        max_attempts=cfg.max_attempts,
                        drain_errors=cfg.drain_errors,
                        drain_cooldown_s=cfg.drain_cooldown_s,
                        canary_pct=cfg.canary_pct,
                        canary_min_samples=cfg.canary_min_samples,
                        canary_err_ratio=cfg.canary_err_ratio,
                        canary_p99_ratio=cfg.canary_p99_ratio,
                        shadow_compare_rows=cfg.shadow_compare_rows),
        )


_router: Optional[Router] = None
_router_lock = threading.Lock()


def get_router() -> Router:
    """The process-wide router (lazily built from env config)."""
    global _router
    with _router_lock:
        if _router is None:
            _router = Router()
        return _router


def peek_router() -> Optional[Router]:
    """The router if one exists — profiler/bench readers must not
    instantiate a routing layer just to report that there isn't one."""
    return _router


def reset_router(config: Optional[RouterConfig] = None) -> Router:
    """Swap in a fresh router (tests / config reload)."""
    global _router
    with _router_lock:
        _router = Router(config)
        return _router
