"""Versioned model registry — atomic publish / warm / canary / live.

The artifact half of the serving fleet (docs/serving.md "Fleet serving"):
`publish(model, version)` exports the model as a mojo zip THROUGH the
persist layer (so persist fault points and retries cover the write) into
the registry directory, then atomically renames it into place — a publish
that dies mid-write leaves only a ``.part`` file that no replica will
ever load, and `live()` never names a half-published artifact.

Version lifecycle (every transition is a Timeline event)::

    publish → published → (warm) → canary → live → retired
                  └──────────────────────────┘ rollback / retire

State is router-process-local (the router owns rollout policy); the
ARTIFACTS live in a shared directory (``H2O3_REGISTRY_DIR``) replicas
warm-load from via ``POST /3/Serving/warm``. Atomicity contract, pinned
by tests:

* a publish whose artifact write fails is never visible to `live()`;
* double-publish of the same (model, version) is idempotent — the first
  artifact wins, the record is returned unchanged;
* `promote` flips the live pointer under the registry lock — a routing
  decision sees the old version or the new one, never a mix;
* `rollback` with no canary is a no-op that still logs a timeline event
  (an operator's "roll back now" must leave an audit trail even when
  there was nothing to do).
"""

from __future__ import annotations

import os
import threading
import time
from typing import Dict, List, Optional

from ..runtime.timeline import Timeline

__all__ = ["ModelRegistry", "get_registry", "peek_registry",
           "reset_registry", "versioned_key"]

# version states, in lifecycle order
STATES = ("published", "warm", "canary", "live", "retired", "failed")


def versioned_key(model: str, version: str) -> str:
    """The DKV key a warm-loaded artifact serves under — the model key the
    router rewrites requests to (`m@v2`), and the detail string
    `serving.scorer` fault checks carry (so a `match=`-scoped fault can
    target exactly one version's traffic)."""
    return f"{model}@{version}"


class _Version:
    __slots__ = ("model", "version", "state", "artifact", "published_ts",
                 "warmed", "events")

    def __init__(self, model: str, version: str, artifact: str):
        self.model = model
        self.version = version
        self.state = "published"
        self.artifact = artifact
        self.published_ts = time.time()
        self.warmed: Dict[str, Dict] = {}    # replica -> warm-load report
        self.events: List[str] = ["published"]

    def describe(self) -> Dict:
        return dict(model=self.model, version=self.version, state=self.state,
                    artifact=self.artifact, published_ts=self.published_ts,
                    key=versioned_key(self.model, self.version),
                    warmed=dict(self.warmed), events=list(self.events))


class ModelRegistry:
    """Per-model version table + live/canary/shadow pointers."""

    def __init__(self, root: Optional[str] = None):
        self.root = root or os.environ.get("H2O3_REGISTRY_DIR") \
            or os.path.join(os.environ.get("TMPDIR", "/tmp"),
                            "h2o3_registry")
        self._lock = threading.Lock()
        # model -> {versions: {v: _Version}, live, canary, canary_pct,
        #           shadow}
        self._models: Dict[str, Dict] = {}

    # -- internals -----------------------------------------------------------
    def _entry(self, model: str) -> Dict:
        # callers hold self._lock
        e = self._models.get(model)
        if e is None:
            e = self._models[model] = dict(versions={}, live=None,
                                           canary=None, canary_pct=0.0,
                                           shadow=None)
        return e

    def _event(self, kind: str, model: str, version: Optional[str],
               **extra) -> None:
        detail = versioned_key(model, version) if version else model
        Timeline.record("registry", f"{kind} {detail}", **extra)
        from ..runtime import tracing

        tracing.event(f"registry_{kind}", model=model,
                      **(dict(version=version) if version else {}), **extra)

    # -- publish (the atomic write) ------------------------------------------
    def publish(self, model_key: str, version: str, model=None,
                source_path: Optional[str] = None) -> Dict:
        """Export `model` (or copy an already-exported mojo at
        `source_path`) into the registry as (model_key, version).

        The artifact is written through the persist layer to a ``.part``
        name and `os.replace`d into place — the version record registers
        only after the rename, so a mid-write failure (persist fault,
        full disk, killed process) leaves `live()`/`versions()` exactly
        as they were. Idempotent: re-publishing an existing (model,
        version) returns the existing record untouched."""
        with self._lock:
            existing = self._entry(model_key)["versions"].get(version)
        if existing is not None:
            self._event("publish_noop", model_key, version,
                        reason="already published")
            return existing.describe()
        os.makedirs(self.root, exist_ok=True)
        final = os.path.join(self.root,
                             f"{model_key}@{version}.zip")
        part = final + ".part"
        blob = self._export_blob(model_key, model, source_path)
        try:
            from ..runtime import persist

            # write through the persist backend: the registry inherits the
            # retry policy AND the persist.open fault point (the atomicity
            # test arms it to kill a publish mid-write)
            with persist.for_uri(part).open(part, "wb") as f:
                f.write(blob)
            os.replace(part, final)        # the atomic flip
        except BaseException:
            # a failed publish must leave no half-artifact a replica could
            # ever list or load
            try:
                os.remove(part)
            except OSError:
                pass
            self._event("publish_failed", model_key, version)
            raise
        rec = _Version(model_key, version, final)
        with self._lock:
            e = self._entry(model_key)
            if version in e["versions"]:       # lost a publish race
                return e["versions"][version].describe()
            e["versions"][version] = rec
        self._event("publish", model_key, version, artifact=final)
        return rec.describe()

    @staticmethod
    def _export_blob(model_key: str, model, source_path: Optional[str]):
        import tempfile

        if source_path is not None:
            with open(source_path, "rb") as f:
                return f.read()
        if model is None:
            raise ValueError(
                f"publish of {model_key!r} needs a model object or a "
                "source_path to an exported mojo")
        from .. import mojo

        with tempfile.TemporaryDirectory(prefix="h2o3_pub_") as td:
            out = mojo.save_model(model, path=td,
                                  filename=f"{model_key}.h2o3")
            with open(out, "rb") as f:
                return f.read()

    # -- lifecycle transitions ----------------------------------------------
    def record_warm(self, model: str, version: str, replica: str,
                    report: Optional[Dict] = None) -> Dict:
        """One replica finished warm-loading (artifact in its DKV, scorer
        cache primed). The version moves to `warm` on its first report."""
        with self._lock:
            rec = self._require(model, version)
            rec.warmed[replica] = dict(report or {}, ts=time.time())
            if rec.state == "published":
                rec.state = "warm"
                rec.events.append("warm")
        self._event("warm", model, version, replica=replica)
        return rec.describe()

    def set_canary(self, model: str, version: str, pct: float) -> Dict:
        """Start (or re-weight) a canary: `pct` % of `model` traffic goes
        to `version`; the rest stays on live."""
        pct = min(max(float(pct), 0.0), 100.0)
        with self._lock:
            rec = self._require(model, version)
            e = self._entry(model)
            if e["live"] == version:
                raise ValueError(
                    f"{versioned_key(model, version)} is already live")
            e["canary"] = version
            e["canary_pct"] = pct
            if rec.state in ("published", "warm"):
                rec.state = "canary"
                rec.events.append("canary")
        self._event("canary", model, version, pct=pct)
        return rec.describe()

    def promote(self, model: str, version: str) -> Dict:
        """Atomic hot-swap: flip the live pointer to `version` under the
        registry lock. The previous live version retires."""
        with self._lock:
            rec = self._require(model, version)
            e = self._entry(model)
            prev = e["live"]
            e["live"] = version
            if e["canary"] == version:
                e["canary"], e["canary_pct"] = None, 0.0
            rec.state = "live"
            rec.events.append("live")
            if prev and prev in e["versions"] and prev != version:
                e["versions"][prev].state = "retired"
                e["versions"][prev].events.append("retired")
        self._event("promote", model, version, previous=prev)
        return rec.describe()

    def rollback(self, model: str, reason: str = "") -> Dict:
        """Abort the canary (auto-rollback's hook, and the operator's).
        With no canary running this is a NO-OP that still records a
        timeline event — audit trails must cover the nothing-to-do case."""
        with self._lock:
            e = self._entry(model)
            version = e["canary"]
            if version is not None:
                rec = e["versions"].get(version)
                e["canary"], e["canary_pct"] = None, 0.0
                if rec is not None:
                    rec.state = "failed"
                    rec.events.append("rollback")
        self._event("rollback", model, version,
                    **(dict(reason=reason) if reason else {}),
                    noop=version is None)
        return dict(model=model, rolled_back=version,
                    noop=version is None, reason=reason or None)

    def set_shadow(self, model: str, version: Optional[str]) -> Dict:
        """Mirror `model` traffic to `version` (compare-only; None
        stops shadowing)."""
        with self._lock:
            if version is not None:
                self._require(model, version)
            self._entry(model)["shadow"] = version
        self._event("shadow", model, version or "-")
        return dict(model=model, shadow=version)

    def retire(self, model: str, version: str) -> Dict:
        with self._lock:
            rec = self._require(model, version)
            e = self._entry(model)
            if e["live"] == version:
                raise ValueError(
                    f"cannot retire the live version "
                    f"{versioned_key(model, version)}; promote a "
                    "replacement first")
            if e["canary"] == version:
                e["canary"], e["canary_pct"] = None, 0.0
            if e["shadow"] == version:
                e["shadow"] = None
            rec.state = "retired"
            rec.events.append("retired")
        self._event("retire", model, version)
        return rec.describe()

    def _require(self, model: str, version: str) -> _Version:
        # callers hold self._lock
        rec = self._entry(model)["versions"].get(version)
        if rec is None:
            raise KeyError(versioned_key(model, version))
        return rec

    # -- read side -----------------------------------------------------------
    def live(self, model: str) -> Optional[str]:
        with self._lock:
            return self._models.get(model, {}).get("live")

    def canary(self, model: str):
        """(version, pct) of the running canary, or (None, 0.0)."""
        with self._lock:
            e = self._models.get(model) or {}
            return e.get("canary"), float(e.get("canary_pct") or 0.0)

    def shadow(self, model: str) -> Optional[str]:
        with self._lock:
            return self._models.get(model, {}).get("shadow")

    def artifact(self, model: str, version: str) -> str:
        with self._lock:
            return self._require(model, version).artifact

    def versions(self, model: str) -> List[Dict]:
        with self._lock:
            e = self._models.get(model) or {}
            return [r.describe() for r in (e.get("versions") or {}).values()]

    def models(self) -> List[str]:
        with self._lock:
            return sorted(self._models)

    def snapshot(self) -> Dict:
        with self._lock:
            return dict(
                root=self.root,
                models={m: dict(live=e["live"], canary=e["canary"],
                                canary_pct=e["canary_pct"],
                                shadow=e["shadow"],
                                versions=[r.describe()
                                          for r in e["versions"].values()])
                        for m, e in self._models.items()})


_registry: Optional[ModelRegistry] = None
_registry_lock = threading.Lock()


def get_registry() -> ModelRegistry:
    """The process-wide registry (lazily built from env config)."""
    global _registry
    with _registry_lock:
        if _registry is None:
            _registry = ModelRegistry()
        return _registry


def peek_registry() -> Optional[ModelRegistry]:
    return _registry


def reset_registry(root: Optional[str] = None) -> ModelRegistry:
    """Swap in a fresh registry (tests / config reload)."""
    global _registry
    with _registry_lock:
        _registry = ModelRegistry(root)
        return _registry
