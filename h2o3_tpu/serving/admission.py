"""Admission control — bounded queues + backpressure for the scoring path.

Upstream H2O accepts every `/3/Predictions` request and lets the JVM heap
absorb the burst; under load that means OOM-killing the cloud. Here the
serving layer sheds load *at the door*: a global bound on
queued+in-flight requests plus a per-model in-flight bound. Overload
degrades to HTTP 429 + `Retry-After` — a signal load balancers and client
retry loops understand — instead of an unbounded host queue.

The controller is a counter, not a queue: the actual queueing lives in the
micro-batcher; admission only decides whether a request may join it.
"""

from __future__ import annotations

import threading
from contextlib import contextmanager
from typing import Dict

from .config import ServingConfig
from .metrics import ServingMetrics


class RejectedError(Exception):
    """Request shed by admission control → HTTP 429 + Retry-After."""

    def __init__(self, msg: str, retry_after_s: float):
        super().__init__(msg)
        self.retry_after_s = retry_after_s


class AdmissionController:
    def __init__(self, config: ServingConfig, metrics: ServingMetrics):
        self.config = config
        self.metrics = metrics
        self._lock = threading.Lock()
        self._total = 0
        self._per_model: Dict[str, int] = {}

    def admit(self, model_key: str) -> None:
        """Claim a slot or raise RejectedError. Pair with release()."""
        cfg = self.config
        # byte-side backpressure: past the ledger's shed threshold the
        # host/device budget is nearly exhausted — shedding at the door
        # (cheap cached read between refresh intervals) beats OOMing the
        # process mid-batch. Same 429 + Retry-After contract as the
        # queue bounds. The read goes through qos.pressure_view() — the
        # ONE snapshot the dataset cache's eviction also reads, so a
        # scrape-time refresh between the two sites can't shed serving
        # requests while admitting training work: within a view,
        # shed-serving implies evict-training-artifacts.
        if cfg.shed_pressure > 0:
            from ..runtime import qos

            view = qos.pressure_view()
            pr = view.value
            if view.decide(cfg.shed_pressure):
                self.metrics.record_rejection(model_key)
                raise RejectedError(
                    f"memory pressure {pr:.2f} >= shed threshold "
                    f"{cfg.shed_pressure:.2f}; retry later",
                    cfg.retry_after_s)
        with self._lock:
            if self._total >= cfg.max_queue:
                self.metrics.record_rejection(model_key)
                raise RejectedError(
                    f"serving queue full ({self._total}/{cfg.max_queue} "
                    "in flight); retry later", cfg.retry_after_s)
            if self._per_model.get(model_key, 0) >= cfg.model_inflight:
                self.metrics.record_rejection(model_key)
                raise RejectedError(
                    f"model {model_key!r} at its in-flight limit "
                    f"({cfg.model_inflight}); retry later",
                    cfg.retry_after_s)
            self._total += 1
            self._per_model[model_key] = self._per_model.get(model_key,
                                                             0) + 1

    def release(self, model_key: str) -> None:
        with self._lock:
            self._total = max(self._total - 1, 0)
            n = self._per_model.get(model_key, 0) - 1
            if n > 0:
                self._per_model[model_key] = n
            else:
                self._per_model.pop(model_key, None)

    @contextmanager
    def slot(self, model_key: str):
        self.admit(model_key)
        try:
            yield
        finally:
            self.release(model_key)

    def stats(self) -> Dict:
        with self._lock:
            return dict(in_flight=self._total,
                        max_queue=self.config.max_queue,
                        model_inflight_limit=self.config.model_inflight,
                        per_model=dict(self._per_model))
