"""Serving configuration — every knob of the scoring subsystem in one place.

All knobs are env-overridable (`H2O3_SERVING_*`) so a deployment can tune
the batcher/admission behavior without code changes, the same way the REST
layer reads `H2O3_MAX_BODY_MB`. Defaults are chosen for a loopback CPU
deployment; a real TPU serving pod wants a larger `max_batch_rows` (amortize
the tunnel round-trip) and a tighter `max_wait_ms` (the device is fast, the
queue should not be the latency floor).
"""

from __future__ import annotations

import os
from dataclasses import dataclass


from ..runtime import env_float as _env_float
from ..runtime import env_int as _env_int


@dataclass(frozen=True)
class ServingConfig:
    """Knobs of the four serving pieces (docs/serving.md has the matrix)."""

    # -- batcher (serving/batcher.py) --------------------------------------
    max_batch_rows: int = 8192     # coalesce up to this many rows per batch
    max_wait_ms: float = 2.0       # first request's max queue dwell
    request_timeout_s: float = 300.0   # caller-side wait bound (500 beyond)
    idle_worker_s: float = 30.0    # per-model worker thread expiry

    # -- admission control (serving/admission.py) --------------------------
    max_queue: int = 256           # global queued+in-flight request bound
    model_inflight: int = 64       # per-model admitted request bound
    retry_after_s: float = 1.0     # Retry-After hint on 429
    shed_pressure: float = 0.97    # memory-ledger pressure at which new
    #                                requests shed with 429 (0 disables)

    # -- compiled-scorer cache (serving/model_cache.py) --------------------
    cache_capacity: int = 32       # LRU entries (model × output_kind)

    # -- failover (serving/model_cache.FailoverState + batcher) ------------
    breaker_reset_s: float = 30.0  # open-breaker dwell before a half-open
    #                                probe retries the primary scorer
    cpu_fallback: bool = True      # degrade to the numpy artifact scorer
    #                                when the device scorer is quarantined

    @staticmethod
    def from_env() -> "ServingConfig":
        return ServingConfig(
            max_batch_rows=_env_int("H2O3_SERVING_MAX_BATCH_ROWS", 8192),
            max_wait_ms=_env_float("H2O3_SERVING_MAX_WAIT_MS", 2.0),
            request_timeout_s=_env_float("H2O3_SERVING_TIMEOUT_S", 300.0),
            idle_worker_s=_env_float("H2O3_SERVING_IDLE_WORKER_S", 30.0),
            max_queue=_env_int("H2O3_SERVING_MAX_QUEUE", 256),
            model_inflight=_env_int("H2O3_SERVING_MODEL_INFLIGHT", 64),
            retry_after_s=_env_float("H2O3_SERVING_RETRY_AFTER_S", 1.0),
            shed_pressure=_env_float("H2O3_SERVING_SHED_PRESSURE", 0.97),
            cache_capacity=_env_int("H2O3_SERVING_CACHE_CAPACITY", 32),
            breaker_reset_s=_env_float("H2O3_SERVING_BREAKER_RESET_S", 30.0),
            cpu_fallback=os.environ.get(
                "H2O3_SERVING_CPU_FALLBACK", "1") not in ("0", "false", ""),
        )
