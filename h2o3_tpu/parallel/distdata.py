"""Process-local → global array plumbing for multi-host training.

Reference parity: `water/fvec/Vec`'s home-node chunk layout + `MRTask`'s
implicit "compute where the data lives". In the TPU framework a multi-host
cloud trains on ONE global `jax.Array` per column whose shards live where
each process parsed them: `jax.make_array_from_process_local_data` is the
DKV-home-node placement, and host-side reductions that the reference ran as
MRTask reduces (global means, min/max, weighted sums) run here as
`multihost_utils.process_allgather` collectives.

Row balancing: byte-range ingest gives every process a *similar but not
equal* row count, while a global row-sharded array needs equal per-device
shards. Every process therefore pads its local block to the agreed
per-process quota with ZERO-WEIGHT rows (w=0 ⇒ no gradient, no histogram,
no Gram contribution — the same trick the single-process path uses for its
pad tail). Algorithms must mask by `w`, which they already do.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import numpy as np


def multiprocess() -> bool:
    import jax

    return jax.process_count() > 1


def allgather_bytes(payload: bytes) -> "list[bytes]":
    """Variable-length byte blobs from every process, in rank order."""
    import jax

    if jax.process_count() == 1:
        return [payload]
    import jax.numpy as jnp
    from jax.experimental import multihost_utils

    lens = np.asarray(multihost_utils.process_allgather(
        jnp.asarray([len(payload)], jnp.int32))).reshape(-1)
    maxlen = int(max(lens.max(), 1))
    buf = np.zeros(maxlen, np.uint8)
    buf[: len(payload)] = np.frombuffer(payload, np.uint8)
    out = np.asarray(multihost_utils.process_allgather(jnp.asarray(buf)))
    out = out.reshape(len(lens), maxlen)
    return [out[r, : lens[r]].tobytes() for r in range(len(lens))]


def allgather_host(arr: np.ndarray) -> np.ndarray:
    """(nproc, *arr.shape) stack of every process's host array. f64 arrays
    travel as raw bytes: with x64 disabled a device gather would silently
    truncate them to f32, rounding exactly the quantities (global sums,
    min/max of timestamp-scale columns) this transport exists to keep
    exact."""
    import jax

    a = np.asarray(arr)
    if jax.process_count() == 1:
        return a[None]
    if a.dtype == np.float64:
        blobs = allgather_bytes(np.ascontiguousarray(a).tobytes())
        return np.stack([np.frombuffer(b, np.float64).reshape(a.shape)
                         for b in blobs])
    import jax.numpy as jnp
    from jax.experimental import multihost_utils

    out = multihost_utils.process_allgather(jnp.asarray(a))
    return np.asarray(out)


def global_sum(arr: np.ndarray) -> np.ndarray:
    return allgather_host(np.asarray(arr)).sum(axis=0)


def allgather_rows(local: np.ndarray) -> np.ndarray:
    """Rank-order concatenation of every process's local rows — the GLOBAL
    row order (ingest shards are contiguous byte ranges assigned in rank
    order, `frame/distributed_parse.py`). Ranks may hold different row
    counts; byte transport keeps dtypes exact."""
    a = np.ascontiguousarray(local)
    if not multiprocess():
        return a
    blobs = allgather_bytes(a.tobytes())
    trail = a.shape[1:]
    return np.concatenate([
        np.frombuffer(b, a.dtype).reshape((-1,) + trail) for b in blobs])


def allgather_rows_padded(local: np.ndarray, quota: int,
                          counts: np.ndarray) -> np.ndarray:
    """Global row-order concat with ONE fixed-size collective: each rank
    pads its rows to `quota` (loop-invariant), gathers (nproc, quota, ...),
    and trims per the known per-rank `counts`. Use for per-round gathers
    where `allgather_rows`'s variable-length byte transport would pay two
    collectives per call. Float64 inputs are rejected (the device gather
    would truncate them — use allgather_rows for exact f64)."""
    a = np.ascontiguousarray(local)
    if a.dtype == np.float64:
        raise TypeError("allgather_rows_padded is f32/int transport; "
                        "use allgather_rows for exact f64")
    if not multiprocess():
        return a
    pad = quota - a.shape[0]
    if pad > 0:
        a = np.concatenate([a, np.zeros((pad,) + a.shape[1:], a.dtype)])
    out = allgather_host(a)                      # (nproc, quota, ...)
    return np.concatenate([out[r, : int(counts[r])]
                           for r in range(len(counts))])


def row_counts(n_local: int) -> np.ndarray:
    """Per-rank local row counts in rank order (one-time collective);
    pair with `allgather_rows_padded`."""
    return allgather_host(np.asarray([n_local], np.int64)).reshape(-1)


def row_offset(n_local: int) -> int:
    """This process's first-row index in the global row order."""
    import jax

    if not multiprocess():
        return 0
    return int(row_counts(n_local)[: jax.process_index()].sum())


def global_any(flag: bool) -> bool:
    """True iff ANY process votes True (one host collective; single-process
    = identity). The canonical transport for control-flow consensus —
    every rank MUST take the same branch or subsequent collectives
    deadlock (clock votes, early-stop votes)."""
    if not multiprocess():
        return bool(flag)
    votes = allgather_host(
        np.asarray([1.0 if flag else 0.0], np.float32)).reshape(-1)
    return bool(votes.max() >= 0.5)


def global_all(flag: bool) -> bool:
    """True iff EVERY process votes True (one host collective)."""
    if not multiprocess():
        return bool(flag)
    votes = allgather_host(
        np.asarray([1.0 if flag else 0.0], np.float32)).reshape(-1)
    return bool(votes.min() >= 0.5)


def global_minmax(local_min: np.ndarray, local_max: np.ndarray):
    """Per-column global (min, max) from per-process locals (NaN-safe: a
    process with no finite values contributes ±inf)."""
    mins = allgather_host(np.asarray(local_min, np.float64))
    maxs = allgather_host(np.asarray(local_max, np.float64))
    return np.min(mins, axis=0), np.max(maxs, axis=0)


def local_quota(n_local: int, row_multiple: int = 8) -> int:
    """The per-process padded row count every process agrees on: the max
    local count, rounded up so each local device shard stays aligned."""
    import jax

    from . import mesh as cloudlib

    counts = allgather_host(np.asarray([n_local], np.int32)).reshape(-1)
    ldev = max(len(jax.local_devices()), 1)
    return cloudlib.pad_to_multiple(int(counts.max()),
                                    max(ldev * row_multiple, row_multiple))


def global_row_array(local: np.ndarray, quota: int, cloud, fill=0):
    """Pad this process's rows to `quota` and assemble the global row-sharded
    jax.Array (nproc·quota global rows, shards resident where parsed)."""
    import jax

    pad = quota - local.shape[0]
    if pad:
        fill_block = np.full((pad,) + local.shape[1:], fill, local.dtype)
        local = np.concatenate([local, fill_block])
    if not multiprocess():
        import jax.numpy as jnp

        return jax.device_put(jnp.asarray(local), cloud.row_sharding())
    return jax.make_array_from_process_local_data(
        cloud.row_sharding(), local)


def replicated_array(host_value, cloud):
    """Host value (identical on every process) → replicated global array."""
    import jax
    import jax.numpy as jnp

    arr = np.asarray(host_value)
    if not multiprocess():
        return jax.device_put(jnp.asarray(arr), cloud.replicated())
    from jax.experimental import multihost_utils
    from jax.sharding import PartitionSpec as P

    return multihost_utils.host_local_array_to_global_array(
        arr, cloud.mesh, P())


def sharded_full(shape, value, dtype, cloud):
    """Create a row-sharded constant directly on the devices (no host
    transfer — works across processes where device_put of host data can't)."""
    import jax
    import jax.numpy as jnp

    return jax.jit(lambda: jnp.full(shape, value, dtype),
                   out_shardings=cloud.row_sharding())()


def global_order_stats(values: np.ndarray, ranks: Sequence[int],
                       iters: int = 4, nb: int = 512) -> np.ndarray:
    """Exact-to-f32-ulp global order statistics x_(k) (0-based, global sort
    order) of a column whose rows are scattered across processes — the
    `hex/quantile/Quantile.java` iterative-histogram-refinement design as a
    host collective.

    Each iteration histograms the local shard into `nb` uniform bins per
    tracked rank interval, `global_sum`s the counts (exact integers ⇒ the
    refinement path is DETERMINISTIC and independent of the process count),
    and shrinks each interval to the bin containing its rank. After `iters`
    rounds the interval width is range·nb^-iters (~1e-11 of range), below
    f32 ulp for f32-sourced data; the midpoint is returned.

    `values` must be this process's finite values (NaNs pre-dropped).
    """
    v = np.sort(np.asarray(values, np.float64))
    ranks = np.asarray(ranks, np.int64)
    lo0, hi0 = ((v[0], v[-1]) if v.size else (np.inf, -np.inf))
    glo, ghi = global_minmax(np.asarray([lo0]), np.asarray([hi0]))
    glo, ghi = float(glo[0]), float(ghi[0])
    if not np.isfinite(glo):
        return np.full(len(ranks), np.nan)
    if ghi <= glo:
        return np.full(len(ranks), glo)
    M = len(ranks)
    lo = np.full(M, glo)
    hi = np.full(M, ghi)
    below = np.zeros(M, np.int64)       # global count of values < lo[m]
    for _ in range(iters):
        # counts[m, b] = #local values in bin b of interval m (right-closed
        # last bin, matching np.histogram)
        edges = lo[:, None] + (hi - lo)[:, None] * (
            np.arange(nb + 1)[None, :] / nb)
        idx = np.searchsorted(v, edges)           # (M, nb+1)
        idx[:, -1] = np.searchsorted(v, edges[:, -1], side="right")
        counts = np.diff(idx, axis=1).astype(np.float64)
        gc = global_sum(counts)                   # exact: integer-valued
        cum = below[:, None] + np.cumsum(gc, axis=1)   # (M, nb)
        # bin containing rank k: first bin with cum > k
        b = (cum <= ranks[:, None]).sum(axis=1)
        b = np.minimum(b, nb - 1)
        prev = np.where(b > 0, np.take_along_axis(cum, np.maximum(
            b - 1, 0)[:, None], axis=1)[:, 0], below)
        below = np.where(b > 0, prev.astype(np.int64), below)
        width = (hi - lo) / nb
        lo = lo + b * width
        hi = lo + width
    return (lo + hi) / 2


def global_quantiles(values: np.ndarray, probs: Sequence[float],
                     n_global: Optional[int] = None) -> np.ndarray:
    """np.quantile (linear interpolation) over the global multiset of a
    scattered column: locate the two adjacent order statistics per prob via
    `global_order_stats` and interpolate. Deterministic across cloud sizes."""
    v = np.asarray(values, np.float64)
    v = v[np.isfinite(v)]
    if n_global is None:
        n_global = int(global_sum(np.asarray([v.size], np.int64))[0])
    if n_global == 0:
        return np.full(len(probs), np.nan)
    t = np.asarray(probs, np.float64) * (n_global - 1)
    k = np.floor(t).astype(np.int64)
    frac = t - k
    k2 = np.minimum(k + 1, n_global - 1)
    ks = np.concatenate([k, k2])
    xs = global_order_stats(v, ks)
    xk, xk2 = xs[: len(k)], xs[len(k):]
    return xk + frac * (xk2 - xk)


# -- canonical row layout (pod training) -------------------------------------
#
# The quota layout above pads every rank's tail, so pad rows INTERLEAVE with
# real rows at rank boundaries in the global order. A padded block then holds
# a different subset of real rows than the same block of a single-process
# fit, and the f32 blocked fold — deterministic per layout — cannot be
# bit-identical across cloud sizes. The canonical layout fixes the geometry
# instead of the algorithm: all real rows stay contiguous in global ingest
# order, ALL pad sits at the global tail, and each rank owns an equal
# `npad // nproc` slice. Byte-range ingest already lands each rank within a
# few rows of its canonical slice, so the exchange moves only the misaligned
# boundary spans (exact byte transport), never the bulk.


def canonical_counts(counts: np.ndarray, npad: int) -> np.ndarray:
    """Per-rank REAL-row counts under the canonical equal split: rank r owns
    canonical rows [r·shard, (r+1)·shard) of [real rows | tail pad]."""
    counts = np.asarray(counts, np.int64)
    nproc = len(counts)
    if npad % nproc:
        raise ValueError(f"npad {npad} not divisible by nproc {nproc}")
    shard = npad // nproc
    n_global = int(counts.sum())
    starts = np.minimum(np.arange(nproc, dtype=np.int64) * shard, n_global)
    stops = np.minimum((np.arange(nproc, dtype=np.int64) + 1) * shard,
                       n_global)
    return stops - starts


def export_spans(src_counts: np.ndarray, dst_counts: np.ndarray,
                 rank: int) -> Tuple[Tuple[int, int], Tuple[int, int]]:
    """Pure routing math (unit-tested in-process): the (global_start, length)
    head and tail spans of `rank`'s source rows that fall OUTSIDE its
    destination range when the global row concatenation is re-split from
    `src_counts` to `dst_counts`. Both count vectors must sum to the same
    global total."""
    src = np.asarray(src_counts, np.int64)
    dst = np.asarray(dst_counts, np.int64)
    soff = int(src[:rank].sum())
    sn = int(src[rank])
    doff = int(dst[:rank].sum())
    dn = int(dst[rank])
    head_stop = min(soff + sn, doff)
    head = (soff, max(head_stop - soff, 0))
    tail_start = max(soff, doff + dn)
    tail = (tail_start, max(soff + sn - tail_start, 0))
    return head, tail


def exchange_rows(local: np.ndarray, src_counts: np.ndarray,
                  dst_counts: np.ndarray) -> np.ndarray:
    """Re-split the conceptual global row concatenation from `src_counts`
    to `dst_counts`: each rank exports only the rows outside its own
    destination range (one small allgather of the boundary spans, exact
    byte transport) and assembles its destination slice from the local
    overlap plus imports. O(misalignment) traffic, not O(n)."""
    a = np.ascontiguousarray(local)
    src = np.asarray(src_counts, np.int64)
    dst = np.asarray(dst_counts, np.int64)
    if int(src.sum()) != int(dst.sum()):
        raise ValueError(f"count mismatch: {src.sum()} != {dst.sum()}")
    if not multiprocess():
        return a
    import jax

    r = jax.process_index()
    soff, sn = int(src[:r].sum()), int(src[r])
    doff, dn = int(dst[:r].sum()), int(dst[r])
    if a.shape[0] != sn:
        raise ValueError(f"rank {r} holds {a.shape[0]} rows, counts say {sn}")
    (hs, hl), (ts, tl) = export_spans(src, dst, r)
    header = np.asarray([hs, hl, ts, tl], np.int64).tobytes()
    payload = (header + a[hs - soff: hs - soff + hl].tobytes()
               + a[ts - soff: ts - soff + tl].tobytes())
    blobs = allgather_bytes(payload)
    trail = a.shape[1:]
    rowbytes = int(a.dtype.itemsize * int(np.prod(trail, dtype=np.int64)))
    out = np.empty((dn,) + trail, a.dtype)
    ov_lo, ov_hi = max(soff, doff), min(soff + sn, doff + dn)
    if ov_hi > ov_lo:
        out[ov_lo - doff: ov_hi - doff] = a[ov_lo - soff: ov_hi - soff]
    covered = max(ov_hi - ov_lo, 0)
    for blob in blobs:
        ghs, ghl, gts, gtl = np.frombuffer(blob[:32], np.int64)
        off = 32
        for gstart, glen in ((int(ghs), int(ghl)), (int(gts), int(gtl))):
            span = blob[off: off + glen * rowbytes]
            off += glen * rowbytes
            lo, hi = max(gstart, doff), min(gstart + glen, doff + dn)
            if hi > lo:
                rows = np.frombuffer(span, a.dtype).reshape((glen,) + trail)
                out[lo - doff: hi - doff] = rows[lo - gstart: hi - gstart]
                covered += hi - lo
    if covered != dn:
        raise RuntimeError(
            f"rank {r}: canonical exchange covered {covered}/{dn} rows")
    return out


def to_canonical(local: np.ndarray, npad: int,
                 counts: Optional[np.ndarray] = None, fill=0) -> np.ndarray:
    """This rank's canonical slice (npad // nproc rows) of the global padded
    layout [all real rows in ingest order | tail pad]. Single-process: the
    local rows padded to npad — the exact layout a 1-device fit builds, which
    is what makes the pod blocked fold bit-identical to it."""
    a = np.ascontiguousarray(local)
    if not multiprocess():
        pad = npad - a.shape[0]
        if pad:
            a = np.concatenate(
                [a, np.full((pad,) + a.shape[1:], fill, a.dtype)])
        return a
    if counts is None:
        counts = row_counts(a.shape[0])
    out = exchange_rows(a, counts, canonical_counts(counts, npad))
    shard = npad // len(counts)
    pad = shard - out.shape[0]
    if pad:
        out = np.concatenate(
            [out, np.full((pad,) + out.shape[1:], fill, out.dtype)])
    return out


def from_canonical(local_padded: np.ndarray, npad: int,
                   counts: np.ndarray) -> np.ndarray:
    """Inverse of `to_canonical`: this rank's INGEST rows recovered from its
    canonical-layout slice (metric read-back — training margins, OOB sums —
    must pair with the local frame's response rows)."""
    counts = np.asarray(counts, np.int64)
    if not multiprocess():
        return np.ascontiguousarray(local_padded[: int(counts.sum())])
    import jax

    r = jax.process_index()
    canon = canonical_counts(counts, npad)
    return exchange_rows(
        np.ascontiguousarray(local_padded[: int(canon[r])]), canon, counts)


def local_shard(garr) -> np.ndarray:
    """This process's rows of a global row-sharded array, in device order."""
    shards = sorted(garr.addressable_shards, key=lambda s: s.index[0].start)
    return np.concatenate([np.asarray(s.data) for s in shards])


def to_local(a) -> np.ndarray:
    """Host view of `a`: the local shard for process-spanning global arrays,
    plain np.asarray otherwise — the one rule for bringing possibly-sharded
    values to the host in metric/scoring code."""
    if multiprocess() and getattr(a, "is_fully_addressable", True) is False:
        return local_shard(a)
    return np.asarray(a)
