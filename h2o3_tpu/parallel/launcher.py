"""Multi-host launcher — cluster bring-up for TPU pods.

Reference parity: `h2o-hadoop-common`'s `h2odriver` (launches the JVM cloud
on YARN) and the `h2o-k8s` helm/stateful-set launcher, plus `H2O.main`'s
clouding handshake (SURVEY.md §3.1). The TPU equivalent is one Python
process per TPU host joined through the JAX coordination service:
`jax.distributed.initialize` replaces Paxos/flatfile discovery — the
coordinator address is the flatfile, `process_id` the node index, and the
"cloud locks" when every process has connected.

Usage (one command per host, e.g. via `gcloud compute tpus tpu-vm ssh
--worker=all`):

    python -m h2o3_tpu.parallel.launcher \
        --coordinator ${HOST0_IP}:8476 --nprocs 8 --rank ${WORKER_ID} \
        train_script.py [script args...]

or programmatically: `launcher.initialize_multihost(...)` then `h2o.init()`.
On a TPU VM the rank/nprocs/coordinator can usually be omitted — JAX infers
them from the TPU metadata (the auto path below).
"""

from __future__ import annotations

import os
import runpy
import sys
from typing import Optional


def initialize_multihost(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> dict:
    """Join (or form) the multi-host cloud. Returns cloud facts.

    With no arguments, uses JAX's auto-detection (TPU pod metadata) — the
    analog of multicast discovery; with explicit arguments it behaves like
    flatfile clouding.
    """
    import jax

    if coordinator_address or num_processes or process_id is not None:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
    else:
        try:
            jax.distributed.initialize()
        except (ValueError, RuntimeError):
            pass  # single-process (no pod metadata): 1-node cloud
    facts = dict(
        process_index=jax.process_index(),
        process_count=jax.process_count(),
        local_devices=len(jax.local_devices()),
        global_devices=len(jax.devices()),
    )
    # fleet-observability hook (ISSUE 13): a rank that also serves REST
    # announces itself to the aggregator so `GET /3/Metrics?scope=fleet`
    # there covers the whole pod — opt-in via env, and soft-fail: cloud
    # bring-up order must not depend on the aggregator being up yet
    agg = os.environ.get("H2O3_FLEET_AGGREGATOR")
    self_url = os.environ.get("H2O3_FLEET_SELF_URL")
    if agg and self_url:
        from ..runtime import fleet

        if fleet.same_origin(agg, self_url):
            # this rank IS the aggregator (shared pod env points every
            # rank at rank0): it already answers the fleet scrape as
            # `self` — registering its own URL as a peer would merge its
            # registry twice and double-count every fleet total
            facts["fleet_registered"] = "self"
        else:
            facts["fleet_registered"] = fleet.register_with(
                agg, f"rank{facts['process_index']}", self_url)
    # failure-detection hook (ISSUE 20): with a fence deadline configured,
    # every pod rank runs the supervisor watcher — a peer that dies
    # mid-collective is detected by the SURVIVORS (lane_hang_report ages),
    # never by the victim, so detection must be armed on all of them
    from ..runtime import supervisor

    if facts["process_count"] > 1 and supervisor.fence_deadline_s() > 0:
        facts["supervisor_watcher"] = supervisor.start()
    return facts


def main(argv=None):
    import argparse

    ap = argparse.ArgumentParser(
        description="h2o3_tpu multi-host launcher (h2odriver equivalent)")
    ap.add_argument("--coordinator", default=None,
                    help="host:port of process 0 (the 'flatfile' head)")
    ap.add_argument("--nprocs", type=int, default=None)
    ap.add_argument("--rank", type=int, default=None)
    ap.add_argument("script", help="training script to run after clouding")
    ap.add_argument("script_args", nargs="*")
    args = ap.parse_args(argv)

    facts = initialize_multihost(args.coordinator, args.nprocs, args.rank)
    from ..runtime.log import Log

    Log.info(f"cloud up: process {facts['process_index']}/{facts['process_count']}"
             f" with {facts['local_devices']} local device(s)")
    sys.argv = [args.script] + list(args.script_args)
    runpy.run_path(args.script, run_name="__main__")


if __name__ == "__main__":
    main()
