"""Cloud/mesh management — the TPU-native replacement for H2O's clouding layer.

Reference parity: `h2o-core/src/main/java/water/H2O.java` (node bootstrap),
`water/Paxos.java` + `water/HeartBeatThread.java` (cloud membership). In the
reference a "cloud" is a set of JVM peers discovered by gossip; here a cloud
is a `jax.sharding.Mesh` over the devices JAX already knows about —
`jax.distributed.initialize()` plays the role of Paxos (one process per TPU
host ≡ one H2O node), and membership is fixed at init, matching H2O's
"cloud locks at first job" semantics (`water/Paxos.java`).

The data-parallel axis is named ``"hosts"`` everywhere: rows of a Frame are
sharded over it, and every MRTask-style reduction lowers to an XLA collective
(`lax.psum`) over it instead of H2O's binary RPC tree (`water/MRTask.java`).
"""

from __future__ import annotations

import os
import threading
from dataclasses import dataclass, field
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:  # jax >= 0.5 exports shard_map at top level
    from jax import shard_map as _shard_map
except ImportError:  # 0.4.x keeps it experimental
    from jax.experimental.shard_map import shard_map as _shard_map

# the ONE version-compat import — graft/test callers re-use this instead
# of duplicating the try/except (a future jax rename is a one-line fix)
shard_map = _shard_map

ROWS_AXIS = "hosts"  # the one inter-node axis H2O has: row/data parallelism

_lock = threading.Lock()
_cloud: Optional["Cloud"] = None
# the (coordinator_address, num_processes, process_id) jax.distributed was
# initialized with — re-initializing the distributed runtime crashes, so a
# repeat init() with the same topology is answered idempotently and a
# CONFLICTING topology is a loud error instead of a crash mid-bootstrap
_dist_topology: Optional[tuple] = None


@dataclass
class Cloud:
    """A locked set of devices arranged in a 1-D data-parallel mesh.

    Mirrors `water.H2O.CLOUD` (static cloud singleton). `size` ≡
    `H2O.CLOUD.size()`; `self_idx` ≡ `H2O.SELF.index()`.
    """

    mesh: Mesh
    name: str = "h2o-tpu"

    @property
    def size(self) -> int:
        return self.mesh.devices.size

    @property
    def self_idx(self) -> int:
        return jax.process_index()

    @property
    def devices(self):
        return list(self.mesh.devices.flat)

    def row_sharding(self) -> NamedSharding:
        """Sharding for per-row (leading-axis) data — H2O's chunk layout."""
        return NamedSharding(self.mesh, P(ROWS_AXIS))

    def replicated(self) -> NamedSharding:
        """Sharding for model state: replicated on every node (like DKV
        cached values on every H2O node)."""
        return NamedSharding(self.mesh, P())


def init(
    devices: Optional[Sequence[jax.Device]] = None,
    name: str = "h2o-tpu",
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> Cloud:
    """Form the cloud. Single-process: mesh over local devices. Multi-host:
    pass coordinator_address/num_processes/process_id (wraps
    `jax.distributed.initialize`, replacing `water/init/NetworkInit.java`).

    Re-init is IDEMPOTENT for the distributed runtime: a second call with
    the same coordinator topology returns the live cloud instead of
    re-invoking `jax.distributed.initialize` (which crashes); a second call
    with a CONFLICTING topology raises a clear error naming both. Device
    re-selection (the single-process `devices=` form) still rebuilds the
    mesh — that is how tests move between 1- and 8-device clouds.
    """
    global _cloud, _dist_topology
    with _lock:
        if coordinator_address is not None and num_processes and num_processes > 1:
            topo = (coordinator_address, int(num_processes),
                    None if process_id is None else int(process_id))
            if _dist_topology is not None:
                if topo != _dist_topology:
                    raise RuntimeError(
                        "cloud already initialized with coordinator "
                        f"topology {_dist_topology}; re-init with {topo} "
                        "conflicts — shut the process down to re-cloud "
                        "(membership is fixed at init, water/Paxos.java "
                        "'cloud locks' semantics)")
                # same topology: the distributed runtime is already up —
                # answer with the live cloud (or rebuild the mesh below if
                # reset() dropped it)
                if _cloud is not None:
                    return _cloud
            else:
                jax.distributed.initialize(
                    coordinator_address=coordinator_address,
                    num_processes=num_processes,
                    process_id=process_id,
                )
                _dist_topology = topo
        if devices is None:
            devices = jax.devices()
        mesh = Mesh(np.asarray(devices), (ROWS_AXIS,))
        _cloud = Cloud(mesh=mesh, name=name)
        _lane_cache_topology(_cloud)
        return _cloud


def cloud() -> Cloud:
    """The current cloud, forming a local one lazily (like `H2O.main` being
    auto-started by the Python client, `h2o-py/h2o/backend/server.py`)."""
    global _cloud
    if _cloud is None:
        init()
    return _cloud


def reset() -> None:
    global _cloud
    with _lock:
        _cloud = None


def shard_call(fn, cloud: "Cloud", in_specs, out_specs, check_rep=True):
    """t5x-style cpu-fallback-to-jit wrapper (SNIPPETS.md [1], `t5x
    partitioning.pjit`): on a multi-device cloud, wrap `fn` in `shard_map`
    over the 1-D ``hosts`` mesh; on a 1-device cloud return `fn` UNCHANGED
    so the caller's plain `jit` runs the IDENTICAL function body — the
    forced-CPU test lane exercises the same sharded code path (blocked
    histogram reduction included) without a mesh, and a parity pin between
    the two lanes compares one implementation against itself.

    `check_rep=False` is required for bodies whose replicated outputs come
    from an `all_gather` + explicit fold (the deterministic histogram
    merge) rather than a `psum` — shard_map cannot statically infer the
    replication there, but the fold IS replicated by construction."""
    if cloud.size > 1:
        return _shard_map(fn, mesh=cloud.mesh, in_specs=in_specs,
                          out_specs=out_specs, check_rep=check_rep)
    return fn


def collective_fence(x) -> None:
    """Serialize multi-device collective programs on the CPU backend.

    XLA:CPU executes async-dispatched executables CONCURRENTLY on one shared
    thunk pool. Two in-flight collective programs can starve each other: one
    holds pool threads at its all-reduce rendezvous while the other's thunks
    occupy the rest, so the final participant never runs and the runtime
    aborts after its 40 s rendezvous timeout (observed as 7/8 participants
    on the 8-virtual-device test cloud of a 1-core host). Blocking on the
    previous program's output before dispatching the next collective keeps
    at most one collective executable in flight. TPU streams already
    serialize executions, so this is a no-op there.

    The blocked time is booked to the ``collective`` phase bucket
    (runtime/phases): on a CPU mesh it is the wait for collective-program
    completion, so bench records decompose a sharded fit's wall into
    {h2d, compute, collective, ...} instead of hiding the merge cost in
    compute."""
    import time as _time

    import jax

    c = _cloud
    if c is not None and c.size > 1 and jax.default_backend() == "cpu":
        t0 = _time.perf_counter()
        from ..runtime import supervisor as _sup

        deadline = _sup.fence_deadline_s()
        if deadline > 0:
            # deadline'd fence (ISSUE 20): a peer rank dying mid-collective
            # leaves this block waiting on the rendezvous forever — the
            # supervisor aborts it with CollectiveTimeout instead, marks
            # the suspect ranks down, and the caller resumes elsewhere
            _sup.deadline_block(x, deadline, tag="collective_fence")
        else:
            jax.block_until_ready(x)
        try:
            from ..runtime import phases as _phases

            _phases.add("collective", _time.perf_counter() - t0)
        except Exception:
            pass


_training_lock = threading.RLock()


def training_guard():
    """Context manager serializing whole training jobs across threads on
    multi-device CPU meshes and on MULTI-PROCESS clouds of any backend.

    `collective_fence` keeps at most one collective executable in flight
    *within* a training loop, but two REST-spawned jobs (grid + AutoML, or
    two concurrent model builds) interleave dispatches from separate
    threads:

    * on a multi-device XLA:CPU mesh that recreates the thunk-pool
      rendezvous deadlock the fence exists to avoid;
    * on a multi-HOST cloud (TPU pod over ICI/DCN included) collective
      launch order must be identical on every rank. This lock serializes
      jobs WITHIN each process; it cannot order jobs ACROSS ranks — that
      is the SPMD contract: every rank runs the same driver script, so
      jobs are submitted in the same program order everywhere (the
      reference demands the same: every node must see the same job
      submissions). Submitting jobs to different ranks from independent
      sources concurrently is unsupported and would deadlock with or
      without this lock; docs/distributed.md spells this out.

    Single-process single-backend TPU (streams serialize, no cross-rank
    ordering to break) returns a no-op context so concurrent jobs still
    overlap host-side work."""
    import contextlib

    if must_serialize_training():
        return _training_lock
    return contextlib.nullcontext()


def must_serialize_training() -> bool:
    """True when `training_guard()` would hand out the real lock — i.e.
    concurrent training jobs are unsafe on this cloud (multi-device CPU
    thunk-pool rendezvous, or multi-process collective launch order). The
    train-pool scheduler (runtime/trainpool.py) checks this and degrades
    to sequential in-thread execution instead of taking the lock from
    worker threads — an RLock already held by the submitting thread (the
    REST grid handler wraps the whole sweep in training_guard) would
    deadlock its own workers."""
    import jax

    c = _cloud
    return bool(c is not None and c.size > 1 and (
        jax.default_backend() == "cpu" or jax.process_count() > 1))


def pad_to_multiple(n: int, k: int) -> int:
    """Rows are padded so each mesh shard is equal-sized (XLA needs static,
    uniform shards; H2O chunks could be ragged — ours cannot)."""
    return ((n + k - 1) // k) * k


# -- per-lane collective skew profiling + straggler detection (ISSUE 13) ------
#
# A slow lane in a sharded fit (the classic data-parallel-boosting
# straggler) was invisible: `collective_fence` books only the DRIVER'S
# total wait. The instrument here records, per collective fence, WHEN each
# lane arrived at the rendezvous: `lane_mark(x, axis, tag)` inserts an
# `io_callback` into the sharded program (ordered before the all_gather by
# an optimization_barrier data dependency), so each lane stamps a host
# timestamp the moment its local partial is ready. The fence's per-lane
# wait is each lane's arrival lag behind the FIRST arriver — the time the
# collective spent waiting on that lane. All bookkeeping is host-side
# dicts; nothing blocks device work, and the instrument is only attached
# to the per-scoring-interval programs (the event-loss fence), NEVER the
# per-level histogram hot path.
#
# The same callback is the injection point for the `mesh.lane_delay`
# fault (runtime/faults, latency-only): arming it with lane=N sleeps N's
# arrival callback, delaying that lane's rendezvous entry for real — the
# detector below must then flag exactly lane N (pinned in
# tests/test_tree_sharded.py and exercised by dryrun_multichip).
#
# Straggler detection: a lane whose per-fence wait persistently (>=
# H2O3_STRAGGLER_FENCES consecutive fences) exceeds
# max(median_wait * H2O3_STRAGGLER_FACTOR, H2O3_STRAGGLER_MIN_MS) fires
# `h2o3_stragglers_total{lane}`, a Timeline event and a zero-duration
# trace span — once per streak, re-armed when the lane recovers.

import functools as _functools
import time as _time
from collections import deque as _deque

_LANE_LOCK = threading.Lock()
_LANE_SEQ = 0                      # monotone fence counter
_LANE_OPEN: dict = {}              # tag -> {lane: t_arrive}
_LANE_RECORDS: "_deque" = _deque(maxlen=256)
_LANE_LAST: dict = {}              # lane -> wait_ms of the most recent fence
_LANE_STREAK: dict = {}            # lane -> consecutive flagged fences
_LANE_FIRED: dict = {}             # lane -> total straggler firings
_LANE_REG: dict = {}
_F32_ZERO = np.float32(0.0)
# Topology cached at init() so watchdog threads can map a lane to its
# owning RANK without ever touching jax (a hung backend blocks any jax
# call — the round-4 rc:124 failure mode the bench watchdog exists for).
_LANE_PROC: dict = {}              # lane -> owning process index
_LANE_SELF: int = 0                # this process's index
_LANE_EXPECT: int = 0              # lanes whose callbacks run IN this process
_LANE_LAST_TS: float = 0.0         # wall time of the last fence flush


def _lane_cache_topology(c: "Cloud") -> None:
    """Cache {lane: process_index} for the new cloud (called under _lock
    from init). On a pod only the LOCAL lanes' io_callbacks ever run in
    this process, so the fence-flush threshold is the local lane count."""
    global _LANE_SELF, _LANE_EXPECT
    self_idx = int(jax.process_index())
    topo = {i: int(getattr(d, "process_index", 0))
            for i, d in enumerate(c.mesh.devices.flat)}
    with _LANE_LOCK:
        _LANE_PROC.clear()
        _LANE_PROC.update(topo)
        _LANE_SELF = self_idx
        _LANE_EXPECT = sum(1 for pr in topo.values() if pr == self_idx)


def lane_timing_enabled() -> bool:
    """Per-lane timing is on by default for mesh-sharded programs;
    H2O3_LANE_TIMING=0 is the escape hatch. Evaluated at TRACE time — the
    cached sharded programs bake the choice in for their lifetime."""
    return os.environ.get("H2O3_LANE_TIMING", "1").lower() not in (
        "0", "false", "no")


def _lane_registry() -> dict:
    """Memoized central-registry families (the usual memoization stance:
    recording a fence must not take the registry registration lock)."""
    if not _LANE_REG:
        from ..runtime import metrics_registry as _reg

        _LANE_REG["skew"] = _reg.histogram(
            "h2o3_collective_skew_ms",
            "per-fence collective skew (ms): slowest lane's arrival lag "
            "behind the first arriver, per instrumented fence tag",
            labelnames=("tag",))
        _LANE_REG["lane_wait"] = _reg.histogram(
            "h2o3_collective_lane_wait_ms",
            "per-lane collective wait (ms): how long each fence waited on "
            "this lane (arrival lag behind the first arriver)",
            labelnames=("lane",))
        _LANE_REG["fences"] = _reg.counter(
            "h2o3_collective_fences",
            "instrumented collective fences recorded")
        _LANE_REG["stragglers"] = _reg.counter(
            "h2o3_stragglers",
            "straggler detections: fences streaks where one lane's wait "
            "persistently exceeded the median by H2O3_STRAGGLER_FACTOR",
            labelnames=("lane",))
    return _LANE_REG


def _lane_arrive_cb(tag: str, lane) -> np.float32:
    """io_callback target: runs ON the lane's execution thread the moment
    its local partial is ready. Stamps the arrival; flushes the fence
    record when every lane of the cloud has reported (or when a lane
    reports twice — a new fence started before a peer's callback landed)."""
    lane = int(lane)
    from ..runtime import faults as _faults

    try:
        _faults.check("mesh.lane_delay", lane=lane)
    except Exception:
        pass   # latency-only point; an injected error class is a misconfig
    if _faults.active():
        # rank death at fence N (pod chaos lane): a hard exit from inside
        # the arrival callback is exactly a process dying mid-collective —
        # peers are left at the rendezvous, which the supervisor's fence
        # deadline must abort. os._exit: no atexit/finalizers, like a kill.
        try:
            _faults.check("mesh.rank_kill", detail=f"lane{lane}", lane=lane)
        except Exception:
            os._exit(43)
    t = _time.perf_counter()
    actions = None
    with _LANE_LOCK:
        open_ = _LANE_OPEN.setdefault(tag, {})
        if lane in open_:
            actions = _flush_locked(tag)
            _LANE_OPEN[tag] = open_ = {}
        open_[lane] = t
        c = _cloud
        # flush when every lane THIS process will ever hear from has
        # reported: all lanes single-process, the local lanes on a pod
        # (remote lanes' callbacks run on their own ranks — waiting for
        # them here would leave every fence open forever)
        expect = _LANE_EXPECT or (c.size if c is not None else 0)
        if c is not None and len(open_) >= expect:
            acts2 = _flush_locked(tag)
            actions = (actions or []) + acts2 if acts2 else actions
    if actions:
        _run_lane_actions(actions)
    return _F32_ZERO


def _flush_locked(tag: str):
    """Fold one fence's arrivals into a record (+ detector update). Caller
    holds _LANE_LOCK; returns deferred registry/timeline actions so the
    lock never nests into other subsystems' locks."""
    global _LANE_SEQ, _LANE_LAST_TS
    arrivals = _LANE_OPEN.pop(tag, None)
    if not arrivals:
        return None
    _LANE_LAST_TS = _time.time()
    if len(arrivals) < 2:
        _LANE_LAST.clear()
        _LANE_LAST.update({lane: 0.0 for lane in arrivals})
        if not (len(arrivals) == 1 and _LANE_EXPECT == 1
                and len(_LANE_PROC) > 1):
            # incomplete fence (a lane re-reported before its local peers
            # landed): nothing comparable to record
            return None
        # 1-local-lane pod rank: this IS the complete local fence. There is
        # no within-rank skew to measure (peer lanes' callbacks run on
        # their own ranks), but the record itself is the pod observable:
        # the fences counter and skew series on every rank's scrape prove
        # that rank's collectives are moving — the fleet aggregator's
        # per-rank liveness and the watchdog's hang evidence both read
        # them — so record the fence with zero wait.
        lane0 = next(iter(arrivals))
        _LANE_SEQ += 1
        _LANE_RECORDS.append(dict(
            seq=_LANE_SEQ, ts=_time.time(), tag=tag,
            waits_ms={str(lane0): 0.0}, skew_ms=0.0))
        return [("fence", tag, 0.0, {lane0: 0.0})]
    tmin = min(arrivals.values())
    waits = {lane: (t - tmin) * 1e3 for lane, t in arrivals.items()}
    skew = max(waits.values())
    _LANE_SEQ += 1
    rec = dict(seq=_LANE_SEQ, ts=_time.time(), tag=tag,
               waits_ms={str(lv): round(w, 3) for lv, w in sorted(waits.items())},
               skew_ms=round(skew, 3))
    _LANE_RECORDS.append(rec)
    _LANE_LAST.clear()
    _LANE_LAST.update(waits)
    # straggler detection on this fence
    from ..runtime import env_float, env_int

    factor = env_float("H2O3_STRAGGLER_FACTOR", 4.0)
    floor_ms = env_float("H2O3_STRAGGLER_MIN_MS", 25.0)
    persist = env_int("H2O3_STRAGGLER_FENCES", 3)
    srt = sorted(waits.values())
    # LOWER median: the threshold must come from a typical healthy lane.
    # The upper middle would, on a 2-lane mesh, be the straggler's own
    # wait (threshold = 4x itself — the detector could never fire), and
    # on any even mesh where half the lanes are slow it would inflate
    # the threshold by the very skew being detected.
    median = srt[(len(srt) - 1) // 2]
    threshold = max(median * factor, floor_ms)
    actions = [("fence", tag, skew, dict(waits))]
    for lane, w in waits.items():
        if w > threshold:
            _LANE_STREAK[lane] = _LANE_STREAK.get(lane, 0) + 1
            if _LANE_STREAK[lane] == persist:
                _LANE_FIRED[lane] = _LANE_FIRED.get(lane, 0) + 1
                actions.append(("straggler", tag, lane,
                                dict(wait_ms=round(w, 1),
                                     median_ms=round(median, 1),
                                     factor=factor, fences=persist)))
        else:
            _LANE_STREAK[lane] = 0
    return actions


def _run_lane_actions(actions) -> None:
    try:
        reg = _lane_registry()
    except Exception:
        return
    for act in actions:
        if act[0] == "fence":
            _, tag, skew, waits = act
            reg["fences"].inc()
            reg["skew"].observe(skew, tag)
            for lane, w in waits.items():
                reg["lane_wait"].observe(w, str(lane))
        else:
            _, tag, lane, info = act
            reg["stragglers"].inc(1, str(lane))
            try:
                from ..runtime import tracing as _tracing
                from ..runtime.timeline import Timeline

                Timeline.record(
                    "straggler",
                    f"lane {lane} waited {info['wait_ms']}ms at '{tag}' "
                    f"fences (median {info['median_ms']}ms, "
                    f"factor {info['factor']})", lane=lane, **info)
                _tracing.record_span(f"straggler:lane{lane}", 0.0,
                                     kind="collective", lane=lane,
                                     tag=tag, **info)
            except Exception:
                pass


def lane_mark(x, axis_name: str, tag: str):
    """Attach the per-lane arrival stamp to `x` inside a sharded program:
    an io_callback carrying this lane's index, ordered BEFORE the
    downstream collective via an optimization_barrier data dependency
    (pure_callback would be DCE'd — its result is unused by the math).
    Identity on the values; returns `x` barrier-tied to the stamp."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import io_callback

    lane = jax.lax.axis_index(axis_name)
    t = io_callback(_functools.partial(_lane_arrive_cb, tag),
                    jax.ShapeDtypeStruct((), jnp.float32), lane,
                    ordered=False)
    x, _ = jax.lax.optimization_barrier((x, t))
    return x


def lane_seq() -> int:
    with _LANE_LOCK:
        return _LANE_SEQ


def lane_last_waits() -> dict:
    """{lane: wait_ms} naming the suspect lane of a hung collective —
    host-side dicts only (safe from the bench watchdog thread while the
    backend hangs). A fence currently OPEN (some lanes arrived, the
    collective still waiting on the rest) takes priority: its partial
    arrivals are reported, so the lanes MISSING from the dict are exactly
    the ones the fence is hung on. With no open fence, the most recent
    COMPLETED fence's waits."""
    with _LANE_LOCK:
        for open_ in _LANE_OPEN.values():
            if open_:
                tmin = min(open_.values())
                return {int(lv): round((t - tmin) * 1e3, 3)
                        for lv, t in sorted(open_.items())}
        return {int(lv): round(w, 3) for lv, w in _LANE_LAST.items()}


def lane_ranks() -> dict:
    """{lane: owning process index}, cached at init() — host dict only,
    safe from watchdog threads while the backend hangs."""
    with _LANE_LOCK:
        return dict(_LANE_PROC)


def lane_hang_report() -> dict:
    """The bench/MULTICHIP watchdog's hung-collective attribution: which
    lanes arrived at the currently-open fence, which are missing, and the
    RANKS owning the missing lanes (cached topology — never a jax call).

    On a pod each process only hears its own lanes, so the report is
    rank-local evidence: a missing LOCAL lane names this rank (its shard
    never reached the rendezvous); all local lanes arrived at the last
    fence but the program is hung → the suspects are the REMOTE ranks.
    Empty dict when no mesh topology was cached (no sharded fit ran)."""
    with _LANE_LOCK:
        topo = dict(_LANE_PROC)
        if not topo:
            return {}
        self_rank = _LANE_SELF
        local = sorted(lv for lv, pr in topo.items() if pr == self_rank)
        remote_ranks = sorted({pr for pr in topo.values() if pr != self_rank})
        out = dict(self_rank=self_rank, local_lanes=local,
                   n_ranks=len(set(topo.values())))
        if _LANE_LAST_TS:
            out["last_fence_age_s"] = round(_time.time() - _LANE_LAST_TS, 1)
        for tag, open_ in _LANE_OPEN.items():
            if open_:
                tmin = min(open_.values())
                missing = [lv for lv in local if lv not in open_]
                out.update(
                    open_fence=tag,
                    arrived={int(lv): round((t - tmin) * 1e3, 3)
                             for lv, t in sorted(open_.items())},
                    missing_local_lanes=missing,
                    suspect_ranks=([self_rank] if missing else remote_ranks))
                return out
        # no open fence: every local lane made its last rendezvous — if the
        # run is hung on a collective, the lanes never heard from are remote
        out.update(suspect_ranks=remote_ranks if remote_ranks else [])
        return out


def lane_records(since_seq: int = 0) -> list:
    with _LANE_LOCK:
        return [dict(r) for r in _LANE_RECORDS if r["seq"] > since_seq]


def lane_summary(since_seq: int = 0) -> dict:
    """Fold the fences recorded after `since_seq` into one summary (the
    per-fit skew embed: record_fit_plan tree fold, bench records, fit
    trace events): fence count, skew p50/max, and the worst lane."""
    recs = lane_records(since_seq)
    if not recs:
        return dict(fences=0)
    skews = sorted(r["skew_ms"] for r in recs)
    per_lane: dict = {}
    for r in recs:
        for lv, w in r["waits_ms"].items():
            per_lane.setdefault(lv, []).append(w)
    worst = max(per_lane, key=lambda lv: max(per_lane[lv]))
    return dict(
        fences=len(recs),
        skew_p50_ms=round(skews[len(skews) // 2], 3),
        skew_max_ms=round(skews[-1], 3),
        worst_lane=int(worst),
        per_lane_max_ms={lv: round(max(ws), 3)
                         for lv, ws in sorted(per_lane.items())},
    )


def lane_stats() -> dict:
    """The full lane-timing snapshot (the /3/Profiler `tree`-adjacent
    fold + dryrun assertions): enabled flag, totals, last fence, per-lane
    straggler streaks and firing counts, recent records tail."""
    with _LANE_LOCK:
        return dict(
            enabled=lane_timing_enabled(),
            fences=_LANE_SEQ,
            last={str(lv): round(w, 3) for lv, w in _LANE_LAST.items()},
            streaks={str(lv): n for lv, n in _LANE_STREAK.items() if n},
            stragglers={str(lv): n for lv, n in _LANE_FIRED.items()},
            records=[dict(r) for r in list(_LANE_RECORDS)[-8:]],
        )


def lane_reset() -> None:
    """Drop lane-timing state (tests). Registry families are monotone and
    stay — only the host-side rings/streaks reset."""
    global _LANE_SEQ, _LANE_LAST_TS
    with _LANE_LOCK:
        _LANE_SEQ = 0
        _LANE_LAST_TS = 0.0
        _LANE_OPEN.clear()
        _LANE_RECORDS.clear()
        _LANE_LAST.clear()
        _LANE_STREAK.clear()
        _LANE_FIRED.clear()
