"""Cloud/mesh management — the TPU-native replacement for H2O's clouding layer.

Reference parity: `h2o-core/src/main/java/water/H2O.java` (node bootstrap),
`water/Paxos.java` + `water/HeartBeatThread.java` (cloud membership). In the
reference a "cloud" is a set of JVM peers discovered by gossip; here a cloud
is a `jax.sharding.Mesh` over the devices JAX already knows about —
`jax.distributed.initialize()` plays the role of Paxos (one process per TPU
host ≡ one H2O node), and membership is fixed at init, matching H2O's
"cloud locks at first job" semantics (`water/Paxos.java`).

The data-parallel axis is named ``"hosts"`` everywhere: rows of a Frame are
sharded over it, and every MRTask-style reduction lowers to an XLA collective
(`lax.psum`) over it instead of H2O's binary RPC tree (`water/MRTask.java`).
"""

from __future__ import annotations

import os
import threading
from dataclasses import dataclass, field
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:  # jax >= 0.5 exports shard_map at top level
    from jax import shard_map as _shard_map
except ImportError:  # 0.4.x keeps it experimental
    from jax.experimental.shard_map import shard_map as _shard_map

# the ONE version-compat import — graft/test callers re-use this instead
# of duplicating the try/except (a future jax rename is a one-line fix)
shard_map = _shard_map

ROWS_AXIS = "hosts"  # the one inter-node axis H2O has: row/data parallelism

_lock = threading.Lock()
_cloud: Optional["Cloud"] = None
# the (coordinator_address, num_processes, process_id) jax.distributed was
# initialized with — re-initializing the distributed runtime crashes, so a
# repeat init() with the same topology is answered idempotently and a
# CONFLICTING topology is a loud error instead of a crash mid-bootstrap
_dist_topology: Optional[tuple] = None


@dataclass
class Cloud:
    """A locked set of devices arranged in a 1-D data-parallel mesh.

    Mirrors `water.H2O.CLOUD` (static cloud singleton). `size` ≡
    `H2O.CLOUD.size()`; `self_idx` ≡ `H2O.SELF.index()`.
    """

    mesh: Mesh
    name: str = "h2o-tpu"

    @property
    def size(self) -> int:
        return self.mesh.devices.size

    @property
    def self_idx(self) -> int:
        return jax.process_index()

    @property
    def devices(self):
        return list(self.mesh.devices.flat)

    def row_sharding(self) -> NamedSharding:
        """Sharding for per-row (leading-axis) data — H2O's chunk layout."""
        return NamedSharding(self.mesh, P(ROWS_AXIS))

    def replicated(self) -> NamedSharding:
        """Sharding for model state: replicated on every node (like DKV
        cached values on every H2O node)."""
        return NamedSharding(self.mesh, P())


def init(
    devices: Optional[Sequence[jax.Device]] = None,
    name: str = "h2o-tpu",
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> Cloud:
    """Form the cloud. Single-process: mesh over local devices. Multi-host:
    pass coordinator_address/num_processes/process_id (wraps
    `jax.distributed.initialize`, replacing `water/init/NetworkInit.java`).

    Re-init is IDEMPOTENT for the distributed runtime: a second call with
    the same coordinator topology returns the live cloud instead of
    re-invoking `jax.distributed.initialize` (which crashes); a second call
    with a CONFLICTING topology raises a clear error naming both. Device
    re-selection (the single-process `devices=` form) still rebuilds the
    mesh — that is how tests move between 1- and 8-device clouds.
    """
    global _cloud, _dist_topology
    with _lock:
        if coordinator_address is not None and num_processes and num_processes > 1:
            topo = (coordinator_address, int(num_processes),
                    None if process_id is None else int(process_id))
            if _dist_topology is not None:
                if topo != _dist_topology:
                    raise RuntimeError(
                        "cloud already initialized with coordinator "
                        f"topology {_dist_topology}; re-init with {topo} "
                        "conflicts — shut the process down to re-cloud "
                        "(membership is fixed at init, water/Paxos.java "
                        "'cloud locks' semantics)")
                # same topology: the distributed runtime is already up —
                # answer with the live cloud (or rebuild the mesh below if
                # reset() dropped it)
                if _cloud is not None:
                    return _cloud
            else:
                jax.distributed.initialize(
                    coordinator_address=coordinator_address,
                    num_processes=num_processes,
                    process_id=process_id,
                )
                _dist_topology = topo
        if devices is None:
            devices = jax.devices()
        mesh = Mesh(np.asarray(devices), (ROWS_AXIS,))
        _cloud = Cloud(mesh=mesh, name=name)
        return _cloud


def cloud() -> Cloud:
    """The current cloud, forming a local one lazily (like `H2O.main` being
    auto-started by the Python client, `h2o-py/h2o/backend/server.py`)."""
    global _cloud
    if _cloud is None:
        init()
    return _cloud


def reset() -> None:
    global _cloud
    with _lock:
        _cloud = None


def shard_call(fn, cloud: "Cloud", in_specs, out_specs, check_rep=True):
    """t5x-style cpu-fallback-to-jit wrapper (SNIPPETS.md [1], `t5x
    partitioning.pjit`): on a multi-device cloud, wrap `fn` in `shard_map`
    over the 1-D ``hosts`` mesh; on a 1-device cloud return `fn` UNCHANGED
    so the caller's plain `jit` runs the IDENTICAL function body — the
    forced-CPU test lane exercises the same sharded code path (blocked
    histogram reduction included) without a mesh, and a parity pin between
    the two lanes compares one implementation against itself.

    `check_rep=False` is required for bodies whose replicated outputs come
    from an `all_gather` + explicit fold (the deterministic histogram
    merge) rather than a `psum` — shard_map cannot statically infer the
    replication there, but the fold IS replicated by construction."""
    if cloud.size > 1:
        return _shard_map(fn, mesh=cloud.mesh, in_specs=in_specs,
                          out_specs=out_specs, check_rep=check_rep)
    return fn


def collective_fence(x) -> None:
    """Serialize multi-device collective programs on the CPU backend.

    XLA:CPU executes async-dispatched executables CONCURRENTLY on one shared
    thunk pool. Two in-flight collective programs can starve each other: one
    holds pool threads at its all-reduce rendezvous while the other's thunks
    occupy the rest, so the final participant never runs and the runtime
    aborts after its 40 s rendezvous timeout (observed as 7/8 participants
    on the 8-virtual-device test cloud of a 1-core host). Blocking on the
    previous program's output before dispatching the next collective keeps
    at most one collective executable in flight. TPU streams already
    serialize executions, so this is a no-op there.

    The blocked time is booked to the ``collective`` phase bucket
    (runtime/phases): on a CPU mesh it is the wait for collective-program
    completion, so bench records decompose a sharded fit's wall into
    {h2d, compute, collective, ...} instead of hiding the merge cost in
    compute."""
    import time as _time

    import jax

    c = _cloud
    if c is not None and c.size > 1 and jax.default_backend() == "cpu":
        t0 = _time.perf_counter()
        jax.block_until_ready(x)
        try:
            from ..runtime import phases as _phases

            _phases.add("collective", _time.perf_counter() - t0)
        except Exception:
            pass


_training_lock = threading.RLock()


def training_guard():
    """Context manager serializing whole training jobs across threads on
    multi-device CPU meshes and on MULTI-PROCESS clouds of any backend.

    `collective_fence` keeps at most one collective executable in flight
    *within* a training loop, but two REST-spawned jobs (grid + AutoML, or
    two concurrent model builds) interleave dispatches from separate
    threads:

    * on a multi-device XLA:CPU mesh that recreates the thunk-pool
      rendezvous deadlock the fence exists to avoid;
    * on a multi-HOST cloud (TPU pod over ICI/DCN included) collective
      launch order must be identical on every rank. This lock serializes
      jobs WITHIN each process; it cannot order jobs ACROSS ranks — that
      is the SPMD contract: every rank runs the same driver script, so
      jobs are submitted in the same program order everywhere (the
      reference demands the same: every node must see the same job
      submissions). Submitting jobs to different ranks from independent
      sources concurrently is unsupported and would deadlock with or
      without this lock; docs/distributed.md spells this out.

    Single-process single-backend TPU (streams serialize, no cross-rank
    ordering to break) returns a no-op context so concurrent jobs still
    overlap host-side work."""
    import contextlib

    if must_serialize_training():
        return _training_lock
    return contextlib.nullcontext()


def must_serialize_training() -> bool:
    """True when `training_guard()` would hand out the real lock — i.e.
    concurrent training jobs are unsafe on this cloud (multi-device CPU
    thunk-pool rendezvous, or multi-process collective launch order). The
    train-pool scheduler (runtime/trainpool.py) checks this and degrades
    to sequential in-thread execution instead of taking the lock from
    worker threads — an RLock already held by the submitting thread (the
    REST grid handler wraps the whole sweep in training_guard) would
    deadlock its own workers."""
    import jax

    c = _cloud
    return bool(c is not None and c.size > 1 and (
        jax.default_backend() == "cpu" or jax.process_count() > 1))


def pad_to_multiple(n: int, k: int) -> int:
    """Rows are padded so each mesh shard is equal-sized (XLA needs static,
    uniform shards; H2O chunks could be ragged — ours cannot)."""
    return ((n + k - 1) // k) * k
