"""h2o3_tpu — a TPU-native ML platform with H2O-3's capabilities.

The public surface mirrors `h2o-py/h2o/h2o.py` (`h2o.init`, `h2o.import_file`,
`h2o.H2OFrame`, …) so reference users can switch imports; the engine under it
is JAX/XLA/Pallas on TPU meshes instead of a JVM cloud — see SURVEY.md for
the layer-by-layer mapping.
"""

from __future__ import annotations

import os as _os
from typing import Optional, Sequence

import numpy as np

# Persistent XLA compilation cache: tree programs are large and (on remote
# axon TPU) each compile pays a tunnel round-trip — cache across processes.
if not _os.environ.get("JAX_COMPILATION_CACHE_DIR"):
    try:
        import jax as _jax

        _cache = _os.path.expanduser("~/.cache/h2o3_tpu/jax_cache")
        _os.makedirs(_cache, exist_ok=True)
        _jax.config.update("jax_compilation_cache_dir", _cache)
        _jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    except Exception:
        pass

from .frame.frame import Frame
from .frame.frame import Frame as H2OFrame
from .frame.parse import import_file as _import_file
from .frame.text import grep, tf_idf, tokenize  # noqa: F401  (h2o.tf_idf surface)
from .parallel import mesh as _mesh

__version__ = "0.1.0"

from .runtime.dkv import DKV as _DKV  # the keyed store (water/DKV.java)
from .runtime.log import Log as _Log


def init(url=None, ip=None, port=None, nthreads=-1, max_mem_size=None,
         strict_version_check=False, **kw):
    """`h2o.init()` — form the local cloud (mesh over visible devices)."""
    return _mesh.init()


def cluster():
    c = _mesh.cloud()

    class _ClusterInfo:
        cloud_size = c.size
        version = __version__

        def show_status(self):
            print(f"h2o3_tpu cloud: {c.size} device(s): {c.devices}")

    return _ClusterInfo()


def connect(**kw):
    return init()


def shutdown(prompt=False):
    _mesh.reset()
    _DKV.clear()


def import_file(path: str, destination_frame=None, header=0, sep=None,
                col_names=None, col_types=None, **kw) -> Frame:
    fr = _import_file(
        path,
        sep=sep,
        header=None if header == 0 else bool(header > 0),
        col_names=col_names,
        col_types=col_types,
    )
    if destination_frame:
        fr.key = destination_frame
    _DKV.put(fr.key, fr)
    _Log.info(f"imported {path} -> {fr.key} ({fr.nrow}x{fr.ncol})")
    return fr


upload_file = import_file


def H2OFrame_from_python(data, column_types=None) -> Frame:
    if isinstance(data, dict):
        return Frame.from_dict(data, column_types=column_types)
    return Frame.from_numpy(np.asarray(data), column_types=column_types)


def get_frame(key: str) -> Frame:
    fr = _DKV.get(key)
    if not isinstance(fr, Frame):
        raise KeyError(key)
    return fr


def remove(obj) -> None:
    if isinstance(obj, str):
        key = obj
    else:  # frames carry .key; models are keyed by model_id
        key = getattr(obj, "key", None) or getattr(obj, "model_id", None)
    _DKV.remove(key)


def ls():
    return _DKV.keys()


def merge(x: Frame, y: Frame, all_x: bool = False, all_y: bool = False,
          by_x=None, by_y=None, method="auto") -> Frame:
    """`h2o.merge` — AstMerge radix join (see frame/rapids.py). by_x/by_y
    pair key columns with different names (right keys renamed pre-join)."""
    from .frame.rapids import merge as _m

    if by_y is not None:
        if by_x is None or len(by_x) != len(by_y):
            raise ValueError("merge: by_x and by_y must be same-length lists")
        renames = dict(zip(by_y, by_x))
        clash = [t for t in renames.values()
                 if t in y.names and t not in renames]
        if clash:
            raise ValueError(
                f"merge: renaming by_y→by_x would overwrite right-frame column(s) {clash}"
            )
        y = Frame({renames.get(n, n): v for n, v in zip(y.names, y.vecs())})
    return _m(x, y, by=by_x, all_x=all_x, all_y=all_y)


def no_progress():
    pass


def show_progress():
    pass


# model save/load (h2o.save_model / h2o.load_model → /3/Models.bin)
def save_model(model, path: str = ".", force: bool = False, filename=None) -> str:
    from .mojo import save_model as _save

    return _save(model, path, filename=filename)


def load_model(path: str):
    from .mojo import load_model as _load

    return _load(path)


def download_mojo(model, path: str = ".", **kw) -> str:
    return save_model(model, path)


def import_mojo(path: str):
    return load_model(path)


def load_grid(grid_file_path: str, grid_id: Optional[str] = None):
    """`h2o.load_grid` — re-import a checkpointed grid from its
    recovery_dir (hex/grid recovery)."""
    import glob as _glob

    from .models.grid import H2OGridSearch

    if grid_id is None:
        hits = sorted(_glob.glob(_os.path.join(grid_file_path, "*.grid.json")))
        if not hits:
            raise FileNotFoundError(f"no grid state under {grid_file_path}")
        if len(hits) > 1:
            ids = [_os.path.basename(h)[: -len(".grid.json")] for h in hits]
            raise ValueError(
                f"multiple grids under {grid_file_path}: {ids}; pass grid_id"
            )
        grid_id = _os.path.basename(hits[0])[: -len(".grid.json")]
    return H2OGridSearch.load(grid_file_path, grid_id)
