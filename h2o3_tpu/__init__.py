"""h2o3_tpu — a TPU-native ML platform with H2O-3's capabilities.

The public surface mirrors `h2o-py/h2o/h2o.py` (`h2o.init`, `h2o.import_file`,
`h2o.H2OFrame`, …) so reference users can switch imports; the engine under it
is JAX/XLA/Pallas on TPU meshes instead of a JVM cloud — see SURVEY.md for
the layer-by-layer mapping.
"""

from __future__ import annotations

import os as _os
from typing import Optional, Sequence

import numpy as np

# Persistent XLA compilation cache: tree programs are large and (on remote
# axon TPU) each compile pays a tunnel round-trip — cache across processes.
if not _os.environ.get("JAX_COMPILATION_CACHE_DIR"):
    try:
        import jax as _jax

        _cache = _os.path.expanduser("~/.cache/h2o3_tpu/jax_cache")
        _os.makedirs(_cache, exist_ok=True)
        _jax.config.update("jax_compilation_cache_dir", _cache)
        _jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.1)
    except Exception:
        pass

from .frame.frame import Frame
from .frame.frame import Frame as H2OFrame
from .frame.parse import import_file as _import_file
from .frame.text import grep, tf_idf, tokenize  # noqa: F401  (h2o.tf_idf surface)
from . import tree_api as tree  # noqa: F401  (h2o.tree.H2OTree surface)
from .parallel import mesh as _mesh

__version__ = "0.1.0"

from .runtime.dkv import DKV as _DKV  # the keyed store (water/DKV.java)
from .runtime.log import Log as _Log
from . import client  # remote-attach REST client (h2o-py H2OConnection)


def _conn_kwargs(kw):
    """Shared connect-kwarg normalization (h2o-py spells the TLS opt-out
    `verify_ssl_certificates`)."""
    return dict(token=kw.get("token"), verbose=kw.get("verbose", True),
                verify_ssl=kw.get("verify_ssl",
                                  kw.get("verify_ssl_certificates", True)))


def init(url=None, ip=None, port=None, nthreads=-1, max_mem_size=None,
         strict_version_check=False, **kw):
    """`h2o.init()` — form the local cloud (mesh over visible devices), or,
    with `url=`/`ip=`/`port=`, attach to a RUNNING server as a thin REST
    client (h2o-py/h2o/h2o.py `init` → `H2OConnection.open`). An explicit
    endpoint that is unreachable raises — no silent local fallback."""
    if url is not None or ip is not None or port is not None:
        return client.connect(url=url, ip=ip, port=port, **_conn_kwargs(kw))
    return _mesh.init()


def connect(url=None, ip=None, port=None, **kw):
    """`h2o.connect(url=)` — attach to a running server by URL; with no
    endpoint, form the local in-process cloud (h2o-py parity)."""
    if url is not None or ip is not None or port is not None:
        return client.connect(url=url, ip=ip, port=port, **_conn_kwargs(kw))
    return init()


def connection():
    """The active remote connection, or None when in-process."""
    return client.current_connection()


def cluster():
    c = _mesh.cloud()

    class _ClusterInfo:
        cloud_size = c.size
        version = __version__

        def show_status(self):
            print(f"h2o3_tpu cloud: {c.size} device(s): {c.devices}")

    return _ClusterInfo()


def shutdown(prompt=False):
    if client.current_connection() is not None:
        client.disconnect()
        return
    _mesh.reset()
    _DKV.clear()


def import_file(path: str, destination_frame=None, header=0, sep=None,
                col_names=None, col_types=None, pattern=None, **kw):
    conn = client.current_connection()
    if conn is not None:
        return conn.import_file(path, destination_frame=destination_frame,
                                sep=sep, col_names=col_names,
                                col_types=col_types, pattern=pattern)
    fr = _import_file(
        path,
        sep=sep,
        header=None if header == 0 else bool(header > 0),
        col_names=col_names,
        col_types=col_types,
        pattern=pattern,
    )
    if destination_frame:
        fr.key = destination_frame
    _DKV.put(fr.key, fr)
    _Log.info(f"imported {path} -> {fr.key} ({fr.nrow}x{fr.ncol})")
    return fr


def upload_file(path: str, destination_frame=None, sep=None, col_names=None,
                col_types=None, **kw):
    conn = client.current_connection()
    if conn is not None:
        # client-side bytes travel to the server (PostFile + Parse)
        return conn.upload_file(path, destination_frame=destination_frame,
                                sep=sep, col_names=col_names,
                                col_types=col_types)
    return import_file(path, destination_frame=destination_frame, sep=sep,
                       col_names=col_names, col_types=col_types, **kw)


def H2OFrame_from_python(data, column_types=None, column_names=None):
    conn = client.current_connection()
    if conn is None:
        return Frame(data, column_names=column_names,
                     column_types=column_types)
    # connected: python data belongs ON the server (h2o-py H2OFrame(obj)
    # uploads to the cluster). Serialize through the local Frame builder
    # (type inference, NA handling), ship CSV bytes, parse with the
    # inferred/requested types; the local temporary never enters the DKV.
    from .frame.frame import frame_to_csv

    fr = Frame(data, column_names=column_names, column_types=column_types)
    _DKV.remove(fr.key)
    types = [fr.vec(n).type for n in fr.names]
    return conn.upload_bytes(frame_to_csv(fr).encode(), "pyframe.csv",
                             col_names=list(fr.names), col_types=types)


def get_frame(key: str):
    conn = client.current_connection()
    if conn is not None:
        return conn.get_frame(key)
    fr = _DKV.get(key)
    if not isinstance(fr, Frame):
        raise KeyError(key)
    return fr


def remove(obj) -> None:
    if isinstance(obj, str):
        key = obj
    else:  # frames carry .key; models are keyed by model_id
        key = getattr(obj, "key", None) or getattr(obj, "model_id", None)
    _DKV.remove(key)


def ls():
    return _DKV.keys()


def merge(x: Frame, y: Frame, all_x: bool = False, all_y: bool = False,
          by_x=None, by_y=None, method="auto") -> Frame:
    """`h2o.merge` — AstMerge radix join (see frame/rapids.py). by_x/by_y
    pair key columns with different names (right keys renamed pre-join)."""
    from .frame.rapids import merge as _m

    if by_y is not None:
        if by_x is None or len(by_x) != len(by_y):
            raise ValueError("merge: by_x and by_y must be same-length lists")
        renames = dict(zip(by_y, by_x))
        clash = [t for t in renames.values()
                 if t in y.names and t not in renames]
        if clash:
            raise ValueError(
                f"merge: renaming by_y→by_x would overwrite right-frame column(s) {clash}"
            )
        y = Frame({renames.get(n, n): v for n, v in zip(y.names, y.vecs())})
    return _m(x, y, by=by_x, all_x=all_x, all_y=all_y)


def assign(data: Frame, xid: str) -> Frame:
    """`h2o.assign` — rebind a frame to a new DKV key (water/rapids assign)."""
    if xid == data.key:
        raise ValueError("new key must differ from the current key")
    _DKV.remove(data.key)
    data.key = xid
    _DKV.put(xid, data)
    return data


def export_file(frame: Frame, path: str, force: bool = False, sep: str = ",",
                header: bool = True, quote_header: bool = False,
                format: Optional[str] = None) -> str:
    """`h2o.export_file` — write a Frame as CSV, or Parquet when
    format="parquet" (or, with no explicit format, the path ends in
    .parquet/.pq). An explicit format always wins over the extension.
    (water/api frames export; the reference's export_file parquet
    support.)"""
    import csv as _csv

    if _os.path.exists(path) and not force:
        raise FileExistsError(f"{path} exists; pass force=True")
    cols = frame.as_data_frame(use_pandas=False)
    if format == "parquet" or (format is None
                               and path.endswith((".parquet", ".pq"))):
        import pyarrow as pa
        import pyarrow.parquet as pq

        pq.write_table(
            pa.table({n: pa.array(cols[n]) for n in frame.names}), path)
        return path
    names = frame.names
    with open(path, "w", newline="") as f:
        wr = _csv.writer(f, delimiter=sep, quoting=_csv.QUOTE_MINIMAL)
        if header:
            if quote_header:  # reference quotes ONLY the header names
                _csv.writer(f, delimiter=sep,
                            quoting=_csv.QUOTE_ALL).writerow(names)
            else:
                wr.writerow(names)
        mats = [cols[n] for n in names]
        for i in range(frame.nrow):
            wr.writerow([
                "" if v is None or (isinstance(v, float) and np.isnan(v)) else v
                for v in (m[i] for m in mats)
            ])
    return path


def get_model(model_id: str):
    """`h2o.get_model` — fetch a trained model from the DKV by id (or from
    the attached server when connected remotely)."""
    conn = client.current_connection()
    if conn is not None:
        m = client.RemoteModel(conn, model_id)
        m._json()          # 404 now, not on first use
        return m
    m = _DKV.get(model_id)
    if m is None:
        raise KeyError(model_id)
    return m


def frames():
    return _DKV.keys(Frame)


def as_list(data, use_pandas: bool = False, header: bool = True):
    """`h2o.as_list` — frame contents as a pandas DataFrame or a list of
    row lists (header row first when header=True)."""
    if use_pandas:
        return data.as_data_frame(use_pandas=True)
    cols = data.as_data_frame(use_pandas=False)
    names = list(data.names)
    rows = [list(r) for r in zip(*(cols[n] for n in names))]
    return [names] + rows if header else rows


def cluster_status() -> None:
    """`h2o.cluster_status` — print cloud health (h2o-py cluster_status;
    reads the SERVER's /3/Cloud when connected)."""
    conn = client.current_connection()
    if conn is not None:
        info = conn.cluster_info()
        print(f"cloud {info.get('cloud_name')!r} v{info.get('version')}: "
              f"{info.get('cloud_size')} node(s), healthy="
              f"{info.get('cloud_healthy', True)}")
        return
    cluster().show_status()


def network_test():
    """`h2o.network_test` — transport microbenchmark (NetworkTestHandler;
    here the data plane is the host↔device link). Returns the per-size
    results table; runs SERVER-side when connected."""
    conn = client.current_connection()
    if conn is not None:
        return conn.get("/3/NetworkTest")["results"]
    from .runtime.nettest import run_network_test

    return run_network_test()


def log_and_echo(message: str = "") -> None:
    """`h2o.log_and_echo` — drop a marker line into the cluster log
    (LogAndEchoHandler)."""
    conn = client.current_connection()
    if conn is not None:
        conn.post("/3/LogAndEcho", message=message)
        return
    _Log.info(f"[LogAndEcho] {message}")


def download_all_logs(dirname: str = ".", filename: Optional[str] = None) -> str:
    """`h2o.download_all_logs` — write the cluster log ring as a zip
    (LogsHandler download; the remote form pulls the SERVER's log)."""
    import io as _io
    import zipfile as _zip

    conn = client.current_connection()
    if conn is not None:
        text = "\n".join(str(ln) for ln in conn.get("/3/Logs")["logs"])
    else:
        text = "\n".join(str(ln) for ln in _Log.get_logs())
    out = _os.path.join(dirname, filename or "h2o3_tpu_logs.zip")
    _os.makedirs(_os.path.dirname(out) or ".", exist_ok=True)
    buf = _io.BytesIO()
    with _zip.ZipFile(buf, "w", _zip.ZIP_DEFLATED) as z:
        z.writestr("h2o3_tpu.log", text)
    with open(out, "wb") as f:
        f.write(buf.getvalue())
    return out


def list_timezones() -> Frame:
    """`h2o.list_timezones` — one string column of zone names."""
    import zoneinfo

    names = sorted(zoneinfo.available_timezones())
    return Frame({"Timezones": np.asarray(names, dtype=object)},
                 column_types={"Timezones": "string"})


def estimate_cluster_mem(ncols: int, nrows: int, num_cols: int = 0,
                         string_cols: int = 0, cat_cols: int = 0,
                         time_cols: int = 0, uuid_cols: int = 0) -> float:
    """`h2o.estimate_cluster_mem` — recommended cluster memory (GB) for a
    dataset, the reference's rule of thumb: ~4× the in-memory data size,
    with per-type byte widths (numeric 8 B, categorical 2 B, time 8 B,
    UUID 16 B, string ~128 B). Unclassified columns count as numeric."""
    if ncols <= 0 or nrows <= 0:
        raise ValueError("ncols and nrows must be positive")
    typed = num_cols + string_cols + cat_cols + time_cols + uuid_cols
    if typed > ncols:
        raise ValueError("column type counts exceed ncols")
    plain = ncols - typed
    row_bytes = ((num_cols + plain) * 8 + string_cols * 128 + cat_cols * 2
                 + time_cols * 8 + uuid_cols * 16)
    gb = nrows * row_bytes / 1e9
    return round(4 * gb, 3)


def remove_all(retained=None) -> None:
    """`h2o.remove_all()` — clear the DKV, optionally keeping some keys
    (water/api RemoveAllHandler `retained_keys`). Connected remotely this
    clears the SERVER's DKV (`DELETE /3/DKV`), passing the retained keys
    through."""
    conn = client.current_connection()
    if conn is not None:
        names = [getattr(o, "key", None) or getattr(o, "model_id", None)
                 or str(o) for o in (retained or [])]
        if names:
            import json as _json

            conn.request("DELETE", "/3/DKV",
                         params={"retained_keys": _json.dumps(names)})
        else:
            conn.delete("/3/DKV")
        return
    keep = {getattr(o, "key", None) or getattr(o, "model_id", None) or o
            for o in (retained or [])}
    if not keep:
        _DKV.clear()
        return
    for k in list(_DKV.keys()):
        if k not in keep:
            _DKV.remove(k)


def insert_missing_values(frame: Frame, fraction: float = 0.1,
                          seed=None) -> Frame:
    """`h2o.insert_missing_values` — set a random fraction of each
    column's cells to NA IN PLACE (hex/CreateFrame MissingInserter). For a
    remote frame this runs server-side via `POST /3/MissingInserter`."""
    from .frame.vec import Vec

    if getattr(frame, "_is_remote", False):
        frame.conn.post("/3/MissingInserter", dataset=frame.key,
                        fraction=fraction, seed=seed)
        frame._cached = None
        return frame
    rng = np.random.default_rng(seed)
    for n in frame.names:
        v = frame.vec(n)
        mask = rng.random(frame.nrow) < fraction
        if v.type in ("real", "int", "time"):
            a = v.numeric_np().copy()
            a[mask] = np.nan
            frame[n] = Vec(a, v.type)       # keep the column's type label
        elif v.type == "enum":
            codes = np.asarray(v.data).copy()
            codes[mask] = -1
            frame[n] = Vec(codes, "enum", domain=v.domain)
        elif v.type == "string":
            strs = np.asarray(v.to_numpy(), dtype=object).copy()
            strs[mask] = None
            frame[n] = Vec(None, "string", strings=strs)
    return frame


def get_timezone() -> str:
    """`h2o.get_timezone` — the cluster datetime parsing zone (read from
    the SERVER when attached remotely)."""
    conn = client.current_connection()
    if conn is not None:
        return str(conn.rapids("(getTimeZone)").get("string"))
    from .frame.rapids_expr import _TIME_ZONE

    return _TIME_ZONE[0]


def set_timezone(tz: str) -> None:
    """`h2o.set_timezone` — honored by Rapids moment/asDate; applied on
    the SERVER when attached remotely (its Rapids session does the date
    parsing)."""
    import zoneinfo

    zoneinfo.ZoneInfo(tz)  # validate now, not at first use
    conn = client.current_connection()
    if conn is not None:
        conn.rapids(f'(setTimeZone "{tz}")')
        return
    from .frame.rapids_expr import _TIME_ZONE

    _TIME_ZONE[0] = tz


def download_csv(data, filename: str) -> str:
    """`h2o.download_csv` — write a frame as CSV client-side. Remote
    frames duck-type `frame_to_csv`'s surface (names/nrow/as_data_frame),
    so local and remote share the ONE serializer (and its guards)."""
    from .frame.frame import frame_to_csv

    with open(filename, "w") as f:
        f.write(frame_to_csv(data))
    return filename


def deep_copy(frame: Frame, dest: str) -> Frame:
    """`h2o.deep_copy` — independent copy of a frame's columns."""
    from .frame.vec import Vec

    out = {}
    for n, v in zip(frame.names, frame.vecs()):
        if v.type == "string":
            out[n] = Vec(None, "string", strings=np.asarray(v.to_numpy()).copy())
        else:
            out[n] = Vec(np.asarray(v.data).copy(), v.type, domain=v.domain)
    fr = Frame(out, key=dest)
    _DKV.put(dest, fr)
    return fr


def create_frame(rows: int = 10000, cols: int = 10, randomize: bool = True,
                 real_fraction: Optional[float] = None,
                 categorical_fraction: Optional[float] = None,
                 integer_fraction: Optional[float] = None,
                 binary_fraction: Optional[float] = None,
                 factors: int = 5, real_range: float = 100.0,
                 integer_range: int = 100, missing_fraction: float = 0.0,
                 has_response: bool = False, response_factors: int = 2,
                 seed: Optional[int] = None, frame_id: Optional[str] = None,
                 ):
    """`h2o.create_frame` — random synthetic frame (water/api CreateFrame),
    the generator many reference pyunits build fixtures with. Connected
    remotely the frame is generated ON the server (`POST /3/CreateFrame`)."""
    conn = client.current_connection()
    if conn is not None:
        out = conn.post(
            "/3/CreateFrame", rows=rows, cols=cols,
            randomize=int(randomize), real_fraction=real_fraction,
            categorical_fraction=categorical_fraction,
            integer_fraction=integer_fraction,
            binary_fraction=binary_fraction, factors=factors,
            real_range=real_range, integer_range=integer_range,
            missing_fraction=missing_fraction,
            has_response=int(has_response),
            response_factors=response_factors, seed=seed, dest=frame_id)
        return client.RemoteFrame(conn, out["destination_frame"]["name"])
    return _create_frame_local(
        rows, cols, randomize, real_fraction, categorical_fraction,
        integer_fraction, binary_fraction, factors, real_range,
        integer_range, missing_fraction, has_response, response_factors,
        seed, frame_id)


def _create_frame_local(rows, cols, randomize, real_fraction,
                        categorical_fraction, integer_fraction,
                        binary_fraction, factors, real_range, integer_range,
                        missing_fraction, has_response, response_factors,
                        seed, frame_id) -> Frame:
    """In-process generator core — what the server's /3/CreateFrame handler
    calls (never routes, so a process acting as both client and server
    can't loop back through its own connection)."""
    rng = np.random.default_rng(seed if seed is not None else 42)
    rf = 0.5 if real_fraction is None else real_fraction
    cf = 0.2 if categorical_fraction is None else categorical_fraction
    intf = 0.3 if integer_fraction is None else integer_fraction
    bf = 0.0 if binary_fraction is None else binary_fraction
    tot = max(rf + cf + intf + bf, 1e-12)
    # largest-remainder apportionment: exactly `cols` columns, and every
    # kind with a nonzero fraction keeps at least its floor share
    fracs = [("real", rf / tot), ("enum", cf / tot),
             ("int", intf / tot), ("bin", bf / tot)]
    floors = {k: int(np.floor(cols * f)) for k, f in fracs}
    rem = cols - sum(floors.values())
    by_rem = sorted(fracs, key=lambda kf: -(cols * kf[1] - floors[kf[0]]))
    for k, f in by_rem[:rem]:
        floors[k] += 1
    kinds = [k for k, _ in fracs for _ in range(floors[k])]
    d = {}
    types = {}
    for i, kind in enumerate(kinds):
        name = f"C{i+1}"
        if not randomize and kind != "enum":
            col = np.zeros(rows)  # CreateFrame randomize=false: constant 0
        elif kind == "real":
            col = rng.uniform(-real_range, real_range, rows)
        elif kind == "int":
            col = rng.integers(-integer_range, integer_range + 1, rows).astype(np.float64)
        elif kind == "bin":
            col = rng.integers(0, 2, rows).astype(np.float64)
        else:
            col = np.asarray([f"c{j}" for j in range(factors)], dtype=object)[
                rng.integers(0, factors, rows)]
            types[name] = "enum"
        if missing_fraction > 0 and kind != "enum":
            col = np.where(rng.uniform(size=rows) < missing_fraction, np.nan, col)
        d[name] = col
    if has_response:
        if response_factors > 1:
            d["response"] = np.asarray(
                [f"r{j}" for j in range(response_factors)], dtype=object)[
                rng.integers(0, response_factors, rows)]
            types["response"] = "enum"
        else:
            d["response"] = rng.normal(size=rows)
    fr = Frame.from_dict(d, column_types=types or None)
    if frame_id:
        fr.key = frame_id
    _DKV.put(fr.key, fr)
    return fr


def interaction(data, factors, pairwise: bool, max_factors: int,
                min_occurrence: int, destination_frame: Optional[str] = None):
    """`h2o.interaction` — interaction columns between categorical factors
    (hex/Interaction.java): combined levels, capped at max_factors most
    frequent (others pooled as 'other'), levels under min_occurrence
    dropped. For a remote frame this runs server-side
    (`POST /3/Interaction`)."""
    if getattr(data, "_is_remote", False):
        import json as _json

        # factors go over verbatim (ints included) — the server-side core
        # does the int→name mapping, so no metadata round-trip here
        out = data.conn.post(
            "/3/Interaction", source_frame=data.key,
            factor_columns=_json.dumps(list(factors)),
            pairwise=int(pairwise), max_factors=max_factors,
            min_occurrence=min_occurrence, dest=destination_frame)
        return client.RemoteFrame(data.conn,
                                  out["destination_frame"]["name"])
    return _interaction_local(data, factors, pairwise, max_factors,
                              min_occurrence, destination_frame)


def _interaction_local(data: Frame, factors, pairwise, max_factors,
                       min_occurrence, destination_frame=None) -> Frame:
    """In-process core — what the server's /3/Interaction handler calls."""
    from .frame.vec import Vec

    facs = [data.names[f] if isinstance(f, int) else f for f in factors]
    pairs = ([(a, b) for i, a in enumerate(facs) for b in facs[i + 1:]]
             if pairwise else [tuple(facs)])
    out = {}
    for combo in pairs:
        labels = []
        for c in combo:
            v = data.vec(c)
            dom = np.asarray((v.domain or []) + [None], dtype=object)
            labels.append(dom[np.asarray(v.data, np.int64)])
        joined = np.asarray(
            ["_".join("NA" if p is None else str(p) for p in row)
             for row in zip(*labels)], dtype=object)
        uniq, counts = np.unique(joined, return_counts=True)
        keep = uniq[counts >= max(min_occurrence, 1)]
        order = np.argsort(-counts[np.isin(uniq, keep)])
        kept = list(keep[order][:max_factors])
        lookup = {lbl: i for i, lbl in enumerate(kept)}
        other = len(kept)
        codes = np.asarray([lookup.get(s, other) for s in joined], np.int32)
        dom = kept + ["other"] if (codes == other).any() else kept
        name = "_".join(combo)
        out[name] = Vec(codes, "enum", domain=dom)
    fr = Frame(out, key=destination_frame)
    _DKV.put(fr.key, fr)
    return fr


from .explanation import (explain, explain_row,  # noqa: E402,F401
                          model_correlation_heatmap, pd_multi_plot,
                          residual_analysis, varimp_heatmap)


def batch():
    """`with h2o.batch():` — defer remote munging ops and ship them as one
    multi-statement Rapids program (see H2OConnection.batch). Requires an
    active remote connection."""
    conn = client.current_connection()
    if conn is None:
        raise client.H2OConnectionError(
            "h2o.batch() needs an active remote connection (h2o.connect)")
    return conn.batch()


def rapids(expr: str):
    """`h2o.rapids` — evaluate a Rapids sexpr against the DKV (routed over
    `/99/Rapids` when attached to a remote server)."""
    conn = client.current_connection()
    if conn is not None:
        return conn.rapids(expr)
    from .frame.rapids_expr import RapidsSession

    return RapidsSession(_DKV).execute(expr)


def no_progress():
    pass


def show_progress():
    pass


# model save/load (h2o.save_model / h2o.load_model → /3/Models.bin)
def save_model(model, path: str = ".", force: bool = False, filename=None) -> str:
    m = getattr(model, "_model", None) or model
    if getattr(m, "_is_remote", False):
        # REST-backed model: the artifact downloads from the server; the
        # local force= overwrite guard applies identically
        target = (path if _os.path.splitext(path)[1]
                  and not _os.path.isdir(path)
                  else _os.path.join(path, filename or f"{m.model_id}.h2o3"))
        if _os.path.exists(target) and not force:
            raise FileExistsError(f"{target} exists; pass force=True")
        return m.download_mojo(path, filename=filename)
    from .mojo import save_model as _save

    return _save(model, path, filename=filename, force=force)


def load_model(path: str):
    from .mojo import load_model as _load

    return _load(path)


def download_mojo(model, path: str = ".", **kw) -> str:
    return save_model(model, path)


def import_mojo(path: str):
    return load_model(path)


def api(endpoint: str, data: Optional[dict] = None):
    """`h2o.api("GET /3/Cloud")` — raw REST call against the attached
    server (h2o-py's escape hatch for routes without a wrapper)."""
    conn = client.current_connection()
    if conn is None:
        raise client.H2OConnectionError(
            "h2o.api needs an active remote connection (h2o.connect)")
    verb, _, path = endpoint.partition(" ")
    if not path.startswith("/"):
        raise ValueError(f"endpoint must be 'VERB /path', got {endpoint!r}")
    return conn.request(verb.upper(), path.strip(), params=data)


def download_model(model, path: str = ".", filename: Optional[str] = None) -> str:
    """`h2o.download_model` — fetch a model's artifact to local disk: a
    REST-backed model downloads from its server, an in-process model
    saves directly (one artifact format — MOJO ≡ binary here). Overwrites
    like h2o-py's download_model does."""
    return save_model(model, path, filename=filename, force=True)


def upload_model(path: str):
    """`h2o.upload_model` — push a LOCAL artifact to the attached server
    and load it there (returns the server-side model); in-process this is
    load_model."""
    conn = client.current_connection()
    if conn is None:
        return load_model(path)
    import urllib.parse as _up

    with open(path, "rb") as f:
        body = f.read()
    up = conn.request(
        "POST", "/3/PostFile?destination_frame="
                f"{_up.quote(_os.path.basename(path))}",
        data=body, content_type="application/octet-stream")
    # delete_source: the PostFile temp copy has served its purpose once
    # loaded — without this every upload leaks one zip in the server tmpdir
    out = conn.post("/99/Models.bin", path=up["destination_frame"],
                    delete_source=1)
    return client.RemoteModel(conn, out["models"][0]["model_id"]["name"])


# one artifact format: uploading a "MOJO" and a binary model are the same op
upload_mojo = upload_model


def print_mojo(mojo_path: str, format: str = "json"):
    """`h2o.print_mojo` — human-readable artifact dump: meta + array
    shapes (and per-forest tree counts for tree kinds). For full tree
    STRUCTURE use `h2o.tree.H2OTree` on the loaded model
    (hex/genmodel PrintMojo analog)."""
    import json as _json

    scorer = load_model(mojo_path)
    out = {"meta": {k: v for k, v in scorer.meta.items()},
           "arrays": {k: list(np.asarray(v).shape)
                      for k, v in scorer.arrays.items()}}
    if format == "json":
        return _json.dumps(out, indent=2, default=str)
    return out


def make_metrics(predicted, actuals, domain: Optional[Sequence] = None,
                 distribution: Optional[str] = None, **kw):
    """`h2o.make_metrics` — ModelMetrics from prediction and actual
    columns (water/api MakeMetricsHandler): regression when no domain,
    binomial for a 2-level domain (predicted = p1 column), multinomial
    for K levels (predicted = K probability columns)."""
    from .models.metrics import (ModelMetricsBinomial,
                                 ModelMetricsMultinomial,
                                 ModelMetricsRegression)

    def _cols(obj):
        if isinstance(obj, Frame):
            return np.column_stack([obj.vec(n).numeric_np()
                                    for n in obj.names])
        a = np.asarray(obj, np.float64)
        return a[:, None] if a.ndim == 1 else a

    pred = _cols(predicted)
    if isinstance(actuals, Frame):
        av = actuals.vec(actuals.names[0])
    else:
        av = actuals
    if domain is None:
        act = (av.numeric_np() if hasattr(av, "numeric_np")
               else np.asarray(av, np.float64))
        return ModelMetricsRegression.make(act, pred[:, 0])
    dom = [str(d) for d in domain]
    if hasattr(av, "data") and getattr(av, "type", None) == "enum":
        codes = np.asarray(av.data, np.int64)
        if av.domain and list(map(str, av.domain)) != dom:
            lookup = {d: i for i, d in enumerate(dom)}
            remap = np.asarray([lookup.get(str(d), -1) for d in av.domain])
            codes = np.where(codes >= 0, remap[np.maximum(codes, 0)], -1)
    else:
        vals = (av.to_numpy() if hasattr(av, "to_numpy")
                else np.asarray(av))
        lookup = {d: i for i, d in enumerate(dom)}
        codes = np.asarray([lookup.get(str(v), -1) for v in vals], np.int64)
    if (codes < 0).any():
        bad = int((codes < 0).sum())
        raise ValueError(
            f"make_metrics: {bad} actual value(s) are NA or outside the "
            f"given domain {dom} — metrics over unmatched rows would be "
            "silently wrong; clean the actuals or fix the domain")
    if len(dom) == 2:
        return ModelMetricsBinomial.make(codes, pred[:, -1])
    if pred.shape[1] != len(dom):
        raise ValueError(
            f"multinomial make_metrics needs {len(dom)} probability "
            f"columns, got {pred.shape[1]}")
    return ModelMetricsMultinomial.make(codes, pred)


def save_grid(grid, grid_directory: str,
              export_cross_validation_predictions: bool = False) -> str:
    """`h2o.save_grid` — export a trained grid (state + per-model
    artifacts) so `h2o.load_grid(grid_directory)` restores it."""
    if export_cross_validation_predictions:
        raise NotImplementedError(
            "export_cross_validation_predictions is not part of this "
            "artifact format (holdout predictions are recomputable from "
            "the restored models)")
    return grid.save(grid_directory)


def load_grid(grid_file_path: str, grid_id: Optional[str] = None):
    """`h2o.load_grid` — re-import a checkpointed grid from its
    recovery_dir (hex/grid recovery)."""
    import glob as _glob

    from .models.grid import H2OGridSearch

    if grid_id is None:
        hits = sorted(_glob.glob(_os.path.join(grid_file_path, "*.grid.json")))
        if not hits:
            raise FileNotFoundError(f"no grid state under {grid_file_path}")
        if len(hits) > 1:
            ids = [_os.path.basename(h)[: -len(".grid.json")] for h in hits]
            raise ValueError(
                f"multiple grids under {grid_file_path}: {ids}; pass grid_id"
            )
        grid_id = _os.path.basename(hits[0])[: -len(".grid.json")]
    return H2OGridSearch.load(grid_file_path, grid_id)
