"""`h2o.automl` — reference parity: `h2o-py/h2o/automl/` + `h2o-automl/`."""

from .automl import EventLog, H2OAutoML, Leaderboard

__all__ = ["H2OAutoML", "Leaderboard", "EventLog"]
