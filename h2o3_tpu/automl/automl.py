"""H2OAutoML — automatic model selection under a budget.

Reference parity: `h2o-automl/src/main/java/ai/h2o/automl/AutoML.java`,
`ModelingStepsExecutor.java`, `modeling/*Steps.java` (the step sequence:
XGBoost defaults ×3, GLM, DRF + XRT, GBM ×5, DeepLearning ×3, random grids,
then two StackedEnsembles — BestOfFamily and AllModels), `Leaderboard.java`
(rank by CV metric), `events/EventLog.java`. Client surface
`h2o-py/h2o/automl/_estimator.py` (`H2OAutoML(max_models=, max_runtime_secs=)
.train()`, `.leaderboard`, `.leader`).

Budgeting: `max_models` counts base models (as upstream); `max_runtime_secs`
is checked between steps. Every base model trains with nfolds=5 CV so the
ensembles can stack holdout predictions.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

import numpy as np

from ..frame.frame import Frame
from ..models.model_base import response_info


class EventLog:
    """ai.h2o.automl.events.EventLog — timestamped progress records."""

    def __init__(self):
        self.events: List[Dict] = []

    def log(self, stage: str, message: str):
        self.events.append({"timestamp": time.time(), "stage": stage, "message": message})


class Leaderboard:
    """ai.h2o.automl.Leaderboard — models ranked by CV metric."""

    def __init__(self, sort_metric: str, decreasing: bool):
        self.sort_metric = sort_metric
        self.decreasing = decreasing
        self.rows: List[Dict] = []

    def add(self, est, lb_frame=None):
        m = (est.model.model_performance(lb_frame) if lb_frame is not None
             else est.model._m(xval=True))
        row = {
            "model_id": est.model_id,
            "algo": est.algo,
            "_est": est,
        }
        for name in ("auc", "logloss", "mean_per_class_error", "rmse", "mse", "mae"):
            row[name] = getattr(m, name, float("nan"))
        self.rows.append(row)
        self._sort()

    def _sort(self):
        key = self.sort_metric

        def sk(r):
            v = r.get(key, float("nan"))
            bad = v is None or (isinstance(v, float) and np.isnan(v))
            return (bad, -v if (self.decreasing and not bad) else (v if not bad else 0))

        self.rows.sort(key=sk)

    def as_data_frame(self, use_pandas=False):
        return [
            {k: v for k, v in r.items() if not k.startswith("_")} for r in self.rows
        ]

    def as_frame(self):
        """Leaderboard as a Frame (the h2o-py leaderboard frame surface)."""
        rows = self.as_data_frame()
        if not rows:
            return Frame({})
        cols = {}
        for k in rows[0]:
            vals = [r.get(k) for r in rows]
            if isinstance(vals[0], str):
                cols[k] = np.asarray(vals, dtype=object)
            else:
                cols[k] = np.asarray(
                    [np.nan if v is None else float(v) for v in vals])
        return Frame.from_dict(cols, column_types={"model_id": "enum",
                                                   "algo": "enum"})

    def __len__(self):
        return len(self.rows)

    def __getitem__(self, i):
        return self.rows[i]


class H2OAutoML:
    def __init__(
        self,
        max_models: Optional[int] = None,
        max_runtime_secs: float = 3600.0,
        max_runtime_secs_per_model: float = 0.0,
        seed: Optional[int] = None,
        nfolds: int = 5,
        sort_metric: str = "AUTO",
        stopping_metric: str = "AUTO",
        stopping_rounds: int = 3,
        stopping_tolerance: float = -1.0,
        exclude_algos: Optional[List[str]] = None,
        include_algos: Optional[List[str]] = None,
        balance_classes: bool = False,
        project_name: Optional[str] = None,
        verbosity: Optional[str] = None,
        keep_cross_validation_predictions: bool = True,
        parallelism: int = 1,
        checkpoint_dir: Optional[str] = None,
        **kw,
    ):
        self.max_models = max_models
        self._lb_frame = None
        self.max_runtime_secs = max_runtime_secs
        self.max_runtime_secs_per_model = max_runtime_secs_per_model
        self.seed = seed if seed is not None else 1234
        self.nfolds = max(int(nfolds), 2)
        self.sort_metric = sort_metric
        self.exclude_algos = set(a.upper() for a in (exclude_algos or []))
        self.include_algos = (
            set(a.upper() for a in include_algos) if include_algos else None
        )
        self.project_name = project_name or f"automl_{int(time.time())}"
        # candidate builds in flight at once (runtime/trainpool.py); results
        # enter the leaderboard in submission order, so any parallelism
        # produces the same leaderboard as the sequential walk
        self.parallelism = max(int(parallelism or 1), 1)
        # sweep checkpoint/resume (runtime/trainpool.SweepCheckpoint): with
        # a checkpoint_dir, every completed candidate persists a record +
        # model artifact, and a killed run re-submitted under the SAME
        # project_name restores those candidates instead of retraining them
        # (candidate names are deterministic given seed/include lists)
        self.checkpoint_dir = checkpoint_dir
        self._ckpt = None
        self.event_log = EventLog()
        self.leaderboard: Optional[Leaderboard] = None
        self.leader = None
        self._models: List = []

    def _allowed(self, algo: str) -> bool:
        algo = algo.upper()
        if self.include_algos is not None:
            return algo in self.include_algos
        return algo not in self.exclude_algos

    # the fixed modeling plan of ai.h2o.automl.modeling.*Steps
    def _steps(self, problem: str) -> List[Dict[str, Any]]:
        from ..models.deeplearning import H2ODeepLearningEstimator
        from ..models.drf import H2ORandomForestEstimator
        from ..models.gbm import H2OGradientBoostingEstimator
        from ..models.glm import H2OGeneralizedLinearEstimator
        from ..models.xgboost import H2OXGBoostEstimator

        steps = []

        def add(algo, cls, name, **parms):
            if self._allowed(algo):
                steps.append({"algo": algo, "cls": cls, "name": name, "parms": parms})

        # XGBoost defaults ×3 (XGBoostSteps def_1..3)
        add("XGBOOST", H2OXGBoostEstimator, "XGBoost_1",
            ntrees=50, max_depth=6, learn_rate=0.3, sample_rate=0.8,
            col_sample_rate_per_tree=0.8, reg_lambda=1.0)
        add("XGBOOST", H2OXGBoostEstimator, "XGBoost_2",
            ntrees=50, max_depth=10, learn_rate=0.2, sample_rate=0.6,
            col_sample_rate_per_tree=0.8, reg_lambda=1.0, min_rows=5.0)
        add("XGBOOST", H2OXGBoostEstimator, "XGBoost_3",
            ntrees=50, max_depth=3, learn_rate=0.3, sample_rate=0.8,
            col_sample_rate_per_tree=0.8, reg_lambda=1.0)
        # GLM (GLMSteps def_1: lambda search)
        add("GLM", H2OGeneralizedLinearEstimator, "GLM_1",
            lambda_search=True, alpha=0.5)
        # DRF + XRT (DRFSteps)
        add("DRF", H2ORandomForestEstimator, "DRF_1", ntrees=50)
        add("DRF", H2ORandomForestEstimator, "XRT_1", ntrees=50,
            histogram_type="Random")
        # GBM ×5 (GBMSteps def_1..5)
        for i, (d, r) in enumerate([(6, 0.8), (7, 0.8), (8, 0.8), (10, 0.6), (15, 0.6)], 1):
            add("GBM", H2OGradientBoostingEstimator, f"GBM_{i}",
                ntrees=60, max_depth=d, sample_rate=r, learn_rate=0.1,
                col_sample_rate=0.8)
        # DeepLearning ×3 (DeepLearningSteps)
        add("DEEPLEARNING", H2ODeepLearningEstimator, "DeepLearning_1",
            hidden=[64, 64], epochs=10, mini_batch_size=128)
        add("DEEPLEARNING", H2ODeepLearningEstimator, "DeepLearning_2",
            hidden=[128], epochs=10, mini_batch_size=128)
        add("DEEPLEARNING", H2ODeepLearningEstimator, "DeepLearning_3",
            hidden=[32, 32, 32], epochs=10, mini_batch_size=128)
        return steps

    def _candidate(self, name, cls, parms, x, y, training_frame):
        """(name, build_fn) for the train pool — one leaderboard model."""
        parms = dict(parms)
        parms["seed"] = self.seed
        parms["nfolds"] = self.nfolds
        parms["keep_cross_validation_predictions"] = True
        # reference AutoML default: fold models are discarded once their
        # holdout predictions/metrics are extracted (frees the device-
        # resident fold forests — deep DRF folds are ~600 MB HBM each)
        parms["keep_cross_validation_models"] = False
        if self.max_runtime_secs_per_model:
            parms["max_runtime_secs"] = self.max_runtime_secs_per_model

        def fn(job):
            est = cls(**parms)
            est._external_job = job   # pool cancel reaches the driver
            if self._ckpt is not None:
                from ..runtime import supervisor as _sup

                # in-flight pointer (mid-fit resume rider): a sweep killed
                # DURING this candidate leaves the breadcrumb a re-run
                # needs — the retrained candidate's fit restores its newest
                # snapshot from ckpt_dir (same run fingerprint), so only
                # the uncheckpointed tail rebuilds (totals.resumed_mid_fit)
                if _sup.ckpt_enabled() and _sup.ckpt_dir():
                    self._ckpt.mark_inflight(
                        name, dict(ckpt_dir=_sup.ckpt_dir(), algo=str(cls.__name__)))
            est.train(x=x, y=y, training_frame=training_frame)
            est._automl_name = name
            return est

        return (name, fn)

    def _checkpoint_candidate(self, name: str, est) -> None:
        """Persist one completed candidate's record (+ artifact when the
        mojo format covers the algo) so a killed run resumes past it.
        Metrics come straight from the leaderboard row Leaderboard.add
        just computed for this model (leaderboard_frame when given, else
        CV) — same footing as fresh rows, and no second scoring pass over
        the leaderboard frame."""
        row = next((r for r in self.leaderboard.rows
                    if r.get("model_id") == est.model_id), {})
        metrics = {}
        for k in self._LEADERBOARD_METRICS:
            v = row.get(k)
            if isinstance(v, (int, float)):
                metrics[k] = float(v)
        payload = dict(model_id=est.model_id, algo=est.algo,
                       metrics=metrics)
        try:
            from ..mojo import save_model

            fname = f"{self.project_name}_{name}.h2o3"
            save_model(est, self.checkpoint_dir, filename=fname, force=True)
            payload["file"] = fname
        except (TypeError, OSError):
            pass    # file-less record: the candidate retrains on resume
        self._ckpt.mark(name, payload)

    def _restorable(self, name: str) -> Optional[Dict]:
        """Checkpoint record usable for restore: it exists AND its artifact
        is still on disk. A file-less record (mojo export failed) or a lost
        artifact must retrain the candidate — restoring it would put an
        unscorable shim on the leaderboard that crashes predict() later."""
        import os

        if self._ckpt is None:
            return None
        p = self._ckpt.completed(name)
        if p and p.get("file") and os.path.exists(
                os.path.join(self.checkpoint_dir, p["file"])):
            return p
        return None

    def _restore_candidate(self, name: str, payload: Dict) -> None:
        """Rebuild a leaderboard entry from its checkpoint record: metric
        values replay from the payload, predict() scores through the saved
        artifact (grid._RecoveredModel does exactly this for grids)."""
        import os

        from ..models.grid import _RecoveredModel
        from ..runtime import trainpool as _tp

        metrics = payload.get("metrics") or {}
        path = (os.path.join(self.checkpoint_dir, payload["file"])
                if payload.get("file") else "")
        shim = _RecoveredModel({}, path or f"{name}.h2o3", metrics)
        shim.algo = payload.get("algo", "unknown")
        shim.model_id = payload.get("model_id", name)
        shim._automl_name = name
        row = {"model_id": shim.model_id, "algo": shim.algo, "_est": shim}
        for k in self._LEADERBOARD_METRICS:
            row[k] = metrics.get(k, float("nan"))
        self.leaderboard.rows.append(row)
        self.leaderboard._sort()
        self._models.append(shim)
        _tp.record_resumed()
        self.event_log.log(
            "resume", f"restored {name} ({shim.model_id}) from checkpoint")

    def _run_candidates(self, cands, budget_left) -> bool:
        """Run candidate builds through the train pool (runtime/trainpool)
        in max_models-bounded waves; leaderboard entries land in submission
        order, so parallelism never changes the resulting leaderboard.
        Candidates with a checkpoint record are RESTORED instead of
        retrained (they still count toward max_models). Returns False once
        the budget or max_models is exhausted."""
        from ..runtime import trainpool as _tp

        i = 0
        while i < len(cands):
            if not budget_left():
                self.event_log.log("budget", "max_runtime_secs reached")
                return False
            remaining = (self.max_models - len(self._models)
                         if self.max_models else len(cands) - i)
            if remaining <= 0:
                return False
            name = cands[i][0]
            payload = self._restorable(name)
            if payload is not None:
                i += 1
                self._restore_candidate(name, payload)
                continue
            # fresh batch up to the wave budget, stopping at the next
            # checkpointed candidate so restore order stays deterministic
            batch = []
            while (i < len(cands) and len(batch) < remaining
                   and self._restorable(cands[i][0]) is None):
                batch.append(cands[i])
                i += 1
            pool = _tp.TrainPool(self.parallelism, label=self.project_name)
            recs = pool.run(batch, stop_when=lambda: not budget_left())
            for (name, _), rec in zip(batch, recs):
                if rec.ok:
                    est = rec.result
                    self._models.append(est)
                    self.leaderboard.add(est, self._lb_frame)
                    self.event_log.log(
                        "model", f"built {name} ({est.model_id})")
                    if self._ckpt is not None:
                        self._checkpoint_candidate(name, est)
                elif rec.status == "failed":
                    self.event_log.log("error", f"{name} failed: {rec.error}")
                elif rec.status in ("skipped", "cancelled"):
                    self.event_log.log("budget",
                                       "max_runtime_secs reached")
                    return False
        return True

    def _run_random_grids(self, x, y, training_frame, budget_left):
        import itertools

        from ..models.deeplearning import H2ODeepLearningEstimator
        from ..models.gbm import H2OGradientBoostingEstimator
        from ..models.xgboost import H2OXGBoostEstimator

        rng = np.random.default_rng(self.seed)
        grids = [
            ("GBM", H2OGradientBoostingEstimator, dict(
                max_depth=[3, 5, 7, 9], learn_rate=[0.05, 0.1, 0.2],
                sample_rate=[0.6, 0.8, 1.0], col_sample_rate=[0.4, 0.7, 1.0],
                ntrees=[60])),
            ("XGBOOST", H2OXGBoostEstimator, dict(
                max_depth=[5, 10, 15], learn_rate=[0.05, 0.1, 0.3],
                sample_rate=[0.6, 0.8, 1.0], reg_lambda=[0.1, 1.0, 10.0],
                ntrees=[50])),
            ("DEEPLEARNING", H2ODeepLearningEstimator, dict(
                hidden=[[32], [64, 64], [128, 128]],
                epochs=[10], mini_batch_size=[128])),
        ]
        cands = []
        for gi, (algo, cls, hp) in enumerate(grids):
            if not self._allowed(algo):
                continue
            keys = list(hp)
            combos = [dict(zip(keys, v))
                      for v in itertools.product(*(hp[k] for k in keys))]
            rng.shuffle(combos)
            for ci, parms in enumerate(combos[:3]):  # budget-bounded sample
                cands.append(self._candidate(
                    f"{algo}_grid_1_model_{ci + 1}", cls, parms,
                    x, y, training_frame))
        self._run_candidates(cands, budget_left)

    def _remote_train(self, x, y, training_frame):
        """AutoML against an attached server: POST `/99/AutoMLBuilder`,
        poll the job, hydrate leaderboard + leader from `/99/AutoML/{id}`
        (h2o-py's H2OAutoML is the same REST choreography). The full
        builder config travels with the request; 0 = unlimited runtime is
        forwarded EXPLICITLY (the server-side default is 3600 s)."""
        import json as _json
        import urllib.parse as _up

        conn = training_frame.conn
        params = dict(training_frame=training_frame.key, response_column=y,
                      project_name=self.project_name, seed=self.seed,
                      nfolds=self.nfolds,
                      max_runtime_secs=self.max_runtime_secs,
                      sort_metric=self.sort_metric)
        if x is not None:
            params["x"] = _json.dumps(list(x))
        if self.max_models:
            params["max_models"] = self.max_models
        if self.exclude_algos:
            params["exclude_algos"] = _json.dumps(sorted(self.exclude_algos))
        if self.include_algos is not None:
            params["include_algos"] = _json.dumps(sorted(self.include_algos))
        out = conn.post("/99/AutoMLBuilder", **params)
        job_key = out["job"]["key"]["name"]
        conn.wait_for_job(
            job_key,
            timeout=(self.max_runtime_secs + 600.0
                     if self.max_runtime_secs > 0 else 86_400.0))
        got = conn.get(f"/99/AutoML/{_up.quote(self.project_name, safe='')}")
        metric = got["leaderboard"].get("sort_metric") or self.sort_metric
        lb = Leaderboard(metric,
                         metric in ("auc", "pr_auc", "accuracy", "r2"))
        lb.rows = got["leaderboard"]["rows"]
        self.leaderboard = lb
        self._remote_conn = conn
        if got.get("leader"):
            from ..client import RemoteModel

            self.leader = RemoteModel(conn, got["leader"]["name"])
        self.event_log.events.extend(got.get("event_log") or [])
        return self

    def train(self, x=None, y=None, training_frame: Optional[Frame] = None,
              validation_frame=None, leaderboard_frame=None, blending_frame=None,
              **kw):
        assert training_frame is not None and y is not None
        if getattr(training_frame, "_is_remote", False):
            return self._remote_train(x, y, training_frame)
        self._lb_frame = leaderboard_frame
        t0 = time.time()
        if self.checkpoint_dir:
            from ..runtime.trainpool import SweepCheckpoint

            # run identity: candidate names (GBM_1, ...) are constants, so
            # without this a checkpoint from a different dataset/response/
            # seed would silently restore the wrong models. Shape + column
            # names stand in for frame identity (auto-generated frame keys
            # don't survive a process restart).
            fp = dict(
                y=str(y),
                x=sorted(str(c) for c in x) if x is not None else None,
                seed=int(self.seed), nfolds=int(self.nfolds),
                nrow=int(training_frame.nrow), ncol=int(training_frame.ncol),
                columns=[str(c) for c in training_frame.names])
            self._ckpt = SweepCheckpoint(self.checkpoint_dir,
                                         self.project_name, fingerprint=fp)
            if len(self._ckpt):
                self.event_log.log(
                    "resume", f"checkpoint has {len(self._ckpt)} completed "
                    "candidate(s); they will be restored, not retrained")
            stranded = self._ckpt.inflight()
            if stranded:
                # candidates the killed run left mid-fit: they retrain, but
                # their fits restore the newest valid mid-fit snapshot via
                # the supervisor store, so only the uncheckpointed tail is
                # rebuilt (runtime/supervisor.py; totals.resumed_mid_fit)
                self.event_log.log(
                    "resume", f"{len(stranded)} candidate(s) were mid-fit "
                    "when the prior run died "
                    f"({', '.join(sorted(stranded))}); their fits will "
                    "resume from fit-level checkpoints where available")
        problem, nclass, domain = response_info(training_frame.vec(y))
        sort_metric = self.sort_metric
        if sort_metric == "AUTO":
            sort_metric = {"binomial": "auc", "multinomial": "mean_per_class_error"}.get(
                problem, "rmse"
            )
        decreasing = sort_metric in ("auc", "pr_auc", "accuracy", "r2")
        self.leaderboard = Leaderboard(sort_metric, decreasing)
        self.event_log.log("init", f"AutoML {self.project_name} problem={problem}")

        budget_left = lambda: (
            self.max_runtime_secs <= 0 or time.time() - t0 < self.max_runtime_secs
        )
        self._run_candidates(
            [self._candidate(s["name"], s["cls"], s["parms"],
                             x, y, training_frame)
             for s in self._steps(problem)],
            budget_left)

        # random grids (modeling.*Steps grids: XGBoost/GBM/DL RandomDiscrete
        # exploration after the defaults, while budget remains)
        self._run_random_grids(x, y, training_frame, budget_left)

        # StackedEnsembles (SE BestOfFamily + AllModels)
        if self._allowed("STACKEDENSEMBLE") and len(self._models) >= 2 and budget_left():
            from ..models.ensemble import H2OStackedEnsembleEstimator
            from ..models.grid import _RecoveredModel

            # checkpoint-restored shims carry no CV holdout predictions to
            # stack — build the ensembles over freshly-trained bases only,
            # instead of letting one shim fail the whole SE stage
            trained = [m for m in self._models
                       if not isinstance(m, _RecoveredModel)]
            best_of_family: Dict[str, Any] = {}
            for r in self.leaderboard.rows:
                if not isinstance(r["_est"], _RecoveredModel):
                    best_of_family.setdefault(r["algo"], r["_est"])
            for name, base in (
                ("StackedEnsemble_BestOfFamily", list(best_of_family.values())),
                ("StackedEnsemble_AllModels", trained),
            ):
                if len(base) < 2:
                    self.event_log.log(
                        "skip", f"{name}: fewer than 2 stackable "
                        "(freshly-trained) base models")
                    continue
                try:
                    se = H2OStackedEnsembleEstimator(base_models=base)
                    se.train(x=x, y=y, training_frame=training_frame)
                    se._automl_name = name
                    # SE has no CV — rank by training metrics as proxy
                    se.model.cross_validation_metrics = se.model.training_metrics
                    self.leaderboard.add(se, self._lb_frame)
                    self.event_log.log("model", f"built {name}")
                except Exception as e:
                    self.event_log.log("error", f"{name} failed: {e}")

        if len(self.leaderboard):
            self.leader = self.leaderboard[0]["_est"]
        self.event_log.log("done", f"{len(self.leaderboard)} models")
        return self

    def predict(self, frame: Frame) -> Frame:
        assert self.leader is not None, "AutoML has no leader; call train() first"
        return self.leader.predict(frame)

    _LEADERBOARD_METRICS = ("auc", "logloss", "mean_per_class_error",
                            "rmse", "mse", "mae")

    def get_best_model(self, algorithm: Optional[str] = None,
                       criterion: Optional[str] = None):
        """Best model overall or of one algorithm family
        (H2OAutoML.get_best_model)."""
        if self.leaderboard is None:
            raise ValueError("AutoML has no leaderboard; call train() first")
        rows = self.leaderboard.rows
        if criterion:
            if criterion not in self._LEADERBOARD_METRICS:
                raise ValueError(
                    f"criterion {criterion!r} not in leaderboard metrics "
                    f"{self._LEADERBOARD_METRICS}")
            decreasing = criterion in ("auc",)

            def sk(r):  # NaN-safe total order (same shape as Leaderboard._sort)
                v = r.get(criterion, float("nan"))
                bad = v is None or (isinstance(v, float) and np.isnan(v))
                return (bad, -v if (decreasing and not bad) else (v if not bad else 0))

            rows = sorted(rows, key=sk)
        for r in rows:
            if algorithm is None or r["algo"].lower() == algorithm.lower():
                if "_est" in r:
                    return r["_est"]
                # remote-hydrated leaderboard: the server strips private
                # keys — return a REST-backed model by id instead
                conn = getattr(self, "_remote_conn", None)
                if conn is not None:
                    from ..client import RemoteModel

                    return RemoteModel(conn, r["model_id"])
                raise KeyError("_est")
        return None

    def get_leaderboard(self, extra_columns=None):
        return self.leaderboard
