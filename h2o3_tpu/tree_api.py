"""Tree inspection — the `h2o.tree.H2OTree` client surface.

Reference parity: `h2o-py/h2o/tree/tree.py` (H2OTree fetching a single tree
over `/3/Tree`) and `hex/schemas/TreeV3` / `hex/tree/TreeHandler.java` on the
server side. Here the model is in-process, so the tree is read straight off
the heap arrays of `models/tree.py`: reachable nodes are enumerated in
breadth-first heap order; children of non-split nodes are -1 (leaf).

NA routing is always "right" in this framework (the NA bin is the last
histogram bin — see models/tree.py), so `nas` is "R" at every split.
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np


class H2OTree:
    """One tree of a trained GBM/DRF/XGBoost/IF model.

    Arrays are aligned over the tree's REACHABLE nodes (BFS order):
      node_ids        heap index of each node
      left_children / right_children   positions in these arrays, -1 at leaves
      features        split feature name (None at leaves)
      thresholds      split threshold (NaN at leaves)
      predictions     node value (the prediction when the node is a leaf)
      nas             NA direction at splits ("R" here), None at leaves
      root_node_id    heap id of the root (always 0)
    """

    def __init__(self, model, tree_number: int = 0,
                 tree_class: Optional[str] = None):
        m = getattr(model, "model", model)
        forest = getattr(m, "forest", None)
        if forest is None:
            raise TypeError("H2OTree requires a tree-based model")
        domain = getattr(m, "domain", None)
        k = 0
        if tree_class is not None:
            if domain is None or str(tree_class) not in [str(d) for d in domain]:
                raise ValueError(f"unknown tree_class {tree_class!r}")
            k = [str(d) for d in domain].index(str(tree_class))
            if len(forest) == 1 and k != len(domain) - 1:
                # binomial: only the positive class is modelled (one forest);
                # the reference TreeHandler rejects the other class too
                raise ValueError(
                    f"binomial models have trees only for class "
                    f"{domain[-1]!r}; tree_class={tree_class!r} is not "
                    "modelled")
            if len(forest) == 1:
                k = 0
        stacked = forest[k]
        ntrees = m.ntrees_built
        if not (0 <= tree_number < ntrees):
            raise ValueError(f"tree_number must be in [0, {ntrees})")
        self.model_id = m.model_id
        self.tree_number = tree_number
        self.tree_class = tree_class
        feat = np.asarray(stacked.feat)[tree_number]
        thr = np.asarray(stacked.thr)[tree_number]
        issp = np.asarray(stacked.is_split)[tree_number]
        val = np.asarray(stacked.value)[tree_number]
        names = list(m.x)

        ids: List[int] = []
        order = {}           # heap id -> position in output arrays
        queue = [0]
        while queue:
            h = queue.pop(0)
            order[h] = len(ids)
            ids.append(h)
            if issp[h]:
                queue.append(2 * h + 1)
                queue.append(2 * h + 2)
        self.node_ids = ids
        self.left_children = [
            order[2 * h + 1] if issp[h] else -1 for h in ids]
        self.right_children = [
            order[2 * h + 2] if issp[h] else -1 for h in ids]
        self.features = [names[feat[h]] if issp[h] else None for h in ids]
        self.thresholds = [
            float(thr[h]) if issp[h] else float("nan") for h in ids]
        self.predictions = [float(val[h]) for h in ids]
        self.nas = ["R" if issp[h] else None for h in ids]
        self.root_node_id = 0
        self.levels = [None] * len(ids)  # numeric splits (enums are codes)
        self.descriptions = [
            (f"split on {self.features[i]} <= {self.thresholds[i]:.6g} "
             f"(NA goes right)") if self.left_children[i] >= 0
            else f"leaf: {self.predictions[i]:.6g}"
            for i in range(len(ids))
        ]

    def __len__(self) -> int:
        return len(self.node_ids)

    def show(self):
        print(f"Tree {self.tree_number} of model {self.model_id} "
              f"({len(self)} nodes)")
        for i in range(len(self)):
            print(f"  [{i}] {self.descriptions[i]}")

    def __repr__(self):
        return (f"<H2OTree model={self.model_id} tree={self.tree_number} "
                f"nodes={len(self)}>")
