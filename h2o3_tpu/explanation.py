"""Model-explanation bundle — `h2o-py/h2o/explanation/_explain.py`.

Upstream's `h2o.explain(...)` renders matplotlib figures; this framework is
headless, so every function here returns the DATA the upstream plots draw —
Frames/tables you can feed to any plotting stack (the documented deviation:
explanations are data-first). The building blocks (partial_plot, TreeSHAP
contributions, permutation/variable importance) are the per-model methods;
this module is the multi-model orchestration layer on top.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np

from .frame.frame import Frame


def _as_models(models) -> List:
    """Normalize: estimator(s), model(s), or an H2OAutoML → list of models."""
    from .automl.automl import H2OAutoML

    if isinstance(models, H2OAutoML):
        if not models.leaderboard or not models.leaderboard.rows:
            raise ValueError("explain: AutoML has no leaderboard models")
        ests = [r["_est"] for r in models.leaderboard.rows if "_est" in r]
        models = ests
    if not isinstance(models, (list, tuple)):
        models = [models]
    out = []
    for m in models:
        out.append(getattr(m, "model", None) or m)
    if not out:
        raise ValueError("explain: no models given")
    return out


def _n_classes(model) -> int:
    dom = getattr(model, "domain", None)
    return len(dom) if dom else 0


def _pred_vector(model, frame: Frame) -> np.ndarray:
    """One comparable score per row: p1 for binomial, the raw prediction
    for regression (via the model's own _response_column, which knows the
    column layout incl. calibrated outputs), predicted-class codes for
    multinomial."""
    pred = model.predict(frame)
    if _n_classes(model) > 2:
        v = pred.vec("predict")
        return np.asarray(v.data, np.float64)
    return model._response_column(pred, None)


def varimp_heatmap(models) -> Frame:
    """Feature × model matrix of SCALED variable importances (upstream
    varimp_heatmap's underlying table): rows union all features, missing
    entries are 0."""
    ms = _as_models(models)
    tables: Dict[str, Dict[str, float]] = {}
    feats: List[str] = []
    for m in ms:
        vt = m.varimp() or []
        col = {}
        for row in vt:
            name, scaled = row[0], float(row[2])
            col[name] = scaled
            if name not in feats:
                feats.append(name)
        tables[m.model_id] = col
    d: Dict[str, np.ndarray] = {
        "feature": np.asarray(feats, dtype=object)}
    for mid, col in tables.items():
        d[mid] = np.asarray([col.get(f, 0.0) for f in feats], np.float64)
    return Frame.from_dict(d, column_types={"feature": "enum"})


def model_correlation_heatmap(models, frame: Frame) -> Frame:
    """Model × model Pearson correlation of predictions on `frame`."""
    ms = _as_models(models)
    if len(ms) < 2:
        raise ValueError("model_correlation_heatmap needs >= 2 models")
    preds = np.stack([_pred_vector(m, frame) for m in ms])
    corr = np.corrcoef(preds)
    ids = [m.model_id for m in ms]
    d: Dict[str, np.ndarray] = {"model": np.asarray(ids, dtype=object)}
    for j, mid in enumerate(ids):
        d[mid] = corr[:, j]
    return Frame.from_dict(d, column_types={"model": "enum"})


def pd_multi_plot(models, frame: Frame, column: str,
                  nbins: int = 20, target=None) -> Frame:
    """Partial-dependence of `column` for every model on one shared grid:
    columns [<column>, <model_id>...] (upstream pd_multi_plot's data)."""
    ms = _as_models(models)
    d: Dict[str, np.ndarray] = {}
    for m in ms:
        tbl = m.partial_plot(frame, cols=[column], nbins=nbins,
                             targets=[target] if target else None)[0]
        if column not in d:
            v = tbl.vec(column)
            if v.type == "enum":
                dom = np.asarray((v.domain or []) + [None], dtype=object)
                d[column] = dom[np.asarray(v.data, np.int64)]
            else:
                d[column] = v.numeric_np()
        d[m.model_id] = tbl.vec("mean_response").numeric_np()
    types = ({column: "enum"}
             if frame.vec(column).type == "enum" else None)
    return Frame.from_dict(d, column_types=types)


def residual_analysis(model, frame: Frame) -> Frame:
    """Fitted vs residual columns for a REGRESSION model (upstream
    residual_analysis_plot's data)."""
    m = getattr(model, "model", None) or model
    # either signal marks classification: tree models carry `problem`,
    # GLMs carry `family` — a conjunction would let both slip through
    if (getattr(m, "problem", None) not in (None, "regression")
            or getattr(m, "family", None) not in (None, "gaussian",
                                                  "poisson", "gamma",
                                                  "tweedie")
            or _n_classes(m) >= 2):
        raise ValueError("residual_analysis is for regression models")
    fitted = _pred_vector(m, frame)
    actual = frame.vec(m.y).numeric_np().astype(np.float64)
    return Frame.from_dict({"fitted": fitted,
                            "residual": actual - fitted})


def explain(models, frame: Frame, columns: Optional[Sequence[str]] = None,
            top_n_features: int = 5) -> Dict:
    """The explanation bundle (`h2o.explain`): a dict of data tables —
    'leaderboard' (AutoML input), 'varimp' per model, 'varimp_heatmap' +
    'model_correlation_heatmap' (≥2 models), and 'pdp' for the top
    important (or given) columns. Values are Frames/tables, not plots."""
    from .automl.automl import H2OAutoML

    out: Dict = {}
    if isinstance(models, H2OAutoML):
        out["leaderboard"] = models.leaderboard.as_frame()
    ms = _as_models(models)
    out["varimp"] = {m.model_id: (m.varimp() or []) for m in ms}
    if len(ms) >= 2:
        out["varimp_heatmap"] = varimp_heatmap(ms)
        out["model_correlation_heatmap"] = model_correlation_heatmap(
            ms, frame)
    if columns is None:
        # top features by scaled importance, restricted to columns present
        # in the frame — from the first model whose varimp yields any
        # (a leaderboard-topping StackedEnsemble has none; fall through)
        columns = []
        for m in ms:
            vt = m.varimp() or []
            cols = [r[0] for r in vt if r[0] in frame.names]
            if cols:
                columns = cols[:top_n_features]
                break
    # multinomial partial dependence needs an explicit class target
    # (averaging predicted labels is meaningless — same contract as
    # partial_plot); pick the last class like upstream's default plots
    target = (str(ms[0].domain[-1]) if _n_classes(ms[0]) > 2 else None)
    out["pdp"] = {c: pd_multi_plot(ms, frame, c, target=target)
                  for c in columns}
    if target is not None:
        out["pdp_target"] = target
    return out


def explain_row(models, frame: Frame, row_index: int) -> Dict:
    """Row-local explanation (`h2o.explain_row`): per-model prediction for
    the row plus SHAP contributions where the model supports them."""
    ms = _as_models(models)
    if not 0 <= row_index < frame.nrow:
        raise ValueError(f"row_index {row_index} out of range")
    one = frame.take(np.asarray([row_index]))
    out: Dict = {"row_index": row_index, "predictions": {},
                 "contributions": {}}
    for m in ms:
        pred = m.predict(one)
        out["predictions"][m.model_id] = {
            n: (pred.vec(n).numeric_np()[0]
                if pred.vec(n).type != "enum"
                else (pred.vec(n).domain or [None])[
                    int(np.asarray(pred.vec(n).data)[0])])
            for n in pred.names}
        try:
            contrib = m.predict_contributions(one)
            out["contributions"][m.model_id] = {
                n: float(contrib.vec(n).numeric_np()[0])
                for n in contrib.names}
        except (AttributeError, ValueError, TypeError):
            pass  # non-tree models: no TreeSHAP surface
    return out
