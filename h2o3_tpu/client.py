"""Remote-attach client — drive a running h2o3_tpu REST server by URL.

Reference parity: `h2o-py/h2o/backend/connection.py` (`H2OConnection.open`,
`request`), `h2o-py/h2o/frame.py` (REST-backed H2OFrame),
`h2o-py/h2o/estimators/estimator_base.py` (train = POST
`/3/ModelBuilders/{algo}` + poll `/3/Jobs`). Upstream's client is
fundamentally a REST client — "server on the TPU pod, thin client on a
laptop" is the reference's primary deployment mode; this module gives the
same split over this framework's 50-route server (`api/server.py`).

Redesign notes: upstream H2OFrame is lazy (an expression DAG flushed on
demand). Here remote frames are EAGER — every munging op posts one Rapids
`(assign ...)` and returns a new server-side key. At client-side scale
(import, asfactor, column select) the latency of one extra round-trip is
noise next to training, and eager keys make every intermediate inspectable
in Flow (`/flow/`).
"""

from __future__ import annotations

import contextlib
import json
import os
import threading
import time
import urllib.error
import urllib.parse
import urllib.request
from typing import Any, Dict, List, Optional, Sequence

__all__ = ["H2OConnection", "RemoteFrame", "RemoteModel", "H2OConnectionError",
           "connect", "current_connection", "disconnect", "remote_train"]


class H2OConnectionError(Exception):
    """Connection-level failure (unreachable server, auth rejection) —
    `h2o.exceptions.H2OConnectionError`."""


class H2OServerError(Exception):
    """Non-2xx reply from the server, with the decoded error payload."""

    def __init__(self, status: int, payload):
        self.status = status
        self.payload = payload
        super().__init__(f"HTTP {status}: {payload}")


_CURRENT: Optional["H2OConnection"] = None


def current_connection() -> Optional["H2OConnection"]:
    return _CURRENT


def connect(url: Optional[str] = None, ip: Optional[str] = None,
            port: Optional[int] = None, token: Optional[str] = None,
            verbose: bool = True, verify_ssl: bool = True) -> "H2OConnection":
    """Attach to a running server and make it the process-wide connection
    (`h2o.connect` — h2o-py/h2o/h2o.py)."""
    global _CURRENT
    if url is None:
        if ip is None and port is None:
            raise ValueError("connect() needs url= or ip=/port=")
        url = f"http://{ip or '127.0.0.1'}:{port or 54321}"
    conn = H2OConnection(url, token=token, verify_ssl=verify_ssl)
    info = conn.cluster_info()          # raises H2OConnectionError if dead
    if verbose:
        print(f"Connected to {url} — cloud "
              f"{info.get('cloud_name')!r} v{info.get('version')}")
    _CURRENT = conn
    return conn


def disconnect() -> None:
    global _CURRENT
    _CURRENT = None


class H2OConnection:
    """One server endpoint + auth. All verbs funnel through `request`,
    which retries transient failures under the shared runtime/retry policy:
    connection drops re-try idempotent verbs (GET/HEAD/DELETE) with capped
    jittered backoff, and HTTP 429 honors the serving engine's Retry-After
    hint on EVERY verb (the server shed the request before acting, so a
    POST re-send is safe). Semantic errors (4xx) fail fast unchanged."""

    def __init__(self, url: str, token: Optional[str] = None,
                 timeout: float = 600.0, verify_ssl: bool = True,
                 max_retries: Optional[int] = None):
        from .runtime import retry as _retrylib

        self.url = url.rstrip("/")
        self.token = token or os.environ.get("H2O3_AUTH_TOKEN")
        self.timeout = timeout
        self._retry = _retrylib.RetryPolicy(
            name="client", max_attempts=max_retries)
        self._batch: Optional[List[str]] = None   # pending Rapids assigns
        # trace() pins per THREAD: a connection shared across threads must
        # not leak one thread's pinned trace id into another's requests
        self._trace_tls = threading.local()
        self._ssl_ctx = None
        if url.startswith("https") and not verify_ssl:
            import ssl

            self._ssl_ctx = ssl.create_default_context()
            self._ssl_ctx.check_hostname = False
            self._ssl_ctx.verify_mode = ssl.CERT_NONE

    # -- plumbing -----------------------------------------------------------
    def request(self, method: str, path: str,
                params: Optional[Dict[str, Any]] = None,
                json_body: Optional[Dict[str, Any]] = None,
                data: Optional[bytes] = None,
                content_type: Optional[str] = None,
                raw: bool = False):
        """One HTTP round-trip. `raw=True` returns the body bytes verbatim
        (download routes: DownloadDataset, MOJO zips) — same auth headers
        and error mapping as JSON requests, so a 401/404/500 raises
        H2OServerError/H2OConnectionError instead of a bare urllib error."""
        # any real round-trip first lands pending batched munging assigns —
        # reads and training must see the chain's results
        self._flush_batch()
        url = self.url + path
        headers = {}
        if self.token:
            headers["Authorization"] = f"Bearer {self.token}"
        # request tracing (docs/observability.md): the CLIENT mints the
        # trace id — one fresh id per request, or the pinned id inside a
        # `with conn.trace():` block so a whole train+predict workflow
        # correlates into one server-side trace
        from .runtime import tracing as _tracing

        headers["X-H2O3-Trace-Id"] = (
            getattr(self._trace_tls, "tid", None) or _tracing.new_trace_id())
        if json_body is not None:
            data = json.dumps(json_body).encode()
            headers["Content-Type"] = "application/json"
        elif params is not None and method != "GET":
            data = urllib.parse.urlencode(
                {k: v for k, v in params.items() if v is not None}).encode()
            headers["Content-Type"] = "application/x-www-form-urlencoded"
        elif content_type:
            headers["Content-Type"] = content_type
        if method == "GET" and params:
            url += "?" + urllib.parse.urlencode(
                {k: v for k, v in params.items() if v is not None})
        req = urllib.request.Request(url, data=data, headers=headers,
                                     method=method)
        body = self._round_trip(req, method, path)
        if raw:
            return body
        return json.loads(body) if body else {}

    def _round_trip(self, req, method: str, path: str) -> bytes:
        """One logical request = up to max_attempts wire attempts.

        429 sleeps the server's Retry-After hint (any verb — admission
        shed the request at the door); connection-level drops back off and
        re-send only idempotent verbs. The retry budget and the policy's
        deadline bound total added latency either way."""
        from .runtime import faults as _faults
        from .runtime import retry as _retrylib

        pol = self._retry
        idempotent = method in ("GET", "HEAD", "DELETE")
        t0 = time.monotonic()
        delay = pol.base_delay_s
        attempt = 1
        _retrylib.record("client", "calls")
        while True:
            try:
                _faults.check("client.request", f"{method} {path}")
                with urllib.request.urlopen(req, timeout=self.timeout,
                                            context=self._ssl_ctx) as r:
                    body = r.read()
                if attempt > 1:
                    _retrylib.record("client", "recovered")
                return body
            except urllib.error.HTTPError as e:
                raw = e.read()        # once: the retry decision below must
                #                       not eat the final error's payload
                if e.code == 429 and attempt < pol.max_attempts:
                    hint = e.headers.get("Retry-After")
                    try:
                        wait = float(hint) if hint else pol.base_delay_s
                    except ValueError:
                        wait = pol.base_delay_s
                    if (time.monotonic() - t0 + wait <= pol.deadline_s
                            and pol.budget.try_spend()):
                        _retrylib.record("client", "retries")
                        time.sleep(wait)
                        attempt += 1
                        continue
                try:
                    payload = json.loads(raw)
                except Exception:
                    payload = e.reason
                _retrylib.record("client", "permanent_failures")
                raise H2OServerError(e.code, payload) from None
            except (urllib.error.URLError, OSError) as e:
                if idempotent and attempt < pol.max_attempts:
                    delay = pol.next_delay(delay)
                    if (time.monotonic() - t0 + delay <= pol.deadline_s
                            and pol.budget.try_spend()):
                        _retrylib.record("client", "retries")
                        time.sleep(delay)
                        attempt += 1
                        continue
                _retrylib.record("client", "permanent_failures")
                raise H2OConnectionError(
                    f"cannot reach {self.url}: {e}") from None

    # NB: the route argument is positional-only so request params named
    # "path" (e.g. /3/ImportFiles) can ride **params without colliding
    def get(self, path: str, /, **params) -> Dict:
        return self.request("GET", path, params=params or None)

    def post(self, path: str, /, **params) -> Dict:
        return self.request("POST", path, params=params)

    def delete(self, path: str, /) -> Dict:
        return self.request("DELETE", path)

    # -- cluster ------------------------------------------------------------
    def cluster_info(self) -> Dict:
        return self.get("/3/Cloud")

    # -- frames -------------------------------------------------------------
    @staticmethod
    def _parse_params(sep, col_names, col_types) -> Dict[str, str]:
        out = {}
        if sep:
            out["separator"] = sep
        if col_names:
            out["column_names"] = json.dumps(list(col_names))
        if col_types:
            out["column_types"] = json.dumps(col_types)
        return out

    def import_file(self, path: str, destination_frame: Optional[str] = None,
                    sep: Optional[str] = None, col_names=None,
                    col_types=None,
                    pattern: Optional[str] = None) -> "RemoteFrame":
        """Server-side import: the path is resolved ON the server
        (`/3/ImportFiles`, or `/3/Parse` when parse options are given —
        ImportFilesHandler / ParseHandler). `pattern` filters a directory
        import server-side."""
        opts = self._parse_params(sep, col_names, col_types)
        if opts or destination_frame:
            if pattern:
                raise ValueError(
                    "pattern= cannot be combined with parse options over a "
                    "connection (the /3/Parse route takes explicit files)")
            out = self.post("/3/Parse", source_frames=json.dumps([path]),
                            destination_frame=destination_frame, **opts)
            return RemoteFrame(self, out["destination_frame"]["name"])
        out = self.post("/3/ImportFiles", path=path, pattern=pattern)
        return RemoteFrame(self, out["destination_frames"][0])

    def upload_file(self, path: str, destination_frame: Optional[str] = None,
                    sep: Optional[str] = None, col_names=None,
                    col_types=None) -> "RemoteFrame":
        """Client-side upload: file bytes travel to the server
        (`/3/PostFile` + `/3/Parse` — PostFileHandler semantics)."""
        with open(path, "rb") as f:
            body = f.read()
        return self.upload_bytes(body, os.path.basename(path),
                                 destination_frame=destination_frame,
                                 sep=sep, col_names=col_names,
                                 col_types=col_types)

    def upload_bytes(self, body: bytes, name: str = "upload.csv",
                     destination_frame: Optional[str] = None,
                     sep: Optional[str] = None, col_names=None,
                     col_types=None) -> "RemoteFrame":
        up = self.request(
            "POST", f"/3/PostFile?destination_frame={urllib.parse.quote(name)}",
            data=body, content_type="application/octet-stream")
        server_path = up["destination_frame"]
        out = self.post("/3/Parse",
                        source_frames=json.dumps([server_path]),
                        destination_frame=destination_frame,
                        **self._parse_params(sep, col_names, col_types))
        return RemoteFrame(self, out["destination_frame"]["name"])

    def get_frame(self, key: str) -> "RemoteFrame":
        fr = RemoteFrame(self, key)
        fr._summary()                    # 404 now, not on first use
        return fr

    def rapids(self, ast: str, rows: Optional[int] = None) -> Dict:
        if (self._batch is not None and rows is None
                and ast.lstrip().startswith(("(assign ", "(rm "))):
            # inside a batch() block: defer munging assigns/removes — ship
            # later as ONE program. Value-returning expressions (scalars,
            # getTimeZone, ...) still execute eagerly: their caller needs
            # the result now.
            self._batch.append(ast)
            return {}
        body: Dict[str, Any] = {"ast": ast}
        if rows is not None:
            body["rows"] = rows
        return self.request("POST", "/99/Rapids", json_body=body)

    def _flush_batch(self) -> None:
        if not self._batch:
            return
        program = "\n".join(self._batch)
        self._batch.clear()   # before the POST: request() re-enters here
        self.request("POST", "/99/Rapids", json_body={"ast": program})

    @contextlib.contextmanager
    def trace(self, trace_id: Optional[str] = None):
        """Pin ONE trace id on every request inside the block (nestable;
        inner blocks win): `with conn.trace() as tid:` train + predict,
        then `GET /3/Trace?trace_id=tid` returns the whole correlated tree
        — request, job, candidate and batch spans under one id."""
        from .runtime import tracing as _tracing

        prev = getattr(self._trace_tls, "tid", None)
        self._trace_tls.tid = trace_id or _tracing.new_trace_id()
        try:
            yield self._trace_tls.tid
        finally:
            self._trace_tls.tid = prev

    def batch(self):
        """Deferred-munging context: inside `with conn.batch():` every
        Rapids assign a RemoteFrame op posts is buffered and shipped as one
        multi-statement program at the first read (or block exit) — a
        chained N-op munge costs ~1 round-trip instead of N (upstream's
        lazy ExprNode DAG collapses chains the same way;
        `water/rapids/Session.java` executes them sequentially)."""
        import contextlib

        @contextlib.contextmanager
        def _ctx():
            prev = self._batch
            self._batch = []
            try:
                yield self
                self._flush_batch()
            except BaseException:
                # land whatever the block chained before the error so
                # already-returned RemoteFrame handles stay valid; if the
                # flush itself fails, the original exception wins
                try:
                    self._flush_batch()
                except Exception:
                    pass
                raise
            finally:
                self._batch = prev

        return _ctx()

    # -- jobs ---------------------------------------------------------------
    def wait_for_job(self, job_key: str, poll: float = 0.2,
                     timeout: float = 3600.0) -> Dict:
        t0 = time.time()
        while True:
            j = self.get(f"/3/Jobs/{urllib.parse.quote(job_key)}")["jobs"][0]
            if j["status"] in ("DONE", "FAILED", "CANCELLED"):
                if j["status"] != "DONE":
                    raise RuntimeError(
                        f"job {job_key} {j['status']}: {j.get('warnings')}")
                return j
            if time.time() - t0 > timeout:
                # best-effort server-side cancel BEFORE raising: an
                # abandoned client poll must not strand device work on the
                # server (water.Job.stop discipline)
                try:
                    self.post(
                        f"/3/Jobs/{urllib.parse.quote(job_key)}/cancel")
                except Exception:
                    pass
                raise TimeoutError(f"job {job_key} still {j['status']} "
                                   f"after {timeout}s (server-side cancel "
                                   "requested)")
            time.sleep(poll)


class RemoteFrame:
    """A server-side Frame by key. Munging ops are eager Rapids assigns."""

    _is_remote = True

    def __init__(self, conn: H2OConnection, key: str):
        self.conn = conn
        self.key = key
        self._cached: Optional[Dict] = None

    # -- metadata -----------------------------------------------------------
    def _summary(self, rows: int = 10) -> Dict:
        if self._cached is None or rows > 10:
            out = self.conn.get(
                f"/3/Frames/{urllib.parse.quote(self.key)}/summary")
            self._cached = out["frames"][0]
        return self._cached

    def refresh(self) -> "RemoteFrame":
        self._cached = None
        return self

    @property
    def names(self) -> List[str]:
        return [c["label"] for c in self._summary()["columns"]]

    @property
    def columns(self) -> List[str]:
        return self.names

    @property
    def nrow(self) -> int:
        return self._summary()["rows"]

    @property
    def ncol(self) -> int:
        return self._summary()["num_columns"]

    @property
    def types(self) -> Dict[str, str]:
        return {c["label"]: c["type"] for c in self._summary()["columns"]}

    @property
    def shape(self):
        return (self.nrow, self.ncol)

    def __repr__(self):
        return f"<RemoteFrame {self.key!r} {self.nrow}x{self.ncol} @ {self.conn.url}>"

    # -- munging (each op = one Rapids assign, new server key) --------------
    _KEY_SEQ = iter(range(1, 1 << 62))

    def _derive(self, ast_fmt: str) -> "RemoteFrame":
        key = f"{self.key}_c{next(RemoteFrame._KEY_SEQ)}"
        self.conn.rapids(f"(assign {key} {ast_fmt})")
        return RemoteFrame(self.conn, key)

    def _col_indices(self, cols) -> List[int]:
        names = self.names
        if not isinstance(cols, (list, tuple)):
            cols = [cols]
        out = []
        for c in cols:
            out.append(c if isinstance(c, int) else names.index(c))
        return out

    def __getitem__(self, cols) -> "RemoteFrame":
        idx = " ".join(str(i) for i in self._col_indices(cols))
        return self._derive(f"(cols {self.key} [{idx}])")

    def __setitem__(self, name: str, col: "RemoteFrame") -> None:
        if not isinstance(col, RemoteFrame):
            raise TypeError("remote frames can only be assigned remote "
                            "columns (got %r)" % type(col).__name__)
        self.conn.rapids(
            f"(assign {self.key} (append {self.key} {col.key} '{name}'))")
        self._cached = None

    def asfactor(self) -> "RemoteFrame":
        return self._derive(f"(as.factor {self.key})")

    def asnumeric(self) -> "RemoteFrame":
        return self._derive(f"(as.numeric {self.key})")

    def drop(self, col) -> "RemoteFrame":
        keep = [i for i in range(self.ncol)
                if i not in set(self._col_indices(col))]
        idx = " ".join(str(i) for i in keep)
        return self._derive(f"(cols {self.key} [{idx}])")

    def head(self, rows: int = 10) -> List[Dict]:
        """First `rows` rows as a list of column dicts (capped server-side
        at 10k — DownloadDataset is the bulk path)."""
        out = self.conn.rapids(f"(assign {self.key} {self.key})",
                               rows=min(rows, 10_000))
        return out["columns"]

    def as_data_frame(self, use_pandas: bool = True):
        """Full frame contents via `/3/DownloadDataset` (CSV over the
        wire), as a pandas DataFrame (default, matching the local Frame
        and h2o-py) or dict-of-lists."""
        text = self.conn.request(
            "GET", f"/3/DownloadDataset?frame_id="
                   f"{urllib.parse.quote(self.key)}", raw=True).decode()
        import csv as _csv
        import io as _io

        rows = list(_csv.reader(_io.StringIO(text)))
        header, body = rows[0], rows[1:]
        types = self.types
        out: Dict[str, list] = {}
        for j, name in enumerate(header):
            vals = [r[j] if j < len(r) else "" for r in body]
            if types.get(name) in ("real", "int", "time"):
                vals = [float(v) if v != "" else float("nan") for v in vals]
            out[name] = vals
        if use_pandas:
            import pandas as pd

            return pd.DataFrame(out, columns=header)
        return out

    def delete(self) -> None:
        self.conn.delete(f"/3/Frames/{urllib.parse.quote(self.key)}")


class _RemoteMetrics:
    """Dict-backed ModelMetrics facade (auc()/rmse()/... accessors match
    the in-process metrics objects)."""

    def __init__(self, d: Dict):
        self._d = d or {}

    def _v(self, k):
        v = self._d.get(k)
        return float(v) if isinstance(v, (int, float)) else v

    def auc(self):
        return self._v("auc")

    def rmse(self):
        return self._v("rmse")

    def mse(self):
        return self._v("mse")

    def logloss(self):
        return self._v("logloss")

    def r2(self):
        return self._v("r2")

    def __getitem__(self, k):
        return self._d[k]

    def get(self, k, default=None):
        return self._d.get(k, default)

    def __repr__(self):
        keys = [k for k in ("auc", "rmse", "logloss", "mse")
                if self._d.get(k) is not None]
        return "<RemoteMetrics %s>" % ", ".join(
            f"{k}={self._d[k]:.5f}" for k in keys)


class RemoteModel:
    """A server-side model by id — the surface `H2OEstimator` delegates to
    (predict / model_performance / metric passthroughs), REST-backed."""

    _is_remote = True

    def __init__(self, conn: H2OConnection, model_id: str):
        self.conn = conn
        self.model_id = model_id
        self._cached: Optional[Dict] = None

    def _json(self) -> Dict:
        if self._cached is None:
            out = self.conn.get(
                f"/3/Models/{urllib.parse.quote(self.model_id)}")
            self._cached = out["models"][0]
        return self._cached

    @property
    def algo(self) -> str:
        return self._json()["algo"]

    @property
    def params(self) -> Dict[str, Any]:
        return {p["name"]: p.get("actual_value")
                for p in self._json().get("parameters", [])}

    def _metrics(self, which: str) -> _RemoteMetrics:
        return _RemoteMetrics(self._json()["output"].get(which) or {})

    def _m(self, valid=False, xval=False) -> _RemoteMetrics:
        if xval:
            return self._metrics("cross_validation_metrics")
        if valid:
            return self._metrics("validation_metrics")
        return self._metrics("training_metrics")

    def _metric(self, name, valid=False, xval=False, train=False):
        return getattr(self._m(valid=valid, xval=xval), name)()

    def auc(self, valid=False, xval=False, train=False):
        return self._metric("auc", valid, xval)

    def rmse(self, valid=False, xval=False, train=False):
        return self._metric("rmse", valid, xval)

    def mse(self, valid=False, xval=False, train=False):
        return self._metric("mse", valid, xval)

    def logloss(self, valid=False, xval=False, train=False):
        return self._metric("logloss", valid, xval)

    @property
    def training_metrics(self):
        return self._m()

    @property
    def validation_metrics(self):
        return self._m(valid=True)

    @property
    def scoring_history(self):
        from .models.model_base import ScoringHistory

        # same dual surface as local models: index the rows OR call it
        # for the h2o-py table form
        return ScoringHistory(
            self._json()["output"].get("scoring_history") or [])

    def varimp(self, use_pandas=False):
        return self._json()["output"].get("variable_importances")

    def predict(self, test_data: RemoteFrame) -> RemoteFrame:
        if not isinstance(test_data, RemoteFrame):
            raise TypeError("a remote model predicts on RemoteFrames "
                            "(import/upload through the connection)")
        out = self.conn.post(
            f"/3/Predictions/models/{urllib.parse.quote(self.model_id)}"
            f"/frames/{urllib.parse.quote(test_data.key)}")
        return RemoteFrame(self.conn, out["predictions_frame"]["name"])

    def model_performance(self, test_data: Optional[RemoteFrame] = None,
                          valid=False, xval=False) -> _RemoteMetrics:
        if test_data is None:
            return self._m(valid=valid, xval=xval)
        out = self.conn.post(
            f"/3/ModelMetrics/models/{urllib.parse.quote(self.model_id)}"
            f"/frames/{urllib.parse.quote(test_data.key)}")
        return _RemoteMetrics(out["model_metrics"][0])

    def download_mojo(self, path: str = ".",
                      filename: Optional[str] = None) -> str:
        """Fetch the model's MOJO artifact zip from the server
        (`GET /3/Models/{id}/mojo` — h2o-py `download_mojo`)."""
        blob = self.conn.request(
            "GET", f"/3/Models/{urllib.parse.quote(self.model_id)}/mojo",
            raw=True)
        if os.path.isdir(path) or not os.path.splitext(path)[1]:
            out = os.path.join(path, filename or f"{self.model_id}.h2o3")
        else:
            out = path
        os.makedirs(os.path.dirname(out) or ".", exist_ok=True)
        with open(out, "wb") as f:
            f.write(blob)
        return out

    def delete(self) -> None:
        self.conn.delete(f"/3/Models/{urllib.parse.quote(self.model_id)}")

    def __repr__(self):
        return f"<RemoteModel {self.model_id!r} @ {self.conn.url}>"


def encode_nondefault_params(parms: Dict[str, Any], cls) -> Dict[str, Any]:
    """Wire-encode an estimator param dict: drop Nones/defaults/private
    keys, JSON-encode containers AND bools (form-encoding a Python bool
    yields 'True', which json.loads rejects server-side, leaving a truthy
    string). ONE encoder shared by estimator and grid remote paths."""
    defaults = {**cls._common_defaults, **cls._param_defaults}
    out: Dict[str, Any] = {}
    for k, v in parms.items():
        if k.startswith("_") or v is None:
            continue
        # bool-aware equality: Python conflates 1==True / 0==False, which
        # would silently drop an explicitly-set int param whose default is
        # a bool (or vice versa) from the wire request
        if (k in defaults and defaults[k] == v
                and isinstance(v, bool) == isinstance(defaults[k], bool)):
            continue
        out[k] = (json.dumps(v) if isinstance(v, (list, tuple, dict, bool))
                  else v)
    return out


def remote_train(est, x: Optional[Sequence], y: Optional[str],
                 training_frame: RemoteFrame,
                 validation_frame: Optional[RemoteFrame] = None):
    """Train `est` (an H2OEstimator) against the frame's server: POST
    `/3/ModelBuilders/{algo}` with the non-default params, poll `/3/Jobs`,
    attach a `RemoteModel`. The estimator's delegation surface
    (auc/predict/model_performance/…) then works unchanged."""
    conn = training_frame.conn
    if validation_frame is not None and not isinstance(validation_frame,
                                                       RemoteFrame):
        raise TypeError(
            "validation_frame must be a RemoteFrame on the same server as "
            "training_frame (got a local %s — upload it first)"
            % type(validation_frame).__name__)
    params = encode_nondefault_params(est._parms, type(est))
    params["training_frame"] = training_frame.key
    if validation_frame is not None:
        params["validation_frame"] = validation_frame.key
    if y is not None:
        params["response_column"] = y
    if x is not None:
        params["x"] = json.dumps(list(x))
    out = conn.post(f"/3/ModelBuilders/{est.algo}", **params)
    job_key = out["job"]["key"]["name"]
    job = conn.wait_for_job(job_key)
    est._model = RemoteModel(conn, job["dest"]["name"])
    est.job = None
    return est
