"""H2OANOVAGLMEstimator — type-III ANOVA decomposition via GLM refits.

Reference parity: `h2o-algos/src/main/java/hex/anovaglm/ANOVAGLM.java`:
expand predictors (and interactions up to `highest_interaction_term`) into
effect terms, fit the full GLM, then refit with each term dropped; the
deviance increase gives a likelihood-ratio chi-square test per term
(`ANOVAGLMModel._result` table). Estimator surface
`h2o-py/h2o/estimators/anovaglm.py`.

Each refit is an independent small IRLS — the Gram einsum batches trivially,
so the whole table is a handful of compiled steps on device.
"""

from __future__ import annotations

import itertools
from typing import List, Optional

import numpy as np

from ..frame.frame import Frame
from .glm import H2OGeneralizedLinearEstimator
from .metrics import ModelMetricsBase
from .model_base import H2OEstimator, H2OModel, response_info

try:
    from scipy.stats import chi2 as _chi2

    def _chi2_sf(x, df):
        return float(_chi2.sf(x, df))
except ImportError:  # scipy not guaranteed — Wilson–Hilferty approximation
    def _chi2_sf(x, df):
        if df <= 0:
            return float("nan")
        z = ((x / df) ** (1 / 3) - (1 - 2 / (9 * df))) / np.sqrt(2 / (9 * df))
        return float(0.5 * np.erfc(z / np.sqrt(2))) if hasattr(np, "erfc") else float(
            0.5 * (1 - np.tanh(0.7978845608 * (z + 0.044715 * z**3)))
        )


def _deviance(family: str, y: np.ndarray, mu: np.ndarray, w: np.ndarray) -> float:
    mu = np.clip(mu, 1e-15, None)
    if family == "binomial":
        mu = np.clip(mu, 1e-15, 1 - 1e-15)
        return float(-2 * np.sum(w * (y * np.log(mu) + (1 - y) * np.log(1 - mu))))
    if family == "poisson":
        with np.errstate(divide="ignore", invalid="ignore"):
            t = np.where(y > 0, y * np.log(y / mu), 0.0)
        return float(2 * np.sum(w * (t - (y - mu))))
    return float(np.sum(w * (y - mu) ** 2))


class ANOVAGLMModel(H2OModel):
    algo = "anovaglm"

    def __init__(self, params, x, y, table, full_glm, terms, builder):
        super().__init__(params)
        self.x = list(x)
        self.y = y
        self._table = table
        self._full = full_glm
        self._terms = terms
        self._builder = builder

    def result(self) -> Frame:
        """The ANOVA table — model_names / degrees of freedom / SS-deviance /
        p-values (ANOVAGLMModel.result())."""
        return Frame.from_dict({
            "model": np.asarray([r["term"] for r in self._table], dtype=object),
            "df": np.asarray([r["df"] for r in self._table], np.float64),
            "deviance": np.asarray([r["deviance"] for r in self._table], np.float64),
            "p_value": np.asarray([r["p_value"] for r in self._table], np.float64),
        })

    def _as_design(self, frame: Frame) -> Frame:
        blocks = self._builder(frame, self._terms)
        X = np.concatenate([blocks[t] for t in self._terms], axis=1)
        return Frame.from_numpy(X.astype(np.float64),
                                names=[f"c{i}" for i in range(X.shape[1])])

    def predict(self, test_data: Frame) -> Frame:
        return self._full.predict(self._as_design(test_data))

    def _make_metrics(self, frame: Frame):
        fr = self._as_design(frame)
        fr[self.y] = np.asarray(frame.vec(self.y).data)
        if frame.vec(self.y).type == "enum":
            fr = fr.asfactor(self.y)
        return self._full.model._make_metrics(fr)


class H2OANOVAGLMEstimator(H2OEstimator):
    algo = "anovaglm"
    _param_defaults = dict(
        family="AUTO",
        link="family_default",
        lambda_=None,
        alpha=None,
        standardize=True,
        highest_interaction_term=2,
        type=3,
        early_stopping=False,
        save_transformed_framekeys=False,
    )

    def _terms(self, x: List[str]) -> List[tuple]:
        hi = int(self._parms.get("highest_interaction_term") or 2)
        hi = max(1, min(hi, len(x)))
        terms = []
        for k in range(1, hi + 1):
            terms += [t for t in itertools.combinations(x, k)]
        return terms

    def _build_design(self, train: Frame, terms) -> tuple:
        """Column blocks per term: numeric cols as-is, categoricals one-hot
        (drop-first), interactions as elementwise products of member blocks."""
        blocks = {}
        for t in terms:
            mats = []
            for c in t:
                v = train.vec(c)
                if v.type == "enum":
                    codes = np.asarray(v.data)
                    K = v.nlevels
                    oh = np.zeros((len(codes), max(K - 1, 1)))
                    for lvl in range(1, K):
                        oh[:, lvl - 1] = (codes == lvl).astype(np.float64)
                    mats.append(oh)
                else:
                    col = v.numeric_np()
                    mats.append(np.nan_to_num(col)[:, None])
            # interaction block = all pairwise products across member blocks
            out = mats[0]
            for m in mats[1:]:
                out = (out[:, :, None] * m[:, None, :]).reshape(len(m), -1)
            blocks[t] = out
        return blocks

    def _fit(self, x, y, train: Frame, valid: Optional[Frame]) -> ANOVAGLMModel:
        p = self._parms
        yvec = train.vec(y)
        problem, nclass, domain = response_info(yvec)
        family = p.get("family", "AUTO")
        if family == "AUTO":
            family = "binomial" if problem == "binomial" else "gaussian"
        if family == "binomial":
            yarr = (np.asarray(yvec.data, np.float64) if yvec.type == "enum"
                    else yvec.numeric_np())
        else:
            yarr = yvec.numeric_np()
        w = np.ones(train.nrow)

        terms = self._terms(list(x))
        blocks = self._build_design(train, terms)

        def fit_dev(active_terms) -> float:
            cols = [blocks[t] for t in active_terms]
            if not cols:
                X = np.zeros((train.nrow, 0))
            else:
                X = np.concatenate(cols, axis=1)
            names = [f"c{i}" for i in range(X.shape[1])]
            fr = Frame.from_numpy(X.astype(np.float64), names=names) if X.shape[1] else None
            if fr is None:
                mu = np.full(train.nrow, yarr.mean())
                return _deviance(family, yarr, mu, w)
            fr[y] = (np.asarray(yvec.data) if yvec.type == "enum" else yarr)
            if yvec.type == "enum":
                fr = fr.asfactor(y)
            g = H2OGeneralizedLinearEstimator(family=family, lambda_=0.0, standardize=False)
            g.train(x=names, y=y, training_frame=fr)
            mu = g.model._score(fr)
            return _deviance(family, yarr, mu, w)

        dev_full = fit_dev(terms)
        table = []
        for t in terms:
            others = [u for u in terms if u != t]
            dev_wo = fit_dev(others)
            df = blocks[t].shape[1]
            lr = max(dev_wo - dev_full, 0.0)
            table.append(dict(
                term=":".join(t), df=df, deviance=lr, p_value=_chi2_sf(lr, df)
            ))

        full_glm = H2OGeneralizedLinearEstimator(family=family, lambda_=0.0, standardize=False)
        Xf = np.concatenate([blocks[t] for t in terms], axis=1)
        names = [f"c{i}" for i in range(Xf.shape[1])]
        fr = Frame.from_numpy(Xf.astype(np.float64), names=names)
        fr[y] = np.asarray(yvec.data) if yvec.type == "enum" else yarr
        if yvec.type == "enum":
            fr = fr.asfactor(y)
        full_glm.train(x=names, y=y, training_frame=fr)

        model = ANOVAGLMModel(self, x, y, table, full_glm, terms, self._build_design)
        model.training_metrics = ModelMetricsBase(nobs=train.nrow)
        return model


ANOVAGLM = H2OANOVAGLMEstimator
