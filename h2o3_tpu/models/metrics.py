"""ModelMetrics — per-problem metric hierarchy.

Reference parity: `h2o-core/src/main/java/hex/ModelMetrics*.java`
(`ModelMetricsBinomial`, `ModelMetricsMultinomial`, `ModelMetricsRegression`,
`ModelMetricsClustering`), `hex/AUC2.java` (threshold-binned ROC: 400-bin
score histogram → AUC / pr-AUC / max-F1 and friends), `hex/ConfusionMatrix.java`.

The reference computes these inside scoring MRTasks via
`ModelMetrics.MetricBuilder` map/reduce; here the reductions are numpy on
gathered predictions (cheap relative to training) with the same binned-AUC
design available for the distributed path. Gini = 2·AUC−1 as in AUC2.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional

import numpy as np

MAX_AUC_BINS = 400  # AUC2.NBINS


def roc_curve_binned(y: np.ndarray, p: np.ndarray, nbins: int = MAX_AUC_BINS):
    """AUC2's design: histogram scores into <=400 threshold bins, then sweep."""
    y = np.asarray(y).astype(np.float64)
    p = np.asarray(p).astype(np.float64)
    qs = np.unique(np.quantile(p, np.linspace(0, 1, nbins)))
    bins = np.searchsorted(qs, p, side="left")
    npos = np.bincount(bins, weights=y, minlength=len(qs) + 1)
    nneg = np.bincount(bins, weights=1 - y, minlength=len(qs) + 1)
    # descending threshold sweep
    tp = np.cumsum(npos[::-1])[::-1]
    fp = np.cumsum(nneg[::-1])[::-1]
    P, Ntot = y.sum(), (1 - y).sum()
    tpr = tp / max(P, 1e-12)
    fpr = fp / max(Ntot, 1e-12)
    return qs, tpr, fpr, tp, fp, P, Ntot


def auc_exact(y: np.ndarray, p: np.ndarray) -> float:
    """Exact rank AUC (ties handled) — matches AUC2 in the limit of one bin
    per distinct score."""
    y = np.asarray(y).astype(np.float64)
    order = np.argsort(p, kind="mergesort")
    ranks = np.empty_like(order, dtype=np.float64)
    ranks[order] = np.arange(1, len(p) + 1)
    # average ranks over ties (vectorized run-length expansion)
    ps = np.asarray(p)[order]
    uniq, start = np.unique(ps, return_index=True)
    end = np.append(start[1:], len(ps))
    avg = (start + 1 + end) / 2.0
    tie_rank = np.repeat(avg, end - start)
    r = np.empty_like(tie_rank)
    r[order] = tie_rank
    npos = y.sum()
    nneg = len(y) - npos
    if npos == 0 or nneg == 0:
        return float("nan")
    return float((r[y == 1].sum() - npos * (npos + 1) / 2) / (npos * nneg))


class MetricValue(float):
    """Float that is also callable with h2o-py's method signature.

    h2o-py exposes metrics as methods (`perf.auc()`, `perf.rmse()`) while the
    internal code reads attributes (`m.auc`); wrapping plain-float fields in
    this keeps both call styles working.
    """

    __slots__ = ()

    def __call__(self, *_a, **_kw) -> float:
        return float(self)


@dataclass
class ModelMetricsBase:
    mse: float = float("nan")
    rmse: float = float("nan")
    nobs: int = 0
    description: str = ""

    def __setattr__(self, k, v):
        # dataclass __init__ assigns via setattr, so this wraps both
        # construction and later post-hoc assignments (e.g. KMeans metrics)
        if isinstance(v, (float, np.floating)) \
                and not isinstance(v, MetricValue):
            v = MetricValue(v)
        object.__setattr__(self, k, v)

    def _ser(self) -> Dict:
        return {k: float(v) if isinstance(v, MetricValue) else v
                for k, v in self.__dict__.items() if not k.startswith("_")}


@dataclass
class ModelMetricsRegression(ModelMetricsBase):
    mae: float = float("nan")
    rmsle: float = float("nan")
    r2: float = float("nan")
    mean_residual_deviance: float = float("nan")

    @staticmethod
    def make(y: np.ndarray, pred: np.ndarray) -> "ModelMetricsRegression":
        y = np.asarray(y, np.float64)
        pred = np.asarray(pred, np.float64)
        err = pred - y
        mse = float(np.mean(err**2))
        with np.errstate(invalid="ignore"):
            rmsle = (
                float(np.sqrt(np.mean((np.log1p(pred) - np.log1p(y)) ** 2)))
                if (pred > -1).all() and (y > -1).all()
                else float("nan")
            )
        var = float(np.var(y))
        return ModelMetricsRegression(
            mse=mse, rmse=float(np.sqrt(mse)), nobs=len(y),
            mae=float(np.mean(np.abs(err))), rmsle=rmsle,
            r2=1.0 - mse / var if var > 0 else float("nan"),
            mean_residual_deviance=mse,
        )


def gains_lift_table(y: np.ndarray, p: np.ndarray, groups: int = 16):
    """Quantile gains/lift table — `hex/GainsLift.java` (16 groups default):
    per group cumulative capture rate, lift, response rate."""
    y = np.asarray(y, np.float64)
    order = np.argsort(-np.asarray(p), kind="mergesort")
    ys = y[order]
    ps = np.asarray(p)[order]
    n = len(ys)
    total_pos = max(ys.sum(), 1e-12)
    bounds = np.unique((np.arange(1, groups + 1) * n) // groups)
    bounds = bounds[bounds > 0]  # n < groups would emit an empty first group
    rows = []
    prev = 0
    cum_pos = 0.0
    overall_rate = total_pos / n
    for b in bounds:
        grp = ys[prev:b]
        s = grp.sum()
        cum_pos += s
        rate = s / max(len(grp), 1)
        rows.append(dict(
            group=len(rows) + 1,
            cumulative_data_fraction=b / n,
            lower_threshold=float(ps[b - 1]),
            lift=float(rate / overall_rate),
            cumulative_lift=float((cum_pos / b) / overall_rate),
            response_rate=float(rate),
            cumulative_response_rate=float(cum_pos / b),
            capture_rate=float(s / total_pos),
            cumulative_capture_rate=float(cum_pos / total_pos),
            gain=100.0 * (rate / overall_rate - 1),
            cumulative_gain=100.0 * ((cum_pos / b) / overall_rate - 1),
        ))
        prev = b
    return rows


@dataclass
class ModelMetricsBinomial(ModelMetricsBase):
    auc: float = float("nan")
    pr_auc: float = float("nan")
    logloss: float = float("nan")
    gini: float = float("nan")
    mean_per_class_error: float = float("nan")
    f1: float = float("nan")
    accuracy: float = float("nan")
    confusion_matrix: Optional[np.ndarray] = None
    threshold: float = 0.5
    gains_lift_table: Optional[List[Dict]] = None
    _roc: Optional[tuple] = None

    def gains_lift(self):
        return self.gains_lift_table

    def roc(self):
        """(fpr, tpr) arrays over the binned threshold sweep (AUC2)."""
        return self._roc

    @staticmethod
    def make(y: np.ndarray, p: np.ndarray) -> "ModelMetricsBinomial":
        y = np.asarray(y, np.float64)
        p = np.clip(np.asarray(p, np.float64), 1e-15, 1 - 1e-15)
        auc = auc_exact(y, p)
        logloss = float(-np.mean(y * np.log(p) + (1 - y) * np.log(1 - p)))
        mse = float(np.mean((p - y) ** 2))
        # max-F1 threshold via the AUC2-style binned sweep
        qs, tpr, fpr, tp, fp, P, Ntot = roc_curve_binned(y, p)
        fn = P - tp
        prec = tp / np.maximum(tp + fp, 1e-12)
        rec = tp / max(P, 1e-12)
        f1s = 2 * prec * rec / np.maximum(prec + rec, 1e-12)
        bi = int(np.argmax(f1s))
        thr = float(qs[min(bi, len(qs) - 1)]) if len(qs) else 0.5
        yhat = (p >= thr).astype(np.float64)
        tp_, fp_ = float(((yhat == 1) & (y == 1)).sum()), float(((yhat == 1) & (y == 0)).sum())
        tn_, fn_ = float(((yhat == 0) & (y == 0)).sum()), float(((yhat == 0) & (y == 1)).sum())
        cm = np.asarray([[tn_, fp_], [fn_, tp_]])
        err0 = fp_ / max(tn_ + fp_, 1e-12)
        err1 = fn_ / max(tp_ + fn_, 1e-12)
        # pr_auc by trapezoid over recall
        order = np.argsort(rec)
        pr_auc = float(np.trapezoid(prec[order], rec[order])) if len(rec) > 1 else float("nan")
        return ModelMetricsBinomial(
            mse=mse, rmse=float(np.sqrt(mse)), nobs=len(y),
            auc=auc, pr_auc=pr_auc, logloss=logloss, gini=2 * auc - 1,
            mean_per_class_error=(err0 + err1) / 2, f1=float(f1s[bi]),
            accuracy=float((yhat == y).mean()), confusion_matrix=cm, threshold=thr,
            gains_lift_table=gains_lift_table(y, p),
            _roc=(fpr, tpr),
        )

    @staticmethod
    def from_binned(qs: np.ndarray, npos: np.ndarray, nneg: np.ndarray,
                    nll_sum: float, sq_sum: float) -> "ModelMetricsBinomial":
        """Metrics from a 400-bin score histogram — `hex/AUC2.java`'s exact
        design: every statistic (AUC/pr-AUC/max-F1/CM/gains) derives from
        per-threshold-bin (pos, neg) counts, so only ~KBs ever leave the
        device. The AUC is the binned trapezoid, which IS the reference's
        reported AUC semantics (AUC2 sweeps its 400 bins the same way)."""
        qs = np.asarray(qs, np.float64)
        npos = np.asarray(npos, np.float64)
        nneg = np.asarray(nneg, np.float64)
        # merge bins with duplicate thresholds (host roc_curve_binned
        # np.unique semantics: ties collapse into one bin)
        uq = np.unique(qs)
        npos_m = np.zeros(len(uq) + 1)
        nneg_m = np.zeros(len(uq) + 1)
        # bin b of searchsorted(qs,...) maps to searchsorted(uq,...) bins
        edge_map = np.searchsorted(uq, qs, side="left")
        full_map = np.concatenate([edge_map, [len(uq)]])
        np.add.at(npos_m, full_map, npos)
        np.add.at(nneg_m, full_map, nneg)
        npos, nneg, qs = npos_m, nneg_m, uq
        P = float(npos.sum())
        Ntot = float(nneg.sum())
        n = P + Ntot
        tp = np.cumsum(npos[::-1])[::-1]
        fp = np.cumsum(nneg[::-1])[::-1]
        tpr = tp / max(P, 1e-12)
        fpr = fp / max(Ntot, 1e-12)
        order = np.argsort(fpr)
        auc = float(np.trapezoid(
            np.r_[0.0, tpr[order], 1.0], np.r_[0.0, fpr[order], 1.0]))
        prec = tp / np.maximum(tp + fp, 1e-12)
        rec = tpr
        f1s = 2 * prec * rec / np.maximum(prec + rec, 1e-12)
        bi = int(np.argmax(f1s))
        thr = float(qs[min(bi, len(qs) - 1)]) if len(qs) else 0.5
        # confusion at the max-F1 threshold straight from the sweep counts
        tp_, fp_ = float(tp[bi]), float(fp[bi])
        fn_, tn_ = P - tp_, Ntot - fp_
        cm = np.asarray([[tn_, fp_], [fn_, tp_]])
        err0 = fp_ / max(tn_ + fp_, 1e-12)
        err1 = fn_ / max(tp_ + fn_, 1e-12)
        oi = np.argsort(rec)
        pr_auc = (float(np.trapezoid(prec[oi], rec[oi]))
                  if len(rec) > 1 else float("nan"))
        # gains/lift from the bin counts (16 cumulative-count groups)
        glt = []
        tot = npos + nneg
        cum_rows = np.cumsum(tot[::-1])[::-1]          # rows scored >= bin
        cum_pos = tp
        prev_rows = prev_pos = 0.0
        for gidx in range(1, 17):
            target = n * gidx / 16.0
            # the group boundary may fall INSIDE a tied-score block (bins
            # cannot split ties); split the block fractionally, assuming a
            # uniform positive rate within it — the expectation of the
            # exact-sort table's arbitrary tie ordering
            sel = int(np.searchsorted(-cum_rows, -target, side="left"))
            b = min(max(sel - 1, 0), len(tot) - 1)
            if cum_rows[b] < target and b > 0:
                b -= 1
            rows_above = float(cum_rows[b + 1]) if b + 1 < len(tot) else 0.0
            pos_above = float(cum_pos[b + 1]) if b + 1 < len(tot) else 0.0
            blk_rows = max(float(cum_rows[b]) - rows_above, 1e-12)
            blk_pos = float(cum_pos[b]) - pos_above
            f = min(max((target - rows_above) / blk_rows, 0.0), 1.0)
            rows = target
            pos = pos_above + f * blk_pos
            frac = rows / max(n, 1e-12)
            capture = pos / max(P, 1e-12)
            g_rows = max(rows - prev_rows, 0.0)
            g_pos = max(pos - prev_pos, 0.0)
            g_cap = g_pos / max(P, 1e-12)
            g_frac = g_rows / max(n, 1e-12)
            cum_lift = capture / max(frac, 1e-12)
            lift = g_cap / max(g_frac, 1e-12)
            glt.append(dict(
                group=gidx, cumulative_data_fraction=frac,
                lower_threshold=float(qs[min(b, len(qs) - 1)]) if len(qs)
                else 0.0,
                lift=lift, cumulative_lift=cum_lift,
                response_rate=g_pos / max(g_rows, 1e-12),
                cumulative_response_rate=pos / max(rows, 1e-12),
                capture_rate=g_cap, cumulative_capture_rate=capture,
                gain=100.0 * (lift - 1.0),
                cumulative_gain=100.0 * (cum_lift - 1.0),
            ))
            prev_rows, prev_pos = rows, pos
        mse = sq_sum / max(n, 1e-12)
        return ModelMetricsBinomial(
            mse=mse, rmse=float(np.sqrt(mse)), nobs=int(n),
            auc=auc, pr_auc=pr_auc, logloss=nll_sum / max(n, 1e-12),
            gini=2 * auc - 1,
            mean_per_class_error=(err0 + err1) / 2, f1=float(f1s[bi]),
            accuracy=(tp_ + tn_) / max(n, 1e-12),
            confusion_matrix=cm, threshold=thr,
            gains_lift_table=glt,
            _roc=(fpr, tpr),
        )


@dataclass
class ModelMetricsMultinomial(ModelMetricsBase):
    logloss: float = float("nan")
    mean_per_class_error: float = float("nan")
    accuracy: float = float("nan")
    confusion_matrix: Optional[np.ndarray] = None

    @staticmethod
    def make(y: np.ndarray, probs: np.ndarray) -> "ModelMetricsMultinomial":
        y = np.asarray(y).astype(np.int64)
        probs = np.clip(np.asarray(probs, np.float64), 1e-15, 1.0)
        probs = probs / probs.sum(axis=1, keepdims=True)
        K = probs.shape[1]
        n = len(y)
        logloss = float(-np.mean(np.log(probs[np.arange(n), y])))
        yhat = probs.argmax(axis=1)
        cm = np.zeros((K, K))
        np.add.at(cm, (y, yhat), 1)
        with np.errstate(invalid="ignore"):
            per_class_err = 1 - np.diag(cm) / np.maximum(cm.sum(axis=1), 1e-12)
        onehot = np.zeros((n, K))
        onehot[np.arange(n), y] = 1
        mse = float(np.mean((probs - onehot) ** 2))
        return ModelMetricsMultinomial(
            mse=mse, rmse=float(np.sqrt(mse)), nobs=n, logloss=logloss,
            mean_per_class_error=float(np.nanmean(per_class_err)),
            accuracy=float((yhat == y).mean()), confusion_matrix=cm,
        )


@dataclass
class ModelMetricsClustering(ModelMetricsBase):
    tot_withinss: float = float("nan")
    betweenss: float = float("nan")
    totss: float = float("nan")


def ndcg_at_k(y: np.ndarray, score: np.ndarray, qid: np.ndarray, k: int = 10) -> float:
    """NDCG@k grouped by query — the lambdarank objective's eval metric
    (XGBoost `rank:ndcg`, used by the MSLR-WEB30K baseline config)."""
    total, nq = 0.0, 0
    for q in np.unique(qid):
        m = qid == q
        rel = np.asarray(y)[m]
        s = np.asarray(score)[m]
        if len(rel) < 2:
            continue
        order = np.argsort(-s, kind="mergesort")
        gains = (2 ** rel[order][:k] - 1) / np.log2(np.arange(2, min(k, len(rel)) + 2))
        ideal = np.sort(rel)[::-1]
        igains = (2 ** ideal[:k] - 1) / np.log2(np.arange(2, min(k, len(rel)) + 2))
        if igains.sum() > 0:
            total += gains.sum() / igains.sum()
            nq += 1
    return total / max(nq, 1)
