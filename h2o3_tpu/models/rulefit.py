"""H2ORuleFitEstimator — interpretable rules + linear terms (Friedman RuleFit).

Reference parity: `h2o-algos/src/main/java/hex/rulefit/RuleFit.java` +
`hex/rulefit/RuleExtractor.java`: train tree ensembles at depths
`min_rule_length`..`max_rule_length` (`algorithm` ∈ {AUTO, DRF, GBM}),
extract every leaf's root→leaf condition conjunction as a binary rule
feature, optionally append winsorized linear terms (`model_type`), then fit
a sparse LASSO GLM over rules+linear and report the surviving rules in
`rule_importance()`. Estimator surface `h2o-py/h2o/estimators/rulefit.py`.

The rule ensembles ride the same tpu_hist heap-tree engine as GBM/DRF; rule
evaluation over rows is an elementwise compare+AND, and the LASSO is the
GLM lambda-search path (one Gram einsum per IRLS step).
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np

from ..frame.frame import Frame
from .drf import H2ORandomForestEstimator
from .gbm import H2OGradientBoostingEstimator
from .glm import H2OGeneralizedLinearEstimator
from .model_base import H2OEstimator, H2OModel, response_info


class Rule:
    """Conjunction of (feature_name, thr, is_right) conditions."""

    __slots__ = ("conds", "support", "coef")

    def __init__(self, conds: Tuple):
        self.conds = conds
        self.support = 0.0
        self.coef = 0.0

    def key(self):
        return self.conds

    def describe(self) -> str:
        parts = []
        for fname, thr, right in self.conds:
            parts.append(f"({fname} > {thr:.6g} or NA)" if right
                         else f"({fname} <= {thr:.6g})")
        return " & ".join(parts)

    def evaluate(self, X: np.ndarray, col_of: dict) -> np.ndarray:
        m = np.ones(X.shape[0], bool)
        for fname, thr, right in self.conds:
            col = X[:, col_of[fname]]
            if right:
                m &= np.isnan(col) | (col > thr)
            else:
                m &= ~np.isnan(col) & (col <= thr)
        return m.astype(np.float64)


def _extract_rules(model, x: List[str], max_len: int) -> List[Rule]:
    """Walk each stacked heap tree; every effective leaf (non-split node whose
    ancestors all split) yields one rule — RuleExtractor semantics."""
    rules = []
    for stacked in model.forest:
        nt = stacked.feat.shape[0]
        feat = np.asarray(stacked.feat)
        thr = np.asarray(stacked.thr)
        issp = np.asarray(stacked.is_split)
        T = feat.shape[1]
        for t in range(nt):
            stack = [(0, ())]
            while stack:
                node, conds = stack.pop()
                if node < T and issp[t, node] and len(conds) < max_len:
                    fname = x[int(feat[t, node])]
                    tv = float(thr[t, node])
                    stack.append((2 * node + 1, conds + ((fname, tv, False),)))
                    stack.append((2 * node + 2, conds + ((fname, tv, True),)))
                elif conds:
                    rules.append(Rule(conds))
    return rules


class RuleFitModel(H2OModel):
    algo = "rulefit"

    def __init__(self, params, x, y, rules, lin_cols, lin_stats, glm, domain,
                 problem):
        super().__init__(params)
        self.x = list(x)
        self.y = y
        self.rules = rules           # kept rules (nonzero coef)
        self.lin_cols = lin_cols     # linear-term column names
        self.lin_stats = lin_stats   # col -> (q05, q95, std)
        self._glm = glm              # fitted sparse GLM over [rules | linear]
        self.domain = domain
        self.problem = problem
        self._col_of = {n: i for i, n in enumerate(self.x)}

    def _matrix(self, frame: Frame) -> np.ndarray:
        cols = [frame.vec(n).numeric_np() for n in self.x]
        return np.column_stack(cols) if cols else np.zeros((frame.nrow, 0))

    def _features(self, frame: Frame) -> Frame:
        X = self._matrix(frame)
        d = {}
        for i, r in enumerate(self.rules):
            d[f"rule_{i}"] = r.evaluate(X, self._col_of)
        for c in self.lin_cols:
            lo, hi, sd = self.lin_stats[c]
            col = np.clip(np.nan_to_num(frame.vec(c).numeric_np()), lo, hi)
            d[f"linear.{c}"] = 0.4 * col / max(sd, 1e-12)
        return Frame.from_dict(d)

    def rule_importance(self) -> Frame:
        imp = [(f"rule_{i}", r.coef, r.describe(), r.support)
               for i, r in enumerate(self.rules) if abs(r.coef) > 1e-10]
        coefs = self._glm.coef()
        for c in self.lin_cols:
            v = coefs.get(f"linear.{c}", 0.0)
            if v:
                imp.append((f"linear.{c}", v, f"linear({c})", float("nan")))
        imp.sort(key=lambda t: -abs(t[1]))
        return Frame.from_dict({
            "variable": np.asarray([i[0] for i in imp], dtype=object),
            "coefficient": np.asarray([i[1] for i in imp], np.float64),
            "rule": np.asarray([i[2] for i in imp], dtype=object),
            "support": np.asarray([i[3] for i in imp], np.float64),
        })

    def predict(self, test_data: Frame) -> Frame:
        return self._glm.predict(self._features(test_data))

    def _make_metrics(self, frame: Frame):
        fr = self._features(frame)
        yv = frame.vec(self.y)
        fr[self.y] = np.asarray(yv.data) if yv.type == "enum" else yv.numeric_np()
        if yv.type == "enum":
            fr = fr.asfactor(self.y)
        return self._glm.model._make_metrics(fr)


class H2ORuleFitEstimator(H2OEstimator):
    algo = "rulefit"
    _param_defaults = dict(
        algorithm="AUTO",          # AUTO→DRF
        min_rule_length=3,
        max_rule_length=3,
        max_num_rules=-1,
        model_type="rules_and_linear",
        rule_generation_ntrees=50,
        distribution="AUTO",
        remove_duplicates=True,
        lambda_=None,
    )

    def _fit(self, x, y, train: Frame, valid: Optional[Frame]) -> RuleFitModel:
        from .model_base import warn_host_solver

        warn_host_solver('rulefit', train.nrow, 500000)
        p = self._parms
        yvec = train.vec(y)
        problem, nclass, domain = response_info(yvec)
        if problem == "multinomial":
            raise ValueError("rulefit supports binomial/regression responses")
        # numeric-only features for rule conditions (categoricals enter trees
        # as codes in frame_to_matrix; condition thresholds stay on codes)
        model_type = str(p.get("model_type", "rules_and_linear"))
        want_rules = "rules" in model_type
        want_linear = "linear" in model_type

        lo_d = int(p.get("min_rule_length", 3))
        hi_d = int(p.get("max_rule_length", 3))
        depths = list(range(min(lo_d, hi_d), max(lo_d, hi_d) + 1))
        ntrees_total = int(p.get("rule_generation_ntrees", 50))
        per_depth = max(1, ntrees_total // max(len(depths), 1))
        algo = str(p.get("algorithm", "AUTO")).upper()
        TreeEst = H2OGradientBoostingEstimator if algo == "GBM" else H2ORandomForestEstimator
        seed = int(self._parms.get("_actual_seed", 1234))

        rules: List[Rule] = []
        if want_rules:
            for d in depths:
                est = TreeEst(ntrees=per_depth, max_depth=d, seed=seed + d)
                est.train(x=x, y=y, training_frame=train)
                rules += _extract_rules(est.model, x, d)

        col_of = {n: i for i, n in enumerate(x)}
        X = np.column_stack([train.vec(n).numeric_np() for n in x])
        # dedupe + drop degenerate (all-0/all-1) rules
        seen = {}
        kept: List[Rule] = []
        feats = {}
        for r in rules:
            k = r.key()
            if k in seen:
                continue
            seen[k] = True
            v = r.evaluate(X, col_of)
            s = v.mean()
            if s <= 0.0 or s >= 1.0:
                continue
            r.support = float(s)
            feats[f"rule_{len(kept)}"] = v
            kept.append(r)

        lin_cols, lin_stats = [], {}
        if want_linear:
            for c in x:
                v = train.vec(c)
                if v.type == "enum":
                    continue
                col = v.numeric_np()
                ok = col[~np.isnan(col)]
                if ok.size == 0 or ok.min() == ok.max():
                    continue
                lo, hi = np.quantile(ok, [0.025, 0.975])
                sd = float(np.std(np.clip(ok, lo, hi)))
                lin_cols.append(c)
                lin_stats[c] = (float(lo), float(hi), sd)
                feats[f"linear.{c}"] = 0.4 * np.clip(np.nan_to_num(col), lo, hi) / max(sd, 1e-12)

        if not feats:
            raise ValueError("rulefit: no usable rule/linear features")
        fr = Frame.from_dict(feats)
        fr[y] = np.asarray(yvec.data) if yvec.type == "enum" else yvec.numeric_np()
        if yvec.type == "enum":
            fr = fr.asfactor(y)

        family = "binomial" if problem == "binomial" else "gaussian"
        glm = H2OGeneralizedLinearEstimator(
            family=family, alpha=1.0, lambda_search=True, standardize=False,
        )
        glm.train(x=list(feats.keys()), y=y, training_frame=fr)

        # honour max_num_rules by walking the lambda path to the largest
        # lambda whose active set fits (RuleFit's rule-count control)
        max_rules = int(p.get("max_num_rules", -1))
        gm = glm.model
        feat_names = list(feats.keys())
        is_rule = np.asarray([nm.startswith("rule_") for nm in feat_names])
        if max_rules > 0 and gm.full_path is not None:
            # last path entry whose ACTIVE RULE set fits = best (smallest)
            # eligible lambda (path is ordered lambda_max → lambda_min);
            # linear terms don't count against the rule budget
            chosen = None
            for lam, beta in gm.full_path:
                nnz_rules = int((np.abs(beta[:-1])[is_rule] > 1e-10).sum())
                if nnz_rules <= max_rules:
                    chosen = (lam, beta)
            if chosen is not None:
                gm.beta = chosen[1]
                gm.lambda_best = chosen[0]
                # metrics must describe the beta predict() will use
                gm.training_metrics = gm._make_metrics(fr)

        coefs = gm.coef()
        for i, r in enumerate(kept):
            r.coef = float(coefs.get(f"rule_{i}", 0.0))
        survivors = [r for r in kept if abs(r.coef) > 1e-10]
        # re-index survivor features and refit-free: keep the glm but rebuild
        # the model's rule list aligned to the original feature names
        model = RuleFitModel(self, x, y, kept, lin_cols, lin_stats, glm,
                             domain, problem)
        # expose only surviving rules in importance; evaluation keeps all
        model._survivors = survivors
        model.training_metrics = gm.training_metrics
        if valid is not None:
            model.validation_metrics = model._make_metrics(valid)
        return model

    def _cv_predict(self, model: RuleFitModel, frame: Frame) -> np.ndarray:
        return model._glm._cv_predict(model._glm.model, model._features(frame))


RuleFit = H2ORuleFitEstimator
