"""H2ORandomForestEstimator — Distributed Random Forest (and XRT).

Reference parity: `h2o-algos/src/main/java/hex/tree/drf/DRF.java` /
`DRFModel.java` — bootstrap row sampling (sample_rate 0.632), per-split
`mtries` column sampling, vote-averaged scoring; XRT = DRF with
`histogram_type=Random` (`ai.h2o.automl` XRT step). Estimator surface:
`h2o-py/h2o/estimators/random_forest.py`.

Round-1 note: training metrics are in-bag (the reference reports OOB);
OOB scoring is tracked for a follow-up round.
"""

from __future__ import annotations

from .shared_tree import H2OSharedTreeEstimator


class H2ORandomForestEstimator(H2OSharedTreeEstimator):
    algo = "drf"
    _mode = "drf"
    _param_defaults = dict(
        ntrees=50,
        max_depth=20,
        min_rows=1.0,
        nbins=20,
        nbins_cats=1024,
        nbins_top_level=1024,
        mtries=-1,
        sample_rate=0.632,
        sample_rate_per_class=None,
        col_sample_rate_change_per_level=1.0,
        col_sample_rate_per_tree=1.0,
        min_split_improvement=1e-5,
        histogram_type="AUTO",
        hist_method="auto",  # auto|onehot|segment|pallas|pallas_factored (tpu_hist strategy)
        distribution="AUTO",
        binomial_double_trees=False,
        score_tree_interval=0,
        balance_classes=False,
        class_sampling_factors=None,
        max_after_balance_size=5.0,
        build_tree_one_node=False,
        calibrate_model=False,
        calibration_frame=None,
        calibration_method="AUTO",
        reg_lambda=None,
    )


H2OXGBRandomForestEstimator = H2ORandomForestEstimator  # alias convenience
DRF = H2ORandomForestEstimator
