"""H2ORandomForestEstimator — Distributed Random Forest (and XRT).

Reference parity: `h2o-algos/src/main/java/hex/tree/drf/DRF.java` /
`DRFModel.java` — bootstrap row sampling (sample_rate 0.632), per-split
`mtries` column sampling, vote-averaged scoring; XRT = DRF with
`histogram_type=Random` (`ai.h2o.automl` XRT step). Estimator surface:
`h2o-py/h2o/estimators/random_forest.py`.

Training metrics are OOB (out-of-bag prediction sums/counts accumulated
per row during the forest build — `shared_tree.py` oob_sum/oob_cnt), as
the reference reports; see `tests/test_gbm.py::test_drf_oob_training_metrics`.
"""

from __future__ import annotations

from .shared_tree import H2OSharedTreeEstimator


class H2ORandomForestEstimator(H2OSharedTreeEstimator):
    algo = "drf"
    _mode = "drf"
    _param_defaults = dict(
        ntrees=50,
        max_depth=20,
        min_rows=1.0,
        nbins=20,
        nbins_cats=1024,
        nbins_top_level=1024,
        mtries=-1,
        sample_rate=0.632,
        sample_rate_per_class=None,
        col_sample_rate_change_per_level=1.0,
        col_sample_rate_per_tree=1.0,
        min_split_improvement=1e-5,
        histogram_type="AUTO",
        hist_method="auto",  # auto|onehot|segment|pallas|pallas_factored (tpu_hist strategy)
        distribution="AUTO",
        binomial_double_trees=False,
        score_tree_interval=0,
        balance_classes=False,
        class_sampling_factors=None,
        max_after_balance_size=5.0,
        build_tree_one_node=False,
        calibrate_model=False,
        calibration_frame=None,
        calibration_method="AUTO",
        reg_lambda=None,
    )


H2OXGBRandomForestEstimator = H2ORandomForestEstimator  # alias convenience
DRF = H2ORandomForestEstimator
