"""Path-dependent TreeSHAP over heap forests — `predict_contributions`.

Reference parity: `h2o-genmodel/src/main/java/hex/genmodel/algos/tree/
TreeSHAP.java` (per-row recursive SHAP with the EXTEND/UNWIND path weights
of Lundberg et al., "Consistent Individualized Feature Attribution for Tree
Ensembles") feeding `Model.scoreContributions` (hex/Model.java, the
`predict_contributions` REST/Python surface).

The trees here are perfect-depth heaps (see `tree.py`): a node is internal
iff ``is_split``; children of heap node i are 2i+1 / 2i+2; per-node training
covers (Σ row weights) are recorded by ``build_tree`` exactly for this
algorithm. Routing matches scoring: right iff ``x > thr`` or ``x`` is NaN
(the NA-goes-right convention of the last histogram bin).

The hot path is the native C++ kernel (``native/tree_shap.cpp``, OpenMP over
rows); this module holds the numpy fallback and the brute-force Shapley
oracle used by the tests.
"""

from __future__ import annotations

from itertools import combinations
from math import factorial

import numpy as np


# ---------------------------------------------------------------------------
# per-row recursive TreeSHAP (numpy fallback; mirrors the C++ kernel)
# ---------------------------------------------------------------------------

def _tree_shap_row(feat, thr, is_split, value, cover, x, phi, scale):
    """Accumulate SHAP values of one tree for one row into phi (len F+1).

    phi[:F] += per-feature contributions · scale; phi[F] += E[f] · scale
    (the bias term — the cover-weighted mean leaf value).

    Each recursion level owns a COPY of the path (a repeated feature unwinds
    a middle element, so the parent's path must stay intact for the cold
    branch — same reason the reference TreeSHAP copies path fragments).
    Path element: [d, z, o, w] = feature, zero-fraction, one-fraction,
    permutation weight.
    """

    def extend(m, pzf, pof, pif):
        l = len(m)
        m.append([pif, pzf, pof, 1.0 if l == 0 else 0.0])
        for i in range(l - 1, -1, -1):
            m[i + 1][3] += pof * m[i][3] * (i + 1.0) / (l + 1.0)
            m[i][3] = pzf * m[i][3] * (l - i) / (l + 1.0)

    def unwound_sum(m, i):
        """Σ path weights with element i unwound (no mutation)."""
        l = len(m) - 1
        one, zero = m[i][2], m[i][1]
        total = 0.0
        nxt = m[l][3]
        for j in range(l - 1, -1, -1):
            if one != 0.0:
                tmp = nxt * (l + 1.0) / ((j + 1.0) * one)
                total += tmp
                nxt = m[j][3] - tmp * zero * (l - j) / (l + 1.0)
            else:
                total += m[j][3] * (l + 1.0) / (zero * (l - j))
        return total

    def unwind(m, i):
        """Remove path element i in place. The recomputed permutation
        weights stay at their positions (only d/z/o shift down) — shifting
        weights too corrupts the shortened path."""
        l = len(m) - 1
        one, zero = m[i][2], m[i][1]
        nxt = m[l][3]
        for j in range(l - 1, -1, -1):
            if one != 0.0:
                tmp = nxt * (l + 1.0) / ((j + 1.0) * one)
                nxt = m[j][3] - tmp * zero * (l - j) / (l + 1.0)
                m[j][3] = tmp
            else:
                m[j][3] = m[j][3] * (l + 1.0) / (zero * (l - j))
        for j in range(i, l):
            m[j][0] = m[j + 1][0]
            m[j][1] = m[j + 1][1]
            m[j][2] = m[j + 1][2]
        del m[l]

    def recurse(node, m, pzf, pof, pif):
        m = [e.copy() for e in m]
        extend(m, pzf, pof, pif)
        if not is_split[node]:
            v = value[node] * scale
            for i in range(1, len(m)):
                phi[m[i][0]] += unwound_sum(m, i) * (m[i][2] - m[i][1]) * v
            return
        f = feat[node]
        xv = x[f]
        go_right = np.isnan(xv) or xv > thr[node]
        hot = 2 * node + 2 if go_right else 2 * node + 1
        cold = 2 * node + 1 if go_right else 2 * node + 2
        cn, ch, cc = cover[node], cover[hot], cover[cold]
        iz, io = 1.0, 1.0
        # a feature already on the path folds its fractions into this split
        for i in range(1, len(m)):
            if m[i][0] == f:
                iz, io = m[i][1], m[i][2]
                unwind(m, i)
                break
        denom = cn if cn > 0 else 1.0
        recurse(hot, m, iz * ch / denom, io, f)
        recurse(cold, m, iz * cc / denom, 0.0, f)

    phi[len(x)] += _expected_value(feat, thr, is_split, value, cover, 0) * scale
    recurse(0, [], 1.0, 1.0, -1)


def _expected_value(feat, thr, is_split, value, cover, node):
    if not is_split[node]:
        return value[node]
    l, r = 2 * node + 1, 2 * node + 2
    cn = cover[node]
    if cn <= 0:
        return value[node]
    return (
        cover[l] / cn * _expected_value(feat, thr, is_split, value, cover, l)
        + cover[r] / cn * _expected_value(feat, thr, is_split, value, cover, r)
    )


def tree_shap_numpy(forest, covers, X: np.ndarray, scale: float = 1.0) -> np.ndarray:
    """SHAP contributions for a stacked forest.

    forest: Tree of (ntrees, T) arrays; covers: (ntrees, T); X: (N, F) raw
    features (NaN = NA). Returns (N, F+1): per-feature φ plus the bias term.
    """
    feat = np.asarray(forest.feat, np.int64)
    thr = np.asarray(forest.thr, np.float64)
    issp = np.asarray(forest.is_split, bool)
    val = np.asarray(forest.value, np.float64)
    cov = np.asarray(covers, np.float64)
    N, F = X.shape
    out = np.zeros((N, F + 1), np.float64)
    ntrees = feat.shape[0]
    for r in range(N):
        phi = out[r]
        for t in range(ntrees):
            _tree_shap_row(feat[t], thr[t], issp[t], val[t], cov[t],
                           X[r], phi, scale)
    return out


def compute_contributions(feat, thr, is_split, value, cover, X: np.ndarray,
                          scale: float, f0: float) -> np.ndarray:
    """Shared contributions entry: native kernel when available, numpy
    mirror otherwise; f0·scale folded into the BiasTerm column. Used by both
    the in-cluster model and the MOJO scorer (single source of truth)."""
    from collections import namedtuple

    from ..native import loader as native_loader

    feat = np.asarray(feat)
    cover = np.asarray(cover)
    if cover.shape != feat.shape:
        raise ValueError(
            f"covers shape {cover.shape} does not match forest {feat.shape} "
            "(model continued from a pre-TreeSHAP checkpoint?); retrain to "
            "enable predict_contributions")
    thr = np.asarray(thr)
    is_split = np.asarray(is_split)
    value = np.asarray(value)
    contrib = native_loader.tree_shap(feat, thr, is_split, value, cover, X, scale)
    if contrib is None:
        Fst = namedtuple("Fst", "feat thr is_split value")
        contrib = tree_shap_numpy(Fst(feat, thr, is_split, value), cover, X, scale)
    contrib[:, -1] += float(f0) * scale
    return contrib


# ---------------------------------------------------------------------------
# brute-force Shapley oracle (tests only) — exponential in F
# ---------------------------------------------------------------------------

def _cond_expectation(feat, thr, is_split, value, cover, x, known):
    """EXPVALUE(x, S): walk splits on known features, average on unknown."""

    def go(node):
        if not is_split[node]:
            return value[node]
        f = feat[node]
        l, r = 2 * node + 1, 2 * node + 2
        if f in known:
            xv = x[f]
            return go(r) if (np.isnan(xv) or xv > thr[node]) else go(l)
        cn = cover[node]
        if cn <= 0:
            return value[node]
        return cover[l] / cn * go(l) + cover[r] / cn * go(r)

    return go(0)


def shapley_bruteforce(forest, covers, x: np.ndarray) -> np.ndarray:
    """Exact path-dependent Shapley values for one row (tests)."""
    feat = np.asarray(forest.feat, np.int64)
    thr = np.asarray(forest.thr, np.float64)
    issp = np.asarray(forest.is_split, bool)
    val = np.asarray(forest.value, np.float64)
    cov = np.asarray(covers, np.float64)
    F = x.shape[0]
    phi = np.zeros(F + 1)
    for t in range(feat.shape[0]):
        args = (feat[t], thr[t], issp[t], val[t], cov[t], x)
        for i in range(F):
            rest = [j for j in range(F) if j != i]
            for k in range(F):
                for S in combinations(rest, k):
                    wgt = factorial(k) * factorial(F - k - 1) / factorial(F)
                    with_i = _cond_expectation(*args, set(S) | {i})
                    without = _cond_expectation(*args, set(S))
                    phi[i] += wgt * (with_i - without)
        phi[F] += _cond_expectation(*args, set())
    return phi
