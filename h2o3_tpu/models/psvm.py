"""H2OSupportVectorMachineEstimator — binary SVM (PSVM).

Reference parity: `h2o-algos/src/main/java/hex/psvm/PSVM.java` (primal SVM
on an Incomplete-Cholesky kernel approximation + interior point, per the
PSVM paper; `kernel_type=gaussian`, `hyper_param` = C, ±1 response,
`rank_ratio` controls the low-rank factor size). Estimator surface
`h2o-py/h2o/estimators/psvm.py` (predict → label, no probabilities;
`decision_function`).

TPU redesign: the ICF low-rank kernel factor is replaced by random Fourier
features — z(x) = √(2/D)·cos(Wx+b) with W~N(0, 2γI) approximates the same
gaussian kernel as a dense (n×D) feature matrix, and the primal squared-hinge
objective is minimized with full-batch Adam: every step is two MXU matmuls,
no interior-point iterations, trivially row-sharded with psum'd gradients.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..frame.frame import Frame
from .metrics import ModelMetricsBinomial
from .model_base import DataInfo, H2OEstimator, H2OModel


class PSVMModel(H2OModel):
    algo = "psvm"

    def __init__(self, params, x, y, dinfo, W, b, beta, bias, domain, kernel,
                 svs_count):
        super().__init__(params)
        self.x = list(x)
        self.y = y
        self.dinfo = dinfo
        self.W = W          # (p, D) fourier projection (None for linear kernel)
        self.b = b          # (D,)
        self.beta = beta    # (D,) or (p,) weights
        self.bias = bias
        self.domain = domain
        self.kernel = kernel
        self.svs_count = svs_count  # rows inside the margin (support vectors)

    def _features(self, X: np.ndarray) -> np.ndarray:
        if self.kernel == "linear" or self.W is None:
            return X
        D = self.W.shape[1]
        return np.sqrt(2.0 / D) * np.cos(X @ self.W + self.b)

    def decision_function(self, frame: Frame) -> np.ndarray:
        X = self.dinfo.transform(frame)
        return self._features(X) @ self.beta + self.bias

    def predict(self, test_data: Frame) -> Frame:
        f = self.decision_function(test_data)
        lab = (f > 0).astype(int)
        return Frame.from_dict(
            {"predict": np.asarray(self.domain, dtype=object)[lab],
             "decision_function": f},
            column_types={"predict": "enum"},
        )

    def _make_metrics(self, frame: Frame):
        f = self.decision_function(frame)
        yv = frame.vec(self.y)
        # decision values as ranking scores: AUC is well-defined without probs
        score = 1.0 / (1.0 + np.exp(-np.clip(f, -30, 30)))
        return ModelMetricsBinomial.make(np.asarray(yv.data), score)


class H2OSupportVectorMachineEstimator(H2OEstimator):
    algo = "psvm"
    _param_defaults = dict(
        hyper_param=1.0,
        kernel_type="gaussian",
        gamma=-1.0,
        rank_ratio=-1.0,
        positive_weight=1.0,
        negative_weight=1.0,
        disable_training_metrics=False,
        sv_threshold=1e-4,
        fact_threshold=1e-5,
        max_iterations=200,
        feasible_threshold=1e-3,
        surrogate_gap_threshold=1e-3,
        mu_factor=10.0,
    )

    def _fit(self, x, y, train: Frame, valid: Optional[Frame]) -> PSVMModel:
        from .model_base import warn_host_solver

        warn_host_solver('psvm', train.nrow, 100000)
        import optax

        p = self._parms
        yvec = train.vec(y)
        if yvec.type != "enum" or yvec.nlevels != 2:
            raise ValueError("psvm requires a binary categorical response")
        domain = yvec.domain
        ypm = np.asarray(yvec.data, np.float32) * 2.0 - 1.0  # ±1

        dinfo = DataInfo(train, x, standardize=True)
        X = dinfo.fit_transform(train)
        n, pdim = X.shape
        kernel = str(p.get("kernel_type", "gaussian")).lower()
        gamma = float(p.get("gamma", -1.0))
        if gamma <= 0:
            gamma = 1.0 / max(pdim, 1)
        C = float(p.get("hyper_param", 1.0))
        wpos = float(p.get("positive_weight", 1.0))
        wneg = float(p.get("negative_weight", 1.0))
        seed = int(self._parms.get("_actual_seed", 1234))
        rng = np.random.default_rng(seed)

        if kernel == "linear":
            W = None
            b = None
            Z = X
        else:
            # rank_ratio sets the ICF rank in the reference; here it sets the
            # fourier feature count (default √n·8 capped to [64, 1024])
            rr = float(p.get("rank_ratio", -1.0))
            D = int(rr * n) if rr > 0 else int(min(max(8 * np.sqrt(n), 64), 1024))
            W = rng.normal(scale=np.sqrt(2 * gamma), size=(pdim, D)).astype(np.float32)
            b = rng.uniform(0, 2 * np.pi, D).astype(np.float32)
            Z = np.sqrt(2.0 / D) * np.cos(X @ W + b)

        Zd = jnp.asarray(Z, jnp.float32)
        yd = jnp.asarray(ypm)
        cw = jnp.asarray(np.where(ypm > 0, wpos, wneg).astype(np.float32))

        def loss(params):
            beta, bias = params
            f = Zd @ beta + bias
            margin = jnp.maximum(0.0, 1.0 - yd * f)
            return 0.5 * jnp.sum(beta * beta) + C * jnp.sum(cw * margin * margin)

        beta0 = (jnp.zeros(Zd.shape[1], jnp.float32), jnp.asarray(0.0, jnp.float32))
        opt = optax.adam(0.05)
        state = opt.init(beta0)

        @jax.jit
        def step(params, state):
            v, g = jax.value_and_grad(loss)(params)
            updates, state = opt.update(g, state)
            return optax.apply_updates(params, updates), state, v

        params = beta0
        prev = np.inf
        for it in range(max(int(p.get("max_iterations", 200)), 50) * 5):
            params, state, v = step(params, state)
            v = float(v)
            if abs(prev - v) < 1e-7 * max(abs(v), 1.0):
                break
            prev = v
        beta, bias = np.asarray(params[0], np.float64), float(params[1])

        f = Z @ beta + bias
        svs = int((ypm * f < 1.0 + float(p.get("sv_threshold", 1e-4))).sum())
        model = PSVMModel(self, x, y, dinfo, W, b, beta, bias, domain, kernel, svs)
        if not p.get("disable_training_metrics"):
            model.training_metrics = model._make_metrics(train)
            if valid is not None:
                model.validation_metrics = model._make_metrics(valid)
        return model

    def _cv_predict(self, model: PSVMModel, frame: Frame) -> np.ndarray:
        f = model.decision_function(frame)
        return 1.0 / (1.0 + np.exp(-np.clip(f, -30, 30)))


PSVM = H2OSupportVectorMachineEstimator
