"""Distribution families — gradients/hessians/links for boosting and GLM.

Reference parity: `h2o-core/src/main/java/hex/DistributionFactory.java` and
the per-family classes (`hex/Distribution.java` subclasses: gaussian,
bernoulli, multinomial, poisson, gamma, tweedie, laplace, quantile, huber)
used by `hex/tree/gbm/GBM.java`'s pseudo-residual pass.

The reference computes first-order pseudo-residuals with per-leaf Newton
`gamma()` corrections; here every family exposes (g, h) on the margin scale
and trees take a single Newton step -G/(H+λ) per leaf — the same estimator
`gpu_hist` uses, identical leaf values for gaussian/bernoulli/multinomial.
All functions are jax-traceable (used inside jitted training steps).
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

FAMILIES = (
    "gaussian", "bernoulli", "multinomial", "poisson", "gamma",
    "tweedie", "laplace", "quantile", "huber",
)


def infer_distribution(problem: str, requested: str = "AUTO") -> str:
    if requested and requested != "AUTO":
        return requested
    return {"binomial": "bernoulli", "multinomial": "multinomial"}.get(problem, "gaussian")


def init_margin(dist: str, y: np.ndarray, w: np.ndarray, mu: float = None,
                **kw) -> float:
    """Initial constant margin f0 (Distribution.init / GBM initial value).
    `mu` overrides the locally computed weighted mean — a multi-host cloud
    passes the global mean (quantile/laplace need full-column order
    statistics and stay single-host)."""
    if dist in ("quantile",):
        return float(np.quantile(y, kw.get("alpha", 0.5)))
    if dist in ("laplace",):
        return float(np.median(y))
    if mu is None:
        mu = float(np.average(y, weights=w))
    if dist == "bernoulli":
        mu = min(max(mu, 1e-10), 1 - 1e-10)
        return float(np.log(mu / (1 - mu)))
    if dist in ("poisson", "gamma", "tweedie"):
        return float(np.log(max(mu, 1e-10)))
    return mu


def grad_hess(dist: str, margin: jax.Array, y: jax.Array, **kw) -> Tuple[jax.Array, jax.Array]:
    """(g, h) of the deviance wrt the margin — the pseudo-residual pass of
    `GBMDriver.buildNextKTrees` (hex/tree/gbm/GBM.java), Newton form."""
    if dist == "gaussian":
        return margin - y, jnp.ones_like(y)
    if dist == "bernoulli":
        p = jax.nn.sigmoid(margin)
        return p - y, p * (1 - p)
    if dist == "poisson":
        mu = jnp.exp(margin)
        return mu - y, mu
    if dist == "gamma":
        ey = y * jnp.exp(-margin)
        return 1.0 - ey, ey
    if dist == "tweedie":
        p = kw.get("tweedie_power", 1.5)
        a = y * jnp.exp((1 - p) * margin)
        b = jnp.exp((2 - p) * margin)
        return b - a, (2 - p) * b - (1 - p) * a
    if dist == "laplace":
        return jnp.sign(margin - y), jnp.ones_like(y)
    if dist == "quantile":
        alpha = kw.get("alpha", 0.5)
        return jnp.where(y > margin, -alpha, 1 - alpha), jnp.ones_like(y)
    if dist == "huber":
        delta = kw.get("huber_delta", 1.0)
        r = margin - y
        return jnp.clip(r, -delta, delta), jnp.ones_like(y)
    raise ValueError(f"unknown distribution {dist!r}")


def multinomial_grad_hess(margins: jax.Array, y_onehot: jax.Array):
    """Per-class (g, h): softmax cross-entropy. margins (N, K)."""
    p = jax.nn.softmax(margins, axis=1)
    return p - y_onehot, p * (1 - p)


def link_inv(dist: str, margin):
    if dist == "bernoulli":
        return jax.nn.sigmoid(margin)
    if dist in ("poisson", "gamma", "tweedie"):
        return jnp.exp(margin)
    return margin


def deviance_name(dist: str) -> str:
    return {"bernoulli": "logloss", "multinomial": "logloss"}.get(dist, "deviance")
