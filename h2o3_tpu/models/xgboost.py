"""H2OXGBoostEstimator — tree_method=tpu_hist.

Reference parity: `h2o-ext-xgboost/src/main/java/hex/tree/xgboost/`
(`XGBoost.java`, `XGBoostModel.java` parameter mapping, `remote/` Rabit
workers) wrapping the native `libxgboost4j` `hist`/`gpu_hist`/`approx`
updaters; estimator surface `h2o-py/h2o/estimators/xgboost.py`. The
BASELINE north star: `tree_method=hist → tpu_hist` (MSLR-WEB30K lambdarank).

Rebuild: there is no JNI/DMatrix layer — frame columns are already bin codes
in HBM, and the `gpu_hist` CUDA updater's job is done by the same
`ops/histogram.py` kernels GBM uses (`tpu_hist`); Rabit allreduce ≡ the
`lax.psum` the tree builder already does under shard_map. This class maps
XGBoost parameter names onto the shared-tree driver and adds:
* XGBoost-exact leaf regularization: reg_lambda shrinks the Newton step and
  reg_alpha soft-thresholds G (xgboost CalcWeight), both applied inside
  `tree.build_tree`,
* `rank:ndcg` lambdarank objective with query groups — pairwise ΔNDCG
  weighted gradients (the xgboost `rank:ndcg` objective).
"""

from __future__ import annotations

from typing import Optional

import functools

import jax
import jax.numpy as jnp
import numpy as np

from ..frame.frame import Frame
from .glm import GLMModel as _GLMModelBase
from .metrics import ndcg_at_k
from .shared_tree import H2OSharedTreeEstimator, SharedTreeModel


class _GBLinearModel(_GLMModelBase):
    """gblinear's fitted model: a GLMModel (it IS a generalized linear
    model — same scoring, coef tables, metrics) under the xgboost algo
    identity, so model ids and summaries say what trained it."""

    algo = "xgboost"


class H2OXGBoostEstimator(H2OSharedTreeEstimator):
    algo = "xgboost"
    _mode = "gbm"
    _param_defaults = dict(
        ntrees=50,
        max_depth=6,
        min_rows=1.0,                 # = min_child_weight
        min_child_weight=1.0,
        learn_rate=0.3,               # = eta
        eta=None,
        sample_rate=1.0,              # = subsample
        subsample=None,
        col_sample_rate=1.0,          # = colsample_bylevel
        colsample_bylevel=None,
        col_sample_rate_per_tree=1.0,  # = colsample_bytree
        colsample_bytree=None,
        max_abs_leafnode_pred=0.0,
        max_delta_step=0.0,
        score_tree_interval=0,
        min_split_improvement=0.0,    # = gamma
        gamma=None,
        nthread=-1,
        max_bins=256,
        max_leaves=0,
        tree_method="auto",           # auto/exact/approx/hist → all tpu_hist
        grow_policy="depthwise",
        booster="gbtree",
        reg_lambda=1.0,
        reg_alpha=0.0,
        quiet_mode=True,
        distribution="AUTO",
        tweedie_power=1.5,
        normalize_type="tree",
        rate_drop=0.0,
        one_drop=False,
        skip_drop=0.0,
        dmatrix_type="auto",
        backend="auto",
        gpu_id=None,
        objective=None,               # e.g. "rank:ndcg" (+ group_column)
        group_column=None,
        ndcg_k=10,
    )

    def _tree_params(self):
        p = self._parms
        def pick(a, b, default):
            va = p.get(a)
            return float(va) if va is not None else float(p.get(b, default) or default)

        return dict(
            ntrees=int(p.get("ntrees", 50)),
            max_depth=int(p.get("max_depth", 6)),
            min_rows=pick("min_child_weight", "min_rows", 1.0),
            nbins=int(p.get("max_bins", 256)) - 1,  # +1 NA bin added downstream
            learn_rate=pick("eta", "learn_rate", 0.3),
            learn_rate_annealing=1.0,
            sample_rate=pick("subsample", "sample_rate", 1.0),
            col_sample_rate=pick("colsample_bylevel", "col_sample_rate", 1.0),
            col_sample_rate_per_tree=pick("colsample_bytree", "col_sample_rate_per_tree", 1.0),
            min_split_improvement=pick("gamma", "min_split_improvement", 0.0),
            histogram_type="QuantilesGlobal",  # xgboost hist = sketch quantiles
            mtries=0,
            reg_lambda=float(p.get("reg_lambda", 1.0)),
            reg_alpha=float(p.get("reg_alpha", 0.0)),
            grow_policy=str(p.get("grow_policy", "depthwise")),
            max_leaves=int(p.get("max_leaves", 0) or 0),
            # xgboost: 0 = no cap, for both knobs
            max_abs_leaf=min(
                float(p.get("max_abs_leafnode_pred", 0) or 0) or np.inf,
                float(p.get("max_delta_step", 0) or 0) or np.inf),
            # DART dropout boosting (h2o-ext-xgboost booster=dart
            # passthrough; xgboost dart.cc). Dropout granularity here is a
            # boosting ROUND (all K class trees of the round together).
            dart=(dict(rate_drop=float(p.get("rate_drop", 0) or 0),
                       one_drop=bool(p.get("one_drop", False)),
                       skip_drop=float(p.get("skip_drop", 0) or 0),
                       normalize_type=str(p.get("normalize_type", "tree")))
                  if str(p.get("booster", "gbtree")) == "dart" else None),
        )

    def _check_params(self):
        """Reject accepted-but-unimplemented combinations LOUDLY — upstream
        either honors or errors on these (`hex/tree/xgboost/XGBoostModel.java`
        createParamsMap); training something silently different is worse
        than failing."""
        p = self._parms
        booster = str(p.get("booster", "gbtree"))
        if booster not in ("gbtree", "dart", "gblinear"):
            raise ValueError(f"booster={booster!r}: expected 'gbtree', "
                             "'dart', or 'gblinear'")
        if booster == "gblinear":
            obj = p.get("objective")
            if obj and str(obj).startswith("rank"):
                raise ValueError(
                    f"objective={obj!r} is not supported with "
                    "booster='gblinear' (lambdarank needs trees)")
            dist = str(p.get("distribution", "AUTO"))
            if dist not in ("AUTO", "gaussian", "bernoulli", "multinomial"):
                raise ValueError(
                    f"distribution={dist!r} with booster='gblinear': only "
                    "AUTO/gaussian/bernoulli/multinomial links are "
                    "implemented for the linear booster")
        for k in ("rate_drop", "skip_drop"):
            v = float(p.get(k, 0) or 0)
            if not 0.0 <= v <= 1.0:
                raise ValueError(f"{k}={v}: must be in [0, 1]")
            if v != 0.0 and booster != "dart":
                raise ValueError(f"{k} is a DART parameter; set "
                                 "booster='dart' to use it")
        if bool(p.get("one_drop", False)) and booster != "dart":
            raise ValueError("one_drop is a DART parameter; set "
                             "booster='dart' to use it")
        if str(p.get("normalize_type", "tree")) not in ("tree", "forest"):
            raise ValueError("normalize_type must be 'tree' or 'forest'")
        gp = str(p.get("grow_policy", "depthwise"))
        if gp not in ("depthwise", "lossguide"):
            raise ValueError(f"grow_policy={gp!r}: expected 'depthwise' or "
                             "'lossguide'")
        if gp == "lossguide":
            ml = int(p.get("max_leaves", 0) or 0)
            if ml == 1 or ml < 0:
                raise ValueError(f"max_leaves={ml}: a tree has at least 2 "
                                 "leaves (0 = bounded by max_depth only)")
            if int(p.get("max_depth", 6)) < 1:
                raise ValueError(
                    "grow_policy='lossguide' needs max_depth >= 1: the heap "
                    "tree layout is depth-capped (leaf-wise growth stops at "
                    "max_leaves OR max_depth, whichever binds first)")
            if p.get("monotone_constraints"):
                raise ValueError("monotone_constraints are not yet supported "
                                 "with grow_policy='lossguide'")
        elif int(p.get("max_leaves", 0) or 0) > 0:
            raise ValueError("max_leaves needs grow_policy='lossguide' "
                             "(depthwise growth is bounded by max_depth)")

    def _cv_can_reuse(self) -> bool:
        """gblinear folds fit a DataInfo design from the fold frame's raw x
        columns, and ranking folds rebuild lambdarank state (and NDCG) from
        the fold frame — both need full fold frames, not sliced codes."""
        if str(self._parms.get("booster", "gbtree")) == "gblinear":
            return False
        obj = self._parms.get("objective")
        if obj and str(obj).startswith("rank"):
            return False
        return super()._cv_can_reuse()

    def _fit(self, x, y, train: Frame, valid: Optional[Frame]):
        self._check_params()
        if str(self._parms.get("booster", "gbtree")) == "gblinear":
            return self._fit_gblinear(x, y, train, valid)
        obj = self._parms.get("objective")
        if obj and str(obj).startswith("rank"):
            gcol = self._parms.get("group_column") or "qid"
            if gcol not in train.names:
                raise ValueError(
                    f"objective={obj!r} needs group_column (qid); {gcol!r} not in frame"
                )
            from ..parallel import distdata

            # the objective contract is GLOBAL rows in global order: on a
            # multi-process cloud, gather qid/rel once so query groups that
            # span ingest-shard boundaries stay whole (upstream rabit gets
            # this for free from its single DMatrix; here the gather is the
            # equivalent one-time cost)
            qid = distdata.allgather_rows(
                train.vec(gcol).numeric_np().astype(np.int64))
            rel = distdata.allgather_rows(
                train.vec(y).numeric_np().astype(np.float64))
            x = [n for n in x if n != gcol]
            self._objective_fn = _make_lambdarank(
                qid, rel, int(self._parms.get("ndcg_k", 10)))
            try:
                model = super()._fit(x, y, train, valid)
            finally:
                self._objective_fn = None
            # NDCG as the headline metric for ranking models (global rows)
            scores = distdata.allgather_rows(
                model._margins(model._matrix(train))[:, 0])
            model.training_metrics.description = (
                f"NDCG@{self._parms.get('ndcg_k', 10)}="
                f"{ndcg_at_k(rel, scores, qid, int(self._parms.get('ndcg_k', 10))):.5f}"
            )
            return model
        return super()._fit(x, y, train, valid)

    def _fit_gblinear(self, x, y, train: Frame, valid: Optional[Frame]):
        """`booster="gblinear"` — the linear booster (upstream
        h2o-ext-xgboost passes it through to xgboost's `gblinear` with the
        shotgun/coordinate updater; `xgboost/src/linear/updater_shotgun.cc`
        CoordinateDelta).

        TPU-first: instead of per-coordinate sequential updates, each
        boosting round is ONE Jacobi ("shotgun") pass — two MXU matmuls
        (Xᵀg and (X∘X)ᵀh) produce every coordinate's gradient/hessian sums
        against the current margin, the elastic-net delta (reg_lambda L2,
        reg_alpha soft-threshold, xgboost's CoordinateDelta formula) is
        applied to all weights at once, damped by eta. All rounds run in a
        single jitted lax.scan. The learned coefficients are wrapped in a
        GLMModel, which reuses the GLM scoring/metrics/coef surface — a
        gblinear model IS a (boosted) generalized linear model."""
        from ..parallel import distdata
        from ..parallel import mesh as cloudlib
        from .glm import attach_linear_artifacts
        from .model_base import DataInfo, response_info

        p = self._parms
        yvec = train.vec(y)
        problem, nclass, domain = response_info(yvec)
        family = {"binomial": "binomial",
                  "multinomial": "multinomial"}.get(problem, "gaussian")
        dist = str(p.get("distribution", "AUTO"))
        if dist != "AUTO":
            # an explicitly requested link must MATCH the response type —
            # silently training a different family is worse than failing
            want = {"bernoulli": "binomial", "multinomial": "multinomial",
                    "gaussian": "gaussian"}[dist]
            if want != family:
                raise ValueError(
                    f"distribution={dist!r} is inconsistent with the "
                    f"response ({problem}, which implies {family}); drop "
                    "the distribution parameter or fix the response type")
        rounds = int(p.get("ntrees", 50))
        eta = float(p.get("eta") if p.get("eta") is not None
                    else p.get("learn_rate", 0.3) or 0.3)
        lam = float(p.get("reg_lambda", 1.0))
        alpha = float(p.get("reg_alpha", 0.0))

        dinfo = DataInfo(train, x, standardize=False)
        n = train.nrow
        w = (train.vec(p["weights_column"]).numeric_np()
             if p.get("weights_column") else np.ones(n)).astype(np.float32)
        if family == "binomial":
            yarr = (np.asarray(yvec.data, np.float32)
                    if yvec.type == "enum"
                    else yvec.numeric_np().astype(np.float32))
        elif family == "multinomial":
            yarr = np.asarray(yvec.data, np.float32)
        else:
            yarr = yvec.numeric_np().astype(np.float32)

        cloud = cloudlib.cloud()
        if distdata.multiprocess():
            # same global-row ingest as GLM: every rank contributes its
            # shard; the jitted scan over global sharded arrays makes XLA
            # insert the cross-host reductions
            X = dinfo.fit_transform(train)
            Xi = np.concatenate([X, np.ones((n, 1), np.float32)], axis=1)
            quota = distdata.local_quota(n)
            Xd = distdata.global_row_array(Xi.astype(np.float32), quota, cloud)
            yd = distdata.global_row_array(yarr, quota, cloud)
            wd = distdata.global_row_array(w, quota, cloud)
        elif cloud.size > 1 and n >= cloud.size:
            X = dinfo.fit_transform(train)
            Xi = np.concatenate([X, np.ones((n, 1), np.float32)], axis=1)
            npad = cloudlib.pad_to_multiple(n, cloud.size)
            padn = npad - n
            rs = cloud.row_sharding()
            Xd = jax.device_put(jnp.asarray(np.concatenate(
                [Xi, np.zeros((padn, Xi.shape[1]), np.float32)])), rs)
            yd = jax.device_put(jnp.asarray(np.concatenate(
                [yarr, np.zeros(padn, np.float32)])), rs)
            wd = jax.device_put(jnp.asarray(np.concatenate(
                [w, np.zeros(padn, np.float32)])), rs)
        else:
            Xd = dinfo.device_design(train, fit=True, add_intercept=True)
            yd, wd = jnp.asarray(yarr), jnp.asarray(w)

        K = nclass if family == "multinomial" else 1
        W = _gblinear_train(Xd, yd, wd, family=family, n_class=K,
                            rounds=rounds, eta=eta, lam=lam, alpha=alpha)
        beta = (np.asarray(W, np.float64) if family == "multinomial"
                else np.asarray(W[0], np.float64))

        model = _GBLinearModel(self, x, y, dinfo, family, beta, domain,
                               lambda_best=lam)
        return attach_linear_artifacts(model, train, valid, Xd, cloud.size, n)

    def _cv_predict(self, model, frame: Frame) -> np.ndarray:
        if isinstance(model, _GLMModelBase):  # gblinear fold models
            return model._score(frame)
        return super()._cv_predict(model, frame)

    def ndcg(self, frame: Frame, k: Optional[int] = None) -> float:
        from ..parallel import distdata

        gcol = self._parms.get("group_column") or "qid"
        qid = distdata.allgather_rows(
            frame.vec(gcol).numeric_np().astype(np.int64))
        rel = distdata.allgather_rows(
            frame.vec(self.model.y).numeric_np().astype(np.float64))
        scores = distdata.allgather_rows(
            self.model._margins(self.model._matrix(frame))[:, 0])
        return ndcg_at_k(rel, scores, qid,
                         k or int(self._parms.get("ndcg_k", 10)))


@functools.partial(jax.jit, static_argnames=("family", "n_class", "rounds"))
def _gblinear_train(Xd, yd, wd, *, family: str, n_class: int, rounds: int,
                    eta: float, lam: float, alpha: float):
    """All gblinear boosting rounds as one jitted lax.scan.

    Per round: margins via one (n,p)×(p,K) matmul, per-row (g, h) from the
    family's link, coordinate gradient/hessian sums via Xᵀg and (X∘X)ᵀh,
    then xgboost's CoordinateDelta (elastic net + clamp-at-zero crossing)
    applied Jacobi-style to every weight, damped by eta. The intercept
    (last design column) is unregularized, like xgboost's bias updater.
    HIGHEST precision keeps the f32 sums exact (TPU matmuls default to
    bf16 operands)."""
    pdim = Xd.shape[1]
    hi = jax.lax.Precision.HIGHEST
    X2 = Xd * Xd
    is_bias = jnp.zeros(pdim, jnp.float32).at[pdim - 1].set(1.0)
    lam_v = lam * (1.0 - is_bias)[:, None]          # (p, 1) broadcast over K
    alpha_v = alpha * (1.0 - is_bias)[:, None]
    onehot = (jax.nn.one_hot(yd.astype(jnp.int32), n_class, dtype=jnp.float32)
              if family == "multinomial" else None)

    def one_round(Wt, _):
        # Wt: (p, K) — transposed so the coord axis is leading
        margin = jnp.matmul(Xd, Wt, precision=hi)   # (n, K)
        if family == "binomial":
            mu = jax.nn.sigmoid(margin[:, 0])
            g = ((mu - yd) * wd)[:, None]
            h = (mu * (1 - mu) * wd)[:, None]
        elif family == "multinomial":
            pr = jax.nn.softmax(margin, axis=1)
            g = (pr - onehot) * wd[:, None]
            # xgboost multiclass_obj: h = 2·p·(1−p)
            h = 2.0 * pr * (1 - pr) * wd[:, None]
        else:
            g = ((margin[:, 0] - yd) * wd)[:, None]
            h = wd[:, None]
        G = jnp.matmul(Xd.T, g, precision=hi)       # (p, K)
        H = jnp.matmul(X2.T, h, precision=hi)
        gl2 = G + lam_v * Wt
        denom = H + lam_v
        tmp = Wt - gl2 / denom
        dw = jnp.where(tmp >= 0,
                       jnp.maximum(-(gl2 + alpha_v) / denom, -Wt),
                       jnp.minimum(-(gl2 - alpha_v) / denom, -Wt))
        dw = jnp.where(H < 1e-5, 0.0, dw)           # xgboost's hess guard
        return Wt + eta * dw, None

    W0 = jnp.zeros((pdim, n_class), jnp.float32)
    Wt, _ = jax.lax.scan(one_round, W0, None, length=rounds)
    return Wt.T                                     # (K, p)


def _make_lambdarank(qid: np.ndarray, rel: np.ndarray, k: int):
    """Pairwise lambdarank (g, h) — xgboost `rank:ndcg`.

    For each query, pairs (i, j) with rel_i > rel_j contribute
    λ = -σ(-(s_i - s_j)) · |ΔNDCG_ij| to g_i (and +λ to g_j); h gets
    σ(1-σ)|ΔNDCG|.

    TPU-first: queries are padded to a common group size and the whole
    pairwise pass runs as ONE jitted program per boosting round — a (Q, G,
    G) batched pairwise block, scattered back to rows by segment_sum. (A
    per-query host loop costs ~1 s per tree on MSLR-sized data; this is a
    single device dispatch.) Ranks use pairwise comparison counts with an
    index tiebreak — equivalent to a stable sort rank."""
    N = len(qid)
    order = np.argsort(qid, kind="mergesort")
    qs = qid[order]
    starts = np.flatnonzero(np.r_[True, qs[1:] != qs[:-1]])
    ends = np.r_[starts[1:], len(qs)]
    Q = len(starts)
    G = int((ends - starts).max()) if Q else 1
    idx_mat = np.full((Q, G), N, np.int64)      # N = pad slot
    for qi, (s, e) in enumerate(zip(starts, ends)):
        idx_mat[qi, : e - s] = order[s:e]
    gains = (2.0 ** rel - 1.0).astype(np.float64)
    rel_pad = np.concatenate([rel.astype(np.float64), [0.0]])
    gain_pad = np.concatenate([gains, [0.0]])
    rmat = rel_pad[idx_mat]                     # (Q, G)
    gmat = gain_pad[idx_mat]
    valid = (idx_mat < N)
    # per-query ideal DCG@k (static — relevance doesn't change per round)
    idcg = np.zeros(Q)
    for qi in range(Q):
        ideal = np.sort(rmat[qi][valid[qi]])[::-1]
        idcg[qi] = ((2.0 ** ideal - 1)
                    / np.log2(np.arange(2, len(ideal) + 2)))[:k].sum()
    inv_idcg = np.where(idcg > 0, 1.0 / np.maximum(idcg, 1e-12), 0.0)

    # bound the (qb, G, G) pairwise block to ~2^27 elements: queries are
    # processed in lax.map chunks, so one huge group (MSLR has ~1250-doc
    # queries) cannot inflate memory to Q·G² — only its own chunk's
    qb = max(1, min(Q, (1 << 27) // max(G * G, 1)))
    Qpad = ((Q + qb - 1) // qb) * qb
    if Qpad != Q:
        idx_mat = np.concatenate(
            [idx_mat, np.full((Qpad - Q, G), N, np.int64)])
        rmat = np.concatenate([rmat, np.zeros((Qpad - Q, G))])
        gmat = np.concatenate([gmat, np.zeros((Qpad - Q, G))])
        valid = np.concatenate([valid, np.zeros((Qpad - Q, G), bool)])
        inv_idcg = np.concatenate([inv_idcg, np.zeros(Qpad - Q)])

    idx_d = jnp.asarray(idx_mat, jnp.int32)
    rmat_d = jnp.asarray(rmat, jnp.float32)
    gmat_d = jnp.asarray(gmat, jnp.float32)
    valid_d = jnp.asarray(valid)
    inv_idcg_d = jnp.asarray(inv_idcg, jnp.float32)

    def objective(margin_dev, y_dev):
        return _lambdarank_pass(margin_dev, idx_d, rmat_d, gmat_d, valid_d,
                                inv_idcg_d, n_rows=N, q_chunk=qb)

    return objective


@functools.partial(jax.jit, static_argnames=("n_rows", "q_chunk"))
def _lambdarank_pass(margin, idx, rmat, gmat, valid, inv_idcg,
                     n_rows: int, q_chunk: int):
    """One lambdarank (g, h) pass over all (padded) query groups.

    Group tensors arrive as ARGUMENTS (not closure captures) so the HLO
    carries no data literals and the persistent compilation cache keys on
    shapes only — the same convention as the tree builder's _one_tree.
    Returns g/h padded with zeros to len(margin) (the tree build's padded
    row count)."""
    Qp, G = idx.shape
    nb = Qp // q_chunk
    reshape = lambda a: a.reshape((nb, q_chunk) + a.shape[1:])
    s_pad = jnp.concatenate(
        [margin.astype(jnp.float32), jnp.zeros(1, jnp.float32)])
    # pad slots (idx == n_rows) read the sentinel; real pad rows of the
    # margin vector are never referenced by idx (idx < n_rows)
    idx_sent = jnp.minimum(idx, n_rows)

    def chunk(args):
        ii, rr, gg, vv, inv = args
        sc = s_pad[ii]                                      # (qb, G)
        sc = jnp.where(vv, sc, -jnp.inf)
        # rank = #better-scored + #equal-scored-earlier (stable-sort rank)
        gt = (sc[:, :, None] < sc[:, None, :]) & vv[:, None, :]
        eq = (sc[:, :, None] == sc[:, None, :]) & vv[:, None, :]
        earlier = jnp.arange(G)[None, :] < jnp.arange(G)[:, None]  # [i,j]=j<i
        rk = gt.sum(axis=2) + (eq & earlier[None, :, :]).sum(axis=2)
        disc = jnp.where(vv, 1.0 / jnp.log2(rk.astype(jnp.float32) + 2.0), 0.0)
        dG = gg[:, :, None] - gg[:, None, :]
        dD = disc[:, :, None] - disc[:, None, :]
        delta = jnp.abs(dG * dD) * inv[:, None, None]
        sij = jnp.where(vv, sc, 0.0)
        sij = sij[:, :, None] - sij[:, None, :]
        rho = jax.nn.sigmoid(-jnp.clip(sij, -35, 35))
        pair_ok = (rr[:, :, None] > rr[:, None, :]) \
            & vv[:, :, None] & vv[:, None, :]
        lam = jnp.where(pair_ok, rho * delta, 0.0)
        hess = jnp.where(pair_ok, rho * (1 - rho) * delta, 0.0)
        g_q = -(lam.sum(axis=2) - lam.sum(axis=1))          # (qb, G)
        h_q = hess.sum(axis=2) + hess.sum(axis=1)
        return g_q, h_q

    g_b, h_b = jax.lax.map(chunk, (
        reshape(idx_sent), reshape(rmat), reshape(gmat),
        reshape(valid), reshape(inv_idcg)))
    flat_idx = idx_sent.reshape(-1)
    M = margin.shape[0]
    g = jax.ops.segment_sum(g_b.reshape(-1), flat_idx,
                            num_segments=n_rows + 1)[:n_rows]
    h = jax.ops.segment_sum(h_b.reshape(-1), flat_idx,
                            num_segments=n_rows + 1)[:n_rows]
    g_full = jnp.zeros(M, jnp.float32).at[:n_rows].set(g.astype(jnp.float32))
    h_full = jnp.full(M, 1e-6, jnp.float32).at[:n_rows].set(
        jnp.maximum(h, 1e-6).astype(jnp.float32))
    return g_full, h_full


XGBoost = H2OXGBoostEstimator
