"""H2OXGBoostEstimator — tree_method=tpu_hist.

Reference parity: `h2o-ext-xgboost/src/main/java/hex/tree/xgboost/`
(`XGBoost.java`, `XGBoostModel.java` parameter mapping, `remote/` Rabit
workers) wrapping the native `libxgboost4j` `hist`/`gpu_hist`/`approx`
updaters; estimator surface `h2o-py/h2o/estimators/xgboost.py`. The
BASELINE north star: `tree_method=hist → tpu_hist` (MSLR-WEB30K lambdarank).

Rebuild: there is no JNI/DMatrix layer — frame columns are already bin codes
in HBM, and the `gpu_hist` CUDA updater's job is done by the same
`ops/histogram.py` kernels GBM uses (`tpu_hist`); Rabit allreduce ≡ the
`lax.psum` the tree builder already does under shard_map. This class maps
XGBoost parameter names onto the shared-tree driver and adds:
* XGBoost-exact leaf regularization: reg_lambda shrinks the Newton step and
  reg_alpha soft-thresholds G (xgboost CalcWeight), both applied inside
  `tree.build_tree`,
* `rank:ndcg` lambdarank objective with query groups — pairwise ΔNDCG
  weighted gradients (the xgboost `rank:ndcg` objective).
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax.numpy as jnp
import numpy as np

from ..frame.frame import Frame
from .metrics import ndcg_at_k
from .shared_tree import H2OSharedTreeEstimator, SharedTreeModel


class H2OXGBoostEstimator(H2OSharedTreeEstimator):
    algo = "xgboost"
    _mode = "gbm"
    _param_defaults = dict(
        ntrees=50,
        max_depth=6,
        min_rows=1.0,                 # = min_child_weight
        min_child_weight=1.0,
        learn_rate=0.3,               # = eta
        eta=None,
        sample_rate=1.0,              # = subsample
        subsample=None,
        col_sample_rate=1.0,          # = colsample_bylevel
        colsample_bylevel=None,
        col_sample_rate_per_tree=1.0,  # = colsample_bytree
        colsample_bytree=None,
        max_abs_leafnode_pred=0.0,
        max_delta_step=0.0,
        score_tree_interval=0,
        min_split_improvement=0.0,    # = gamma
        gamma=None,
        nthread=-1,
        max_bins=256,
        max_leaves=0,
        tree_method="auto",           # auto/exact/approx/hist → all tpu_hist
        grow_policy="depthwise",
        booster="gbtree",
        reg_lambda=1.0,
        reg_alpha=0.0,
        quiet_mode=True,
        distribution="AUTO",
        tweedie_power=1.5,
        normalize_type="tree",
        rate_drop=0.0,
        one_drop=False,
        skip_drop=0.0,
        dmatrix_type="auto",
        backend="auto",
        gpu_id=None,
        objective=None,               # e.g. "rank:ndcg" (+ group_column)
        group_column=None,
        ndcg_k=10,
    )

    def _tree_params(self):
        p = self._parms
        def pick(a, b, default):
            va = p.get(a)
            return float(va) if va is not None else float(p.get(b, default) or default)

        return dict(
            ntrees=int(p.get("ntrees", 50)),
            max_depth=int(p.get("max_depth", 6)),
            min_rows=pick("min_child_weight", "min_rows", 1.0),
            nbins=int(p.get("max_bins", 256)) - 1,  # +1 NA bin added downstream
            learn_rate=pick("eta", "learn_rate", 0.3),
            learn_rate_annealing=1.0,
            sample_rate=pick("subsample", "sample_rate", 1.0),
            col_sample_rate=pick("colsample_bylevel", "col_sample_rate", 1.0),
            col_sample_rate_per_tree=pick("colsample_bytree", "col_sample_rate_per_tree", 1.0),
            min_split_improvement=pick("gamma", "min_split_improvement", 0.0),
            histogram_type="QuantilesGlobal",  # xgboost hist = sketch quantiles
            mtries=0,
            reg_lambda=float(p.get("reg_lambda", 1.0)),
            reg_alpha=float(p.get("reg_alpha", 0.0)),
        )

    def _fit(self, x, y, train: Frame, valid: Optional[Frame]) -> SharedTreeModel:
        obj = self._parms.get("objective")
        if obj and str(obj).startswith("rank"):
            gcol = self._parms.get("group_column") or "qid"
            if gcol not in train.names:
                raise ValueError(
                    f"objective={obj!r} needs group_column (qid); {gcol!r} not in frame"
                )
            qid = train.vec(gcol).numeric_np().astype(np.int64)
            x = [n for n in x if n != gcol]
            self._objective_fn = _make_lambdarank(
                qid, train.vec(y).numeric_np(), int(self._parms.get("ndcg_k", 10))
            )
            try:
                model = super()._fit(x, y, train, valid)
            finally:
                self._objective_fn = None
            # NDCG as the headline metric for ranking models
            scores = model._margins(model._matrix(train))[:, 0]
            model.training_metrics.description = (
                f"NDCG@{self._parms.get('ndcg_k', 10)}="
                f"{ndcg_at_k(train.vec(y).numeric_np(), scores, qid, int(self._parms.get('ndcg_k', 10))):.5f}"
            )
            return model
        return super()._fit(x, y, train, valid)

    def ndcg(self, frame: Frame, k: Optional[int] = None) -> float:
        gcol = self._parms.get("group_column") or "qid"
        qid = frame.vec(gcol).numeric_np().astype(np.int64)
        scores = self.model._margins(self.model._matrix(frame))[:, 0]
        return ndcg_at_k(
            frame.vec(self.model.y).numeric_np(), scores, qid,
            k or int(self._parms.get("ndcg_k", 10)),
        )


def _make_lambdarank(qid: np.ndarray, rel: np.ndarray, k: int):
    """Pairwise lambdarank (g, h) closure — xgboost `rank:ndcg`.

    For each query, pairs (i, j) with rel_i > rel_j contribute
    λ = -σ(-(s_i - s_j)) · |ΔNDCG_ij| to g_i (and +λ to g_j); h gets
    σ(1-σ)|ΔNDCG|. Small per-query groups ⇒ host numpy is fine; the tree
    build over the resulting (g, h) stays on device."""
    order = np.argsort(qid, kind="mergesort")
    groups = []
    qs = qid[order]
    starts = np.flatnonzero(np.r_[True, qs[1:] != qs[:-1]])
    ends = np.r_[starts[1:], len(qs)]
    for s, e in zip(starts, ends):
        groups.append(order[s:e])
    gains = (2.0 ** rel - 1.0)

    def objective(margin_dev, y_dev) -> Tuple[jnp.ndarray, jnp.ndarray]:
        s = np.asarray(margin_dev, np.float64)
        g = np.zeros(len(s))
        h = np.zeros(len(s))
        for rows in groups:
            if len(rows) < 2:
                continue
            r = rel[rows]
            sc = s[rows]
            # ideal DCG for normalization
            ideal = np.sort(r)[::-1]
            idcg = ((2.0 ** ideal - 1) / np.log2(np.arange(2, len(r) + 2)))[:k].sum()
            if idcg <= 0:
                continue
            # current ranks by score (desc)
            rk = np.empty(len(sc), np.int64)
            rk[np.argsort(-sc, kind="mergesort")] = np.arange(len(sc))
            disc = 1.0 / np.log2(rk + 2.0)
            gi = gains[rows]
            dG = gi[:, None] - gi[None, :]              # gain diff
            dD = disc[:, None] - disc[None, :]          # discount diff
            delta = np.abs(dG * dD) / idcg              # |ΔNDCG| if swapped
            sij = sc[:, None] - sc[None, :]
            rho = 1.0 / (1.0 + np.exp(np.clip(sij, -35, 35)))  # σ(-(si-sj))
            mask = (r[:, None] > r[None, :])
            lam = rho * delta * mask
            hess = rho * (1 - rho) * delta * mask
            g[rows] += -(lam.sum(axis=1) - lam.T.sum(axis=1))
            h[rows] += hess.sum(axis=1) + hess.T.sum(axis=1)
        return jnp.asarray(g, jnp.float32), jnp.asarray(np.maximum(h, 1e-6), jnp.float32)

    return objective


XGBoost = H2OXGBoostEstimator
