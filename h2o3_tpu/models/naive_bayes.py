"""H2ONaiveBayesEstimator — Naive Bayes classifier.

Reference parity: `h2o-algos/src/main/java/hex/naivebayes/NaiveBayes.java`:
per-class priors; numeric features → per-(class, feature) Gaussian moments;
categorical features → per-(class, feature, level) counts with Laplace
smoothing; `eps_sdev`/`min_sdev` floors. Estimator surface
`h2o-py/h2o/estimators/naive_bayes.py`.

The sufficient statistics are one segment-sum over rows keyed by class —
one jitted reduction (psum-able over row shards), replacing the NBTask
MRTask.
"""

from __future__ import annotations

from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..frame.frame import Frame
from .metrics import ModelMetricsBinomial, ModelMetricsMultinomial
from .model_base import H2OEstimator, H2OModel, response_info


class NaiveBayesModel(H2OModel):
    algo = "naivebayes"

    def __init__(self, params, x, y, domain, priors, num_stats, cat_tables, spec):
        super().__init__(params)
        self.x = list(x)
        self.y = y
        self.domain = domain
        self.priors = priors          # (K,)
        self.num_stats = num_stats    # dict col -> (K, 2) mean/sd
        self.cat_tables = cat_tables  # dict col -> ((K, L) probs, domain)
        self.spec = spec              # list of (name, kind)

    def _log_probs(self, frame: Frame) -> np.ndarray:
        n = frame.nrow
        K = len(self.priors)
        logp = np.tile(np.log(self.priors)[None, :], (n, 1))
        for name, kind in self.spec:
            v = frame.vec(name)
            if kind == "num":
                col = v.numeric_np()
                mean, sd = self.num_stats[name][:, 0], self.num_stats[name][:, 1]
                valid = ~np.isnan(col)
                ll = (
                    -0.5 * np.log(2 * np.pi * sd[None, :] ** 2)
                    - 0.5 * ((np.where(valid, col, 0.0)[:, None] - mean[None, :]) / sd[None, :]) ** 2
                )
                logp += np.where(valid[:, None], ll, 0.0)
            else:
                probs, dom = self.cat_tables[name]
                codes = np.asarray(v.data)
                if v.domain != dom and v.domain:
                    remap = np.asarray(
                        [dom.index(d) if d in dom else -1 for d in v.domain], np.int64
                    )
                    codes = np.where(codes >= 0, remap[np.maximum(codes, 0)], -1)
                valid = codes >= 0
                safe = np.maximum(codes, 0)
                ll = np.log(probs[:, safe]).T  # (n, K)
                logp += np.where(valid[:, None], ll, 0.0)
        return logp

    def predict(self, test_data: Frame) -> Frame:
        logp = self._log_probs(test_data)
        m = logp - logp.max(axis=1, keepdims=True)
        probs = np.exp(m) / np.exp(m).sum(axis=1, keepdims=True)
        lab = probs.argmax(axis=1)
        d = {"predict": np.asarray(self.domain, dtype=object)[lab]}
        for i, cls in enumerate(self.domain):
            d[str(cls)] = probs[:, i]
        return Frame.from_dict(d, column_types={"predict": "enum"})

    def _make_metrics(self, frame: Frame):
        logp = self._log_probs(frame)
        m = logp - logp.max(axis=1, keepdims=True)
        probs = np.exp(m) / np.exp(m).sum(axis=1, keepdims=True)
        yv = frame.vec(self.y)
        if len(self.domain) == 2:
            return ModelMetricsBinomial.make(np.asarray(yv.data), probs[:, 1])
        return ModelMetricsMultinomial.make(np.asarray(yv.data), probs)


class H2ONaiveBayesEstimator(H2OEstimator):
    algo = "naivebayes"
    _param_defaults = dict(
        laplace=0.0,
        min_sdev=0.001,
        eps_sdev=0.0,
        min_prob=0.001,
        eps_prob=0.0,
        compute_metrics=True,
        balance_classes=False,
        class_sampling_factors=None,
        max_after_balance_size=5.0,
    )

    def _fit(self, x, y, train: Frame, valid: Optional[Frame]) -> NaiveBayesModel:
        from .model_base import warn_host_solver

        warn_host_solver('naivebayes', train.nrow, 2000000)
        p = self._parms
        yvec = train.vec(y)
        problem, K, domain = response_info(yvec)
        if problem == "regression":
            raise ValueError("naivebayes requires a categorical response")
        ycodes = np.asarray(yvec.data, np.int64)
        n = train.nrow
        laplace = float(p.get("laplace", 0.0))
        min_sdev = max(float(p.get("min_sdev", 0.001)), 1e-10)

        counts = np.bincount(ycodes, minlength=K).astype(np.float64)
        priors = counts / counts.sum()

        yj = jnp.asarray(ycodes, jnp.int32)
        num_stats = {}
        cat_tables = {}
        spec = []
        for name in x:
            v = train.vec(name)
            if v.type == "enum":
                L = max(v.nlevels, 1)
                codes = np.asarray(v.data)
                ok = codes >= 0
                tab = np.zeros((K, L))
                np.add.at(tab, (ycodes[ok], codes[ok]), 1.0)
                tab = (tab + laplace) / (
                    tab.sum(axis=1, keepdims=True) + laplace * L + 1e-300
                )
                tab = np.maximum(tab, float(p.get("min_prob", 0.001)) * 1e-3)
                cat_tables[name] = (tab, v.domain)
                spec.append((name, "cat"))
            else:
                col = v.numeric_np()
                ok = ~np.isnan(col)
                cj = jnp.asarray(np.where(ok, col, 0.0), jnp.float32)
                wj = jnp.asarray(ok.astype(np.float32))
                # per-class {Σw, Σx, Σx²} — one segment reduction (NBTask)
                stats = jax.ops.segment_sum(
                    jnp.stack([wj, cj * wj, cj * cj * wj], axis=1), yj, num_segments=K
                )
                stats = np.asarray(stats, np.float64)
                cnt = np.maximum(stats[:, 0], 1.0)
                mean = stats[:, 1] / cnt
                var = np.maximum(stats[:, 2] / cnt - mean**2, 0.0)
                sd = np.maximum(np.sqrt(var * cnt / np.maximum(cnt - 1, 1.0)), min_sdev)
                num_stats[name] = np.column_stack([mean, sd])
                spec.append((name, "num"))

        model = NaiveBayesModel(self, x, y, domain, priors, num_stats, cat_tables, spec)
        model.training_metrics = model._make_metrics(train)
        if valid is not None:
            model.validation_metrics = model._make_metrics(valid)
        return model

    def _cv_predict(self, model: NaiveBayesModel, frame: Frame) -> np.ndarray:
        logp = model._log_probs(frame)
        m = logp - logp.max(axis=1, keepdims=True)
        probs = np.exp(m) / np.exp(m).sum(axis=1, keepdims=True)
        return probs[:, 1] if len(model.domain) == 2 else probs


NaiveBayes = H2ONaiveBayesEstimator
