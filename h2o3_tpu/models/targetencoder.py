"""H2OTargetEncoderEstimator — categorical target encoding.

Reference parity: `h2o-algos/src/main/java/ai/h2o/targetencoding/
TargetEncoder.java` (+ `TargetEncoderModel.java`): per-level target means
with `data_leakage_handling` ∈ {None, KFold, LeaveOneOut}, PAVLOU-style
blending toward the prior — lambda = 1/(1+exp(-(n-k)/f)) with
`inflection_point` k and `smoothing` f — and optional uniform `noise`.
`transform()` appends `<col>_te` columns. Estimator surface
`h2o-py/h2o/estimators/targetencoder.py`.

The fit is one segment-mean per encoded column (a psum-able reduction over
row shards); transforms are table lookups.
"""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from ..frame.frame import Frame
from ..frame.vec import Vec
from .metrics import ModelMetricsBase
from .model_base import H2OEstimator, H2OModel


def _blend(level_sum, level_cnt, prior, k, f):
    with np.errstate(over="ignore"):
        lam = 1.0 / (1.0 + np.exp(-(level_cnt - k) / max(f, 1e-12)))
    mean = level_sum / np.maximum(level_cnt, 1e-12)
    return lam * mean + (1 - lam) * prior


class TargetEncoderModel(H2OModel):
    algo = "targetencoder"

    def __init__(self, params, x, y, encodings, prior, blending, k, f, noise,
                 leakage, fold_assign, seed):
        super().__init__(params)
        self.x = list(x)
        self.y = y
        self.encodings = encodings    # col → (domain, sums, counts)
        self.prior = prior
        self.blending = blending
        self.k = k
        self.f = f
        self.noise = noise
        self.leakage = leakage
        self._fold_assign = fold_assign  # training-time fold ids (KFold)
        self.seed = seed

    def _encode_col(self, v: Vec, col: str, sums, cnts, dom,
                    y_arr: Optional[np.ndarray], as_training: bool) -> np.ndarray:
        codes = np.asarray(v.data) if v.type == "enum" else v.numeric_np().astype(np.int64)
        if v.type == "enum" and v.domain != dom and v.domain:
            lookup = {d: i for i, d in enumerate(dom)}
            remap = np.asarray([lookup.get(d, -1) for d in v.domain], np.int64)
            codes = np.where(codes >= 0, remap[np.maximum(codes, 0)], -1)
        n = len(codes)
        out = np.full(n, self.prior)
        ok = (codes >= 0) & (codes < len(sums))
        ci = np.maximum(codes, 0)
        if as_training and self.leakage == "LeaveOneOut" and y_arr is not None:
            s = sums[ci] - y_arr
            c = np.maximum(cnts[ci] - 1, 0)
        else:
            s = sums[ci]
            c = cnts[ci]
        if self.blending:
            enc = _blend(s, c, self.prior, self.k, self.f)
        else:
            enc = np.where(c > 0, s / np.maximum(c, 1e-12), self.prior)
        out[ok] = enc[ok]
        return out

    def transform(self, frame: Frame, as_training: bool = False,
                  noise: Optional[float] = None) -> Frame:
        """Append `<col>_te` columns (TargetEncoderModel.transformAsTrainingFrame
        / transform)."""
        out = {n: v for n, v in zip(frame.names, frame.vecs())}
        yv = frame.vec(self.y) if (self.y in frame.names) else None
        y_arr = None
        if yv is not None:
            y_arr = (np.asarray(yv.data, np.float64) if yv.type == "enum"
                     else yv.numeric_np())
        rng = np.random.default_rng(self.seed)
        nz = self.noise if noise is None else noise
        for col, (dom, sums, cnts, fold_tables) in self.encodings.items():
            if col not in frame.names:
                continue
            v = frame.vec(col)
            if (as_training and self.leakage == "KFold"
                    and self._fold_assign is not None
                    and len(self._fold_assign) == frame.nrow):
                enc = np.full(frame.nrow, self.prior)
                codes = np.asarray(v.data)
                for fid, (fs, fc) in fold_tables.items():
                    m = self._fold_assign == fid
                    enc[m] = self._encode_col(
                        Vec(codes[m], "enum", domain=v.domain), col, fs, fc,
                        dom, None, False)
            else:
                enc = self._encode_col(v, col, sums, cnts, dom, y_arr, as_training)
            if as_training and nz:
                enc = enc + rng.uniform(-nz, nz, len(enc))
            out[f"{col}_te"] = Vec(enc.astype(np.float32), "real")
        return Frame(out)

    def predict(self, test_data: Frame) -> Frame:
        return self.transform(test_data)

    def _make_metrics(self, frame: Frame):
        return self.training_metrics


class H2OTargetEncoderEstimator(H2OEstimator):
    algo = "targetencoder"
    _param_defaults = dict(
        columns=None,
        data_leakage_handling="None",
        blending=False,
        inflection_point=10.0,
        smoothing=20.0,
        noise=0.01,
    )

    def _fit(self, x, y, train: Frame, valid: Optional[Frame]) -> TargetEncoderModel:
        p = self._parms
        cols: List[str] = list(p.get("columns") or
                               [c for c in x if train.vec(c).type == "enum"])
        yvec = train.vec(y)
        y_arr = (np.asarray(yvec.data, np.float64) if yvec.type == "enum"
                 else yvec.numeric_np())
        prior = float(np.nanmean(y_arr))
        leakage = str(p.get("data_leakage_handling", "None"))
        seed = int(self._parms.get("_actual_seed", 1234))

        fold_assign = None
        if leakage == "KFold":
            fc = p.get("fold_column")
            if fc:
                fold_assign = train.vec(fc).numeric_np().astype(np.int64)
            else:
                rng = np.random.default_rng(seed)
                fold_assign = rng.integers(0, 5, train.nrow)

        encodings: Dict[str, tuple] = {}
        for col in cols:
            v = train.vec(col)
            if v.type != "enum":
                continue
            codes = np.asarray(v.data)
            K = max(v.nlevels, 1)
            ok = codes >= 0
            sums = np.zeros(K)
            cnts = np.zeros(K)
            np.add.at(sums, codes[ok], y_arr[ok])
            np.add.at(cnts, codes[ok], 1.0)
            fold_tables = {}
            if fold_assign is not None:
                # out-of-fold tables: global minus the fold's own rows
                for fid in np.unique(fold_assign):
                    m = ok & (fold_assign == fid)
                    fs = sums.copy()
                    fc_ = cnts.copy()
                    np.add.at(fs, codes[m], -y_arr[m])
                    np.add.at(fc_, codes[m], -1.0)
                    fold_tables[fid] = (fs, fc_)
            encodings[col] = (v.domain, sums, cnts, fold_tables)

        model = TargetEncoderModel(
            self, cols, y, encodings, prior,
            bool(p.get("blending", False)),
            float(p.get("inflection_point", 10.0)),
            float(p.get("smoothing", 20.0)),
            float(p.get("noise", 0.01)),
            leakage, fold_assign, seed,
        )
        model.training_metrics = ModelMetricsBase(nobs=train.nrow)
        return model

    def transform(self, frame: Frame, **kw) -> Frame:
        return self.model.transform(frame, **kw)


TargetEncoder = H2OTargetEncoderEstimator
