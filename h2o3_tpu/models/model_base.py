"""Model framework — estimator lifecycle, jobs, CV, early stopping, DataInfo.

Reference parity:
* `h2o-core/src/main/java/hex/Model.java` / `hex/ModelBuilder.java` — the
  train/score lifecycle, n-fold CV orchestration (`computeCrossValidation`),
  parameter validation.
* `water/Job.java` — async job tracking (here: synchronous with progress).
* `hex/ScoreKeeper.java` — early stopping on a moving average of the
  stopping metric.
* `hex/DataInfo.java` — the numeric adapter reused by GLM/DeepLearning/PCA/
  KMeans: categorical one-hot expansion, standardization, NA mean-imputation.
* `h2o-py/h2o/estimators/estimator_base.py` — the Python estimator facade
  whose signatures (`train(x, y, training_frame, validation_frame)`,
  `predict`, `model_performance`) are the compatibility contract.

TPU note: builders prepare host-side numpy, then hand dense arrays to jitted
training programs; the padded/sharded device placement happens inside each
algorithm (see `tree.py`, `glm.py`, `deeplearning.py`).
"""

from __future__ import annotations

import functools
import itertools
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Union

import numpy as np

from ..frame.frame import Frame
from ..frame.vec import Vec
from .metrics import (
    ModelMetricsBase,
    ModelMetricsBinomial,
    ModelMetricsMultinomial,
    ModelMetricsRegression,
)

_model_counter = itertools.count()


@functools.lru_cache(maxsize=64)
def _device_expand_fn(sig):
    """Jitted design-matrix expansion, cached per DataInfo signature
    (column kinds/cardinalities + transfer dtype per numeric column,
    use_all, standardize, intercept) so every same-shaped frame reuses one
    compiled program. Numeric columns arrive in up to three transfer
    groups — uint8 / int16 / f32 — the analog of the reference's columnar
    chunk compression (water/fvec C1Chunk, C2Chunk): small-range integer
    columns travel the tunnel at 1–2 bytes/value, LOSSLESSLY, and widen to
    f32 on device."""
    import jax
    import jax.numpy as jnp

    spec, use_all, standardized, add_intercept = sig

    def expand(nums8, nums16, nums32, cats, means, stds):
        parts = []
        idx = [0, 0, 0]
        groups = (nums8, nums16, nums32)
        ci = 0
        for kind, K in spec:
            if kind == "num":
                g = K  # for num entries, K carries the transfer group id
                parts.append(groups[g][:, idx[g]].astype(jnp.float32)[:, None])
                idx[g] += 1
            else:
                codes = cats[:, ci]
                ci += 1
                oh = (codes[:, None] == jnp.arange(K, dtype=jnp.int32)[None, :]
                      ).astype(jnp.float32)
                if not use_all and K > 0:
                    oh = oh[:, 1:]
                parts.append(oh)
        X = jnp.concatenate(parts, axis=1)
        if standardized:
            X = (X - means[None, :]) / stds[None, :]
        # trailing NaN cleanup, mirroring fit_transform/transform
        X = jnp.nan_to_num(X, nan=0.0)
        if add_intercept:
            X = jnp.concatenate(
                [X, jnp.ones((X.shape[0], 1), jnp.float32)], axis=1)
        return X

    return jax.jit(expand)


class JobCancelled(RuntimeError):
    """Raised inside a training driver when its Job was cancelled
    (`water.Job.JobCancelledException` — cancellation takes effect at the
    driver's next safe point, a scoring boundary)."""


class ScoringHistory(list):
    """Scoring-history rows, list-compatible AND callable: h2o-py's
    `model.scoring_history()` returns a table, while this framework's
    drivers (and earlier rounds' tests) index the rows directly — one
    object serves both surfaces."""

    def __call__(self, use_pandas: bool = False):
        cols = {}
        for k in (list(self[0]) if self else []):
            vals = [r.get(k) for r in self]
            if isinstance(vals[0], str):
                cols[k] = np.asarray(vals, dtype=object)
            else:
                cols[k] = np.asarray(
                    [np.nan if v is None else float(v) for v in vals])
        fr = Frame.from_dict(cols) if cols else Frame({})
        if use_pandas:
            return fr.as_data_frame(use_pandas=True)
        return fr


# scoring-program row bucket: jitted scorer inputs (tree _margins, GLM
# scoring design) quantize their row dimension to this multiple so nearby
# frame sizes share one compiled program (each extra program is a tunnel
# compile round-trip cold). ONE constant — tree and GLM must bucket alike.
SCORE_ROW_BUCKET = 512


@dataclass
class Job:
    """`water.Job` — progress/cancel tracking for a training run."""

    dest: str
    description: str = ""
    start_time: float = 0.0
    end_time: float = 0.0
    progress: float = 0.0
    status: str = "CREATED"  # CREATED/RUNNING/DONE/FAILED/CANCELLED
    warnings: List[str] = field(default_factory=list)
    cancel_requested: bool = False
    # observability spine: the REST request (or client call) that created
    # this job stamps its trace id here, so the job's worker thread — and
    # every trainpool candidate under it — records spans in the same trace
    trace_id: Optional[str] = None

    def start(self):
        self.start_time = time.time()
        self.status = "RUNNING"
        return self

    def update(self, progress: float):
        self.progress = float(progress)

    def cancel(self):
        """Request cancellation (`DELETE /3/Jobs/{id}` / Job.stop): takes
        effect at the driver's next safe point."""
        if self.status in ("CREATED", "RUNNING"):
            self.cancel_requested = True

    def check_cancelled(self):
        """Driver-side safe point: finalize + raise if a cancel is pending."""
        if self.cancel_requested and self.status == "RUNNING":
            self.status = "CANCELLED"
            self.end_time = time.time()
            raise JobCancelled(self.dest)

    def done(self):
        self.end_time = time.time()
        self.progress = 1.0
        self.status = "DONE"

    @property
    def run_time(self) -> float:
        return (self.end_time or time.time()) - self.start_time


class ScoreKeeper:
    """`hex.ScoreKeeper.stopEarly` — moving-average early stopping."""

    def __init__(self, stopping_rounds: int, stopping_metric: str, tolerance: float,
                 larger_is_better: Optional[bool] = None):
        self.k = stopping_rounds
        self.metric = stopping_metric
        self.tol = tolerance
        if larger_is_better is None:
            larger_is_better = stopping_metric.lower() in ("auc", "pr_auc", "accuracy", "r2")
        self.more = larger_is_better
        self.history: List[float] = []

    def record(self, value: float) -> bool:
        """Record a scoring event; True ⇒ stop now (moving average of the
        last k events is not better than the best before them by > tol)."""
        self.history.append(float(value))
        k = self.k
        if k <= 0 or len(self.history) < 2 * k:
            return False
        hist = np.asarray(self.history)
        recent = hist[-k:].mean()
        prior = hist[:-k]
        best_prior = prior.max() if self.more else prior.min()
        margin = self.tol * max(abs(best_prior), 1e-12)
        if self.more:
            return recent <= best_prior + margin
        return recent >= best_prior - margin


class DataInfo:
    """`hex.DataInfo` — Frame → dense numeric design matrix.

    use_all_factor_levels / standardize / imputeMissing mirror the reference
    flags; categorical expansion is one-hot (the reference's default enum
    encoding for GLM/DL)."""

    def __init__(
        self,
        frame: Frame,
        x: Sequence[str],
        standardize: bool = True,
        use_all_factor_levels: bool = False,
        impute_missing: bool = True,
        max_categorical_levels: int = 1000,
    ):
        self.x = list(x)
        self.standardize = standardize
        self.use_all = use_all_factor_levels
        self.coef_names: List[str] = []
        self._spec = []  # per input col: ("num", name) | ("cat", name, domain)
        for n in self.x:
            v = frame.vec(n)
            if v.type == "enum":
                dom = (v.domain or [])[:max_categorical_levels]
                self._spec.append(("cat", n, dom))
                levels = dom if use_all_factor_levels else dom[1:]
                self.coef_names += [f"{n}.{d}" for d in levels]
            else:
                self._spec.append(("num", n, None))
                self.coef_names.append(n)
        self.means: Optional[np.ndarray] = None
        self.stds: Optional[np.ndarray] = None
        self.impute_missing = impute_missing
        self.col_means: Dict[str, float] = {}

    def fit_transform(self, frame: Frame) -> np.ndarray:
        X = self._expand(frame, fit=True)
        if self.standardize:
            from ..parallel import distdata

            if distdata.multiprocess():
                # global moments across the multi-host cloud (the MRTask
                # mean/σ reduce) — local stats would skew each shard.
                # Two-pass: mean first, then Σ(x−μ)² — the one-pass
                # E[x²]−E[x]² form cancels catastrophically for columns
                # with large mean and small spread
                finite = ~np.isnan(X)
                s = distdata.global_sum(np.nansum(X, axis=0))
                c = np.maximum(distdata.global_sum(finite.sum(axis=0)), 1.0)
                self.means = s / c
                dev2 = distdata.global_sum(
                    np.nansum((X - self.means) ** 2, axis=0))
                self.stds = np.sqrt(dev2 / c)
            else:
                self.means = np.nanmean(X, axis=0)
                self.stds = np.nanstd(X, axis=0)
            self.stds = np.where(self.stds < 1e-10, 1.0, self.stds)
            X = (X - self.means) / self.stds
        return np.nan_to_num(X, nan=0.0).astype(np.float32)

    def transform(self, frame: Frame) -> np.ndarray:
        X = self._expand(frame, fit=False)
        if self.standardize and self.means is not None:
            X = (X - self.means) / self.stds
        return np.nan_to_num(X, nan=0.0).astype(np.float32)

    def device_design(self, frame: Frame, fit: bool,
                      add_intercept: bool = False, cloud=None,
                      quota: Optional[int] = None,
                      row_bucket: int = 0):
        """Expanded design matrix built ON DEVICE from compact columns.

        Semantically identical to fit_transform/transform (same one-hot
        layout, imputation, standardization — the stats are derived
        analytically from the codes), but the host→device transfer is the
        compact representation (numeric f32 + categorical int32 codes,
        ~P_cat× smaller than the dense one-hot), and the expansion runs as
        one compiled program. This is what makes wide-categorical GLM
        viable through a remote-chip tunnel.

        With `cloud` (a mesh of >1 devices, possibly multi-process) the
        compact packs are assembled as ROW-SHARDED global arrays (padded to
        `quota` rows per process) and expanded in place, so multi-device
        meshes get the same byte-compressed transfer as a single chip —
        no dense f32 upload and no unsharded intermediate on device 0.
        Requires fitted stats (fit=False; call fit_transform first — its
        global-moment collectives keep standardization identical to the
        dense path on every cloud size)."""
        import jax
        import jax.numpy as jnp

        n = frame.nrow
        nums, cats = [], []
        means, stds = [], []
        # wide-numeric fast pre-pass: per-column nanmean/nanstd/isnan calls
        # cost ~2 s of host time at MNIST width (784 × 60k); batching them
        # as axis-0 reductions over one stacked matrix is ~10× cheaper and
        # numerically identical
        _num_cols = [nm for k, nm, _ in self._spec if k == "num"]
        _pre = {}
        if len(_num_cols) > 8:
            mat = np.stack([frame.vec(nm).numeric_np()
                            for nm in _num_cols], axis=1)
            nan_mask = np.isnan(mat)
            has_nan_vec = nan_mask.any(axis=0)
            if fit:
                has_valid = ~nan_mask.all(axis=0)
                with np.errstate(all="ignore"):
                    mvec = np.where(has_valid, np.nanmean(mat, axis=0), 0.0)
                    svec = np.where(has_valid, np.nanstd(mat, axis=0), 0.0)
                nvalid = (~nan_mask).sum(axis=0)
                # isfinite-else-0.0, matching the narrow per-column path:
                # nan_to_num would map an infinite column mean to ±1.8e308
                # and diverge the standardization stats by frame width
                _pre = {nm: (mat[:, j], bool(has_nan_vec[j]),
                             float(mvec[j]) if np.isfinite(mvec[j]) else 0.0,
                             float(svec[j]) if np.isfinite(svec[j]) else 0.0,
                             int(nvalid[j]))
                        for j, nm in enumerate(_num_cols)}
            else:
                # scoring path: stats come from the stored fit-time values;
                # only the column data + NaN flags are needed
                _pre = {nm: (mat[:, j], bool(has_nan_vec[j]), 0.0, 0.0, 0)
                        for j, nm in enumerate(_num_cols)}
        pos = 0  # expanded-column position (for stored-stat lookups)
        for kind, name, dom in self._spec:
            v = frame.vec(name)
            if kind == "num":
                if name in _pre:
                    c, has_nan, pre_m, pre_s, n_ok = _pre[name]
                else:
                    c = v.numeric_np()
                    has_nan = bool(np.isnan(c).any())
                    n_ok = int((~np.isnan(c)).sum()) if fit else 0
                    pre_m = pre_s = 0.0
                    if fit:
                        with np.errstate(all="ignore"):
                            pre_m = (float(np.nanmean(c)) if n_ok else 0.0)
                            pre_s = (float(np.nanstd(c)) if n_ok else 0.0)
                        pre_m = pre_m if np.isfinite(pre_m) else 0.0
                        pre_s = pre_s if np.isfinite(pre_s) else 0.0
                if self.impute_missing:
                    if fit:
                        self.col_means[name] = pre_m
                    if has_nan:
                        c = np.where(np.isnan(c),
                                     self.col_means.get(name, 0.0), c)
                        # post-impute plain std: mean-filling leaves the
                        # mean unchanged and shrinks the variance by the
                        # valid-row fraction (exactly, analytically)
                        pre_s = pre_s * float(np.sqrt(n_ok / max(n, 1)))
                if fit and self.standardize:
                    # stats over valid rows only (nanmean/nanstd), exactly
                    # like fit_transform. All-NaN columns get (0, 1) so
                    # they standardize to the zeros fit_transform's
                    # trailing nan_to_num produces.
                    means.append([pre_m])
                    stds.append([pre_s if pre_s >= 1e-10 else 1.0])
                if not self.impute_missing and has_nan:
                    if self.standardize:
                        # fit_transform zeroes missing AFTER scaling, so the
                        # raw fill that standardizes to 0 is the column mean
                        mm = (means[-1][0] if fit
                              else float(self.means[pos])
                              if self.means is not None else 0.0)
                        c = np.where(np.isnan(c), mm, c)
                    else:
                        c = np.nan_to_num(c, nan=0.0)
                nums.append(c.astype(np.float32))
                pos += 1
            else:
                codes = np.asarray(v.data)
                if v.domain != dom and v.domain:
                    remap = np.asarray(
                        [dom.index(d) if d in dom else -1 for d in v.domain],
                        np.int64)
                    codes = np.where(codes >= 0, remap[np.maximum(codes, 0)], -1)
                cats.append(codes.astype(np.int32))
                if fit and self.standardize:
                    K = len(dom)
                    cnt = np.bincount(codes[codes >= 0], minlength=K)[:K]
                    p_lvl = cnt / max(n, 1)
                    lv = p_lvl if self.use_all else p_lvl[1:]
                    means.append(lv.tolist())
                    stds.append([float(s) if (s := np.sqrt(pl * (1 - pl))) >= 1e-10
                                 else 1.0 for pl in lv])
                pos += len(dom) if self.use_all else max(len(dom) - 1, 0)
        if fit and self.standardize:
            self.means = np.asarray(
                [m for grp in means for m in grp], np.float64)
            self.stds = np.asarray(
                [s for grp in stds for s in grp], np.float64)

        cats_a = (np.stack(cats, axis=1) if cats
                  else np.zeros((n, 0), np.int32))
        # per-column transfer dtype: integer-valued small-range columns
        # ship as 1–2 bytes/value (LOSSLESS — C1Chunk/C2Chunk parity);
        # everything else as f32. Group id rides the spec signature, so the
        # layout is FROZEN at fit: scoring frames reuse the training
        # program when their values still fit the stored dtypes, and fall
        # back to ONE stable all-f32 program otherwise (per-frame
        # re-derivation would churn fresh XLA compiles on every frame
        # whose integrality/range differs).
        def _fits_group(c, g):
            if g == 2:
                return True
            if not c.size:
                return False
            with np.errstate(invalid="ignore"):
                if not bool(np.all(np.mod(c, 1.0) == 0.0)):
                    return False
            lo, hi = (0.0, 255.0) if g == 0 else (-32768.0, 32767.0)
            return bool(lo <= c.min() and c.max() <= hi)

        def _local_groups():
            out = []
            for c in nums:
                out.append(0 if _fits_group(c, 0)
                           else 1 if _fits_group(c, 1) else 2)
            return out

        from ..parallel import distdata

        multiproc = cloud is not None and distdata.multiprocess()
        if fit:
            num_group = _local_groups()
            self._transfer_groups = list(num_group)
        else:
            stored = getattr(self, "_transfer_groups", None)
            ok = bool(stored is not None and len(stored) == len(nums) and all(
                _fits_group(c, g) for c, g in zip(nums, stored)))
            if multiproc:
                # pack layout is part of the compiled program: every rank
                # must make the SAME stored-vs-fallback decision
                ok = bool(distdata.allgather_host(
                    np.asarray([ok], np.int32)).all())
            if ok:
                num_group = stored
            elif cloud is not None:
                # sharded ingest with no (usable) fit-time decision: decide
                # now, globally — per-rank data ranges differ, so take the
                # widest group each column needs anywhere
                num_group = _local_groups()
                if multiproc:
                    num_group = list(distdata.allgather_host(
                        np.asarray(num_group, np.int32)
                    ).reshape(-1, len(num_group)).max(axis=0)) if nums else []
                    num_group = [int(g) for g in num_group]
                if stored is None:
                    self._transfer_groups = list(num_group)
            else:
                num_group = [2] * len(nums)
        groups = ([], [], [])                 # uint8, int16, f32
        for c, g in zip(nums, num_group):
            groups[g].append(c)
        dts = (np.uint8, np.int16, np.float32)
        packs = [
            (np.stack(g, axis=1).astype(dt) if g
             else np.zeros((n, 0), dt))
            for g, dt in zip(groups, dts)
        ]
        gi = iter(num_group)
        sig = (tuple((k, next(gi) if k == "num" else (len(d) if d else 0))
                     for k, _, d in self._spec),
               self.use_all, self.standardize and self.means is not None,
               add_intercept)
        fn = _device_expand_fn(sig)
        m_h = (np.asarray(self.means, np.float32)
               if self.standardize and self.means is not None
               else np.zeros(0, np.float32))
        s_h = (np.asarray(self.stds, np.float32)
               if self.standardize and self.stds is not None
               else np.ones(0, np.float32))
        if row_bucket and cloud is None:
            from ..parallel.mesh import pad_to_multiple

            # quantize the expand program's row dimension: nearby scoring
            # frame sizes (CV folds, pages) reuse ONE compiled program; the
            # zero-filled pad rows expand to garbage the CALLER slices off
            npad_b = pad_to_multiple(n, row_bucket)
            if npad_b != n:
                packs = [np.concatenate(
                    [p, np.zeros((npad_b - n,) + p.shape[1:], p.dtype)])
                    for p in packs]
                cats_a = np.concatenate(
                    [cats_a, np.zeros((npad_b - n, cats_a.shape[1]),
                                      cats_a.dtype)])

        from ..runtime import phases as _phases

        nbytes = sum(p.nbytes for p in packs) + cats_a.nbytes
        if cloud is not None and (cloud.size > 1 or multiproc):
            from ..parallel import mesh as cloudlib

            if quota is None:
                # every rank must agree on the padded per-process rows
                quota = (distdata.local_quota(n) if multiproc
                         else cloudlib.pad_to_multiple(n, cloud.size))
            m_r = distdata.replicated_array(m_h, cloud)
            s_r = distdata.replicated_array(s_h, cloud)

            def _sharded():
                gp = [distdata.global_row_array(pk, quota, cloud)
                      for pk in packs]
                gc = distdata.global_row_array(cats_a, quota, cloud)
                return fn(gp[0], gp[1], gp[2], gc, m_r, s_r)

            return _phases.accounted_h2d(_sharded, nbytes)
        return _phases.accounted_h2d(
            lambda: fn(jnp.asarray(packs[0]), jnp.asarray(packs[1]),
                       jnp.asarray(packs[2]), jnp.asarray(cats_a),
                       jnp.asarray(m_h), jnp.asarray(s_h)),
            nbytes)

    def _expand(self, frame: Frame, fit: bool) -> np.ndarray:
        cols = []
        for kind, n, dom in self._spec:
            v = frame.vec(n)
            if kind == "num":
                c = v.numeric_np()
                if self.impute_missing:
                    if fit:
                        from ..parallel import distdata

                        if distdata.multiprocess():
                            # global imputation mean — a local shard mean
                            # would bake different values into each
                            # process's design matrix (and into the saved
                            # model's col_means)
                            sc = distdata.global_sum(np.asarray(
                                [np.nansum(c), float((~np.isnan(c)).sum())],
                                np.float64))
                            self.col_means[n] = float(sc[0] / max(sc[1], 1.0))
                        else:
                            self.col_means[n] = float(np.nanmean(c))
                    c = np.where(np.isnan(c), self.col_means.get(n, 0.0), c)
                cols.append(c[:, None])
            else:
                codes = np.asarray(v.data)
                if v.domain != dom and v.domain:
                    remap = np.asarray(
                        [dom.index(d) if d in dom else -1 for d in v.domain], np.int64
                    )
                    codes = np.where(codes >= 0, remap[np.maximum(codes, 0)], -1)
                K = len(dom)
                oh = np.zeros((len(codes), K))
                valid = codes >= 0
                oh[np.nonzero(valid)[0], codes[valid]] = 1.0
                if not self.use_all and K > 0:
                    oh = oh[:, 1:]
                cols.append(oh)
        return np.concatenate(cols, axis=1) if cols else np.zeros((frame.nrow, 0))


class H2OModel:
    """Trained-model half of `hex.Model` + the `h2o-py` ModelBase surface."""

    algo = "base"

    def __init__(self, params: "H2OEstimator"):
        self.parms = params
        # honour a user-chosen model_id (estimator parameter), else generate
        user_id = None
        if hasattr(params, "_parms"):
            user_id = params._parms.get("model_id")
        self.model_id = user_id or f"{self.algo}_{next(_model_counter)}"
        self.training_metrics: Optional[ModelMetricsBase] = None
        self.validation_metrics: Optional[ModelMetricsBase] = None
        self.cross_validation_metrics: Optional[ModelMetricsBase] = None
        self.scoring_history: ScoringHistory = ScoringHistory()
        self.varimp_table: Optional[List] = None
        self.run_time: float = 0.0
        self._cv_holdout_pred: Optional[np.ndarray] = None
        self.cross_validation_models: Optional[List] = None

    # -- metric accessors (h2o-py ModelBase) --------------------------------
    def _m(self, valid=False, xval=False):
        if xval and self.cross_validation_metrics:
            return self.cross_validation_metrics
        if valid and self.validation_metrics:
            return self.validation_metrics
        return self.training_metrics

    def auc(self, valid=False, xval=False):
        return getattr(self._m(valid, xval), "auc", float("nan"))

    def logloss(self, valid=False, xval=False):
        return getattr(self._m(valid, xval), "logloss", float("nan"))

    def rmse(self, valid=False, xval=False):
        return self._m(valid, xval).rmse

    def mse(self, valid=False, xval=False):
        return self._m(valid, xval).mse

    def mae(self, valid=False, xval=False):
        return getattr(self._m(valid, xval), "mae", float("nan"))

    def r2(self, valid=False, xval=False):
        return getattr(self._m(valid, xval), "r2", float("nan"))

    def mean_per_class_error(self, valid=False, xval=False):
        return getattr(self._m(valid, xval), "mean_per_class_error", float("nan"))

    def varimp(self, use_pandas=False):
        return self.varimp_table

    def summary(self):
        """Model summary table (h2o-py ModelBase.summary) — generic form;
        concrete models override with their architecture specifics."""
        return dict(model_id=self.model_id, algo=self.algo,
                    run_time_s=round(self.run_time, 3))

    def show(self):
        print(f"Model: {self.model_id} ({self.algo})")
        for k, v in self.summary().items():
            print(f"  {k}: {v}")
        if self.training_metrics is not None:
            print(f"  training: {self.training_metrics._ser()}")

    def gains_lift(self, valid=False, xval=False):
        m = self._m(valid, xval)
        return m.gains_lift() if hasattr(m, "gains_lift") else None

    def roc(self, valid=False, xval=False):
        m = self._m(valid, xval)
        return m.roc() if hasattr(m, "roc") else None

    def predict(self, test_data: Frame) -> Frame:
        raise NotImplementedError

    def scoring_signature(self) -> tuple:
        """(n_features, dtype) identifying this model's compiled
        scoring-program family — the shape-bearing parts of the serving
        cache key (serving/model_cache.py). Two models under the same DKV
        key with different signatures can never share an executable."""
        x = getattr(self, "x", None)
        nf = len(x) if isinstance(x, (list, tuple)) else (1 if x else 0)
        return (nf, "float32")

    def model_performance(self, test_data: Optional[Frame] = None, **kw):
        if test_data is None:
            return self.training_metrics
        return self._make_metrics(test_data)

    def _make_metrics(self, frame: Frame):
        raise NotImplementedError

    # -- model understanding (h2o-py ModelBase surface) ---------------------
    @staticmethod
    def _response_stats(p: np.ndarray, weights: Optional[np.ndarray]):
        """(mean, sd, sem) of one response column, optionally weighted."""
        if weights is None:
            mean = float(np.mean(p))
            sd = float(np.std(p, ddof=1)) if len(p) > 1 else 0.0
        else:
            wsum = max(float(weights.sum()), 1e-12)
            mean = float((p * weights).sum() / wsum)
            sd = float(np.sqrt(((p - mean) ** 2 * weights).sum() / wsum))
        return mean, sd, sd / max(np.sqrt(len(p)), 1.0)

    def _response_column(self, pred: Frame, target: Optional[str]) -> np.ndarray:
        """Pick the response column of a prediction frame — a chosen class
        probability, binomial p1, or the raw (regression) prediction."""
        if target is not None:
            return pred.vec(str(target)).numeric_np().astype(np.float64)
        domain = getattr(self, "domain", None)
        if domain is not None and len(domain) == 2 and str(domain[1]) in pred.names:
            return pred.vec(str(domain[1])).numeric_np().astype(np.float64)
        if domain is not None and len(domain) > 2:
            raise ValueError(
                "multinomial models need `targets=[<class label>, ...]` "
                "(averaging the predicted class labels is meaningless — "
                "hex/PartialDependence requires targets too)")
        return pred.vec("predict").numeric_np().astype(np.float64)

    def partial_plot(self, data: Frame, cols=None, nbins: int = 20,
                     plot: bool = False, include_na: bool = False,
                     user_splits=None, targets=None, row_index=None,
                     weight_column: Optional[str] = None, **_kw):
        """Partial-dependence tables, one Frame per column (× target for
        multinomial): columns [<col>, mean_response, stddev_response,
        std_error_mean_response]. 1-D PDP over nbins grid points (numeric) or
        the categorical levels — `h2o-py ModelBase.partial_plot` /
        `hex/PartialDependence.java`. `row_index` gives a single-row ICE
        curve instead of the dataset mean."""
        if cols is None:
            raise ValueError("cols is required")
        if isinstance(cols, str):
            cols = [cols]
        if row_index is not None:
            data = Frame({n: v.take(np.asarray([row_index]))
                          for n, v in data._vecs.items()})
        weights = None
        if weight_column is not None:
            weights = data.vec(weight_column).numeric_np().astype(np.float64)
        tlist = list(targets) if targets else [None]
        out = []
        for col in cols:
            v = data.vec(col)
            if v.type == "enum":
                values = list(range(len(v.domain or [])))
                labels = list(v.domain or [])
            else:
                raw = v.numeric_np()
                raw = raw[~np.isnan(raw)]
                if user_splits and col in user_splits:
                    values = list(user_splits[col])
                else:
                    lo, hi = (float(raw.min()), float(raw.max())) if len(raw) else (0.0, 1.0)
                    values = list(np.linspace(lo, hi, nbins))
                labels = values
            if include_na:
                values = values + [np.nan]
                labels = labels + [float("nan") if v.type != "enum" else ".missing(NA)"]
            # ONE predict per grid value; every target reads its own column
            rows = {tgt: [] for tgt in tlist}
            for val in values:
                n = data.nrow
                if v.type == "enum":
                    is_na = isinstance(val, float) and np.isnan(val)
                    code = -1 if is_na else int(val)
                    const = Vec(np.full(n, code, np.int32), "enum",
                                domain=v.domain)
                else:
                    const = Vec(np.full(n, val, np.float64), "real")
                pred = self.predict(Frame({**data._vecs, col: const}))
                for tgt in tlist:
                    p = self._response_column(pred, tgt)
                    rows[tgt].append(self._response_stats(p, weights))
            for tgt in tlist:
                d = {
                    col: (np.asarray(labels, dtype=object) if v.type == "enum"
                          else np.asarray(labels, np.float64)),
                    "mean_response": np.asarray([r[0] for r in rows[tgt]]),
                    "stddev_response": np.asarray([r[1] for r in rows[tgt]]),
                    "std_error_mean_response": np.asarray(
                        [r[2] for r in rows[tgt]]),
                }
                fr_out = Frame.from_dict(
                    d, column_types={col: "enum"} if v.type == "enum" else None)
                if tgt is not None:
                    fr_out.target = tgt
                out.append(fr_out)
        return out

    def permutation_importance(self, frame: Frame, metric: str = "AUTO",
                               n_samples: int = -1, n_repeats: int = 1,
                               features=None, seed: int = -1,
                               use_pandas: bool = False) -> Frame:
        """Permutation variable importance (`h2o-py permutation_varimp` /
        `hex/PermutationVarImp.java`): |metric(baseline) − metric(feature
        shuffled)|, averaged over n_repeats."""
        problem = getattr(self, "problem", None)
        if metric in ("AUTO", "auto", None):
            metric = {"binomial": "auc", "multinomial": "logloss"}.get(
                problem, "rmse")
        metric = metric.lower()
        rng = np.random.default_rng(None if seed in (-1, None) else seed)
        if 0 < n_samples < frame.nrow:
            idx = rng.choice(frame.nrow, n_samples, replace=False)
            frame = Frame({n: v.take(idx) for n, v in frame._vecs.items()})
        base = getattr(self._make_metrics(frame), metric)
        feats = list(features) if features else list(self.x)
        rel = []
        for f in feats:
            deltas = []
            v = frame.vec(f)
            for _ in range(max(n_repeats, 1)):
                perm = rng.permutation(frame.nrow)
                shuf = Vec(np.asarray(v.data)[perm] if v.data is not None else None,
                           v.type, domain=v.domain)
                m = getattr(self._make_metrics(Frame({**frame._vecs, f: shuf})),
                            metric)
                deltas.append(abs(base - m))
            rel.append(float(np.mean(deltas)))
        rel_a = np.asarray(rel, np.float64)
        mx = rel_a.max() if rel_a.size and rel_a.max() > 0 else 1.0
        tot = rel_a.sum() if rel_a.sum() > 0 else 1.0
        order = np.argsort(-rel_a)
        return Frame.from_dict({
            "Variable": np.asarray(feats, dtype=object)[order],
            "Relative Importance": rel_a[order],
            "Scaled Importance": rel_a[order] / mx,
            "Percentage": rel_a[order] / tot,
        })


class H2OEstimator:
    """Parameter-holder + builder — `hex.ModelBuilder` merged with the
    generated `h2o-py` estimator classes (h2o-bindings/bin/gen_python.py).

    Subclasses define `_param_defaults` and `_fit`; unknown kwargs raise like
    the reference's schema validation does."""

    algo = "base"
    supervised = True  # class-level default; see _is_supervised()
    _param_defaults: Dict[str, Any] = {}
    _common_defaults: Dict[str, Any] = dict(
        model_id=None,
        seed=-1,
        max_runtime_secs=0.0,
        ignored_columns=None,
        ignore_const_cols=True,
        weights_column=None,
        offset_column=None,
        fold_column=None,
        nfolds=0,
        fold_assignment="AUTO",
        keep_cross_validation_predictions=False,
        keep_cross_validation_models=True,
        stopping_rounds=0,
        stopping_metric="AUTO",
        stopping_tolerance=0.001,
        score_each_iteration=False,
        categorical_encoding="AUTO",
        export_checkpoints_dir=None,
        checkpoint=None,
    )

    def __init__(self, **kwargs):
        self._parms: Dict[str, Any] = dict(self._common_defaults)
        self._parms.update(self._param_defaults)
        for k, v in kwargs.items():
            if k not in self._parms:
                raise TypeError(f"{type(self).__name__}: unknown parameter {k!r}")
            self._parms[k] = v
        self._model: Optional[H2OModel] = None
        self.job: Optional[Job] = None

    def __getattr__(self, name):
        parms = object.__getattribute__(self, "_parms")
        if name in parms:
            return parms[name]
        raise AttributeError(name)

    def __setattr__(self, name, value):
        if name.startswith("_") or name in ("job",):
            object.__setattr__(self, name, value)
        elif name in self._parms:
            self._parms[name] = value
        else:
            object.__setattr__(self, name, value)

    def _is_supervised(self) -> bool:
        """Instance-level supervision check — overridable where a parameter
        flips it (e.g. DeepLearning autoencoder=True)."""
        return type(self).supervised

    @property
    def actual_params(self) -> Dict[str, Any]:
        return dict(self._parms)

    # -- training entrypoint (estimator_base.train) -------------------------
    def train(
        self,
        x: Optional[Sequence[str]] = None,
        y: Optional[str] = None,
        training_frame: Optional[Frame] = None,
        validation_frame: Optional[Frame] = None,
        **kw,
    ) -> "H2OEstimator":
        if training_frame is None:
            raise ValueError("training_frame is required")
        if self._is_supervised() and y is None:
            raise ValueError(f"{self.algo}: response column y is required")
        if getattr(training_frame, "_is_remote", False):
            # the frame lives on an attached server: train over REST and
            # bind a RemoteModel — the delegation surface below then works
            # unchanged (h2o-py estimator_base semantics). Dispatch AFTER
            # the client-side arg validation so bad calls raise locally.
            from ..client import remote_train

            return remote_train(self, x, y, training_frame, validation_frame)
        ignored = set(self._parms.get("ignored_columns") or [])
        if x is None:
            x = [
                n for n in training_frame.names
                if n != y and n not in ignored
                and n not in (self._parms.get("weights_column"),
                              self._parms.get("offset_column"),
                              self._parms.get("fold_column"))
            ]
        else:
            x = [training_frame.names[i] if isinstance(i, int) else i for i in x]
            x = [n for n in x if n != y and n not in ignored]
        if self._parms.get("ignore_const_cols", True):
            x = [n for n in x if not _is_const(training_frame.vec(n))]

        if self._is_supervised() and y is not None:
            # rows with a missing response are dropped before training —
            # ModelBuilder.init response filtering (hex/ModelBuilder.java)
            na = training_frame.vec(y).isna_np()
            if na.any():
                training_frame = training_frame.take(np.nonzero(~na)[0])
            if validation_frame is not None:
                nav = validation_frame.vec(y).isna_np()
                if nav.any():
                    validation_frame = validation_frame.take(np.nonzero(~nav)[0])

        # a REST-created Job (h_train) rides through so /3/Jobs progress and
        # cancellation act on THE job driving this estimator
        ext = getattr(self, "_external_job", None)
        self.job = ext if ext is not None else Job(
            dest=f"{self.algo}_{next(_model_counter)}",
            description=f"{self.algo} train")
        if self.job.status == "CREATED":
            self.job.start()
        t0 = time.time()
        seed = int(self._parms.get("seed", -1))
        if seed in (-1, None):
            self._parms["_actual_seed"] = 1234
        else:
            self._parms["_actual_seed"] = seed

        nfolds = int(self._parms.get("nfolds") or 0)
        if nfolds < 0 or nfolds == 1:
            raise ValueError(
                f"nfolds must be 0 (no CV) or >= 2, got {nfolds}")
        fold_col = self._parms.get("fold_column")
        if fold_col and nfolds:
            raise ValueError(
                "specify EITHER nfolds OR fold_column, not both "
                "(hex/ModelBuilder cv_init)")
        if fold_col and fold_col not in training_frame.names:
            raise ValueError(f"fold_column {fold_col!r} not in frame")
        model = self._fit(x, y, training_frame, validation_frame)
        # a fold_column triggers CV by itself (its folds are the column's
        # distinct values) — but only for estimators that CAN cross-
        # validate: TargetEncoder-style builders consume fold_column for
        # their own leakage handling inside _fit and define no _cv_predict
        supports_cv = type(self)._cv_predict is not H2OEstimator._cv_predict
        if ((nfolds >= 2 or (fold_col and supports_cv))
                and self._is_supervised()):
            self._run_cv(model, x, y, training_frame, nfolds)
        model.run_time = time.time() - t0
        self._model = model
        from ..runtime.dkv import DKV

        DKV.put(model.model_id, model)  # h2o.get_model / h2o.models surface
        # result before done(): a REST poller that sees DONE must be able to
        # fetch the model that instant (h_train's thread sets result later,
        # which would leave a 404 window)
        self.job.result = model.model_id
        self.job.done()
        ckpt_dir = self._parms.get("export_checkpoints_dir")
        if ckpt_dir:
            # auto-export the finished model (Model export_checkpoints_dir)
            try:
                from ..mojo import save_model

                save_model(model, ckpt_dir, force=True)
            except TypeError:
                pass  # artifact format doesn't cover this algo yet
        return self

    # -- n-fold CV (ModelBuilder.computeCrossValidation) --------------------
    def _run_cv(self, model: H2OModel, x, y, train: Frame, nfolds: int):
        n = train.nrow
        rng = np.random.default_rng(self._parms["_actual_seed"])
        fold_col = self._parms.get("fold_column")
        if fold_col:
            assign = train.vec(fold_col).numeric_np().astype(np.int64)
            folds = np.unique(assign)
        else:
            mode = self._parms.get("fold_assignment", "AUTO")
            if mode in ("AUTO", "Random"):
                assign = rng.integers(0, nfolds, n)
            elif mode == "Modulo":
                assign = np.arange(n) % nfolds
            else:  # Stratified — approximate by per-class modulo
                yv = train.vec(y).numeric_np()
                order = np.argsort(yv, kind="mergesort")
                assign = np.empty(n, np.int64)
                assign[order] = np.arange(n) % nfolds
            folds = np.arange(nfolds)
        # -- CV fold reuse ---------------------------------------------------
        # Tree builders expose the parent fit's BinnedMatrix; folds then
        # reuse its codes via row-index slicing instead of two full
        # `Frame.take` copies + a per-fold re-bin/re-pack (LightGBM/XGBoost-
        # style CV over one quantized matrix). The fold frame shrinks to the
        # response + any *_column parameters. H2O3_CV_REBIN=1 (or the bench
        # comparator H2O3_TRAIN_LEGACY=1) restores the seed per-fold path,
        # which stays bit-exact with earlier rounds.
        import os as _os

        from ..parallel import distdata
        from ..runtime import trainpool as _trainpool

        reuse_bm = None
        if (_os.environ.get("H2O3_CV_REBIN", "") in ("", "0")
                and not _trainpool.legacy()
                and not distdata.multiprocess()
                and self._cv_can_reuse()):
            reuse_bm = self._cv_reuse_source(model, train)
        keep_cols = [y] + sorted(
            v for k, v in self._parms.items()
            if k.endswith("_column") and isinstance(v, str)
            and v in train.names and v != y)

        holdout = None
        cv_models = []
        for f in folds:
            idx_tr = np.nonzero(assign != f)[0]
            idx_ho = np.nonzero(assign == f)[0]
            sub = type(self)()
            sub._parms.update(
                {k: v for k, v in self._parms.items() if not k.startswith("_")}
            )
            sub._parms["nfolds"] = 0
            sub._parms["model_id"] = None  # fold models get their own ids
            sub._parms["_actual_seed"] = self._parms["_actual_seed"]
            _trainpool.record_cv_fold(reused=reuse_bm is not None)
            if reuse_bm is not None:
                # reuse folds take their NATURAL row bucket instead of the
                # parent's padded shape: pad rows are zero-weight no-ops
                # (results are padded-shape invariant), every fold of every
                # sweep candidate lands on the same ~((k-1)/k)-size bucket,
                # and the one extra compile amortizes across all of them —
                # while the parent shape would tax each fold ~k/(k-1)×
                # extra histogram compute forever.
                tr = Frame({nm: train.vec(nm).take(idx_tr)
                            for nm in keep_cols})
                sub._parms["_cv_reuse"] = dict(bm=reuse_bm, rows=idx_tr)
                cvm = sub._fit(x, y, tr, None)
                pred = sub._cv_predict_codes(cvm, reuse_bm.codes[idx_ho])
            else:
                # seed path: pad fold fits up to the parent's padded row
                # shape so every fold reuses the parent's compiled tree
                # program (the second program load costs seconds through a
                # remote-chip tunnel)
                sub._parms["_npad_floor"] = getattr(model, "_npad", 0)
                tr = train.take(idx_tr)
                ho = train.take(idx_ho)
                cvm = sub._fit(x, y, tr, None)
                pred = sub._cv_predict(cvm, ho)
            if holdout is None:
                holdout = np.zeros((n,) + pred.shape[1:], dtype=np.float64)
            holdout[assign == f] = pred
            if self._parms.get("keep_cross_validation_models", True):
                if reuse_bm is not None:
                    # fold validation metrics straight from the holdout
                    # prediction (same probabilities _make_metrics would
                    # score — the codes path IS the scoring path here)
                    cvm.validation_metrics = self._metrics_from_cv(
                        train.vec(y).take(idx_ho), None, pred)
                else:
                    cvm.validation_metrics = cvm._make_metrics(ho)
                cv_models.append(cvm)
        model._cv_holdout_pred = holdout
        model.cross_validation_models = cv_models or None
        model.cross_validation_metrics = self._metrics_from_cv(train.vec(y), assign, holdout)

    def _metrics_from_cv(self, yvec: Vec, assign, holdout):
        if yvec.type == "enum" and yvec.nlevels == 2:
            return ModelMetricsBinomial.make(np.asarray(yvec.data), holdout[:, -1] if holdout.ndim > 1 else holdout)
        if yvec.type == "enum":
            return ModelMetricsMultinomial.make(np.asarray(yvec.data), holdout)
        return ModelMetricsRegression.make(yvec.numeric_np(), holdout if holdout.ndim == 1 else holdout[:, 0])

    def _cv_predict(self, model: H2OModel, frame: Frame) -> np.ndarray:
        """Holdout prediction as probabilities (classif) or values (regr)."""
        raise NotImplementedError

    # -- CV fold-reuse hooks (overridden by builders that can slice a
    # parent-fit artifact per fold — see shared_tree.py) --------------------
    def _cv_can_reuse(self) -> bool:
        return False

    def _cv_reuse_source(self, model: H2OModel, train: Frame):
        return None

    def _cv_predict_codes(self, model: H2OModel, codes) -> np.ndarray:
        raise NotImplementedError

    def _fit(self, x, y, train: Frame, valid: Optional[Frame]) -> H2OModel:
        raise NotImplementedError

    # -- model delegation ---------------------------------------------------
    @property
    def model(self) -> H2OModel:
        if self._model is None:
            raise ValueError("model not trained; call train() first")
        return self._model

    def predict(self, test_data: Frame) -> Frame:
        return self.model.predict(test_data)

    def model_performance(self, test_data=None, valid=False, xval=False):
        if test_data is not None:
            return self.model.model_performance(test_data)
        return self.model._m(valid=valid, xval=xval)

    # metric passthroughs
    def auc(self, **kw):
        return self.model.auc(**kw)

    def logloss(self, **kw):
        return self.model.logloss(**kw)

    def rmse(self, **kw):
        return self.model.rmse(**kw)

    def mse(self, **kw):
        return self.model.mse(**kw)

    def varimp(self, **kw):
        return self.model.varimp(**kw)

    # model-understanding passthroughs (h2o-py keeps these on the estimator)
    def partial_plot(self, *a, **kw):
        return self.model.partial_plot(*a, **kw)

    def permutation_importance(self, *a, **kw):
        return self.model.permutation_importance(*a, **kw)

    def predict_contributions(self, *a, **kw):
        return self.model.predict_contributions(*a, **kw)

    def predict_leaf_node_assignment(self, *a, **kw):
        return self.model.predict_leaf_node_assignment(*a, **kw)

    def staged_predict_proba(self, *a, **kw):
        return self.model.staged_predict_proba(*a, **kw)

    def feature_frequencies(self, *a, **kw):
        return self.model.feature_frequencies(*a, **kw)

    @property
    def scoring_history(self):
        return self.model.scoring_history

    @property
    def model_id(self):
        return self.model.model_id


def warn_host_solver(algo: str, n_rows: int, bound: int = 500_000) -> None:
    """Long-tail algorithms solve host-side in numpy (documented in
    docs/architecture.md §"Host-side solvers"): correct at their usual
    scale, but a big frame deserves a loud heads-up rather than a silent
    slow fit."""
    if n_rows > bound:
        from ..runtime.log import Log

        Log.warn(
            f"{algo}: {n_rows} rows exceed the ~{bound} row envelope of "
            "this host-side (numpy) solver; expect host memory/time to "
            "scale accordingly (docs/architecture.md)")


def _is_const(v: Vec) -> bool:
    if v.type == "string":
        return False
    a = v.numeric_np()
    fin = a[~np.isnan(a)]
    return fin.size > 0 and float(fin.min()) == float(fin.max())


def response_info(yvec: Vec):
    """(problem_kind, nclass, domain) from the response Vec — mirrors
    ModelBuilder's distribution inference from response type."""
    if yvec.type == "enum":
        k = yvec.nlevels
        if k < 2:
            raise ValueError(
                "categorical response has fewer than two classes "
                "(ModelBuilder rejects constant responses)")
        return ("binomial" if k == 2 else "multinomial"), k, yvec.domain
    return "regression", 1, None
