"""Shared histogram-tree machinery — the engine under GBM / DRF / IF / XGBoost.

Reference parity: `h2o-algos/src/main/java/hex/tree/SharedTree.java`
(per-level driver loop), `hex/tree/DTree.java` (`DecidedNode`,
`UndecidedNode`, `Split.findBestSplitPoint` — argmax squared-error reduction
over bins), `hex/tree/ScoreBuildHistogram2.java` (the fused
score-build-histogram MRTask), and XGBoost's `gpu_hist` updater.

TPU-first redesign, not a translation:

* The reference grows trees with dynamic node objects and per-level chunk
  scans. Here a tree is a **perfect binary heap of static depth** (arrays of
  size 2^(D+1)-1) so the whole per-tree build is ONE jitted XLA program:
  unrolled levels, each = histogram → best-split → partition, all fused.
* Row partition state is a per-row level-local node index (the reference's
  "row-to-leaf assignment vec", `SharedTree` nids Vec); rows in decided-leaf
  subtrees keep flowing left so every depth-D cell inherits its deciding
  ancestor's rows — which makes the cell's Newton value equal the ancestor
  leaf's value, eliminating all dynamic control flow.
* Cross-host histogram merge is `lax.psum` (MRTask.reduce / Rabit allreduce).
* NAs live in a reserved last bin and traverse right; the split search can
  therefore isolate them (DHistogram's NA bucket semantics).
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..ops import packing
from ..ops.histogram import build_histograms, ordered_axis_fold


class Tree(NamedTuple):
    """One decision tree as flat heap arrays (length 2^(D+1)-1)."""

    feat: jax.Array      # int32, split feature per node (0 where not split)
    bin: jax.Array       # int32, split bin per node
    thr: jax.Array       # f32, raw-value threshold (left iff x <= thr)
    is_split: jax.Array  # bool
    value: jax.Array     # f32, Newton leaf value at every node


def heap_size(depth: int) -> int:
    return 2 ** (depth + 1) - 1


def compact_switch_depth(max_depth: int, compact_cap: int) -> int:
    """First level handled by active-node compaction (max_depth = never) —
    the ONE switch rule shared by `build_tree` and the driver's fit-plan
    recorder (`ops.histogram.record_fit_plan`), so the recorded plan
    cannot diverge from the structure that actually runs."""
    if not compact_cap:
        return max_depth
    for d in range(max_depth):
        if 2 ** d > compact_cap:
            return d
    return max_depth


def histogram_level_plan(max_depth: int, compact_cap: int = 0):
    """(label, n_nodes) of each histogram pass a depthwise `build_tree`
    dispatches: d0 over 1 node, deeper dense levels over the PARENT count
    (sibling subtraction builds only left children), then the compact
    transition + per-level passes over compact_cap+1 slots. Consumed by
    the driver's per-fit kernel-plan recording."""
    d_sw = compact_switch_depth(max_depth, compact_cap)
    levels = [("d%d" % d, 1 if d == 0 else 2 ** (d - 1))
              for d in range(min(d_sw, max_depth))]
    if d_sw < max_depth:
        levels.append(("compact_transition", compact_cap + 1))
        levels += [("d%d" % d, compact_cap + 1)
                   for d in range(d_sw, max_depth)]
    return levels


# one-hot contraction beats a per-row dynamic gather on TPU by ~10× (the
# VPU has no fast per-lane table lookup; XLA serializes row gathers), but
# materializes an (N, L) operand — only worth it for small tables
_ONEHOT_LOOKUP_MAX = 128


def _lookup_int(table: jax.Array, idx: jax.Array, L: int) -> jax.Array:
    """table[idx] for an int32 table of length L (exact)."""
    if L > _ONEHOT_LOOKUP_MAX:
        return table[idx]
    oh = idx[:, None] == jnp.arange(L, dtype=jnp.int32)[None, :]
    return jnp.where(oh, table[None, :], 0).sum(axis=1)


def _lookup_bool(table: jax.Array, idx: jax.Array, L: int) -> jax.Array:
    """table[idx] for a bool table of length L."""
    if L > _ONEHOT_LOOKUP_MAX:
        return table[idx]
    oh = idx[:, None] == jnp.arange(L, dtype=jnp.int32)[None, :]
    return (oh & table[None, :]).any(axis=1)


def _row_feature_value(codes: jax.Array, rf: jax.Array) -> jax.Array:
    """codes[i, rf[i]] as int32 — the row-wise feature pick of the
    partition step, as a one-hot contraction over the F axis (O(N·F), so
    only for narrow frames; wide frames keep the gather)."""
    F = codes.shape[1]
    if F > _ONEHOT_LOOKUP_MAX:
        return jnp.take_along_axis(
            codes, rf[:, None].astype(jnp.int32), axis=1)[:, 0].astype(jnp.int32)
    feat_oh = rf[:, None] == jnp.arange(F, dtype=jnp.int32)[None, :]
    return jnp.where(feat_oh, codes.astype(jnp.int32), 0).sum(axis=1)


def _leaf_totals(ids, vals3, nseg: int, axis_name, n_shard_blocks: int,
                 onehot_ok: bool):
    """Exact per-cell {Σw, Σg·w, Σh·w} totals of the final tree level —
    (nseg, 3). With ``n_shard_blocks`` the accumulation runs per contiguous
    row block and folds deterministically (`ordered_axis_fold`), so leaf
    values are bit-stable across device counts; otherwise the historical
    single-pass + psum formulation is preserved bit-for-bit.

    `onehot_ok` selects the small-heap MXU one-hot matmul (Precision.
    HIGHEST — the TPU default would truncate the per-leaf g/h sums to
    bf16); the selection depends only on nseg, so every block (and every
    device count) runs the same kernel."""
    use_oh = onehot_ok and nseg <= 2 * _ONEHOT_LOOKUP_MAX

    def one(ids_b, vals_b):
        if use_oh:
            oh = (ids_b[:, None] == jnp.arange(nseg, dtype=jnp.int32)[None, :]
                  ).astype(jnp.float32)
            return jnp.dot(vals_b, oh, preferred_element_type=jnp.float32,
                           precision=jax.lax.Precision.HIGHEST).T
        return jax.ops.segment_sum(vals_b.T, ids_b, num_segments=nseg)

    if n_shard_blocks > 0:
        n = ids.shape[0]
        rows = n // n_shard_blocks
        parts = [one(ids[b * rows:(b + 1) * rows],
                     vals3[:, b * rows:(b + 1) * rows])
                 for b in range(n_shard_blocks)]
        return ordered_axis_fold(jnp.stack(parts), axis_name)
    tot = one(ids, vals3)
    if axis_name is not None:
        tot = jax.lax.psum(tot, axis_name)
    return tot


def _fused_level_best(hist, node_ok, feat_mask, keep, nbins: int, min_rows,
                      reg_lambda, reg_alpha, gsum, hsum, wsum,
                      monotone=None, lo_lvl=None, hi_lvl=None):
    """Single-pass split search (ISSUE 7 tentpole): ONE sequential pass
    over features computes each feature's (L, B) gain tile and folds it
    into a running per-node best, so a level emits only the (L,) winner
    tuple — the legacy path materializes ~6 (L, F, B) f32 temporaries
    (cumsums, thresholded sums, gain, masks) that round-trip HBM at every
    level (xgboost EvaluateSplits restructured as a running scan-argmax).

    Bit-exact with the legacy flat ``argmax(gain.reshape(L, F·B))``:
    per-feature cumsums are the same per-lane folds as the (L, F, B)
    ``jnp.cumsum`` (lanes are independent), the running compare uses
    strict ``>`` so ties keep the EARLIEST feature/bin exactly like
    argmax's first-occurrence rule, and NaN gains (possible at
    reg_lambda=0) are treated as the maximum with first-occurrence order,
    matching argmax's NaN propagation.

    Returns (best_gain, best_feat, best_bin, vL_best, vR_best) — the
    child-value pair at the winning bin is only meaningful under
    `monotone` (it feeds the bound propagation); it is 0 where no
    admissible split exists, which the caller neutralizes via the
    do_split gate."""
    L, F, B = hist.shape[0], hist.shape[1], hist.shape[2]
    G, H, W = gsum[:, None], hsum[:, None], wsum[:, None]   # (L, 1)
    tl1 = lambda A: jnp.sign(A) * jnp.maximum(jnp.abs(A) - reg_alpha, 0.0)
    Gt = tl1(G)
    base = Gt * Gt / (H + reg_lambda)                        # (L, 1)
    bin_ok = (jnp.arange(nbins) < nbins - 1)[None, :]        # no NA-bin split
    mono_on = monotone is not None

    def body(f, carry):
        best_g, best_f, best_b, vl_b, vr_b = carry
        hf = jax.lax.dynamic_index_in_dim(hist, f, axis=1, keepdims=False)
        WL = jnp.cumsum(hf[..., 0], axis=1)                  # (L, B)
        GL = jnp.cumsum(hf[..., 1], axis=1)
        HL = jnp.cumsum(hf[..., 2], axis=1)
        GR, HR, WR = G - GL, H - HL, W - WL
        GLt, GRt = tl1(GL), tl1(GR)
        gain = (GLt * GLt / (HL + reg_lambda)
                + GRt * GRt / (HR + reg_lambda) - base)
        ok = (WL >= min_rows) & (WR >= min_rows) & bin_ok
        ok = ok & (jax.lax.dynamic_index_in_dim(feat_mask, f,
                                                keepdims=False) > 0)
        ok = ok & node_ok[:, None]
        if keep is not None:
            ok = ok & jax.lax.dynamic_index_in_dim(
                keep, f, axis=1, keepdims=False)[:, None]
        if mono_on:
            vL = jnp.clip(-GLt / (HL + reg_lambda + 1e-12),
                          lo_lvl[:, None], hi_lvl[:, None])
            vR = jnp.clip(-GRt / (HR + reg_lambda + 1e-12),
                          lo_lvl[:, None], hi_lvl[:, None])
            mc = jax.lax.dynamic_index_in_dim(monotone, f, keepdims=False)
            ok = ok & ((mc == 0) | (mc * (vR - vL) >= 0))
        gain = jnp.where(ok, gain, -jnp.inf)
        bb_f = jnp.argmax(gain, axis=1).astype(jnp.int32)     # (L,)
        g_f = jnp.take_along_axis(gain, bb_f[:, None], axis=1)[:, 0]
        better = (g_f > best_g) | (jnp.isnan(g_f) & ~jnp.isnan(best_g))
        best_g = jnp.where(better, g_f, best_g)
        best_f = jnp.where(better, f, best_f).astype(jnp.int32)
        best_b = jnp.where(better, bb_f, best_b)
        if mono_on:
            vl_b = jnp.where(better, jnp.take_along_axis(
                vL, bb_f[:, None], axis=1)[:, 0], vl_b)
            vr_b = jnp.where(better, jnp.take_along_axis(
                vR, bb_f[:, None], axis=1)[:, 0], vr_b)
        return best_g, best_f, best_b, vl_b, vr_b

    init = (jnp.full(L, -jnp.inf, jnp.float32), jnp.zeros(L, jnp.int32),
            jnp.zeros(L, jnp.int32), jnp.zeros(L, jnp.float32),
            jnp.zeros(L, jnp.float32))
    return jax.lax.fori_loop(0, F, body, init)


def value_at(table: jax.Array, idx: jax.Array) -> jax.Array:
    """table[idx] for a small f32 table (e.g. leaf values by heap index) as
    an MXU one-hot matvec. Precision.HIGHEST is required: the TPU default
    truncates f32 matmul operands to bf16, which would round every leaf
    value added to the boosting margins (the one-hot operand is exact in
    any precision, so HIGHEST recovers the exact gather semantics)."""
    L = table.shape[0]
    if L > 2 * _ONEHOT_LOOKUP_MAX:
        return table[idx]
    oh = (idx[:, None] == jnp.arange(L, dtype=jnp.int32)[None, :]
          ).astype(jnp.float32)
    return jnp.dot(oh, table, preferred_element_type=jnp.float32,
                   precision=jax.lax.Precision.HIGHEST)


@functools.partial(
    jax.jit,
    static_argnames=(
        "max_depth", "nbins", "hist_method", "axis_name", "mtries",
        "compact_cap", "pack_bits", "fused_split", "n_shard_blocks",
    ),
)
def build_tree(
    codes: jax.Array,        # (N, F) uint bin codes, or the `ops.packing`
    #                          packed (N·bits/8, F) words when pack_bits
    g: jax.Array,            # (N,) gradients
    h: jax.Array,            # (N,) hessians
    w: jax.Array,            # (N,) row weights (0 = masked/pad/OOB)
    feat_mask: jax.Array,    # (F,) f32 1/0 — column sampling
    edges: jax.Array,        # (F, nbins-2) raw-value right edges (+inf padded)
    max_depth: int,
    nbins: int,
    min_rows: float = 10.0,
    min_split_improvement: float = 0.0,
    reg_lambda: float = 1.0,
    reg_alpha: float = 0.0,
    hist_method: str = "auto",
    axis_name: Optional[str] = None,
    mtries: int = 0,
    mtries_rate=None,  # traced per-node column keep-probability; when set,
    #                    overrides the static mtries/F so DRF and XRT (and
    #                    every mtries value) share ONE compiled program
    key: Optional[jax.Array] = None,
    monotone: Optional[jax.Array] = None,  # (F,) ∈ {-1,0,1}
    max_abs_leaf=None,  # traced scalar: |leaf value| cap (GBM
    #                     max_abs_leafnode_pred / xgboost max_delta_step)
    compact_cap: int = 0,
    pack_bits: int = 0,
    fused_split: bool = False,
    n_shard_blocks: int = 0,
):
    """Build one tree; returns (Tree, final_leaf_heap_idx (N,),
    gain_per_feature (F,), cover (T,) — Σ training row weights per heap node,
    recorded for path-dependent TreeSHAP (hex/genmodel TreeSHAP node weights).
    With compact_cap > 0, a 5th element is returned: an i32 overflow flag
    (see below).

    mtries > 0 samples ~mtries of F features per node per level (DRF's
    per-split column sampling, `hex/tree/drf/DRF.java` _mtry) — bernoulli
    approximation of exact without-replacement draws, same expectation.

    Scalar hyperparameters (min_rows, min_split_improvement, reg_*) are
    TRACED, not static: one compiled program serves every model that shares
    the structural config (shapes, depth, bins) — grids / CV / AutoML vary
    these scalars freely without recompiling.

    compact_cap > 0 switches levels wider than the cap to ACTIVE-NODE
    COMPACTION (DHistogram's allocate-only-active-nodes semantics, made
    static-shaped): deep levels track at most `compact_cap` live nodes in
    compact slots instead of materializing 2^d × F × B histograms that are
    overwhelmingly empty (measured: DRF depth-17 levels carry ~700 active
    nodes of 131k heap cells). Exactness is preserved: if the live-node
    count ever exceeds the cap, the returned overflow flag is nonzero and
    the caller must rebuild densely (the driver does). Requires
    monotone=None.

    pack_bits in {4, 5, 6} means `codes` is the `ops.packing` packed word
    matrix: histogram kernels consume it (per-chunk unpack — the host/CPU
    path never widens; in-graph kernels widen once per program) and the
    partition step reads each row's selected-feature code straight from
    the packed words (`packed_row_values`, two byte gathers per row).

    fused_split=True switches the per-level split search to the
    single-pass scan-argmax (`_fused_level_best`, bit-exact with the
    legacy flat argmax); False keeps the seed formulation — the
    ``H2O3_TREE_LEGACY=1`` comparator.

    n_shard_blocks > 0 (ISSUE 12) makes every row reduction (histograms
    and final leaf totals) use the shard-invariant blocked fold of
    `ops.histogram` — this call's rows are accumulated in that many
    contiguous blocks whose partials merge in a fixed order, across
    devices via `all_gather` when `axis_name` is set. An N-device
    shard_map'd call with S/N local blocks is then bit-identical to a
    1-device call with S blocks. 0 preserves the historical single-fold
    (+ psum) formulation bit-for-bit.
    """
    if pack_bits:
        F = codes.shape[1]
        N = packing.packed_nrows(codes.shape[0], pack_bits)
    else:
        N, F = codes.shape
    T = heap_size(max_depth)
    feat_a = jnp.zeros(T, jnp.int32)
    bin_a = jnp.zeros(T, jnp.int32)
    thr_a = jnp.zeros(T, jnp.float32)
    split_a = jnp.zeros(T, bool)
    value_a = jnp.zeros(T, jnp.float32)
    cover_a = jnp.zeros(T, jnp.float32)   # Σ row weights per node (TreeSHAP)

    idx = jnp.zeros(N, jnp.int32)          # level-local node index
    active = jnp.ones(1, bool)             # per-level-node: may still split
    gain_per_feature = jnp.zeros(F, jnp.float32)
    if key is None:
        key = jax.random.PRNGKey(0)
    BIG = jnp.float32(3.4e38)
    # per-level-node value bounds for monotone constraints (LightGBM-style
    # mid-point bound propagation; every node's value is clamped into them)
    lo_lvl = jnp.full(1, -BIG)
    hi_lvl = jnp.full(1, BIG)

    if compact_cap and monotone is not None:
        raise ValueError("compact_cap requires monotone=None")
    d_switch = compact_switch_depth(max_depth, compact_cap)
    # per-row frozen leaf id (absolute heap node) — maintained only when the
    # compact phase can run, since compaction stops flowing dead rows left
    row_leaf = jnp.zeros(N, jnp.int32) if d_switch < max_depth else None

    hist_prev = None
    for d in range(min(d_switch, max_depth)):
        L = 2 ** d
        base = L - 1                        # heap offset of this level
        if d == 0:
            hist = build_histograms(
                codes, idx, g, h, w, L, nbins, method=hist_method,
                axis_name=axis_name, pack_bits=pack_bits,
                n_shard_blocks=n_shard_blocks,
            )  # (L, F, B, 3)
        else:
            # sibling subtraction (the gpu_hist/LightGBM trick): build only
            # LEFT children histograms; right = parent − left. Halves the
            # histogram work at every level.
            is_left = (idx % 2 == 0)
            hist_left = build_histograms(
                codes, idx // 2, g, h, w * is_left.astype(w.dtype),
                L // 2, nbins, method=hist_method, axis_name=axis_name,
                pack_bits=pack_bits, n_shard_blocks=n_shard_blocks,
            )  # (L/2, F, B, 3) indexed by parent
            hist_right = hist_prev - hist_left
            hist = jnp.stack([hist_left, hist_right], axis=1).reshape(
                L, *hist_left.shape[1:]
            )
        hist_prev = hist

        wsum = hist[..., 0].sum(axis=2)[:, 0]   # (L,) totals (same for all F)
        gsum = hist[..., 1].sum(axis=2)[:, 0]
        hsum = hist[..., 2].sum(axis=2)[:, 0]
        # Newton leaf value with elastic-net regularization (xgboost's
        # CalcWeight: soft-threshold G by alpha, shrink by lambda)
        gthr = jnp.sign(gsum) * jnp.maximum(jnp.abs(gsum) - reg_alpha, 0.0)
        node_val = (-gthr / (hsum + reg_lambda + 1e-12)).astype(jnp.float32)
        if max_abs_leaf is not None:
            # cap before monotone bounds so the bounds (which encode the
            # constraint) always win over the magnitude cap
            node_val = jnp.clip(node_val, -max_abs_leaf, max_abs_leaf)
        if monotone is not None:
            node_val = jnp.clip(node_val, lo_lvl, hi_lvl)
        value_a = value_a.at[base : base + L].set(node_val)
        cover_a = cover_a.at[base : base + L].set(wsum.astype(jnp.float32))

        # per-(node,feature) bernoulli keep with the same node psum'd RNG
        # on every host (key is replicated) so partitions stay consistent.
        # Drawn identically (one split per level) on both search paths.
        keep = None
        if mtries > 0 or mtries_rate is not None:
            key, sub = jax.random.split(key)
            rate = mtries_rate if mtries_rate is not None else (mtries / F)
            keep = jax.random.uniform(sub, (L, F)) < rate
            keep = keep.at[:, 0].set(keep[:, 0] | ~keep.any(axis=1))  # >=1 kept

        vLs = vRs = None
        if fused_split:
            best_gain, bf, bb, vLs, vRs = _fused_level_best(
                hist, active, feat_mask, keep, nbins, min_rows, reg_lambda,
                reg_alpha, gsum, hsum, wsum, monotone=monotone,
                lo_lvl=lo_lvl if monotone is not None else None,
                hi_lvl=hi_lvl if monotone is not None else None)
        else:
            # legacy split search: cumulative over bins → gain per (L, F, B)
            cw = jnp.cumsum(hist[..., 0], axis=2)
            cg = jnp.cumsum(hist[..., 1], axis=2)
            ch = jnp.cumsum(hist[..., 2], axis=2)
            GL, HL, WL = cg, ch, cw
            G = gsum[:, None, None]
            H = hsum[:, None, None]
            W = wsum[:, None, None]
            GR, HR, WR = G - GL, H - HL, W - WL
            # xgboost CalcSplitGain: L1 soft-threshold the gradient sums
            # before squaring (ThresholdL1); exact no-op at reg_alpha=0
            tl1 = lambda A: jnp.sign(A) * jnp.maximum(jnp.abs(A) - reg_alpha, 0.0)
            GLt, GRt, Gt = tl1(GL), tl1(GR), tl1(G)
            gain = (
                GLt * GLt / (HL + reg_lambda)
                + GRt * GRt / (HR + reg_lambda)
                - Gt * Gt / (H + reg_lambda)
            )
            ok = (WL >= min_rows) & (WR >= min_rows)
            ok = ok & (jnp.arange(nbins)[None, None, :] < nbins - 1)   # no split at NA bin
            ok = ok & (feat_mask[None, :, None] > 0)
            ok = ok & active[:, None, None]
            if monotone is not None:
                # monotone_constraints (hex/tree Constraints / LightGBM): a
                # split on feature f with constraint c is admissible only
                # when c·(value_right − value_left) ≥ 0, where the child
                # values use the SAME soft-thresholded formula as
                # materialized node values and are clamped into the node's
                # inherited bounds. Bound propagation (below) then
                # guarantees zero violations.
                gthrL = jnp.sign(GL) * jnp.maximum(jnp.abs(GL) - reg_alpha, 0.0)
                gthrR = jnp.sign(GR) * jnp.maximum(jnp.abs(GR) - reg_alpha, 0.0)
                vL = jnp.clip(-gthrL / (HL + reg_lambda + 1e-12),
                              lo_lvl[:, None, None], hi_lvl[:, None, None])
                vR = jnp.clip(-gthrR / (HR + reg_lambda + 1e-12),
                              lo_lvl[:, None, None], hi_lvl[:, None, None])
                mc = monotone[None, :, None]
                ok = ok & ((mc == 0) | (mc * (vR - vL) >= 0))
            if keep is not None:
                ok = ok & keep[:, :, None]
            gain = jnp.where(ok, gain, -jnp.inf)

            flat = gain.reshape(L, F * nbins)
            best = jnp.argmax(flat, axis=1)
            best_gain = jnp.take_along_axis(flat, best[:, None], axis=1)[:, 0]
            bf = (best // nbins).astype(jnp.int32)
            bb = (best % nbins).astype(jnp.int32)
            if monotone is not None:
                # child values at the chosen split, gathered from the SAME
                # vL/vR used by the admissibility check (bound propagation)
                sel = (bf * nbins + bb)[:, None]
                flat_pick = lambda A: jnp.take_along_axis(
                    A.reshape(L, F * nbins), sel, axis=1)[:, 0]
                vLs = flat_pick(vL)
                vRs = flat_pick(vR)
        do_split = best_gain > jnp.maximum(min_split_improvement, 1e-10)
        gain_per_feature = gain_per_feature + jax.ops.segment_sum(
            jnp.where(do_split, best_gain, 0.0).astype(jnp.float32), bf, num_segments=F
        )

        # raw threshold: edges[f][b] for b < nbins-2, +inf at the last value bin
        pad_edges = jnp.concatenate(
            [edges.astype(jnp.float32), jnp.full((F, 1), jnp.inf, jnp.float32)], axis=1
        )
        bthr = pad_edges[bf, jnp.minimum(bb, nbins - 2)]

        feat_a = feat_a.at[base : base + L].set(jnp.where(do_split, bf, 0))
        bin_a = bin_a.at[base : base + L].set(jnp.where(do_split, bb, 0))
        thr_a = thr_a.at[base : base + L].set(jnp.where(do_split, bthr, 0.0))
        split_a = split_a.at[base : base + L].set(do_split)

        # partition rows: decided-leaf rows flow left; splitters route by
        # code. All per-row lookups are one-hot contractions (L and F are
        # small) — a take_along_axis gather here costs ~10× more VPU time.
        rf = _lookup_int(bf, idx, L)
        rb = _lookup_int(bb, idx, L)
        rs = _lookup_bool(do_split, idx, L)
        if pack_bits:
            # the row's selected-feature code straight from the packed
            # words: two byte gathers + a shift per row, O(N) instead of
            # the O(N·F) one-hot contraction over full-width codes
            rcode = packing.packed_row_values(codes, rf, pack_bits)
        else:
            rcode = _row_feature_value(codes, rf)
        go_right = (rcode > rb) & rs
        idx = 2 * idx + go_right.astype(jnp.int32)
        if row_leaf is not None:
            row_leaf = jnp.where(rs, (2 ** (d + 1) - 1) + idx, row_leaf)
        active = jnp.repeat(do_split, 2)

        if monotone is not None:
            # propagate bounds to children: on a ±1-constrained split the
            # mid-point of the chosen split's child values caps the lower-
            # valued side and floors the higher-valued side. vLs/vRs were
            # gathered above from the SAME vL/vR the admissibility check
            # used (legacy flat_pick or the fused running carry).
            mid = 0.5 * (vLs + vRs)
            c = monotone[bf] * do_split.astype(monotone.dtype)
            # c=+1: left ≤ mid ≤ right; c=−1: mirrored; c=0: inherit as-is
            hi_left = jnp.where(c > 0, jnp.minimum(hi_lvl, mid), hi_lvl)
            lo_left = jnp.where(c < 0, jnp.maximum(lo_lvl, mid), lo_lvl)
            hi_right = jnp.where(c < 0, jnp.minimum(hi_lvl, mid), hi_lvl)
            lo_right = jnp.where(c > 0, jnp.maximum(lo_lvl, mid), lo_lvl)
            lo_lvl = jnp.stack([lo_left, lo_right], axis=1).reshape(2 * L)
            hi_lvl = jnp.stack([hi_left, hi_right], axis=1).reshape(2 * L)

    if d_switch >= max_depth:
        # pure dense build: final level values from exact per-cell totals.
        # For small heaps the f32 one-hot matmul (MXU) beats segment_sum's
        # sorted scatter ~3×; arithmetic stays f32 either way, only the
        # reduction tree differs.
        Lf = 2 ** max_depth
        basef = Lf - 1
        # Precision.HIGHEST inside (small heaps): TPU's default matmul
        # truncates f32 operands to bf16, which would round the per-leaf
        # g/h sums (leaf values)
        tot = _leaf_totals(idx, jnp.stack([w, g * w, h * w]), Lf,
                           axis_name, n_shard_blocks, onehot_ok=True)
        gthr_f = jnp.sign(tot[:, 1]) * jnp.maximum(jnp.abs(tot[:, 1]) - reg_alpha, 0.0)
        leaf_val = (-gthr_f / (tot[:, 2] + reg_lambda + 1e-12)).astype(jnp.float32)
        if max_abs_leaf is not None:
            leaf_val = jnp.clip(leaf_val, -max_abs_leaf, max_abs_leaf)
        if monotone is not None:
            leaf_val = jnp.clip(leaf_val, lo_lvl, hi_lvl)
        value_a = value_a.at[basef:].set(leaf_val)
        cover_a = cover_a.at[basef:].set(tot[:, 0].astype(jnp.float32))
        out = (
            Tree(feat_a, bin_a, thr_a, split_a, value_a),
            idx + basef,
            gain_per_feature,
            cover_a,
        )
        if compact_cap:
            return out + (jnp.int32(0),)
        return out

    # ---- compact phase: levels d_switch..max_depth with ≤ CAP live slots --
    CAP = compact_cap
    M = CAP // 2
    if 2 * M != CAP:
        raise ValueError("compact_cap must be even (slot pairs)")
    overflow = jnp.int32(0)
    L_t = 2 ** d_switch
    act_i = active.astype(jnp.int32)
    overflow += (act_i.sum() > CAP).astype(jnp.int32)
    sid_nodes = jnp.where(active, jnp.minimum(jnp.cumsum(act_i) - 1, CAP),
                          CAP)                                    # (L_t,)
    row_slot = sid_nodes[idx]                                     # (N,)
    slot_node = jnp.full(CAP + 1, -1, jnp.int32).at[sid_nodes].set(
        jnp.where(active, jnp.arange(L_t, dtype=jnp.int32), -1))
    # transition histogram: one fresh pass in slot space (no subtraction
    # available across the dense/compact boundary)
    slot_hist = build_histograms(
        codes, row_slot, g, h, w * (row_slot < CAP).astype(w.dtype),
        CAP + 1, nbins, method=hist_method, axis_name=axis_name,
        pack_bits=pack_bits, n_shard_blocks=n_shard_blocks)

    pad_edges_c = jnp.concatenate(
        [edges.astype(jnp.float32), jnp.full((F, 1), jnp.inf, jnp.float32)],
        axis=1)
    slot_iota = jnp.arange(CAP + 1, dtype=jnp.int32)

    for d in range(d_switch, max_depth):
        base = 2 ** d - 1
        valid = (slot_node >= 0) & (slot_iota < CAP)
        wsum = slot_hist[..., 0].sum(axis=2)[:, 0]
        gsum = slot_hist[..., 1].sum(axis=2)[:, 0]
        hsum = slot_hist[..., 2].sum(axis=2)[:, 0]
        gthr = jnp.sign(gsum) * jnp.maximum(jnp.abs(gsum) - reg_alpha, 0.0)
        node_val = (-gthr / (hsum + reg_lambda + 1e-12)).astype(jnp.float32)
        if max_abs_leaf is not None:
            node_val = jnp.clip(node_val, -max_abs_leaf, max_abs_leaf)
        abs_node = jnp.where(valid, base + slot_node, T)   # T drops
        value_a = value_a.at[abs_node].set(
            jnp.where(valid, node_val, 0.0), mode="drop")
        cover_a = cover_a.at[abs_node].set(
            jnp.where(valid, wsum.astype(jnp.float32), 0.0), mode="drop")

        # split search over live slots (same math as the dense level)
        keep = None
        if mtries > 0 or mtries_rate is not None:
            key, sub = jax.random.split(key)
            rate = mtries_rate if mtries_rate is not None else (mtries / F)
            keep = jax.random.uniform(sub, (CAP + 1, F)) < rate
            keep = keep.at[:, 0].set(keep[:, 0] | ~keep.any(axis=1))
        if fused_split:
            best_gain, bf, bb, _, _ = _fused_level_best(
                slot_hist, valid, feat_mask, keep, nbins, min_rows,
                reg_lambda, reg_alpha, gsum, hsum, wsum)
        else:
            cw = jnp.cumsum(slot_hist[..., 0], axis=2)
            cg = jnp.cumsum(slot_hist[..., 1], axis=2)
            ch = jnp.cumsum(slot_hist[..., 2], axis=2)
            GL, HL, WL = cg, ch, cw
            G = gsum[:, None, None]
            H = hsum[:, None, None]
            W = wsum[:, None, None]
            GR, HR, WR = G - GL, H - HL, W - WL
            tl1 = lambda A: jnp.sign(A) * jnp.maximum(jnp.abs(A) - reg_alpha, 0.0)
            GLt, GRt, Gt = tl1(GL), tl1(GR), tl1(G)
            gain = (GLt * GLt / (HL + reg_lambda)
                    + GRt * GRt / (HR + reg_lambda)
                    - Gt * Gt / (H + reg_lambda))
            ok = (WL >= min_rows) & (WR >= min_rows)
            ok = ok & (jnp.arange(nbins)[None, None, :] < nbins - 1)
            ok = ok & (feat_mask[None, :, None] > 0)
            ok = ok & valid[:, None, None]
            if keep is not None:
                ok = ok & keep[:, :, None]
            gain = jnp.where(ok, gain, -jnp.inf)
            flat = gain.reshape(CAP + 1, F * nbins)
            best = jnp.argmax(flat, axis=1)
            best_gain = jnp.take_along_axis(flat, best[:, None], axis=1)[:, 0]
            bf = (best // nbins).astype(jnp.int32)
            bb = (best % nbins).astype(jnp.int32)
        do = best_gain > jnp.maximum(min_split_improvement, 1e-10)
        gain_per_feature = gain_per_feature + jax.ops.segment_sum(
            jnp.where(do, best_gain, 0.0).astype(jnp.float32), bf,
            num_segments=F)
        bthr = pad_edges_c[bf, jnp.minimum(bb, nbins - 2)]
        feat_a = feat_a.at[abs_node].set(
            jnp.where(valid & do, bf, 0), mode="drop")
        bin_a = bin_a.at[abs_node].set(
            jnp.where(valid & do, bb, 0), mode="drop")
        thr_a = thr_a.at[abs_node].set(
            jnp.where(valid & do, bthr, 0.0), mode="drop")
        split_a = split_a.at[abs_node].set(valid & do, mode="drop")

        # partition rows (plain gathers: CAP-wide tables, N small)
        do = do & valid
        rs_do = do[row_slot]
        bf_r = bf[row_slot]
        bb_r = bb[row_slot]
        if pack_bits:
            rcode = packing.packed_row_values(codes, bf_r, pack_bits)
        else:
            rcode = _row_feature_value(codes, bf_r)
        go_right = (rcode > bb_r) & rs_do
        child_local = 2 * slot_node[row_slot] + go_right.astype(jnp.int32)
        row_leaf = jnp.where(rs_do, (2 ** (d + 1) - 1) + child_local,
                             row_leaf)

        # child slot assignment: split parents ranked, children interleaved
        do_i = do.astype(jnp.int32)
        rank = jnp.minimum(jnp.cumsum(do_i) - 1, M - 1)
        overflow += (do_i.sum() > M).astype(jnp.int32)
        new_row_slot = jnp.where(
            rs_do, 2 * rank[row_slot] + go_right.astype(jnp.int32), CAP)

        tgt = jnp.where(do, rank, M)                      # (CAP+1,) ∈ [0,M]
        pr = jnp.full(M + 1, CAP, jnp.int32).at[tgt].set(
            jnp.where(do, slot_iota, CAP))
        par_node = jnp.where(pr < CAP,
                             slot_node[jnp.minimum(pr, CAP)], -1)  # (M+1,)
        kids = jnp.stack([2 * par_node, 2 * par_node + 1], axis=1
                         ).reshape(2 * (M + 1))
        kids = jnp.where(kids < 0, -1, kids)
        new_slot_node = jnp.concatenate(
            [kids[:CAP], jnp.full(1, -1, jnp.int32)])

        # child histograms: LEFT from one masked pass in parent-slot space,
        # RIGHT by parent-minus-left (the sibling-subtraction trick)
        wl = w * ((~go_right) & rs_do).astype(w.dtype)
        hl = build_histograms(codes, row_slot, g, h, wl, CAP + 1, nbins,
                              method=hist_method, axis_name=axis_name,
                              pack_bits=pack_bits,
                              n_shard_blocks=n_shard_blocks)
        prc = jnp.minimum(pr, CAP)
        hl_p = hl[prc]
        hp_p = slot_hist[prc]
        pair = jnp.stack([hl_p, hp_p - hl_p], axis=1
                         ).reshape((2 * (M + 1),) + hl.shape[1:])
        slot_hist = jnp.concatenate(
            [pair[:CAP], jnp.zeros((1,) + hl.shape[1:], hl.dtype)])
        slot_node = new_slot_node
        row_slot = new_row_slot

    # final level: exact per-slot totals (dead rows sit in the trash slot)
    basef = 2 ** max_depth - 1
    valid = (slot_node >= 0) & (slot_iota < CAP)
    tot = _leaf_totals(row_slot, jnp.stack([w, g * w, h * w]), CAP + 1,
                       axis_name, n_shard_blocks, onehot_ok=False)
    gthr_f = jnp.sign(tot[:, 1]) * jnp.maximum(
        jnp.abs(tot[:, 1]) - reg_alpha, 0.0)
    leaf_val = (-gthr_f / (tot[:, 2] + reg_lambda + 1e-12)).astype(jnp.float32)
    if max_abs_leaf is not None:
        leaf_val = jnp.clip(leaf_val, -max_abs_leaf, max_abs_leaf)
    abs_node = jnp.where(valid, basef + slot_node, T)
    value_a = value_a.at[abs_node].set(
        jnp.where(valid, leaf_val, 0.0), mode="drop")
    cover_a = cover_a.at[abs_node].set(
        jnp.where(valid, tot[:, 0].astype(jnp.float32), 0.0), mode="drop")
    return (
        Tree(feat_a, bin_a, thr_a, split_a, value_a),
        row_leaf,
        gain_per_feature,
        cover_a,
        overflow,
    )


def _search_splits(hist, feat_mask, nbins, min_rows, reg_lambda, reg_alpha):
    """Best (gain, feat, bin) per node for an (L, F, B, 3) histogram —
    the split search of `build_tree` without the level-wise bookkeeping
    (`hex/tree/DTree.Split.findBestSplitPoint`; xgboost EvaluateSplits)."""
    L, F = hist.shape[0], hist.shape[1]
    wsum = hist[..., 0].sum(axis=2)[:, 0]
    gsum = hist[..., 1].sum(axis=2)[:, 0]
    hsum = hist[..., 2].sum(axis=2)[:, 0]
    GL = jnp.cumsum(hist[..., 1], axis=2)
    HL = jnp.cumsum(hist[..., 2], axis=2)
    WL = jnp.cumsum(hist[..., 0], axis=2)
    G, H, W = (a[:, None, None] for a in (gsum, hsum, wsum))
    GR, HR, WR = G - GL, H - HL, W - WL
    # xgboost CalcSplitGain: L1 soft-threshold before squaring (ThresholdL1)
    tl1 = lambda A: jnp.sign(A) * jnp.maximum(jnp.abs(A) - reg_alpha, 0.0)
    GLt, GRt, Gt = tl1(GL), tl1(GR), tl1(G)
    gain = (GLt * GLt / (HL + reg_lambda)
            + GRt * GRt / (HR + reg_lambda)
            - Gt * Gt / (H + reg_lambda))
    ok = (WL >= min_rows) & (WR >= min_rows)
    ok = ok & (jnp.arange(nbins)[None, None, :] < nbins - 1)  # NA bin
    ok = ok & (feat_mask[None, :, None] > 0)
    gain = jnp.where(ok, gain, -jnp.inf)
    flat = gain.reshape(L, F * nbins)
    best = jnp.argmax(flat, axis=1)
    best_gain = jnp.take_along_axis(flat, best[:, None], axis=1)[:, 0]
    return (best_gain, (best // nbins).astype(jnp.int32),
            (best % nbins).astype(jnp.int32), wsum, gsum, hsum)


@functools.partial(
    jax.jit,
    static_argnames=(
        "max_depth", "nbins", "max_leaves", "hist_method", "axis_name",
    ),
)
def build_tree_lossguide(
    codes: jax.Array,        # (N, F) uint bin codes
    g: jax.Array,
    h: jax.Array,
    w: jax.Array,
    feat_mask: jax.Array,    # (F,) per-tree column mask
    edges: jax.Array,
    max_depth: int,
    nbins: int,
    max_leaves: int,
    min_rows: float = 1.0,
    min_split_improvement: float = 0.0,
    reg_lambda: float = 1.0,
    reg_alpha: float = 0.0,
    hist_method: str = "auto",
    axis_name: Optional[str] = None,
    max_abs_leaf=None,
):
    """Leaf-wise (best-first) growth — xgboost `grow_policy=lossguide`
    (`h2o-ext-xgboost/.../XGBoostModel.java` grow_policy passthrough to the
    native `hist` updater; LightGBM's growth strategy).

    TPU-first shape: the frontier is a fixed array of `max_leaves` leaf
    slots, each holding its node's histogram and cached best split; every
    iteration of a `lax.fori_loop` splits the best-gain slot, builds the
    LEFT child's histogram in one masked pass and derives the right child
    by parent-minus-left subtraction. All shapes are static, so one
    compiled program serves the whole forest. The tree still lives in the
    same depth-capped heap as `build_tree`, so scoring, packing, MOJO
    export and TreeSHAP are unchanged.

    Returns the same tuple as `build_tree`.
    """
    N, F = codes.shape
    T = heap_size(max_depth)
    S = max(2, min(max_leaves if max_leaves > 0 else 2 ** max_depth,
                   2 ** max_depth))
    # derived from codes (not a fresh constant) so that under shard_map the
    # fori_loop row-state carry is device-varying from iteration 0
    zeros_n = codes[:, 0].astype(jnp.int32) * 0

    hist0 = build_histograms(codes, zeros_n, g, h, w, 1, nbins,
                             method=hist_method, axis_name=axis_name)
    bg0, bf0, bb0, ws0, gs0, hs0 = _search_splits(
        hist0, feat_mask, nbins, min_rows, reg_lambda, reg_alpha)

    def newton(gs, hs):
        gthr = jnp.sign(gs) * jnp.maximum(jnp.abs(gs) - reg_alpha, 0.0)
        v = (-gthr / (hs + reg_lambda + 1e-12)).astype(jnp.float32)
        if max_abs_leaf is not None:
            v = jnp.clip(v, -max_abs_leaf, max_abs_leaf)
        return v

    def depth_of(node):
        # floor(log2(node+1)) by exact integer comparisons (max_depth small)
        return (node[..., None] + 1 >=
                2 ** jnp.arange(1, max_depth + 1, dtype=jnp.int32)
                ).sum(axis=-1).astype(jnp.int32)

    pad_edges = jnp.concatenate(
        [edges.astype(jnp.float32), jnp.full((F, 1), jnp.inf, jnp.float32)],
        axis=1)

    value_a = jnp.zeros(T, jnp.float32).at[0].set(newton(gs0, hs0)[0])
    cover_a = jnp.zeros(T, jnp.float32).at[0].set(ws0.astype(jnp.float32)[0])
    feat_a = jnp.zeros(T, jnp.int32)
    bin_a = jnp.zeros(T, jnp.int32)
    thr_a = jnp.zeros(T, jnp.float32)
    split_a = jnp.zeros(T, bool)

    slot_node = jnp.full(S, -1, jnp.int32).at[0].set(0)
    slot_hist = jnp.zeros((S,) + hist0.shape[1:], hist0.dtype
                          ).at[0].set(hist0[0])
    # root at depth 0 can always be considered (max_depth >= 1)
    slot_gain = jnp.full(S, -jnp.inf, jnp.float32).at[0].set(bg0[0])
    slot_feat = jnp.zeros(S, jnp.int32).at[0].set(bf0[0])
    slot_bin = jnp.zeros(S, jnp.int32).at[0].set(bb0[0])

    def body(t, st):
        (feat_a, bin_a, thr_a, split_a, value_a, cover_a,
         row_node, row_slot, slot_node, slot_hist,
         slot_gain, slot_feat, slot_bin, gain_pf) = st
        s_star = jnp.argmax(slot_gain).astype(jnp.int32)
        gain = slot_gain[s_star]
        do = gain > jnp.maximum(min_split_improvement, 1e-10)
        node = slot_node[s_star]
        bf = slot_feat[s_star]
        bb = slot_bin[s_star]
        left = 2 * node + 1
        right = 2 * node + 2
        new_slot = (t + 1).astype(jnp.int32)

        bthr = pad_edges[bf, jnp.minimum(bb, nbins - 2)]
        feat_a = feat_a.at[node].set(jnp.where(do, bf, feat_a[node]))
        bin_a = bin_a.at[node].set(jnp.where(do, bb, bin_a[node]))
        thr_a = thr_a.at[node].set(jnp.where(do, bthr, thr_a[node]))
        split_a = split_a.at[node].set(split_a[node] | do)

        in_node = row_slot == s_star
        rcode = jnp.take(codes, bf, axis=1).astype(jnp.int32)
        go_right = in_node & (rcode > bb) & do
        row_node = jnp.where(go_right, right,
                             jnp.where(in_node & do, left, row_node))
        row_slot = jnp.where(go_right, new_slot, row_slot)

        # left child = one masked histogram pass; right = parent − left
        wl = w * (in_node & ~go_right & do).astype(w.dtype)
        hist_l = build_histograms(codes, zeros_n, g, h, wl, 1, nbins,
                                  method=hist_method, axis_name=axis_name)[0]
        hist_r = slot_hist[s_star] - hist_l
        slot_hist = slot_hist.at[s_star].set(
            jnp.where(do, hist_l, slot_hist[s_star]))
        slot_hist = slot_hist.at[new_slot].set(
            jnp.where(do, hist_r, slot_hist[new_slot]))
        slot_node = slot_node.at[s_star].set(jnp.where(do, left, node))
        slot_node = slot_node.at[new_slot].set(
            jnp.where(do, right, slot_node[new_slot]))

        ch = jnp.stack([hist_l, hist_r])           # (2, F, B, 3)
        cg, cbf, cbb, cws, cgs, chs = _search_splits(
            ch, feat_mask, nbins, min_rows, reg_lambda, reg_alpha)
        cval = newton(cgs, chs)
        value_a = value_a.at[left].set(jnp.where(do, cval[0], value_a[left]))
        value_a = value_a.at[right].set(jnp.where(do, cval[1], value_a[right]))
        cover_a = cover_a.at[left].set(
            jnp.where(do, cws.astype(jnp.float32)[0], cover_a[left]))
        cover_a = cover_a.at[right].set(
            jnp.where(do, cws.astype(jnp.float32)[1], cover_a[right]))

        # children at the depth cap cannot split further
        can = depth_of(jnp.stack([left, right])) < max_depth
        cg = jnp.where(can, cg, -jnp.inf)
        slot_gain = slot_gain.at[s_star].set(jnp.where(do, cg[0], -jnp.inf))
        slot_gain = slot_gain.at[new_slot].set(
            jnp.where(do, cg[1], slot_gain[new_slot]))
        slot_feat = slot_feat.at[s_star].set(jnp.where(do, cbf[0], 0))
        slot_feat = slot_feat.at[new_slot].set(
            jnp.where(do, cbf[1], slot_feat[new_slot]))
        slot_bin = slot_bin.at[s_star].set(jnp.where(do, cbb[0], 0))
        slot_bin = slot_bin.at[new_slot].set(
            jnp.where(do, cbb[1], slot_bin[new_slot]))

        gain_pf = gain_pf + jnp.where(
            do & (jnp.arange(F, dtype=jnp.int32) == bf), gain, 0.0
        ).astype(jnp.float32)
        return (feat_a, bin_a, thr_a, split_a, value_a, cover_a,
                row_node, row_slot, slot_node, slot_hist,
                slot_gain, slot_feat, slot_bin, gain_pf)

    st = (feat_a, bin_a, thr_a, split_a, value_a, cover_a,
          zeros_n, zeros_n, slot_node, slot_hist,
          slot_gain, slot_feat, slot_bin, jnp.zeros(F, jnp.float32))
    st = jax.lax.fori_loop(0, S - 1, body, st)
    (feat_a, bin_a, thr_a, split_a, value_a, cover_a,
     row_node, _, _, _, _, _, _, gain_pf) = st
    return (
        Tree(feat_a, bin_a, thr_a, split_a, value_a),
        row_node,
        gain_pf,
        cover_a,
    )


def predict_codes(tree: Tree, codes: jax.Array, max_depth: int) -> jax.Array:
    """Leaf value per row, traversing on binned codes (training-time path)."""
    N = codes.shape[0]
    node = jnp.zeros(N, jnp.int32)
    for _ in range(max_depth):
        f = tree.feat[node]
        b = tree.bin[node]
        s = tree.is_split[node]
        c = jnp.take_along_axis(codes, f[:, None].astype(jnp.int32), axis=1)[:, 0]
        child = 2 * node + 1 + ((c.astype(jnp.int32) > b) & s).astype(jnp.int32)
        node = jnp.where(s, child, node)
    return tree.value[node]


def predict_codes_packed(tree: Tree, packed: jax.Array, bits: int,
                         max_depth: int) -> jax.Array:
    """Leaf value per row, traversing straight on the `ops.packing` packed
    word matrix (the streamed/GOSS margin-update path, ISSUE 14): each
    level reads the row's split-feature code via `packed_row_values` (two
    byte gathers + a shift) instead of widening the block. With bits=0
    `packed` is a full-width code matrix and this is `predict_codes`."""
    if not bits:
        return predict_codes(tree, packed, max_depth)
    N = packing.packed_nrows(packed.shape[0], bits)
    node = jnp.zeros(N, jnp.int32)
    for _ in range(max_depth):
        f = tree.feat[node]
        b = tree.bin[node]
        s = tree.is_split[node]
        c = packing.packed_row_values(packed, f, bits)
        child = 2 * node + 1 + ((c > b) & s).astype(jnp.int32)
        node = jnp.where(s, child, node)
    return tree.value[node]


def predict_raw(tree: Tree, X: jax.Array, max_depth: int) -> jax.Array:
    """Leaf value per row on raw features (scoring path; NaN → right,
    mirroring the NA-bin-is-last training semantics)."""
    N = X.shape[0]
    node = jnp.zeros(N, jnp.int32)
    for _ in range(max_depth):
        f = tree.feat[node]
        t = tree.thr[node]
        s = tree.is_split[node]
        x = jnp.take_along_axis(X, f[:, None].astype(jnp.int32), axis=1)[:, 0]
        right = jnp.isnan(x) | (x > t)
        child = 2 * node + 1 + (right & s).astype(jnp.int32)
        node = jnp.where(s, child, node)
    return tree.value[node]


def stack_trees(trees) -> Tree:
    """Stack per-tree arrays into (ntrees, T) for vmapped forest scoring."""
    return Tree(*[jnp.stack([getattr(t, f) for t in trees]) for f in Tree._fields])


@functools.partial(jax.jit, static_argnames=("max_depth",))
def predict_forest_raw(forest: Tree, X: jax.Array, max_depth: int) -> jax.Array:
    """Σ over trees of leaf values — (N,) or (ntrees, N) summed. The scoring
    analog of `hex/Model.score0` / `BigScore` MRTask (hex/Model.java).

    Reference walk, one gather-round per level. The production scoring path
    is `build_score_table` + `predict_forest_fused` below (~10× faster on
    deep forests); this stays as the oracle the fused path is tested
    against."""
    per_tree = jax.vmap(lambda t: predict_raw(t, X, max_depth))(forest)
    return per_tree.sum(axis=0)


# ---- fused forest scoring: subtree-fetch walk ---------------------------
#
# The per-level walk above issues one random gather per level per tree; on
# TPU a gather costs ~13 ns per gathered ROW regardless of row width (the
# payload rides the same HBM fetch), so a depth-20 forest pays 21
# gather-rounds where 5 would do. The fused scorer restructures the tree
# into per-round "subtree rows": one 128-lane row holds the (feat, split,
# thr) records of a node's next _SCORE_K levels (2^K-1 records × 2 f32),
# so each fetch round descends K levels using only in-register one-hot
# selects between fetches. A depth-20 walk = 4 subtree fetches + 1 leaf
# value gather (measured 386 ms vs 4379 ms for 64 trees × 50k rows on
# TPU v5e; depth-5: 118 ms vs 615 ms).
#
# Scoring analog of `hex/genmodel/algos/tree/SharedTreeMojoModel.scoreTree`
# / `hex/Model.java` BigScore — redesigned for TPU memory semantics.

_SCORE_K = 5                 # levels per fetch round: 2*(2^5-1)=62 ≤ 64 lanes
_SCORE_W2 = 64               # f32 lanes per anchor block
_SCORE_FOLD = 2              # anchor blocks per 128-lane row (8,128 tiling)
_XV_ONEHOT_MAX = 128         # one-hot X-value fetch only for F ≤ this


def score_round_meta(max_depth: int):
    """Static round plan: (base_level, levels_this_round, row_offset)."""
    meta, base, row_off = [], 0, 0
    while base < max_depth:
        k = min(_SCORE_K, max_depth - base)
        A = 2 ** base
        meta.append((base, k, row_off))
        row_off += (A + _SCORE_FOLD - 1) // _SCORE_FOLD
        base += k
    return tuple(meta), row_off


def build_score_table(forest: Tree, max_depth: int):
    """Heap forest → (walk, value): walk (nt, ROWS, 128) f32 subtree rows,
    value (nt, T) f32 leaf values. Jittable; one-time per model, cache the
    result. Minor dim is exactly 128 lanes so the (8,128) device tiling
    adds no padding (a (T, 6) minor dim would pad 21×)."""
    feat = jnp.asarray(forest.feat)
    nt, T = feat.shape
    enc = feat.astype(jnp.float32) * 2.0 + forest.is_split.astype(jnp.float32)
    thr = forest.thr.astype(jnp.float32)
    meta, _ = score_round_meta(max_depth)
    if not meta:                              # depth-0 stumps: root value only
        return jnp.zeros((nt, 1, _SCORE_FOLD * _SCORE_W2), jnp.float32), \
            forest.value.astype(jnp.float32)
    rows = []
    for (base, k, _row_off) in meta:
        A = 2 ** base
        recs = []
        for level in range(k):
            lo = 2 ** (base + level) - 1
            cnt = 2 ** level
            e = jax.lax.dynamic_slice_in_dim(enc, lo, A * cnt, 1)
            t = jax.lax.dynamic_slice_in_dim(thr, lo, A * cnt, 1)
            recs.append(jnp.stack([e.reshape(nt, A, cnt),
                                   t.reshape(nt, A, cnt)],
                                  axis=-1).reshape(nt, A, 2 * cnt))
        blk = jnp.concatenate(recs, axis=-1)          # (nt, A, 2*(2^k-1))
        pad = _SCORE_W2 - blk.shape[-1]
        if pad:
            blk = jnp.pad(blk, ((0, 0), (0, 0), (0, pad)))
        if A % _SCORE_FOLD:
            blk = jnp.pad(blk, ((0, 0), (0, _SCORE_FOLD - A % _SCORE_FOLD),
                                (0, 0)))
        rows.append(blk.reshape(nt, -1, _SCORE_FOLD * _SCORE_W2))
    walk = jnp.concatenate(rows, axis=1)
    return walk, forest.value.astype(jnp.float32)


build_score_table_jit = jax.jit(build_score_table,
                                static_argnames=("max_depth",))


@functools.partial(jax.jit, static_argnames=("max_depth",))
def predict_forest_fused(walk: jax.Array, value: jax.Array, X: jax.Array,
                         max_depth: int) -> jax.Array:
    """Σ over trees of leaf values from a `build_score_table` pack.
    Matches `predict_forest_raw` (incl. NaN → right) to reduction-order
    rounding."""
    nt = walk.shape[0]
    N, F = X.shape
    node = jnp.zeros((nt, N), jnp.int32)
    fi = jnp.arange(F, dtype=jnp.int32)
    Xb = X[None]
    X_flat = X.reshape(-1)
    row_iota = jnp.arange(N, dtype=jnp.int32)[None, :]
    meta, _ = score_round_meta(max_depth)
    for (base, k, row_off) in meta:
        lvl_base = 2 ** base - 1
        a = jnp.clip(node - lvl_base, 0, 2 ** base - 1)
        # a row is live in this round iff its node reached level `base`
        # (rows frozen at shallower leaves keep node < lvl_base forever)
        active = node >= lvl_base
        ridx = (a >> 1) + row_off
        frow = jnp.take_along_axis(walk, ridx[:, :, None], axis=1)
        blk01 = jnp.where(((a & 1) == 1)[..., None],
                          frow[..., _SCORE_W2:], frow[..., :_SCORE_W2])
        rel = jnp.zeros_like(node)
        for level in range(k):
            cnt = 2 ** level
            rbase = 2 * (cnt - 1)
            blk = blk01[..., rbase: rbase + 2 * cnt].reshape(nt, N, cnt, 2)
            oh = rel[..., None] == jnp.arange(cnt, dtype=jnp.int32)
            e = jnp.where(oh, blk[..., 0], 0.0).sum(-1)
            t = jnp.where(oh, blk[..., 1], 0.0).sum(-1)
            ei = e.astype(jnp.int32)
            sp = (ei & 1) == 1
            f = ei >> 1
            if F <= _XV_ONEHOT_MAX:
                xv = jnp.where(f[..., None] == fi, Xb, 0.0).sum(-1)
            else:
                xv = jnp.take(X_flat, row_iota * F + f, mode="clip")
            right = (jnp.isnan(xv) | (xv > t)).astype(jnp.int32)
            go = active & sp
            node = jnp.where(go, 2 * node + 1 + right, node)
            rel = jnp.where(go, 2 * rel + right, rel)
            active = go
    v = jnp.take_along_axis(value, node, axis=1)
    return v.sum(axis=0)
