"""H2OGradientBoostingEstimator — GBM.

Reference parity: `h2o-algos/src/main/java/hex/tree/gbm/GBM.java`
(`GBMDriver.buildNextKTrees` — k trees/iteration, learn-rate annealing,
row/col sampling) and the generated estimator
`h2o-py/h2o/estimators/gbm.py` (parameter names are the API contract; the
HIGGS baseline config is `ntrees=100, histogram_type=UniformAdaptive`).

The training loop lives in `shared_tree.py`; histograms in
`ops/histogram.py` (the Pallas/onehot `tpu_hist` kernels).
"""

from __future__ import annotations

from .shared_tree import H2OSharedTreeEstimator


class H2OGradientBoostingEstimator(H2OSharedTreeEstimator):
    algo = "gbm"
    _mode = "gbm"
    _param_defaults = dict(
        ntrees=50,
        max_depth=5,
        min_rows=10.0,
        nbins=20,
        nbins_cats=1024,
        nbins_top_level=1024,
        learn_rate=0.1,
        learn_rate_annealing=1.0,
        sample_rate=1.0,
        sample_rate_per_class=None,
        col_sample_rate=1.0,
        col_sample_rate_change_per_level=1.0,
        col_sample_rate_per_tree=1.0,
        min_split_improvement=1e-5,
        histogram_type="AUTO",
        hist_method="auto",  # auto|onehot|segment|pallas|pallas_factored (tpu_hist strategy)
        distribution="AUTO",
        tweedie_power=1.5,
        quantile_alpha=0.5,
        huber_alpha=0.9,
        max_abs_leafnode_pred=float("inf"),
        pred_noise_bandwidth=0.0,
        calibrate_model=False,
        calibration_frame=None,
        calibration_method="AUTO",
        monotone_constraints=None,
        # gradient-based sampling on the out-of-core streamed path
        # (ISSUE 14, GOSS-shaped — "Out-of-Core GPU Gradient Boosting"
        # §sampling): after goss_start_tree trees, keep the top
        # goss_top_rate fraction of rows by |gradient| plus a random
        # goss_other_rate fraction of the rest amplified by
        # (1-top)/other, so later trees stream a fraction of the blocks
        goss=False,
        goss_top_rate=0.2,
        goss_other_rate=0.1,
        goss_start_tree=None,   # default: max(1, ntrees // 10)
        score_tree_interval=0,
        balance_classes=False,
        class_sampling_factors=None,
        max_after_balance_size=5.0,
        build_tree_one_node=False,
        reg_lambda=None,
    )


GBM = H2OGradientBoostingEstimator
