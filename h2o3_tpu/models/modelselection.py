"""H2OModelSelectionEstimator — best-subset GLM predictor selection.

Reference parity: `h2o-algos/src/main/java/hex/modelselection/ModelSelection.java`
(`mode` ∈ {allsubsets, maxr, maxrsweep, forward, backward}): per subset size
find the predictor set maximizing R² (gaussian) / minimizing deviance, via
exhaustive enumeration (allsubsets), greedy add + pairwise swap (maxr), or
stepwise add/drop by p-value (forward/backward). Estimator surface
`h2o-py/h2o/estimators/model_selection.py` (`result()`,
`get_best_model_predictors`, `coef()`).

Each candidate is an independent GLM IRLS whose Gram is one einsum — the
candidate sweep is embarrassingly parallel on device.
"""

from __future__ import annotations

import itertools
from typing import List, Optional

import numpy as np

from ..frame.frame import Frame
from .glm import H2OGeneralizedLinearEstimator
from .metrics import ModelMetricsBase
from .model_base import H2OEstimator, H2OModel


class ModelSelectionModel(H2OModel):
    algo = "modelselection"

    def __init__(self, params, x, y, results, best_glms):
        super().__init__(params)
        self.x = list(x)
        self.y = y
        self._results = results    # [{size, predictors, r2, model_idx}]
        self._best = best_glms     # parallel list of fitted GLM estimators

    def result(self) -> Frame:
        return Frame.from_dict({
            "model_size": np.asarray([r["size"] for r in self._results], np.float64),
            "predictor_names": np.asarray(
                [", ".join(r["predictors"]) for r in self._results], dtype=object),
            "r2": np.asarray([r["r2"] for r in self._results], np.float64),
        })

    def get_best_model_predictors(self):
        return [r["predictors"] for r in self._results]

    def get_best_r2_values(self):
        return [r["r2"] for r in self._results]

    def coef(self, predictor_size: Optional[int] = None):
        if predictor_size is None:
            return [g.coef() for g in self._best]
        for r, g in zip(self._results, self._best):
            if r["size"] == predictor_size:
                return g.coef()
        raise ValueError(f"no model of size {predictor_size}")

    def predict(self, test_data: Frame) -> Frame:
        return self._best[-1].predict(test_data)

    def _make_metrics(self, frame: Frame):
        return self._best[-1].model._make_metrics(frame)


class H2OModelSelectionEstimator(H2OEstimator):
    algo = "modelselection"
    _param_defaults = dict(
        family="AUTO",
        mode="maxr",
        max_predictor_number=1,
        min_predictor_number=1,
        p_values_threshold=0.0,
        lambda_=None,
        alpha=None,
        standardize=True,
        intercept=True,
        build_glm_model=False,
    )

    def _glm_r2(self, preds: List[str], y, train: Frame):
        g = H2OGeneralizedLinearEstimator(
            family=self._parms.get("family", "AUTO"),
            lambda_=0.0,
            standardize=bool(self._parms.get("standardize", True)),
        )
        g.train(x=preds, y=y, training_frame=train)
        m = g.model.training_metrics
        r2 = getattr(m, "r2", float("nan"))
        if np.isnan(r2):  # classification: use 1 - logloss ordering surrogate
            r2 = -getattr(m, "logloss", float("nan"))
        return g, float(r2)

    def _fit(self, x, y, train: Frame, valid: Optional[Frame]) -> ModelSelectionModel:
        p = self._parms
        mode = str(p.get("mode", "maxr")).lower()
        maxp = min(int(p.get("max_predictor_number", 1)), len(x))
        minp = max(int(p.get("min_predictor_number", 1)), 1)
        results, best_glms = [], []

        if mode in ("allsubsets",):
            for size in range(minp, maxp + 1):
                best = (None, -np.inf, None)
                for combo in itertools.combinations(x, size):
                    g, r2 = self._glm_r2(list(combo), y, train)
                    if r2 > best[1]:
                        best = (list(combo), r2, g)
                results.append(dict(size=size, predictors=best[0], r2=best[1]))
                best_glms.append(best[2])
        elif mode in ("maxr", "maxrsweep", "forward"):
            current: List[str] = []
            for size in range(1, maxp + 1):
                # greedy add
                best = (None, -np.inf, None)
                for c in x:
                    if c in current:
                        continue
                    cand = current + [c]
                    g, r2 = self._glm_r2(cand, y, train)
                    if r2 > best[1]:
                        best = (cand, r2, g)
                current, r2, g = best
                if mode in ("maxr", "maxrsweep"):
                    # pairwise replacement sweep until no improvement
                    improved = True
                    while improved:
                        improved = False
                        for i, old in enumerate(list(current)):
                            for c in x:
                                if c in current:
                                    continue
                                cand = current[:i] + [c] + current[i + 1 :]
                                g2, r22 = self._glm_r2(cand, y, train)
                                if r22 > r2 + 1e-12:
                                    current, r2, g = cand, r22, g2
                                    improved = True
                if size >= minp:
                    results.append(dict(size=size, predictors=list(current), r2=r2))
                    best_glms.append(g)
        elif mode == "backward":
            current = list(x)
            g, r2 = self._glm_r2(current, y, train)
            stack = [(list(current), r2, g)]
            while len(current) > minp:
                best = (None, -np.inf, None)
                for i in range(len(current)):
                    cand = current[:i] + current[i + 1 :]
                    g2, r22 = self._glm_r2(cand, y, train)
                    if r22 > best[1]:
                        best = (cand, r22, g2)
                current, r2, g = best
                stack.append((list(current), r2, g))
            for preds, r2, g in reversed(stack):
                results.append(dict(size=len(preds), predictors=preds, r2=r2))
                best_glms.append(g)
        else:
            raise ValueError(f"unknown mode {mode!r}")

        model = ModelSelectionModel(self, x, y, results, best_glms)
        model.training_metrics = ModelMetricsBase(nobs=train.nrow)
        return model


ModelSelection = H2OModelSelectionEstimator
