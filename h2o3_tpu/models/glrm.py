"""H2OGeneralizedLowRankEstimator — GLRM.

Reference parity: `h2o-algos/src/main/java/hex/glrm/GLRM.java` /
`GlrmLoss.java` / `GlrmRegularizer.java`: A ≈ X·Y (n×k · k×p) minimizing
per-entry losses + regularizers via alternating proximal updates;
NAs are simply excluded from the loss (which is what makes GLRM an imputer);
`recover_svd`, `transform` init. Estimator surface
`h2o-py/h2o/estimators/glrm.py`.

TPU shape: each alternating step is a masked least-squares solve — the
(k×k) normal equations per row/column batch as einsums under jit (MXU),
host Cholesky on the tiny systems. Quadratic loss + L2 regularization in
round 1; the proximal-operator structure is in place for the loss zoo.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..frame.frame import Frame
from .metrics import ModelMetricsBase
from .model_base import DataInfo, H2OEstimator, H2OModel


class GLRMModel(H2OModel):
    algo = "glrm"

    def __init__(self, params, x, dinfo, X, Y, k, objective):
        super().__init__(params)
        self.x = list(x)
        self.y = None
        self.dinfo = dinfo
        self.X = X  # (n, k) archetypes weights
        self.Y = Y  # (k, p) archetypes
        self.k = k
        self.objective = objective

    def archetypes(self) -> np.ndarray:
        return self.Y

    def reconstruct(self, frame: Frame) -> Frame:
        Xn = self._project(frame)
        R = Xn @ self.Y
        names = self.dinfo.coef_names
        return Frame.from_dict({f"reconstr_{names[j]}": R[:, j] for j in range(R.shape[1])})

    def _project(self, frame: Frame) -> np.ndarray:
        # _expand keeps NaNs (transform() would zero-fill and destroy the
        # observation mask, silently treating holes as observed zeros)
        A = self.dinfo._expand(frame, fit=False)
        if self.dinfo.means is not None:  # STANDARDIZE or DEMEAN was fit
            A = (A - self.dinfo.means) / self.dinfo.stds
        mask = ~np.isnan(A)
        A0 = np.nan_to_num(A, nan=0.0)
        lam = 1e-6
        Xn = np.zeros((A.shape[0], self.k))
        YT = self.Y.T  # (p, k)
        for i in range(A.shape[0]):
            m = mask[i]
            G = YT[m].T @ YT[m] + lam * np.eye(self.k)
            Xn[i] = np.linalg.solve(G, YT[m].T @ A0[i, m])
        return Xn

    def transform(self, frame: Frame) -> Frame:
        Xn = self._project(frame)
        return Frame.from_dict({f"Arch{j+1}": Xn[:, j] for j in range(self.k)})

    predict = reconstruct

    def _make_metrics(self, frame):
        return self.training_metrics


class H2OGeneralizedLowRankEstimator(H2OEstimator):
    algo = "glrm"
    supervised = False
    _param_defaults = dict(
        k=1,
        loss="Quadratic",
        multi_loss="Categorical",
        loss_by_col=None,
        regularization_x="None",
        regularization_y="None",
        gamma_x=0.0,
        gamma_y=0.0,
        max_iterations=1000,
        max_updates=2000,
        init_step_size=1.0,
        min_step_size=1e-4,
        init="PlusPlus",
        svd_method="Randomized",
        impute_original=False,
        recover_svd=False,
        transform="NONE",
        period=1,
    )

    def _fit(self, x, y, train: Frame, valid: Optional[Frame]) -> GLRMModel:
        from .model_base import warn_host_solver

        warn_host_solver('glrm', train.nrow, 200000)
        p = self._parms
        seed = p["_actual_seed"]
        k = int(p.get("k", 1))
        transform = p.get("transform", "NONE")
        dinfo = DataInfo(train, x, standardize=transform in ("STANDARDIZE", "NORMALIZE"),
                         use_all_factor_levels=True, impute_missing=False)
        A_raw = dinfo._expand(train, fit=True)
        if dinfo.standardize:
            dinfo.means = np.nanmean(A_raw, axis=0)
            dinfo.stds = np.where(np.nanstd(A_raw, axis=0) < 1e-10, 1.0,
                                  np.nanstd(A_raw, axis=0))
            A_raw = (A_raw - dinfo.means) / dinfo.stds
        elif transform == "DEMEAN":
            dinfo.means = np.nanmean(A_raw, axis=0)
            dinfo.stds = np.ones(A_raw.shape[1])
            A_raw = A_raw - dinfo.means
        n, pd = A_raw.shape
        k = min(k, min(n, pd))
        mask = (~np.isnan(A_raw)).astype(np.float32)
        A = np.nan_to_num(A_raw, nan=0.0).astype(np.float32)

        gx = float(p.get("gamma_x", 0.0)) + 1e-6
        gy = float(p.get("gamma_y", 0.0)) + 1e-6

        rng = np.random.default_rng(seed)
        if p.get("init", "PlusPlus") == "Random":
            X = rng.normal(scale=0.1, size=(n, k)).astype(np.float32)
            Y = rng.normal(scale=0.1, size=(k, pd)).astype(np.float32)
        else:
            # SVD warm start on the zero-imputed matrix (GLRM init=SVD;
            # markedly better basin than random for the ALS iterations)
            Uz, s, Vt = np.linalg.svd(A, full_matrices=False)
            X = (Uz[:, :k] * s[:k]).astype(np.float32)
            Y = Vt[:k].astype(np.float32)

        Aj = jnp.asarray(A)
        Mj = jnp.asarray(mask)

        @jax.jit
        def update_X(Xc, Yc):
            # row-wise masked normal equations, batched: G_i = Y M_i Y' (k,k)
            G = jnp.einsum("kp,np,lp->nkl", Yc, Mj, Yc) + gx * jnp.eye(k)[None]
            b = jnp.einsum("kp,np->nk", Yc, Aj * Mj)
            return jax.vmap(jnp.linalg.solve)(G, b)

        @jax.jit
        def update_Y(Xc, Yc):
            G = jnp.einsum("nk,np,nl->pkl", Xc, Mj, Xc) + gy * jnp.eye(k)[None]
            b = jnp.einsum("nk,np->pk", Xc, Aj * Mj)
            return jax.vmap(jnp.linalg.solve)(G, b).T

        @jax.jit
        def objective(Xc, Yc):
            R = (Aj - Xc @ Yc) * Mj
            return jnp.sum(R * R) + gx * jnp.sum(Xc * Xc) + gy * jnp.sum(Yc * Yc)

        Xj, Yj = jnp.asarray(X), jnp.asarray(Y)
        prev = np.inf
        iters = min(int(p.get("max_iterations", 1000)), 300)
        for it in range(iters):
            Xj = update_X(Xj, Yj)
            Yj = update_Y(Xj, Yj)
            if it % 5 == 4 or it == iters - 1:
                obj = float(objective(Xj, Yj))
                if abs(prev - obj) < 1e-8 * max(abs(prev), 1):
                    break
                prev = obj

        model = GLRMModel(self, x, dinfo, np.asarray(Xj), np.asarray(Yj), k,
                          float(objective(Xj, Yj)))
        mm = ModelMetricsBase(nobs=n)
        mm.description = f"objective={model.objective:.6g}"
        model.training_metrics = mm
        return model


GLRM = H2OGeneralizedLowRankEstimator
