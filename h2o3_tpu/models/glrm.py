"""H2OGeneralizedLowRankEstimator — GLRM.

Reference parity: `h2o-algos/src/main/java/hex/glrm/GLRM.java` /
`GlrmLoss.java` / `GlrmRegularizer.java`: A ≈ X·Y (n×k · k×p) minimizing
per-entry losses + regularizers via alternating proximal updates;
NAs are simply excluded from the loss (which is what makes GLRM an imputer);
`recover_svd`, `transform` init. Estimator surface
`h2o-py/h2o/estimators/glrm.py`.

TPU shape: each alternating step is a masked least-squares solve — the
(k×k) normal equations per row/column batch as einsums under jit (MXU),
batched device solves on the tiny systems. Quadratic loss + L2
regularization in round 1; the proximal-operator structure is in place for
the loss zoo. The WHOLE alternating loop runs as one jitted
`lax.while_loop` with the objective-convergence test (checked every 5th
iteration, like the host loop did) ON DEVICE — the host reads only the
final (X, Y, objective, iterations) (ISSUE 15); ``H2O3_EST_LEGACY=1``
restores the per-iteration host loop, and the NaN-masked expansion is
cached through the dataset cache's std layer so sweep candidates and CV
folds share one extraction.
"""

from __future__ import annotations

import time
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..frame.frame import Frame
from ..parallel import distdata
from ..parallel import mesh as cloudlib
from ..runtime import qos as _qos
from . import estimator_engine as _est
from .metrics import ModelMetricsBase
from .model_base import DataInfo, H2OEstimator, H2OModel


def _glrm_fit_fn(cloud):
    """The whole GLRM alternating fit as ONE device program (ISSUE 15):
    `lax.while_loop` over fused (update_X; update_Y) masked normal-equation
    solves, the objective-delta convergence test every 5th iteration (the
    host loop's cadence) evaluated ON DEVICE via `lax.cond` so off-cycle
    iterations never pay the residual pass. Returns the final
    (X, Y, objective, iterations, converged). Cached per cloud; shapes
    (n, p, k) key the traced program as usual."""

    def build():
        # carry (X, Y, obj, it, done) enters as traced arguments and cond
        # gains `it < stop_at` so the QoS gate can run the fit as bounded
        # resumable segments; the every-5th objective cadence keys off the
        # ABSOLUTE iteration index, so it survives segmentation bit-exactly
        def inner(A, M, X0, Y0, prev0, it0, done0, gx, gy, max_it, stop_at,
                  tol):
            kk = X0.shape[1]
            AM = A * M
            eyek = jnp.eye(kk)

            def update_X(Xc, Yc):
                G = jnp.einsum("kp,np,lp->nkl", Yc, M, Yc) + gx * eyek[None]
                b = jnp.einsum("kp,np->nk", Yc, AM)
                return jax.vmap(jnp.linalg.solve)(G, b)

            def update_Y(Xc, Yc):
                G = jnp.einsum("nk,np,nl->pkl", Xc, M, Xc) + gy * eyek[None]
                b = jnp.einsum("nk,np->pk", Xc, AM)
                return jax.vmap(jnp.linalg.solve)(G, b).T

            def objective(Xc, Yc):
                R = (A - Xc @ Yc) * M
                return (jnp.sum(R * R) + gx * jnp.sum(Xc * Xc)
                        + gy * jnp.sum(Yc * Yc))

            def cond(state):
                _, _, _, it, done = state
                return (~done) & (it < max_it) & (it < stop_at)

            def body(state):
                Xc, Yc, prev, it, _ = state
                Xc = update_X(Xc, Yc)
                Yc = update_Y(Xc, Yc)
                do_check = ((it % 5) == 4) | (it == max_it - 1)
                obj = jax.lax.cond(do_check,
                                   lambda _: objective(Xc, Yc),
                                   lambda _: prev, None)
                done = do_check & (jnp.abs(prev - obj)
                                   < tol * jnp.maximum(jnp.abs(prev), 1.0))
                return Xc, Yc, obj, it + 1, done

            X, Y, obj, it, done = jax.lax.while_loop(
                cond, body, (X0, Y0, prev0, it0, done0))
            return X, Y, obj, it, done

        return jax.jit(inner)

    return _est.cached_program(cloud, ("glrm_fit",), build)


class GLRMModel(H2OModel):
    algo = "glrm"

    def __init__(self, params, x, dinfo, X, Y, k, objective):
        super().__init__(params)
        self.x = list(x)
        self.y = None
        self.dinfo = dinfo
        self.X = X  # (n, k) archetypes weights
        self.Y = Y  # (k, p) archetypes
        self.k = k
        self.objective = objective

    def archetypes(self) -> np.ndarray:
        return self.Y

    def reconstruct(self, frame: Frame) -> Frame:
        Xn = self._project(frame)
        R = Xn @ self.Y
        names = self.dinfo.coef_names
        return Frame.from_dict({f"reconstr_{names[j]}": R[:, j] for j in range(R.shape[1])})

    def _project(self, frame: Frame) -> np.ndarray:
        # _expand keeps NaNs (transform() would zero-fill and destroy the
        # observation mask, silently treating holes as observed zeros)
        A = self.dinfo._expand(frame, fit=False)
        if self.dinfo.means is not None:  # STANDARDIZE or DEMEAN was fit
            A = (A - self.dinfo.means) / self.dinfo.stds
        mask = ~np.isnan(A)
        A0 = np.nan_to_num(A, nan=0.0)
        lam = 1e-6
        Xn = np.zeros((A.shape[0], self.k))
        YT = self.Y.T  # (p, k)
        for i in range(A.shape[0]):
            m = mask[i]
            G = YT[m].T @ YT[m] + lam * np.eye(self.k)
            Xn[i] = np.linalg.solve(G, YT[m].T @ A0[i, m])
        return Xn

    def transform(self, frame: Frame) -> Frame:
        Xn = self._project(frame)
        return Frame.from_dict({f"Arch{j+1}": Xn[:, j] for j in range(self.k)})

    predict = reconstruct

    def _make_metrics(self, frame):
        return self.training_metrics


class H2OGeneralizedLowRankEstimator(H2OEstimator):
    algo = "glrm"
    supervised = False
    _param_defaults = dict(
        k=1,
        loss="Quadratic",
        multi_loss="Categorical",
        loss_by_col=None,
        regularization_x="None",
        regularization_y="None",
        gamma_x=0.0,
        gamma_y=0.0,
        max_iterations=1000,
        max_updates=2000,
        init_step_size=1.0,
        min_step_size=1e-4,
        init="PlusPlus",
        svd_method="Randomized",
        impute_original=False,
        recover_svd=False,
        transform="NONE",
        period=1,
    )

    def _expand_masked(self, train: Frame, x, transform: str):
        """(dinfo, zero-filled A float32, observation mask float32) — the
        NaN-masked standardized expansion, cached through the dataset
        cache's std layer (keyed by the transform) so every sweep
        candidate/CV fold sharing the frame extracts once."""

        def build():
            dinfo = DataInfo(train, x,
                             standardize=transform in ("STANDARDIZE",
                                                       "NORMALIZE"),
                             use_all_factor_levels=True, impute_missing=False)
            A_raw = dinfo._expand(train, fit=True)
            if dinfo.standardize:
                dinfo.means = np.nanmean(A_raw, axis=0)
                sd = np.nanstd(A_raw, axis=0)
                dinfo.stds = np.where(sd < 1e-10, 1.0, sd)
                A_raw = (A_raw - dinfo.means) / dinfo.stds
            elif transform == "DEMEAN":
                dinfo.means = np.nanmean(A_raw, axis=0)
                dinfo.stds = np.ones(A_raw.shape[1])
                A_raw = A_raw - dinfo.means
            mask = (~np.isnan(A_raw)).astype(np.float32)
            A = np.nan_to_num(A_raw, nan=0.0).astype(np.float32)
            return ((dinfo, A, mask), int(A.nbytes + mask.nbytes), "host")

        if not _est.cache_enabled():
            return build()[0]
        from . import dataset_cache as _dc

        return _dc.std_artifact(train, x, ("glrm", str(transform)), build)

    def _fit(self, x, y, train: Frame, valid: Optional[Frame]) -> GLRMModel:
        from .model_base import warn_host_solver

        warn_host_solver('glrm', train.nrow, 200000)
        p = self._parms
        seed = p["_actual_seed"]
        k = int(p.get("k", 1))
        transform = p.get("transform", "NONE")
        dinfo, A, mask = self._expand_masked(train, x, transform)
        n, pd = A.shape
        k = min(k, min(n, pd))

        gx = float(p.get("gamma_x", 0.0)) + 1e-6
        gy = float(p.get("gamma_y", 0.0)) + 1e-6

        rng = np.random.default_rng(seed)
        if p.get("init", "PlusPlus") == "Random":
            X = rng.normal(scale=0.1, size=(n, k)).astype(np.float32)
            Y = rng.normal(scale=0.1, size=(k, pd)).astype(np.float32)
        else:
            # SVD warm start on the zero-imputed matrix (GLRM init=SVD;
            # markedly better basin than random for the ALS iterations)
            Uz, s, Vt = np.linalg.svd(A, full_matrices=False)
            X = (Uz[:, :k] * s[:k]).astype(np.float32)
            Y = Vt[:k].astype(np.float32)

        Aj = jnp.asarray(A)
        Mj = jnp.asarray(mask)
        iters = min(int(p.get("max_iterations", 1000)), 300)
        engine_on = (not _est.legacy() and not distdata.multiprocess()
                     and iters > 0)

        if engine_on:
            # the WHOLE alternating loop as one device program: while_loop
            # over (update_X; update_Y) pairs, the objective-delta test
            # every 5th iteration ON DEVICE (the host loop's cadence)
            fn = _glrm_fit_fn(cloudlib.cloud())
            t0 = time.perf_counter()
            with _est.iter_phase():
                # segmented dispatch under QoS: bounded device programs
                # with the (X, Y, obj, it, done) carry kept on device
                Xj, Yj = jnp.asarray(X), jnp.asarray(Y)
                obj_d = jnp.float32(jnp.inf)
                it_d = jnp.int32(0)
                done_d = jnp.asarray(False)
                for stop in _est.segment_stops(iters):
                    Xj, Yj, obj_d, it_d, done_d = fn(
                        Aj, Mj, Xj, Yj, obj_d, it_d, done_d,
                        jnp.float32(gx), jnp.float32(gy), jnp.int32(iters),
                        jnp.int32(stop), jnp.float32(1e-8))
                    if stop < iters:
                        if bool(done_d) or int(it_d) >= iters:
                            break
                        _qos.yield_point("est_segment",
                                         compensate="est_iter")
                obj = float(obj_d)
            _est.record_fit("glrm", "fused", iterations=int(it_d),
                            converged=bool(done_d),
                            wall_s=time.perf_counter() - t0)
        else:
            @jax.jit
            def update_X(Xc, Yc):
                # row-wise masked normal equations, batched: G_i = Y M_i Y'
                G = jnp.einsum("kp,np,lp->nkl", Yc, Mj, Yc) + gx * jnp.eye(k)[None]
                b = jnp.einsum("kp,np->nk", Yc, Aj * Mj)
                return jax.vmap(jnp.linalg.solve)(G, b)

            @jax.jit
            def update_Y(Xc, Yc):
                G = jnp.einsum("nk,np,nl->pkl", Xc, Mj, Xc) + gy * jnp.eye(k)[None]
                b = jnp.einsum("nk,np->pk", Xc, Aj * Mj)
                return jax.vmap(jnp.linalg.solve)(G, b).T

            @jax.jit
            def objective(Xc, Yc):
                R = (Aj - Xc @ Yc) * Mj
                return jnp.sum(R * R) + gx * jnp.sum(Xc * Xc) + gy * jnp.sum(Yc * Yc)

            Xj, Yj = jnp.asarray(X), jnp.asarray(Y)
            prev = np.inf
            it_done = 0
            for it in range(iters):
                Xj = update_X(Xj, Yj)
                Yj = update_Y(Xj, Yj)
                it_done = it + 1
                if it % 5 == 4 or it == iters - 1:
                    obj = float(objective(Xj, Yj))
                    if abs(prev - obj) < 1e-8 * max(abs(prev), 1):
                        break
                    prev = obj
            obj = float(objective(Xj, Yj))
            _est.record_fit("glrm", "legacy", iterations=it_done)

        model = GLRMModel(self, x, dinfo, np.asarray(Xj), np.asarray(Yj), k,
                          obj)
        mm = ModelMetricsBase(nobs=n)
        mm.description = f"objective={model.objective:.6g}"
        model.training_metrics = mm
        return model


GLRM = H2OGeneralizedLowRankEstimator
