"""SharedTree driver — the boosting/forest loop over the jitted tree builder.

Reference parity: `h2o-algos/src/main/java/hex/tree/SharedTree.java`
(`Driver.computeImpl`: init counts → outer tree loop → score/early-stop) and
`hex/tree/gbm/GBM.java` (`GBMDriver.buildNextKTrees`: k trees per iteration,
one per class). Scoring cadence follows `score_tree_interval` /
`score_each_iteration`; early stopping is `hex/ScoreKeeper.java` semantics;
variable importance is squared-error-reduction per feature
(`hex/tree/SharedTree.java` varimp from split gains).

The per-tree step (gradients → histograms → splits → partition) is one XLA
program (see `tree.py`); on a multi-device cloud it runs under `shard_map`
with rows sharded over ``hosts`` and histogram merges as `lax.psum` —
replacing the MRTask RPC-tree reduce of `ScoreBuildHistogram2.java`.
"""

from __future__ import annotations

import functools
import os
import threading
import time
from typing import Dict, List, NamedTuple, Optional, Sequence

_PROFILE = bool(os.environ.get("H2O3_PROFILE"))

from ..runtime import phases as _phases_acct
from ..runtime import qos as _qos


class _Phase:
    """Env-gated phase timer (H2O3_PROFILE=1) — the `water.util.Timer`
    per-stage logging analog for the training driver."""

    _SUBTRACT_KEYS = _phases_acct.COMPILE_KEYS + ("collective",)

    def __init__(self):
        self.t = time.time()
        self._comp0 = _phases_acct.totals(self._SUBTRACT_KEYS)

    def mark(self, name, sync=None):
        """Record a phase boundary into /3/Timeline (always); under
        H2O3_PROFILE=1 or H2O3_PHASE_ACCOUNTING=1 additionally device-sync
        first, so the recorded seconds are execution (not dispatch) time.
        Boundaries also feed runtime.phases so bench.py can decompose
        wall-clock into {h2d, compute, d2h, ...} buckets."""
        from ..runtime.timeline import Timeline

        _phases = _phases_acct
        synced = (_PROFILE or _phases.ENABLED) and sync is not None
        if synced:
            # fetch one element: through a remote-device tunnel,
            # block_until_ready can return before the computation lands —
            # a tiny D2H is the only reliable barrier
            import numpy as _np

            try:
                _np.asarray(sync.ravel()[:1])
            except Exception:
                jax.block_until_ready(sync)
        now = time.time()
        if _PROFILE:
            print(f"[h2o3-profile] {name}: {now - self.t:.3f}s", flush=True)
        Timeline.record("train_phase", name, secs=round(now - self.t, 4),
                        synced=synced)
        # compile/trace time inside this interval is already accounted by
        # the monitoring listener, and collective-fence waits by
        # mesh.collective_fence — subtract both so the compute bucket
        # holds execution time, not compilation or merge waits (the phase
        # split must sum to ≤ wall, never double-count)
        comp = _phases.totals(self._SUBTRACT_KEYS)
        _phases.add_mark(name, max(now - self.t - (comp - self._comp0), 0.0))
        self._comp0 = comp
        self.t = now

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ..frame.binning import BinnedMatrix, bin_apply, build_bins
from ..frame.frame import Frame
from ..parallel import distdata
from ..parallel import mesh as cloudlib
from . import distributions as dist_mod
from . import tree as treelib
from .metrics import (
    ModelMetricsBinomial,
    ModelMetricsMultinomial,
    ModelMetricsRegression,
)
from .model_base import (SCORE_ROW_BUCKET, DataInfo, H2OEstimator,
                         H2OModel, ScoreKeeper, ScoringHistory,
                         response_info)


_predict_codes_jit = jax.jit(treelib.predict_codes, static_argnames=("max_depth",))


@functools.partial(jax.jit, static_argnames=("nbins",))
def _binom_binned_stats(margins, y_d, n, nbins: int = 400):
    """AUC2-style 400-bin score histogram ON DEVICE (hex/AUC2.java): the
    quantile edges, per-bin (pos, neg) counts and the logloss/mse sums are
    the only things that cross the wire (~KBs instead of the 4·n-byte
    margin pull + a host rank sort).

    `n` is TRACED (pad rows masked out), so CV folds padded to the parent
    frame's row shape reuse ONE compiled program instead of recompiling
    per fold row count (cold-start tax, VERDICT r03 #2)."""
    valid = jnp.arange(margins.shape[0]) < n
    p = jax.nn.sigmoid(margins[:, 0])
    y = y_d[:, 0]
    qs = jnp.nanquantile(jnp.where(valid, p, jnp.nan),
                         jnp.linspace(0.0, 1.0, nbins))
    bins = jnp.searchsorted(qs, p, side="left")
    vf = valid.astype(jnp.float32)
    npos = jax.ops.segment_sum(y * vf, bins, num_segments=nbins + 1)
    nneg = jax.ops.segment_sum((1.0 - y) * vf, bins,
                               num_segments=nbins + 1)
    pc = jnp.clip(p, 1e-15, 1 - 1e-15)
    nll = -jnp.sum(jnp.where(valid & (y > 0.5), jnp.log(pc), 0.0)
                   + jnp.where(valid & (y <= 0.5), jnp.log(1.0 - pc), 0.0))
    sq = jnp.sum(jnp.where(valid, (p - y) ** 2, 0.0))
    return qs, npos, nneg, nll, sq


def _event_loss_terms(margins, y_d, valid, inv_ntrees, mode: str,
                      problem: str, dist: str):
    """Per-row (loss·mask, mask) terms of the scoring-event mean loss —
    the ONE source of the event math, shared by the historical whole-array
    reduction (`_event_loss_device`) and the sharded blocked reduction
    (`_sharded_event_loss_fn`) so the two can never diverge. Clips use
    1e-7 (the f64 path's 1e-15 rounds to exactly 0/1 in f32, which would
    turn a saturated probability into an inf logloss)."""
    vf = valid.astype(jnp.float32)
    probs = _margins_to_preds(mode, problem, dist, margins, inv_ntrees, jnp)
    eps = 1e-7
    if problem == "binomial":
        pc = jnp.clip(probs[:, 1], eps, 1 - eps)
        y = y_d[:, 0]
        nll = -jnp.where(y > 0.5, jnp.log(pc), jnp.log1p(-pc))
        return nll * vf, vf
    if problem == "multinomial":
        pc = jnp.clip(probs, eps, 1.0)
        nll = -jnp.sum(jnp.log(pc) * y_d, axis=1)
        return nll * vf, vf
    sq = (probs[:, 0] - y_d[:, 0]) ** 2
    return sq * vf, vf


@functools.partial(jax.jit, static_argnames=("mode", "problem", "dist"))
def _event_loss_device(margins, y_d, valid, inv_ntrees, mode: str,
                       problem: str, dist: str):
    """Scoring-event mean loss ON DEVICE: ONE scalar is the only D2H — the
    host path pulled the full margin matrix (4·n·K bytes) through the
    tunnel per event. The link mapping is _margins_to_preds (the same
    source model.predict uses); `inv_ntrees` is traced so every event of a
    fit reuses ONE compiled program. On a multi-process cloud the inputs
    are global sharded arrays, so the mean comes back global and
    replicated — no separate host collective needed."""
    num, den = _event_loss_terms(margins, y_d, valid, inv_ntrees, mode,
                                 problem, dist)
    return jnp.sum(num) / jnp.maximum(jnp.sum(den), 1e-12)


def _sharded_event_loss_fn(cloud, shard_mode: str, n_shards: int,
                           mode: str, problem: str, dist: str):
    """Deterministic scoring-event loss for sharded fits: per-block partial
    sums + `ordered_axis_fold`, mirroring the histogram merge, so the
    early-stopping decisions an N-device fit makes are bit-identical to
    the 1-device forced-shard lane's (a last-ulp loss difference at the
    stopping tolerance boundary would otherwise diverge the tree COUNT,
    not just the bits). Cached on the cloud like the step programs."""
    from ..ops.histogram import ordered_axis_fold

    local_blocks = (n_shards // cloud.size if shard_mode == "mesh"
                    else n_shards)
    axis = (cloudlib.ROWS_AXIS
            if shard_mode == "mesh" and cloud.size > 1 else None)
    key_ = ("event", local_blocks, axis, mode, problem, dist)
    with _STEP_FNS_LOCK:
        cache = cloud.__dict__.setdefault("_event_fns_cache", {})
        fn = cache.get(key_)
        if fn is not None:
            return fn

    def inner(margins, y_d, valid, inv_ntrees):
        num, den = _event_loss_terms(margins, y_d, valid, inv_ntrees,
                                     mode, problem, dist)
        rows = num.shape[0] // local_blocks
        parts = jnp.stack([
            jnp.stack([jnp.sum(num[b * rows:(b + 1) * rows]),
                       jnp.sum(den[b * rows:(b + 1) * rows])])
            for b in range(local_blocks)])
        # the ONE instrumented fence of the fit (ISSUE 13): the event-loss
        # program runs once per scoring interval, so per-lane arrival
        # stamps here profile collective skew without touching the
        # per-level histogram hot path
        tot = ordered_axis_fold(parts, axis, timing_tag="event_loss")
        return tot[0] / jnp.maximum(tot[1], 1e-12)

    if axis is not None:
        rspec = P(cloudlib.ROWS_AXIS)
        inner = cloudlib.shard_call(
            inner, cloud, in_specs=(rspec, rspec, rspec, P()),
            out_specs=P(), check_rep=False)
    fn = jax.jit(inner)
    with _STEP_FNS_LOCK:
        cloud.__dict__.setdefault("_event_fns_cache", {})[key_] = fn
    return fn


@functools.partial(jax.jit, static_argnames=("max_depth",))
def _predict_forest_codes_jit(forest, codes, max_depth: int):
    """Σ over a stacked forest of per-row leaf values on binned codes."""
    per_tree = jax.vmap(lambda t: treelib.predict_codes(t, codes, max_depth))(forest)
    return per_tree.sum(axis=0)


@functools.partial(jax.jit, static_argnames=("max_depth",),
                   donate_argnums=(2,))
def _margin_ffwd_jit(forest, codes, margins, k, max_depth: int):
    """Checkpoint fast-forward: add a restored class-k forest's leaf sums
    to the margins in ONE program (works on process-spanning arrays, where
    the eager .at add would be rejected)."""
    per_tree = jax.vmap(
        lambda t: treelib.predict_codes(t, codes, max_depth))(forest)
    return margins.at[:, k].add(per_tree.sum(axis=0))


@functools.partial(jax.jit, static_argnames=("max_depth",),
                   donate_argnums=(2,))
def _valid_margin_update(packed, codes_v, margins_v, k, max_depth: int):
    """Add class-k leaf sums of a packed tree chunk to the validation
    margins — one jitted program so it also runs on process-spanning
    (multi-host) arrays, where eager slicing is rejected. `k` is TRACED
    (dynamic slice): one compiled program serves all K classes instead of
    K compile-cache loads."""
    sl = jax.lax.dynamic_index_in_dim(packed, k, axis=1, keepdims=False)
    forest = treelib.Tree(
        sl[..., 0].astype(jnp.int32), sl[..., 1].astype(jnp.int32),
        sl[..., 2], sl[..., 3] > 0.5, sl[..., 4],
    )
    per_tree = jax.vmap(
        lambda t: treelib.predict_codes(t, codes_v, max_depth))(forest)
    return margins_v.at[:, k].add(per_tree.sum(axis=0))


# ---- DART dropout boosting (xgboost booster=dart; dart.cc) --------------
#
# Dropout granularity is a boosting ROUND: all K class trees of a round
# drop together, with one scale per round on the STORED (learn-rate-folded)
# leaf contributions. Commit normalization per xgboost docs: with k rounds
# dropped and learning rate lr, "tree" scales dropped rounds by k/(k+lr)
# and the new round by 1/(k+lr); "forest" scales both by 1/(1+lr).
# Scales are tracked host-side and baked into the packed leaf values after
# the loop, so scoring / MOJO / TreeSHAP see ordinary trees.


def _round_contribs(pk, codes, max_depth: int):
    """One packed round (K, T, C) → (N, K) leaf contributions on codes."""
    K = pk.shape[0]
    cs = []
    for k in range(K):
        t = treelib.Tree(pk[k, :, 0].astype(jnp.int32),
                         pk[k, :, 1].astype(jnp.int32),
                         pk[k, :, 2], pk[k, :, 3] > 0.5, pk[k, :, 4])
        cs.append(treelib.predict_codes(t, codes, max_depth))
    return jnp.stack(cs, axis=1)


@functools.partial(jax.jit, static_argnames=("max_depth",))
def _dart_drop_sum_jit(chunks, scales, codes, max_depth: int):
    """Σ over selected rounds of scale·leaf values → (N, K) margin mass.
    `chunks` is a pow2-padded TUPLE of (1, K, T, C) round packs — selected
    host-side so the work is O(dropped), and concatenated INSIDE the jit so
    process-spanning (multi-host) arrays are handled; zero-scale pad
    entries contribute exactly 0."""
    packed_sel = jnp.concatenate(chunks, axis=0)
    return jax.vmap(
        lambda pk, s: s * _round_contribs(pk, codes, max_depth)
    )(packed_sel, scales).sum(axis=0)


@functools.partial(jax.jit, donate_argnums=(0,))
def _dart_sub_jit(margins, dsum):
    return margins - dsum


@functools.partial(jax.jit, donate_argnums=(0,),
                   static_argnames=("max_depth",))
def _dart_fix_jit(margins, packed_new, dsum, codes, c_coef, d_coef,
                  max_depth: int):
    """margins + c_coef·(new round's contribution) + d_coef·dsum — the
    post-step normalization (coefficients differ between the training
    margins, which already had dsum subtracted, and validation margins,
    which did not)."""
    c_new = _round_contribs(packed_new[0], codes, max_depth)
    return margins + c_coef * c_new + d_coef * dsum


@functools.partial(jax.jit, donate_argnums=(0,))
def _dart_scale_jit(pk, s):
    return pk.at[..., 4].multiply(s)


def _margins_to_preds(mode, problem, dist, m, inv_ntrees, xp):
    """margins → predictions — the ONE per-mode link mapping, parameterized
    by array module so host scoring (np) and the device event kernel (jnp)
    cannot diverge. `inv_ntrees` is a scalar (python float on host, traced
    on device)."""
    if mode == "drf":
        # DRF: leaf values are per-leaf response means; prediction is the
        # forest average (hex/tree/drf/DRFModel.score0 vote averaging)
        m = m * inv_ntrees
        if problem == "binomial":
            p1 = xp.clip(m[:, 0], 0.0, 1.0)
            return xp.stack([1 - p1, p1], axis=1)
        if problem == "multinomial":
            p = xp.clip(m, 0.0, None)
            s = p.sum(axis=1, keepdims=True)
            return xp.where(s > 0, p / xp.maximum(s, 1e-12), 1.0 / m.shape[1])
        return m[:, :1]
    if problem == "binomial":
        p1 = 1 / (1 + xp.exp(-m[:, 0]))
        return xp.stack([1 - p1, p1], axis=1)
    if problem == "multinomial":
        e = xp.exp(m - m.max(axis=1, keepdims=True))
        return e / e.sum(axis=1, keepdims=True)
    mm = m[:, 0]
    if dist in ("poisson", "gamma", "tweedie"):
        return xp.exp(mm)[:, None]
    return mm[:, None]


def probs_from_margins(mode, problem, dist, m: np.ndarray, ntrees: int) -> np.ndarray:
    """Host-side margins → predictions (train-time scoring + model.predict)."""
    return _margins_to_preds(mode, problem, dist, np.asarray(m),
                             1.0 / max(ntrees, 1), np)


def _metrics_for(problem, yvec, probs):
    if problem == "binomial":
        return ModelMetricsBinomial.make(np.asarray(yvec.data), probs[:, 1])
    if problem == "multinomial":
        return ModelMetricsMultinomial.make(np.asarray(yvec.data), probs)
    return ModelMetricsRegression.make(yvec.numeric_np(), probs[:, 0])


def frame_to_matrix(frame: Frame, x: Sequence[str], expected_domains=None):
    """Frame → (X float64 with NaN NAs, is_categorical, domains). Enums stay
    as integer codes (the DHistogram categorical-bins path), not one-hot.

    expected_domains (training-time domains, aligned with x) triggers test-
    frame adaptation: codes are remapped label→training-code, unseen levels
    become NA — `hex/Model.adaptTestForTrain` semantics."""
    cols, cats, doms = [], [], []
    for i, n in enumerate(x):
        v = frame.vec(n)
        col = v.numeric_np()
        exp = expected_domains[i] if expected_domains is not None else None
        if v.type == "enum" and exp is not None and v.domain != exp:
            lookup = {lbl: j for j, lbl in enumerate(exp)}
            remap = np.asarray(
                [lookup.get(lbl, -1) for lbl in (v.domain or [])], np.float64
            )
            codes = np.asarray(v.data)
            col = np.where(
                codes >= 0,
                remap[np.maximum(codes, 0)] if len(remap) else -1.0,
                -1.0,
            )
            col = np.where(col < 0, np.nan, col)
        cols.append(col)
        cats.append(v.type == "enum")
        doms.append(v.domain)
    return np.column_stack(cols), np.asarray(cats), doms


class _StepCfg(NamedTuple):
    """STRUCTURAL configuration of the per-iteration tree-step program —
    only what changes the traced computation graph (shapes, depth, bins,
    problem/mode, static branches). Scalar hyperparameters (learn rate,
    min_rows, regularization, …) are TRACED inputs (the `hp` vector), so
    one compiled program serves every model sharing this cfg: CV folds,
    grid points, and AutoML steps vary scalars without recompiling.
    The jitted step functions are cached per (cfg, cloud)."""

    npad: int
    K: int
    F: int
    nbins: int
    problem: str
    dist: str
    mode: str
    max_depth: int
    has_mtries: bool   # the rate itself is TRACED (hp[8]) so DRF and XRT
    #                    share one program
    no_row_sampling: bool
    has_col_sampling: bool
    has_monotone: bool
    tweedie_power: float
    quantile_alpha: float
    hist_method: str = "auto"
    grow_policy: str = "depthwise"   # "lossguide" = xgboost leaf-wise
    max_leaves: int = 0              # lossguide leaf budget (0 = 2^depth)
    compact_cap: int = 0             # deep-level active-node compaction
    pack_bits: int = 0               # device-RESIDENT sub-byte code packing
    fused_split: bool = False        # single-pass split search (ISSUE 7)
    # sharded end-to-end training (ISSUE 12):
    #   "off"       — single-device semantics (also the H2O3_TREE_SHARD=0
    #                 escape hatch on a mesh: data stays on one device)
    #   "mesh"      — shard_map over the 1-D hosts mesh, blocked
    #                 deterministic histogram merge (all_gather + ordered
    #                 fold), rows sharded over devices
    #   "blocks"    — the SAME blocked reduction on one device, no mesh
    #                 (H2O3_TREE_SHARD=1: the forced-CPU lane that is
    #                 bit-identical to any mesh fit sharing n_shards)
    #   "mesh_psum" — the pre-ISSUE-12 shard_map + psum path, kept for the
    #                 legacy comparator and lossguide growth (multi-process
    #                 fits run "mesh" since ISSUE 18's pod lane)
    shard_mode: str = "off"
    n_shards: int = 0                # canonical total block count (S)


def _pack_hp(tp, lr, colp, mtries_rate=0.0) -> "jnp.ndarray":
    """The traced scalar hyperparameters, in a fixed layout:
    [min_rows, min_split_improvement, reg_lambda, reg_alpha, lr,
    learn_rate_annealing, col_sample_product, max_abs_leaf, mtries_rate]."""
    cap = float(tp.get("max_abs_leaf", np.inf))
    return jnp.asarray(
        [tp["min_rows"], tp["min_split_improvement"], tp["reg_lambda"],
         tp.get("reg_alpha", 0.0), lr, tp["learn_rate_annealing"], colp,
         cap if np.isfinite(cap) else 3.4e38, mtries_rate],
        jnp.float32)


_STEP_FNS_CAP = 32
# the per-cloud step-program cache and the device-pack registry are shared
# by concurrent candidate fits (runtime/trainpool.py) — guard them
_STEP_FNS_LOCK = threading.Lock()
_DEV_PACKS_LOCK = threading.Lock()


@jax.jit
def _stack_args(*xs):
    return jnp.stack(xs)


@jax.jit
def _concat_args(*xs):
    return jnp.concatenate(xs, axis=0)


# sub-byte code packing lives in ops/packing.py since ISSUE 7 (the
# histogram kernels and the partition step consume the packed words
# directly); these aliases keep the driver's historical surface
from ..ops import packing as _packing
from ..ops.histogram import host_callback_safe as _host_callback_safe
from ..ops.histogram import record_fit_plan as _record_fit_plan

_pack_host = _packing.pack_host
_unpack_device = _packing.unpack_device
_pack_bits_for = _packing.pack_bits_for


def tree_legacy() -> bool:
    """True when ``H2O3_TREE_LEGACY=1`` pins the seed tree hot path —
    full-width resident codes, the (L, F, B)-temporary split search and
    blocking chunk-boundary scoring — as the bit-exactness comparator
    (same pattern as the ingest/munge/train legacy flags)."""
    return os.environ.get("H2O3_TREE_LEGACY", "") == "1"


def _shard_plan(ndev: int, multiproc: bool, tp) -> tuple:
    """(shard_mode, n_shards) for one fit — the ONE place the ISSUE 12
    sharding decision is made (the warm-up thread and the training path
    must agree or they would warm different programs).

    Default: a multi-device single-process cloud runs the deterministic
    sharded path ("mesh"); one device runs unsharded ("off").
    ``H2O3_TREE_SHARD=0`` is the escape hatch (never shard — a broken mesh
    still trains, on one device); ``H2O3_TREE_SHARD=1`` forces the blocked
    reduction structure on a single device ("blocks") — the forced-CPU
    lane whose fits are bit-identical to mesh fits.

    n_shards (S) is the canonical block count: every row reduction runs as
    S ordered block partials regardless of how many devices they live on,
    so any two fits sharing S agree bitwise. S defaults to
    ``H2O3_TREE_SHARD_BLOCKS`` (8), raised to lcm(S, ndev) so each device
    holds a whole number of blocks — fits on 1/2/4/8 devices all share
    S=8 and are mutually bit-stable.

    Multi-process pod clouds (ISSUE 18) run the SAME deterministic "mesh"
    path over the global mesh: the canonical row layout (_fit's pod branch)
    keeps all real rows contiguous in global ingest order with the pad at
    the tail, so the S ordered block partials are the same sums a 1-device
    forced-shard fit computes and an N-process fit is bit-identical to it.
    Legacy comparator and lossguide growth keep the pre-ISSUE-12 shard_map
    + psum path ("mesh_psum") — on pods too. H2O3_TREE_SHARD=0 demotes a
    pod to mesh_psum rather than "off" (the data lives on other processes,
    so "train on one device" is not available there)."""
    import math

    env = os.environ.get("H2O3_TREE_SHARD", "").strip()
    legacy_lane = (tree_legacy()
                   or tp.get("grow_policy", "depthwise") == "lossguide")
    if multiproc:
        if env == "0" or legacy_lane:
            return ("mesh_psum" if ndev > 1 else "off"), 0
    elif env == "0":
        return "off", 0
    elif legacy_lane:
        return ("mesh_psum" if ndev > 1 else "off"), 0
    base = max(int(os.environ.get("H2O3_TREE_SHARD_BLOCKS", "8") or 8), 1)
    if ndev > 1:
        return "mesh", base * ndev // math.gcd(base, ndev)
    if env == "1":
        return "blocks", base
    return "off", 0


def _bucket_rows(npad: int) -> int:
    """Round a padded row count up to {1, 1.125, 1.25, ..., 2}·2^k so
    near-same-size datasets share compiled programs (≤12.5% pad overhead).
    Small shapes stay exact — their compiles are cheap and padding is not.
    H2O3_BUCKET_ROWS=0 disables (exact shapes; used by determinism tests
    to show padded-shape invariance of the trained model)."""
    if npad <= 8192 or os.environ.get("H2O3_BUCKET_ROWS", "1") == "0":
        return npad
    p = 1 << (npad.bit_length() - 1)
    for eighths in range(8, 17):
        cand = p * eighths // 8
        if cand >= npad:
            return cand
    return 2 * p


@jax.jit
def _sum_args(*xs):
    return sum(xs[1:], xs[0])


@jax.jit
def _copy_args(*xs):
    """Device copies (compact-cap chunk snapshot) — module-level so every
    chunk after the first is a jit dispatch-cache hit."""
    return tuple(x + 0 for x in xs)


def _tree_step_fns(cfg: _StepCfg, cloud):
    """(tree_jit, single_jit) for one step configuration, cached ON the
    cloud instance (keyed by cfg) so a mesh re-init naturally drops stale
    shard_map closures. LRU-bounded: evicting releases the jitted
    executables, so long-running servers sweeping many structural configs
    (depths/shapes) don't accumulate programs forever."""
    from collections import OrderedDict

    with _STEP_FNS_LOCK:
        cache = cloud.__dict__.setdefault("_step_fns_cache", OrderedDict())
        fns = cache.get(cfg)
        if fns is None:
            fns = _build_tree_step_fns(cfg, cloud)
            cache[cfg] = fns
            while len(cache) > _STEP_FNS_CAP:
                cache.popitem(last=False)
        else:
            cache.move_to_end(cfg)
        return fns


def _build_tree_step_fns(cfg: _StepCfg, cloud):
    """Construct (tree_jit, single_jit) for one step configuration.

    All data (including the monotone-constraint vector) arrives as
    ARGUMENTS — a closure-captured device array would be embedded in the
    HLO as a literal, defeating the persistent compilation cache and
    bloating programs."""
    npad, K, F = cfg.npad, cfg.K, cfg.F

    def _grads(margins, y_d, k):
        if cfg.mode == "drf":
            return -y_d[:, k], jnp.ones_like(y_d[:, k])
        if cfg.problem == "multinomial":
            p = jax.nn.softmax(margins, axis=1)
            return p[:, k] - y_d[:, k], p[:, k] * (1 - p[:, k])
        return dist_mod.grad_hess(
            cfg.dist, margins[:, 0], y_d[:, 0],
            tweedie_power=cfg.tweedie_power, alpha=cfg.quantile_alpha,
        )

    def _build_one(codes, g, h, w, fm, edges, mono, hp, key):
        if cfg.grow_policy == "lossguide":
            lg_kwargs = dict(max_depth=cfg.max_depth, nbins=cfg.nbins,
                             max_leaves=cfg.max_leaves,
                             hist_method=cfg.hist_method)
            # consult the shard PLAN, not just the cloud size: under the
            # H2O3_TREE_SHARD=0 escape hatch the data is unsharded and
            # padded for one device — running collectives anyway would
            # defeat the hatch (and reject non-dividing npads)
            if cloud.size > 1 and cfg.shard_mode == "mesh_psum":
                rspec = P(cloudlib.ROWS_AXIS)

                def inner_lg(codes, g, h, w, fm, edges, mono, hp, key):
                    return treelib.build_tree_lossguide(
                        codes, g, h, w, fm, edges,
                        min_rows=hp[0], min_split_improvement=hp[1],
                        reg_lambda=hp[2], reg_alpha=hp[3],
                        max_abs_leaf=hp[7],
                        axis_name=cloudlib.ROWS_AXIS, **lg_kwargs,
                    )

                fn = cloudlib.shard_call(
                    inner_lg, cloud,
                    in_specs=(rspec, rspec, rspec, rspec, P(), P(), P(),
                              P(), P()),
                    out_specs=(
                        treelib.Tree(P(), P(), P(), P(), P()), rspec,
                        P(), P(),
                    ),
                    # older jax's replication checker rejects the
                    # fori_loop frontier carry (psum'd values re-entering
                    # the loop); the outputs ARE replicated — newer jax
                    # infers it, 0.4.x needs the check off
                    check_rep=False,
                )
                return fn(codes, g, h, w, fm, edges, mono, hp, key)
            return treelib.build_tree_lossguide(
                codes, g, h, w, fm, edges,
                min_rows=hp[0], min_split_improvement=hp[1],
                reg_lambda=hp[2], reg_alpha=hp[3], max_abs_leaf=hp[7],
                **lg_kwargs)
        kwargs = dict(max_depth=cfg.max_depth, nbins=cfg.nbins,
                      hist_method=cfg.hist_method,
                      compact_cap=cfg.compact_cap,
                      pack_bits=cfg.pack_bits,
                      fused_split=cfg.fused_split)
        use_mesh = cloud.size > 1 and cfg.shard_mode in ("mesh", "mesh_psum")
        if use_mesh or cfg.shard_mode == "blocks":
            # ISSUE 12: the sharded tree step. ONE inner function serves
            # both lanes (the t5x-style cpu-fallback contract, SNIPPETS.md
            # [1] via mesh.shard_call): on the mesh it runs under shard_map
            # with rows sharded and S/ndev local blocks per device; on one
            # device ("blocks") the identical body runs under plain jit
            # with all S blocks local — bit-identical by the ordered-fold
            # construction in ops/histogram.
            local_blocks = (cfg.n_shards // cloud.size
                            if cfg.shard_mode == "mesh" else
                            cfg.n_shards if cfg.shard_mode == "blocks"
                            else 0)
            axis = cloudlib.ROWS_AXIS if use_mesh else None
            rspec = P(cloudlib.ROWS_AXIS)

            def inner(codes, g, h, w, fm, edges, mono, hp, key):
                kw = dict(kwargs)
                if cfg.has_monotone:
                    kw["monotone"] = mono
                if cfg.has_mtries:
                    kw["mtries_rate"] = hp[8]
                return treelib.build_tree(
                    codes, g, h, w, fm, edges, key=key,
                    min_rows=hp[0], min_split_improvement=hp[1],
                    reg_lambda=hp[2], reg_alpha=hp[3], max_abs_leaf=hp[7],
                    axis_name=axis, n_shard_blocks=local_blocks, **kw,
                )

            out_specs = (
                treelib.Tree(P(), P(), P(), P(), P()), rspec, P(), P(),
            )
            if cfg.compact_cap:
                # overflow flag: derived from the merged histograms, so it
                # is identical (replicated) on every shard
                out_specs = out_specs + (P(),)
            fn = cloudlib.shard_call(
                inner, cloud,
                in_specs=(rspec, rspec, rspec, rspec, P(), P(), P(), P(),
                          P()),
                out_specs=out_specs,
                # the deterministic merge replicates via all_gather + fold,
                # which shard_map cannot statically infer — and on the
                # psum path jax 0.4.x's replication checker rejects the
                # level loop's carry ("Scan carry ... mismatched
                # replication types": psum'd values re-entering the scan),
                # exactly the lossguide failure fixed in ISSUE 12. The
                # outputs ARE replicated on every path; the static check
                # stays off (newer jax infers it correctly anyway).
                check_rep=False,
            )
            return fn(codes, g, h, w, fm, edges, mono, hp, key)
        if cfg.has_monotone:
            kwargs["monotone"] = mono
        if cfg.has_mtries:
            kwargs["mtries_rate"] = hp[8]
        return treelib.build_tree(
            codes, g, h, w, fm, edges, key=key, max_abs_leaf=hp[7],
            min_rows=hp[0], min_split_improvement=hp[1],
            reg_lambda=hp[2], reg_alpha=hp[3], **kwargs)

    def _one_tree(margins, codes_a, y_a, w_a, rate_a, edges_a, mono, hp,
                  key, m, g_ext=None, h_ext=None):
        """Build the K trees of boosting iteration m (traced int)."""
        krow, kcol, ktree = jax.random.split(jax.random.fold_in(key, 0), 3)
        # rate_a is per-row: constant sample_rate, or per-class rates when
        # sample_rate_per_class is set. With no sampling at all the
        # per-tree npad-point RNG draw is skipped entirely (static flag).
        if cfg.no_row_sampling:
            row_mask = jnp.ones(npad, jnp.float32)
            wt = w_a
        else:
            row_mask = (
                jax.random.uniform(krow, (npad,)) < rate_a
            ).astype(jnp.float32)
            wt = w_a * row_mask
        if cfg.has_col_sampling:
            fm = (jax.random.uniform(kcol, (F,)) < hp[6]).astype(jnp.float32)
            fm = fm.at[0].set(jnp.maximum(fm[0], 1 - fm.sum().clip(0, 1)))
        else:
            fm = jnp.ones(F, jnp.float32)
        scale = (hp[4] * jnp.power(hp[5], m.astype(jnp.float32))
                 ).astype(jnp.float32)
        trs, covs, gains_acc = [], [], jnp.zeros(F, jnp.float32)
        oob_inc = None
        ov_sum = jnp.int32(0)
        for k in range(K):
            ktree = jax.random.fold_in(ktree, k)
            if g_ext is not None:
                g, h = g_ext, h_ext
            else:
                g, h = _grads(margins, y_a, k)
            if cfg.compact_cap:
                tr, leaf_idx, gains, cover, ov = _build_one(
                    codes_a, g, h, wt, fm, edges_a, mono, hp, ktree)
                ov_sum = ov_sum + ov
            else:
                tr, leaf_idx, gains, cover = _build_one(
                    codes_a, g, h, wt, fm, edges_a, mono, hp, ktree)
            tr = tr._replace(value=tr.value * scale)
            # margins track Σ tree outputs for ALL modes: GBM boosting
            # margins, or DRF leaf-mean sums (÷ntrees at scoring time)
            leaf_vals = treelib.value_at(tr.value, leaf_idx)
            margins = margins.at[:, k].add(leaf_vals)
            if cfg.mode == "drf":
                # out-of-bag contribution (DRF OOB scoring): rows NOT
                # sampled into this tree accumulate its prediction
                col = leaf_vals * (1.0 - row_mask)
                oob_inc = col[:, None] if oob_inc is None else jnp.concatenate(
                    [oob_inc, col[:, None]], axis=1)
            trs.append(tr)
            covs.append(cover)
            gains_acc = gains_acc + gains
        stacked = treelib.Tree(
            *[jnp.stack([getattr(t, f) for t in trs]) for f in treelib.Tree._fields]
        )
        covers = jnp.stack(covs)                      # (K, T)
        return (margins, stacked, covers, gains_acc, oob_inc,
                (1.0 - row_mask), ov_sum)

    def _pack(stacked, covers):
        """Tree fields + covers → one f32 array (…, T, 6): a single D2H
        transfer moves a whole chunk of trees (each sync transfer through
        a remote-TPU tunnel pays seconds of fixed latency)."""
        return jnp.stack(
            [stacked.feat.astype(jnp.float32),
             stacked.bin.astype(jnp.float32),
             stacked.thr,
             stacked.is_split.astype(jnp.float32),
             stacked.value,
             covers],
            axis=-1,
        )

    @functools.partial(jax.jit, donate_argnums=(0, 1, 2))
    def tree_jit(margins, oob_sum, oob_cnt, codes_a, y_a, w_a, rate_a,
                 edges_a, mono, hp, key, m):
        margins, stacked, covers, gains, oob_inc, oob_mask, ov = _one_tree(
            margins, codes_a, y_a, w_a, rate_a, edges_a, mono, hp,
            jax.random.fold_in(key, m), m
        )
        if oob_inc is not None:
            oob_sum = oob_sum + oob_inc
            oob_cnt = oob_cnt + oob_mask
        return margins, oob_sum, oob_cnt, _pack(stacked, covers), gains, ov

    single_jit = jax.jit(
        lambda margins, codes_a, y_a, w_a, rate_a, edges_a, mono, hp, key, m, g_ext, h_ext: (
            lambda r: (r[0], _pack(r[1], r[2]), r[3])
        )(_one_tree(margins, codes_a, y_a, w_a, rate_a, edges_a, mono, hp,
                    jax.random.fold_in(key, m), m, g_ext, h_ext)),
        donate_argnums=(0,),
    )
    return tree_jit, single_jit


_DEV_PACKS: List = []  # weakrefs of models holding HBM forest packs (FIFO)


def pack_nbytes(pd) -> int:
    """HBM footprint of a packed forest — the ONE sizing rule shared by
    eviction and DKV accounting."""
    return int(np.prod(pd.shape)) * getattr(pd.dtype, "itemsize", 4)


def _register_dev_pack(model, budget: int) -> None:
    """Track device-resident forests; past `budget` total bytes, evict the
    OLDEST packs to host so long grid/AutoML runs on small-HBM devices
    cannot accumulate forests until allocation fails. The newest pack is
    never evicted (it is the model being trained)."""
    import weakref

    with _DEV_PACKS_LOCK:
        _DEV_PACKS.append(weakref.ref(model))
        live, total = [], 0
        for r in _DEV_PACKS:
            m = r()
            if m is not None and m.__dict__.get("_packed_dev") is not None:
                live.append(r)
                total += pack_nbytes(m._packed_dev)
        drop = 0
        while total > budget and drop < len(live) - 1:
            m = live[drop]()
            if m is not None:
                total -= pack_nbytes(m._packed_dev)
                m.release_device_forest()
            drop += 1
        _DEV_PACKS[:] = live[drop:]


class SharedTreeModel(H2OModel):
    algo = "sharedtree"

    def __init__(self, params, x, y, bm: BinnedMatrix, problem, nclass, domain,
                 distribution, f0, forest, max_depth, mode="gbm",
                 packed_dev=None, nclasses_packed=1):
        # report the concrete builder's algo (gbm/drf/...), not the shared base
        self.algo = getattr(params, "algo", self.algo)
        super().__init__(params)
        self.x = list(x)
        self.y = y
        self.bm = bm
        self.problem = problem
        self.nclass = nclass
        self.domain = domain
        self.distribution = distribution
        self.f0 = f0              # scalar or (K,) initial margin
        # device-resident pack: (ntrees, K, T, 6) in HBM. Deep heaps are
        # 12.6 MB/tree and a remote-chip tunnel moves ~6 MB/s, so the host
        # copy (mojo/save/tree-API consumers) is materialized LAZILY;
        # scoring slices the pack on device and never pays the transfer.
        self._packed_dev = packed_dev
        self._K_packed = nclasses_packed
        self.forest = forest      # list over classes of stacked Tree arrays
        self.max_depth = max_depth
        self.mode = mode          # 'gbm' (summed margins) | 'drf' (averaged leaves)
        if packed_dev is not None:
            self.ntrees_built = int(packed_dev.shape[0])
        else:
            self.ntrees_built = int(forest[0].feat.shape[0]) if forest else 0
        if packed_dev is None:
            self.covers = None    # list over classes of (ntrees, T) — TreeSHAP

    @property
    def forest(self):
        if self._forest is None and self._packed_dev is not None:
            self._materialize_host_forest()
        return self._forest

    @forest.setter
    def forest(self, v):
        self._forest = v
        self.__dict__.pop("_padded_forests", None)
        self.__dict__.pop("_score_tables", None)

    @property
    def covers(self):
        if self.__dict__.get("_covers") is None and self._packed_dev is not None:
            self._materialize_host_forest()
        return self.__dict__.get("_covers")

    @covers.setter
    def covers(self, v):
        self._covers = v

    def release_device_forest(self):
        """Materialize the host copy and free the HBM pack (eviction)."""
        if self.__dict__.get("_packed_dev") is not None:
            self._materialize_host_forest()
            self._packed_dev = None
            self.__dict__.pop("_padded_forests", None)
            self.__dict__.pop("_score_tables", None)

    def _materialize_host_forest(self):
        """The deferred forest D2H: one bulk transfer, then host slicing."""
        ap = np.asarray(self._packed_dev)
        forest, covers = [], []
        for k in range(self._K_packed):
            forest.append(treelib.Tree(
                np.ascontiguousarray(ap[:, k, :, 0]).astype(np.int32),
                np.ascontiguousarray(ap[:, k, :, 1]).astype(np.int32),
                np.ascontiguousarray(ap[:, k, :, 2]),
                ap[:, k, :, 3] > 0.5,
                np.ascontiguousarray(ap[:, k, :, 4]),
            ))
            covers.append(np.ascontiguousarray(ap[:, k, :, 5]))
        self._forest = forest
        self._covers = covers

    def summary(self):
        """ModelSummary of SharedTreeModel: tree count + depth/leaf stats."""
        s = super().summary()
        depths, leaves = [], []
        if self._forest is None and self._packed_dev is not None:
            # device reduction — stats without materializing the host forest
            issp = self._packed_dev[..., 3] > 0.5          # (nt, K, T)
            T = issp.shape[2]
            nd = jnp.floor(jnp.log2(jnp.arange(1, T + 1, dtype=jnp.float32)))
            d_tk = jnp.max(jnp.where(issp, nd[None, None, :] + 1, 0.0),
                           axis=2)                          # (nt, K)
            l_tk = issp.sum(axis=2) + 1
            depths = [int(v) for v in np.asarray(d_tk).ravel()]
            leaves = [int(v) for v in np.asarray(l_tk).ravel()]
        else:
            for stacked in self.forest:
                issp = np.asarray(stacked.is_split)
                node_depth = np.floor(np.log2(np.arange(1, issp.shape[1] + 1)))
                for t in range(issp.shape[0]):
                    d = node_depth[issp[t]].max() + 1 if issp[t].any() else 0
                    depths.append(int(d))
                    leaves.append(int(issp[t].sum() + 1))
        s.update(number_of_trees=self.ntrees_built,
                 min_depth=int(min(depths, default=0)),
                 max_depth=int(max(depths, default=0)),
                 mean_leaves=float(np.mean(leaves)) if leaves else 0.0)
        req = getattr(self, "requested_max_depth", self.max_depth)
        if req != self.max_depth:
            # the HBM-feasibility clamp reduced the user's max_depth — make
            # that visible in the model summary, not just a log line
            s.update(requested_max_depth=int(req),
                     max_depth_clamped_to=int(self.max_depth))
        return s

    def _matrix(self, frame: Frame) -> np.ndarray:
        X, _, _ = frame_to_matrix(frame, self.x, expected_domains=self.bm.domains)
        return X

    def _padded_forest(self, k: int):
        """Class-k forest with ntrees padded to the next power of two
        (zero-valued unsplit trees add 0 to the margin), cached on the
        model: models differing only in tree count share one compiled
        scoring program — AutoML/SE score many models per run — and
        repeated scoring reuses the same backing arrays."""
        cache = self.__dict__.setdefault("_padded_forests", {})
        if k not in cache:
            if self._forest is None and self._packed_dev is not None:
                # slice the device pack in HBM — scoring never pulls the
                # forest to host
                ap = self._packed_dev
                nt = int(ap.shape[0])
                bucket = 1 << (nt - 1).bit_length() if nt else 0
                sl = ap[:, k]                              # (nt, T, 6)
                if bucket != nt:
                    sl = jnp.concatenate(
                        [sl, jnp.zeros((bucket - nt,) + sl.shape[1:],
                                       sl.dtype)], axis=0)
                cache[k] = treelib.Tree(
                    sl[..., 0].astype(jnp.int32), sl[..., 1].astype(jnp.int32),
                    sl[..., 2], sl[..., 3] > 0.5, sl[..., 4])
                return cache[k]
            stacked = self.forest[k]
            nt = int(np.asarray(stacked.feat).shape[0])
            bucket = 1 << (nt - 1).bit_length() if nt else 0
            if bucket != nt:
                padn = bucket - nt
                stacked = treelib.Tree(*[
                    np.concatenate([np.asarray(f), np.zeros(
                        (padn,) + np.asarray(f).shape[1:],
                        np.asarray(f).dtype)], axis=0)
                    for f in stacked
                ])
            cache[k] = stacked
        return cache[k]

    @property
    def _n_class_forests(self) -> int:
        if self._forest is None and self._packed_dev is not None:
            return self._K_packed
        return len(self.forest)

    def _score_table(self, k: int):
        """Fused-scorer pack for class-k forest (treelib.build_score_table),
        cached beside `_padded_forests` — scoring fresh frames is the hot
        path for model_performance / AutoML leaderboard_frame / REST
        Predictions, and the pack build (~150 ms) amortizes across them."""
        cache = self.__dict__.setdefault("_score_tables", {})
        if k not in cache:
            cache[k] = treelib.build_score_table_jit(
                self._padded_forest(k), max_depth=self.max_depth)
            # the padded Tree slices are dead weight once the score pack
            # exists (fused is the default path); drop them so deep-forest
            # HBM peaks don't stack pack + padded forest + score table.
            # `_padded_forest` rebuilds on demand for the walk fallback /
            # tree-API consumers.
            self.__dict__.get("_padded_forests", {}).pop(k, None)
        return cache[k]

    # margin(s) on raw feature matrix
    def _margins(self, X: np.ndarray) -> np.ndarray:
        n = X.shape[0]
        # row-bucket the jitted scorer's input: nearby frame sizes (CV
        # folds of 2667 vs 2666 rows, paged scoring) land on ONE compiled
        # program instead of recompiling per exact row count — each extra
        # program costs a tunnel compile round-trip cold. Zero-filled pad
        # rows walk the trees harmlessly and are sliced off below.
        npad = cloudlib.pad_to_multiple(n, SCORE_ROW_BUCKET)
        if npad != n:
            X = np.concatenate([np.asarray(X, np.float32),
                                np.zeros((npad - n, X.shape[1]),
                                         np.float32)])
        Xj = jnp.asarray(X, jnp.float32)
        fused = os.environ.get("H2O3_FOREST_SCORER", "fused") != "walk"
        outs = []
        for k in range(self._n_class_forests):
            if fused:
                walk, value = self._score_table(k)
                s = treelib.predict_forest_fused(walk, value, Xj,
                                                 self.max_depth)
            else:
                s = treelib.predict_forest_raw(self._padded_forest(k), Xj,
                                               self.max_depth)
            f0k = self.f0 if np.ndim(self.f0) == 0 else self.f0[k]
            outs.append(np.asarray(s, np.float64)[:n] + f0k)
        return np.column_stack(outs)

    def _margins_codes(self, codes: np.ndarray) -> np.ndarray:
        """Forest margins on PRE-BINNED codes (the CV fold-reuse holdout
        path): rows of the parent's `BinnedMatrix` score through
        `predict_codes` directly — no raw-matrix rebuild, no re-bin. Rows
        are bucketed like `_margins` so CV folds share compiled scorers;
        zero pad codes walk the trees harmlessly and are sliced off."""
        n = codes.shape[0]
        npad = cloudlib.pad_to_multiple(n, SCORE_ROW_BUCKET)
        if npad != n:
            codes = np.concatenate(
                [codes, np.zeros((npad - n, codes.shape[1]), codes.dtype)])
        cj = jnp.asarray(codes)
        outs = []
        for k in range(self._n_class_forests):
            stacked = jax.tree.map(jnp.asarray, self._padded_forest(k))
            s = _predict_forest_codes_jit(stacked, cj, self.max_depth)
            f0k = self.f0 if np.ndim(self.f0) == 0 else self.f0[k]
            outs.append(np.asarray(s, np.float64)[:n] + f0k)
        return np.column_stack(outs)

    def _probs_from_codes(self, codes: np.ndarray) -> np.ndarray:
        return self._finish_probs(self._margins_codes(codes))

    def _score_probs(self, X: np.ndarray, offset: Optional[np.ndarray] = None) -> np.ndarray:
        return self._finish_probs(self._margins(X), offset)

    def _finish_probs(self, m: np.ndarray,
                      offset: Optional[np.ndarray] = None) -> np.ndarray:
        if offset is not None and self.mode != "drf":
            m = m + offset[:, None]
        out = probs_from_margins(self.mode, self.problem, self.distribution,
                                 m, self.ntrees_built)
        dists = getattr(self, "balance_dists", None)
        if dists is not None and self.problem in ("binomial", "multinomial"):
            # hex.Model correctProbabilities: rescale balanced-trained
            # probabilities back to the prior class distribution
            prior, modeld = dists
            if self.problem == "binomial" and len(prior) == 2:
                ratio = np.asarray(prior) / np.maximum(np.asarray(modeld), 1e-12)
                out = out * ratio[None, :]
            else:
                out = out * (np.asarray(prior) / np.maximum(np.asarray(modeld), 1e-12))[None, :]
            out = out / np.maximum(out.sum(axis=1, keepdims=True), 1e-12)
        return out

    def _offset_of(self, frame: Frame) -> Optional[np.ndarray]:
        oc = self.parms._parms.get("offset_column") if hasattr(self.parms, "_parms") else None
        if oc and oc in frame.names:
            return frame.vec(oc).numeric_np()
        return None

    def predict(self, test_data: Frame) -> Frame:
        out = self._score_probs(self._matrix(test_data), self._offset_of(test_data))
        if self.problem in ("binomial", "multinomial"):
            lab = out.argmax(axis=1)
            d = {"predict": np.asarray(self.domain, dtype=object)[lab]}
            for i, cls in enumerate(self.domain):
                d[str(cls)] = out[:, i]
            cal = getattr(self, "calibrator", None)
            if cal is not None and self.problem == "binomial":
                # calibrate_model: appended cal_ columns (hex/tree
                # CalibrationHelper — Platt scaling / isotonic)
                p1 = cal(out[:, 1])
                d[f"cal_{self.domain[0]}"] = 1.0 - p1
                d[f"cal_{self.domain[1]}"] = p1
            fr = Frame.from_dict(d, column_types={"predict": "enum"})
            return fr
        return Frame.from_dict({"predict": out[:, 0]})

    def _make_metrics(self, frame: Frame):
        out = self._score_probs(self._matrix(frame), self._offset_of(frame))
        return _metrics_for(self.problem, frame.vec(self.y), out)

    def predict_contributions(self, test_data: Frame, output_format="Original",
                              top_n=0, bottom_n=0, compare_abs=False) -> Frame:
        """Per-row SHAP feature contributions + BiasTerm (path-dependent
        TreeSHAP — hex/genmodel TreeSHAP.java via Model.scoreContributions).
        Contributions are in the margin space (log-odds for GBM binomial,
        response for regression, probability for DRF binomial) and sum with
        BiasTerm to the raw prediction. Binomial/regression only, as in the
        reference."""
        if self.problem == "multinomial":
            raise ValueError(
                "predict_contributions is not supported for multinomial "
                "models (reference parity: hex/Model.scoreContributions)")
        if output_format not in ("Original", "Compact", "original", "compact"):
            raise ValueError("output_format must be 'Original' or 'Compact' "
                             "(they coincide here: enums stay integer-coded, "
                             "one column per input feature)")
        oc = (self.parms._parms.get("offset_column")
              if hasattr(self.parms, "_parms") else None)
        if oc:
            raise ValueError(
                "predict_contributions is not supported for models trained "
                "with an offset_column (reference parity)")
        covers = getattr(self, "covers", None)
        if not covers:
            raise ValueError(
                "this model has no recorded node covers "
                "(trained before TreeSHAP support); retrain to enable "
                "predict_contributions")
        from .tree_shap import compute_contributions

        X = self._matrix(test_data)
        scale = 1.0 / max(self.ntrees_built, 1) if self.mode == "drf" else 1.0
        stacked = self.forest[0]
        f0k = self.f0 if np.ndim(self.f0) == 0 else self.f0[0]
        contrib = compute_contributions(
            stacked.feat, stacked.thr, stacked.is_split, stacked.value,
            covers[0], X, scale, f0k)
        names = list(self.x) + ["BiasTerm"]
        if top_n or bottom_n:
            # top/bottom-N pairs per row: (feature, value) columns, ranked by
            # signed value (or |value| with compare_abs), BiasTerm excluded
            vals = contrib[:, :-1]
            keys = np.abs(vals) if compare_abs else vals
            order = np.argsort(-keys, axis=1, kind="stable")
            d = {}
            fn_arr = np.asarray(self.x, dtype=object)
            nf = len(self.x)
            tn = nf if top_n < 0 else min(top_n, nf)
            bn = nf if bottom_n < 0 else min(bottom_n, nf)
            for i in range(tn):
                sel = order[:, i]
                d[f"top_feature_{i + 1}"] = fn_arr[sel]
                d[f"top_value_{i + 1}"] = np.take_along_axis(
                    vals, sel[:, None], axis=1)[:, 0]
            for i in range(bn):
                sel = order[:, nf - 1 - i]
                d[f"bottom_feature_{i + 1}"] = fn_arr[sel]
                d[f"bottom_value_{i + 1}"] = np.take_along_axis(
                    vals, sel[:, None], axis=1)[:, 0]
            d["BiasTerm"] = contrib[:, -1]
            return Frame.from_dict(d)
        return Frame.from_dict({n2: contrib[:, j] for j, n2 in enumerate(names)})

    def staged_predict_proba(self, test_data: Frame) -> Frame:
        """Class-1 probability after each successive tree (binomial GBM) —
        `h2o-py ModelBase.staged_predict_proba` (hex/tree staged scoring)."""
        if self.problem != "binomial" or self.mode == "drf":
            raise ValueError("staged_predict_proba supports binomial "
                             "boosting models only (reference parity)")
        oc = (self.parms._parms.get("offset_column")
              if hasattr(self.parms, "_parms") else None)
        if oc or getattr(self, "balance_dists", None) is not None:
            raise ValueError(
                "staged_predict_proba is not supported for models trained "
                "with offset_column or balance_classes (staged margins "
                "would disagree with predict())")
        X = jnp.asarray(self._matrix(test_data), jnp.float32)
        stacked = self.forest[0]
        per_tree = np.asarray(jax.vmap(
            lambda t: treelib.predict_raw(t, X, self.max_depth)
        )(jax.tree.map(jnp.asarray, stacked)))            # (ntrees, N)
        f0k = self.f0 if np.ndim(self.f0) == 0 else self.f0[0]
        margins = f0k + np.cumsum(per_tree, axis=0)
        probs = 1.0 / (1.0 + np.exp(-margins))
        return Frame.from_dict(
            {f"T{t + 1}": probs[t] for t in range(probs.shape[0])})

    @staticmethod
    def _route_rows(feat_t, thr_t, issp_t, X, max_depth, visit=None):
        """Route all rows of X root→leaf through one heap tree (NaN and
        x > thr go right — the single NA-routing rule shared by scoring,
        leaf assignment and feature frequencies). `visit(split_mask,
        split_feature_per_row, goes_right)` is called once per level;
        returns the final heap node per row."""
        N = X.shape[0]
        node = np.zeros(N, np.int64)
        for _ in range(max_depth):
            s = issp_t[node]
            if not s.any():
                break
            f = feat_t[node]
            xv = X[np.arange(N), f]
            right = (np.isnan(xv) | (xv > thr_t[node])) & s
            if visit is not None:
                visit(s, f, right)
            node = np.where(s, 2 * node + 1 + right.astype(np.int64), node)
        return node

    def feature_frequencies(self, test_data: Frame) -> Frame:
        """Per row, how many times each feature decides the row's path,
        summed over all trees — `h2o-py ModelBase.feature_frequencies`
        (hex/tree/SharedTreeModel feature frequencies)."""
        X = self._matrix(test_data)
        N = X.shape[0]
        counts = np.zeros((N, len(self.x)), np.int64)

        def visit(s, f, right):
            np.add.at(counts, (np.nonzero(s)[0], f[s]), 1)

        for stacked in self.forest:
            feat = np.asarray(stacked.feat)
            thr = np.asarray(stacked.thr)
            issp = np.asarray(stacked.is_split)
            for t in range(self.ntrees_built):
                self._route_rows(feat[t], thr[t], issp[t], X,
                                 self.max_depth, visit)
        return Frame.from_dict(
            {n2: counts[:, j].astype(np.float64)
             for j, n2 in enumerate(self.x)})

    def predict_leaf_node_assignment(self, test_data: Frame,
                                     type: str = "Path") -> Frame:
        """Leaf assignment per (tree, class): decision-path strings ("LRL…")
        or heap node ids — `Model.scoreLeafNodeAssignment`
        (hex/tree/SharedTreeModel leaf_node_assignment)."""
        if type not in ("Path", "Node_ID"):
            raise ValueError("type must be 'Path' or 'Node_ID'")
        X = self._matrix(test_data)
        N = X.shape[0]
        d = {}
        ctypes_ = {}
        for k, stacked in enumerate(self.forest):
            feat = np.asarray(stacked.feat)
            thr = np.asarray(stacked.thr)
            issp = np.asarray(stacked.is_split)
            for t in range(self.ntrees_built):
                paths = [np.full(N, "", dtype=f"<U{self.max_depth}")]

                def visit(s, right, _p=paths):
                    step = np.where(s, np.where(right, "R", "L"), "")
                    _p[0] = np.char.add(_p[0], step)

                node = self._route_rows(
                    feat[t], thr[t], issp[t], X, self.max_depth,
                    (lambda s, f, right: visit(s, right))
                    if type == "Path" else None)
                col = (f"T{t + 1}.C{k + 1}")
                if type == "Path":
                    d[col] = paths[0].astype(object)
                    ctypes_[col] = "enum"
                else:
                    d[col] = node.astype(np.float64)
        return Frame.from_dict(d, column_types=ctypes_ or None)


class H2OSharedTreeEstimator(H2OEstimator):
    """Common GBM/DRF/IF driver. Subclasses set `_mode` ('gbm'|'drf')."""

    _mode = "gbm"

    def _tree_params(self) -> Dict:
        p = self._parms
        return dict(
            ntrees=int(p.get("ntrees", 50)),
            max_depth=int(p.get("max_depth", 5 if self._mode == "gbm" else 20)),
            min_rows=float(p.get("min_rows", 10.0 if self._mode == "gbm" else 1.0)),
            nbins=int(p.get("nbins", 20)),
            learn_rate=float(p.get("learn_rate", 0.1)),
            learn_rate_annealing=float(p.get("learn_rate_annealing", 1.0)),
            sample_rate=float(p.get("sample_rate", 1.0 if self._mode == "gbm" else 0.632)),
            col_sample_rate=float(p.get("col_sample_rate", 1.0)),
            col_sample_rate_per_tree=float(p.get("col_sample_rate_per_tree", 1.0)),
            min_split_improvement=float(p.get("min_split_improvement", 1e-5)),
            histogram_type=p.get("histogram_type", "AUTO"),
            hist_method=p.get("hist_method", "auto"),
            mtries=int(p.get("mtries", -1)) if "mtries" in p else 0,
            reg_lambda=float(p.get("reg_lambda"))
            if p.get("reg_lambda") is not None
            else (0.0 if self._mode == "drf" else 1.0),
            reg_alpha=float(p.get("reg_alpha") or 0.0) if "reg_alpha" in p else 0.0,
            max_abs_leaf=float(p.get("max_abs_leafnode_pred") or np.inf)
            if "max_abs_leafnode_pred" in p else np.inf,
            # gradient-based sampling (ISSUE 14, GOSS-shaped): opt-in, only
            # meaningful on the out-of-core streamed path — later trees
            # stream the top-|g| rows plus an amplified random rest
            goss=bool(p.get("goss", False)),
            # `is None` (not `or`): an explicit 0.0 must reach the
            # validator's 0 < rate < 1 check, not be swapped for the default
            goss_top_rate=float(
                0.2 if p.get("goss_top_rate") is None
                else p["goss_top_rate"]),
            goss_other_rate=float(
                0.1 if p.get("goss_other_rate") is None
                else p["goss_other_rate"]),
            goss_start_tree=p.get("goss_start_tree"),
        )

    def _resolved_mtries(self, tp, F, problem) -> int:
        """DRF's per-split column-sample count (hex/tree/drf/DRF.java
        _mtry defaults); 0 for non-DRF modes."""
        if self._mode != "drf":
            return 0
        mtries = tp["mtries"]
        if mtries in (-1, 0):
            return (max(1, int(np.sqrt(F))) if problem != "regression"
                    else max(1, F // 3))
        if mtries == -2:
            return F
        if mtries > F:
            raise ValueError(
                f"mtries={mtries} exceeds the {F} usable feature columns")
        return mtries

    def _make_step_cfg(self, tp, npad, K, F, nbins, problem, dist,
                       pack_bits: int = 0, shard_mode: str = "off",
                       n_shards: int = 0) -> _StepCfg:
        """The structural step config, derivable before any device upload —
        built identically by the early warm-up thread and the training path
        so both hit the same cached program. `pack_bits` is the resident
        code packing the caller resolved (0 = full-width);
        `shard_mode`/`n_shards` come from `_shard_plan` — the host-callback
        histogram default is gated to the collective-free modes (a
        pure_callback cannot run under a collective program; the mesh lane
        keeps the in-graph scatter, which is pinned bit-exact with it)."""
        host_ok = shard_mode in ("off", "blocks")
        mtries = self._resolved_mtries(tp, F, problem)
        colp = tp["col_sample_rate"] * tp["col_sample_rate_per_tree"]
        legacy = tree_legacy()
        # the ONE auto→concrete hist-method resolution for the fused path:
        # CPU's XLA scatter loops updates at ~100 ns each, the host
        # np.add.at callback runs the same sequential f32 fold ~9× faster
        # and consumes the packed codes without widening. Resolved HERE (a
        # structural cfg field → program-cache key), like the env override
        # below, so an in-process flag flip retraces instead of being
        # silently frozen into a cached program.
        #
        # Row floor: a pure_callback custom-call embeds a process-local
        # pointer, so host-path programs are EXCLUDED from the persistent
        # compilation cache — every fresh process pays the full XLA
        # compile (~5 s/config). Real workloads amortize that against the
        # 9× per-level win; tiny fits (tests, toy frames) never do, so
        # they keep the cacheable segment program.
        hist_method = os.environ.get(
            "H2O3_HIST_METHOD", tp.get("hist_method", "auto"))
        if (hist_method == "auto" and not legacy and host_ok
                and jax.default_backend() == "cpu"
                and _host_callback_safe()
                and npad >= int(os.environ.get(
                    "H2O3_HOST_HIST_MIN_ROWS", 32768))):
            # host_callback_safe: on a 1-core host the in-graph callback
            # deadlocks (the intra-op pool's only thread blocks inside the
            # custom call while the operand producers queue behind it), so
            # single-core hosts keep the bit-identical segment scatter
            hist_method = "host"
        return _StepCfg(
            npad=npad, K=K, F=F, nbins=nbins, problem=problem, dist=dist,
            mode=self._mode, max_depth=tp["max_depth"],
            has_mtries=mtries > 0,
            no_row_sampling=(tp["sample_rate"] >= 1.0
                             and not self._parms.get("sample_rate_per_class")),
            has_col_sampling=colp < 1.0,
            has_monotone=getattr(self, "_monotone_vec", None) is not None,
            tweedie_power=(float(self._parms.get("tweedie_power", 1.5))
                           if "tweedie_power" in self._parms else 1.5),
            quantile_alpha=(float(self._parms.get("quantile_alpha", 0.5))
                            if "quantile_alpha" in self._parms else 0.5),
            hist_method=hist_method,
            grow_policy=tp.get("grow_policy", "depthwise"),
            max_leaves=int(tp.get("max_leaves", 0)),
            pack_bits=int(pack_bits),
            fused_split=not legacy,
            shard_mode=shard_mode,
            n_shards=int(n_shards),
            # deep trees switch wide levels to active-node compaction
            # (measured: DRF depth-17 levels carry ~700 live nodes of 131k
            # heap cells). Off for monotone (needs per-node bounds) and
            # custom objectives (single-tree path keeps the simple shape);
            # the driver rebuilds a chunk densely if the cap overflows.
            compact_cap=(
                # sanitize: the slot pairing needs an even cap ≥ 2
                max(2, int(os.environ.get("H2O3_COMPACT_CAP", 4096)) // 2 * 2)
                if tp["max_depth"] > 12
                and getattr(self, "_monotone_vec", None) is None
                and getattr(self, "_objective_fn", None) is None
                else 0),
        )

    @staticmethod
    def _validate_tree_params(tp) -> None:
        """Value-range validation (hex.ModelBuilder.init / SharedTree
        checkParams): reject nonsense LOUDLY instead of training a
        degenerate model — ntrees=0 'trains' to AUC 0.5, sample_rate=2
        silently clamps, learn_rate<=0 never moves the margin."""
        def bad(msg):
            raise ValueError(msg)

        if tp["ntrees"] < 1:
            bad(f"ntrees must be >= 1, got {tp['ntrees']}")
        if tp["max_depth"] < 1:
            bad(f"max_depth must be >= 1, got {tp['max_depth']} "
                "(0 = unlimited is not supported: the heap tree layout "
                "needs a finite depth cap)")
        for k in ("learn_rate", "learn_rate_annealing", "sample_rate",
                  "col_sample_rate", "col_sample_rate_per_tree"):
            v = tp.get(k)
            if v is not None and not (0.0 < v <= 1.0):
                bad(f"{k} must be in (0, 1], got {v}")
        if tp["nbins"] < 2:
            bad(f"nbins must be >= 2, got {tp['nbins']}")
        if tp["min_rows"] <= 0:
            bad(f"min_rows must be > 0, got {tp['min_rows']}")
        if tp.get("min_split_improvement", 0) < 0:
            bad("min_split_improvement must be >= 0, got "
                f"{tp['min_split_improvement']}")
        mt = tp.get("mtries", 0)
        if mt not in (-2, -1, 0) and mt < 1:
            bad(f"mtries must be -2, -1, or >= 1, got {mt}")
        if tp.get("goss"):
            a, b = tp["goss_top_rate"], tp["goss_other_rate"]
            if not (0.0 < a < 1.0 and 0.0 < b < 1.0 and a + b <= 1.0):
                bad("goss rates must satisfy 0 < goss_top_rate < 1, "
                    f"0 < goss_other_rate < 1, sum <= 1 (got {a}, {b})")
            st = tp.get("goss_start_tree")
            if st is not None and int(st) < 1:
                bad(f"goss_start_tree must be >= 1, got {st} (the first "
                    "trees must train unsampled to seed the gradients)")

    def _ooc_plan(self, tp, npad, F, nbins, resident_bits, shard_mode,
                  n_shards, K):
        """(n_blocks, goss_cfg) — the ONE out-of-core decision per fit
        (ISSUE 14). ``H2O3_TREE_OOC`` gates it: ``0`` never streams (the
        escape hatch — bit-identical to a plain in-core fit), ``1``
        always streams, ``auto`` (default) streams when the packed code
        matrix exceeds the stream budget (``H2O3_STREAM_BUDGET_MB``,
        default half the ledger's device capacity). The block count S is
        a multiple of ``H2O3_TREE_SHARD_BLOCKS`` — the PR 9 deterministic
        reduction grid — sized so a block is ~budget/4 (double buffer +
        headroom); ``H2O3_STREAM_BLOCKS`` forces it (tests pin the
        streamed-vs-in-core bit identity by sharing S).

        The disk tier rides the same decision: packed bytes past the HOST
        budget (``H2O3_STREAM_HOST_BUDGET_MB``; ``H2O3_TREE_OOC_DISK=0``
        disables) also stream — the store then spills overflow blocks to
        persist-backed files and restores them bit-identically, so "fits
        on disk" replaces "fits in host RAM" with the same contract.

        Mesh-sharded fits are ELIGIBLE since round 19 (the PR 11 gap):
        an oversubscribed mesh fit converts to the blocks lane and
        streams — bit-identity with the mesh fit holds transitively
        because both fold the same block grid in the same order (the
        ordered_axis_fold contract; S stays a multiple of the mesh grid
        via the ``base = max(base, n_shards)`` rule below).

        Ineligible fits (legacy comparator, multiproc mesh_psum,
        checkpoint, DART, custom objectives, lossguide, monotone,
        nbins > 256) train in-core exactly as before; a goss request on
        an ineligible fit warns and trains unsampled."""
        env = (os.environ.get("H2O3_TREE_OOC", "auto").strip() or "auto")
        goss_cfg = None
        if tp.get("goss"):
            if self._mode != "gbm" or K != 1:
                raise ValueError(
                    "goss requires a GBM fit with a single margin "
                    "(binomial or regression response)")
            if tp["sample_rate"] < 1.0 \
                    or self._parms.get("sample_rate_per_class"):
                raise ValueError(
                    "goss replaces row sampling; keep sample_rate=1.0")
            start = tp.get("goss_start_tree")
            if start is None:
                start = max(1, int(tp["ntrees"]) // 10)
            goss_cfg = dict(top_rate=float(tp["goss_top_rate"]),
                            other_rate=float(tp["goss_other_rate"]),
                            start_tree=int(start))
        eligible = (env != "0" and not tree_legacy()
                    and shard_mode in ("off", "blocks", "mesh")
                    and self._parms.get("checkpoint") is None
                    and not tp.get("dart")
                    and getattr(self, "_objective_fn", None) is None
                    and tp.get("grow_policy", "depthwise") != "lossguide"
                    and getattr(self, "_monotone_vec", None) is None
                    and nbins <= 256)
        if not eligible:
            if goss_cfg is not None:
                from ..runtime.log import Log

                Log.warn("goss: this fit is not eligible for the "
                         "out-of-core streamed path (see docs/perf.md); "
                         "training unsampled in-core")
            return 0, None
        codes_bytes = (npad * F * resident_bits // 8 if resident_bits
                       else npad * F)
        from . import block_store as _bs

        budget = _bs.stream_budget_bytes()
        host_budget = _bs.stream_host_budget_bytes()
        over_host = host_budget > 0 and codes_bytes > host_budget
        if env != "1" and goss_cfg is None and codes_bytes <= budget \
                and not over_host:
            return 0, None
        base = max(int(os.environ.get("H2O3_TREE_SHARD_BLOCKS", "8") or 8),
                   1)
        if n_shards:
            # a forced-blocks fit keeps its grid a multiple of its S, so
            # the streamed reduction stays bit-compatible with it
            base = max(base, n_shards)
        forced = int(os.environ.get("H2O3_STREAM_BLOCKS", "0") or 0)
        if forced > 0:
            S = forced
        else:
            target = max(budget // 4, 1)
            needed = max(-(-codes_bytes // target), base)
            S = -(-needed // base) * base
        return max(min(S, max(npad // 8, 1)), 1), goss_cfg

    # -- CV fold reuse (model_base._run_cv fast path) -----------------------
    def _cv_can_reuse(self) -> bool:
        """Tree fits can slice the parent's binned codes per fold unless a
        feature needs the fold's raw x columns or frame-path scoring:
        checkpoint continuation (re-bins with the prior model's edges),
        monotone constraints (validated against the training frame's
        column types), and offset_column (per-fold validation metrics
        apply the holdout's offset through the frame scoring path)."""
        return (self._parms.get("checkpoint") is None
                and not self._parms.get("monotone_constraints")
                and not self._parms.get("offset_column"))

    def _cv_reuse_source(self, model, train: Frame):
        bm = getattr(model, "bm", None)
        if isinstance(bm, BinnedMatrix) and bm.codes is not None \
                and bm.codes.shape[0] == train.nrow:
            return bm
        return None

    def _cv_predict_codes(self, model: SharedTreeModel,
                          codes: np.ndarray) -> np.ndarray:
        """`_cv_predict` on pre-binned holdout codes (fold-reuse path)."""
        out = model._probs_from_codes(codes)
        if model.problem == "binomial":
            return out[:, 1]
        if model.problem == "multinomial":
            return out
        return out[:, 0]

    def _fit(self, x, y, train: Frame, valid: Optional[Frame]) -> SharedTreeModel:
        _ph = _Phase()
        tp = self._tree_params()
        self._validate_tree_params(tp)
        seed = self._parms["_actual_seed"]
        yvec = train.vec(y)
        problem, nclass, domain = response_info(yvec)
        dist = dist_mod.infer_distribution(
            problem, self._parms.get("distribution", "AUTO")
        )
        if self._mode == "drf":
            # DRF trees fit raw response means (no boosting margin)
            dist = "gaussian" if problem == "regression" else dist

        # CV fold reuse (models/model_base._run_cv): the parent fit already
        # built the full frame's BinnedMatrix — folds slice its rows instead
        # of re-running frame_to_matrix + build_bins per fold (the
        # LightGBM/XGBoost-style CV over one quantized matrix).
        # H2O3_CV_REBIN=1 disables this upstream, restoring the seed path.
        cvr = self._parms.get("_cv_reuse")
        from . import dataset_cache as _dsc

        multiproc = distdata.multiprocess()
        cloud = cloudlib.cloud()
        ndev = cloud.size
        # ISSUE 12 / ISSUE 18: the ONE sharding decision for this fit,
        # taken up-front because the pod lane (deterministic multi-process
        # SPMD) changes the data layout and cache eligibility below. On a
        # pod the rows live in the CANONICAL global layout and every
        # reduction folds the global block order, so the fit is
        # bit-identical to the 1-device forced-shard fit sharing S.
        shard_mode, n_shards = _shard_plan(ndev, multiproc, tp)
        pod = multiproc and shard_mode == "mesh"
        # pod fits reuse the dataset cache: their builders are
        # collective-free (the canonical row exchange runs EAGERLY every
        # fit, before any builder, so a cache hit/miss divergence across
        # ranks can never strand one rank inside a collective)
        use_cache = (cvr is None and (pod or not multiproc)
                     and _dsc.enabled())
        if cvr is not None:
            pbm, cv_rows = cvr["bm"], np.asarray(cvr["rows"])
            X = None
            is_cat = np.asarray(pbm.is_categorical, bool)
            doms = list(pbm.domains)
            n, F = int(len(cv_rows)), int(pbm.codes.shape[1])
            nbins = int(pbm.nbins)
        else:
            if use_cache:
                X, is_cat, doms = _dsc.matrix(
                    train, x, builder=lambda: frame_to_matrix(train, x))
            else:
                X, is_cat, doms = frame_to_matrix(train, x)
            n, F = X.shape
            # clamp nbins to max categorical cardinality like nbins_cats
            max_card = int(max([len(d) for d, c in zip(doms, is_cat) if c and d], default=0))
            nbins = max(tp["nbins"] + 1, min(max_card + 1, 1 << 10))
        # memory-feasibility depth clamp: the static level-complete heap
        # materializes ~2^D·F·nbins per-node histograms at the deepest level
        # (~96 B/bin-slot empirical, incl. XLA tile padding and co-resident
        # sibling buffers). The reference's dynamic trees shrink with the
        # data; the static heap must cap depth or the compile OOMs HBM
        # (e.g. DRF's default max_depth=20 at nbins=20 needs ~22 GB).
        # Skipped under checkpoint= (the prior model's heap depth governs —
        # new trees must concatenate onto the same heap shape).
        requested_depth = tp["max_depth"]
        if self._parms.get("checkpoint") is None:
            try:
                stats = jax.devices()[0].memory_stats() or {}
                hbm_budget = int(stats.get("bytes_limit", 0)) // 2 or (8 << 30)
            except Exception:
                hbm_budget = 8 << 30
            feas = tp["max_depth"]
            while feas > 4 and (1 << feas) * F * nbins * 96 > hbm_budget:
                feas -= 1
            if tp["max_depth"] > feas:
                from ..runtime.log import Log

                Log.warn(
                    f"max_depth={tp['max_depth']} clamped to {feas}: the "
                    f"level-complete heap's deepest histograms (F={F}, "
                    f"nbins={nbins}) would exceed the HBM budget "
                    f"({hbm_budget >> 30} GiB)")
                tp["max_depth"] = feas
        _ph.mark("frame_to_matrix")
        col_ranges = None
        if multiproc:
            # multi-host cloud: this process holds its ingest shard; global
            # facts come from collectives. The full tree feature envelope is
            # cloud-size-agnostic (custom objectives included — they run on
            # globally-gathered rows, see the contract at the custom_obj
            # branch below and docs/distributed.md).
            with np.errstate(all="ignore"):
                lmin = np.nanmin(np.where(np.isnan(X), np.inf, X), axis=0)
                lmax = np.nanmax(np.where(np.isnan(X), -np.inf, X), axis=0)
            gmin, gmax = distdata.global_minmax(lmin, lmax)
            col_ranges = np.stack([gmin, gmax], axis=1)
        col_qedges = None
        if multiproc and (tp["histogram_type"] == "QuantilesGlobal"):
            # distributed QuantilesGlobal: per-column GLOBAL quantile edges
            # via iterative histogram refinement (hex/quantile/Quantile.java
            # as a host collective) — every process derives identical edges
            nvalue = nbins - 1
            qs = np.linspace(0, 1, nvalue + 1)[1:-1]
            col_qedges = []
            for j in range(X.shape[1]):
                if is_cat[j]:
                    col_qedges.append(None)
                    continue
                colv = X[:, j]
                colv = colv[np.isfinite(colv)]
                col_qedges.append(
                    np.unique(distdata.global_quantiles(colv, qs)))
        if cvr is not None:
            # row-slice the parent's codes; edges/domains are shared objects
            # (the fold model scores through the SAME quantization grid)
            bm = BinnedMatrix(
                codes=pbm.codes[cv_rows], edges=pbm.edges, nbins=pbm.nbins,
                names=list(pbm.names), is_categorical=pbm.is_categorical,
                domains=list(pbm.domains))
        elif use_cache:
            bm = _dsc.bins(
                train, x, nbins, tp["histogram_type"], seed,
                builder=lambda: build_bins(
                    X, nbins=nbins, histogram_type=tp["histogram_type"],
                    names=list(x), is_categorical=is_cat, domains=doms,
                    seed=seed, col_ranges=col_ranges,
                    col_quantile_edges=col_qedges))
        else:
            bm = build_bins(
                X, nbins=nbins, histogram_type=tp["histogram_type"], names=list(x),
                is_categorical=is_cat, domains=doms, seed=seed,
                col_ranges=col_ranges, col_quantile_edges=col_qedges,
            )

        w = (
            train.vec(self._parms["weights_column"]).numeric_np()
            if self._parms.get("weights_column")
            else np.ones(n)
        ).astype(np.float32)
        mc = self._parms.get("monotone_constraints")
        if mc:
            # {col: ±1} → (F,) vector aligned with x (GBM monotone_constraints)
            vec = np.zeros(len(x), np.float32)
            for cname, d in dict(mc).items():
                if cname not in x:
                    raise ValueError(f"monotone_constraints: unknown column {cname!r}")
                if train.vec(cname).type == "enum":
                    raise ValueError(
                        f"monotone_constraints: {cname!r} is categorical — "
                        "constraints apply to numeric columns only")
                vec[list(x).index(cname)] = float(d)
            self._monotone_vec = jnp.asarray(vec)
        else:
            self._monotone_vec = None

        balance_dists = None  # (prior_dist, model_dist) for score correction
        if (self._parms.get("balance_classes")
                and problem in ("binomial", "multinomial")):
            # class balancing as per-class row weights — expectation-equal to
            # the reference's minority oversampling (ModelBuilder
            # balance_classes / class_sampling_factors); scoring applies the
            # priorClassDist/modelClassDist probability correction below
            codes_y = np.asarray(yvec.data)
            counts = np.bincount(codes_y, minlength=nclass).astype(np.float64)
            n_bal = n
            if multiproc:
                # global class distribution (the MRTask class-count reduce)
                counts = distdata.global_sum(counts)
                n_bal = float(counts.sum())
            csf = self._parms.get("class_sampling_factors")
            if csf is not None:
                factors = np.asarray(csf, np.float64)
            else:
                factors = n_bal / (len(counts) * np.maximum(counts, 1.0))
            cap = float(self._parms.get("max_after_balance_size", 5.0))
            factors = np.minimum(factors, cap * n_bal / np.maximum(counts, 1.0))
            w = (w * factors[codes_y]).astype(np.float32)
            prior_dist = counts / counts.sum()
            model_w = counts * factors
            balance_dists = (prior_dist, model_w / model_w.sum())

        offset = (
            train.vec(self._parms["offset_column"]).numeric_np().astype(np.float32)
            if self._parms.get("offset_column")
            else None
        )
        if offset is not None and self._mode == "drf":
            # reference parity: DRF.init rejects offsets ("Offsets are not yet
            # supported for DRF") — and scoring here never applies them
            raise ValueError("offset_column is not supported for DRF")

        if problem == "regression":
            yk = yvec.numeric_np().astype(np.float32)[:, None]
            K = 1
        elif problem == "binomial":
            yk = np.asarray(yvec.data, np.float32)[:, None]
            K = 1
        else:
            K = nclass
            codes = np.asarray(yvec.data)
            yk = np.zeros((n, K), np.float32)
            yk[np.arange(n), codes] = 1.0

        # initial margins (global moments on a multi-host cloud)
        if multiproc and not pod:
            sw = float(distdata.global_sum(np.asarray([w.sum()]))[0])
            swy = distdata.global_sum((yk * w[:, None]).sum(axis=0))
        elif pod and self._mode != "drf" \
                and getattr(self, "_objective_fn", None) is None:
            # pod determinism: f0 must match the 1-device comparator's
            # host computation BITWISE, and a sum of per-rank partials
            # does not (numpy's pairwise reduction groups differently).
            # The response/weight columns are small — gather them exactly
            # (byte transport, rank order = global ingest order) and run
            # the single-process formulas on the global vectors.
            yk_g = distdata.allgather_rows(yk)
            w_g = distdata.allgather_rows(w)
        if self._mode == "drf":
            f0 = np.zeros(K, np.float32)
        elif problem == "multinomial":
            pri = (np.average(yk_g, axis=0, weights=w_g) if pod
                   else swy / max(sw, 1e-12) if multiproc
                   else np.average(yk, axis=0, weights=w))
            f0 = np.log(np.clip(pri, 1e-10, 1.0)).astype(np.float32)
        elif getattr(self, "_objective_fn", None) is not None:
            f0 = np.zeros(1, np.float32)  # custom objectives start at 0 margin
        elif multiproc and not pod and dist in ("quantile", "laplace"):
            # order-statistic inits need GLOBAL quantiles of the response
            alpha = (float(self._parms.get("quantile_alpha", 0.5))
                     if dist == "quantile" else 0.5)
            f0 = np.asarray([np.float32(
                distdata.global_quantiles(yk[:, 0], [alpha])[0])])
        else:
            f0 = np.float32(dist_mod.init_margin(
                dist, yk_g[:, 0] if pod else yk[:, 0],
                w_g if pod else w,
                mu=(float(swy[0]) / max(sw, 1e-12))
                if (multiproc and not pod) else None,
                alpha=float(self._parms.get("quantile_alpha", 0.5))))
            f0 = np.asarray([f0])

        # `ndev_eff` is the device count the data will actually span — 1
        # under the H2O3_TREE_SHARD=0 escape hatch even on a mesh
        # (everything lands on the default device, exactly the 1-device
        # code path).
        ndev_eff = ndev if shard_mode in ("mesh", "mesh_psum") else 1
        # every mesh shard AND every deterministic reduction block must be
        # an equal, 8-row-aligned slice (pack groups divide 8)
        row_mult = max(ndev_eff * 8, n_shards * 8, 8)
        if multiproc and not pod:
            quota = distdata.local_quota(n)
            npad = quota * jax.process_count()
            pad = quota - n          # LOCAL padding (zero-weight rows)
        else:
            n_layout = n
            if pod:
                # pod canonical layout (ISSUE 18): the padded GLOBAL shape
                # comes from the SAME formula the 1-device comparator runs
                # on the same global row count — identical npad and block
                # grid are two legs of the bit-identity argument (the
                # third is the canonical row order, parallel/distdata.py)
                _counts = distdata.row_counts(n)
                n_layout = int(_counts.sum())
            npad = cloudlib.pad_to_multiple(n_layout, row_mult)
            # row-count bucketing (the ntrees-bucketing trick, applied to
            # rows): CV folds and near-same-size frames land on a shared
            # padded shape, so they reuse ONE compiled tree program instead
            # of paying a compile-cache load each (~4-10 s through a remote
            # chip tunnel). ≤12.5% extra zero-weight rows — exact no-ops.
            # bucket values are (2^k/8)·{8..16} — divisible by any power-of-
            # two shard count but not e.g. a 6-device mesh or the blocked
            # reduction's S·8 grid, so round back up to the row multiple to
            # keep shard_map's equal-shard (and equal-block) invariant
            npad = cloudlib.pad_to_multiple(_bucket_rows(npad), row_mult)
            # CV fold fits inherit the parent fit's padded row count
            # (_npad_floor): the fold then reuses the parent's ALREADY-LOADED
            # executable instead of paying a second compile-cache load for
            # the smaller bucket (~4-10 s through a remote-chip tunnel);
            # the extra rows are zero-weight no-ops (deep trees included:
            # active-node compaction made deep fold compute cheap, so one
            # shared program beats a second multi-second program load)
            floor = int(self._parms.get("_npad_floor") or 0)
            if floor > npad and floor % row_mult == 0:
                npad = floor
            pad = npad - n_layout
            if pod:
                # equal per-rank slice of the canonical layout. row_mult is
                # a multiple of ndev·8 on the pod lane and the process
                # count divides the device count, so the slice is 8-aligned
                # (pack groups and local device shards both divide it).
                quota = npad // jax.process_count()
                pad = quota - int(distdata.canonical_counts(
                    _counts, npad)[jax.process_index()])

        def padr(a, fill=0):
            if pod:
                # canonical relayout: rows move to the global-order slice
                # this rank owns (a COLLECTIVE — call sites run it eagerly,
                # never inside a dataset-cache builder)
                return distdata.to_canonical(a, npad, counts=_counts,
                                             fill=fill)
            if a.ndim == 1:
                return np.concatenate([a, np.full(pad, fill, a.dtype)])
            return np.concatenate([a, np.full((pad,) + a.shape[1:], fill, a.dtype)])

        def unpadr(a):
            """Inverse of padr for metric read-back: this rank's REAL rows
            in INGEST order (pod slices hold canonical-order rows that must
            pair with the local frame's response)."""
            if pod:
                return distdata.from_canonical(np.asarray(a), npad, _counts)
            return np.asarray(a)[:n]

        _ph.mark("build_bins")

        # ---- resident sub-byte code packing (ISSUE 7 tentpole) -----------
        # The device-resident code matrix stays PACKED for the whole fit:
        # the histogram kernels consume the packed words (the CPU host
        # callback unpacks per 64k-row chunk; in-graph kernels widen once
        # per program) and the partition step reads per-row codes straight
        # from them — so the matrix the dataset cache holds in HBM (and
        # ships through the ~6 MB/s tunnel) shrinks 2-4×. Paths that score
        # `predict_codes` against the resident matrix (DART dropout,
        # checkpoint fast-forward) and the lossguide builder keep full
        # width; H2O3_TREE_LEGACY=1 restores the seed unpack-once path.
        # Pod fits keep the packed-resident win: quota is 8-aligned, so
        # packing this rank's canonical slice equals slicing the packed
        # global matrix — same bytes the 1-device comparator holds.
        resident_bits = 0
        if (not tree_legacy() and (pod or not multiproc)
                and self._parms.get("checkpoint") is None
                and not tp.get("dart")
                and tp.get("grow_policy", "depthwise") != "lossguide"
                and nbins <= 256):
            resident_bits = _pack_bits_for(nbins, npad)

        # ---- out-of-core streaming (ISSUE 14 tentpole) -------------------
        # When the packed code matrix exceeds the stream budget (or
        # H2O3_TREE_OOC=1 forces it), the fit streams host-resident blocks
        # through a bounded device set instead of uploading the matrix. A
        # streamed fit IS an S-block deterministic reduction: cfg takes
        # the shard_mode="blocks" decisions (histogram dispatch, blocked
        # scoring-event loss, host metrics path), so the in-core
        # comparator (H2O3_TREE_OOC=0 with H2O3_TREE_SHARD=1 sharing S)
        # is bit-identical by construction — pinned in
        # tests/test_tree_stream.py.
        ooc_blocks, goss_cfg = 0, None
        if not multiproc and shard_mode in ("off", "blocks", "mesh"):
            ooc_blocks, goss_cfg = self._ooc_plan(
                tp, npad, F, nbins, resident_bits, shard_mode, n_shards, K)
        elif tp.get("goss"):
            # multi-process fits never stream, but a goss request must
            # fail/warn IDENTICALLY to the 1-device path — not be
            # silently dropped by the shard gate
            self._ooc_plan(tp, npad, F, nbins, resident_bits, shard_mode,
                           n_shards, K)
        if ooc_blocks:
            # mesh-gap closure (round 19): an oversubscribed mesh fit
            # converts to the single-lane blocks reduction and streams —
            # ndev_eff MUST drop to 1 with it (the codes never get a
            # device_put to a row sharding; the store uploads per block).
            # Bit-identity with the mesh fit holds because S stays a
            # multiple of the mesh grid and both fold blocks in order.
            shard_mode, n_shards = "blocks", ooc_blocks
            ndev_eff = 1
            row_mult = max(n_shards * 8, 8)
            npad = cloudlib.pad_to_multiple(
                _bucket_rows(cloudlib.pad_to_multiple(n, row_mult)),
                row_mult)
            floor = int(self._parms.get("_npad_floor") or 0)
            if floor > npad and floor % row_mult == 0:
                npad = floor
            pad = npad - n
            if resident_bits:
                resident_bits = _pack_bits_for(nbins, npad)

        # ---- background program warm-up ----------------------------------
        # The first dispatch of the tree-step program pays trace + XLA
        # compile-cache load (~3 s through a remote-TPU tunnel) in the
        # calling thread, and the big H2D uploads below are synchronous
        # through the same tunnel. Overlap the two: a daemon thread traces
        # and dispatches the step program on device-CREATED dummy zeros (no
        # tunnel traffic) while this thread streams the real data up.
        # Checkpoint continuation mutates max_depth/nbins after this point,
        # so it skips the early warm-up (rare path; costs only the load).
        warm_thread = None
        if self._parms.get("checkpoint") is None \
                and getattr(self, "_objective_fn", None) is None \
                and not multiproc and not ooc_blocks \
                and os.environ.get("H2O3_WARM_THREAD", "1") != "0":
            cfg_early = self._make_step_cfg(tp, npad, K, F, nbins, problem,
                                            dist, pack_bits=resident_bits,
                                            shard_mode=shard_mode,
                                            n_shards=n_shards)
            # sweep-warm reuse: when this config's step program is already
            # built in-process (a CV fold after its parent, or a repeat
            # grid/AutoML candidate), the dummy warm execution is pure
            # waste — a full tree step on zeros competing with the sweep's
            # real work. The legacy comparator keeps the seed behavior.
            from ..runtime import trainpool as _tpool

            if not _tpool.legacy() and cfg_early in \
                    cloud.__dict__.get("_step_fns_cache", {}):
                cfg_early = None
            code_dt = jnp.uint8 if nbins <= 256 else jnp.uint16
            # packed codes: the dummy matrix takes the packed shape so the
            # warm trace IS the real program
            codes_shape = ((npad * resident_bits // 8, F) if resident_bits
                           else (npad, F))
            drf = self._mode == "drf"

            def _warm():
                try:
                    tj, _ = _tree_step_fns(cfg_early, cloud)
                    args = [
                        jnp.zeros((npad, K), jnp.float32),                # margins
                        jnp.zeros((npad, K) if drf else (1, K), jnp.float32),
                        jnp.zeros(npad if drf else 1, jnp.float32),
                        jnp.zeros(codes_shape, code_dt),                  # codes
                        jnp.zeros((npad, K), jnp.float32),                # y
                        jnp.zeros(npad, jnp.float32),                     # w
                        jnp.ones(npad, jnp.float32),                      # rate
                        jnp.zeros((F, nbins - 2), jnp.float32),           # edges
                        jnp.zeros(F, jnp.float32),                        # mono
                        jnp.zeros(9, jnp.float32),                        # hp
                        jax.random.PRNGKey(0),
                        np.int32(0),
                    ]
                    if ndev_eff > 1:
                        # shard exactly the args the real call shards
                        # (mono/hp/key stay uncommitted there — committing
                        # them here would compile a different executable)
                        rs_ = cloud.row_sharding()
                        rep = cloud.replicated()
                        shardings = [rs_, rs_ if drf else None,
                                     rs_ if drf else None, rs_, rs_, rs_,
                                     rs_, rep, None, None, None, None]
                        args = [a if s is None else jax.device_put(a, s)
                                for a, s in zip(args, shardings)]
                    out = tj(*args)
                    # the warm execution must FINISH before the real tree
                    # programs dispatch — a CPU mesh deadlocks on two
                    # concurrent collective executables (collective_fence)
                    cloudlib.collective_fence(out[0])
                    # also pre-load the other per-config program of a cold
                    # run: the device-side AUC2 training-metrics reduction
                    # (VERDICT r03 #2 — warm ALL programs of a config, not
                    # just the first tree program)
                    if (problem == "binomial" and dist == "bernoulli"
                            and self._mode == "gbm" and ndev == 1
                            and shard_mode == "off"):
                        _binom_binned_stats(
                            jnp.zeros((npad, K), jnp.float32),
                            jnp.zeros((npad, K), jnp.float32),
                            jnp.int32(npad))
                except Exception:  # warm-up is advisory; real call reports
                    pass

            if cfg_early is not None:
                warm_thread = threading.Thread(target=_warm, daemon=True)
                warm_thread.start()

        edges = np.full((F, nbins - 2), np.float32(np.inf), np.float32)
        for j, e in enumerate(bm.edges):
            edges[j, : min(len(e), nbins - 2)] = e[: nbins - 2]

        if multiproc:
            # each process supplies its ingest shard of the global arrays,
            # homed on its own devices (the DKV chunk-home placement)
            if pod:
                from ..runtime import phases as _phases_mod

                # the relayout collective runs EAGERLY; the cache builder
                # below only packs + assembles the global array
                # (make_array_from_process_local_data is metadata-only),
                # so a per-rank cache hit/miss divergence is harmless.
                # No rank ever materializes the global matrix: per-host
                # pack + H2D is the 1/N canonical slice.
                codes_canon = padr(bm.codes)

                def _build_codes_pod():
                    if resident_bits:
                        packed = _pack_host(codes_canon, resident_bits)
                        _phases_mod.add("h2d", 0.0, packed.nbytes)
                        return distdata.global_row_array(
                            packed, quota * resident_bits // 8, cloud)
                    _phases_mod.add("h2d", 0.0, codes_canon.nbytes)
                    return distdata.global_row_array(codes_canon, quota,
                                                     cloud)

                if use_cache:
                    codes_d = _dsc.device_codes(
                        train, x, nbins, tp["histogram_type"], seed, npad,
                        builder=_build_codes_pod, pack_bits=resident_bits,
                        n_devices=ndev_eff)
                else:
                    codes_d = _build_codes_pod()
            else:
                codes_d = distdata.global_row_array(padr(bm.codes), quota,
                                                    cloud)
            y_d = distdata.global_row_array(
                padr(yk).astype(np.float32), quota, cloud)
            w_d = distdata.global_row_array(padr(w), quota, cloud)
            edges_d = distdata.replicated_array(edges, cloud)
            rs_m = cloud.row_sharding()
            margins = jax.jit(
                lambda: jnp.broadcast_to(
                    jnp.asarray(f0)[None, :], (npad, K)).astype(jnp.float32),
                out_shardings=rs_m)()
            if offset is not None:
                off_g = distdata.global_row_array(padr(offset), quota, cloud)
                margins = jax.jit(lambda m, o: m + o[:, None],
                                  out_shardings=rs_m)(margins, off_g)
        else:
            from ..runtime import phases as _phases_mod

            def _build_codes_dev():
                codes_p = padr(bm.codes)
                rs_codes = (cloud.row_sharding() if ndev_eff > 1 else None)
                if resident_bits:
                    # fused path: ship packed AND keep it packed in HBM —
                    # the resident matrix is 2-4× smaller and the tree
                    # kernels consume the packed words directly. On a mesh
                    # the artifact is ROW-SHARDED at build time, straight
                    # from HOST memory (packed word groups align with the
                    # 8-row shard grid): each chip receives only its
                    # slice — staging the whole matrix on one device and
                    # resharding would make per-chip HBM peak equal the
                    # GLOBAL matrix, defeating the scale-out win.
                    packed = _pack_host(codes_p, resident_bits)
                    _phases_mod.add("h2d", 0.0, packed.nbytes)
                    if rs_codes is not None:
                        return jax.device_put(packed, rs_codes)
                    return jnp.asarray(packed)
                if rs_codes is not None:
                    # full-width sharded upload (rare: nbins>256 / dart /
                    # checkpoint on a mesh): per-shard host→chip transfers;
                    # the pack-for-transfer trick below targets the single
                    # slow tunnel and would stage everything on one chip
                    _phases_mod.add("h2d", 0.0, codes_p.nbytes)
                    return jax.device_put(codes_p, rs_codes)
                pack_bits = (_pack_bits_for(nbins, codes_p.shape[0])
                             if codes_p.dtype == np.uint8 else 0)
                if pack_bits:
                    # legacy/ungated path: the bin-code matrix is still the
                    # biggest fixed H2D cost (~6 MB/s tunnel) — ship 4/5/6-
                    # bit codes (half to 3/4 of the bytes), widen on device
                    packed = _pack_host(codes_p, pack_bits)
                    _phases_mod.add("h2d", 0.0, packed.nbytes)
                    return _unpack_device(jnp.asarray(packed), pack_bits)
                _phases_mod.add("h2d", 0.0, codes_p.nbytes)
                return jnp.asarray(codes_p)

            ooc_store = None
            if ooc_blocks:
                # out-of-core: the matrix NEVER uploads whole. Packed
                # blocks are built O(block) from the padded codes and live
                # on host; the bounded device resident set fills lazily as
                # the streamed level loop walks them. Cached like
                # device_codes so a sweep packs the blocks once.
                def _build_store():
                    from . import block_store as _bs

                    return _bs.BlockStore.from_codes(
                        padr(bm.codes), n_blocks=ooc_blocks,
                        pack_bits=resident_bits, register=not use_cache)

                if use_cache:
                    ooc_store = _dsc.blocked_codes(
                        train, x, nbins, tp["histogram_type"], seed, npad,
                        builder=_build_store, pack_bits=resident_bits,
                        n_blocks=ooc_blocks)
                else:
                    ooc_store = _build_store()
                codes_d = None
            elif use_cache and (ndev_eff == 1 or shard_mode == "mesh"):
                # sweep-level reuse: every candidate sharing this
                # (frame, x, nbins, histogram) trains off ONE device-resident
                # code matrix — the pack + tunnel upload happens once. The
                # packing mode AND the shard layout key the cache entry: a
                # packed and a full-width consumer (or a 1-device and an
                # 8-shard consumer) never share an artifact.
                codes_d = _dsc.device_codes(
                    train, x, nbins, tp["histogram_type"], seed, npad,
                    builder=_build_codes_dev, pack_bits=resident_bits,
                    n_devices=ndev_eff)
            else:
                codes_d = _build_codes_dev()
            if yk.size and bool(np.all((yk >= 0) & (yk <= 255)
                                       & (yk == np.floor(yk)))):
                # integer-ish response (class indicators, counts): ship uint8
                # through the tunnel (4× smaller) and widen on device
                _phases_mod.add("h2d", 0.0, npad)
                y_d = jnp.asarray(padr(yk.astype(np.uint8))).astype(jnp.float32)
            else:
                _phases_mod.add("h2d", 0.0, 4 * npad)
                y_d = jnp.asarray(padr(yk))
            if np.all(w == 1.0):
                # trivial weights: build on device (zero-weight padded tail)
                # instead of pushing 4·npad bytes of 1.0s through the tunnel
                w_d = jnp.ones(npad, jnp.float32).at[n:].set(0.0) if pad else (
                    jnp.ones(npad, jnp.float32))
            else:
                _phases_mod.add("h2d", 0.0, 4 * npad)
                w_d = jnp.asarray(padr(w))
            _phases_mod.add("h2d", 0.0, edges.nbytes)
            edges_d = jnp.asarray(edges)

            if ndev_eff > 1:
                rs = cloud.row_sharding()
                codes_d = jax.device_put(codes_d, rs)
                y_d = jax.device_put(y_d, rs)
                w_d = jax.device_put(w_d, rs)
                edges_d = jax.device_put(edges_d, cloud.replicated())

            margins = jnp.broadcast_to(jnp.asarray(f0)[None, :], (npad, K)).astype(jnp.float32)
            if offset is not None:
                margins = margins + jnp.asarray(padr(offset))[:, None]
            if ndev_eff > 1:
                margins = jax.device_put(margins, cloud.row_sharding())

        # real-row mask for device-side event metrics (pads excluded); on a
        # multi-process cloud it is global, so event sums come back global
        if multiproc:
            if pod:
                # canonical pad lives at the GLOBAL tail, so no exchange:
                # a slice is real up to its canonical row count
                cc_r = int(distdata.canonical_counts(
                    _counts, npad)[jax.process_index()])
                row_mask_d = distdata.global_row_array(
                    (np.arange(quota) < cc_r).astype(np.float32), quota,
                    cloud)
            else:
                row_mask_d = distdata.global_row_array(
                    np.ones(n, np.float32), quota, cloud)
        else:
            row_mask_d = (jnp.arange(npad) < n).astype(jnp.float32)
            if ndev_eff > 1:
                row_mask_d = jax.device_put(row_mask_d, cloud.row_sharding())

        # checkpoint= continue-training: restore the prior forest and fast-
        # forward margins (SharedTree checkpoint restart — `_parms.checkpoint`
        # compat checks + tree restore in hex/tree/SharedTree.java)
        prior_stacked: List = []
        n_prior = 0
        ckpt = self._parms.get("checkpoint")
        if ckpt is not None:
            pm = ckpt.model if hasattr(ckpt, "model") else ckpt
            if not isinstance(pm, SharedTreeModel):
                raise ValueError("checkpoint must be a prior tree model")
            # compatible iff the user asks for the prior heap depth OR the
            # same ORIGINAL depth the prior fit clamped down from (the HBM
            # clamp must not break continuation with identical parameters)
            depth_ok = tp["max_depth"] in (
                pm.max_depth, getattr(pm, "requested_max_depth", pm.max_depth))
            if not depth_ok or pm.nclass != nclass:
                raise ValueError(
                    "checkpoint incompatible: max_depth/nclass must match "
                    "(SharedTree checkpoint parameter compatibility checks)"
                )
            tp["max_depth"] = pm.max_depth
            # re-bin the CURRENT training data with the prior model's edges so
            # split bins stay aligned with the restored trees
            bm = pm.bm
            nbins = bm.nbins
            edges_np = np.full((F, nbins - 2), np.inf, np.float32)
            for j, e in enumerate(bm.edges):
                edges_np[j, : min(len(e), nbins - 2)] = e[: nbins - 2]
            n_prior = pm.ntrees_built
            f0 = np.asarray(pm.f0).reshape(-1).astype(np.float32)
            prior_stacked = list(pm.forest)
            prior_replicated: List = []   # reused by the valid fast-forward
            if multiproc:
                # every rank restored the SAME artifact (the model object the
                # user passed exists identically on each process); codes are
                # this rank's shard, the forest is replicated, margins fast-
                # forward inside jit programs
                codes_d = distdata.global_row_array(
                    padr(bin_apply(bm, X)), quota, cloud)
                edges_d = distdata.replicated_array(edges_np, cloud)
                rs_m = cloud.row_sharding()
                margins = jax.jit(
                    lambda f: jnp.broadcast_to(
                        f[None, :], (npad, K)).astype(jnp.float32),
                    out_shardings=rs_m)(f0)
                for k in range(K):
                    forest_k = jax.tree.map(
                        lambda a: distdata.replicated_array(
                            np.asarray(a), cloud), pm.forest[k])
                    prior_replicated.append(forest_k)
                    margins = _margin_ffwd_jit(
                        forest_k, codes_d, margins, jnp.int32(k),
                        tp["max_depth"])
                if offset is not None:
                    off_g = distdata.global_row_array(padr(offset), quota,
                                                      cloud)
                    margins = jax.jit(lambda m, o: m + o[:, None],
                                      out_shardings=rs_m)(margins, off_g)
            else:
                codes_d = jnp.asarray(padr(bin_apply(bm, X)))
                edges_d = jnp.asarray(edges_np)
                margins = jnp.broadcast_to(
                    jnp.asarray(f0)[None, :], (npad, K)).astype(jnp.float32)
                for k in range(K):
                    margins = _margin_ffwd_jit(
                        jax.tree.map(jnp.asarray, pm.forest[k]), codes_d,
                        margins, jnp.int32(k), tp["max_depth"])
                if offset is not None:
                    margins = margins + jnp.asarray(padr(offset))[:, None]
                if ndev_eff > 1:
                    codes_d = jax.device_put(codes_d, cloud.row_sharding())
                    edges_d = jax.device_put(edges_d, cloud.replicated())
                    margins = jax.device_put(margins, cloud.row_sharding())

        # validation margins tracked incrementally per tree (the Score pass of
        # SharedTree.Driver on the validation frame) — early stopping uses the
        # validation metric when a validation_frame is given (ScoreKeeper).
        # Built AFTER the checkpoint block so codes_v uses the active binning
        # and margins_v is fast-forwarded through the restored forest.
        valid_state = None
        if valid is not None:
            Xv, _, _ = frame_to_matrix(valid, x, expected_domains=bm.domains)
            codes_np_v = bin_apply(bm, Xv)
            yvv = valid.vec(y)
            n_v = valid.nrow          # LOCAL valid rows on a multi-proc cloud
            if problem == "regression":
                ykv = yvv.numeric_np().astype(np.float32)[:, None]
            elif problem == "binomial":
                ykv = np.asarray(yvv.data, np.float32)[:, None]
            else:
                cv = np.asarray(yvv.data)
                ykv = np.zeros((n_v, K), np.float32)
                ykv[np.arange(n_v), cv] = 1.0
            if multiproc:
                # each process scores its ingest shard of the valid frame;
                # metric pieces are globally reduced in _score_event
                quota_v = distdata.local_quota(n_v)
                codes_v = distdata.global_row_array(codes_np_v, quota_v,
                                                    cloud)
                y_dev_v = distdata.global_row_array(ykv, quota_v, cloud)
                vmask_d = distdata.global_row_array(
                    np.ones(n_v, np.float32), quota_v, cloud)
                rs_v = cloud.row_sharding()
                margins_v = jax.jit(
                    lambda f: jnp.broadcast_to(
                        f[None, :],
                        (quota_v * jax.process_count(), K)
                    ).astype(jnp.float32),
                    out_shardings=rs_v)(np.asarray(f0).reshape(-1))
            else:
                codes_v = jnp.asarray(codes_np_v)
                y_dev_v = jnp.asarray(ykv)
                vmask_d = jnp.ones(n_v, jnp.float32)
                margins_v = jnp.broadcast_to(
                    jnp.asarray(np.asarray(f0).reshape(-1))[None, :],
                    (n_v, K)).astype(jnp.float32)
            if n_prior:
                for k in range(K):
                    forest_k = (prior_replicated[k] if multiproc else
                                jax.tree.map(jnp.asarray, prior_stacked[k]))
                    margins_v = _margin_ffwd_jit(
                        forest_k, codes_v, margins_v, jnp.int32(k),
                        tp["max_depth"])
            if self._parms.get("offset_column") and self._parms["offset_column"] in valid.names:
                off_v = valid.vec(self._parms["offset_column"]).numeric_np().astype(np.float32)
                if multiproc:
                    off_g = distdata.global_row_array(off_v, quota_v, cloud)
                    margins_v = jax.jit(lambda m, o: m + o[:, None],
                                        out_shardings=rs_v)(margins_v, off_g)
                else:
                    margins_v = margins_v + jnp.asarray(off_v)[:, None]
            # slot 1 deliberately None: the host ykv copy it used to hold is
            # superseded by the device y_dev_v (slot 4); indices are stable
            valid_state = [codes_v, None, margins_v, n_v, y_dev_v, vmask_d]

        _ph.mark("device_put", sync=codes_d)
        key = jax.random.PRNGKey(seed & 0x7FFFFFFF)
        ntrees_target = max(int(tp["ntrees"]) - n_prior, 0)
        gain_total = np.zeros(F, np.float64)
        stopper = (
            ScoreKeeper(
                int(self._parms.get("stopping_rounds", 0)),
                self._default_stopping_metric(problem),
                float(self._parms.get("stopping_tolerance", 1e-3)),
            )
            if int(self._parms.get("stopping_rounds", 0)) > 0
            else None
        )
        score_interval = int(self._parms.get("score_tree_interval", 0) or 0)
        lr = tp["learn_rate"] if self._mode == "gbm" else 1.0
        max_runtime = float(self._parms.get("max_runtime_secs", 0) or 0)
        t0 = time.time()
        history: List[Dict] = []
        built = 0

        # ---- ONE jitted program per boosting iteration -------------------
        # Per-call overhead matters: a remote/axon TPU pays a full tunnel
        # round-trip per dispatch, so sampling, gradients, the K tree builds,
        # and the margin updates are fused into a single XLA program — the
        # analog of the fused ScoreBuildHistogram2 pass (hex/tree/
        # ScoreBuildHistogram2.java fuses scoring into histogram building).
        colp = tp["col_sample_rate"] * tp["col_sample_rate_per_tree"]
        custom_obj = getattr(self, "_objective_fn", None)
        mono_vec = getattr(self, "_monotone_vec", None)
        cfg = self._make_step_cfg(tp, npad, K, F, nbins, problem, dist,
                                  pack_bits=resident_bits,
                                  shard_mode=shard_mode, n_shards=n_shards)
        if ooc_blocks and cfg.compact_cap:
            # the streamed level loop is dense-only; deep streamed fits
            # keep exactness by skipping active-node compaction (the
            # in-core comparator must match — docs/perf.md)
            cfg = cfg._replace(compact_cap=0)
        # per-fit kernel plan (ISSUE 7 satellite): resolve + record which
        # histogram kernel each level will actually run (method, pallas
        # row_chunk, VMEM-pressure fallbacks — logged once per fit) into
        # the metrics registry and the /3/Profiler `tree` fold, so the
        # auto-dispatch is never guesswork. Shares resolve_method with
        # build_histograms, so the plan cannot diverge from reality.
        if cfg.grow_policy != "lossguide":
            plan_levels = treelib.histogram_level_plan(cfg.max_depth,
                                                      cfg.compact_cap)
        else:
            plan_levels = [("lossguide_node", 1)]
        plan_tag = (f"{getattr(self, 'algo', self._mode)}:{K}x{tp['ntrees']}t"
                    f"_d{cfg.max_depth}")
        _record_fit_plan(
            plan_tag, plan_levels, nbins, cfg.hist_method,
            pack_bits=cfg.pack_bits,
            axis_name=cloudlib.ROWS_AXIS if ndev_eff > 1 else None,
            n_shards=cfg.n_shards, n_devices=ndev_eff)
        # per-lane collective skew of THIS fit (ISSUE 13): fences recorded
        # after this sequence point belong to this fit (training is
        # serialized on meshes via training_guard)
        lane_seq0 = cloudlib.lane_seq()
        # fit trace span: a dashboard reading /3/Trace sees how many chips
        # (and reduction blocks) this fit actually spanned
        try:
            from ..runtime import tracing as _tracing

            _sp = _tracing.current()
            if _sp is not None:
                _sp.annotate(n_devices=ndev_eff, n_shards=cfg.n_shards,
                             pack_bits=cfg.pack_bits,
                             shard_mode=cfg.shard_mode)
        except Exception:
            pass
        # sharded fits score through the blocked deterministic loss (the
        # early-stop decision must be bit-stable across device counts);
        # unsharded fits keep the historical whole-array reduction
        loss_fn = None
        if cfg.shard_mode in ("mesh", "blocks"):
            loss_fn = _sharded_event_loss_fn(
                cloud, cfg.shard_mode, cfg.n_shards, self._mode, problem,
                dist)
        if warm_thread is not None:
            warm_thread.join()
        stream0 = None
        if ooc_blocks:
            # the streamed out-of-core step replaces the monolithic jitted
            # tree program: same call contract, per-block programs inside
            # (models/tree_stream.py). custom objectives / DART / compact
            # never reach here (gated in _ooc_plan), so _single_jit is
            # unused on this path.
            from . import tree_stream as _tstream

            _tree_jit = _tstream.StreamedTreeStep(cfg, ooc_store,
                                                  seed=seed, goss=goss_cfg)
            _single_jit = None
            stream0 = dict(ooc_store.counters)
            ooc_store.peak_window_start()   # THIS fit's resident watermark
        else:
            _tree_jit, _single_jit = _tree_step_fns(cfg, cloud)
        mono_d = (jnp.asarray(mono_vec) if mono_vec is not None
                  else jnp.zeros(F, jnp.float32))
        hp_d = _pack_hp(
            tp, lr, colp,
            mtries_rate=self._resolved_mtries(tp, F, problem) / max(F, 1))
        if multiproc:
            # small per-call args go in as host numpy (identical on every
            # process ⇒ jit replicates them); locally-committed jnp arrays
            # would carry a single-device sharding the global mesh rejects
            mono_d = np.asarray(mono_d)
            hp_d = np.asarray(hp_d)
            key = np.asarray(key)

        def _train_chunk(margins, oob_sum, oob_cnt, key, m0, nsteps: int,
                         tree_fn=None):
            """nsteps async per-tree dispatches (NOT lax.scan: a scan body
            defeats XLA's onehot→reduction fusion and materializes the
            (rows × nodes·bins) one-hot in HBM, ~300× slower; sequential
            cached-jit enqueues pipeline on device with ~µs host overhead)."""
            tree_fn = tree_fn or _tree_jit
            packed_list, gains_list, ov_list = [], [], []
            for i in range(nsteps):
                margins, oob_sum, oob_cnt, packed, gains, ov = tree_fn(
                    margins, oob_sum, oob_cnt, codes_d, y_d, w_d, rate_d,
                    edges_d, mono_d, hp_d, key, np.int32(m0 + i)
                )
                # CPU mesh: one collective executable in flight at a time
                cloudlib.collective_fence(margins)
                packed_list.append(packed)
                gains_list.append(gains)
                ov_list.append(ov)
            # jitted combine only on multi-host meshes (eager stack/sum
            # would reject process-spanning arrays there). Single-process
            # stays EAGER: a jitted multi-arg combine has been observed to
            # interleave with in-flight collective tree programs on the
            # XLA:CPU thunk pool and deadlock the all-reduce rendezvous.
            if distdata.multiprocess() or ndev_eff == 1:
                # single REAL device has no collective programs in flight,
                # so the jitted combine is safe there too — and it turns
                # ~2·nsteps eager dispatches per chunk (each paying the
                # remote-tunnel latency) into three
                return (margins, oob_sum, oob_cnt,
                        _stack_args(*packed_list), _sum_args(*gains_list),
                        _sum_args(*ov_list))
            return (margins, oob_sum, oob_cnt,
                    jnp.stack(packed_list), sum(gains_list), sum(ov_list))

        # chunking: one device dispatch per `chunk` trees (remote dispatch
        # latency amortization); scoring/stopping checks at chunk boundaries
        need_host_each = (
            custom_obj is not None
            or bool(self._parms.get("score_each_iteration"))
        )
        dart = tp.get("dart")
        if need_host_each or dart:
            chunk = 1
        elif score_interval:
            chunk = score_interval
        elif stopper is not None:
            chunk = max(1, min(10, ntrees_target))
        else:
            chunk = min(25, max(ntrees_target, 1))

        m = 0
        # per-row sampling rate: constant sample_rate, or per-class rates
        # (sample_rate_per_class, hex/tree SharedTree class sampling)
        srpc = self._parms.get("sample_rate_per_class")
        if srpc and problem not in ("binomial", "multinomial"):
            raise ValueError("sample_rate_per_class requires a categorical "
                             "response (classification only)")
        if srpc:
            rates_np = np.asarray(list(srpc), np.float32)
            if len(rates_np) != nclass:
                raise ValueError(
                    f"sample_rate_per_class needs {nclass} entries, got {len(rates_np)}")
            rate_rows = rates_np[np.asarray(yvec.data, np.int64)]
            rate_d = (distdata.global_row_array(
                          padr(rate_rows.astype(np.float32)), quota, cloud)
                      if multiproc
                      else jnp.asarray(padr(rate_rows.astype(np.float32))))
        elif multiproc:
            rate_d = distdata.sharded_full(
                (npad,), np.float32(tp["sample_rate"]), jnp.float32, cloud)
        else:
            rate_d = jnp.full(npad, np.float32(tp["sample_rate"]))
        row_sampled = tp["sample_rate"] < 1.0 or bool(srpc)
        if ndev_eff > 1 and not multiproc:
            rate_d = jax.device_put(rate_d, cloud.row_sharding())
        # DRF OOB accumulators (out-of-bag prediction sums / counts per row)
        if self._mode == "drf":
            if multiproc:
                oob_sum = distdata.sharded_full((npad, K), 0.0, jnp.float32,
                                                cloud)
                oob_cnt = distdata.sharded_full((npad,), 0.0, jnp.float32,
                                                cloud)
            else:
                oob_sum = jnp.zeros((npad, K), jnp.float32)
                oob_cnt = jnp.zeros(npad, jnp.float32)
                if ndev_eff > 1:
                    oob_sum = jax.device_put(oob_sum, cloud.row_sharding())
                    oob_cnt = jax.device_put(oob_cnt, cloud.row_sharding())
        elif multiproc:
            # unused placeholders, replicated via implicit np conversion
            oob_sum = np.zeros((1, K), np.float32)
            oob_cnt = np.zeros(1, np.float32)
        else:
            oob_sum = jnp.zeros((1, K), jnp.float32)  # unused placeholder
            oob_cnt = jnp.zeros(1, jnp.float32)
        packed_chunks: List = []   # device-resident (nsteps, K, T, 5) arrays
        gains_chunks: List = []    # device-resident (F,) arrays
        packed_host: List = []     # flushed-to-host chunks (OOM guard)
        dev_bytes = 0
        # deep forests (heap 2^(d+1) nodes × 5 fields × K) can exceed HBM if
        # the whole run stays device-resident — flush to host past this
        # budget. Generous by default (the bench chip has 16 GB): a flush
        # costs minutes of tunnel D2H, an HBM-resident pack costs bytes
        _PACK_BUDGET = int(os.environ.get("H2O3_PACK_BUDGET_MB", 4096)) << 20

        def _flush_packed():
            nonlocal dev_bytes
            for pk in packed_chunks:
                packed_host.append(np.asarray(pk))
            packed_chunks.clear()
            dev_bytes = 0
        # custom objective on a multi-process cloud: the gathered-global
        # response and this rank's row offset are loop-invariant
        _y_glob_d = None
        _row_off = 0
        _row_counts = None
        _nn_loc = n
        if custom_obj is not None and multiproc:
            import jax as _jax

            if pod:
                # canonical slices concatenated in rank order ARE the
                # global ingest order — gather/scatter below need no
                # reordering, only the canonical per-rank counts
                _row_counts = distdata.canonical_counts(_counts, npad)
                _nn_loc = int(_row_counts[_jax.process_index()])
            else:
                _row_counts = distdata.row_counts(n)
            y_loc = distdata.to_local(y_d)[:_nn_loc]
            y_loc = (y_loc[:, 0] if y_loc.ndim == 2 else y_loc)
            _y_glob_d = jnp.asarray(
                distdata.allgather_rows(np.asarray(y_loc, np.float32)))
            _row_off = int(_row_counts[: _jax.process_index()].sum())
        # DART per-round state: one stored-contribution scale per committed
        # round (host floats), a dedicated RNG (deterministic from seed)
        dart_scales: List[float] = []
        dart_rng = np.random.default_rng(
            (int(self._parms["_actual_seed"]) + 7919) & 0x7FFFFFFF)

        def _run_chunk(margins, oob_sum, oob_cnt, m0, nsteps):
            """One chunk of tree dispatches, incl. the compact-cap
            overflow-rebuild guard (exactness is never traded)."""
            if cfg.compact_cap:
                # snapshot the mutable (donated) state: if any tree in the
                # chunk overflows the compact-slot cap, the chunk is
                # rebuilt DENSELY from here
                snap = _copy_args(margins, oob_sum, oob_cnt)
            margins, oob_sum, oob_cnt, packed, gains, ov = _train_chunk(
                margins, oob_sum, oob_cnt, key, m0, nsteps=nsteps)
            if cfg.compact_cap and int(np.asarray(ov)) > 0:
                from ..runtime.log import Log

                Log.warn(
                    f"tree chunk at m={m0}: compact-node cap "
                    f"{cfg.compact_cap} overflowed — rebuilding the "
                    "chunk with dense levels")
                dense_jit, _ = _tree_step_fns(
                    cfg._replace(compact_cap=0), cloud)
                margins, oob_sum, oob_cnt = snap
                margins, oob_sum, oob_cnt, packed, gains, _ = _train_chunk(
                    margins, oob_sum, oob_cnt, key, m0, nsteps=nsteps,
                    tree_fn=dense_jit)
            return margins, oob_sum, oob_cnt, packed, gains

        # ---- mid-fit checkpointing (ISSUE 20 tentpole) -------------------
        # Snapshot the LIVE loop state every H2O3_CKPT_TREES trees so a
        # killed or aborted fit resumes here instead of at tree 0. The
        # saved margins/OOB/gain arrays are the exact f32 values — a
        # forest fast-forward (`_margin_ffwd_jit`) rounds differently than
        # the incremental per-tree adds, and resume must be BIT-IDENTICAL
        # to the undisturbed fit. Per-rank shards are saved in the pod
        # canonical layout, so a fit that lost ranks restores on ONE host
        # by rank-ordered concatenation (the degrade path), with the shard
        # plan S pinned in the run fingerprint. Gated off for paths whose
        # loop state lives elsewhere (DART round scales, custom-objective
        # host state, out-of-core streams, checkpoint= continuations);
        # H2O3_CKPT=0 disables everything (bit-identical escape hatch).
        from ..runtime import faults as _rfaults
        from ..runtime import supervisor as _sup

        ckpt_fp = None
        ckpt_every = _sup.ckpt_every_trees()
        ckpt_dirp = _sup.ckpt_dir()
        ckpt_rank = jax.process_index() if multiproc else 0
        ckpt_nproc = jax.process_count() if multiproc else 1
        if (_sup.ckpt_enabled() and ckpt_dirp and not dart
                and custom_obj is None and not ooc_blocks
                and not prior_stacked):
            _glob_rows = (int(_counts.sum()) if pod
                          else (npad if multiproc else n))
            ckpt_fp = _sup.run_fingerprint(
                mode=self._mode, problem=problem, cols=list(x), y=y,
                rows=int(_glob_rows), npad=int(npad), K=int(K), F=int(F),
                nbins=int(nbins), seed=int(seed),
                n_shards=int(cfg.n_shards), ntrees=int(tp["ntrees"]),
                max_depth=int(tp["max_depth"]),
                learn_rate=float(tp.get("learn_rate") or 0.0),
                sample_rate=float(tp.get("sample_rate") or 1.0),
                col_sample=float(colp),
                min_rows=float(tp.get("min_rows") or 1.0),
                dist=str(dist), has_valid=valid_state is not None)

        def _save_fit_ckpt():
            """Commit one snapshot: forest-so-far + f32 gain partial sum
            (restored as gains_chunks[0], the same left-fold prefix) +
            live margins/OOB local shards + scoring history + early-stop
            cursor. The .part+rename commit and torn-write rejection live
            in runtime/supervisor."""
            _flush_packed()
            all_p = (packed_host[0] if len(packed_host) == 1
                     else np.concatenate(packed_host, axis=0))
            gacc = None
            for g in gains_chunks:
                gh = np.asarray(g, np.float32)
                gacc = gh if gacc is None else gacc + gh
            arrays = dict(
                packed=all_p,
                gains=(gacc if gacc is not None
                       else np.zeros(F, np.float32)),
                margins=(distdata.to_local(margins) if multiproc
                         else np.asarray(margins)))
            if self._mode == "drf":
                arrays["oob_sum"] = (distdata.to_local(oob_sum)
                                     if multiproc else np.asarray(oob_sum))
                arrays["oob_cnt"] = (distdata.to_local(oob_cnt)
                                     if multiproc else np.asarray(oob_cnt))
            if valid_state is not None:
                arrays["margins_v"] = (
                    distdata.to_local(valid_state[2]) if multiproc
                    else np.asarray(valid_state[2]))
            meta = dict(
                history=history,
                stopper_history=(list(stopper.history)
                                 if stopper is not None else None),
                has_valid=valid_state is not None, npad=int(npad),
                n_shards=int(cfg.n_shards))
            _sup.save_fit_checkpoint(
                ckpt_dirp, "tree", ckpt_fp, built, arrays, meta,
                rank=ckpt_rank, nproc=ckpt_nproc)

        if ckpt_fp is not None:
            rec = _sup.latest_fit_checkpoint(ckpt_dirp, "tree", ckpt_fp)
            ok = (rec is not None and 0 < rec["step"] <= ntrees_target
                  and (rec["nproc"] == ckpt_nproc
                       or (ckpt_nproc == 1
                           and not rec["meta"].get("has_valid"))))
            if multiproc:
                # consensus: every rank restores the same snapshot or none
                # (a rank-divergent restore would deadlock the collectives)
                ok = distdata.global_all(bool(ok))
            if ok:
                sh = rec["shards"]
                meta0 = rec["meta"]

                def _rows_back(name):
                    """One checkpointed row-sharded state array back onto
                    the CURRENT topology: same-nproc ranks recommit their
                    own shard; a shrunken (1-host) resume concatenates the
                    rank shards — canonical layout makes that the global
                    padded array."""
                    if multiproc and rec["nproc"] == ckpt_nproc:
                        return distdata.global_row_array(
                            sh[ckpt_rank][name], quota, cloud)
                    a = (sh[0][name] if rec["nproc"] == 1 else
                         np.concatenate([s[name] for s in sh], axis=0))
                    a = jnp.asarray(a)
                    if ndev_eff > 1:
                        a = jax.device_put(a, cloud.row_sharding())
                    return a

                margins = _rows_back("margins")
                if self._mode == "drf":
                    oob_sum = _rows_back("oob_sum")
                    oob_cnt = _rows_back("oob_cnt")
                if valid_state is not None and "margins_v" in sh[0]:
                    if multiproc:
                        valid_state[2] = distdata.global_row_array(
                            sh[ckpt_rank]["margins_v"], quota_v, cloud)
                    else:
                        valid_state[2] = jnp.asarray(sh[0]["margins_v"])
                # forest + gain prefix are replicated — rank 0's copy is
                # everyone's copy
                packed_host.append(np.asarray(sh[0]["packed"], np.float32))
                gains_chunks.append(np.asarray(sh[0]["gains"], np.float32))
                history.extend(meta0.get("history") or [])
                if stopper is not None and meta0.get("stopper_history"):
                    stopper.history = [
                        float(v) for v in meta0["stopper_history"]]
                m = built = int(rec["step"])
                _sup.note_mid_fit_resume("tree", m, restored=m)
        ckpt_last_m = built
        _sup.fit_started("tree", ckpt_fp or "", ntrees_target)

        # overlapped chunk scoring (ISSUE 7 tentpole part 3): double-buffer
        # — chunk m+1's tree programs are ENQUEUED while chunk m's metric
        # transfers and evaluates, so the device stays busy through
        # score_tree_interval instead of idling at every chunk boundary.
        # Gated to paths whose scoring event runs on device (the DRF OOB
        # event pulls host arrays) and OFF under the legacy comparator,
        # DART and custom objectives (inherently host-synced, chunk=1) and
        # compact-cap fits (their overflow-flag pull is a host sync, so a
        # "speculative" chunk would complete synchronously before the stop
        # decision — strictly worse than the sequential path).
        # (Out-of-core fits skip chunk-level speculation: the streamed step
        # is host-driven, so a "speculative" chunk would consume real
        # stream bandwidth synchronously before the stop decision — the
        # double buffer lives INSIDE its level loop instead.)
        # (checkpointing also disables overlap: the speculative chunk
        # donates the very margins buffers the snapshot saver reads)
        overlap = (not tree_legacy() and not multiproc
                   and custom_obj is None and not dart
                   and not cfg.compact_cap and not ooc_blocks
                   and not (self._mode == "drf" and row_sampled)
                   and ckpt_fp is None
                   and os.environ.get("H2O3_TREE_OVERLAP", "1") != "0")
        spec = None        # speculatively dispatched next chunk (+ nsteps)
        spec_snap = None   # pre-dispatch state copies (its buffers donate)

        def _discard_spec():
            """Abandon the speculative chunk on an early stop: restore the
            pre-dispatch state copies (the spec's programs donated the live
            buffers) and drop its outputs — trees past the stopping point
            vanish exactly as if the sequential path never built them."""
            nonlocal spec, margins, oob_sum, oob_cnt
            if spec is not None:
                margins, oob_sum, oob_cnt = spec_snap
                spec = None

        while m < ntrees_target:
            # QoS chunk-boundary yield: while a serving dispatch is in
            # flight the next chunk's programs hold back here. The wait
            # lands inside the next chunk mark's interval (which books to
            # "compute"), so it is compensated out of that bucket.
            _qos.yield_point(
                "tree_chunk",
                compensate=("compute" if (_PROFILE or _phases_acct.ENABLED)
                            else None))
            # in-process candidate-crash injection (kill-and-resume pins)
            # + supervisor heartbeat: liveness at every chunk boundary
            _rfaults.check("supervisor.fit_abort", detail=f"m={m}")
            _sup.pulse("tree", m)
            nsteps = min(chunk, ntrees_target - m)
            drop_idx = ()
            dsum = dsum_v = None
            if dart and m > 0 and dart_rng.random() >= dart["skip_drop"]:
                mask = dart_rng.random(m) < dart["rate_drop"]
                if dart["one_drop"] and not mask.any():
                    mask[int(dart_rng.integers(0, m))] = True
                drop_idx = tuple(int(i) for i in np.nonzero(mask)[0])
            if drop_idx:
                # margins_eff = margins − Σ dropped rounds (this run's
                # rounds only; a checkpointed prior forest stays frozen).
                # Host-side selection of the dropped round packs keeps the
                # device work O(dropped); padded to pow2 (zero scales) to
                # bound program variants.
                nb = 1 << (len(drop_idx) - 1).bit_length()
                sel_chunks = tuple(packed_chunks[i] for i in drop_idx)
                sel_chunks += (packed_chunks[drop_idx[0]],) * (
                    nb - len(drop_idx))
                sc = np.zeros(nb, np.float32)
                sc[: len(drop_idx)] = [dart_scales[i] for i in drop_idx]
                sc_d = jnp.asarray(sc)
                dsum = _dart_drop_sum_jit(sel_chunks, sc_d, codes_d,
                                          tp["max_depth"])
                margins = _dart_sub_jit(margins, dsum)
                cloudlib.collective_fence(margins)
                if valid_state is not None:
                    dsum_v = _dart_drop_sum_jit(sel_chunks, sc_d,
                                                valid_state[0],
                                                tp["max_depth"])
            if custom_obj is not None:
                # Custom-objective contract (cloud-size-agnostic, the
                # reference's MRTask stance for hex/tree/SharedTree.java):
                # the objective sees the GLOBAL rows in global row order —
                # margin vector in, (g, h) vectors out, all length
                # N_global. On multi-process clouds the driver gathers the
                # margins host-side (N·4 bytes per rank per round), every
                # rank runs the objective on identical inputs, and each
                # rank scatters back its own row range. Per-query host
                # structures (lambdarank) therefore see whole queries even
                # when they span ingest-shard boundaries.
                if multiproc:
                    m_loc = distdata.to_local(margins)[:_nn_loc]
                    m_loc = (m_loc[:, 0] if m_loc.ndim == 2
                             else m_loc).astype(np.float32)
                    # fixed-size gather: ONE collective per round (counts
                    # are loop-invariant, gathered once above)
                    m_glob = distdata.allgather_rows_padded(
                        m_loc, quota, _row_counts)
                    g_g, h_g = custom_obj(jnp.asarray(m_glob), _y_glob_d)
                    g_g = np.asarray(g_g)[_row_off: _row_off + _nn_loc]
                    h_g = np.asarray(h_g)[_row_off: _row_off + _nn_loc]
                    if pod:
                        # rows are already this rank's canonical slice —
                        # global_row_array pads to quota, no exchange
                        g_ext = distdata.global_row_array(
                            g_g.astype(np.float32), quota, cloud)
                        h_ext = distdata.global_row_array(
                            h_g.astype(np.float32), quota, cloud)
                    else:
                        g_ext = distdata.global_row_array(
                            padr(g_g.astype(np.float32)), quota, cloud)
                        h_ext = distdata.global_row_array(
                            padr(h_g.astype(np.float32)), quota, cloud)
                else:
                    g_ext, h_ext = custom_obj(margins[:, 0], y_d[:, 0])
                margins, packed, gains = _single_jit(
                    margins, codes_d, y_d, w_d, rate_d, edges_d, mono_d,
                    hp_d, key, jnp.int32(m), g_ext, h_ext
                )
                cloudlib.collective_fence(margins)
                packed = packed[None]
                nsteps = 1
            elif spec is not None:
                # consume the chunk dispatched while the PREVIOUS chunk's
                # metric was in flight (overlapped chunk scoring)
                margins, oob_sum, oob_cnt, packed, gains, nsteps = spec
                spec = None
            else:
                margins, oob_sum, oob_cnt, packed, gains = _run_chunk(
                    margins, oob_sum, oob_cnt, m, nsteps)
            # chunks stay on device until the post-loop bulk D2H (sync
            # transfers through the tunnel cost ~seconds each), unless the
            # accumulated forest would blow the HBM budget
            packed_chunks.append(packed)
            gains_chunks.append(gains)
            dev_bytes += int(np.prod(packed.shape)) * 4
            if dev_bytes > _PACK_BUDGET and not dart:
                # dart never flushes: dropout selection needs every prior
                # round on device (dart forests are shallow/small)
                _flush_packed()
            if valid_state is not None:
                for k in range(K):
                    valid_state[2] = _valid_margin_update(
                        packed, valid_state[0], valid_state[2],
                        jnp.int32(k), tp["max_depth"])
                cloudlib.collective_fence(valid_state[2])
            if dart:
                k_d = len(drop_idx)
                if k_d:
                    lr = tp["learn_rate"]
                    if dart["normalize_type"] == "forest":
                        fd = fn = 1.0 / (1.0 + lr)
                    else:                      # "tree"
                        fd = k_d / (k_d + lr)
                        fn = 1.0 / (k_d + lr)
                    margins = _dart_fix_jit(
                        margins, packed, dsum, codes_d,
                        jnp.float32(fn - 1.0), jnp.float32(fd),
                        tp["max_depth"])
                    cloudlib.collective_fence(margins)
                    if valid_state is not None:
                        valid_state[2] = _dart_fix_jit(
                            valid_state[2], packed, dsum_v, valid_state[0],
                            jnp.float32(fn - 1.0), jnp.float32(fd - 1.0),
                            tp["max_depth"])
                        cloudlib.collective_fence(valid_state[2])
                    for i in drop_idx:
                        dart_scales[i] *= fd
                    dart_scales.append(fn)
                else:
                    dart_scales.append(1.0)
            if _PROFILE or _phases_acct.ENABLED:
                # synced boundary: without it the compute bucket would time
                # async dispatch, not execution, and overstate throughput
                _ph.mark(f"chunk_{m}_{nsteps}trees", sync=margins)
            m += nsteps
            built = m

            do_score = (
                (score_interval and built % score_interval == 0)
                or self._parms.get("score_each_iteration")
                or (stopper is not None and not score_interval)
            )
            # REST job cancellation takes effect at scoring boundaries —
            # single-process only (a per-rank host decision would diverge a
            # multi-process cloud)
            if (self.job is not None and jax.process_count() == 1):
                self.job.check_cancelled()
            if do_score:
                if self._mode == "drf" and row_sampled and n_prior == 0:
                    # score on OOB predictions (DRF scoring history is OOB;
                    # pulls host arrays — stays synchronous, overlap off).
                    # unpadr restores INGEST order on pods; the host event
                    # path pairs the means with the local response, so pass
                    # the host yk (identical values to y_d, same layout).
                    osum = unpadr(distdata.to_local(oob_sum)).astype(np.float64)
                    ocnt = unpadr(distdata.to_local(oob_cnt)).astype(np.float64)
                    have = ocnt > 0
                    mnp = unpadr(distdata.to_local(margins)).astype(np.float64)
                    oob_mean = np.where(have[:, None],
                                        osum / np.maximum(ocnt[:, None], 1.0),
                                        mnp / max(built, 1))
                    ev0 = self._score_event(problem, dist,
                                            oob_mean * max(built, 1),
                                            yk, w_d, n, built + n_prior)
                    fin = lambda ev0=ev0: ev0
                else:
                    # ENQUEUE the device loss program(s) now; block later
                    fin = self._score_event_async(
                        problem, dist, margins, y_d, w_d, n,
                        built + n_prior, row_mask=row_mask_d,
                        loss_fn=loss_fn)
                vfin = None
                if valid_state is not None:
                    vfin = self._score_event_async(
                        problem, dist, valid_state[2],
                        valid_state[4], None, valid_state[3],
                        built + n_prior, row_mask=valid_state[5],
                    )
                if overlap and m < ntrees_target:
                    # double-buffer: enqueue chunk m+1's tree programs
                    # BEFORE blocking on chunk m's metric scalar — the
                    # device crunches the next chunk through the host's
                    # metric wait + stopping decision. If the decision is
                    # "stop", the speculative chunk is discarded and the
                    # pre-dispatch state restored (bit-exact either way).
                    if stopper is not None or max_runtime:
                        spec_snap = _copy_args(margins, oob_sum, oob_cnt)
                    sp_n = min(chunk, ntrees_target - m)
                    spec = _run_chunk(margins, oob_sum, oob_cnt,
                                      m, sp_n) + (sp_n,)
                ev = fin()
                if vfin is not None:
                    vev = vfin()
                    ev.update({f"validation_{k2}": v for k2, v in vev.items()
                               if k2 not in ("number_of_trees", "timestamp")})
                history.append(ev)
                if stopper is not None:
                    # ScoreKeeper watches validation when present (hex.ScoreKeeper)
                    key_name = (
                        f"validation_{stopper.metric}"
                        if valid_state is not None else stopper.metric
                    )
                    val = ev.get(key_name)
                    if val is None:
                        val = ev.get(
                            "validation_training_deviance"
                            if valid_state is not None else "training_deviance",
                            np.nan,
                        )
                    if stopper.record(val):
                        _discard_spec()
                        break
            if max_runtime:
                # clock consensus: every rank must take the same branch or
                # the next chunk's collectives deadlock
                if distdata.global_any(time.time() - t0 > max_runtime):
                    _discard_spec()
                    break
            if self.job:
                self.job.update(built / max(ntrees_target, 1))
            if (ckpt_fp is not None and m < ntrees_target
                    and built - ckpt_last_m >= ckpt_every):
                # cadenced snapshot — never after a stopper break (resume
                # replays the final chunk deterministically instead)
                _save_fit_ckpt()
                ckpt_last_m = built

        _sup.fit_finished("tree")
        if dart:
            # bake the per-round DART scales into the stored leaf values so
            # scoring / MOJO / TreeSHAP see ordinary trees (xgboost keeps a
            # parallel weight_drop vector; baking is equivalent and keeps
            # every downstream surface unchanged)
            for i, s in enumerate(dart_scales[: len(packed_chunks)]):
                if s != 1.0:
                    packed_chunks[i] = _dart_scale_jit(packed_chunks[i],
                                                       jnp.float32(s))

        # ---- forest stays ON DEVICE; host materialization is lazy --------
        # Deep heaps are big (depth-18 ⇒ 12.6 MB/tree) and a remote-chip
        # tunnel moves ~6 MB/s — an eager D2H of a 50-tree DRF forest costs
        # ~80 s, dominating training. The packed array is kept in HBM;
        # `.forest` (mojo/save/tree-API consumers) pulls it to host on
        # first access. Fallbacks to the eager host path: checkpoint
        # continuation (needs host concat with the prior forest), multi-host
        # meshes, and over-budget runs that already flushed chunks.
        packed_dev = None
        if packed_chunks and not packed_host and not prior_stacked \
                and not multiproc and ndev == 1:
            # single-device only: on a multi-device mesh the pack becomes a
            # multi-device array whose later (scoring/eviction) executions
            # can interleave with the next model's COLLECTIVE tree programs —
            # XLA:CPU runs concurrent executions on one thunk pool and the
            # all-reduce rendezvous deadlocks (observed: 7/8 participants).
            # Multi-device hosts also have fast local D2H, so the eager host
            # path costs little there; the pack exists for the single
            # remote-chip tunnel where D2H is ~6 MB/s.
            _ph.mark("train_loop_dispatch")
            packed_dev = (packed_chunks[0] if len(packed_chunks) == 1
                          else _concat_args(*packed_chunks))
            packed_chunks.clear()
            all_packed = None
            _ph.mark("forest_devkeep")
            gain_total += np.asarray(sum(gains_chunks), np.float64)
        elif packed_chunks or packed_host:
            _ph.mark("train_loop_dispatch")
            # remaining device chunks: single device-side concat + ONE D2H
            # (per-chunk sync transfers only happen on over-budget flushes)
            if packed_chunks:
                if multiproc:
                    # eager concat of process-spanning arrays needs jit;
                    # chunks are replicated, so host concat is equivalent
                    packed_host.extend(np.asarray(pk) for pk in packed_chunks)
                else:
                    rest = (packed_chunks[0] if len(packed_chunks) == 1
                            else jnp.concatenate(packed_chunks, axis=0))
                    packed_host.append(np.asarray(rest))
                packed_chunks.clear()
            all_packed = (packed_host[0] if len(packed_host) == 1
                          else np.concatenate(packed_host, axis=0))
            _ph.mark("forest_D2H")
            if multiproc:
                # replicated chunks pull to host per-chunk (eager device sum
                # would need jit for process-spanning arrays), but the fold
                # stays f32 left-to-right like the single-process
                # `sum(gains_chunks)` so pod varimp is bit-identical to the
                # forced-shard comparator
                acc = None
                for g in gains_chunks:
                    gh = np.asarray(g, np.float32)
                    acc = gh if acc is None else acc + gh
                if acc is not None:
                    gain_total += np.asarray(acc, np.float64)
            else:
                gain_total += np.asarray(sum(gains_chunks), np.float64)
            _ph.mark("gains_D2H")
        else:
            all_packed = np.zeros((0, K, treelib.heap_size(tp["max_depth"]), 6),
                                  np.float32)
        forest = None
        covers_by_class = None
        if packed_dev is None:
            # stacked forests sliced straight off the bulk array — no
            # per-tree host Trees, no 6×ntrees tiny H2D transfers
            forest = []
            covers_by_class = []
            prior_covers = getattr(pm, "covers", None) if prior_stacked else None
            for k in range(K):
                new = treelib.Tree(
                    np.ascontiguousarray(all_packed[:, k, :, 0]).astype(np.int32),
                    np.ascontiguousarray(all_packed[:, k, :, 1]).astype(np.int32),
                    np.ascontiguousarray(all_packed[:, k, :, 2]),
                    all_packed[:, k, :, 3] > 0.5,
                    np.ascontiguousarray(all_packed[:, k, :, 4]),
                )
                cov_k = np.ascontiguousarray(all_packed[:, k, :, 5])
                if prior_stacked:
                    prior = prior_stacked[k]
                    new = treelib.Tree(*[
                        np.concatenate([np.asarray(getattr(prior, f)),
                                        getattr(new, f)], axis=0)
                        for f in treelib.Tree._fields
                    ])
                    if prior_covers is not None and k < len(prior_covers):
                        cov_k = np.concatenate(
                            [np.asarray(prior_covers[k], np.float32), cov_k], axis=0)
                forest.append(new)
                covers_by_class.append(cov_k)
            if prior_stacked and prior_covers is None:
                # continued from a pre-TreeSHAP checkpoint: the prior trees
                # have no covers, so a partial covers array would misalign
                # with the forest — disable contributions for this model
                covers_by_class = None
        model = SharedTreeModel(
            self, x, y, bm, problem, nclass, domain, dist,
            np.asarray(f0) if K > 1 else float(f0[0]),
            forest, tp["max_depth"], mode=self._mode,
            packed_dev=packed_dev, nclasses_packed=K,
        )
        model._npad = npad  # CV passes this to folds as _npad_floor
        if packed_dev is None:
            model.covers = covers_by_class
        else:
            _register_dev_pack(model, _PACK_BUDGET)
        model.requested_max_depth = requested_depth  # pre-clamp user value
        model.balance_dists = balance_dists
        model.calibrator = None
        if self._parms.get("calibrate_model"):
            if problem != "binomial":
                raise ValueError("calibrate_model is only supported for "
                                 "binomial models")
            model.calibrator = self._fit_calibrator(model)
        model.scoring_history = ScoringHistory(history)
        if gain_total.sum() > 0:
            order = np.argsort(-gain_total)
            model.varimp_table = [
                (list(x)[i], float(gain_total[i]),
                 float(gain_total[i] / gain_total.max()),
                 float(gain_total[i] / gain_total.sum()))
                for i in order
            ]
        # training metrics straight from the final margins (already on device)
        # instead of a fresh forest re-predict — saves transfers + a compile
        _ph.mark("forest_unpack")
        # sharded fits take the host metrics path: the binned-AUC reduction
        # is a whole-array scatter whose sharded lowering is not bit-stable
        # across device counts, and the margins D2H is local on a CPU mesh
        device_auc = (not multiproc and problem == "binomial"
                      and dist == "bernoulli" and self._mode == "gbm"
                      and cfg.shard_mode not in ("mesh", "blocks"))
        if device_auc:
            # binomial GBM/XGB: the whole training-metric reduction runs on
            # device (AUC2 binned design) — no margin D2H, no host rank sort
            qs_b, npos_b, nneg_b, nll_b, sq_b = _binom_binned_stats(
                margins, y_d, jnp.int32(n))
            model.training_metrics = ModelMetricsBinomial.from_binned(
                np.asarray(qs_b), np.asarray(npos_b), np.asarray(nneg_b),
                float(nll_b), float(sq_b))
            _ph.mark("training_metrics")
        if multiproc:
            # this process's real rows in INGEST order (training metrics
            # are local-shard on a multi-host cloud; the forest itself is
            # identical everywhere; pods undo the canonical relayout first)
            margins_np = unpadr(
                distdata.local_shard(margins)).astype(np.float64)
        elif not device_auc:
            margins_np = np.asarray(margins[:n]).astype(np.float64)
        _ph.mark("margins_D2H")
        if self._mode == "drf" and row_sampled and n_prior > 0:
            # checkpoint continuation: the prior forest's per-tree sample
            # masks are gone, so OOB accounting cannot be reconstructed —
            # metrics fall back to in-bag; make the semantics change loud
            from ..runtime.log import Log

            Log.warn("DRF checkpoint continuation: training metrics are "
                     "in-bag (OOB state is not carried across checkpoints)")
        if self._mode == "drf" and row_sampled and n_prior == 0:
            # DRF training metrics are OUT-OF-BAG (DRF OOB scoring): each
            # row is scored only by trees that did not sample it; in-bag
            # margins back-fill rows every tree happened to include
            if multiproc:
                osum = unpadr(
                    distdata.local_shard(oob_sum)).astype(np.float64)
                ocnt = unpadr(
                    distdata.local_shard(oob_cnt)).astype(np.float64)
            else:
                osum = np.asarray(oob_sum[:n], np.float64)
                ocnt = np.asarray(oob_cnt[:n], np.float64)
            have = ocnt > 0
            oob_mean = np.where(
                have[:, None], osum / np.maximum(ocnt[:, None], 1.0),
                margins_np / max(model.ntrees_built, 1))
            # feed as "margins × ntrees" so probs_from_margins' ÷ntrees
            # reproduces the OOB mean
            probs_tr = self._probs_from_margins(
                problem, dist, oob_mean * max(model.ntrees_built, 1),
                model.ntrees_built)
        elif not device_auc:
            probs_tr = self._probs_from_margins(problem, dist, margins_np,
                                                model.ntrees_built)
        if not device_auc:
            model.training_metrics = _metrics_for(problem, train.vec(y),
                                                  probs_tr)
        _ph.mark("training_metrics")
        if valid is not None:
            if valid_state is not None and self._mode != "drf":
                # multiproc: local-shard validation metrics, matching the
                # local-shard training metrics above (forest is identical
                # on every rank; scoring history carried the global numbers)
                mv = (distdata.local_shard(valid_state[2])
                      if multiproc else np.asarray(valid_state[2]))
                mv = mv[:valid_state[3]].astype(np.float64)
                probs_v = self._probs_from_margins(problem, dist, mv,
                                                   model.ntrees_built)
                model.validation_metrics = _metrics_for(problem, valid.vec(y), probs_v)
            else:
                model.validation_metrics = model._make_metrics(valid)
        # per-fit stream summary (ISSUE 14): blocks uploaded/evicted/reused
        # and bytes streamed per tree land on the recorded kernel plan
        # (/3/Profiler `tree` fold) and on the model, so "how many bytes
        # did this fit move" is a read, not a rerun
        if ooc_blocks and stream0 is not None:
            delta = {k2: ooc_store.counters[k2] - stream0.get(k2, 0)
                     for k2 in ooc_store.counters}
            stream_stats = dict(
                blocks=int(ooc_blocks),
                blocks_uploaded=delta["uploaded"],
                blocks_evicted=delta["evicted"],
                blocks_reused=delta["reused"],
                streamed_bytes=delta["bytes_streamed"],
                bytes_per_tree=int(delta["bytes_streamed"]
                                   / max(model.ntrees_built, 1)),
                resident_block_peak=int(ooc_store.peak_window_bytes()),
                spilled_blocks=delta.get("spilled", 0),
                restored_blocks=delta.get("restored", 0),
                spilled_bytes=delta.get("bytes_spilled", 0),
                restored_bytes=delta.get("bytes_restored", 0),
                disk_bytes=int(ooc_store.disk_bytes()),
                resident_host_peak=int(ooc_store.host_peak_window_bytes()),
                goss=bool(goss_cfg))
            from ..ops.histogram import attach_fit_stream

            attach_fit_stream(plan_tag, stream_stats)
            model._stream_stats = stream_stats
        # per-fit collective-skew summary (ISSUE 13): fold the fences this
        # fit recorded into the plan ring (/3/Profiler `tree`) and the fit
        # trace, so a dashboard sees which lane a sharded fit waited on
        if cfg.shard_mode == "mesh" and ndev_eff > 1:
            try:
                skew = cloudlib.lane_summary(lane_seq0)
                if skew.get("fences"):
                    from ..ops.histogram import attach_fit_skew

                    attach_fit_skew(plan_tag, skew)
                    from ..runtime import tracing as _tracing

                    _tracing.event(
                        "collective_skew", fences=skew["fences"],
                        skew_p50_ms=skew["skew_p50_ms"],
                        skew_max_ms=skew["skew_max_ms"],
                        worst_lane=skew["worst_lane"])
            except Exception:
                pass
        return model

    def _probs_from_margins(self, problem, dist, m: np.ndarray, ntrees: int) -> np.ndarray:
        return probs_from_margins(self._mode, problem, dist, m, ntrees)

    def _fit_calibrator(self, model: SharedTreeModel):
        """calibrate_model: fit Platt scaling (default) or isotonic
        regression of the true labels on predicted p1 over the
        calibration_frame (hex/tree CalibrationHelper)."""
        calib = self._parms.get("calibration_frame")
        if calib is None:
            raise ValueError("calibrate_model=True requires calibration_frame")
        # score EXACTLY as predict will (incl. offsets) so the map composes
        p1 = model._score_probs(model._matrix(calib),
                                model._offset_of(calib))[:, 1]
        ycal = np.asarray(calib.vec(model.y).data, np.float64)
        method = str(self._parms.get("calibration_method", "AUTO"))
        multiproc = distdata.multiprocess()
        if method in ("AUTO", "PlattScaling"):
            # 1-D logistic regression y ~ a·logit(p) + b via Newton. On a
            # multi-process cloud each rank holds its calibration shard;
            # gradient and Hessian are row sums, so one global_sum per
            # Newton step makes every rank converge to the SAME (a, b)
            z = np.log(np.clip(p1, 1e-12, 1 - 1e-12)
                       / np.clip(1 - p1, 1e-12, 1 - 1e-12))
            X = np.column_stack([z, np.ones_like(z)])
            ab = np.zeros(2)
            for _ in range(25):
                mu = 1 / (1 + np.exp(-(X @ ab)))
                Wd = np.clip(mu * (1 - mu), 1e-10, None)
                grad = X.T @ (ycal - mu)
                Hm = (X * Wd[:, None]).T @ X
                if multiproc:
                    packed = distdata.global_sum(
                        np.concatenate([grad, Hm.ravel()]))
                    grad, Hm = packed[:2], packed[2:].reshape(2, 2)
                step = np.linalg.solve(Hm + 1e-9 * np.eye(2), grad)
                ab = ab + step
                if np.max(np.abs(step)) < 1e-10:
                    break
            a, b = float(ab[0]), float(ab[1])

            def platt(p):
                zz = np.log(np.clip(p, 1e-12, 1 - 1e-12)
                            / np.clip(1 - p, 1e-12, 1 - 1e-12))
                return 1 / (1 + np.exp(-(a * zz + b)))

            return platt
        if method == "IsotonicRegression":
            from .isotonic import pav

            if multiproc:
                # PAV needs the globally sorted sequence — allgather the
                # (p, y) pairs as raw bytes (per-rank lengths differ;
                # calibration frames are holdout-sized, and the reference's
                # Isotonic calibration also centralizes them)
                p1 = np.concatenate([
                    np.frombuffer(b, np.float64) for b in
                    distdata.allgather_bytes(
                        np.ascontiguousarray(p1, np.float64).tobytes())])
                ycal = np.concatenate([
                    np.frombuffer(b, np.float64) for b in
                    distdata.allgather_bytes(
                        np.ascontiguousarray(ycal, np.float64).tobytes())])
            tx, ty = pav(p1, ycal, np.ones_like(ycal))
            return lambda p: np.interp(p, tx, ty)
        raise ValueError(f"unknown calibration_method {method!r}")

    def _default_stopping_metric(self, problem):
        sm = self._parms.get("stopping_metric", "AUTO")
        if sm and sm != "AUTO":
            return sm.lower()
        return "logloss" if problem in ("binomial", "multinomial") else "deviance"

    def _score_event_async(self, problem, dist, margins, y_d, w_d, n,
                           ntrees, row_mask=None, loss_fn=None):
        """Dispatch a scoring-history event and return a FINALIZER.

        Device path: the loss-reduction program is enqueued immediately
        and the returned callable blocks on its scalar only when invoked —
        the overlapped-chunk-scoring hook (ISSUE 7): the driver enqueues
        chunk m+1's tree programs between dispatch and finalize, so the
        device crunches the next chunk while the host waits on chunk m's
        metric and runs the early-stopping decision. Host paths compute
        eagerly and return a constant finalizer.

        `loss_fn` (sharded fits) is the blocked deterministic loss program
        (`_sharded_event_loss_fn`) replacing the whole-array reduction. It
        is the only loss program containing collectives, so it alone is
        fenced after dispatch (at most one collective executable in flight
        on a CPU mesh; no-op elsewhere) — the collective-free events
        (validation frames, the escape hatch) stay fully async so the
        overlapped speculative chunk keeps the device busy behind them."""
        if row_mask is not None and not isinstance(margins, np.ndarray):
            # QoS chunk-fence yield: the loss program is a training-class
            # dispatch — hold it back while serving is in flight
            _qos.yield_point("score_event")
            if loss_fn is not None:
                val_dev = loss_fn(margins, y_d, row_mask,
                                  jnp.float32(1.0 / max(ntrees, 1)))
                cloudlib.collective_fence(val_dev)
            else:
                val_dev = _event_loss_device(
                    margins, y_d, row_mask,
                    jnp.float32(1.0 / max(ntrees, 1)),
                    self._mode, problem, dist)

            def _fin() -> Dict:
                val = float(val_dev)
                ev: Dict = {"number_of_trees": ntrees,
                            "timestamp": time.time()}
                if problem in ("binomial", "multinomial"):
                    ev["logloss"] = val
                    ev["training_deviance"] = val
                    if problem == "binomial":
                        ev["auc"] = float("nan")  # full AUC at final scoring
                else:
                    ev["deviance"] = val
                    ev["rmse"] = float(np.sqrt(val))
                    ev["training_deviance"] = val
                return ev

            return _fin
        ev = self._score_event(problem, dist, margins, y_d, w_d, n,
                               ntrees, row_mask=row_mask)
        return lambda: ev

    def _score_event(self, problem, dist, margins, y_d, w_d, n, ntrees,
                     row_mask=None) -> Dict:
        """One scoring-history event. With `row_mask` (device real-row
        mask), the loss sums are computed ON DEVICE and only two scalars
        cross the wire — at 1M rows the host path's full-margin pull is
        4·n·K bytes through the tunnel per event. On a multi-process cloud
        the device inputs are global, so the sums come back global and
        identical on every rank (the early-stopping decisions that read
        them therefore agree); the host fallback (OOB means arrive as numpy)
        reduces with ONE `global_sum` instead."""
        if row_mask is not None and not isinstance(margins, np.ndarray):
            return self._score_event_async(problem, dist, margins, y_d,
                                           w_d, n, ntrees,
                                           row_mask=row_mask)()
        multiproc = distdata.multiprocess()
        m = distdata.to_local(margins)[:n].astype(np.float64)
        y = distdata.to_local(y_d)[:n].astype(np.float64)
        probs = self._probs_from_margins(problem, dist, m, ntrees)

        def _gmean(local_sum: float, local_cnt: float) -> float:
            if multiproc:
                tot = distdata.global_sum(
                    np.asarray([local_sum, local_cnt], np.float64))
                return float(tot[0] / max(tot[1], 1e-12))
            return float(local_sum / max(local_cnt, 1e-12))

        ev: Dict = {"number_of_trees": ntrees, "timestamp": time.time()}
        if problem == "binomial":
            p = np.clip(probs[:, 1], 1e-15, 1 - 1e-15)
            nll = -np.log(np.where(y[:, 0] > 0.5, p, 1 - p))
            ev["logloss"] = _gmean(float(nll.sum()), float(len(nll)))
            ev["auc"] = float("nan")  # full AUC computed at final scoring
            ev["training_deviance"] = ev["logloss"]
        elif problem == "multinomial":
            p = np.clip(probs, 1e-15, 1)
            nll = -np.log(p[y.astype(bool)])
            ev["logloss"] = _gmean(float(nll.sum()), float(len(nll)))
            ev["training_deviance"] = ev["logloss"]
        else:
            sq = (probs[:, 0] - y[:, 0]) ** 2
            ev["deviance"] = _gmean(float(sq.sum()), float(len(sq)))
            ev["rmse"] = float(np.sqrt(ev["deviance"]))
            ev["training_deviance"] = ev["deviance"]
        return ev

    def _cv_predict(self, model: SharedTreeModel, frame: Frame) -> np.ndarray:
        out = model._score_probs(model._matrix(frame))
        if model.problem == "binomial":
            return out[:, 1]
        if model.problem == "multinomial":
            return out
        return out[:, 0]
