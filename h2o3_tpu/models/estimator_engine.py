"""Device-resident iteration engine for the non-tree estimators (ISSUE 15).

The tree path got fused device-resident kernels (PR 7), deterministic mesh
sharding (PR 9) and streaming (PR 11); GLM, K-Means, PCA/GLRM and
DeepLearning stayed seed-shaped: every fit re-extracted and re-uploaded its
float matrix and iterated in a host Python loop with a blocking device sync
per iteration. This module is the shared spine that routes the same
treatment to them:

- **One matrix, one upload** — `host_matrix` / `device_matrix` /
  `design_matrix` resolve the standardized float design through the
  dataset cache's new ``std`` layer (keyed by frame fingerprint + x +
  standardization/impute/expansion params + pad/shard grid), so every CV
  fold and sweep candidate sharing a frame reuses ONE extraction and ONE
  device artifact instead of paying `fit_transform` + H2D per fit.
- **Shard plan** — `shard_plan()` is the one mode decision (mirroring
  `shared_tree._shard_plan`): a multi-device single-process cloud runs row
  reductions as S canonical ordered blocks merged by
  `ops.histogram.ordered_axis_fold` ("mesh"); ``H2O3_EST_SHARD=1`` forces
  the identical blocked structure on one device ("blocks") so an N-device
  fit is bit-identical to the 1-device forced-shard lane; ``=0`` is the
  escape hatch. Multi-process clouds and ``H2O3_EST_LEGACY=1`` keep the
  pre-engine paths.
- **Observability** — per-fit plans (`record_fit`: algo, path, iterations,
  converged-on-device, matrix cache hit/miss, n_shards) in a bounded ring
  surfaced at /3/Profiler's ``est`` fold, `h2o3_est_dispatch{algo,path}` /
  `h2o3_est_iterations{algo}` registry families, and the fused iteration
  wall booked into the ``est_iter`` phase bucket (`iter_phase`).

The estimators' fused whole-iteration programs themselves (GLM IRLS as a
`lax.while_loop`, K-Means Lloyd, PCA power iteration, GLRM alternating
solves, DL's `lax.scan` epochs) live in their own modules; this engine
holds what they share.
"""

from __future__ import annotations

import math
import os
import threading
import time
from collections import deque
from contextlib import contextmanager
from typing import Optional, Tuple

import numpy as np


def legacy() -> bool:
    """``H2O3_EST_LEGACY=1`` restores the host per-iteration estimator
    paths as the bench/parity comparator (for GLM lambda search that is
    the host IRLS loop — the pre-device-program shape)."""
    return os.environ.get("H2O3_EST_LEGACY", "").lower() in ("1", "true",
                                                            "yes")


def shard_blocks() -> int:
    return max(int(os.environ.get("H2O3_EST_SHARD_BLOCKS", "8") or 8), 1)


def shard_plan(ndev: int, multiproc: bool) -> Tuple[str, int]:
    """(shard_mode, n_shards) for one estimator fit — the ONE place the
    decision is made (mirrors `shared_tree._shard_plan`).

    "mesh": multi-device cloud — S ordered blocks spread over the lanes,
    merged by `ordered_axis_fold`. "blocks": 1 device,
    ``H2O3_EST_SHARD=1`` — the same S-block structure forced on one chip
    (the bit-identity comparator lane). "off": plain full-row reductions
    (1 device default — bit-exact with the pre-engine math). The legacy
    comparator always reports "off". Multi-process POD clouds (ISSUE 18)
    report "mesh" like any multi-device cloud — the caller decides whether
    its fit supports the pod lane (GLM does; estimators that keep the
    pre-engine multi-process paths gate on their own `engine_on`)."""
    env = os.environ.get("H2O3_EST_SHARD", "").strip()
    if legacy() or env == "0":
        return "off", 0
    if multiproc:
        base = shard_blocks()
        return "mesh", base * ndev // math.gcd(base, ndev)
    base = shard_blocks()
    if ndev > 1:
        return "mesh", base * ndev // math.gcd(base, ndev)
    if env == "1":
        return "blocks", base
    return "off", 0


def pad_rows(n: int, n_shards: int) -> int:
    """Rows padded to the canonical block grid (zero-filled, zero-weight
    rows — exact no-ops in every weighted reduction)."""
    if n_shards <= 0:
        return n
    from ..parallel.mesh import pad_to_multiple

    return pad_to_multiple(n, n_shards)


def local_plan(cloud, shard_mode: str, n_shards: int):
    """(local_blocks, axis_name) for one fused program under the shard
    plan — the ONE derivation of 'how many ordered blocks does THIS
    lane/device compute, and over which mesh axis do partials gather'
    (mesh: n_shards spread over the lanes; blocks: all on one device;
    off: 0 = plain full-row reductions)."""
    from ..parallel.mesh import ROWS_AXIS

    local_blocks = (n_shards // cloud.size if shard_mode == "mesh"
                    else n_shards)
    axis = (ROWS_AXIS if shard_mode == "mesh" and cloud.size > 1 else None)
    return local_blocks, axis


def block_slices(nrows: int, local_blocks: int):
    """The canonical per-block row slices of one lane's rows — every
    estimator's blocked partials must cut the same grid or two fits
    sharing S would not be bit-comparable."""
    rows = nrows // local_blocks
    return [slice(i * rows, (i + 1) * rows) for i in range(local_blocks)]


def fold_blocks(parts, axis_name: Optional[str], tag: Optional[str] = None):
    """Deterministic ordered merge of per-block partials — the PR 9
    blocked-fold contract, re-exported so estimator programs and the tree
    path can never drift apart."""
    from ..ops.histogram import ordered_axis_fold

    return ordered_axis_fold(parts, axis_name, timing_tag=tag)


# -- cached matrices through the dataset cache's std layer --------------------

def cache_enabled() -> bool:
    from . import dataset_cache

    return dataset_cache.enabled() and not legacy()


def _expansion_key(frame, x, use_all: bool) -> bool:
    """use_all_factor_levels only changes the design when a categorical
    column exists — normalize it out of the key for all-numeric frames so
    GLM (use_all=False) and K-Means (use_all=True) share one artifact."""
    if not use_all:
        return False
    return any(frame.vec(c).type == "enum" for c in x)


def host_matrix(frame, x, *, standardize: bool, use_all: bool = False,
                impute: bool = True):
    """(DataInfo, standardized float32 host matrix) for (frame, x) —
    cached. The host artifact backs K-Means init draws and is the parent
    of `device_matrix`."""
    from .model_base import DataInfo

    ua = _expansion_key(frame, x, use_all)

    def build():
        dinfo = DataInfo(frame, x, standardize=standardize,
                         use_all_factor_levels=ua, impute_missing=impute)
        X = dinfo.fit_transform(frame)
        return (dinfo, X), int(X.nbytes), "host"

    if not cache_enabled():
        return build()[0]
    from . import dataset_cache

    return dataset_cache.std_artifact(
        frame, x, ("host", bool(standardize), ua, bool(impute)), build)


def device_matrix(frame, x, *, standardize: bool, use_all: bool = False,
                  impute: bool = True, n_shards: int = 0, n_devices: int = 1):
    """(DataInfo, device design matrix) — the cached host matrix uploaded
    ONCE (padded to the block grid, row-sharded over the mesh when
    n_devices > 1). Consumers that iterate on the plain standardized
    matrix (K-Means, PCA, GLRM's quadratic path) share this artifact; the
    numbers are bitwise the `fit_transform` values the legacy paths use,
    so "off"-mode fused fits stay bit-comparable."""
    ua = _expansion_key(frame, x, use_all)
    npad = pad_rows(frame.nrow, n_shards)
    # resolve the host layer OUTSIDE the device layer's build: std_artifact
    # holds the cache entry's (non-reentrant) lock around the builder, and
    # both layers live on the same entry
    dinfo, X = host_matrix(frame, x, standardize=standardize, use_all=ua,
                           impute=impute)

    def build():
        Xp = X
        if npad != X.shape[0]:
            Xp = np.concatenate(
                [X, np.zeros((npad - X.shape[0], X.shape[1]), X.dtype)])
        from ..runtime import phases as _phases

        def _put():
            import jax
            import jax.numpy as jnp

            if n_devices > 1:
                from ..parallel import mesh as cloudlib

                return jax.device_put(jnp.asarray(Xp),
                                      cloudlib.cloud().row_sharding())
            return jnp.asarray(Xp)

        Xd = _phases.accounted_h2d(_put, int(Xp.nbytes))
        return (dinfo, Xd), int(Xp.nbytes), "device"

    if not cache_enabled():
        return build()[0]
    from . import dataset_cache

    return dataset_cache.std_artifact(
        frame, x, ("dev", bool(standardize), ua, bool(impute),
                   int(npad), int(n_devices)), build)


def design_matrix(frame, x, *, standardize: bool, use_all: bool = False,
                  add_intercept: bool = False, n_shards: int = 0,
                  n_devices: int = 1):
    """(DataInfo, device design matrix) via `DataInfo.device_design` — the
    compact-upload + on-device-expansion path GLM and DeepLearning already
    run (small-range integer columns travel at 1-2 bytes/value, the dense
    one-hot never crosses the link), now cached so a sweep expands and
    uploads once. Bitwise the same artifact those estimators built per-fit
    before."""
    from .model_base import DataInfo

    ua = _expansion_key(frame, x, use_all)
    npad = pad_rows(frame.nrow, n_shards)

    def build():
        import jax

        dinfo = DataInfo(frame, x, standardize=standardize,
                         use_all_factor_levels=ua, impute_missing=True)
        if n_devices > 1:
            from ..parallel import mesh as cloudlib

            cloud = cloudlib.cloud()
            # stats fit on host first (device_design sharded assembly
            # requires fitted stats); compact packs shard straight from
            # host — no unsharded intermediate on device 0
            dinfo.fit_transform(frame)
            Xd = dinfo.device_design(frame, fit=False,
                                     add_intercept=add_intercept,
                                     cloud=cloud, quota=npad)
        else:
            Xd = dinfo.device_design(frame, fit=True,
                                     add_intercept=add_intercept,
                                     row_bucket=n_shards or 0)
        nbytes = int(np.prod(Xd.shape)) * Xd.dtype.itemsize
        return (dinfo, Xd), nbytes, "device"

    if not cache_enabled():
        return build()[0]
    from . import dataset_cache

    return dataset_cache.std_artifact(
        frame, x, ("design", bool(standardize), ua, bool(add_intercept),
                   int(npad), int(n_devices)), build)


# -- per-cloud fused-program cache --------------------------------------------

_PROG_LOCK = threading.Lock()


def cached_program(cloud, key: tuple, build):
    """Get-or-build one fused estimator program, cached on the cloud (like
    `shared_tree._sharded_event_loss_fn`) so sweep candidates share traces
    and a mesh rebuild drops the stale executables with the old cloud."""
    with _PROG_LOCK:
        cache = cloud.__dict__.setdefault("_est_fns_cache", {})
        fn = cache.get(key)
        if fn is None:
            fn = cache[key] = build()
        return fn


# -- observability ------------------------------------------------------------

_PLAN_LOCK = threading.Lock()
_PLANS: "deque" = deque(maxlen=16)
_REG: dict = {}


def _registry() -> dict:
    """Memoized registry families (the usual stance: recording a fit must
    not take the registry registration lock)."""
    if not _REG:
        from ..runtime import metrics_registry as _reg

        _REG["dispatch"] = _reg.counter(
            "h2o3_est_dispatch",
            "estimator-engine fit dispatches by algo and resolved path "
            "(fused/fused_blocks/fused_mesh/legacy/host)",
            labelnames=("algo", "path"))
        _REG["iterations"] = _reg.counter(
            "h2o3_est_iterations",
            "estimator iterations executed inside fused device programs "
            "(whole-fit loops — the host observed only the final state)",
            labelnames=("algo",))
    return _REG


def record_fit(algo: str, path: str, *, iterations: Optional[int] = None,
               converged: Optional[bool] = None,
               matrix_cache: Optional[str] = None, n_shards: int = 0,
               n_devices: int = 1, wall_s: Optional[float] = None,
               **extra) -> dict:
    """Record one estimator fit's plan: how it dispatched (fused vs
    legacy, shard mode), how many device iterations it ran, whether the
    on-device convergence test fired, and whether the standardized matrix
    came out of the cache. Ring + counters; the ring is the /3/Profiler
    ``est`` fold."""
    plan = dict(algo=algo, ts=time.time(), path=path,
                n_shards=int(n_shards), n_devices=int(n_devices))
    if iterations is not None:
        plan["iterations"] = int(iterations)
    if converged is not None:
        plan["converged"] = bool(converged)
    if matrix_cache is not None:
        plan["matrix_cache"] = matrix_cache
    if wall_s is not None:
        plan["wall_s"] = round(float(wall_s), 4)
    plan.update(extra)
    with _PLAN_LOCK:
        _PLANS.append(plan)
    try:
        reg = _registry()
        reg["dispatch"].inc(1, algo, path)
        if iterations:
            reg["iterations"].inc(int(iterations), algo)
    except Exception:
        pass
    try:
        from ..runtime import tracing as _tracing

        _tracing.event("est_fit", algo=algo, path=path,
                       iterations=iterations, n_shards=n_shards)
    except Exception:
        pass
    return plan


def matrix_cache_state(before: dict) -> str:
    """"hit"/"miss" verdict for the std layer between two
    `dataset_cache.snapshot()` reads around a fit's matrix resolution."""
    from . import dataset_cache

    after = dataset_cache.snapshot()
    if after.get("std_misses", 0) > before.get("std_misses", 0):
        return "miss"
    if after.get("std_hits", 0) > before.get("std_hits", 0):
        return "hit"
    return "off"


def est_stats() -> dict:
    """Per-fit plans + cumulative dispatch/iteration counters (the
    /3/Profiler ``est`` fold). Pure counter read — never fits anything."""
    with _PLAN_LOCK:
        plans = list(_PLANS)
    out = dict(plans=plans, dispatch={}, iterations={})
    try:
        reg = _registry()
        out["dispatch"] = {"/".join(lv): c.value()
                           for lv, c in reg["dispatch"].children().items()}
        out["iterations"] = {lv[0]: c.value()
                             for lv, c in reg["iterations"].children().items()}
    except Exception:
        pass
    return out


def reset_plans() -> None:
    """Drop the plan ring (tests). Registry counters are monotone and stay."""
    with _PLAN_LOCK:
        _PLANS.clear()


# -- QoS dispatch segmentation (ISSUE 19) -------------------------------------

def max_iters_per_dispatch() -> int:
    """Cap on ``while_loop`` iterations per device dispatch.

    Under the multi-tenant QoS gate a fused estimator fit becomes a
    RESUMABLE sequence of bounded device programs: each segment runs at
    most this many iterations (the loop cond gains ``it < stop_at``), the
    carry round-trips on device between segments, and the call site visits
    ``qos.yield_point("est_segment")`` between dispatches so serving never
    waits behind an unbounded fused loop. 0 = unbounded (one fused
    dispatch — the default whenever QoS is off). ``stop_at = max_iter``
    makes segmentation the identity: same trip count, same body, same
    bits (pinned)."""
    import os

    try:
        cap = int(os.environ.get("H2O3_QOS_EST_ITERS_PER_DISPATCH", "0"))
    except ValueError:
        cap = 0
    if cap > 0:
        return cap
    from ..runtime import qos

    return 32 if qos.enabled() else 0


def segment_stops(max_iter: int):
    """The ``stop_at`` schedule for one fused fit under the dispatch cap:
    ``[cap, 2·cap, …, max_iter]``, or ``[max_iter]`` when uncapped (the
    single-dispatch identity path)."""
    max_iter = int(max_iter)
    cap = max_iters_per_dispatch()
    if cap <= 0 or cap >= max_iter:
        return [max_iter]
    return list(range(cap, max_iter, cap)) + [max_iter]


# -- mid-fit carry snapshots (ISSUE 20) ---------------------------------------
# A fused estimator fit is a sequence of bounded device programs under the
# QoS dispatch cap (segment_stops above); each boundary is also a natural
# checkpoint: the while_loop carry IS the whole fit state. Snapshotting it
# through the supervisor store makes a killed kmeans/GLM fit resume at the
# last completed segment instead of iteration 0 — and because the carry
# round-trips the exact f32 values, the resumed fit is bit-identical to an
# undisturbed one (the remaining segments run the same body on the same
# carry). Disabled (fingerprint None) unless H2O3_CKPT_DIR is set.

def segment_fingerprint(algo: str, **fields):
    """Run fingerprint for one fused fit's carry snapshots, or None when
    fit checkpointing is off — the single gate the call sites branch on."""
    from ..runtime import supervisor as _sup

    if not (_sup.ckpt_enabled() and _sup.ckpt_dir()):
        return None
    return _sup.run_fingerprint(algo=algo, **fields)


def _carry_host(a):
    """One carry leaf to host bits. Replicated process-spanning arrays
    (the only multi-host carry shape — β/centroids are replicated) read
    their local copy; everything else is directly materializable."""
    import jax
    import jax.numpy as jnp

    a = jnp.asarray(a)
    if getattr(a, "is_fully_addressable", True):
        return np.asarray(a)
    return np.asarray(a.addressable_data(0))


def segment_carry_save(algo: str, fingerprint, stop: int, carry) -> None:
    """Snapshot the fused loop's carry tuple at a completed segment
    boundary (``stop`` iterations done). No-op when fingerprint is None."""
    if fingerprint is None:
        return
    import jax

    from ..runtime import supervisor as _sup

    arrays = {f"c{i}": _carry_host(c) for i, c in enumerate(carry)}
    _sup.save_fit_checkpoint(
        _sup.ckpt_dir(), f"est{algo}", fingerprint, int(stop), arrays,
        meta=dict(ncarry=len(carry)),
        rank=jax.process_index(), nproc=jax.process_count())


def segment_carry_restore(algo: str, fingerprint):
    """Newest valid carry snapshot for this fit → ``(stop, carry_tuple)``
    or None. The carry is replicated, so any rank's shard reconstructs it;
    multi-process clouds take a consensus vote first (a rank-divergent
    restore would deadlock the segment collectives)."""
    if fingerprint is None:
        return None
    import jax.numpy as jnp

    from ..parallel import distdata
    from ..runtime import supervisor as _sup

    rec = _sup.latest_fit_checkpoint(_sup.ckpt_dir(), f"est{algo}",
                                     fingerprint)
    ok = rec is not None
    if distdata.multiprocess():
        ok = distdata.global_all(bool(ok))
    if not ok:
        return None
    sh = rec["shards"][0]
    n = int(rec["meta"].get("ncarry", len(sh)))
    carry = tuple(jnp.asarray(sh[f"c{i}"]) for i in range(n))
    _sup.note_mid_fit_resume(f"est{algo}", int(rec["step"]),
                             restored=int(rec["step"]))
    return int(rec["step"]), carry


@contextmanager
def iter_phase():
    """Book a fused iteration loop's wall into the ``est_iter`` phase
    bucket (compile/trace time the first call triggers is subtracted —
    it is already accounted by the monitoring listener)."""
    from ..runtime import phases as _phases

    _phases.install_listener()
    comp0 = _phases.totals(_phases.COMPILE_KEYS)
    t0 = time.perf_counter()
    try:
        yield
    finally:
        el = (time.perf_counter() - t0
              - (_phases.totals(_phases.COMPILE_KEYS) - comp0))
        _phases.add("est_iter", max(el, 0.0))
