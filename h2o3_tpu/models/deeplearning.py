"""H2ODeepLearningEstimator — multilayer perceptron.

Reference parity: `h2o-algos/src/main/java/hex/deeplearning/DeepLearning.java`,
`DeepLearningTask.java` (per-row fwd/bwd with **Hogwild!** lock-free weight
races + inter-node model averaging in `reduce()`), `Neurons.java` (rectifier/
tanh/maxout fwd/bwd, dropout), `DeepLearningModelInfo.java` (flat weights),
and the estimator surface `h2o-py/h2o/estimators/deeplearning.py`
(MNIST-rectifier is a BASELINE.json headline config).

Deliberate semantic change (SURVEY.md §2.4): Hogwild's benign races and
per-node model averaging are replaced by **synchronous data-parallel
minibatch SGD** — batch rows sharded over the ``hosts`` mesh axis, gradients
averaged by XLA-inserted `psum` (the MRTask.reduce of DeepLearningTask,
compiled). Results become deterministic; accuracy targets must match, the
trajectory will not. `train_samples_per_iteration` survives as the scoring/
early-stopping cadence, matching the reference's sync-interval meaning.

Optimizers mirror the reference: ADADELTA (`adaptive_rate=true`, rho/epsilon)
or annealed-momentum SGD (`rate`, `rate_annealing`, `momentum_start/ramp/
stable` — Nesterov).
"""

from __future__ import annotations

import functools
import time
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..frame.frame import Frame
from ..parallel import distdata
from ..parallel import mesh as cloudlib
from .metrics import (
    ModelMetricsBinomial,
    ModelMetricsMultinomial,
    ModelMetricsRegression,
)
from .model_base import (DataInfo, H2OEstimator, H2OModel, ScoreKeeper,
                         ScoringHistory, response_info)

ACTIVATIONS = (
    "Rectifier", "Tanh", "Maxout",
    "RectifierWithDropout", "TanhWithDropout", "MaxoutWithDropout",
)


def _act(name: str, x, k2=None, dropout=0.0):
    base = name.replace("WithDropout", "")
    if base == "Rectifier":
        h = jax.nn.relu(x)
    elif base == "Tanh":
        h = jnp.tanh(x)
    elif base == "Maxout":
        # Neurons.Maxout: pairs of units, max over the pair (channel dim 2)
        h = jnp.max(x.reshape(x.shape[0], -1, 2), axis=2)
    else:
        raise ValueError(f"unknown activation {name}")
    if dropout > 0.0 and k2 is not None:
        keep = jax.random.bernoulli(k2, 1 - dropout, h.shape)
        h = jnp.where(keep, h / (1 - dropout), 0.0)
    return h


def _init_params(key, sizes: List[int], activation: str, seed_dist="UniformAdaptive"):
    """DeepLearningModelInfo.randomizeWeights — uniform-adaptive init."""
    params = []
    maxout = activation.startswith("Maxout")
    for i in range(len(sizes) - 1):
        fan_in, fan_out = sizes[i], sizes[i + 1]
        hidden = i < len(sizes) - 2
        out_dim = fan_out * 2 if (maxout and hidden) else fan_out
        key, sub = jax.random.split(key)
        limit = jnp.sqrt(6.0 / (fan_in + out_dim))
        W = jax.random.uniform(sub, (fan_in, out_dim), jnp.float32, -limit, limit)
        b = jnp.zeros(out_dim, jnp.float32)
        params.append((W, b))
    return params


def _forward(params, X, activation, hidden_dropout, input_dropout, key, train: bool):
    h = X
    if train and input_dropout > 0:
        key, sub = jax.random.split(key)
        keep = jax.random.bernoulli(sub, 1 - input_dropout, h.shape)
        h = jnp.where(keep, h / (1 - input_dropout), 0.0)
    L = len(params)
    for i, (W, b) in enumerate(params):
        z = h @ W + b
        if i < L - 1:
            dr = hidden_dropout[i] if train and hidden_dropout else 0.0
            if train and dr > 0:
                key, sub = jax.random.split(key)
            else:
                sub = None
            h = _act(activation, z, sub, dr if train else 0.0)
        else:
            h = z  # output layer linear; link applied in the loss/score
    return h


class DeepLearningModel(H2OModel):
    algo = "deeplearning"

    def __init__(self, params_est, x, y, dinfo, problem, nclass, domain,
                 net_params, activation, distribution):
        super().__init__(params_est)
        self.x = list(x)
        self.y = y
        self.dinfo = dinfo
        self.problem = problem
        self.nclass = nclass
        self.domain = domain
        self.net_params = net_params
        self.activation = activation
        self.distribution = distribution

    def _score(self, frame: Frame, X_pre=None) -> np.ndarray:
        """X_pre: optional pre-transformed (and possibly device-resident)
        design matrix — the training loop passes its HBM copy so scoring
        events skip the host re-expansion and the big re-upload."""
        X = X_pre if X_pre is not None else jnp.asarray(self.dinfo.transform(frame))
        out = _forward(self.net_params, X, self.activation, None, 0.0, None, False)
        if self.problem == "autoencoder":
            return np.asarray(out, np.float64)  # reconstruction
        if self.problem in ("binomial", "multinomial"):
            return np.asarray(jax.nn.softmax(out, axis=1), np.float64)
        if self.distribution in ("poisson", "gamma", "tweedie"):
            return np.asarray(jnp.exp(out[:, 0]), np.float64)[:, None]
        return np.asarray(out[:, :1], np.float64)

    def anomaly(self, frame: Frame) -> Frame:
        """Per-row reconstruction MSE (`h2o.anomaly` on an autoencoder)."""
        if self.problem != "autoencoder":
            raise ValueError("anomaly() requires autoencoder=True")
        X = self.dinfo.transform(frame)  # one expansion, reused for both
        rec = np.asarray(_forward(self.net_params, jnp.asarray(X),
                                  self.activation, None, 0.0, None, False),
                         np.float64)
        return Frame.from_dict(
            {"Reconstruction.MSE": np.mean((rec - X) ** 2, axis=1)})

    def predict(self, test_data: Frame) -> Frame:
        out = self._score(test_data)
        if self.problem == "autoencoder":
            # reconstructed inputs in the expanded coefficient space
            return Frame.from_dict(
                {f"reconstr_{n}": out[:, i]
                 for i, n in enumerate(self.dinfo.coef_names)})
        if self.problem in ("binomial", "multinomial"):
            lab = out.argmax(axis=1)
            d = {"predict": np.asarray(self.domain, dtype=object)[lab]}
            for i, cls in enumerate(self.domain):
                d[str(cls)] = out[:, i]
            return Frame.from_dict(d, column_types={"predict": "enum"})
        return Frame.from_dict({"predict": out[:, 0]})

    def _make_metrics(self, frame: Frame, X_pre=None):
        out = self._score(frame, X_pre=X_pre)
        if self.problem == "autoencoder":
            X = (np.asarray(X_pre) if X_pre is not None
                 else self.dinfo.transform(frame))
            mse = float(np.mean((out - X) ** 2))
            m = ModelMetricsRegression(mse=mse, rmse=float(np.sqrt(mse)),
                                       nobs=frame.nrow,
                                       description="autoencoder reconstruction")
            return m
        yv = frame.vec(self.y)
        if self.problem == "binomial":
            return ModelMetricsBinomial.make(np.asarray(yv.data), out[:, 1])
        if self.problem == "multinomial":
            return ModelMetricsMultinomial.make(np.asarray(yv.data), out)
        return ModelMetricsRegression.make(yv.numeric_np(), out[:, 0])


class H2ODeepLearningEstimator(H2OEstimator):
    algo = "deeplearning"
    _param_defaults = dict(
        activation="Rectifier",
        hidden=[200, 200],
        epochs=10.0,
        train_samples_per_iteration=-2,
        mini_batch_size=32,           # reference default is 1 (per-row Hogwild);
                                      # sync-DP wants real batches — documented delta
        adaptive_rate=True,
        rho=0.99,
        epsilon=1e-8,
        rate=0.005,
        rate_annealing=1e-6,
        rate_decay=1.0,
        momentum_start=0.0,
        momentum_ramp=1e6,
        momentum_stable=0.0,
        nesterov_accelerated_gradient=True,
        input_dropout_ratio=0.0,
        hidden_dropout_ratios=None,
        l1=0.0,
        l2=0.0,
        max_w2=float("inf"),
        initial_weight_distribution="UniformAdaptive",
        initial_weight_scale=1.0,
        loss="Automatic",
        distribution="AUTO",
        score_interval=5.0,
        score_training_samples=10000,
        score_validation_samples=0,
        score_duty_cycle=0.1,
        overwrite_with_best_model=True,
        standardize=True,
        use_all_factor_levels=True,
        shuffle_training_data=False,
        reproducible=False,
        variable_importances=True,
        export_weights_and_biases=False,
        elastic_averaging=False,
        autoencoder=False,
    )

    def _is_supervised(self) -> bool:  # autoencoder trains without a response
        return not self._parms.get("autoencoder", False)

    def _fit(self, x, y, train: Frame, valid: Optional[Frame]) -> DeepLearningModel:
        p = self._parms
        seed = p["_actual_seed"]
        autoenc = bool(p.get("autoencoder", False))
        if autoenc:
            problem, nclass, domain = "autoencoder", 0, None
            dist = "gaussian"
        else:
            yvec = train.vec(y)
            problem, nclass, domain = response_info(yvec)
            dist = p.get("distribution", "AUTO")
            if dist == "AUTO":
                dist = {"binomial": "bernoulli", "multinomial": "multinomial"}.get(
                    problem, "gaussian"
                )
        dinfo = DataInfo(
            train, x,
            standardize=bool(p.get("standardize", True)),
            use_all_factor_levels=bool(p.get("use_all_factor_levels", True)),
        )
        _max_runtime = float(p.get("max_runtime_secs", 0) or 0)
        multiproc = distdata.multiprocess()
        cloud = cloudlib.cloud()
        # ONE scan-path decision reused by the design-matrix choice and the
        # training loop below (a second copy of this predicate diverging
        # would read X_dev_pre=None inside the loop)
        use_scan = not (_max_runtime > 0) or multiproc
        if use_scan and not multiproc and cloud.size == 1:
            # device-resident training path: build the design matrix ON
            # device from compact columns (small-range integer features
            # travel as 1–2 bytes/value — MNIST-style pixel data is 4×
            # fewer tunnel bytes than the dense f32 upload, losslessly).
            # Single-device only: a multi-device mesh needs the
            # shard-straight-from-host upload so no unsharded intermediate
            # lands on device 0. The artifact rides the dataset cache's
            # std layer (ISSUE 15): a sweep's DL candidates (and AutoML's
            # three DeepLearning steps) expand + upload ONCE per frame.
            X = None
            from . import estimator_engine as _est

            if _est.cache_enabled():
                dinfo, X_dev_pre = _est.design_matrix(
                    train, x,
                    standardize=bool(p.get("standardize", True)),
                    use_all=bool(p.get("use_all_factor_levels", True)))
            else:
                X_dev_pre = dinfo.device_design(train, fit=True)
            n, nfeat = train.nrow, int(X_dev_pre.shape[1])
        else:
            X = dinfo.fit_transform(train)
            n, nfeat = X.shape
            X_dev_pre = None
        raw_hidden = p.get("hidden")
        if raw_hidden is not None:
            raw_hidden = list(raw_hidden)     # materialize once (iterables)
            if not raw_hidden:
                raise ValueError("hidden must be a non-empty list of layer "
                                 "sizes (got [])")
        hidden = list(raw_hidden if raw_hidden is not None else [200, 200])
        if any((not float(h).is_integer()) or h < 1 for h in hidden):
            raise ValueError(
                f"hidden must be a non-empty list of positive layer sizes, "
                f"got {raw_hidden}")
        hidden = [int(h) for h in hidden]
        if float(p.get("epochs", 10.0)) <= 0:
            raise ValueError(f"epochs must be > 0, got {p.get('epochs')}")
        if int(p.get("mini_batch_size", 32)) < 1:
            raise ValueError("mini_batch_size must be >= 1, got "
                             f"{p.get('mini_batch_size')}")
        for k in ("input_dropout_ratio", "rho"):
            v = p.get(k)
            if v is not None and not (0.0 <= float(v) < 1.0):
                raise ValueError(f"{k} must be in [0, 1), got {v}")
        eps_v = p.get("epsilon")
        if eps_v is not None and not (0.0 < float(eps_v) <= 1.0):
            raise ValueError(f"epsilon must be in (0, 1], got {eps_v}")
        activation = p.get("activation", "Rectifier")
        if activation not in ACTIVATIONS:
            raise ValueError(f"activation {activation!r} not in {ACTIVATIONS}")
        if autoenc:
            K = nfeat  # reconstruct the (expanded, standardized) inputs
        else:
            K = nclass if problem in ("binomial", "multinomial") else 1
        sizes = [nfeat] + hidden + [K]

        if autoenc:
            yarr = np.zeros(n, np.float32)  # unused placeholder
        elif problem in ("binomial", "multinomial"):
            yarr = np.asarray(yvec.data, np.int32)
        else:
            yarr = yvec.numeric_np().astype(np.float32)
        w = (
            train.vec(p["weights_column"]).numeric_np()
            if p.get("weights_column")
            else np.ones(n)
        ).astype(np.float32)

        if multiproc:
            # early stopping / time budget use a global any-rank-stops vote
            # at every scoring event, so host control flow stays aligned
            n_global = int(distdata.global_sum(np.asarray([n]))[0])
        else:
            n_global = n
        batch = int(p.get("mini_batch_size", 32))
        batch = max(batch, cloud.size)
        batch = cloudlib.pad_to_multiple(batch, cloud.size)

        key = jax.random.PRNGKey(seed & 0x7FFFFFFF)
        key, kinit = jax.random.split(key)
        params = _init_params(kinit, sizes, activation)

        hidden_dropout = p.get("hidden_dropout_ratios")
        if hidden_dropout is None and activation.endswith("WithDropout"):
            hidden_dropout = [0.5] * len(hidden)
        hidden_dropout = tuple(hidden_dropout) if hidden_dropout else None
        input_dropout = float(p.get("input_dropout_ratio", 0.0))
        l1 = float(p.get("l1", 0.0))
        l2 = float(p.get("l2", 0.0))
        max_w2 = float(p.get("max_w2", float("inf")))
        adaptive = bool(p.get("adaptive_rate", True))
        rho = float(p.get("rho", 0.99))
        eps = float(p.get("epsilon", 1e-8))
        rate0 = float(p.get("rate", 0.005))
        rate_annealing = float(p.get("rate_annealing", 1e-6))
        mom_start = float(p.get("momentum_start", 0.0))
        mom_ramp = max(float(p.get("momentum_ramp", 1e6)), 1.0)
        mom_stable = float(p.get("momentum_stable", 0.0))

        def loss_fn(params, xb, yb, wb, key):
            out = _forward(params, xb, activation, hidden_dropout, input_dropout, key, True)
            if autoenc:
                nll = jnp.mean((out - xb) ** 2, axis=1)
            elif problem in ("binomial", "multinomial"):
                logp = jax.nn.log_softmax(out, axis=1)
                nll = -jnp.take_along_axis(logp, yb[:, None].astype(jnp.int32), axis=1)[:, 0]
            elif dist == "poisson":
                nll = jnp.exp(out[:, 0]) - yb * out[:, 0]
            else:
                nll = 0.5 * (out[:, 0] - yb) ** 2
            loss = jnp.sum(nll * wb) / jnp.maximum(jnp.sum(wb), 1e-12)
            if l2 > 0:
                loss = loss + l2 * sum(jnp.sum(W * W) for W, _ in params)
            if l1 > 0:
                loss = loss + l1 * sum(jnp.sum(jnp.abs(W)) for W, _ in params)
            return loss

        # ADADELTA state: (E[g²], E[Δ²]) per tensor (Neurons ADADELTA impl).
        # Only the per-batch (max_runtime) path uses the structured layout;
        # the scan path carries the fused flat state (oflat below).
        if use_scan:
            opt_state = None
        elif adaptive:
            opt_state = [
                (jnp.zeros_like(W), jnp.zeros_like(W), jnp.zeros_like(b), jnp.zeros_like(b))
                for W, b in params
            ]
        else:
            opt_state = [(jnp.zeros_like(W), jnp.zeros_like(b)) for W, b in params]

        def _update(params, opt_state, grads, it):
            """One optimizer update (ADADELTA per Neurons.java, or
            momentum/annealed-rate SGD) — shared by the per-batch step and
            the device-resident scan."""
            new_params, new_state = [], []
            if adaptive:
                for (W, b), (Eg2W, Ed2W, Eg2b, Ed2b), (gW, gb) in zip(params, opt_state, grads):
                    Eg2W = rho * Eg2W + (1 - rho) * gW * gW
                    dW = -jnp.sqrt(Ed2W + eps) / jnp.sqrt(Eg2W + eps) * gW
                    Ed2W = rho * Ed2W + (1 - rho) * dW * dW
                    Eg2b = rho * Eg2b + (1 - rho) * gb * gb
                    db = -jnp.sqrt(Ed2b + eps) / jnp.sqrt(Eg2b + eps) * gb
                    Ed2b = rho * Ed2b + (1 - rho) * db * db
                    W2, b2 = W + dW, b + db
                    if np.isfinite(max_w2):
                        norms = jnp.sum(W2 * W2, axis=0, keepdims=True)
                        scale = jnp.sqrt(jnp.minimum(max_w2 / jnp.maximum(norms, 1e-12), 1.0))
                        W2 = W2 * scale
                    new_params.append((W2, b2))
                    new_state.append((Eg2W, Ed2W, Eg2b, Ed2b))
            else:
                rate = rate0 / (1.0 + rate_annealing * it)
                mom = jnp.minimum(
                    mom_start + (mom_stable - mom_start) * it / mom_ramp,
                    jnp.maximum(mom_stable, mom_start),
                ) if mom_ramp > 0 else mom_stable
                for (W, b), (vW, vb), (gW, gb) in zip(params, opt_state, grads):
                    vW2 = mom * vW - rate * gW
                    vb2 = mom * vb - rate * gb
                    new_params.append((W + vW2, b + vb2))
                    new_state.append((vW2, vb2))
            return new_params, new_state

        @functools.partial(jax.jit, donate_argnums=(0, 1))
        def train_step(params, opt_state, xb, yb, wb, key, it):
            grads = jax.grad(loss_fn)(params, xb, yb, wb, key)
            return _update(params, opt_state, grads, it)

        # ---- flat-parameter scan path ----------------------------------
        # The per-tensor optimizer updates are ~200 tiny elementwise ops
        # per step; inside lax.scan that op overhead dominates a small
        # MLP's step time (~430 µs/step measured). Flattening params and
        # optimizer state into single vectors fuses ADADELTA into a
        # handful of full-vector ops — identical math, elementwise either
        # way. The flat layout exists only inside the scan; boundaries
        # (scoring, model export) see the per-layer (W, b) list.
        _seg_shapes = []
        _seg_offs = []
        _off = 0
        for W0, b0 in params:                 # actual shapes (maxout widens)
            for t in (W0, b0):
                _seg_shapes.append(tuple(t.shape))
                _seg_offs.append(_off)
                _off += int(np.prod(t.shape))
        _flat_n = _off

        def _flatten(ps):
            return jnp.concatenate([jnp.ravel(t) for W, b in ps
                                    for t in (W, b)])

        def _unflatten(v):
            out = []
            for i in range(0, len(_seg_shapes), 2):
                W = jax.lax.dynamic_slice(
                    v, (_seg_offs[i],),
                    (int(np.prod(_seg_shapes[i])),)).reshape(_seg_shapes[i])
                b = jax.lax.dynamic_slice(
                    v, (_seg_offs[i + 1],),
                    (int(np.prod(_seg_shapes[i + 1])),)
                ).reshape(_seg_shapes[i + 1])
                out.append((W, b))
            return out

        def _clamp_w2(v):
            """Per-layer max_w2 column-norm clamp on the flat vector
            (only traced when the non-default max_w2 is set)."""
            for i in range(0, len(_seg_shapes), 2):
                shp = _seg_shapes[i]
                W = jax.lax.dynamic_slice(
                    v, (_seg_offs[i],), (int(np.prod(shp)),)).reshape(shp)
                norms = jnp.sum(W * W, axis=0, keepdims=True)
                scale = jnp.sqrt(jnp.minimum(
                    max_w2 / jnp.maximum(norms, 1e-12), 1.0))
                v = jax.lax.dynamic_update_slice(
                    v, (W * scale).ravel(), (_seg_offs[i],))
            return v

        def _flat_update(pv, ov, gv, it):
            if adaptive:
                eg2, ed2 = ov
                eg2 = rho * eg2 + (1 - rho) * gv * gv
                d = -jnp.sqrt(ed2 + eps) / jnp.sqrt(eg2 + eps) * gv
                ed2 = rho * ed2 + (1 - rho) * d * d
                pv = pv + d
                if np.isfinite(max_w2):
                    pv = _clamp_w2(pv)
                return pv, (eg2, ed2)
            rate = rate0 / (1.0 + rate_annealing * it)
            mom = jnp.minimum(
                mom_start + (mom_stable - mom_start) * it / mom_ramp,
                jnp.maximum(mom_stable, mom_start),
            ) if mom_ramp > 0 else mom_stable
            (vel,) = ov
            vel = mom * vel - rate * gv
            return pv + vel, (vel,)

        @functools.partial(jax.jit, donate_argnums=(0, 1),
                           static_argnames=("nsteps",))
        def train_chunk(pflat, oflat, X_d, y_d, w_d, key, it0, nsteps):
            """nsteps minibatch updates as ONE device program (lax.scan):
            the training set lives in HBM; one random permutation per chunk
            re-batches it into (nsteps, batch, ·) slices that scan consumes
            directly — no per-step gathers, no per-batch host→device uploads
            (either would dominate the step time through a remote-chip
            tunnel). Replaces the reference's per-row Hogwild loop
            (DeepLearningTask.map) with compiled minibatch SGD; the
            per-chunk reshuffle matches `shuffle_training_data` semantics."""
            kperm, kdrop = jax.random.split(key)
            need = nsteps * batch
            nrows = X_d.shape[0]            # global padded rows on a mesh
            perm = jax.random.permutation(kperm, nrows)
            reps = -(-need // nrows)                   # ceil: allow short n
            sel = jnp.tile(perm, reps)[:need]
            xs = (X_d[sel].reshape(nsteps, batch, -1),
                  y_d[sel].reshape((nsteps, batch) + y_d.shape[1:]),
                  w_d[sel].reshape(nsteps, batch),
                  jax.random.split(kdrop, nsteps))

            def flat_loss(pv, xb, yb, wb, k):
                return loss_fn(_unflatten(pv), xb, yb, wb, k)

            def body(carry, xb_yb_wb_k):
                pv, ov, it = carry
                xb, yb, wb, k = xb_yb_wb_k
                gv = jax.grad(flat_loss)(pv, xb, yb, wb, k)
                pv, ov = _flat_update(pv, ov, gv, it)
                return (pv, ov, it + 1.0), None

            (pflat, oflat, _), _ = jax.lax.scan(
                body, (pflat, oflat, jnp.float32(it0)), xs)
            return pflat, oflat

        # sync-DP: batches row-sharded over the mesh; params replicated —
        # XLA inserts the gradient psum (the Hogwild replacement)
        rs = cloud.row_sharding() if cloud.size > 1 else None
        epochs = float(p.get("epochs", 10.0))
        tspi = int(p.get("train_samples_per_iteration", -2))
        score_every = tspi if tspi > 0 else max(n_global, batch)
        stopper = (
            ScoreKeeper(int(p.get("stopping_rounds", 0)),
                        "logloss" if problem != "regression" else "deviance",
                        float(p.get("stopping_tolerance", 1e-3)))
            if int(p.get("stopping_rounds", 0)) > 0 else None
        )

        rng = np.random.default_rng(seed)
        total = int(epochs * n_global)
        seen = 0
        it = 0
        next_score = score_every
        history: List[Dict] = []
        t0 = time.time()
        max_runtime = _max_runtime
        model = DeepLearningModel(self, x, y, dinfo, problem, nclass, domain,
                                  params, activation, dist)
        # device-resident fast path: data in HBM (row-sharded on a mesh),
        # scan over steps; GSPMD turns the per-chunk permutation gather into
        # collectives and psums the sharded-batch gradients automatically.
        # max_runtime keeps the per-batch path (its wall check needs host
        # control between steps) — EXCEPT on multi-process clouds, where the
        # per-batch path would draw rank-divergent local batches; there the
        # scan path stays and the budget is checked (with the clock-
        # consensus vote) at scoring boundaries instead.
        if use_scan:
            if multiproc:
                # each process contributes its ingest shard as COMPACT
                # packs (uint8/int16 integer columns, int32 codes) expanded
                # on device — the same byte-compressed transfer the single-
                # chip path gets; zero-weight padding balances unequal byte
                # ranges (loss is Σw-normalized so padded rows are exact
                # no-ops). Stats were fitted by fit_transform's global
                # collectives, so the design ≡ the dense f32 upload.
                quota = distdata.local_quota(n)
                X_dev = dinfo.device_design(train, fit=False, cloud=cloud,
                                            quota=quota)
                y_dev = distdata.global_row_array(yarr, quota, cloud)
                w_dev = distdata.global_row_array(w, quota, cloud)
            elif rs is not None:
                # shard straight from host — an unsharded intermediate on
                # device 0 would defeat row sharding for data that only
                # fits when split across the mesh; rows pad to the mesh
                # multiple with zero weight
                quota = cloudlib.pad_to_multiple(n, cloud.size)
                X_dev = dinfo.device_design(train, fit=False, cloud=cloud,
                                            quota=quota)
                y_dev = distdata.global_row_array(yarr, quota, cloud)
                w_dev = distdata.global_row_array(w, quota, cloud)
            else:
                X_dev = X_dev_pre
                y_dev = jnp.asarray(yarr)
                w_dev = jnp.asarray(w)
            # scoring reuses the HBM copy — except on a multi-process mesh,
            # where fetching a cross-process-sharded eager result raises.
            # Quota-padded rows would corrupt training metrics, so scoring
            # gets a one-time device-side slice of the real rows.
            if jax.process_count() != 1:
                X_score = None
            elif int(X_dev.shape[0]) == n:
                X_score = X_dev
            else:
                X_score = X_dev[:n]
        else:
            # max_runtime path: no persistent device copy; scoring falls
            # back to the transient per-event transform
            X_score = None
        # mesh/quota padding adds zero-weight rows the permutation covers
        # too — discount them so `epochs` counts REAL samples (1.0 when
        # unpadded)
        real_frac = (n_global / float(X_dev.shape[0]) if use_scan else 1.0)
        if use_scan:
            pflat = _flatten(params)
            oflat = (tuple(jnp.zeros(_flat_n, jnp.float32)
                           for _ in range(2)) if adaptive
                     else (jnp.zeros(_flat_n, jnp.float32),))
        _score_time = 0.0
        while seen < total:
            # REST job cancellation (single-process: a per-rank host
            # decision would diverge a multi-process cloud)
            if self.job is not None and jax.process_count() == 1:
                self.job.check_cancelled()
            if use_scan:
                upto = min(next_score, total)
                eff_batch = max(batch * real_frac, 1e-9)
                steps = max(1, -(-int(upto - seen) // int(max(eff_batch, 1))))
                key, sub = jax.random.split(key)
                pflat, oflat = train_chunk(
                    pflat, oflat, X_dev, y_dev, w_dev, sub,
                    float(it), int(steps))
                # CPU mesh: serialize collective executables (see
                # parallel.mesh.collective_fence)
                cloudlib.collective_fence(pflat)
                seen += max(int(steps * eff_batch), 1)
                it += steps
            else:
                idx = rng.integers(0, n, batch)
                xb = jnp.asarray(X[idx])
                yb = jnp.asarray(yarr[idx])
                wb = jnp.asarray(w[idx])
                if rs is not None:
                    xb, yb, wb = (jax.device_put(a, rs) for a in (xb, yb, wb))
                key, sub = jax.random.split(key)
                params, opt_state = train_step(params, opt_state, xb, yb, wb,
                                               sub, jnp.float32(it))
                cloudlib.collective_fence(params[0][0])
                seen += batch
                it += 1
            if seen >= next_score or seen >= total:
                next_score += score_every
                # train_samples_per_iteration=-2 (auto-tune): cap the wall
                # share spent scoring at score_duty_cycle, like the
                # reference's computeSamplesPerIteration duty-cycle target.
                # Early stopping keeps every event (scoring IS its signal),
                # as does the final event and score_each_iteration.
                if (seen < total and stopper is None and tspi == -2
                        and not max_runtime
                        and not p.get("score_each_iteration")):
                    want_skip = _score_time > float(
                        p.get("score_duty_cycle", 0.1) or 0.1) * max(
                        time.time() - t0, 1e-9)
                    # per-rank clocks diverge; one rank skipping while
                    # another scores would desync the scoring path's
                    # collectives — skip only on a UNANIMOUS vote
                    want_skip = distdata.global_all(want_skip)
                    if want_skip:
                        if self.job:
                            self.job.update(min(seen / total, 1.0))
                        continue
                _t_sc = time.time()
                if use_scan:
                    params = _unflatten(pflat)
                model.net_params = params
                sm = model._make_metrics(train, X_pre=X_score)
                ev = {
                    "epochs": seen / n_global, "iterations": it,
                    "samples": seen, "timestamp": time.time(),
                }
                if problem in ("regression", "autoencoder"):
                    ev["deviance"] = sm.mse
                    metric_val = sm.mse
                else:
                    ev["logloss"] = sm.logloss
                    metric_val = sm.logloss
                history.append(ev)
                stop = stopper is not None and stopper.record(metric_val)
                # metrics are local-shard here, so ranks may disagree — a
                # global any-rank-stops vote keeps the remaining collective
                # programs aligned across processes
                stop = distdata.global_any(stop)
                _score_time += time.time() - _t_sc
                if stop:
                    break
            if max_runtime:
                hit = distdata.global_any(time.time() - t0 > max_runtime)
                if hit:
                    break
            if self.job:
                self.job.update(min(seen / total, 1.0))

        if use_scan:
            params = _unflatten(pflat)
        model.net_params = params
        model.scoring_history = ScoringHistory(history)
        model.training_metrics = model._make_metrics(train, X_pre=X_score)
        if valid is not None:
            model.validation_metrics = model._make_metrics(valid)
        return model

    def _cv_predict(self, model: DeepLearningModel, frame: Frame) -> np.ndarray:
        out = model._score(frame)
        if model.problem == "binomial":
            return out[:, 1]
        if model.problem == "multinomial":
            return out
        return out[:, 0]


def _dryrun_dp_step(cloud, n_devices: int):
    """One sharded DP train step for __graft_entry__.dryrun_multichip."""
    rng = np.random.default_rng(0)
    n, f, k = 16 * n_devices, 8, 3
    X = rng.normal(size=(n, f)).astype(np.float32)
    y = rng.integers(0, k, n).astype(np.int32)
    key = jax.random.PRNGKey(0)
    params = _init_params(key, [f, 16, k], "Rectifier")
    rs = cloud.row_sharding()
    Xj = jax.device_put(jnp.asarray(X), rs)
    yj = jax.device_put(jnp.asarray(y), rs)

    @jax.jit
    def step(params, X, y):
        def loss(params):
            out = _forward(params, X, "Rectifier", None, 0.0, None, False)
            logp = jax.nn.log_softmax(out, axis=1)
            return -jnp.mean(jnp.take_along_axis(logp, y[:, None], axis=1))

        grads = jax.grad(loss)(params)
        return jax.tree.map(lambda p, g: p - 0.1 * g, params, grads)

    out = step(params, Xj, yj)
    jax.block_until_ready(out)


DeepLearning = H2ODeepLearningEstimator
