"""H2OGeneralizedAdditiveEstimator — GAM (GLM + penalized smooth terms).

Reference parity: `h2o-algos/src/main/java/hex/gam/GAM.java` +
`hex/gam/MatrixFrameUtils/GamUtils.java`: each `gam_column` is expanded into
a cubic-regression-spline basis with `num_knots` knots at quantiles, a
roughness penalty matrix S (scaled by `scale`) is added to the GLM normal
equations, and identifiability comes from centering the basis. Estimator
surface `h2o-py/h2o/estimators/gam.py`.

TPU shape: the basis expansion is a host-side one-time transform; training is
the same one-einsum-Gram IRLS as GLM (`glm._gram_step`) with the block
penalty Σ scale_k · S_k added to the p×p system on host.
"""

from __future__ import annotations

from typing import Dict, List, Optional

import jax.numpy as jnp
import numpy as np

from ..frame.frame import Frame
from .glm import _gram_step, _linkinv
from .metrics import (
    ModelMetricsBinomial,
    ModelMetricsRegression,
)
from .model_base import DataInfo, H2OEstimator, H2OModel, response_info


# basis + penalty live in ops/splines (numpy-only) so the offline MOJO
# scorer can build the identical design without importing JAX
from ..ops.splines import second_diff_penalty as _second_diff_penalty
from ..ops.splines import spline_basis as _spline_basis


class GAMModel(H2OModel):
    algo = "gam"

    def __init__(self, params, x, y, dinfo, family, beta, domain, gam_spec):
        super().__init__(params)
        self.x = list(x)
        self.y = y
        self.dinfo = dinfo
        self.family = family
        self.beta = beta
        self.domain = domain
        self.gam_spec = gam_spec  # list of (col, knots, basis_center)

    def _design(self, frame: Frame) -> np.ndarray:
        parts = []
        if self.dinfo.x:
            parts.append(self.dinfo.transform(frame))
        for col, knots, center in self.gam_spec:
            # NaN→0 matches the training-time basis (see _fit)
            B = _spline_basis(np.nan_to_num(frame.vec(col).numeric_np()), knots) - center
            parts.append(B.astype(np.float32))
        X = np.concatenate(parts, axis=1) if parts else np.zeros((frame.nrow, 0), np.float32)
        return np.concatenate([X, np.ones((X.shape[0], 1), np.float32)], axis=1)

    def _score(self, frame: Frame) -> np.ndarray:
        eta = self._design(frame) @ self.beta
        return np.asarray(_linkinv(self.family, jnp.asarray(eta)))

    def coef(self) -> Dict[str, float]:
        names = list(self.dinfo.coef_names)
        for col, knots, _ in self.gam_spec:
            names += [f"{col}_cr_{i}" for i in range(len(knots) - 1)]
        names.append("Intercept")
        return dict(zip(names, self.beta))

    def predict(self, test_data: Frame) -> Frame:
        out = self._score(test_data)
        if self.family == "binomial":
            d = {"predict": np.asarray(self.domain, dtype=object)[(out > 0.5).astype(int)],
                 str(self.domain[0]): 1 - out, str(self.domain[1]): out}
            return Frame.from_dict(d, column_types={"predict": "enum"})
        return Frame.from_dict({"predict": out})

    def _make_metrics(self, frame: Frame):
        out = self._score(frame)
        yv = frame.vec(self.y)
        if self.family == "binomial":
            return ModelMetricsBinomial.make(np.asarray(yv.data), out)
        return ModelMetricsRegression.make(yv.numeric_np(), out)


class H2OGeneralizedAdditiveEstimator(H2OEstimator):
    algo = "gam"
    _param_defaults = dict(
        family="AUTO",
        gam_columns=None,
        num_knots=None,
        scale=None,
        bs=None,
        spline_orders=None,
        standardize=False,
        lambda_=None,
        alpha=None,
        max_iterations=50,
        beta_epsilon=1e-4,
        keep_gam_cols=False,
    )

    def _fit(self, x, y, train: Frame, valid: Optional[Frame]) -> GAMModel:
        from .model_base import warn_host_solver

        warn_host_solver('gam', train.nrow, 500000)
        p = self._parms
        gam_cols: List[str] = list(p.get("gam_columns") or [])
        if not gam_cols:
            raise ValueError("gam requires gam_columns")
        # h2o-py allows nested lists for multivariate splines; flatten singles
        gam_cols = [c[0] if isinstance(c, (list, tuple)) else c for c in gam_cols]
        yvec = train.vec(y)
        problem, nclass, domain = response_info(yvec)
        family = p.get("family", "AUTO")
        if family == "AUTO":
            family = "binomial" if problem == "binomial" else "gaussian"

        lin_x = [c for c in x if c not in gam_cols]
        dinfo = DataInfo(train, lin_x, standardize=bool(p.get("standardize", False)))
        parts = []
        if lin_x:
            parts.append(dinfo.fit_transform(train))
        else:
            dinfo.fit_transform(train)

        def _per_col(val, default, name):
            if val is None:
                return [default] * len(gam_cols)
            if np.isscalar(val):
                return [val] * len(gam_cols)
            val = list(val)
            if len(val) == 1:
                return val * len(gam_cols)
            if len(val) != len(gam_cols):
                raise ValueError(
                    f"gam: {name} has {len(val)} entries for "
                    f"{len(gam_cols)} gam_columns"
                )
            return val

        nks = _per_col(p.get("num_knots"), 10, "num_knots")
        scales = _per_col(p.get("scale"), 1.0, "scale")
        gam_spec = []
        pen_blocks = []  # (offset, S·scale)
        off = parts[0].shape[1] if parts else 0
        for col, k, sc in zip(gam_cols, nks, scales):
            v = train.vec(col).numeric_np()
            knots = np.unique(np.quantile(v[~np.isnan(v)], np.linspace(0, 1, max(int(k), 3))))
            B = _spline_basis(np.nan_to_num(v), knots)
            center = B.mean(axis=0)
            Bc = (B - center).astype(np.float32)
            gam_spec.append((col, knots, center))
            # normalize S to the Gram block's scale so `scale` is a relative
            # smoothing knob (the reference normalizes its penalty similarly)
            Sk = _second_diff_penalty(Bc.shape[1])
            rel = float((Bc**2).sum()) / max(np.trace(Sk), 1e-12)
            pen_blocks.append((off, Sk * rel * 1e-3 * float(sc)))
            off += Bc.shape[1]
            parts.append(Bc)
        X = np.concatenate(parts, axis=1)
        n, pdim = X.shape
        Xi = np.concatenate([X, np.ones((n, 1), np.float32)], axis=1)

        if family == "binomial":
            yarr = (np.asarray(yvec.data, np.float32) if yvec.type == "enum"
                    else yvec.numeric_np().astype(np.float32))
        else:
            yarr = yvec.numeric_np().astype(np.float32)
        wcol = p.get("weights_column")
        w = (train.vec(wcol).numeric_np() if wcol else np.ones(n)).astype(np.float32)

        # penalty matrix over the full (p+1) system
        S = np.zeros((pdim + 1, pdim + 1))
        for o, Sk in pen_blocks:
            m = Sk.shape[0]
            S[o : o + m, o : o + m] += Sk
        lam = p.get("lambda_")
        ridge = float(lam[0] if isinstance(lam, (list, tuple)) else (lam or 0.0))
        if ridge > 0:
            S[np.arange(pdim), np.arange(pdim)] += ridge * n

        Xd, yd, wd = jnp.asarray(Xi), jnp.asarray(yarr), jnp.asarray(w)
        beta = np.zeros(pdim + 1)
        for it in range(int(p.get("max_iterations", 50))):
            gram, xy = _gram_step(Xd, yd, wd, jnp.asarray(beta, jnp.float32), family)
            A = np.asarray(gram, np.float64) + S
            try:
                nb = np.linalg.solve(A + 1e-8 * np.eye(pdim + 1), np.asarray(xy, np.float64))
            except np.linalg.LinAlgError:
                nb = np.linalg.lstsq(A, np.asarray(xy, np.float64), rcond=None)[0]
            delta = np.max(np.abs(nb - beta))
            beta = nb
            if delta < float(p.get("beta_epsilon", 1e-4)):
                break
            if family == "gaussian":
                break

        model = GAMModel(self, x, y, dinfo, family, beta, domain, gam_spec)
        model.training_metrics = model._make_metrics(train)
        if valid is not None:
            model.validation_metrics = model._make_metrics(valid)
        return model

    def _cv_predict(self, model: GAMModel, frame: Frame) -> np.ndarray:
        return model._score(frame)


GAM = H2OGeneralizedAdditiveEstimator
