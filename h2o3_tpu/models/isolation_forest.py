"""H2OIsolationForestEstimator — anomaly detection.

Reference parity: `h2o-algos/src/main/java/hex/tree/isofor/IsolationForest.java`
(+ `isoforextended/`): trees isolate rows by random (feature, threshold)
splits on subsamples; the anomaly score is the normalized mean path length
(`IsolationForestModel.score0`). Built on the same static-heap tree arrays
as GBM (`models/tree.py`), with random splits instead of gain search —
growing each random tree is a tiny jitted partition program.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..frame.binning import build_bins
from ..frame.frame import Frame
from .metrics import ModelMetricsBase
from .model_base import H2OEstimator, H2OModel
from .shared_tree import frame_to_matrix
from . import tree as treelib


def _avg_path_length(n: float) -> float:
    """c(n) from the IF paper — expected path length of an unsuccessful BST
    search (used to normalize scores, IsolationForestModel)."""
    if n <= 1:
        return 0.0
    h = np.log(n - 1) + 0.5772156649
    return 2.0 * h - 2.0 * (n - 1) / n


def forest_path_lengths(trees, X: np.ndarray, max_depth: int) -> np.ndarray:
    """Mean path length per row over the forest (shared by the live model and
    the MOJO scorer). `trees` = iterable of (feat, thr, is_split, leaf_n)."""
    n = X.shape[0]
    total = np.zeros(n)
    ntrees = 0
    for feat, thr, split, leaf_n in trees:
        ntrees += 1
        node = np.zeros(n, np.int64)
        depth = np.zeros(n)
        for _ in range(max_depth):
            s = split[node]
            xv = X[np.arange(n), feat[node]]
            right = np.isnan(xv) | (xv > thr[node])
            child = 2 * node + 1 + (right & s).astype(np.int64)
            depth = depth + s.astype(np.float64)
            node = np.where(s, child, node)
        # add c(leaf size): unresolved subtree correction
        ln = leaf_n[node]
        with np.errstate(divide="ignore", invalid="ignore"):
            corr = np.where(
                ln > 1,
                2.0 * (np.log(np.maximum(ln - 1, 1)) + 0.5772156649)
                - 2.0 * (ln - 1) / np.maximum(ln, 1),
                0.0,
            )
        total += depth + corr
    return total / max(ntrees, 1)


def anomaly_scores(path_lengths: np.ndarray, sample_size: int) -> np.ndarray:
    c = _avg_path_length(sample_size)
    return np.power(2.0, -path_lengths / max(c, 1e-12))


class IsolationForestModel(H2OModel):
    algo = "isolationforest"

    def __init__(self, params, x, trees, sample_size, max_depth):
        super().__init__(params)
        self.x = list(x)
        self.y = None
        self.trees = trees  # list of (feat (T,), thr (T,), is_split (T,))
        self.sample_size = sample_size
        self.max_depth = max_depth

    def _path_lengths(self, X: np.ndarray) -> np.ndarray:
        return forest_path_lengths(self.trees, X, self.max_depth)

    def predict(self, test_data: Frame) -> Frame:
        X, _, _ = frame_to_matrix(test_data, self.x)
        pl = self._path_lengths(X)
        score = anomaly_scores(pl, self.sample_size)
        return Frame.from_dict({"predict": score, "mean_length": pl})

    def _make_metrics(self, frame: Frame):
        return ModelMetricsBase(nobs=frame.nrow)


class H2OIsolationForestEstimator(H2OEstimator):
    algo = "isolationforest"
    supervised = False
    _param_defaults = dict(
        ntrees=50,
        max_depth=8,
        sample_size=256,
        sample_rate=-1.0,
        mtries=-1,
        contamination=-1.0,
        score_tree_interval=0,
    )

    def _fit(self, x, y, train: Frame, valid: Optional[Frame]) -> IsolationForestModel:
        p = self._parms
        seed = p["_actual_seed"]
        X, _, _ = frame_to_matrix(train, x)
        n, F = X.shape
        rng = np.random.default_rng(seed)
        sample_size = int(p.get("sample_size", 256))
        if p.get("sample_rate", -1.0) and float(p.get("sample_rate", -1.0)) > 0:
            sample_size = max(2, int(float(p["sample_rate"]) * n))
        sample_size = min(sample_size, n)
        D = int(p.get("max_depth", 8))
        T = treelib.heap_size(D)
        ntrees = int(p.get("ntrees", 50))

        lo = np.nanmin(X, axis=0)
        hi = np.nanmax(X, axis=0)
        trees = []
        for t in range(ntrees):
            idx = rng.choice(n, sample_size, replace=False)
            Xs = X[idx]
            feat = np.zeros(T, np.int64)
            thr = np.zeros(T)
            split = np.zeros(T, bool)
            leaf_n = np.zeros(T)
            # iterative random splitting over the static heap
            members = {0: np.arange(sample_size)}
            for node in range(T):
                rows = members.get(node)
                if rows is None:
                    leaf_n[node] = 0
                    continue
                leaf_n[node] = len(rows)
                depth = int(np.floor(np.log2(node + 1)))
                if depth >= D or len(rows) <= 1:
                    continue
                f = rng.integers(0, F)
                col = Xs[rows, f]
                cmin, cmax = np.nanmin(col), np.nanmax(col)
                if not np.isfinite(cmin) or cmin >= cmax:
                    continue
                cut = rng.uniform(cmin, cmax)
                feat[node] = f
                thr[node] = cut
                split[node] = True
                right = np.isnan(col) | (col > cut)
                members[2 * node + 1] = rows[~right]
                members[2 * node + 2] = rows[right]
            trees.append((feat, thr, split, leaf_n))

        model = IsolationForestModel(self, x, trees, sample_size, D)
        model.training_metrics = ModelMetricsBase(nobs=n)
        scores = model.predict(train).vec("predict").numeric_np()
        model.training_metrics.description = f"mean_score={scores.mean():.4f}"
        return model


IsolationForest = H2OIsolationForestEstimator
