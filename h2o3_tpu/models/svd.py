"""H2OSingularValueDecompositionEstimator — truncated SVD.

Reference parity: `h2o-algos/src/main/java/hex/svd/SVD.java`
(`svd_method` ∈ {GramSVD, Power, Randomized}; outputs `d`, `v`, optional `u`
frame when `keep_u`). Estimator surface `h2o-py/h2o/estimators/svd.py`.

GramSVD — the reference default — maps cleanly to TPU: the p×p Gram `X'X`
is one einsum over row-sharded data (XLA inserts the psum, replacing
`hex/gram/Gram.java`'s MRTask), then a tiny host eigendecomposition; the
Power method iterates `v ← X'Xv` on device instead.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..frame.frame import Frame
from .metrics import ModelMetricsBase
from .model_base import DataInfo, H2OEstimator, H2OModel


class SVDModel(H2OModel):
    algo = "svd"

    def __init__(self, params, x, dinfo, d, v, u):
        super().__init__(params)
        self.x = list(x)
        self.y = None
        self.dinfo = dinfo
        self.d = d        # (k,) singular values
        self.v = v        # (p, k) right singular vectors
        self.u = u        # (n, k) left singular vectors or None

    @property
    def u_frame(self) -> Optional[Frame]:
        if self.u is None:
            return None
        return Frame.from_dict({f"u{i+1}": self.u[:, i] for i in range(self.u.shape[1])})

    def predict(self, test_data: Frame) -> Frame:
        """Project new rows onto the right singular vectors: X v / d (= u)."""
        X = self.dinfo.transform(test_data)
        scores = (X @ self.v) / np.maximum(self.d[None, :], 1e-300)
        return Frame.from_dict({f"u{i+1}": scores[:, i] for i in range(scores.shape[1])})

    def _make_metrics(self, frame: Frame):
        return self.training_metrics


class H2OSingularValueDecompositionEstimator(H2OEstimator):
    algo = "svd"
    supervised = False
    _param_defaults = dict(
        nv=1,
        transform="NONE",
        svd_method="GramSVD",
        max_iterations=1000,
        use_all_factor_levels=True,
        keep_u=True,
    )

    def _fit(self, x, y, train: Frame, valid: Optional[Frame]) -> SVDModel:
        p = self._parms
        transform = p.get("transform", "NONE")
        standardize = transform in ("STANDARDIZE", "NORMALIZE")
        dinfo = DataInfo(
            train, x, standardize=standardize,
            use_all_factor_levels=bool(p.get("use_all_factor_levels", True)),
        )
        X = dinfo.fit_transform(train)
        if transform == "DEMEAN":
            X = X - X.mean(axis=0)
        elif transform == "DESCALE":
            sd = X.std(axis=0)
            X = X / np.where(sd < 1e-10, 1.0, sd)
        n, pdim = X.shape
        k = min(int(p.get("nv", 1)), pdim)
        method = p.get("svd_method", "GramSVD")
        Xd = jnp.asarray(X)

        if method == "Power":
            # power iteration with deflation: v ← X'(Xv), normalized each step
            gram_mv = jax.jit(lambda X, v: X.T @ (X @ v))
            V = np.zeros((pdim, k))
            d2 = np.zeros(k)
            rng = np.random.default_rng(p["_actual_seed"])
            for j in range(k):
                v = rng.normal(size=pdim)
                v /= np.linalg.norm(v)
                for _ in range(int(p.get("max_iterations", 1000))):
                    w = np.asarray(gram_mv(Xd, jnp.asarray(v, jnp.float32)), np.float64)
                    w -= V[:, :j] @ (V[:, :j].T @ w)  # deflate previous vectors
                    nw = np.linalg.norm(w)
                    if nw < 1e-300:
                        break
                    wn = w / nw
                    if np.abs(wn @ v) > 1 - 1e-9:
                        v = wn
                        break
                    v = wn
                V[:, j] = v
                d2[j] = v @ np.asarray(gram_mv(Xd, jnp.asarray(v, jnp.float32)), np.float64)
            evecs, evals = V, np.maximum(d2, 0)
        elif method == "Randomized":
            rng = np.random.default_rng(p["_actual_seed"])
            om = jnp.asarray(rng.normal(size=(pdim, min(k + 10, pdim))).astype(np.float32))
            Y = np.asarray(jax.jit(lambda X, om: X @ om)(Xd, om), np.float64)
            Q, _ = np.linalg.qr(Y)
            B = np.asarray(jax.jit(lambda X, Q: Q.T @ X)(Xd, jnp.asarray(Q, jnp.float32)))
            _, s, Vt = np.linalg.svd(B, full_matrices=False)
            evecs = Vt[:k].T
            evals = s[:k] ** 2
        else:  # GramSVD
            gram = np.asarray(jax.jit(lambda X: X.T @ X)(Xd), np.float64)
            ev, evec = np.linalg.eigh(gram)
            order = np.argsort(-ev)
            evals = np.maximum(ev[order][:k], 0)
            evecs = evec[:, order][:, :k]

        # deterministic sign (largest |loading| positive) — matches PCA
        for j in range(evecs.shape[1]):
            i = np.abs(evecs[:, j]).argmax()
            if evecs[i, j] < 0:
                evecs[:, j] = -evecs[:, j]

        d = np.sqrt(evals)
        u = None
        if bool(p.get("keep_u", True)):
            u = np.asarray(jax.jit(lambda X, V: X @ V)(Xd, jnp.asarray(evecs, jnp.float32)),
                           np.float64) / np.maximum(d[None, :], 1e-300)
        model = SVDModel(self, x, dinfo, d, evecs, u)
        model.training_metrics = ModelMetricsBase(nobs=n)
        return model


SVD = H2OSingularValueDecompositionEstimator
