"""Dataset-artifact cache — bin/pack/transfer a training frame ONCE per sweep.

Every tree fit runs the same prep pipeline over its training frame:
`frame_to_matrix` (Frame → float64 matrix), `build_bins` (quantize to bin
codes), sub-byte bit-packing and the H2D upload of the code matrix. A grid
sweep or AutoML run repeats that per candidate even though every candidate
shares ONE (frame, x) pair — exactly the waste XGBoost's `gpu_hist` avoids
by quantizing once and reusing the compressed binned matrix across all
boosting work ("XGBoost: Scalable GPU Accelerated Learning", PAPERS.md).

This module is the sweep-level analog: a fingerprinted multi-layer cache

- **matrix**: key(frame, x) → (X float64, is_categorical, domains)
- **bins**: + (nbins, histogram_type[, seed for Random]) → `BinnedMatrix`
- **device**: + (npad rows, pack mode, shard layout) → the device-resident
  packed code matrix, so repeat candidates skip the pack + upload
  entirely. On a single-process multi-device cloud the artifact is the
  row-sharded jax.Array itself (per-shard placement reused across the
  sweep, ISSUE 12). Multi-process POD fits cache their global row-sharded
  array too (ISSUE 18): the canonical row exchange runs eagerly inside the
  fit, so the cached builder is collective-free (a per-rank hit/miss
  divergence can never strand a rank in a collective) and each rank's
  entry accounts only its local shards' bytes.
- **std**: + a caller-supplied standardization key (standardize /
  use_all_factor_levels / impute / intercept / pad grid, see
  `models/estimator_engine.py`) → the standardized float design matrix
  the non-tree estimators iterate on — the fitted `DataInfo` plus either
  the host float32 matrix or the device-resident (possibly row-sharded)
  design array (ISSUE 15). GLM, K-Means, PCA, GLRM and DeepLearning —
  and every CV fold and sweep candidate sharing a frame — reuse ONE
  upload instead of re-extracting and re-uploading per fit.

Fingerprint: frame identity (id + DKV key + a weakref guard), row count,
the frame's in-place mutation counter (`Frame._touch` bumps it), the x
column list, and each column's Vec/buffer identity — replacing a column or
mutating the frame invalidates, while Rapids-style functional ops produce
new frames (new ids) naturally.

Eviction: LRU over entries with both an entry cap
(``H2O3_DATASET_CACHE_ENTRIES``, default 4) and a byte budget
(``H2O3_DATASET_CACHE_MB``, default 1024, host+device bytes). Dead frames
drop their entries via weakref callback. ``H2O3_DATASET_CACHE=0`` (or the
bench comparator ``H2O3_TRAIN_LEGACY=1``) disables caching entirely.

Stats (hits/misses/evictions per layer) feed ``GET /3/Training/metrics``.
"""

from __future__ import annotations

import os
import threading
import weakref
from collections import OrderedDict
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from ..runtime import memory_ledger as _memory

_LOCK = threading.RLock()
_ENTRIES: "OrderedDict[tuple, _Entry]" = OrderedDict()
_STATS = dict(matrix_hits=0, matrix_misses=0, bins_hits=0, bins_misses=0,
              device_hits=0, device_misses=0, blocks_hits=0,
              blocks_misses=0, std_hits=0, std_misses=0, evictions=0)


def enabled() -> bool:
    if os.environ.get("H2O3_DATASET_CACHE", "1") in ("0", "false", "no"):
        return False
    from ..runtime import trainpool

    return not trainpool.legacy()


def _caps() -> Tuple[int, int]:
    """(max entries, max bytes) — read per call so tests can env-tune."""
    ents = int(os.environ.get("H2O3_DATASET_CACHE_ENTRIES", 4))
    mb = float(os.environ.get("H2O3_DATASET_CACHE_MB", 1024))
    return max(ents, 1), int(mb * 1e6)


class _StdArtifact:
    """One cached standardized-design artifact (ISSUE 15): the fitted
    DataInfo-equivalent `aux` plus the matrix itself (host np.ndarray or a
    device jax.Array — `space` says which side of the link the bytes live
    on, for the ledger's host/device split)."""

    __slots__ = ("value", "_nbytes", "space")

    def __init__(self, value, nbytes: int, space: str = "host"):
        self.value = value
        self._nbytes = int(nbytes)
        self.space = space

    def nbytes(self) -> int:
        return self._nbytes


class _Entry:
    __slots__ = ("frame_ref", "key", "matrix", "bins", "device", "blocks",
                 "std", "lock", "owner_base", "__weakref__")

    def __init__(self, frame, key):
        self.frame_ref = weakref.ref(frame, lambda _: _drop(key))
        self.key = key
        self.matrix = None                      # (X, is_cat, doms)
        self.bins: Dict[tuple, object] = {}     # bkey -> BinnedMatrix
        self.device: Dict[tuple, object] = {}   # (bkey, npad) -> jax array
        self.blocks: Dict[tuple, object] = {}   # (bkey, npad, ...) -> BlockStore
        self.std: Dict[tuple, _StdArtifact] = {}  # skey -> _StdArtifact
        self.lock = threading.Lock()            # serializes builds per entry
        self.owner_base = ""                    # memory-ledger owner prefix

    def nbytes(self) -> int:
        total = 0
        if self.matrix is not None:
            total += int(self.matrix[0].nbytes)
        for bm in self.bins.values():
            total += int(bm.codes.nbytes)
        for arr in self.device.values():
            total += _arr_nbytes(arr)
        for st in self.blocks.values():
            total += int(st.nbytes_total())
        for art in self.std.values():
            total += art.nbytes()
        return total


_LAYERS = ("matrix", "bins", "device", "blocks", "std")


def _arr_nbytes(arr) -> int:
    """Per-PROCESS resident bytes of a cached device artifact: a pod fit's
    global row-sharded array holds only this rank's shards locally, and the
    per-rank ledger/caps must see that 1/N footprint (ISSUE 18)."""
    try:
        if getattr(arr, "is_fully_addressable", True) is False:
            return sum(int(s.data.nbytes) for s in arr.addressable_shards)
    except Exception:
        pass
    return int(np.prod(arr.shape)) * arr.dtype.itemsize


def _register_ledger(e: "_Entry", frame) -> None:
    """Memory-ledger owners for one cache entry: `dataset_cache:<fp>:<layer>`
    per layer, byte callbacks through a weakref (the ledger must never pin
    an evicted entry alive), referent = the owning frame."""
    from ..runtime import memory_ledger as ml

    e.owner_base = f"dataset_cache:{ml.fingerprint(e.key)}"
    wr = weakref.ref(e)

    def _layer_fn(layer):
        def _bytes():
            ent = wr()
            if ent is None:
                return (0, 0)
            return ml.measure(getattr(ent, layer))
        return _bytes

    for layer in _LAYERS:
        ml.register(f"{e.owner_base}:{layer}", kind="dataset_cache",
                    bytes_fn=_layer_fn(layer), referent=frame,
                    type_name=layer)


def _release_entry(e: "_Entry", trigger: str) -> None:
    """Unregister an entry's ledger owners + emit ONE eviction event with
    the bytes actually freed and why (cap/pressure/weakref/clear) — cache
    thrash becomes visible in /3/Timeline and /3/Trace instead of silent."""
    if not e.owner_base:
        return
    from ..runtime import memory_ledger as ml

    try:
        freed = e.nbytes()
    except Exception:
        freed = 0
    ml.record_event("evict", e.owner_base, freed, trigger=trigger,
                    kind="dataset_cache",
                    space="device" if e.device else "host")
    for layer in _LAYERS:
        ml.unregister(f"{e.owner_base}:{layer}")
    # close block stores so their spill FILES go with the entry — a
    # dropped entry that left files behind would (correctly) surface as a
    # `<owner>:spill` leak, but the cache releasing an entry is the
    # orderly path, not the leak
    for st in list(e.blocks.values()):
        try:
            st.close()
        except Exception:
            pass


def _drop(key) -> None:
    with _LOCK:
        e = _ENTRIES.pop(key, None)
    if e is not None:
        try:
            _release_entry(e, "weakref")
        except Exception:
            # interpreter teardown: a frame dying at exit fires this
            # weakref callback after module globals are gone — nothing
            # left to account to
            pass


def _frame_key(frame, x: Tuple[str, ...]) -> tuple:
    cols = tuple(
        (n, id(v), id(v.data) if getattr(v, "data", None) is not None else 0)
        for n, v in ((n, frame.vec(n)) for n in x))
    return (id(frame), frame.key, int(frame.nrow),
            int(getattr(frame, "_version", 0)), x, cols)


def _entry_for(frame, x: Tuple[str, ...]) -> "_Entry":
    key = _frame_key(frame, x)
    with _LOCK:
        e = _ENTRIES.get(key)
        if e is not None and e.frame_ref() is frame:
            _ENTRIES.move_to_end(key)
            return e
        e = _ENTRIES[key] = _Entry(frame, key)
        _register_ledger(e, frame)
        _evict_locked(keep=key)
        return e


def _pop_entry_locked(key, trigger: str) -> None:
    e = _ENTRIES.pop(key, None)
    if e is None:
        return
    _STATS["evictions"] += 1
    _release_entry(e, trigger)


def _evict_locked(keep=None) -> None:
    """LRU-evict entries other than `keep` until both caps are met, then
    keep shedding while the memory ledger reports pressure above
    `H2O3_MEM_EVICT_PRESSURE` (the byte-side twin of admission shedding)."""
    # Iterate snapshots: _LOCK is reentrant, so a frame's weakref death
    # callback (_drop) triggered by GC mid-iteration in THIS thread can pop
    # from _ENTRIES even while we hold the lock.
    max_entries, max_bytes = _caps()
    victims = [k for k in list(_ENTRIES) if k != keep]
    while victims and len(_ENTRIES) > max_entries:
        _pop_entry_locked(victims.pop(0), "cap")
    while victims and sum(e.nbytes() for e in list(_ENTRIES.values())) > max_bytes:
        _pop_entry_locked(victims.pop(0), "cap")
    from ..runtime import qos as _qos

    # ONE pressure snapshot decides — qos.pressure_view(), the same view
    # serving admission reads, so shed-serving can never be true here
    # while evict-training-artifacts is false (pressure is RSS/HBM-budget
    # dominated — it cannot drop mid-loop just because entries were
    # unregistered, so re-reading per victim would only burn a full
    # accounting pass under _LOCK per pop): past the threshold, DEVICE
    # blocks shed FIRST (ISSUE 14 — a shed block keeps its host copy and
    # costs only a re-upload, the cheapest byte to give back), then HOST
    # blocks spill to disk (round 19 — the spilled copy is kept, so a
    # re-shed is free and only a restore pays a read), then every LRU
    # victim entry, oldest first — training artifacts always go before
    # serving sheds (the eviction threshold sits below the serving one)
    if (victims or any(e.blocks for e in list(_ENTRIES.values()))) \
            and _qos.pressure_view().evict_cache:
        for e in list(_ENTRIES.values()):
            for st in list(e.blocks.values()):
                st.shed(trigger="pressure")
        for e in list(_ENTRIES.values()):
            for st in list(e.blocks.values()):
                try:
                    st.shed_host(trigger="pressure")
                except Exception:
                    pass
        while victims:
            _pop_entry_locked(victims.pop(0), "pressure")


def _bins_key(nbins: int, histogram_type: str, seed) -> tuple:
    ht = "UniformAdaptive" if histogram_type in ("AUTO", None) \
        else str(histogram_type)
    # only Random binning draws from the seed; other types share across seeds
    return (int(nbins), ht, int(seed) if ht == "Random" else None)


def matrix(frame, x, builder: Callable[[], tuple]):
    """(X, is_categorical, domains) for (frame, x) — cached."""
    e = _entry_for(frame, tuple(x))
    with e.lock:
        if e.matrix is not None:
            with _LOCK:
                _STATS["matrix_hits"] += 1
            return e.matrix
        with _LOCK:
            _STATS["matrix_misses"] += 1
        built = builder()
        # publish under _LOCK: nbytes()/snapshot() iterate entry dicts
        # holding only _LOCK, so mutations must not race them (lock order
        # is always entry.lock → _LOCK, never reversed)
        with _LOCK:
            e.matrix = built
        _memory.record_event("alloc", f"{e.owner_base}:matrix",
                             int(built[0].nbytes), trigger="miss",
                             kind="dataset_cache")
    with _LOCK:
        _evict_locked(keep=e.key)
    return e.matrix


def bins(frame, x, nbins: int, histogram_type: str, seed,
         builder: Callable[[], object]):
    """`BinnedMatrix` for (frame, x, nbins, histogram_type) — cached."""
    e = _entry_for(frame, tuple(x))
    bkey = _bins_key(nbins, histogram_type, seed)
    with e.lock:
        bm = e.bins.get(bkey)
        if bm is not None:
            with _LOCK:
                _STATS["bins_hits"] += 1
            return bm
        with _LOCK:
            _STATS["bins_misses"] += 1
        bm = builder()
        with _LOCK:   # see matrix(): publish vs nbytes()/snapshot() races
            e.bins[bkey] = bm
        _memory.record_event("alloc", f"{e.owner_base}:bins",
                             int(bm.codes.nbytes), trigger="miss",
                             kind="dataset_cache")
    with _LOCK:
        _evict_locked(keep=e.key)
    return bm


def device_codes(frame, x, nbins: int, histogram_type: str, seed, npad: int,
                 builder: Callable[[], object], pack_bits: int = 0,
                 n_devices: int = 1):
    """Device-resident (padded) code matrix — cached so repeat candidates
    skip the pack + H2D upload. With `pack_bits` > 0 the cached artifact
    is the `ops.packing` packed word matrix (2-4× smaller resident HBM,
    ISSUE 7); with `n_devices` > 1 it is the ROW-SHARDED jax.Array over
    the 1-D hosts mesh (ISSUE 12) — each shard resident on its chip,
    padded to the mesh multiple by the caller. The packing mode and the
    shard layout are part of the key, so packed vs full-width consumers
    (e.g. a legacy-flag comparator run) and 1-device vs N-shard consumers
    never share an entry. `builder` does the pack/upload/placement and
    its own byte accounting on a miss."""
    e = _entry_for(frame, tuple(x))
    dkey = (_bins_key(nbins, histogram_type, seed), int(npad),
            int(pack_bits), int(n_devices))
    with e.lock:
        arr = e.device.get(dkey)
        if arr is not None:
            with _LOCK:
                _STATS["device_hits"] += 1
            return arr
        with _LOCK:
            _STATS["device_misses"] += 1
        arr = builder()
        with _LOCK:   # see matrix(): publish vs nbytes()/snapshot() races
            e.device[dkey] = arr
        _memory.record_event(
            "alloc", f"{e.owner_base}:device", _arr_nbytes(arr),
            trigger="miss", kind="dataset_cache", space="device")
    with _LOCK:
        _evict_locked(keep=e.key)
    return arr


def blocked_codes(frame, x, nbins: int, histogram_type: str, seed, npad: int,
                  builder: Callable[[], object], pack_bits: int = 0,
                  n_blocks: int = 0):
    """Row-BLOCKED packed code artifact (a `models.block_store.BlockStore`)
    — the out-of-core materialization of `device_codes` (ISSUE 14): packed
    sub-byte blocks live on host, a bounded LRU resident set lives on
    device, and the whole store is accounted through this entry's
    ``dataset_cache:<fp>:blocks`` ledger layer (the store itself does not
    register a second owner). Cached per (bins key, npad, pack mode, block
    grid) so a sweep's candidates share ONE blocked pack; the block grid
    aligns with the PR 9 shard layout, so a later sharded consumer shares
    block boundaries. `builder` packs the blocks on a miss."""
    e = _entry_for(frame, tuple(x))
    dkey = (_bins_key(nbins, histogram_type, seed), int(npad),
            int(pack_bits), int(n_blocks))
    with e.lock:
        st = e.blocks.get(dkey)
        if st is not None:
            with _LOCK:
                _STATS["blocks_hits"] += 1
            return st
        with _LOCK:
            _STATS["blocks_misses"] += 1
        st = builder()
        with _LOCK:   # see matrix(): publish vs nbytes()/snapshot() races
            e.blocks[dkey] = st
        _memory.record_event("alloc", f"{e.owner_base}:blocks",
                             int(st.host_bytes()), trigger="miss",
                             kind="dataset_cache")
    with _LOCK:
        _evict_locked(keep=e.key)
    return st


def std_artifact(frame, x, skey: tuple, builder: Callable[[], tuple]):
    """Standardized-design artifact for (frame, x, skey) — cached (ISSUE
    15). `skey` carries the standardization/impute/expansion parameters
    (composed by `models/estimator_engine.py` — the ONE place the key
    layout lives); `builder` returns ``(value, nbytes, space)`` on a miss,
    where `value` is whatever the engine wants back (typically a
    ``(DataInfo, matrix)`` pair) and `space` is ``"host"`` or ``"device"``
    for the ledger's split. Every estimator fit and CV fold sharing the
    (frame, x, params) triple then reuses one extraction + one upload."""
    e = _entry_for(frame, tuple(x))
    skey = tuple(skey)
    with e.lock:
        art = e.std.get(skey)
        if art is not None:
            with _LOCK:
                _STATS["std_hits"] += 1
            return art.value
        with _LOCK:
            _STATS["std_misses"] += 1
        value, nbytes, space = builder()
        art = _StdArtifact(value, nbytes, space)
        with _LOCK:   # see matrix(): publish vs nbytes()/snapshot() races
            e.std[skey] = art
        _memory.record_event("alloc", f"{e.owner_base}:std", int(nbytes),
                             trigger="miss", kind="dataset_cache",
                             space=space)
    with _LOCK:
        _evict_locked(keep=e.key)
    return art.value


def snapshot() -> Dict:
    with _LOCK:
        stats = dict(_STATS)
        entries = len(_ENTRIES)
        nbytes = sum(e.nbytes() for e in list(_ENTRIES.values()))
    stats.update(entries=entries, bytes=int(nbytes), enabled=enabled())
    return stats


def clear() -> None:
    """Drop every entry (tests / explicit memory release)."""
    with _LOCK:
        doomed = list(_ENTRIES.values())
        _ENTRIES.clear()
    for e in doomed:
        _release_entry(e, "clear")


def reset_stats() -> None:
    with _LOCK:
        for k in _STATS:
            _STATS[k] = 0
