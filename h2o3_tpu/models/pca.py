"""H2OPrincipalComponentAnalysisEstimator (+ SVD) — dimensionality reduction.

Reference parity: `h2o-algos/src/main/java/hex/pca/PCA.java`
(`pca_method` ∈ {GramSVD, Power, GLRM, Randomized}) and `hex/svd/SVD.java`.
GramSVD — the reference default — is exactly the TPU-friendly path: the
(p×p) Gram `X'X` is one device program over the cached standardized matrix
(ISSUE 15: blocked `ordered_axis_fold` partials under the estimator shard
plan, so an N-device Gram is bit-identical to the 1-device forced-shard
lane), then a tiny host-side f64 eigendecomposition of the p×p result —
ONE D2H per fit, not one per step. Randomized projection (Halko) runs as
ONE jitted power-iteration program (sketch → q subspace iterations with
on-device QR → on-device SVD of the small B), replacing the former
host-QR/host-SVD round-trips. ``H2O3_EST_LEGACY=1`` restores the seed
paths; multi-process clouds stay on them.
"""

from __future__ import annotations

import os
import time
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from ..frame.frame import Frame
from ..parallel import distdata
from ..parallel import mesh as cloudlib
from . import estimator_engine as _est
from .metrics import ModelMetricsBase
from .model_base import DataInfo, H2OEstimator, H2OModel


def _gram_fn(cloud, shard_mode: str, n_shards: int):
    """(X, w, mu) → (X−μ)'diag(w)... the centered masked Gram as ONE
    device program: `Xc = (X − μ)·w` (w is the real-row mask — zero pad
    rows must not contribute −μ terms), reduced as `local_blocks` ordered
    block partials under the shard plan. "off" mode computes the plain
    `Xc.T @ Xc` the legacy path jitted, bit-comparable."""
    local_blocks, axis = _est.local_plan(cloud, shard_mode, n_shards)
    key = ("pca_gram", local_blocks, axis)

    def build():
        def inner(X, w, mu):
            Xc = (X - mu[None, :]) * w[:, None]
            if local_blocks:
                sl = _est.block_slices(X.shape[0], local_blocks)
                parts = jnp.stack([Xc[s].T @ Xc[s] for s in sl])
                return _est.fold_blocks(parts, axis)
            return Xc.T @ Xc

        if axis is not None:
            rspec = P(cloudlib.ROWS_AXIS)
            inner = cloudlib.shard_call(
                inner, cloud, in_specs=(rspec, rspec, P()),
                out_specs=P(), check_rep=False)
        return jax.jit(inner)

    return _est.cached_program(cloud, key, build)


def _randomized_fn(cloud, q: int):
    """Halko randomized subspace iteration as ONE device program: sketch
    `Y = Xc @ Ω`, q power iterations `Y ← Xc (Xc' Q)` with on-device QR
    re-orthonormalization, then the SVD of the small `B = Q' Xc` — no host
    QR/SVD round-trip per step (ISSUE 15). Zero pad rows produce zero Q
    rows and drop out of B exactly."""

    def build():
        def inner(X, w, mu, Om):
            Xc = (X - mu[None, :]) * w[:, None]
            Y = Xc @ Om
            for _ in range(q):
                Q, _ = jnp.linalg.qr(Y)
                Y = Xc @ (Xc.T @ Q)
            Q, _ = jnp.linalg.qr(Y)
            B = Q.T @ Xc
            _, s, Vt = jnp.linalg.svd(B, full_matrices=False)
            return s, Vt

        return jax.jit(inner)

    return _est.cached_program(cloud, ("pca_randomized", q), build)


class PCAModel(H2OModel):
    algo = "pca"

    def __init__(self, params, x, dinfo, eigenvectors, eigenvalues, k):
        super().__init__(params)
        self.x = list(x)
        self.y = None
        self.dinfo = dinfo
        self.eigenvectors = eigenvectors  # (p, k)
        self.eigenvalues = eigenvalues    # (k,) variances
        self.k = k

    @property
    def importance(self):
        ev = np.asarray(self.eigenvalues, np.float64)
        sd = np.sqrt(np.maximum(ev, 0))
        prop = ev / max(ev.sum(), 1e-300)
        return {
            "Standard deviation": sd,
            "Proportion of Variance": prop,
            "Cumulative Proportion": np.cumsum(prop),
        }

    def predict(self, test_data: Frame) -> Frame:
        X = self.dinfo.transform(test_data)
        if getattr(self, "_demean_mu", None) is not None:
            X = X - self._demean_mu
        scores = X @ np.asarray(self.eigenvectors)
        return Frame.from_dict({f"PC{i+1}": scores[:, i] for i in range(self.k)})

    transform = predict

    def _make_metrics(self, frame: Frame):
        return self.training_metrics


class H2OPrincipalComponentAnalysisEstimator(H2OEstimator):
    algo = "pca"
    supervised = False
    _param_defaults = dict(
        k=1,
        transform="NONE",
        pca_method="GramSVD",
        use_all_factor_levels=False,
        compute_metrics=True,
        impute_missing=True,
        max_iterations=1000,
    )

    def _fit(self, x, y, train: Frame, valid: Optional[Frame]) -> PCAModel:
        p = self._parms
        k = int(p.get("k", 1))
        transform = p.get("transform", "NONE")
        standardize = transform in ("STANDARDIZE", "NORMALIZE")
        use_all = bool(p.get("use_all_factor_levels", False))
        method = p.get("pca_method", "GramSVD")
        cloud = cloudlib.cloud()
        multiproc = distdata.multiprocess()
        engine_on = not _est.legacy() and not multiproc
        shard_mode, n_shards = (_est.shard_plan(cloud.size, multiproc)
                                if engine_on else ("off", 0))
        if shard_mode == "mesh" and (train.nrow < cloud.size
                                     or method == "Randomized"):
            # distributed QR is out of scope — a mesh cloud runs the
            # Randomized sketch on one device like the seed did
            shard_mode, n_shards = "off", 0

        if engine_on:
            from . import dataset_cache as _dc

            cache0 = _dc.snapshot() if _est.cache_enabled() else None
            ndev_eff = cloud.size if shard_mode == "mesh" else 1
            dinfo, X = _est.host_matrix(train, x, standardize=standardize,
                                        use_all=use_all)
            _, Xd = _est.device_matrix(train, x, standardize=standardize,
                                       use_all=use_all, n_shards=n_shards,
                                       n_devices=ndev_eff)
        else:
            cache0 = None
            ndev_eff = 1
            dinfo = DataInfo(train, x, standardize=standardize,
                             use_all_factor_levels=use_all)
            X = dinfo.fit_transform(train)
            Xd = None
        n, pdim = X.shape
        mu = (X.mean(axis=0).astype(np.float32) if transform == "DEMEAN"
              else np.zeros(pdim, np.float32))
        k = min(k, pdim)

        if not engine_on:
            if transform == "DEMEAN":
                X = X - mu
            Xd = jnp.asarray(X)
            if method in ("GramSVD", "GLRM", "Power"):
                gram = np.asarray(jax.jit(lambda X: X.T @ X)(Xd), np.float64) / max(n - 1, 1)
                evals, evecs = np.linalg.eigh(gram)
                order = np.argsort(-evals)
                evals = np.maximum(evals[order][:k], 0)
                evecs = evecs[:, order][:, :k]
            else:  # Randomized (Halko) — sketch on device, QR/SVD on host
                rng = np.random.default_rng(p["_actual_seed"])
                om = jnp.asarray(rng.normal(size=(pdim, min(k + 10, pdim))).astype(np.float32))
                Y = np.asarray(jax.jit(lambda X, om: X @ om)(Xd, om), np.float64)
                Q, _ = np.linalg.qr(Y)
                B = np.asarray(jax.jit(lambda X, Q: Q.T @ X)(Xd, jnp.asarray(Q, jnp.float32)))
                _, s, Vt = np.linalg.svd(B, full_matrices=False)
                evecs = Vt[:k].T
                evals = (s[:k] ** 2) / max(n - 1, 1)
            _est.record_fit("pca", "legacy", n_shards=0, n_devices=1,
                            method=method)
        else:
            npad = int(Xd.shape[0])
            w = np.zeros(npad, np.float32)
            w[:n] = 1.0
            wd = (jax.device_put(jnp.asarray(w), cloud.row_sharding())
                  if ndev_eff > 1 else jnp.asarray(w))
            mud = jnp.asarray(mu)
            t0 = time.perf_counter()
            if method in ("GramSVD", "GLRM", "Power"):
                fn = _gram_fn(cloud, shard_mode, n_shards)
                with _est.iter_phase():
                    gram_d = fn(Xd, wd, mud)
                    cloudlib.collective_fence(gram_d)
                    gram = np.asarray(gram_d, np.float64) / max(n - 1, 1)
                # p×p eigendecomposition on host in f64 — ONE tiny D2H,
                # exactly the legacy numerics
                evals, evecs = np.linalg.eigh(gram)
                order = np.argsort(-evals)
                evals = np.maximum(evals[order][:k], 0)
                evecs = evecs[:, order][:, :k]
                iters = None
            else:  # Randomized — one fused power-iteration program
                rng = np.random.default_rng(p["_actual_seed"])
                l = min(k + 10, pdim)
                om = jnp.asarray(
                    rng.normal(size=(pdim, l)).astype(np.float32))
                q = max(int(os.environ.get("H2O3_PCA_POWER_ITERS", "2")), 0)
                fn = _randomized_fn(cloud, q)
                with _est.iter_phase():
                    s_d, Vt_d = fn(Xd, wd, mud, om)
                    s = np.asarray(s_d, np.float64)
                    Vt = np.asarray(Vt_d, np.float64)
                evecs = Vt[:k].T
                evals = (s[:k] ** 2) / max(n - 1, 1)
                iters = q
            _est.record_fit(
                "pca",
                {"mesh": "fused_mesh", "blocks": "fused_blocks"}.get(
                    shard_mode, "fused"),
                iterations=iters,
                matrix_cache=(_est.matrix_cache_state(cache0)
                              if cache0 is not None else None),
                n_shards=n_shards, n_devices=ndev_eff, method=method,
                wall_s=time.perf_counter() - t0)

        # deterministic sign (largest |loading| positive)
        for j in range(evecs.shape[1]):
            i = np.abs(evecs[:, j]).argmax()
            if evecs[i, j] < 0:
                evecs[:, j] = -evecs[:, j]

        model = PCAModel(self, x, dinfo, evecs, evals, k)
        if transform == "DEMEAN":
            model._demean_mu = mu.astype(np.float64)
        model.training_metrics = ModelMetricsBase(nobs=n)
        return model


PCA = H2OPrincipalComponentAnalysisEstimator
