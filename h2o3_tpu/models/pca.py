"""H2OPrincipalComponentAnalysisEstimator (+ SVD) — dimensionality reduction.

Reference parity: `h2o-algos/src/main/java/hex/pca/PCA.java`
(`pca_method` ∈ {GramSVD, Power, GLRM, Randomized}) and `hex/svd/SVD.java`.
GramSVD — the reference default — is exactly the TPU-friendly path: the
(p×p) Gram `X'X` is one einsum over row-sharded data (psum inserted by XLA,
replacing the Gram MRTask of `hex/gram/Gram.java`), then a tiny host-side
eigendecomposition. Randomized projection (Halko) is provided for wide data.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..frame.frame import Frame
from .metrics import ModelMetricsBase
from .model_base import DataInfo, H2OEstimator, H2OModel


class PCAModel(H2OModel):
    algo = "pca"

    def __init__(self, params, x, dinfo, eigenvectors, eigenvalues, k):
        super().__init__(params)
        self.x = list(x)
        self.y = None
        self.dinfo = dinfo
        self.eigenvectors = eigenvectors  # (p, k)
        self.eigenvalues = eigenvalues    # (k,) variances
        self.k = k

    @property
    def importance(self):
        ev = np.asarray(self.eigenvalues, np.float64)
        sd = np.sqrt(np.maximum(ev, 0))
        prop = ev / max(ev.sum(), 1e-300)
        return {
            "Standard deviation": sd,
            "Proportion of Variance": prop,
            "Cumulative Proportion": np.cumsum(prop),
        }

    def predict(self, test_data: Frame) -> Frame:
        X = self.dinfo.transform(test_data)
        scores = X @ np.asarray(self.eigenvectors)
        return Frame.from_dict({f"PC{i+1}": scores[:, i] for i in range(self.k)})

    transform = predict

    def _make_metrics(self, frame: Frame):
        return self.training_metrics


class H2OPrincipalComponentAnalysisEstimator(H2OEstimator):
    algo = "pca"
    supervised = False
    _param_defaults = dict(
        k=1,
        transform="NONE",
        pca_method="GramSVD",
        use_all_factor_levels=False,
        compute_metrics=True,
        impute_missing=True,
        max_iterations=1000,
    )

    def _fit(self, x, y, train: Frame, valid: Optional[Frame]) -> PCAModel:
        p = self._parms
        k = int(p.get("k", 1))
        transform = p.get("transform", "NONE")
        standardize = transform in ("STANDARDIZE", "NORMALIZE")
        dinfo = DataInfo(
            train, x, standardize=standardize,
            use_all_factor_levels=bool(p.get("use_all_factor_levels", False)),
        )
        X = dinfo.fit_transform(train)
        n, pdim = X.shape
        if transform in ("DEMEAN", "DESCALE") or transform == "NONE":
            mu = X.mean(axis=0) if transform == "DEMEAN" else np.zeros(pdim)
            if transform == "DEMEAN":
                X = X - mu
        k = min(k, pdim)
        method = p.get("pca_method", "GramSVD")

        Xd = jnp.asarray(X)
        if method in ("GramSVD", "GLRM", "Power"):
            gram = np.asarray(jax.jit(lambda X: X.T @ X)(Xd), np.float64) / max(n - 1, 1)
            evals, evecs = np.linalg.eigh(gram)
            order = np.argsort(-evals)
            evals = np.maximum(evals[order][:k], 0)
            evecs = evecs[:, order][:, :k]
        else:  # Randomized (Halko) — sketch on device, QR/SVD on host
            rng = np.random.default_rng(p["_actual_seed"])
            om = jnp.asarray(rng.normal(size=(pdim, min(k + 10, pdim))).astype(np.float32))
            Y = np.asarray(jax.jit(lambda X, om: X @ om)(Xd, om), np.float64)
            Q, _ = np.linalg.qr(Y)
            B = np.asarray(jax.jit(lambda X, Q: Q.T @ X)(Xd, jnp.asarray(Q, jnp.float32)))
            _, s, Vt = np.linalg.svd(B, full_matrices=False)
            evecs = Vt[:k].T
            evals = (s[:k] ** 2) / max(n - 1, 1)

        # deterministic sign (largest |loading| positive)
        for j in range(evecs.shape[1]):
            i = np.abs(evecs[:, j]).argmax()
            if evecs[i, j] < 0:
                evecs[:, j] = -evecs[:, j]

        model = PCAModel(self, x, dinfo, evecs, evals, k)
        model.training_metrics = ModelMetricsBase(nobs=n)
        return model


PCA = H2OPrincipalComponentAnalysisEstimator
