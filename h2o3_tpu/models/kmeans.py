"""H2OKMeansEstimator — K-Means clustering.

Reference parity: `h2o-algos/src/main/java/hex/kmeans/KMeans.java` — Lloyd
iterations with k-means|| (parallel) initialization, `init` ∈
{Random, PlusPlus, Furthest, User}, standardization, categorical one-hot;
estimator surface `h2o-py/h2o/estimators/kmeans.py`.

TPU shape: one Lloyd iteration = a single jitted program — pairwise
distances ride the MXU (‖x−c‖² expanded to x·cᵀ), assignment is an argmin,
and the centroid update is a segment-sum; with rows sharded over ``hosts``
the per-cluster sums/counts psum across hosts exactly like the reference's
MRTask reduce (`KMeans.Lloyds`).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..frame.frame import Frame
from .metrics import ModelMetricsClustering
from .model_base import DataInfo, H2OEstimator, H2OModel


@functools.partial(jax.jit, static_argnames=("k",))
def _lloyd_step(X, cents, w, k: int):
    d2 = (
        jnp.sum(X * X, axis=1, keepdims=True)
        - 2.0 * X @ cents.T
        + jnp.sum(cents * cents, axis=1)[None, :]
    )
    assign = jnp.argmin(d2, axis=1)
    mind2 = jnp.min(d2, axis=1)
    sums = jax.ops.segment_sum(X * w[:, None], assign, num_segments=k)
    cnts = jax.ops.segment_sum(w, assign, num_segments=k)
    new_cents = jnp.where(cnts[:, None] > 0, sums / jnp.maximum(cnts[:, None], 1e-12), cents)
    wss = jnp.sum(jnp.maximum(mind2, 0.0) * w)
    return new_cents, assign, wss, cnts


class KMeansModel(H2OModel):
    algo = "kmeans"

    def __init__(self, params, x, dinfo, centers_std, k):
        super().__init__(params)
        self.x = list(x)
        self.y = None
        self.dinfo = dinfo
        self.centers_std = centers_std  # in standardized space
        self.k = k

    def centers(self) -> np.ndarray:
        """De-standardized centroids (KMeansModel._output._centers_raw)."""
        c = np.asarray(self.centers_std, np.float64)
        if self.dinfo.standardize and self.dinfo.means is not None:
            c = c * self.dinfo.stds + self.dinfo.means
        return c

    def predict(self, test_data: Frame) -> Frame:
        X = self.dinfo.transform(test_data)
        d2 = (
            np.sum(X * X, axis=1, keepdims=True)
            - 2.0 * X @ np.asarray(self.centers_std).T
            + np.sum(np.asarray(self.centers_std) ** 2, axis=1)[None, :]
        )
        return Frame.from_dict({"predict": d2.argmin(axis=1).astype(np.float64)})

    def _make_metrics(self, frame: Frame):
        X = self.dinfo.transform(frame)
        c = np.asarray(self.centers_std)
        d2 = (
            np.sum(X * X, axis=1, keepdims=True) - 2.0 * X @ c.T
            + np.sum(c * c, axis=1)[None, :]
        )
        wss = float(np.maximum(d2.min(axis=1), 0).sum())
        mu = X.mean(axis=0)
        totss = float(((X - mu) ** 2).sum())
        m = ModelMetricsClustering(
            tot_withinss=wss, totss=totss, betweenss=totss - wss, nobs=X.shape[0]
        )
        m.mse = wss / max(X.shape[0], 1)
        m.rmse = float(np.sqrt(m.mse))
        return m

    def tot_withinss(self):
        return self.training_metrics.tot_withinss

    def betweenss(self):
        return self.training_metrics.betweenss

    def totss(self):
        return self.training_metrics.totss


class H2OKMeansEstimator(H2OEstimator):
    algo = "kmeans"
    supervised = False
    _param_defaults = dict(
        k=1,
        estimate_k=False,
        max_iterations=10,
        init="Furthest",
        user_points=None,
        standardize=True,
    )

    def _fit(self, x, y, train: Frame, valid: Optional[Frame]) -> KMeansModel:
        if int(self._parms.get("k", 1) or 0) < 1:
            raise ValueError(f"k must be >= 1, got {self._parms.get('k')}")
        if int(self._parms.get("max_iterations", 10) or 0) < 1:
            raise ValueError("max_iterations must be >= 1")
        p = self._parms
        seed = p["_actual_seed"]
        k = int(p.get("k", 1))
        dinfo = DataInfo(train, x, standardize=bool(p.get("standardize", True)),
                         use_all_factor_levels=True)
        X = dinfo.fit_transform(train)
        n = X.shape[0]
        rng = np.random.default_rng(seed)
        init = p.get("init", "Furthest")

        if p.get("user_points") is not None:
            up = p["user_points"]
            cents = np.asarray(up.to_numpy() if isinstance(up, Frame) else up, np.float32)
        elif init == "Random":
            cents = X[rng.choice(n, k, replace=False)]
        else:
            # PlusPlus / Furthest seeding (k-means|| degenerate single pass)
            cents = [X[rng.integers(n)]]
            for _ in range(k - 1):
                d2 = np.min(
                    [(np.sum((X - c) ** 2, axis=1)) for c in cents], axis=0
                )
                if init == "Furthest":
                    cents.append(X[int(d2.argmax())])
                else:
                    probs = d2 / max(d2.sum(), 1e-12)
                    cents.append(X[rng.choice(n, p=probs)])
            cents = np.asarray(cents, np.float32)

        Xd = jnp.asarray(X)
        wd = jnp.ones(n, jnp.float32)
        cd = jnp.asarray(cents, jnp.float32)
        prev = np.inf
        for it in range(int(p.get("max_iterations", 10))):
            cd, assign, wss, cnts = _lloyd_step(Xd, cd, wd, k)
            wss = float(wss)
            if abs(prev - wss) < 1e-7 * max(abs(prev), 1):
                break
            prev = wss

        model = KMeansModel(self, x, dinfo, np.asarray(cd), k)
        model.training_metrics = model._make_metrics(train)
        if valid is not None:
            model.validation_metrics = model._make_metrics(valid)
        return model


KMeans = H2OKMeansEstimator
