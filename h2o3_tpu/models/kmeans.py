"""H2OKMeansEstimator — K-Means clustering.

Reference parity: `h2o-algos/src/main/java/hex/kmeans/KMeans.java` — Lloyd
iterations with k-means|| (parallel) initialization, `init` ∈
{Random, PlusPlus, Furthest, User}, standardization, categorical one-hot;
estimator surface `h2o-py/h2o/estimators/kmeans.py`.

TPU shape (ISSUE 15): the WHOLE Lloyd fit is one jitted program — a
`lax.while_loop` whose body fuses distance→assign→update (pairwise
distances ride the MXU via ‖x−c‖² expanded to x·cᵀ, assignment is an
argmin, the centroid update a segment-sum) and whose WSS-convergence test
runs ON DEVICE, so the host reads only the final (centers, wss,
iterations) instead of paying a dispatch + sync per iteration. The
standardized matrix comes from the dataset cache's std layer (one
extraction + one upload per sweep), and under the estimator shard plan the
per-cluster sums/counts/WSS reduce as S canonical ordered blocks
(`ordered_axis_fold`) so an N-device fit is bit-identical to the 1-device
forced-shard lane. ``H2O3_EST_LEGACY=1`` restores the host per-iteration
loop; user-supplied init points and multi-process clouds stay on it.

k-means++/Furthest seeding keeps a RUNNING min-distance vector — O(k·n·p)
total instead of the former O(k²·n·p) recompute-all-centers-per-draw —
with draws bitwise identical to the old code (min over the same per-center
distance arrays, folded incrementally).
"""

from __future__ import annotations

import functools
import time
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from ..frame.frame import Frame
from ..parallel import distdata
from ..parallel import mesh as cloudlib
from ..runtime import qos as _qos
from . import estimator_engine as _est
from .metrics import ModelMetricsClustering
from .model_base import DataInfo, H2OEstimator, H2OModel


@functools.partial(jax.jit, static_argnames=("k",))
def _lloyd_step(X, cents, w, k: int):
    d2 = (
        jnp.sum(X * X, axis=1, keepdims=True)
        - 2.0 * X @ cents.T
        + jnp.sum(cents * cents, axis=1)[None, :]
    )
    assign = jnp.argmin(d2, axis=1)
    mind2 = jnp.min(d2, axis=1)
    sums = jax.ops.segment_sum(X * w[:, None], assign, num_segments=k)
    cnts = jax.ops.segment_sum(w, assign, num_segments=k)
    new_cents = jnp.where(cnts[:, None] > 0, sums / jnp.maximum(cnts[:, None], 1e-12), cents)
    wss = jnp.sum(jnp.maximum(mind2, 0.0) * w)
    return new_cents, assign, wss, cnts


def _lloyd_fit_fn(cloud, shard_mode: str, n_shards: int, k: int):
    """The whole Lloyd fit as ONE device program (ISSUE 15): while_loop
    over fused distance→assign→update steps, WSS convergence on device
    (|WSSₜ₋₁ − WSSₜ| < tol·max(|WSSₜ₋₁|, 1), the host loop's test). Row
    reductions run as `local_blocks` ordered block partials merged by
    `ordered_axis_fold` under the shard plan. Every mode (including
    "off") uses the one-hot-matmul cluster reduction, whose f32
    accumulation order differs from `_lloyd_step`'s segment-sum — fused
    vs legacy is a TOLERANCE comparison (pinned), while blocks vs mesh
    stays bitwise. Cached per cloud."""
    local_blocks, axis = _est.local_plan(cloud, shard_mode, n_shards)
    key = ("kmeans_lloyd", k, local_blocks, axis)

    def build():
        # carry (cents, prev_wss, it, done) enters as traced arguments so
        # the QoS gate can run the fit as a resumable sequence of bounded
        # segments (est.segment_stops); cond's extra `it < stop_at`
        # conjunct makes stop_at = max_iter the single-dispatch identity —
        # same trip count, same body, same bits (pinned)
        def inner(X, w, cents0, prev0, it0, done0, max_iter, stop_at, tol):
            xsq = jnp.sum(X * X, axis=1)
            karange = jnp.arange(k, dtype=jnp.int32)[None, :]

            def step(cents):
                d2 = (xsq[:, None] - 2.0 * X @ cents.T
                      + jnp.sum(cents * cents, axis=1)[None, :])
                assign = jnp.argmin(d2, axis=1)
                mind2 = jnp.maximum(jnp.min(d2, axis=1), 0.0)
                # per-cluster sums/counts as a ONE-HOT MATMUL instead of a
                # segment-sum scatter: ~4x faster on CPU (100k scalar
                # scatter-adds become one (k,n)@(n,p) gemm) and MXU-shaped
                # on TPU; per-block partials stay deterministic
                oh = ((assign[:, None] == karange).astype(jnp.float32)
                      * w[:, None])
                if local_blocks:
                    sl = _est.block_slices(X.shape[0], local_blocks)
                    sums = _est.fold_blocks(jnp.stack(
                        [oh[s].T @ X[s] for s in sl]), axis)
                    cnts = _est.fold_blocks(jnp.stack(
                        [jnp.sum(oh[s], axis=0) for s in sl]), axis)
                    wss = _est.fold_blocks(jnp.stack(
                        [jnp.sum((mind2 * w)[s])[None] for s in sl]),
                        axis)[0]
                else:
                    sums = oh.T @ X
                    cnts = jnp.sum(oh, axis=0)
                    wss = jnp.sum(mind2 * w)
                new_cents = jnp.where(cnts[:, None] > 0,
                                      sums / jnp.maximum(cnts[:, None], 1e-12),
                                      cents)
                return new_cents, wss

            def cond(state):
                cents, prev, it, done = state
                return (~done) & (it < max_iter) & (it < stop_at)

            def body(state):
                cents, prev, it, _ = state
                new_cents, wss = step(cents)
                done = jnp.abs(prev - wss) < tol * jnp.maximum(
                    jnp.abs(prev), 1.0)
                return new_cents, wss, it + 1, done

            cents, wss, it, done = jax.lax.while_loop(
                cond, body, (cents0, prev0, it0, done0))
            return cents, wss, it, done

        if axis is not None:
            rspec = P(cloudlib.ROWS_AXIS)
            rep = P()
            inner = cloudlib.shard_call(
                inner, cloud, in_specs=(rspec, rspec) + (rep,) * 7,
                out_specs=(rep, rep, rep, rep), check_rep=False)
        return jax.jit(inner)

    return _est.cached_program(cloud, key, build)


def _seed_centers(X, k: int, init: str, rng) -> np.ndarray:
    """PlusPlus / Furthest seeding (k-means|| degenerate single pass) with
    a RUNNING min-distance vector: each draw folds only the NEW center's
    distances into d², O(k·n·p) total — the recompute-every-center form
    was O(k²·n·p). np.minimum folds the identical per-center distance
    arrays the old np.min-over-list computed, so draws (and therefore
    centers) are bitwise unchanged for a given seed."""
    n = X.shape[0]
    cents = [X[rng.integers(n)]]
    d2 = np.sum((X - cents[0]) ** 2, axis=1)
    for _ in range(k - 1):
        if init == "Furthest":
            c = X[int(d2.argmax())]
        else:
            probs = d2 / max(d2.sum(), 1e-12)
            c = X[rng.choice(n, p=probs)]
        cents.append(c)
        d2 = np.minimum(d2, np.sum((X - c) ** 2, axis=1))
    return np.asarray(cents, np.float32)


class KMeansModel(H2OModel):
    algo = "kmeans"

    def __init__(self, params, x, dinfo, centers_std, k):
        super().__init__(params)
        self.x = list(x)
        self.y = None
        self.dinfo = dinfo
        self.centers_std = centers_std  # in standardized space
        self.k = k

    def centers(self) -> np.ndarray:
        """De-standardized centroids (KMeansModel._output._centers_raw)."""
        c = np.asarray(self.centers_std, np.float64)
        if self.dinfo.standardize and self.dinfo.means is not None:
            c = c * self.dinfo.stds + self.dinfo.means
        return c

    def predict(self, test_data: Frame) -> Frame:
        X = self.dinfo.transform(test_data)
        d2 = (
            np.sum(X * X, axis=1, keepdims=True)
            - 2.0 * X @ np.asarray(self.centers_std).T
            + np.sum(np.asarray(self.centers_std) ** 2, axis=1)[None, :]
        )
        return Frame.from_dict({"predict": d2.argmin(axis=1).astype(np.float64)})

    def _make_metrics(self, frame: Frame):
        X = self.dinfo.transform(frame)
        c = np.asarray(self.centers_std)
        d2 = (
            np.sum(X * X, axis=1, keepdims=True) - 2.0 * X @ c.T
            + np.sum(c * c, axis=1)[None, :]
        )
        wss = float(np.maximum(d2.min(axis=1), 0).sum())
        mu = X.mean(axis=0)
        totss = float(((X - mu) ** 2).sum())
        m = ModelMetricsClustering(
            tot_withinss=wss, totss=totss, betweenss=totss - wss, nobs=X.shape[0]
        )
        m.mse = wss / max(X.shape[0], 1)
        m.rmse = float(np.sqrt(m.mse))
        return m

    def tot_withinss(self):
        return self.training_metrics.tot_withinss

    def betweenss(self):
        return self.training_metrics.betweenss

    def totss(self):
        return self.training_metrics.totss


class H2OKMeansEstimator(H2OEstimator):
    algo = "kmeans"
    supervised = False
    _param_defaults = dict(
        k=1,
        estimate_k=False,
        max_iterations=10,
        init="Furthest",
        user_points=None,
        standardize=True,
    )

    def _fit(self, x, y, train: Frame, valid: Optional[Frame]) -> KMeansModel:
        if int(self._parms.get("k", 1) or 0) < 1:
            raise ValueError(f"k must be >= 1, got {self._parms.get('k')}")
        if int(self._parms.get("max_iterations", 10) or 0) < 1:
            raise ValueError("max_iterations must be >= 1")
        p = self._parms
        seed = p["_actual_seed"]
        k = int(p.get("k", 1))
        std = bool(p.get("standardize", True))
        max_iter = int(p.get("max_iterations", 10))
        init = p.get("init", "Furthest")
        rng = np.random.default_rng(seed)
        cloud = cloudlib.cloud()
        multiproc = distdata.multiprocess()
        # engine gate: legacy comparator, multi-process clouds and USER
        # init points keep the host per-iteration loop (ISSUE 15 corners)
        engine_on = (not _est.legacy() and not multiproc
                     and p.get("user_points") is None)
        shard_mode, n_shards = (_est.shard_plan(cloud.size, multiproc)
                                if engine_on else ("off", 0))
        if shard_mode == "mesh" and train.nrow < cloud.size:
            shard_mode, n_shards = "off", 0
            engine_on = cloud.size == 1 and engine_on

        if not engine_on:
            dinfo = DataInfo(train, x, standardize=std,
                             use_all_factor_levels=True)
            X = dinfo.fit_transform(train)
            n = X.shape[0]
            if p.get("user_points") is not None:
                up = p["user_points"]
                cents = np.asarray(up.to_numpy() if isinstance(up, Frame) else up, np.float32)
            elif init == "Random":
                cents = X[rng.choice(n, k, replace=False)]
            else:
                cents = _seed_centers(X, k, init, rng)
            Xd = jnp.asarray(X)
            wd = jnp.ones(n, jnp.float32)
            cd = jnp.asarray(cents, jnp.float32)
            prev = np.inf
            iters = 0
            for it in range(max_iter):
                cd, assign, wss, cnts = _lloyd_step(Xd, cd, wd, k)
                wss = float(wss)
                iters = it + 1
                if abs(prev - wss) < 1e-7 * max(abs(prev), 1):
                    break
                prev = wss
            _est.record_fit("kmeans", "legacy", iterations=iters,
                            n_shards=0, n_devices=1)
            model = KMeansModel(self, x, dinfo, np.asarray(cd), k)
        else:
            from . import dataset_cache as _dc

            cache0 = _dc.snapshot() if _est.cache_enabled() else None
            ndev_eff = cloud.size if shard_mode == "mesh" else 1
            # host matrix backs the init draws; the device artifact is its
            # one cached upload (padded to the block grid, zero-weight)
            dinfo, X = _est.host_matrix(train, x, standardize=std,
                                        use_all=True)
            _, Xd = _est.device_matrix(train, x, standardize=std,
                                       use_all=True, n_shards=n_shards,
                                       n_devices=ndev_eff)
            n = X.shape[0]
            npad = int(Xd.shape[0])
            if init == "Random":
                cents = X[rng.choice(n, k, replace=False)]
            else:
                cents = _seed_centers(X, k, init, rng)
            w = np.zeros(npad, np.float32)
            w[:n] = 1.0
            wd = (jax.device_put(jnp.asarray(w), cloud.row_sharding())
                  if ndev_eff > 1 else jnp.asarray(w))
            fn = _lloyd_fit_fn(cloud, shard_mode, n_shards, k)
            t0 = time.perf_counter()
            with _est.iter_phase():
                # segmented dispatch under QoS: each segment is one bounded
                # device program; the carry round-trips on device, only the
                # tiny it/done scalars are read between segments
                cd = jnp.asarray(cents, jnp.float32)
                wss_d = jnp.float32(jnp.inf)
                it_d = jnp.int32(0)
                done_d = jnp.asarray(False)
                stops = _est.segment_stops(max_iter)
                # mid-fit carry snapshots (ISSUE 20): a killed fit resumes
                # at the last completed segment; exact f32 carry round-trip
                # keeps the remaining segments bit-identical
                ck_fp = _est.segment_fingerprint(
                    "kmeans", rows=int(npad), p=int(Xd.shape[1]), k=int(k),
                    seed=int(self._parms.get("seed") or 0),
                    max_iter=int(max_iter), n_shards=int(n_shards),
                    shard_mode=str(shard_mode), std=bool(std),
                    init=str(init)) if len(stops) > 1 else None
                rest = _est.segment_carry_restore("kmeans", ck_fp)
                if rest is not None:
                    s0, (cd, wss_d, it_d, done_d) = rest
                    stops = [s for s in stops if s > s0] or [max_iter]
                for stop in stops:
                    cd, wss_d, it_d, done_d = fn(
                        Xd, wd, cd, wss_d, it_d, done_d,
                        jnp.int32(max_iter), jnp.int32(stop),
                        jnp.float32(1e-7))
                    if stop < max_iter:
                        if bool(done_d) or int(it_d) >= max_iter:
                            break
                        _est.segment_carry_save(
                            "kmeans", ck_fp, stop,
                            (cd, wss_d, it_d, done_d))
                        _qos.yield_point("est_segment", compensate="est_iter")
                cloudlib.collective_fence(cd)
                cents_out = np.asarray(cd)
            _est.record_fit(
                "kmeans",
                {"mesh": "fused_mesh", "blocks": "fused_blocks"}.get(
                    shard_mode, "fused"),
                iterations=int(it_d), converged=bool(done_d),
                matrix_cache=(_est.matrix_cache_state(cache0)
                              if cache0 is not None else None),
                n_shards=n_shards, n_devices=ndev_eff,
                wall_s=time.perf_counter() - t0)
            model = KMeansModel(self, x, dinfo, cents_out, k)

        model.training_metrics = model._make_metrics(train)
        if valid is not None:
            model.validation_metrics = model._make_metrics(valid)
        return model


KMeans = H2OKMeansEstimator
