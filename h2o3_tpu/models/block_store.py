"""BlockStore — the row-blocked packed code matrix of the out-of-core path.

"Fits in HBM" stops being the dataset ceiling (ROADMAP item 2, following
"Out-of-Core GPU Gradient Boosting", arXiv 2005.09148): the sub-byte packed
bin-code matrix lives on HOST as equal row-blocks, and only a bounded
RESIDENT SET of blocks lives on device at any moment. The streamed tree
driver (`models/tree_stream.py`) walks blocks in canonical order — block
boundaries are the PR 9 deterministic-reduction block grid, so a streamed
histogram pass folds the same per-block partials in the same order as the
in-core ``shard_mode="blocks"`` fit and stays BIT-IDENTICAL to it.

Accounting and shedding:

- the store is a **memory-ledger owner** (``block_store:<id>`` standalone,
  or folded into its ``dataset_cache:<fp>:blocks`` layer when the dataset
  cache holds it): host block bytes and resident device bytes are
  attributed like every other subsystem's.
- the resident set is LRU-bounded by a byte budget
  (``H2O3_STREAM_BUDGET_MB``, default: half the device capacity the ledger
  sees) and **sheds device blocks first** when
  ``memory_ledger.pressure()`` crosses ``H2O3_MEM_EVICT_PRESSURE`` — the
  `_evict_locked`-style response, except a shed block costs only a future
  re-upload (the host copy remains), so it is always the cheapest byte to
  give back. Every eviction lands in the Timeline/trace as a ``memory``
  event (owner, bytes, trigger), mirroring the dataset-cache events.
- uploads are double-buffer friendly: ``prefetch(b+1)`` dispatches the
  next block's H2D while the caller's kernel consumes block ``b`` (the
  `_score_event_async` dispatch-before-block pattern); transfer seconds
  land in the new ``h2d_stream`` phase bucket and upload/evict/reuse
  counters + streamed bytes feed the Prometheus scrape and the per-fit
  tree fold at ``/3/Profiler``.
"""

from __future__ import annotations

import os
import threading
import time
import weakref
from collections import OrderedDict
from typing import Dict, List, Optional

import numpy as np

from ..ops import packing
from ..runtime import env_float
from ..runtime import memory_ledger as _ml
from ..runtime import phases as _phases

_TOTALS_LOCK = threading.Lock()
# process-lifetime stream totals — the bench/loadgen record embed next to
# the memory embeds (`streamed_bytes`, `resident_block_peak`)
_TOTALS = {"streamed_bytes": 0, "resident_block_peak": 0}

_REG: Dict = {}


def _registry() -> Dict:
    """Memoized registry families (the usual lazy-memoization stance)."""
    if not _REG:
        from ..runtime import metrics_registry as reg

        _REG["blocks"] = reg.counter(
            "h2o3_tree_stream_blocks",
            "out-of-core code blocks by lifecycle event "
            "(uploaded/evicted/reused)",
            labelnames=("event",))
        _REG["bytes"] = reg.counter(
            "h2o3_tree_stream_bytes",
            "bytes streamed host->device by the out-of-core tree path")
        _REG["resident_peak"] = reg.gauge(
            "h2o3_tree_stream_resident_peak_bytes",
            "high watermark of device-resident out-of-core block bytes")
    return _REG


def stream_budget_bytes() -> int:
    """The resident-set byte budget of the out-of-core path:
    ``H2O3_STREAM_BUDGET_MB`` when set, else half the device capacity the
    memory ledger sees (``memory_stats()`` limit on real chips;
    ``H2O3_DEVICE_BUDGET_MB`` / host budget on census backends) — the
    other half stays free for margins, histograms and the forest pack."""
    mb = env_float("H2O3_STREAM_BUDGET_MB", 0.0)
    if mb > 0:
        return int(mb * 1e6)
    return max(_ml.device_capacity_bytes() // 2, 1)


def process_totals() -> Dict:
    """Cumulative stream totals for record embeds (0s when never used)."""
    with _TOTALS_LOCK:
        return dict(_TOTALS)


def _account_totals(nbytes: int = 0, resident: int = 0) -> None:
    with _TOTALS_LOCK:
        _TOTALS["streamed_bytes"] += int(nbytes)
        if resident > _TOTALS["resident_block_peak"]:
            _TOTALS["resident_block_peak"] = int(resident)


class BlockStore:
    """Host-resident packed row-blocks + a bounded LRU device resident set."""

    _IDS = iter(range(1 << 62))

    def __init__(self, host_blocks: List[np.ndarray], block_rows: int,
                 pack_bits: int, owner: str = "",
                 budget_bytes: Optional[int] = None, register: bool = True):
        self.host_blocks = list(host_blocks)
        self.n_blocks = len(self.host_blocks)
        self.block_rows = int(block_rows)
        self.pack_bits = int(pack_bits)
        self.owner = owner or f"block_store:{next(self._IDS)}"
        # resolved ONCE: the default consults the memory ledger's device
        # probe (an O(live-arrays) census walk on CPU backends) — far too
        # heavy for the per-miss hot path in get()
        self._budget = (int(budget_bytes) if budget_bytes is not None
                        else stream_budget_bytes())
        self._lock = threading.Lock()
        self._resident: "OrderedDict[int, object]" = OrderedDict()
        self._resident_bytes = 0
        self._window_peak = 0
        self.counters = dict(uploaded=0, evicted=0, reused=0,
                             bytes_streamed=0)
        self.resident_peak_bytes = 0
        self._registered = False
        if register:
            # standalone owner (cache-disabled fits): the referent is the
            # store itself, so a dropped store retires its owner
            wr = weakref.ref(self)

            def _bytes():
                st = wr()
                if st is None:
                    return (0, 0)
                return st.host_bytes(), st.resident_bytes()

            _ml.register(self.owner, kind="block_store", bytes_fn=_bytes,
                         referent=self, type_name="blocks")
            self._registered = True

    # -- construction ------------------------------------------------------

    @classmethod
    def from_codes(cls, codes: np.ndarray, n_blocks: int, pack_bits: int,
                   **kw) -> "BlockStore":
        """Blocked (and sub-byte packed) store from a padded full-width
        code matrix. Each block is packed independently via
        `ops.packing.pack_host_range` — O(block) transients, the
        streaming-ingest contract — and, with ``pack_bits=0`` (nbins too
        wide to pack), blocks are contiguous row copies."""
        n = codes.shape[0]
        if n % n_blocks:
            raise ValueError(f"{n} rows do not divide into {n_blocks} blocks")
        rows = n // n_blocks
        if pack_bits and rows % packing.GROUP_ROWS[pack_bits]:
            raise ValueError(
                f"block rows {rows} not aligned to the {pack_bits}-bit "
                "pack group")
        blocks = []
        for b in range(n_blocks):
            if pack_bits:
                blocks.append(packing.pack_host_range(
                    codes, pack_bits, b * rows, (b + 1) * rows))
            else:
                blocks.append(np.ascontiguousarray(codes[b * rows:
                                                         (b + 1) * rows]))
        return cls(blocks, rows, pack_bits, **kw)

    # -- sizes -------------------------------------------------------------

    def host_bytes(self) -> int:
        return sum(int(hb.nbytes) for hb in self.host_blocks)

    def resident_bytes(self) -> int:
        with self._lock:
            return self._resident_bytes

    def nbytes_total(self) -> int:
        return self.host_bytes() + self.resident_bytes()

    def budget_bytes(self) -> int:
        """Resident budget, floored at two blocks so the double buffer
        (consume b, prefetch b+1) always fits."""
        floor = 2 * max((int(hb.nbytes) for hb in self.host_blocks),
                        default=0)
        return max(self._budget, floor)

    def peak_window_start(self) -> None:
        """Reset the per-window resident peak — a fit sharing a cached
        store marks its own window so `peak_window_bytes()` reports THIS
        fit's watermark, not the store-lifetime one."""
        with self._lock:
            self._window_peak = self._resident_bytes

    def peak_window_bytes(self) -> int:
        with self._lock:
            return self._window_peak

    # -- resident-set management -------------------------------------------

    def _evict_locked(self, b: int, trigger: str) -> None:
        arr = self._resident.pop(b, None)
        if arr is None:
            return
        nbytes = int(self.host_blocks[b].nbytes)
        self._resident_bytes -= nbytes
        self.counters["evicted"] += 1
        try:
            _registry()["blocks"].inc(1, "evicted")
        except Exception:
            pass
        _ml.record_event("evict", f"{self.owner}:block{b}", nbytes,
                         trigger=trigger, space="device", kind="block_store")

    def shed(self, keep=(), trigger: str = "pressure") -> int:
        """Drop device blocks (LRU first) except `keep` — the
        pressure-shedding hook. Host copies remain; cost is a future
        re-upload, so device blocks are always the first bytes returned
        when `memory_ledger.pressure()` crosses the eviction threshold."""
        dropped = 0
        with self._lock:
            for b in [b for b in list(self._resident) if b not in keep]:
                self._evict_locked(b, trigger)
                dropped += 1
        return dropped

    def _upload(self, b: int):
        import jax

        hb = self.host_blocks[b]

        def _put():
            return jax.device_put(hb)

        t0 = time.perf_counter()
        arr = _put()
        if _phases.ENABLED:
            # accounted transfer: a tiny D2H is the only reliable barrier
            # through a remote tunnel (see phases.accounted_h2d)
            try:
                np.asarray(arr.ravel()[:1])
            except Exception:
                jax.block_until_ready(arr)
            _phases.add("h2d_stream", time.perf_counter() - t0, hb.nbytes)
        else:
            _phases.add("h2d_stream", 0.0, hb.nbytes)
        return arr

    def get(self, b: int):
        """Device array of block `b`: LRU hit, or evict-then-upload."""
        with self._lock:
            arr = self._resident.get(b)
            if arr is not None:
                self._resident.move_to_end(b)
                self.counters["reused"] += 1
                try:
                    _registry()["blocks"].inc(1, "reused")
                except Exception:
                    pass
                return arr
        # pressure shed BEFORE growing the resident set: past the ledger's
        # eviction threshold only the double buffer stays resident
        try:
            if _ml.pressure() >= _ml.evict_threshold():
                self.shed(keep={b, (b + 1) % self.n_blocks},
                          trigger="pressure")
        except Exception:
            pass
        hb_bytes = int(self.host_blocks[b].nbytes)
        with self._lock:
            arr = self._resident.get(b)
            if arr is not None:
                self._resident.move_to_end(b)
                self.counters["reused"] += 1
                return arr
            budget = self.budget_bytes()
            while self._resident and self._resident_bytes + hb_bytes > budget:
                self._evict_locked(next(iter(self._resident)), "cap")
        arr = self._upload(b)
        with self._lock:
            cur = self._resident.get(b)
            if cur is not None:
                # lost a concurrent-miss race (a shared cached store can
                # be streamed by several sweep candidates): the transfer
                # happened and is counted, but the resident entry — and
                # its bytes — stay singular; our duplicate array is
                # dropped to the GC
                self._resident.move_to_end(b)
                self.counters["uploaded"] += 1
                self.counters["bytes_streamed"] += hb_bytes
                peak = self._resident_bytes
                arr = cur
            else:
                self._resident[b] = arr
                self._resident_bytes += hb_bytes
                self.counters["uploaded"] += 1
                self.counters["bytes_streamed"] += hb_bytes
                if self._resident_bytes > self.resident_peak_bytes:
                    self.resident_peak_bytes = self._resident_bytes
                if self._resident_bytes > self._window_peak:
                    self._window_peak = self._resident_bytes
                peak = self._resident_bytes
        try:
            reg = _registry()
            reg["blocks"].inc(1, "uploaded")
            reg["bytes"].inc(hb_bytes)
            reg["resident_peak"].set(
                max(self.resident_peak_bytes,
                    reg["resident_peak"].value() or 0))
        except Exception:
            pass
        _account_totals(hb_bytes, peak)
        return arr

    def prefetch(self, b: int) -> None:
        """Dispatch block `b`'s H2D now so the upload overlaps the
        caller's compute on the previous block (double buffering). The
        device_put is async on real backends; `get(b)` then finds it
        resident."""
        try:
            self.get(b)
        except Exception:
            pass   # advisory; the blocking get reports real failures

    def account_external_bytes(self, nbytes: int) -> None:
        """Fold an out-of-band H2D (e.g. a GOSS compact-sample upload)
        into the stream byte counters so `streamed_bytes` reflects every
        byte the out-of-core path actually moved."""
        with self._lock:
            self.counters["bytes_streamed"] += int(nbytes)
        try:
            _registry()["bytes"].inc(int(nbytes))
        except Exception:
            pass
        _account_totals(int(nbytes))

    # -- stats / lifecycle -------------------------------------------------

    def stats(self) -> Dict:
        with self._lock:
            out = dict(self.counters)
        out.update(n_blocks=self.n_blocks, block_rows=self.block_rows,
                   pack_bits=self.pack_bits,
                   host_bytes=self.host_bytes(),
                   resident_bytes=self.resident_bytes(),
                   resident_peak_bytes=self.resident_peak_bytes,
                   budget_bytes=self.budget_bytes())
        return out

    def close(self) -> None:
        self.shed(trigger="clear")
        if self._registered:
            _ml.unregister(self.owner)
            self._registered = False
