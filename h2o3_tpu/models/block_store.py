"""BlockStore — the row-blocked packed code matrix of the out-of-core path.

"Fits in HBM" stops being the dataset ceiling (ROADMAP item 2, following
"Out-of-Core GPU Gradient Boosting", arXiv 2005.09148): the sub-byte packed
bin-code matrix lives on HOST as equal row-blocks, and only a bounded
RESIDENT SET of blocks lives on device at any moment. The streamed tree
driver (`models/tree_stream.py`) walks blocks in canonical order — block
boundaries are the PR 9 deterministic-reduction block grid, so a streamed
histogram pass folds the same per-block partials in the same order as the
in-core ``shard_mode="blocks"`` fit and stays BIT-IDENTICAL to it.

Accounting and shedding:

- the store is a **memory-ledger owner** (``block_store:<id>`` standalone,
  or folded into its ``dataset_cache:<fp>:blocks`` layer when the dataset
  cache holds it): host block bytes and resident device bytes are
  attributed like every other subsystem's.
- the resident set is LRU-bounded by a byte budget
  (``H2O3_STREAM_BUDGET_MB``, default: half the device capacity the ledger
  sees) and **sheds device blocks first** when
  ``memory_ledger.pressure()`` crosses ``H2O3_MEM_EVICT_PRESSURE`` — the
  `_evict_locked`-style response, except a shed block costs only a future
  re-upload (the host copy remains), so it is always the cheapest byte to
  give back. Every eviction lands in the Timeline/trace as a ``memory``
  event (owner, bytes, trigger), mirroring the dataset-cache events.
- uploads are double-buffer friendly: ``prefetch(b+1)`` dispatches the
  next block's H2D while the caller's kernel consumes block ``b`` (the
  `_score_event_async` dispatch-before-block pattern); transfer seconds
  land in the new ``h2d_stream`` phase bucket and upload/evict/reuse
  counters + streamed bytes feed the Prometheus scrape and the per-fit
  tree fold at ``/3/Profiler``.

The disk tier (round 19) adds the third level of the LRU: host blocks
that overflow ``H2O3_STREAM_HOST_BUDGET_MB`` (ledger-derived default:
half the host budget; ``H2O3_TREE_OOC_DISK=0`` disables the tier) SPILL
through the persist layer as atomic ``.part``+rename files and stream
back through ``Persist.open_resuming`` — a torn or injected
``persist.read`` failure resumes at the current offset under the shared
retry policy instead of failing the fit. ``prefetch`` goes asynchronous
once blocks live on disk, so the disk→host read of block ``b+1``
overlaps block ``b``'s H2D and compute. Restored bytes are byte-identical
to what was packed, so a spilled fit sharing the block grid stays
BIT-IDENTICAL to in-core. Spill files are ledger-visible as
``<owner>:spill`` owners in the new ``disk`` space — a store dropped
without ``close()`` leaves files behind and surfaces as a leak.
"""

from __future__ import annotations

import os
import tempfile
import threading
import time
import weakref
from collections import OrderedDict
from typing import Dict, List, Optional

import numpy as np

from ..ops import packing
from ..runtime import env_float
from ..runtime import memory_ledger as _ml
from ..runtime import persist as _persist
from ..runtime import phases as _phases

_TOTALS_LOCK = threading.Lock()
# process-lifetime stream totals — the bench/loadgen record embed next to
# the memory embeds (`streamed_bytes`, `resident_block_peak`)
_TOTALS = {"streamed_bytes": 0, "resident_block_peak": 0,
           "spilled_bytes": 0, "restored_bytes": 0,
           "resident_host_peak": 0}

_REG: Dict = {}


def _registry() -> Dict:
    """Memoized registry families (the usual lazy-memoization stance)."""
    if not _REG:
        from ..runtime import metrics_registry as reg

        _REG["blocks"] = reg.counter(
            "h2o3_tree_stream_blocks",
            "out-of-core code blocks by lifecycle event "
            "(uploaded/evicted/reused)",
            labelnames=("event",))
        _REG["bytes"] = reg.counter(
            "h2o3_tree_stream_bytes",
            "bytes streamed host->device by the out-of-core tree path")
        _REG["resident_peak"] = reg.gauge(
            "h2o3_tree_stream_resident_peak_bytes",
            "high watermark of device-resident out-of-core block bytes")
        _REG["spill_blocks"] = reg.counter(
            "h2o3_tree_spill_blocks",
            "disk-tier block events (spilled: host->disk write or host "
            "drop with a disk copy kept; restored: disk->host read)",
            labelnames=("event",))
        _REG["spill_bytes"] = reg.counter(
            "h2o3_tree_spill_bytes",
            "disk-tier bytes by direction (spill: written host->disk; "
            "restore: read disk->host)",
            labelnames=("direction",))
        _REG["spill_host_peak"] = reg.gauge(
            "h2o3_tree_spill_resident_host_peak_bytes",
            "high watermark of host-resident out-of-core block bytes "
            "while the disk tier is active")
    return _REG


def stream_budget_bytes() -> int:
    """The resident-set byte budget of the out-of-core path:
    ``H2O3_STREAM_BUDGET_MB`` when set, else half the device capacity the
    memory ledger sees (``memory_stats()`` limit on real chips;
    ``H2O3_DEVICE_BUDGET_MB`` / host budget on census backends) — the
    other half stays free for margins, histograms and the forest pack."""
    mb = env_float("H2O3_STREAM_BUDGET_MB", 0.0)
    if mb > 0:
        return int(mb * 1e6)
    return max(_ml.device_capacity_bytes() // 2, 1)


def stream_host_budget_bytes() -> int:
    """The HOST-resident byte budget of the disk spill tier:
    ``H2O3_STREAM_HOST_BUDGET_MB`` when set, else half the ledger's host
    budget (``H2O3_MEM_BUDGET_MB`` / MemTotal) — packed blocks past it
    spill to disk through the persist layer. 0 (or
    ``H2O3_TREE_OOC_DISK=0``) disables the tier: every block stays
    host-resident, the pre-round-19 behavior."""
    if os.environ.get("H2O3_TREE_OOC_DISK", "") == "0":
        return 0
    mb = env_float("H2O3_STREAM_HOST_BUDGET_MB", 0.0)
    if mb > 0:
        return int(mb * 1e6)
    return max(_ml._host_budget_bytes() // 2, 1)


def process_totals() -> Dict:
    """Cumulative stream totals for record embeds (0s when never used)."""
    with _TOTALS_LOCK:
        return dict(_TOTALS)


def _account_totals(nbytes: int = 0, resident: int = 0) -> None:
    with _TOTALS_LOCK:
        _TOTALS["streamed_bytes"] += int(nbytes)
        if resident > _TOTALS["resident_block_peak"]:
            _TOTALS["resident_block_peak"] = int(resident)


def _account_spill_totals(spilled: int = 0, restored: int = 0,
                          host_peak: int = 0) -> None:
    with _TOTALS_LOCK:
        _TOTALS["spilled_bytes"] += int(spilled)
        _TOTALS["restored_bytes"] += int(restored)
        if host_peak > _TOTALS["resident_host_peak"]:
            _TOTALS["resident_host_peak"] = int(host_peak)


class BlockStore:
    """Packed row-blocks across three LRU tiers: a bounded device resident
    set, a bounded host set, and persist-backed spill files on disk."""

    _IDS = iter(range(1 << 62))

    def __init__(self, host_blocks: List[np.ndarray], block_rows: int,
                 pack_bits: int, owner: str = "",
                 budget_bytes: Optional[int] = None,
                 host_budget_bytes: Optional[int] = None,
                 register: bool = True):
        self.host_blocks = list(host_blocks)
        self.n_blocks = len(self.host_blocks)
        self.block_rows = int(block_rows)
        self.pack_bits = int(pack_bits)
        self.owner = owner or f"block_store:{next(self._IDS)}"
        # resolved ONCE: the default consults the memory ledger's device
        # probe (an O(live-arrays) census walk on CPU backends) — far too
        # heavy for the per-miss hot path in get()
        self._budget = (int(budget_bytes) if budget_bytes is not None
                        else stream_budget_bytes())
        # host-tier budget (0 disables the disk tier); sizes and dtypes
        # are pinned up front because a spilled slot holds None
        self._host_budget = (int(host_budget_bytes)
                             if host_budget_bytes is not None
                             else stream_host_budget_bytes())
        self._block_nbytes = [int(hb.nbytes) for hb in self.host_blocks]
        self._block_meta = [(hb.shape, hb.dtype) for hb in self.host_blocks]
        self._lock = threading.Lock()
        self._resident: "OrderedDict[int, object]" = OrderedDict()
        self._resident_bytes = 0
        self._window_peak = 0
        # host LRU: block id -> None for host-resident blocks, in LRU order
        self._host_lru: "OrderedDict[int, None]" = OrderedDict()
        for b in range(self.n_blocks):
            self._host_lru[b] = None
        self._host_bytes_resident = sum(self._block_nbytes)
        self._host_window_peak = self._host_bytes_resident
        self._on_disk: set = set()          # blocks with a spill file
        self._spill_dir: Optional[str] = None
        self._spill_registered = False
        self._pending: set = set()          # async prefetches in flight
        self._pool = None
        # serializes the restore slow path (evict-then-read-then-insert)
        # so concurrent prefetch + compute restores cannot both claim the
        # same headroom and push the watermark over the host budget
        self._restore_lock = threading.Lock()
        self.counters = dict(uploaded=0, evicted=0, reused=0,
                             bytes_streamed=0, spilled=0, restored=0,
                             bytes_spilled=0, bytes_restored=0)
        self.resident_peak_bytes = 0
        self.host_resident_peak_bytes = self._host_bytes_resident
        self._registered = False
        if register:
            # standalone owner (cache-disabled fits): the referent is the
            # store itself, so a dropped store retires its owner
            wr = weakref.ref(self)

            def _bytes():
                st = wr()
                if st is None:
                    return (0, 0)
                return st.host_bytes(), st.resident_bytes()

            _ml.register(self.owner, kind="block_store", bytes_fn=_bytes,
                         referent=self, type_name="blocks")
            self._registered = True
        if self._host_budget > 0:
            self._enforce_host_budget(keep=())

    # -- construction ------------------------------------------------------

    @classmethod
    def from_codes(cls, codes: np.ndarray, n_blocks: int, pack_bits: int,
                   **kw) -> "BlockStore":
        """Blocked (and sub-byte packed) store from a padded full-width
        code matrix. Each block is packed independently via
        `ops.packing.pack_host_range` — O(block) transients, the
        streaming-ingest contract — and, with ``pack_bits=0`` (nbins too
        wide to pack), blocks are contiguous row copies."""
        n = codes.shape[0]
        if n % n_blocks:
            raise ValueError(f"{n} rows do not divide into {n_blocks} blocks")
        rows = n // n_blocks
        if pack_bits and rows % packing.GROUP_ROWS[pack_bits]:
            raise ValueError(
                f"block rows {rows} not aligned to the {pack_bits}-bit "
                "pack group")
        blocks = []
        for b in range(n_blocks):
            if pack_bits:
                blocks.append(packing.pack_host_range(
                    codes, pack_bits, b * rows, (b + 1) * rows))
            else:
                blocks.append(np.ascontiguousarray(codes[b * rows:
                                                         (b + 1) * rows]))
        return cls(blocks, rows, pack_bits, **kw)

    # -- sizes -------------------------------------------------------------

    def host_bytes(self) -> int:
        """HOST-RESIDENT block bytes (spilled slots hold None and do not
        count — their bytes live in `disk_bytes()`)."""
        with self._lock:
            return self._host_bytes_resident

    def resident_bytes(self) -> int:
        with self._lock:
            return self._resident_bytes

    def disk_bytes(self) -> int:
        """Bytes held by spill files (kept even after a restore — the
        'spilled copies kept' rule makes a later host shed free)."""
        with self._lock:
            return sum(self._block_nbytes[b] for b in self._on_disk)

    def nbytes_total(self) -> int:
        return self.host_bytes() + self.resident_bytes()

    def budget_bytes(self) -> int:
        """Resident budget, floored at two blocks so the double buffer
        (consume b, prefetch b+1) always fits."""
        floor = 2 * max(self._block_nbytes, default=0)
        return max(self._budget, floor)

    def host_budget_bytes(self) -> int:
        """Host-tier budget (0: disk tier disabled), floored at two
        blocks so the disk double buffer (restore b+1 while b computes)
        always fits."""
        if self._host_budget <= 0:
            return 0
        floor = 2 * max(self._block_nbytes, default=0)
        return max(self._host_budget, floor)

    def peak_window_start(self) -> None:
        """Reset the per-window resident peaks — a fit sharing a cached
        store marks its own window so `peak_window_bytes()` reports THIS
        fit's watermark, not the store-lifetime one."""
        with self._lock:
            self._window_peak = self._resident_bytes
            self._host_window_peak = self._host_bytes_resident

    def peak_window_bytes(self) -> int:
        with self._lock:
            return self._window_peak

    def host_peak_window_bytes(self) -> int:
        with self._lock:
            return self._host_window_peak

    # -- disk tier ---------------------------------------------------------

    def _spill_dir_path(self) -> str:
        """Lazily-created per-store spill directory; also registers the
        ``<owner>:spill`` ledger owner whose bytes come from the
        FILESYSTEM (not the store object), so a store dropped without
        ``close()`` leaves a dead owner that still reports disk bytes —
        the leak detector's cue."""
        if self._spill_dir is None:
            base = os.environ.get("H2O3_SPILL_DIR") or tempfile.gettempdir()
            safe = self.owner.replace(":", "_").replace("/", "_")
            # rank-unique (ISSUE 18): pod ranks on different hosts can
            # share H2O3_SPILL_DIR (NFS) and pids collide across hosts
            try:
                import jax

                rank = int(jax.process_index())
            except Exception:
                rank = 0
            d = os.path.join(base,
                             f"h2o3_spill_r{rank}_{os.getpid()}_{safe}")
            os.makedirs(d, exist_ok=True)
            self._spill_dir = d
        if not self._spill_registered:
            self._spill_registered = True
            d = self._spill_dir

            def _disk():
                try:
                    with os.scandir(d) as it:
                        return (0, 0, sum(e.stat().st_size for e in it
                                          if e.is_file()))
                except OSError:
                    return (0, 0, 0)

            _ml.register(f"{self.owner}:spill", kind="block_store",
                         bytes_fn=_disk, referent=self, type_name="spill")
        return self._spill_dir

    def _spill_path(self, b: int) -> str:
        return os.path.join(self._spill_dir_path(), f"block{b}.bin")

    def _write_spill(self, b: int, hb: np.ndarray) -> None:
        """host→disk through the persist layer: write ``.part``, fsync,
        atomic rename — the registry publish pattern, so a crash mid-spill
        never leaves a torn file where a restore would read it."""
        path = self._spill_path(b)
        part = path + ".part"
        be = _persist.for_uri(path)
        t0 = time.perf_counter()
        fh = be.open(part, "wb")
        try:
            fh.write(hb.tobytes() if not hb.flags.c_contiguous
                     else memoryview(hb).cast("B"))
            fh.flush()
            try:
                os.fsync(fh.fileno())
            except (OSError, AttributeError):
                pass
        finally:
            fh.close()
        os.replace(part, path)
        _phases.add("disk_stream", time.perf_counter() - t0, hb.nbytes)

    def _read_spill(self, b: int) -> np.ndarray:
        """disk→host via the persist layer's resuming reader: a torn or
        fault-injected read resumes at the current offset under the
        shared retry policy instead of failing the fit."""
        path = self._spill_path(b)
        expected = self._block_nbytes[b]
        shape, dtype = self._block_meta[b]
        be = _persist.for_uri(path)
        t0 = time.perf_counter()
        buf = bytearray()
        with be.open_resuming(path) as src:
            while len(buf) < expected:
                chunk = src.read(min(1 << 20, expected - len(buf)))
                if not chunk:
                    break
                buf += chunk
        if len(buf) != expected:
            raise IOError(f"spill file {path} truncated: "
                          f"{len(buf)} of {expected} bytes")
        _phases.add("disk_stream", time.perf_counter() - t0, expected)
        return np.frombuffer(bytes(buf), dtype=dtype).reshape(shape)

    def _pick_spill_victim_locked(self, keep) -> Optional[int]:
        for b in self._host_lru:
            if b not in keep:
                return b
        return None

    def _enforce_host_budget(self, keep=(), trigger: str = "host_cap",
                             headroom: int = 0) -> int:
        """Spill LRU host blocks (except `keep`) until host-resident bytes
        plus `headroom` (bytes an imminent restore is about to insert) fit
        the host budget. File writes run OUTSIDE the lock; a block already
        on disk just drops its host copy (spilled copies kept)."""
        budget = self.host_budget_bytes()
        if budget <= 0:
            return 0
        spilled = 0
        while True:
            with self._lock:
                if self._host_bytes_resident + headroom <= budget:
                    return spilled
                b = self._pick_spill_victim_locked(keep)
                if b is None:
                    return spilled
                hb = self.host_blocks[b]
                on_disk = b in self._on_disk
            if hb is None:
                # raced with another spiller; bookkeeping already done
                continue
            if not on_disk:
                self._write_spill(b, hb)
            nbytes = self._block_nbytes[b]
            with self._lock:
                if self.host_blocks[b] is None:
                    continue
                self.host_blocks[b] = None
                self._host_lru.pop(b, None)
                self._host_bytes_resident -= nbytes
                self._on_disk.add(b)
                self.counters["spilled"] += 1
                self.counters["bytes_spilled"] += nbytes
            spilled += 1
            try:
                reg = _registry()
                reg["spill_blocks"].inc(1, "spilled")
                reg["spill_bytes"].inc(nbytes, "spill")
            except Exception:
                pass
            _account_spill_totals(spilled=nbytes)
            _ml.record_event("spill", f"{self.owner}:block{b}", nbytes,
                             trigger=trigger, space="disk",
                             kind="block_store")

    def shed_host(self, keep=(), trigger: str = "pressure") -> int:
        """Spill ALL host blocks except `keep` — the second stage of the
        pressure response (device blocks shed first via `shed`; host
        blocks spill after, and blocks already on disk just drop their
        host copy). No-op when the disk tier is disabled."""
        if self._host_budget <= 0:
            return 0
        spilled = 0
        while True:
            with self._lock:
                b = self._pick_spill_victim_locked(keep)
                if b is None:
                    return spilled
                hb = self.host_blocks[b]
                on_disk = b in self._on_disk
            if hb is None:
                continue
            if not on_disk:
                self._write_spill(b, hb)
            nbytes = self._block_nbytes[b]
            with self._lock:
                if self.host_blocks[b] is None:
                    continue
                self.host_blocks[b] = None
                self._host_lru.pop(b, None)
                self._host_bytes_resident -= nbytes
                self._on_disk.add(b)
                self.counters["spilled"] += 1
                self.counters["bytes_spilled"] += nbytes
            spilled += 1
            try:
                reg = _registry()
                reg["spill_blocks"].inc(1, "spilled")
                reg["spill_bytes"].inc(nbytes, "spill")
            except Exception:
                pass
            _account_spill_totals(spilled=nbytes)
            _ml.record_event("spill", f"{self.owner}:block{b}", nbytes,
                             trigger=trigger, space="disk",
                             kind="block_store")

    def fetch_host(self, b: int) -> np.ndarray:
        """Host array of block `b`, restoring from its spill file when the
        host copy was shed. Touches the host LRU and enforces the host
        budget (so a restore can spill a colder block in turn). This is
        the ONE host read path — the streamed driver's host-method
        kernels, GOSS gathers and device uploads all come through here,
        which is what makes restored bytes bit-identical by construction."""
        b = int(b)
        with self._lock:
            hb = self.host_blocks[b]
            if hb is not None:
                self._host_lru.move_to_end(b) if b in self._host_lru \
                    else self._host_lru.setdefault(b, None)
                return hb
        nbytes = self._block_nbytes[b]
        with self._restore_lock:
            with self._lock:
                cur = self.host_blocks[b]
                if cur is not None:
                    # a concurrent restore (prefetch) won; keep the winner
                    return cur
            # make room FIRST — the watermark must never exceed the
            # budget, even transiently. Keep the block being restored and
            # its successor (the disk double buffer); a colder block pays
            # the spill
            self._enforce_host_budget(keep={b, (b + 1) % self.n_blocks},
                                      headroom=nbytes)
            arr = self._read_spill(b)
            with self._lock:
                self.host_blocks[b] = arr
                self._host_lru[b] = None
                self._host_lru.move_to_end(b)
                self._host_bytes_resident += nbytes
                self.counters["restored"] += 1
                self.counters["bytes_restored"] += nbytes
                if self._host_bytes_resident > self.host_resident_peak_bytes:
                    self.host_resident_peak_bytes = self._host_bytes_resident
                if self._host_bytes_resident > self._host_window_peak:
                    self._host_window_peak = self._host_bytes_resident
                host_peak = self._host_bytes_resident
        try:
            reg = _registry()
            reg["spill_blocks"].inc(1, "restored")
            reg["spill_bytes"].inc(nbytes, "restore")
            reg["spill_host_peak"].set(
                max(self.host_resident_peak_bytes,
                    reg["spill_host_peak"].value() or 0))
        except Exception:
            pass
        _account_spill_totals(restored=nbytes, host_peak=host_peak)
        _ml.record_event("restore", f"{self.owner}:block{b}", nbytes,
                         trigger="stream", space="disk", kind="block_store")
        return arr

    # -- resident-set management -------------------------------------------

    def _evict_locked(self, b: int, trigger: str) -> None:
        arr = self._resident.pop(b, None)
        if arr is None:
            return
        nbytes = self._block_nbytes[b]
        self._resident_bytes -= nbytes
        self.counters["evicted"] += 1
        try:
            _registry()["blocks"].inc(1, "evicted")
        except Exception:
            pass
        _ml.record_event("evict", f"{self.owner}:block{b}", nbytes,
                         trigger=trigger, space="device", kind="block_store")

    def shed(self, keep=(), trigger: str = "pressure") -> int:
        """Drop device blocks (LRU first) except `keep` — the
        pressure-shedding hook. Host copies remain; cost is a future
        re-upload, so device blocks are always the first bytes returned
        when `memory_ledger.pressure()` crosses the eviction threshold."""
        dropped = 0
        with self._lock:
            for b in [b for b in list(self._resident) if b not in keep]:
                self._evict_locked(b, trigger)
                dropped += 1
        return dropped

    def _upload(self, b: int):
        import jax

        hb = self.fetch_host(b)

        def _put():
            return jax.device_put(hb)

        t0 = time.perf_counter()
        arr = _put()
        if _phases.ENABLED:
            # accounted transfer: a tiny D2H is the only reliable barrier
            # through a remote tunnel (see phases.accounted_h2d)
            try:
                np.asarray(arr.ravel()[:1])
            except Exception:
                jax.block_until_ready(arr)
            _phases.add("h2d_stream", time.perf_counter() - t0, hb.nbytes)
        else:
            _phases.add("h2d_stream", 0.0, hb.nbytes)
        return arr

    def get(self, b: int):
        """Device array of block `b`: LRU hit, or evict-then-upload."""
        with self._lock:
            arr = self._resident.get(b)
            if arr is not None:
                self._resident.move_to_end(b)
                self.counters["reused"] += 1
                try:
                    _registry()["blocks"].inc(1, "reused")
                except Exception:
                    pass
                return arr
        # pressure shed BEFORE growing the resident set: past the ledger's
        # eviction threshold only the double buffer stays resident
        try:
            if _ml.pressure() >= _ml.evict_threshold():
                self.shed(keep={b, (b + 1) % self.n_blocks},
                          trigger="pressure")
        except Exception:
            pass
        hb_bytes = self._block_nbytes[b]
        with self._lock:
            arr = self._resident.get(b)
            if arr is not None:
                self._resident.move_to_end(b)
                self.counters["reused"] += 1
                return arr
            budget = self.budget_bytes()
            while self._resident and self._resident_bytes + hb_bytes > budget:
                self._evict_locked(next(iter(self._resident)), "cap")
        arr = self._upload(b)
        with self._lock:
            cur = self._resident.get(b)
            if cur is not None:
                # lost a concurrent-miss race (a shared cached store can
                # be streamed by several sweep candidates): the transfer
                # happened and is counted, but the resident entry — and
                # its bytes — stay singular; our duplicate array is
                # dropped to the GC
                self._resident.move_to_end(b)
                self.counters["uploaded"] += 1
                self.counters["bytes_streamed"] += hb_bytes
                peak = self._resident_bytes
                arr = cur
            else:
                self._resident[b] = arr
                self._resident_bytes += hb_bytes
                self.counters["uploaded"] += 1
                self.counters["bytes_streamed"] += hb_bytes
                if self._resident_bytes > self.resident_peak_bytes:
                    self.resident_peak_bytes = self._resident_bytes
                if self._resident_bytes > self._window_peak:
                    self._window_peak = self._resident_bytes
                peak = self._resident_bytes
        try:
            reg = _registry()
            reg["blocks"].inc(1, "uploaded")
            reg["bytes"].inc(hb_bytes)
            reg["resident_peak"].set(
                max(self.resident_peak_bytes,
                    reg["resident_peak"].value() or 0))
        except Exception:
            pass
        _account_totals(hb_bytes, peak)
        return arr

    def _prefetch_pool(self):
        if self._pool is None:
            from concurrent.futures import ThreadPoolExecutor

            with self._lock:
                if self._pool is None:
                    self._pool = ThreadPoolExecutor(
                        max_workers=1,
                        thread_name_prefix="h2o3-spill-prefetch")
        return self._pool

    def prefetch(self, b: int) -> None:
        """Dispatch block `b`'s H2D now so the upload overlaps the
        caller's compute on the previous block (double buffering). The
        device_put is async on real backends; `get(b)` then finds it
        resident. Once blocks live on disk the whole fetch moves to a
        single background worker — a synchronous prefetch would serialize
        the disk read with the caller's compute, which is the one cost
        the three-tier pipeline exists to hide; max_workers=1 keeps it a
        strict double buffer (one restore+upload in flight)."""
        b = int(b)
        with self._lock:
            disk_active = bool(self._on_disk)
            if disk_active:
                if b in self._pending:
                    return
                self._pending.add(b)
        if not disk_active:
            try:
                self.get(b)
            except Exception:
                pass   # advisory; the blocking get reports real failures
            return

        def _run():
            try:
                self.get(b)
            except Exception:
                pass
            finally:
                with self._lock:
                    self._pending.discard(b)

        try:
            self._prefetch_pool().submit(_run)
        except Exception:
            with self._lock:
                self._pending.discard(b)

    def account_external_bytes(self, nbytes: int) -> None:
        """Fold an out-of-band H2D (e.g. a GOSS compact-sample upload)
        into the stream byte counters so `streamed_bytes` reflects every
        byte the out-of-core path actually moved."""
        with self._lock:
            self.counters["bytes_streamed"] += int(nbytes)
        try:
            _registry()["bytes"].inc(int(nbytes))
        except Exception:
            pass
        _account_totals(int(nbytes))

    # -- stats / lifecycle -------------------------------------------------

    def stats(self) -> Dict:
        with self._lock:
            out = dict(self.counters)
        out.update(n_blocks=self.n_blocks, block_rows=self.block_rows,
                   pack_bits=self.pack_bits,
                   host_bytes=self.host_bytes(),
                   resident_bytes=self.resident_bytes(),
                   disk_bytes=self.disk_bytes(),
                   resident_peak_bytes=self.resident_peak_bytes,
                   host_resident_peak_bytes=self.host_resident_peak_bytes,
                   budget_bytes=self.budget_bytes(),
                   host_budget_bytes=self.host_budget_bytes())
        return out

    def close(self) -> None:
        self.shed(trigger="clear")
        pool, self._pool = self._pool, None
        if pool is not None:
            pool.shutdown(wait=True)
        # remove spill files BEFORE retiring the :spill owner — its bytes
        # come from the filesystem, so files left behind would read as a
        # leak (which is exactly what an unclosed store should read as)
        with self._lock:
            on_disk = list(self._on_disk)
            self._on_disk.clear()
            sd = self._spill_dir
        freed = 0
        for b in on_disk:
            try:
                p = os.path.join(sd, f"block{b}.bin") if sd else None
                if p and os.path.exists(p):
                    freed += self._block_nbytes[b]
                    os.remove(p)
            except OSError:
                pass
        if sd:
            try:
                os.rmdir(sd)
            except OSError:
                pass
        if self._spill_registered:
            _ml.unregister(f"{self.owner}:spill",
                           event="free" if freed else None, nbytes=freed,
                           trigger="close", space="disk")
            self._spill_registered = False
        if self._registered:
            _ml.unregister(self.owner)
            self._registered = False
